# Test/bench entry points (the reference pins quality with Makefile:3-7 —
# fmt + clippy + `cargo test` under a quickcheck budget; here the suite +
# dryrun + bench are the equivalent gates).
.PHONY: test test-fast test-chaos test-recovery test-restart test-overload test-fuzz test-devicefault test-device-stripped dryrun bench bench-smoke trace-smoke critpath-smoke overload-smoke fuzz-smoke failover-smoke telemetry-smoke pallas-smoke scenario-smoke

test:
	python -m pytest tests/ -x -q

# the CI-shrunk load (tests/harness.py COMMANDS_PER_CLIENT, hypothesis
# max_examples both scale down under CI=true)
test-fast:
	CI=true python -m pytest tests/ -x -q -m "not slow"

# the full fault-injection matrix (crash x loss x protocol, including the
# `slow`-marked sweep rows tier-1 skips)
test-chaos:
	python -m pytest tests/test_faults.py -x -q -m chaos

# the recovery slice: per-dot MPrepare/MPromise recovery (EPaxos/Atlas/
# Newt AND Caesar's (clock, preds) synod), noop commits, FPaxos leader
# failover (sim + TCP), and the crashed-coordinator model checker rows
# (Caesar included, n=3/f=1 exhaustive)
test-recovery:
	python -m pytest tests/ -x -q -m recovery

# the restart-and-rejoin slice: WAL durability edges, snapshot/restore,
# crash-restart chaos rows (restored tolerance, all five protocols —
# Caesar MSync records + FPaxos MSlotSync slot catch-up included), WAL
# tail-replay rows, TCP WAL recovery + on_peer_up revival, the FPaxos
# leader-kill 3-phase TCP row
test-restart:
	python -m pytest tests/ -x -q -m restart

# the overload-control slice: bounded queues + watermark backpressure,
# admission sheds + client backoff/deadlines, open-loop bursts, the
# SlowProcess nemesis, and the queue-gauge metrics export
test-overload:
	python -m pytest tests/ -x -q -m overload

# the chaos-fuzzing + consistency-audit slice: auditor verdicts on
# hand-built histories, digest divergence detection (incl. the TCP
# forked-replica row), fuzzer determinism, shrinker minimality, and the
# GC-straggler mutation self-test
test-fuzz:
	python -m pytest tests/ -x -q -m fuzz

# close the tier-1 coverage hole on the pinned jax: run every
# jax-version-guarded device test module (discovered by guard scan —
# tests/test_device_runner.py today; new guarded device suites ride
# along automatically) from guard-stripped copies (the guard exists
# because jaxlib 0.4.x segfaults flakily while tracing the drivers' scan
# bodies) in their own pytest processes, the way PR 6 validated its
# changes.  On jax >= 0.5 the regular suite already covers the modules
# and this is a no-op
test-device-stripped:
	python scripts/run_device_stripped.py

dryrun:
	python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

bench:
	python bench.py

# tiny CPU-sized bench rows (table + Newt serving), in-process: catches
# import breaks and order-of-magnitude regressions in the bench seams
# without a chip — the per-push CI slice runs this
bench-smoke:
	python bench.py --smoke

# observability gate: tiny traced sim, byte-identical same-seed span
# logs, Perfetto conversion + stage-latency report all validate — the
# per-push CI slice runs this next to bench-smoke
trace-smoke:
	python scripts/trace_smoke.py

# critical-path gate: localhost 3-process EPaxos with tracing — >= 99%
# of sampled spans stitch across processes, every attribution vector
# telescopes exactly to reply-submit, a SlowProcess nemesis is named
# the dominant quorum-wait contributor, and a forced
# StalledExecutionError dumps flight-recorder black boxes from every
# live process that the same correlator stitches — the per-push CI
# slice runs this next to trace-smoke
critpath-smoke:
	python scripts/critpath_smoke.py

# overload gate: tiny CPU open-loop burst at ~2x saturation against a
# tight admission limit — bounded queue depths, typed sheds reaching
# clients, nonzero goodput while shedding, post-burst latency back to
# baseline — the per-push CI slice runs this next to bench/trace-smoke
overload-smoke:
	python scripts/overload_smoke.py

# live-telemetry gate: localhost EPaxos cluster with the /metrics
# exposition endpoints live — scrape twice mid-run (well-formed, required
# key set, monotonic counters), windowed series files parse, `obs watch`
# renders, and the perf-regression gate trips on an injected 2x latency
# (plus a report-only `bench.py --regress` over the smoke row when
# bench-smoke left one behind) — the per-push CI slice runs this
telemetry-smoke:
	python scripts/telemetry_smoke.py

# chaos-fuzz gate: seeded fault-schedule sweep with composed nemeses
# over EVERY protocol x EVERY nemesis class (fixed seed set + targeted
# Caesar-crash and FPaxos-restart rows, budget-checked), auditor-clean +
# byte-identical determinism (same seed => same plan/trace/verdict).
# Set FANTOCH_FUZZ_BUDGET_S for a longer soak (nightly — the soak
# samples all five protocols with crash AND restart nemeses un-gated) —
# the per-push CI slice runs the fixed set next to
# bench/trace/overload-smoke
fuzz-smoke:
	python scripts/fuzz_smoke.py

# the accelerator fault-tolerance slice: DeviceFault nemesis (hang /
# raise / corrupt) against all three device planes, dispatch deadlines,
# shadow-check corruption attribution, host-twin failover bit-for-bit
# parity, exactly-once pipeline replay, and online rebuild + cutback
test-devicefault:
	python -m pytest tests/ -x -q -m devicefault

# accelerator failover gate: a seeded device hang against a live plane
# — the typed DeviceFailedError is observed, host-twin goodput stays
# nonzero while degraded, cutback costs exactly one counted re-upload,
# and the faulted run's output is bit-for-bit the fault-free run's —
# the per-push CI slice runs this next to fuzz-smoke
failover-smoke:
	python scripts/failover_smoke.py

# Pallas-kernel gate: interpret-mode route-vs-route parity across all
# four fused resolve families (pred/graph step, votes commit, fused
# round), probe verdicts, the executor donation seam, and the
# compile-cache discipline (bounded signatures; zero misses => zero
# true recompiles) — the per-push CI slice runs this next to
# failover-smoke
pallas-smoke:
	python scripts/pallas_smoke.py

# scenario-observatory gate (r20): a declarative spec expands
# byte-identically, a 3-point offered-rate ladder (sim timeline, EPaxos
# n=3) runs to a DETECTED saturation knee with p50/p95/p99 + goodput
# per point, curves.json round-trips through plot/db, the PNG renders
# headless, and `obs curves` passes the spec's SLO verdicts
scenario-smoke:
	python scripts/scenario_smoke.py
