# Test/bench entry points (the reference pins quality with Makefile:3-7 —
# fmt + clippy + `cargo test` under a quickcheck budget; here the suite +
# dryrun + bench are the equivalent gates).
.PHONY: test test-fast test-chaos test-recovery dryrun bench bench-smoke trace-smoke

test:
	python -m pytest tests/ -x -q

# the CI-shrunk load (tests/harness.py COMMANDS_PER_CLIENT, hypothesis
# max_examples both scale down under CI=true)
test-fast:
	CI=true python -m pytest tests/ -x -q -m "not slow"

# the full fault-injection matrix (crash x loss x protocol, including the
# `slow`-marked sweep rows tier-1 skips)
test-chaos:
	python -m pytest tests/test_faults.py -x -q -m chaos

# the recovery slice: per-dot MPrepare/MPromise recovery, noop commits,
# FPaxos leader failover (sim + TCP), and the crashed-coordinator model
# checker rows
test-recovery:
	python -m pytest tests/ -x -q -m recovery

dryrun:
	python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

bench:
	python bench.py

# tiny CPU-sized bench rows (table + Newt serving), in-process: catches
# import breaks and order-of-magnitude regressions in the bench seams
# without a chip — the per-push CI slice runs this
bench-smoke:
	python bench.py --smoke

# observability gate: tiny traced sim, byte-identical same-seed span
# logs, Perfetto conversion + stage-latency report all validate — the
# per-push CI slice runs this next to bench-smoke
trace-smoke:
	python scripts/trace_smoke.py
