"""North-star benchmark: 1M concurrent EPaxos commands at 50% key-conflict,
batched dependency-graph resolution latency on one chip.

Target (BASELINE.json): < 10 ms.  Prints one JSON line:
{"metric": ..., "value": N, "unit": "ms", "vs_baseline": target_ms / N}.

The workload mirrors the reference's ConflictRate key generator
(fantoch/src/client/key_gen.rs:8,87-99): with probability 0.5 a command
touches the single hot key "CONFLICT" (one long dependency chain — the
worst case for the serial Tarjan walk the reference uses,
fantoch_ps/src/executor/graph/tarjan.rs), otherwise a private per-client
key (no deps).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

TARGET_MS = 10.0
BATCH = 1_000_000
CONFLICT = 0.5
ITERS = 10


def build_workload(batch: int, conflict: float, clients: int = 4096):
    """(dep, dot_src, dot_seq): conflicting commands chain on the hot key;
    private commands chain per client (latest-per-key sequential deps)."""
    rng = np.random.default_rng(42)
    hot = rng.random(batch) < conflict
    # key id 0 = hot key; else private per-client key
    key = np.where(hot, 0, 1 + rng.integers(0, clients, size=batch)).astype(np.int64)
    # latest-per-key chain (what KeyDeps::add_cmd produces)
    dep = np.full(batch, -1, dtype=np.int32)
    last = {}
    for i, k in enumerate(key):
        prev = last.get(k)
        if prev is not None:
            dep[i] = prev
        last[k] = i
    dot_src = (1 + rng.integers(0, 5, size=batch)).astype(np.int32)
    dot_seq = np.arange(batch, dtype=np.int32)
    return dep, dot_src, dot_seq


def main() -> None:
    from fantoch_tpu.ops.graph_resolve import resolve_functional

    dep_np, src_np, seq_np = build_workload(BATCH, CONFLICT)
    dep = jax.device_put(jnp.asarray(dep_np))
    src = jax.device_put(jnp.asarray(src_np))
    seq = jax.device_put(jnp.asarray(seq_np))

    # warmup / compile
    res = resolve_functional(dep, src, seq)
    jax.block_until_ready(res.order)
    assert bool(res.resolved.all())

    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        res = resolve_functional(dep, src, seq)
        jax.block_until_ready(res.order)
        times.append((time.perf_counter() - t0) * 1000.0)
    p50 = float(np.median(times))

    print(
        json.dumps(
            {
                "metric": "epaxos_1m_cmds_50pct_conflict_graph_resolve_p50",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / p50, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
