"""North-star benchmark: 1M concurrent EPaxos commands at 50% key-conflict,
batched dependency-graph resolution latency on one chip.

Target (BASELINE.json): < 10 ms.  Prints one JSON line:
{"metric": ..., "value": N, "unit": "ms", "vs_baseline": target_ms / N}.
End-to-end serving rides alongside as a second headline triple
({"serving_metric": "serving_newt_cmds_per_s", "serving_value": N,
"serving_unit": "cmds/s"} — the depth-K pipelined serving loop,
ROADMAP item 1).

The workload mirrors the reference's ConflictRate key generator
(fantoch/src/client/key_gen.rs:8,87-99): with probability 0.5 a command
touches the single hot key "CONFLICT" (one long dependency chain — the
worst case for the serial Tarjan walk the reference uses,
fantoch_ps/src/executor/graph/tarjan.rs), otherwise a private per-client
key (no deps).

Two measurements in one JSON line:
  * value        — raw device-kernel p50 (ms) over 1M commands: the
    graph-resolution latency of the north star;
  * executor_*   — the *integrated* path: the same workload fed as real
    (Dot, Command, deps) adds through BatchedDependencyGraph
    (executor/graph/batched.py), timed end to end including host-side
    batch assembly and the execute-queue drain.

Process architecture (round-1 postmortem: the TPU plugin can block
*indefinitely and uninterruptibly* at backend init — SIGALRM does not break
it, reproduced): the parent process NEVER touches a backend.  It re-execs
itself as a measurement child under a hard timeout; on failure it retries,
then falls back to a CPU-forced child so a number is always captured (the
JSON records which platform it came from).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Tuple

TARGET_MS = 10.0
BATCH = 1_000_000
CONFLICT = 0.5
ITERS = 10
EXECUTOR_BATCH = 250_000  # integrated-path batch (host object assembly bound)

METRIC = "epaxos_1m_cmds_50pct_conflict_graph_resolve_p50"
PROBE_TIMEOUT_S = 90
PROBE_RETRIES = 2
# Cold TPU compiles through the remote-compile tunnel can eat ~450s before
# the secondary measurements even start (observed 2026-07-31: primary +
# executor alone took ~7.5 min uncached); the persistent .jax_cache makes
# warm reruns fast, so the budget only matters on the first run after a
# kernel change.
#  the TPU child runs ~14 min with a warm compile cache; first-time rows
#  (e.g. a new serving family) add minutes of tunnel-side XLA compiles,
#  so leave headroom — a timeout here forfeits the round's chip record
CHILD_TIMEOUT_S = int(os.environ.get("FANTOCH_BENCH_TIMEOUT_S", "1500"))

_CHILD_ENV = "FANTOCH_BENCH_CHILD"  # "tpu" | "cpu"


def slope_timed(run_k, k_lo: int, k_hi: int, iters: int, rounds: int = 3):
    """Shared slope-timing harness: ``run_k(k)`` executes k chained
    resolves in one dispatch and returns a scalar to materialize.  Returns
    (per_op_ms or None if the slope was noise-negative, lo_p50, hi_p50) —
    the slope removes the rig's fixed per-dispatch round-trip (~80 ms
    measured), which would otherwise mask a <10 ms kernel.

    The slope is the median over ``rounds`` independent (lo, hi) passes:
    a single two-point fit over a tunnel whose round-trip jitters by a
    few ms is under-conditioned — one run recorded a 0.129 ms primary
    where three same-day runs of the identical build said 2.3-3.0 ms.
    Interleaving the passes also spreads any slow drift across both
    endpoints instead of biasing one."""
    import numpy as np

    def timed(k):
        out = []
        for _ in range(iters):
            t0 = time.perf_counter()
            float(run_k(k))
            out.append((time.perf_counter() - t0) * 1000.0)
        return float(np.median(out))

    float(run_k(k_lo))  # compile / warm the k_lo program
    float(run_k(k_hi))  # compile / warm the k_hi program
    slopes, los, his = [], [], []
    for _ in range(rounds):
        lo, hi = timed(k_lo), timed(k_hi)
        slopes.append((hi - lo) / (k_hi - k_lo))
        los.append(lo)
        his.append(hi)
    slope = float(np.median(slopes))
    lo, hi = float(np.median(los)), float(np.median(his))
    return (slope if slope > 0 else None), lo, hi


def build_workload(batch: int, conflict: float, clients: int = 4096, seed: int = 42):
    """(key, dep, dot_src, dot_seq): conflicting commands chain on the hot
    key; private commands chain per client (latest-per-key sequential
    deps).  ``key`` is the per-command conflict-key id the protocol knows
    at commit time (KeyDeps is keyed by it)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    hot = rng.random(batch) < conflict
    # key id 0 = hot key; else private per-client key
    key = np.where(hot, 0, 1 + rng.integers(0, clients, size=batch)).astype(np.int32)
    # latest-per-key chain (what KeyDeps::add_cmd produces)
    dep = np.full(batch, -1, dtype=np.int32)
    last = {}
    for i, k in enumerate(key):
        prev = last.get(k)
        if prev is not None:
            dep[i] = prev
        last[k] = i
    dot_src = (1 + rng.integers(0, 5, size=batch)).astype(np.int32)
    dot_seq = np.arange(batch, dtype=np.int32)
    return key, dep, dot_src, dot_seq


def enable_compile_cache(jax_mod=None) -> None:
    """Persistent XLA compilation cache in-repo: first-ever compiles through
    the remote-compile tunnel run minutes; cached reloads run sub-second, so
    the driver's end-of-round bench rides the cache warmed by dev runs.
    Delegates to the shared fantoch_tpu.hostenv helper (also used by
    tests/conftest.py and the multichip dryrun); ``jax_mod`` is accepted
    for caller compatibility and ignored."""
    from fantoch_tpu.hostenv import enable_compile_cache as _enable

    _enable()


def child_main(mode: str) -> None:
    """Measurement child: the only process that touches a jax backend."""
    if mode == "cpu":
        from fantoch_tpu.hostenv import force_cpu_platform

        force_cpu_platform()

    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    enable_compile_cache(jax)

    from fantoch_tpu.observability.device import (
        compile_ms,
        recompile_count,
        subscribe_recompiles,
    )
    from fantoch_tpu.ops.graph_resolve import (
        _residual_size_for,
        resolve_functional_keyed,
    )

    subscribe_recompiles()
    platform = jax.devices()[0].platform

    key_np, dep_np, src_np, seq_np = build_workload(BATCH, CONFLICT)
    key = jax.device_put(jnp.asarray(key_np))
    dep = jax.device_put(jnp.asarray(dep_np))
    src = jax.device_put(jnp.asarray(src_np))
    seq = jax.device_put(jnp.asarray(seq_np))
    residual = _residual_size_for(BATCH)

    # correctness check of the measured kernel on this workload: everything
    # resolves (latest-per-key chains, no cycles, nothing missing)
    res = resolve_functional_keyed(
        key, dep, src, seq, residual_size=residual, return_structure=False
    )
    assert int(res.n_resolved) == BATCH, f"resolved {int(res.n_resolved)}/{BATCH}"
    assert not bool(res.overflow)

    # slope-timed device latency (see slope_timed): K back-to-back resolves
    # inside ONE dispatch, serialized by a real data dependence (order[0]
    # of resolve i perturbs the key batch of resolve i+1 by a runtime zero
    # the compiler cannot fold).  One chain kernel serves both the 1M
    # primary and the chip-only 4M scaling row (residual_size is static).
    @functools.partial(jax.jit, static_argnames=("k", "residual_size"))
    def resolve_chain(key, dep, src, seq, *, k, residual_size):
        carry = jnp.int32(0)
        for _ in range(k):
            r = resolve_functional_keyed(
                key + (carry >> jnp.int32(30)),  # runtime zero, data-dependent
                dep,
                src,
                seq,
                residual_size=residual_size,
                return_structure=False,
            )
            carry = r.order[0]
        return carry + r.n_resolved

    # 1->5 keeps the chained program small: a wider span conditions the
    # slope better on paper, but the k=9 chain is a fresh multi-minute
    # XLA compile over the tunnel (one attempt blew the whole child
    # budget before printing this row) — slope robustness comes from the
    # median-of-rounds in slope_timed instead
    K_LO, K_HI = 1, 5
    slope, lo_p50, hi_p50 = slope_timed(
        lambda k: resolve_chain(key, dep, src, seq, k=k, residual_size=residual),
        K_LO, K_HI, ITERS,
    )
    if slope is not None:
        p50 = slope
        method = (
            f"slope over {K_LO}->{K_HI} chained in-dispatch resolves, "
            f"p50 of {ITERS}; removes the rig's fixed dispatch round-trip"
        )
    else:
        # noise swamped the slope — fall back to the conservative single-call
        # number rather than fabricating a near-zero latency
        p50 = lo_p50
        method = (
            f"single-call p50 of {ITERS} (slope measurement failed: "
            "non-positive median slope across rounds at "
            f"t(K={K_LO})={lo_p50:.1f}ms, t(K={K_HI})={hi_p50:.1f}ms); "
            "includes the rig's fixed dispatch round-trip"
        )

    record = {
        "metric": METRIC,
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p50, 3),
        "platform": platform,
        "method": method,
        "single_call_ms_p50": round(lo_p50, 3),
        "dispatch_overhead_ms": round(lo_p50 - p50, 3),
        "residual_size": residual,
        # XLA backend compiles observed during the resolve warmup+timing
        # (observability plane): >0 with a warm persistent cache means a
        # shape/program change paid compile time inside this row — and
        # the cumulative wall names what the count hides (one cold
        # resolve_graph_plane_step program costs ~50s on a 1-core host)
        "graph_resolve_recompiles": recompile_count(),
        "jax_compile_ms": compile_ms(),
    }
    # print the primary measurement NOW: if a secondary measurement hangs
    # past the parent's timeout, the parent still recovers this line from
    # the killed child's partial stdout (it takes the last valid line)
    print(json.dumps(record), flush=True)
    def bench_scale_4m() -> dict:
        """Chip-only scaling row (runs LAST: its fresh 4M-shape compile
        must never cost the budget the executor/serving/pool rows need):
        4x the north-star batch, correctness-checked before timing; the
        ratio to the 1M number is reported only when both came from the
        slope method (mixing a slope with a dispatch-laden single call
        would make the ratio meaningless).  Local scope: the ~80 MB of
        device buffers free on every exit path."""
        b4 = 4 * BATCH
        k4_np, d4_np, s4_np, q4_np = build_workload(b4, CONFLICT)
        res4 = _residual_size_for(b4)
        key4 = jax.device_put(jnp.asarray(k4_np))
        dep4 = jax.device_put(jnp.asarray(d4_np))
        src4 = jax.device_put(jnp.asarray(s4_np))
        seq4 = jax.device_put(jnp.asarray(q4_np))
        check = resolve_functional_keyed(
            key4, dep4, src4, seq4, residual_size=res4, return_structure=False
        )
        assert int(check.n_resolved) == b4, (
            f"4M workload resolved {int(check.n_resolved)}/{b4}"
        )
        assert not bool(check.overflow)
        slope4, lo4, _hi4 = slope_timed(
            lambda k: resolve_chain(key4, dep4, src4, seq4, k=k, residual_size=res4),
            1, 3, 5,
        )
        out = {
            "scale_batch": b4,
            "scale_ms": round(slope4 if slope4 is not None else lo4, 3),
            "scale_method": "slope 1->3" if slope4 is not None else "single-call",
        }
        if slope4 is not None and slope is not None:
            out["scale_vs_1m"] = round(slope4 / p50, 2)
        return out

    # secondary measurements must never cost us the primary one
    try:
        exec_ms, exec_cmds_per_s, order_ms = bench_integrated_executor()
        record.update(
            executor_batch=EXECUTOR_BATCH,
            executor_ms=round(exec_ms, 1),
            executor_cmds_per_s=int(exec_cmds_per_s),
            executor_order_ms=round(order_ms, 1),
            executor_order_cmds_per_s=int(EXECUTOR_BATCH / (order_ms / 1000.0)),
        )
    except Exception as exc:  # noqa: BLE001 — report, don't die
        print(f"# integrated-executor bench failed: {exc!r}", file=sys.stderr)
        record["executor_error"] = repr(exc)[:200]
    print(json.dumps(record), flush=True)
    try:
        record.update(bench_general_path())
    except Exception as exc:  # noqa: BLE001
        print(f"# general-path bench failed: {exc!r}", file=sys.stderr)
        record["general_error"] = repr(exc)[:200]
    try:
        record.update(bench_native_resolver(key_np, dep_np, src_np, seq_np))
    except Exception as exc:  # noqa: BLE001
        print(f"# native-resolver bench failed: {exc!r}", file=sys.stderr)
        record["native_error"] = repr(exc)[:200]
    try:
        record.update(bench_table_path())
    except Exception as exc:  # noqa: BLE001
        print(f"# table-path bench failed: {exc!r}", file=sys.stderr)
        record["table_error"] = repr(exc)[:200]
    try:
        record.update(bench_pred_path())
    except Exception as exc:  # noqa: BLE001
        print(f"# pred-path bench failed: {exc!r}", file=sys.stderr)
        record["pred_error"] = repr(exc)[:200]
    try:
        record.update(bench_graph_plane())
    except Exception as exc:  # noqa: BLE001
        print(f"# graph-plane bench failed: {exc!r}", file=sys.stderr)
        record["graph_plane_error"] = repr(exc)[:200]
    try:
        # pure asyncio + tiny kernels: rides both children unchanged
        record.update(bench_pred_serving())
    except Exception as exc:  # noqa: BLE001
        print(f"# pred-serving bench failed: {exc!r}", file=sys.stderr)
        record["pred_serving_error"] = repr(exc)[:200]
    try:
        record.update(bench_device_serving())
        if "serving_newt_cmds_per_s" in record:
            # end-to-end serving is a HEADLINE metric next to the kernel
            # p50 (ROADMAP item 1): the pipelined Newt serving loop's
            # cmds/s, promoted to its own top-level metric triple, with
            # the r16 occupancy gauge riding along — throughput without
            # fill is half a story (empty rounds can post big cmds/s on
            # a full feed while starving under real arrivals)
            record["serving_metric"] = "serving_newt_cmds_per_s"
            record["serving_value"] = record["serving_newt_cmds_per_s"]
            record["serving_unit"] = "cmds/s"
            record["serving_fill_frac"] = record.get(
                "serving_newt_dispatch_fill_frac", 0.0
            )
    except Exception as exc:  # noqa: BLE001
        print(f"# device-serving bench failed: {exc!r}", file=sys.stderr)
        record["serving_error"] = repr(exc)[:200]
    try:
        # the r16 adaptive-ingest row: open-loop arrivals at 2x this
        # rig's saturation through the batched+chained serving loop vs
        # the legacy dispatch-on-anything loop
        record.update(bench_serving_batched())
    except Exception as exc:  # noqa: BLE001
        print(f"# batched-serving bench failed: {exc!r}", file=sys.stderr)
        record["serving_ingest_error"] = repr(exc)[:200]
    try:
        record.update(bench_local_pool())
    except Exception as exc:  # noqa: BLE001
        print(f"# local-pool bench failed: {exc!r}", file=sys.stderr)
        record["pool_error"] = repr(exc)[:200]
    try:
        # latency-under-load curve (overload plane): pure asyncio, so it
        # rides both the cpu and tpu children unchanged
        record.update(bench_overload())
    except Exception as exc:  # noqa: BLE001
        print(f"# overload bench failed: {exc!r}", file=sys.stderr)
        record["overload_error"] = repr(exc)[:200]
    try:
        # r20 scenario-curve row: deterministic virtual-time sim, so it
        # rides both children unchanged (no backend in the loop)
        record.update(bench_curve())
    except Exception as exc:  # noqa: BLE001
        print(f"# curve bench failed: {exc!r}", file=sys.stderr)
        record["curve_error"] = repr(exc)[:200]
    try:
        # accelerator failover drill (fault plane, r17): rides both
        # children — the table plane + injector are backend-agnostic
        record.update(bench_failover())
    except Exception as exc:  # noqa: BLE001
        print(f"# failover bench failed: {exc!r}", file=sys.stderr)
        record["failover_error"] = repr(exc)[:200]
    try:
        # r19 route-vs-route kernel races: interpret-mode parity rows on
        # the cpu child, Mosaic-lowered fusion rows on the tpu child
        record.update(bench_pallas_resolve())
        record.update(bench_table_pallas())
    except Exception as exc:  # noqa: BLE001
        print(f"# pallas bench failed: {exc!r}", file=sys.stderr)
        record["pallas_error"] = repr(exc)[:200]
    # scaling row last and chip only: CPU sorts at 4M would eat the
    # fallback child's whole budget, and a cold 4M compile must not
    # crowd out the rows above on first run after a kernel change
    if platform != "cpu":
        try:
            record.update(bench_scale_4m())
        except Exception as exc:  # noqa: BLE001 — scaling row is optional
            print(f"# 4M scaling bench failed: {exc!r}", file=sys.stderr)
            record["scale_error"] = repr(exc)[:200]

    print(json.dumps(record), flush=True)


def bench_integrated_executor():
    """Time the integrated executor path: commands crossing the
    Protocol/Executor boundary *as arrays* (the commit-buffer seam,
    BatchedDependencyGraph.handle_add_arrays) including batch assembly,
    the device resolve and the execute-queue drain.
    Returns (wall ms with the Command-object drain, commands/s, wall ms
    with the array drain — order as (src, seq) columns, no 250k-object
    materialization)."""
    import numpy as np

    from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
    from fantoch_tpu.executor.graph.batched import BatchedDependencyGraph
    from fantoch_tpu.ops.frontier import pack_dots

    shard = 0
    key_np, dep_np, src_np, seq_np = build_workload(EXECUTOR_BATCH, CONFLICT)
    # dots: (source, arrival+1); dep column -> packed dep dots
    dot_seq = seq_np.astype(np.int64) + 1
    dot_src = src_np.astype(np.int64)
    has_dep = dep_np >= 0
    dep_idx = np.where(has_dep, dep_np, 0)
    dep_dots = np.where(
        has_dep, pack_dots(dot_src[dep_idx], dot_seq[dep_idx]), -1
    ).reshape(-1, 1)
    # the command arena the protocol would hold anyway (not timed: these
    # objects exist at submit time in any design)
    cmds = [
        Command.from_keys(Rifl(1, i + 1), shard, {f"k{i}": (KVOp.put(""),)})
        for i in range(EXECUTOR_BATCH)
    ]

    clock = RunTime()

    def run_once(array_drain=False):
        graph = BatchedDependencyGraph(
            1, shard, Config(5, 2, batched_graph_executor=True)
        )
        graph.record_order_arrays = array_drain
        t0 = time.perf_counter()
        graph.handle_add_arrays(dot_src, dot_seq, key_np, dep_dots, cmds, clock)
        if array_drain:
            graph.resolve_now(clock)
            order_src, _order_seq = graph.take_order_arrays()
            executed = len(order_src)
        else:
            executed = len(graph.commands_to_execute())
        wall_ms = (time.perf_counter() - t0) * 1000.0
        assert executed == EXECUTOR_BATCH, f"executed {executed}/{EXECUTOR_BATCH}"
        return wall_ms

    run_once()  # warm the XLA compile cache for this batch shape
    wall_ms = min(run_once() for _ in range(3))
    order_ms = min(run_once(array_drain=True) for _ in range(3))
    return wall_ms, EXECUTOR_BATCH / (wall_ms / 1000.0), order_ms


def bench_local_pool(total: int = 1 << 19, conflict: float = 0.5):
    """Multi-process host scaling (VERDICT r4 #8): aggregate ordering
    throughput through N key-sharded worker processes
    (run/local_pool.OrderingPool — the pool.rs analog at process
    granularity) at N=1 and N=4.  Records cpu_count so the scaling
    ratio is interpretable: on a single-core host 4 processes cannot
    beat 1 (they time-slice), and the row says so instead of hiding it.
    """
    import multiprocessing as mp

    import numpy as np

    from fantoch_tpu.run.local_pool import OrderingPool

    out = {"pool_total": total, "pool_cpus": mp.cpu_count()}
    # disjoint dot ranges: chunk A warms each worker's compile/native
    # load, chunks B and C are measured runs (re-adding the same dots
    # would violate the committed-once invariant).  Each arm takes the
    # better of the two measured chunks: one measured run per arm once
    # recorded pool_scaling_4w = 2.92 on a ONE-core host — the 1w arm had
    # absorbed a burst of unrelated host activity, and a single sample
    # can't tell that from real scaling.
    key_a, dep_a, src_a, seq_a = build_workload(total, conflict, seed=21)
    measured = [
        build_workload(total, conflict, seed=22),
        build_workload(total, conflict, seed=23),
    ]
    thr = {}
    for workers in (1, 4):
        shards_a = OrderingPool.shard_columns(
            key_a, src_a.astype(np.int64), seq_a.astype(np.int64) + 1,
            dep_a.astype(np.int64), workers,
        )
        shard_runs = [
            OrderingPool.shard_columns(
                key_m, src_m.astype(np.int64),
                seq_m.astype(np.int64) + 1 + (i + 1) * total,
                dep_m.astype(np.int64), workers,
            )
            for i, (key_m, dep_m, src_m, seq_m) in enumerate(measured)
        ]
        # pipelined pool serving (4w only): the run/pipeline.py
        # dispatch/drain split at the pool seam — both chunks in flight
        # so IPC serialization of chunk k+1 overlaps the workers'
        # ordering of chunk k.  Fresh dot ranges: re-adding measured
        # dots would violate the committed-once invariant.
        pipe_runs = []
        if workers == 4:
            pipe_runs = [
                OrderingPool.shard_columns(
                    key_m, src_m.astype(np.int64),
                    seq_m.astype(np.int64) + 1 + (i + 3) * total,
                    dep_m.astype(np.int64), workers,
                )
                for i, (key_m, dep_m, src_m, seq_m) in enumerate(
                    build_workload(total, conflict, seed=s) for s in (24, 25)
                )
            ]
        all_shards = shards_a + [
            s for run in shard_runs + pipe_runs for s in run
        ]
        with OrderingPool(workers) as pool:
            pool.prepare(max(len(s[0]) for s in all_shards))
            pool.run_shards(shards_a)  # warm
            dt = None
            for shards_m in shard_runs:
                t0 = time.perf_counter()
                orders = pool.run_shards(shards_m)
                run_dt = time.perf_counter() - t0
                executed = sum(len(src) for src, _ in orders)
                assert executed == total, f"pool ordered {executed}/{total}"
                dt = run_dt if dt is None else min(dt, run_dt)
            if pipe_runs:
                t0 = time.perf_counter()
                order_runs = pool.run_shards_pipelined(pipe_runs, depth=1)
                pipe_dt = time.perf_counter() - t0
                executed = sum(
                    len(src) for orders in order_runs for src, _ in orders
                )
                want = len(pipe_runs) * total
                assert executed == want, f"pool ordered {executed}/{want}"
                out["pool_cmds_per_s_4w_pipelined"] = int(executed / pipe_dt)
        thr[workers] = total / dt
        out[f"pool_ms_{workers}w"] = round(dt * 1000.0, 1)
        out[f"pool_cmds_per_s_{workers}w"] = int(thr[workers])
    out["pool_scaling_4w"] = round(thr[4] / thr[1], 2)
    if out["pool_cpus"] < 4:
        # BENCH_r05 recorded pool_scaling_4w 0.58 with pool_cpus 1: on a
        # host with fewer cores than workers the 4w arm time-slices, so
        # the ratio measures contention, not scaling — say so in-record
        # instead of letting downstream readers book it as a regression
        out["pool_scaling_note"] = (
            f"host has {out['pool_cpus']} cpu(s) for 4 workers: "
            "pool_scaling_4w reflects time-slicing contention, not "
            "scaling; compare only across runs with pool_cpus >= 4"
        )
    return out


def bench_general_path(batch: int = 1 << 18, width: int = 4):
    """Slope-timed ``resolve_general`` on a multi-key workload (VERDICT r2
    weak #7: the general path had never been measured).  Commands carry up
    to ``width`` deps: the latest command on each of their keys — the
    dominant all-backward shape, which takes the arrival-order fast path.
    ``general_fallback_*`` forces the iterative branch on the same graph at
    a smaller batch and reports how much of it converges within the default
    budget (deep alternating chains are the honest worst case: resolution
    there is depth-bound, the remainder goes to the host oracle as stuck)."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fantoch_tpu.ops.graph_resolve import TERMINAL, resolve_general

    rng = np.random.default_rng(7)
    keys = rng.integers(0, 4096, size=(batch, width))  # one dep slot per key
    deps = np.full((batch, width), TERMINAL, dtype=np.int32)
    last = {}
    for i in range(batch):
        slot = 0
        for k in keys[i]:
            prev = last.get(k)
            # prev != i: a row repeating a key must not depend on itself
            # (KeyDeps returns the previous latest, never the command)
            if prev is not None and prev != i and slot < width:
                deps[i, slot] = prev
                slot += 1
            last[k] = i
    dmat = jax.device_put(jnp.asarray(deps))
    src = jax.device_put(jnp.asarray((1 + rng.integers(0, 5, size=batch)).astype(np.int32)))
    seq = jax.device_put(jnp.asarray(np.arange(batch, dtype=np.int32)))

    @functools.partial(jax.jit, static_argnames=("k",))
    def resolve_k(dmat, src, seq, *, k):
        carry = jnp.int32(0)
        for _ in range(k):
            r = resolve_general(dmat + (carry >> jnp.int32(30)), src, seq)
            carry = r.order[0]
        return carry + r.resolved.sum()

    slope, lo, _hi = slope_timed(
        lambda k: resolve_k(dmat, src, seq, k=k), 1, 3, 5
    )
    out = {
        "general_batch": batch,
        "general_width": width,
        "general_ms": round(slope if slope is not None else lo, 3),
        "general_method": "slope 1->3" if slope is not None else "single-call",
    }

    # the adversarial fallback (VERDICT r3 weak #3): arrival order is a
    # random permutation, so deps point forward as often as backward and
    # the arrival-order fast path cannot apply.  Measured through the
    # *integrated* executor seam — the combined device-budget + host
    # stuck-finish path that actually serves this shape — and it must
    # fully resolve (the r3 kernel-only measurement stalled at 55%).
    from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
    from fantoch_tpu.executor.graph.batched import BatchedDependencyGraph
    from fantoch_tpu.ops.frontier import pack_dots

    fb = batch // 8
    rng2 = np.random.default_rng(13)
    perm = rng2.permutation(fb)
    inv = np.empty(fb, np.int64)
    inv[perm] = np.arange(fb)
    d_sub = deps[:fb]
    # renumber rows through the permutation: row i of the adversarial
    # batch is old row inv[i]; its deps map through perm
    adv = np.where(
        d_sub[inv] >= 0, perm[np.clip(d_sub[inv], 0, fb - 1)], -1
    ).astype(np.int64)
    dot_src_fb = np.ones(fb, dtype=np.int64)
    dot_seq_fb = (inv + 1).astype(np.int64)  # dot = original arrival id
    dep_dots = np.where(adv >= 0, pack_dots(np.ones_like(adv), inv[np.clip(adv, 0, fb - 1)] + 1), -1)
    key_col = np.full(fb, -1, dtype=np.int32)  # multi-key: general path
    cmds = [
        Command.from_keys(Rifl(1, i + 1), 0, {f"g{i}": (KVOp.put(""),)})
        for i in range(fb)
    ]
    clock = RunTime()

    def run_fb():
        graph = BatchedDependencyGraph(
            1, 0, Config(5, 2, batched_graph_executor=True)
        )
        t0 = time.perf_counter()
        graph.handle_add_arrays(dot_src_fb, dot_seq_fb, key_col, dep_dots, cmds, clock)
        executed = len(graph.commands_to_execute())
        ms = (time.perf_counter() - t0) * 1000.0
        return ms, executed

    run_fb()  # warm
    results = [run_fb() for _ in range(3)]
    best = min(ms for ms, _ in results)
    executed = results[0][1]

    # the headline fallback number is SLOPE-TIMED over the in-dispatch
    # resident peel-and-compact resolver (resolve_general_resident, r13)
    # — the same `slope 1->3` method as `general_method`, so the rig's
    # fixed ~68 ms dispatch round-trip no longer pollutes the key (the
    # pre-r13 one-shot executor-seam wall stays as
    # general_fallback_seam_ms; resolved_frac still comes from the
    # integrated seam and must be 1.0)
    from fantoch_tpu.ops.graph_resolve import resolve_general_resident

    adv32 = jax.device_put(jnp.asarray(adv.astype(np.int32)))
    fsrc = jax.device_put(jnp.asarray(dot_src_fb.astype(np.int32)))
    fseq = jax.device_put(jnp.asarray(dot_seq_fb.astype(np.int32)))

    @functools.partial(jax.jit, static_argnames=("k",))
    def fallback_k(dmat, src, seq, *, k):
        carry = jnp.int32(0)
        for _ in range(k):
            r = resolve_general_resident(
                dmat + (carry >> jnp.int32(30)), src, seq
            )
            carry = r.order[0]
        return carry + r.resolved.sum()

    fb_slope, fb_lo, _fb_hi = slope_timed(
        lambda k: fallback_k(adv32, fsrc, fseq, k=k), 1, 3, 5
    )
    out.update(
        general_fallback_batch=fb,
        general_fallback_ms=round(
            fb_slope if fb_slope is not None else fb_lo, 3
        ),
        general_fallback_method=(
            "slope 1->3" if fb_slope is not None else "single-call"
        ),
        general_fallback_definition=(
            "chained-slope over the in-dispatch resident peel-and-compact "
            "resolver (r13); pre-r13 rows measured the one-shot executor "
            "seam incl. the dispatch round-trip (kept as "
            "general_fallback_seam_ms)"
        ),
        general_fallback_seam_ms=round(best, 3),
        general_fallback_resolved_frac=round(executed / fb, 4),
    )
    return out


def bench_native_resolver(key_np, dep_np, src_np, seq_np):
    """The native C++ host resolver (fantoch_tpu/native — the Rust-Tarjan
    twin) on the same 1M-command workload: the framework's host-side
    ordering path, reported for comparison on every platform."""
    import numpy as np

    from fantoch_tpu import native
    from fantoch_tpu.ops.frontier import pack_dots

    if not native.available():
        return {"native_ms": None}
    n = len(dep_np)
    has_dep = dep_np >= 0
    offsets = np.zeros(n + 1, dtype=np.int32)
    offsets[1:] = np.cumsum(has_dep.astype(np.int32))
    targets = dep_np[has_dep].astype(np.int32)
    packed = pack_dots(src_np.astype(np.int64), seq_np.astype(np.int64))

    order, _sizes = native.resolve_sccs(offsets, targets, packed)  # warm/load
    assert len(order) == n
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        order, _sizes = native.resolve_sccs(offsets, targets, packed)
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return {"native_ms": round(best, 3)}


def bench_pred_path(
    batch: int = 4096, keys: int = 512, rounds: int = 3, width: int = 3
):
    """Caesar's predecessors plane (ROADMAP item 4): ``rounds``
    steady-state batches of committed commands through the resident
    device plane (``Config.device_pred_plane`` ->
    executor/pred_plane.DevicePredPlane, one donated dispatch per batch)
    against the per-info host ``PredecessorsGraph`` twin.  The workload
    is the serving shape: commands over ``keys`` conflict keys, each
    depending on up to ``width`` lower-clock predecessors of its keys,
    arriving in commit order with a cross-batch residual seam (the last
    command of each batch depends on one from the NEXT batch staying
    missing until it commits).  The timed region is the ORDERING layer
    (feed -> per-key execution order; KVStore execution costs the same
    on both twins and is excluded), the plane fed through the arrays
    seam exactly as Caesar feeds it.  Per-key order parity is asserted
    in-row; the first batch is excluded from timing (compile + lazy
    materialization)."""
    import numpy as np

    from fantoch_tpu.core import Config, Dot, KVOp, Rifl
    from fantoch_tpu.core.command import Command
    from fantoch_tpu.executor.pred import PredecessorsExecutionInfo
    from fantoch_tpu.protocol.common.pred_clocks import Clock

    rng = np.random.default_rng(17)
    total = batch * (rounds + 2)  # 2 warm rounds (see below) + measured
    per_key: dict = {}
    infos = []
    for i in range(total):
        src = 1 + (i % 3)
        dot = Dot(src, i // 3 + 1)
        ks = [f"pk{rng.integers(0, keys)}"]
        deps = set()
        for k in ks:
            hist = per_key.setdefault(k, [])
            deps.update(hist[-width:])
            hist.append(dot)
        cmd = Command.from_single(
            Rifl(1, i + 1), 0, ks[0], KVOp.put("")
        )
        infos.append(PredecessorsExecutionInfo(dot, cmd, Clock(i + 1, src), deps))
    batches = [infos[i : i + batch] for i in range(0, total, batch)]
    # the cross-batch residual seam: defer each batch's FIRST command
    # (whose same-key successors arrive later in the same batch) to the
    # next batch, so every round carries missing-blocked rows that stay
    # resident (plane) / pending-indexed (host) until the following feed
    # commits their dependency
    for i in range(len(batches) - 1):
        batches[i][0], batches[i + 1][-1] = batches[i + 1][-1], batches[i][0]

    def drain_orders(graph, orders: dict) -> None:
        """Drain command_to_execute into per-key rifl order (the
        agreement contract conflicting commands care about)."""
        while True:
            cmd = graph.command_to_execute()
            if cmd is None:
                return
            for key in cmd.keys(0):
                orders.setdefault(key, []).append(cmd.rifl)

    warm = 2  # round 0 compiles the install shape, round 1 the patched
    # (residual re-feed) shape; steady state starts at round 2

    def run_host():
        from fantoch_tpu.executor.pred import PredecessorsGraph

        graph = PredecessorsGraph(1, Config(3, 1))
        orders: dict = {}
        for b in batches[:warm]:  # symmetry with the compile rounds
            for info in b:
                graph.add(info.dot, info.cmd, info.clock, info.deps, None)
            drain_orders(graph, orders)
        t0 = time.perf_counter()
        for b in batches[warm:]:
            for info in b:
                graph.add(info.dot, info.cmd, info.clock, info.deps, None)
            drain_orders(graph, orders)
        return orders, time.perf_counter() - t0

    def run_plane():
        from fantoch_tpu.executor.pred import PredArraysBuilder
        from fantoch_tpu.executor.pred_plane import DevicePredPlane

        def to_arrays(b):
            builder = PredArraysBuilder()
            for info in b:
                builder.add_commit(info.dot, info.cmd, info.clock, info.deps)
            return builder.take()

        abatches = [to_arrays(b) for b in batches]
        plane = DevicePredPlane(1, Config(3, 1))
        orders: dict = {}
        for b in abatches[:warm]:  # compile + lazy materialization
            plane.add_arrays(b, None)
            drain_orders(plane, orders)
        t0 = time.perf_counter()
        for b in abatches[warm:]:
            plane.add_arrays(b, None)
            drain_orders(plane, orders)
        return plane, orders, time.perf_counter() - t0

    host_orders, host_dt = run_host()
    plane, plane_orders, plane_dt = run_plane()
    # parity gate: identical per-key execution order on both twins
    assert plane_orders == host_orders, "pred plane diverged from host twin"
    assert sum(len(v) for v in plane_orders.values()) == total
    measured = total - warm * batch
    return {
        "pred_plane_definition": (
            "steady-state resident ordering dispatches (arrays feed) vs "
            "the per-info host PredecessorsGraph twin, per-key order "
            "parity asserted in-row; two warm rounds (compile + "
            "materialization + patched shape) excluded (r13)"
        ),
        "pred_plane_batch": batch,
        "pred_plane_rounds": rounds,
        "pred_plane_ms": round(plane_dt * 1000.0, 1),
        "pred_plane_cmds_per_s": int(measured / plane_dt),
        "pred_host_ms": round(host_dt * 1000.0, 1),
        "pred_host_cmds_per_s": int(measured / host_dt),
        "pred_plane_speedup": round(host_dt / plane_dt, 2),
        "pred_plane_dispatches": plane.dispatches,
        "pred_plane_grows": plane.grows,
        "pred_plane_new_rows": plane.stats["new_rows"],
        "pred_plane_update_capacity": plane.stats["update_capacity"],
        "pred_plane_residual_rows": plane.stats["residual_rows"],
        "pred_plane_compactions": plane.stats["compactions"],
        "pred_plane_kernel_ms": round(plane.stats["kernel_ms"], 3),
        "pred_plane_resident_uploads": plane.resident_uploads,
    }


def bench_graph_plane(
    batch: int = 4096, keys: int = 512, rounds: int = 3, pipeline_depth: int = 2
):
    """The resident graph backlog (ROADMAP item 5's remainder):
    ``rounds`` steady-state feeds of committed commands through the
    device graph plane (``Config.device_graph_plane`` ->
    executor/graph/graph_plane.DeviceGraphPlane, one donated dispatch
    per feed with only the emitted order fetched back) against the
    host-column ``BatchedDependencyGraph`` twin (whole-backlog
    ``jnp.asarray`` re-upload per resolve), BOTH pinned to the XLA
    kernels — the row isolates residency, not resolver choice.  The
    workload is the EPaxos serving shape: single-key latest-per-key
    chains over ``keys`` conflict keys arriving in commit order through
    the arrays seam, with a cross-batch residual seam (each batch's
    first command defers to the next batch, so every round carries
    missing-blocked rows that stay resident / re-join the host columns
    until the following feed commits their dependency).  Per-key order
    parity is asserted in-row; the first two rounds are excluded from
    timing (compile + lazy materialization + the patched shape).  The
    pipelined variant runs the same feeds at depth-K delivery lag and
    must drain the identical order."""
    import numpy as np

    from fantoch_tpu.core import Command, Config, KVOp, Rifl, RunTime
    from fantoch_tpu.executor.graph.batched import (
        BatchedDependencyGraph,
        key_hash,
    )

    clock = RunTime()
    rng = np.random.default_rng(23)
    total = batch * (rounds + 2)  # 2 warm rounds + measured
    last = {}
    rows = []
    for i in range(total):
        k = int(rng.integers(0, keys))
        prev = last.get(k)
        last[k] = i + 1
        rows.append(
            (i + 1, key_hash(f"gk{k}"), ((1 << 32) | prev) if prev else -1)
        )
    batches = [rows[i : i + batch] for i in range(0, total, batch)]
    # the cross-batch residual seam (the bench_pred_path move): defer
    # each batch's FIRST command to the next batch, so every round
    # leaves missing-blocked rows behind
    for i in range(len(batches) - 1):
        batches[i][0], batches[i + 1][-1] = batches[i + 1][-1], batches[i][0]
    feeds = []
    for b in batches:
        src = np.ones(len(b), dtype=np.int64)
        seq = np.array([r[0] for r in b], dtype=np.int64)
        key = np.array([r[1] for r in b], dtype=np.int32)
        dd = np.array([[r[2]] for r in b], dtype=np.int64)
        cmds = [
            Command.from_single(Rifl(1, int(s)), 0, f"g{int(k)}", KVOp.put(""))
            for s, k in zip(seq, key)
        ]
        feeds.append((src, seq, key, dd, cmds))

    warm = 2

    def drain_orders(graph, orders: dict) -> None:
        while True:
            cmd = graph.command_to_execute()
            if cmd is None:
                return
            for k in cmd.keys(0):
                orders.setdefault(k, []).append(cmd.rifl)

    def run(plane: bool, depth: int = 1):
        config = Config(
            3, 1, host_native_resolver=False, batched_graph_executor=True,
            device_graph_plane=plane,
        )
        graph = BatchedDependencyGraph(1, 0, config)
        if plane:
            graph._plane.pipeline_depth = depth
            # a window covering the run keeps resident_uploads at
            # exactly 1: steady-state residency, no compaction re-uploads
            # (slots bump exactly to `total`; the blocked residue rides
            # within it)
            graph._plane.reserve(total)
        orders: dict = {}
        for feed in feeds[:warm]:
            graph.handle_add_arrays(*feed, clock)
            drain_orders(graph, orders)
        # kernel_ms is a running tally: exclude the warm rounds' wall
        # (the compile rounds would otherwise dominate the stamped key
        # and flap the --regress gate with cache state)
        warm_kernel_ms = graph._plane.stats["kernel_ms"] if plane else 0.0
        t0 = time.perf_counter()
        for feed in feeds[warm:]:
            graph.handle_add_arrays(*feed, clock)
            drain_orders(graph, orders)
        if plane:
            graph.flush_plane_pipeline(clock)
        else:
            graph.resolve_now(clock)
        drain_orders(graph, orders)
        dt = time.perf_counter() - t0
        return graph, orders, dt, warm_kernel_ms

    _g_host, host_orders, host_dt, _ = run(plane=False)
    g_plane, plane_orders, plane_dt, warm_kernel_ms = run(plane=True)
    g_pipe, pipe_orders, pipe_dt, _ = run(plane=True, depth=pipeline_depth)
    # parity gate: identical per-key execution order on all three
    assert plane_orders == host_orders, "graph plane diverged from host twin"
    assert pipe_orders == host_orders, "pipelined plane diverged"
    assert sum(len(v) for v in plane_orders.values()) == total
    plane = g_plane._plane
    measured = total - warm * batch
    return {
        "graph_plane_definition": (
            "steady-state resident feeds (arrays seam, single-key "
            "serving chains + cross-batch residual seam) vs the "
            "host-column BatchedDependencyGraph twin, both XLA-pinned; "
            "per-key order parity asserted in-row; two warm rounds "
            "excluded (r14)"
        ),
        "graph_plane_batch": batch,
        "graph_plane_rounds": rounds,
        "graph_plane_ms": round(plane_dt * 1000.0, 1),
        "graph_plane_cmds_per_s": int(measured / plane_dt),
        "graph_host_ms": round(host_dt * 1000.0, 1),
        "graph_host_cmds_per_s": int(measured / host_dt),
        "graph_plane_speedup": round(host_dt / plane_dt, 2),
        "graph_plane_pipelined_cmds_per_s": int(measured / pipe_dt),
        "graph_plane_pipeline_depth": pipeline_depth,
        "graph_plane_dispatches": plane.dispatches,
        "graph_plane_grows": plane.grows,
        "graph_plane_new_rows": plane.stats["new_rows"],
        "graph_plane_update_capacity": plane.stats["update_capacity"],
        "graph_plane_patched_cells": plane.stats["patched_cells"],
        "graph_plane_residual_rows": plane.stats["residual_rows"],
        "graph_plane_compactions": plane.stats["compactions"],
        "graph_plane_kernel_ms": round(
            plane.stats["kernel_ms"] - warm_kernel_ms, 3
        ),
        "graph_plane_resident_uploads": plane.resident_uploads,
        "graph_plane_slot_capacity": plane._cap,
    }


def bench_pred_serving(commands_per_client: int = 30, clients: int = 3):
    """Caesar SERVING through the pred plane (ROADMAP item 4's
    remainder): a localhost n=3 TCP cluster — the real
    protocol/executor path (process_runner -> PredArraysBuilder column
    drains -> PredecessorsExecutor -> DevicePredPlane) — closed-loop,
    vs the identical cluster with the plane off.  Pure run-layer row
    (boot + TCP + asyncio dominate on CPU; the plane is asserted
    ENGAGED via its dispatch counters rather than expected to win the
    wall here — the ordering-layer win is bench_pred_path, the chip
    numbers are the TPU-rig rows)."""
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.core import Config
    from fantoch_tpu.protocol import Caesar
    from fantoch_tpu.run.harness import run_overload_phase

    def workload():
        return Workload(
            shard_count=1,
            key_gen=ConflictRateKeyGen(30),
            keys_per_command=1,
            commands_per_client=commands_per_client,
            payload_size=16,
        )

    def run(plane: bool):
        config = Config(
            n=3, f=1,
            gc_interval_ms=50,
            executor_executed_notification_interval_ms=50,
            device_pred_plane=plane,
        )
        return run_overload_phase(Caesar, config, workload(), clients)

    host = run(plane=False)
    served = run(plane=True)
    device = served["device"]
    assert device.get("pred_plane_dispatches", 0) > 0, (
        "the pred plane did not carry the serving run"
    )
    return {
        "pred_plane_serving_definition": (
            "closed-loop localhost Caesar n=3 TCP serving through the "
            "resident pred plane (PredArraysBuilder column drains) vs "
            "the plane-off twin; run-layer wall, plane engagement "
            "asserted via dispatch counters (r14)"
        ),
        "pred_plane_serving_cmds_per_s": served["goodput_cmds_per_s"],
        "pred_plane_serving_p50_ms": served["p50_ms"],
        "pred_plane_serving_host_cmds_per_s": host["goodput_cmds_per_s"],
        "pred_plane_serving_host_p50_ms": host["p50_ms"],
        "pred_plane_serving_dispatches": device.get("pred_plane_dispatches", 0),
        "pred_plane_serving_resident_uploads": device.get(
            "pred_plane_resident_uploads", 0
        ),
    }


def bench_table_path(
    batch: int = 100_000, keys: int = 4096, n: int = 3, rounds: int = 3
):
    """The Newt/Tempo table path (VERDICT r3 item 2): ``batch`` single-key
    commands through the kernel-batched clock proposal
    (BatchedKeyClocks.proposal_batch -> ops/table_ops.batched_clock_proposal)
    and one vectorized executor stability pass
    (TableExecutor.handle_batch -> ops/table_ops.stable_clocks), against
    the sequential host twins (SequentialKeyClocks.proposal +
    per-info VotesTable stability — the reference's per-command path,
    sequential.rs:36-47 / mod.rs:247-270).

    Since r06 the headline arrays number (``table_cmds_per_s_arrays``) is
    STEADY-STATE: ``rounds`` consecutive batches through persistent
    clock/executor instances, so the resident device clock table
    (resident_clock_proposal) and the executor's per-key state amortize
    the way a serving process amortizes them; the old fresh-instance
    one-shot stays as ``table_cmds_per_s_arrays_cold``.  The
    device-resident votes-table plane (``Config.device_table_plane``,
    executor/table_plane.py) gets its own steady-state row, and
    ``table_fused_*`` measures the all-device fused round chain
    (ops/table_ops.fused_table_rounds: proposal + vote coalescing +
    frontier update + stability, S rounds per dispatch — kernel-only,
    the chip path)."""
    import numpy as np

    from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
    from fantoch_tpu.core.ids import process_ids
    from fantoch_tpu.executor.table import TableExecutor, TableVotes
    from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks
    from fantoch_tpu.protocol.common.table_clocks import (
        SequentialKeyClocks,
        VoteRange,
    )

    shard = 0
    rng = np.random.default_rng(11)
    key_ids = rng.integers(0, keys, size=batch)
    cmds = [
        Command.from_single(Rifl(1, i + 1), shard, f"t{key_ids[i]}", KVOp.put(""))
        for i in range(batch)
    ]
    mins = [0] * batch

    def time_proposals(clocks):
        fn = getattr(clocks, "proposal_batch", None)
        t0 = time.perf_counter()
        if fn is not None:
            results = fn(cmds, mins)
        else:
            results = [clocks.proposal(c, 0) for c in cmds]
        ms = (time.perf_counter() - t0) * 1000.0
        return ms, results

    time_proposals(BatchedKeyClocks(1, shard))  # warm the kernel compile
    batched_ms, proposals = time_proposals(BatchedKeyClocks(1, shard))
    seq_ms, seq_props = time_proposals(SequentialKeyClocks(1, shard))
    assert [c for c, _ in proposals] == [c for c, _ in seq_props]

    # the array-native seam (VERDICT r4 #4): same kernel, no Votes objects
    key_strs = [f"t{key_ids[i]}" for i in range(batch)]
    arr_clocks = BatchedKeyClocks(1, shard)
    t0 = time.perf_counter()
    clock_col, start_col = arr_clocks.proposal_batch_arrays(key_strs, mins)
    arrays_ms = (time.perf_counter() - t0) * 1000.0
    assert [int(c) for c in clock_col] == [c for c, _ in seq_props]

    # executor side: every process votes the coordinator's range, so the
    # whole batch is stable — one vectorized pass drains it
    pids = list(process_ids(shard, n))
    infos = []
    for i, (clock, votes) in enumerate(proposals):
        key = f"t{key_ids[i]}"
        (rng0,) = votes.get(key)
        all_votes = [VoteRange(p, rng0.start, rng0.end) for p in pids]
        infos.append(
            TableVotes(Dot(1, i + 1), clock, cmds[i].rifl, key,
                       (KVOp.put(""),), all_votes)
        )
    clock_t = RunTime()

    def time_executor(batched):
        config = Config(n, 1, newt_detached_send_interval_ms=5,
                        batched_table_executor=batched)
        ex = TableExecutor(1, shard, config)
        t0 = time.perf_counter()
        ex.handle_batch(infos, clock_t)
        ms = (time.perf_counter() - t0) * 1000.0
        executed = sum(1 for _ in ex.to_clients_iter())
        assert executed == batch, f"stable-drained {executed}/{batch}"
        return ms

    time_executor(True)  # warm
    exec_batched_ms = min(time_executor(True) for _ in range(3))
    exec_seq_ms = min(time_executor(False) for _ in range(3))

    # array-borne executor seam: votes as columns (every process votes
    # the consumed range), ExecutorResult objects only at the boundary
    from fantoch_tpu.executor.table import TableVotesArrays

    pid_col = np.array(pids, dtype=np.int64)
    seqs = np.arange(1, batch + 1, dtype=np.int64)
    votes_arrays = TableVotesArrays(
        keys=key_strs,
        dot_src=np.ones(batch, dtype=np.int64),
        dot_seq=seqs,
        clock=clock_col,
        rifl_src=np.ones(batch, dtype=np.int64),
        rifl_seq=seqs,
        ops=[(KVOp.put(""),)] * batch,
        vote_row=np.repeat(np.arange(batch, dtype=np.int64), n),
        vote_by=np.tile(pid_col, batch),
        vote_start=np.repeat(start_col, n),
        vote_end=np.repeat(clock_col, n),
    )

    def time_executor_arrays():
        config = Config(n, 1, newt_detached_send_interval_ms=5,
                        batched_table_executor=True)
        ex = TableExecutor(1, shard, config)
        t0 = time.perf_counter()
        ex.handle_batch_arrays(votes_arrays, clock_t)
        ms = (time.perf_counter() - t0) * 1000.0
        executed = sum(1 for _ in ex.to_clients_iter())
        assert executed == batch, f"arrays-drained {executed}/{batch}"
        return ms

    time_executor_arrays()  # warm
    exec_arrays_ms = min(time_executor_arrays() for _ in range(3))

    # ordering-only drain (the table twin of executor_order_*): stable rows
    # emit as rifl columns, no KVStore / ExecutorResult work
    def time_executor_order():
        config = Config(n, 1, newt_detached_send_interval_ms=5,
                        batched_table_executor=True)
        ex = TableExecutor(1, shard, config)
        ex.record_order_arrays = True
        t0 = time.perf_counter()
        ex.handle_batch_arrays(votes_arrays, clock_t)
        ms = (time.perf_counter() - t0) * 1000.0
        _, seq = ex.take_order_arrays()
        assert len(seq) == batch, f"order-drained {len(seq)}/{batch}"
        return ms

    time_executor_order()  # warm
    exec_order_ms = min(time_executor_order() for _ in range(3))

    # steady-state rounds: persistent BatchedKeyClocks (clock table stays
    # ON DEVICE between batches) + persistent TableExecutor (per-key vote
    # state lives across batches) — each timed round is one resident
    # proposal dispatch, the protocol-side column assembly, and one
    # executor arrays pass; round 0 warms compiles and state
    vote_row = np.repeat(np.arange(batch, dtype=np.int64), n)
    vote_by = np.tile(pid_col, batch)
    ones = np.ones(batch, dtype=np.int64)
    ops_col = [(KVOp.put(""),)] * batch

    plane_counters = {}

    def steady_rounds(plane: bool):
        config = Config(n, 1, newt_detached_send_interval_ms=5,
                        batched_table_executor=True,
                        device_table_plane=plane)
        ex = TableExecutor(1, shard, config)
        clocks = BatchedKeyClocks(1, shard)
        times = []
        for r in range(rounds + 1):
            t0 = time.perf_counter()
            ck, st = clocks.proposal_batch_arrays(key_strs, mins)
            round_arrays = TableVotesArrays(
                keys=key_strs,
                dot_src=ones,
                dot_seq=seqs + r * batch,
                clock=ck,
                rifl_src=ones,
                rifl_seq=seqs + r * batch,
                ops=ops_col,
                vote_row=vote_row,
                vote_by=vote_by,
                vote_start=np.repeat(st, n),
                vote_end=np.repeat(ck, n),
            )
            ex.handle_batch_arrays(round_arrays, clock_t)
            times.append((time.perf_counter() - t0) * 1000.0)
            drained = sum(1 for _ in ex.to_clients_iter())
            assert drained == batch, f"steady round drained {drained}/{batch}"
        if plane:
            # per-dispatch device counters (observability plane): BENCH
            # rows carry them so a kernel-side regression is explainable
            # from the record alone
            plane_counters.update(ex.device_counters() or {})
        return float(np.median(times[1:]))

    resident_ms = steady_rounds(plane=False)
    plane_ms = steady_rounds(plane=True)

    # the all-device fused chain: S rounds of proposal + dense vote
    # application + stability in ONE dispatch (every process votes every
    # consumed range — the flow-through regime), kernel-only
    fused = _bench_fused_table_rounds(batch=batch, keys=keys, n=n)

    return {
        "table_batch": batch,
        "table_proposal_ms": round(batched_ms, 1),
        "table_proposal_seq_ms": round(seq_ms, 1),
        "table_proposal_arrays_ms": round(arrays_ms, 1),
        "table_executor_ms": round(exec_batched_ms, 1),
        "table_executor_seq_ms": round(exec_seq_ms, 1),
        "table_executor_arrays_ms": round(exec_arrays_ms, 1),
        # same definition as rounds 3/4 (object-batched path), kept for
        # cross-round comparability; the arrays seam gets its own key
        "table_cmds_per_s": int(
            batch / ((batched_ms + exec_batched_ms) / 1000.0)
        ),
        # headline arrays number = the steady-state resident round (the
        # serving regime; definition changed in r06, see docstring)
        "table_cmds_per_s_arrays": int(batch / (resident_ms / 1000.0)),
        "table_arrays_definition": "steady-state-resident (r06)",
        "table_executor_order_ms": round(exec_order_ms, 1),
        "table_cmds_per_s_order": int(
            batch / ((arrays_ms + exec_order_ms) / 1000.0)
        ),
        # r06 steady-state rows (see docstring): resident clock table +
        # persistent executor; `_cold` is the pre-r06 fresh-instance
        # definition, kept for cross-round comparability
        "table_cmds_per_s_arrays_cold": int(
            batch / ((arrays_ms + exec_arrays_ms) / 1000.0)
        ),
        "table_round_ms_resident": round(resident_ms, 1),
        "table_plane_round_ms": round(plane_ms, 1),
        "table_cmds_per_s_plane": int(batch / (plane_ms / 1000.0)),
        # device-plane dispatch counters for the plane steady-state row
        # (observability plane): occupancy = vote_rows / row_capacity —
        # padding waste; residual_runs explain gap-feed overhead
        "table_plane_dispatches": plane_counters.get("table_plane_dispatches", 0),
        "table_plane_occupancy": round(
            plane_counters.get("table_plane_vote_rows", 0)
            / max(1, plane_counters.get("table_plane_row_capacity", 1)),
            3,
        ),
        "table_plane_residual_runs": plane_counters.get(
            "table_plane_residual_runs", 0
        ),
        "table_plane_kernel_ms": plane_counters.get("table_plane_kernel_ms", 0.0),
        **fused,
    }


def _bench_fused_table_rounds(
    batch: int, keys: int, n: int, chain: int = 8
):
    """The all-device table round chain (ops/table_ops.fused_table_rounds):
    ``chain`` rounds of clock proposal + dense vote application + frontier
    update + stability thread through ONE ``lax.scan`` dispatch with the
    clock table AND the frontier matrix donated — the votes-table twin of
    the graph bench's chained in-dispatch resolves.  Kernel-only (no host
    emit): the number the chip path is gated on."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fantoch_tpu.core.config import Config
    from fantoch_tpu.ops.table_ops import fused_table_rounds, next_pow2

    _, _, threshold = Config(n, 1).newt_quorum_sizes()
    rng = np.random.default_rng(19)
    kcap = next_pow2(keys + 1)
    bcap = next_pow2(batch)
    # chain of distinct per-round key columns (pad rows hit the scratch
    # bucket kcap-1, the BatchedKeyClocks pad convention)
    keys_np = rng.integers(0, keys, size=(chain, bcap)).astype(np.int32)
    mins_np = np.zeros((chain, bcap), dtype=np.int32)

    run = functools.partial(
        fused_table_rounds, threshold=threshold, voters=n
    )

    def dispatch_chain():
        prior = jnp.zeros((kcap,), jnp.int32)
        frontier = jnp.zeros((kcap, n), jnp.int32)
        out = run(prior, frontier, jnp.asarray(keys_np), jnp.asarray(mins_np))
        return out

    out = dispatch_chain()  # compile + correctness gate
    executable = np.asarray(jax.device_get(out[4]))
    gaps = np.asarray(jax.device_get(out[5]))
    assert bool(executable.all()), "dense fused rounds must flow through"
    assert int(gaps.sum()) == 0, "dense regime saw a vote gap"
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = dispatch_chain()
        jax.block_until_ready(out[0])
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    per_round = best / chain
    return {
        "table_fused_chain": chain,
        "table_fused_round_ms": round(per_round, 3),
        "table_fused_cmds_per_s": int(bcap / (per_round / 1000.0)),
    }


def bench_device_serving(
    total: int = 32_768, batch: int = 4096, conflict: float = 0.5, n: int = 3,
    families: Tuple[str, ...] = ("newt", "caesar", "paxos"),
    sweep: bool = True,
    pipeline_depth: int = None,
):
    """The served TPU path (run/device_runner.DeviceDriver): real Command
    objects through the device protocol round — batch assembly, the
    donated-state jit dispatch, and KVStore execution in device order —
    measured as steady-state rounds (first round excluded: it compiles).
    This is the round trip a `--device-step` server pays per batch.

    The HEADLINE serving keys (``serving_newt_round_ms`` /
    ``serving_newt_cmds_per_s``) measure the depth-K pipelined loop
    (run/pipeline.py) — what a live ``--device-step`` server actually
    runs under saturation; the pre-r07 synchronous round is kept as
    ``serving_newt_sync_*`` so the overlap win stays visible.  Every
    pipelined row stamps ``serving_pipeline_depth`` and a
    ``*_idle_frac`` (fraction of the serving span the device sat idle —
    the dispatch wall the loop exists to amortize).

    Also sweeps the compiled batch size (1k/4k/16k): the round cost is
    dispatch-dominated on CPU and sort-dominated on device, so cmds/s
    should grow with batch until the per-row host seam (result emit)
    takes over — the sweep records where (VERDICT r4 weak #3)."""
    import numpy as np

    from fantoch_tpu.core import Command, Dot, KVOp, Rifl
    from fantoch_tpu.run.device_runner import DeviceDriver

    from fantoch_tpu.run.pipeline import requested_pipeline_depth

    # one-knob resolution shared with the serving loop (arg > env), with
    # the bench's own default of 2 on top (transfer of round k+1 + emit
    # of round k-1 overlap compute of round k)
    depth = requested_pipeline_depth(pipeline_depth)
    if depth is None:
        depth = 2
    assert depth >= 1, f"pipeline depth must be >= 1, got {depth}"

    rng = np.random.default_rng(21)
    hot = rng.random(total) < conflict
    keys = np.where(hot, 0, 1 + rng.integers(0, 4096, size=total))
    cmds = [
        (
            Dot(1, i + 1),
            Command.from_single(
                Rifl(1, i + 1), 0, f"sk{keys[i]}", KVOp.put("")
            ),
        )
        for i in range(total)
    ]

    def measure(batch_size: int, driver_cls=DeviceDriver, pipelined=False):
        """Steady-state serving rounds; ``pipelined`` runs the depth-K
        loop (dispatch runs ahead; the tail flushes inside the timed
        region — it serves real commands).  Returns (round_ms, cmds/s,
        idle_frac, device_counters) with idle_frac from the driver's
        overlap counters."""
        driver = driver_cls(n, batch_size=batch_size, key_buckets=8192)
        driver.pipeline_depth = depth if pipelined else 1
        driver.step(cmds[:batch_size])  # compile + warm
        step = driver.step_pipelined if pipelined else driver.step
        # idle_frac must cover only the steady-state timed region, not
        # the compile round
        driver.reset_overlap_instrument()
        t0 = time.perf_counter()
        served = 0
        for start in range(batch_size, total, batch_size):
            served += len(step(cmds[start : start + batch_size]))
        if pipelined:
            served += len(driver.flush_pipeline())
        wall_ms = (time.perf_counter() - t0) * 1000.0
        rounds = (total - batch_size) // batch_size
        assert served == total - batch_size, f"served {served}/{total}"
        counters = driver.device_counters()
        return (
            round(wall_ms / rounds, 2),
            int(served / (wall_ms / 1000.0)),
            counters.get("device_idle_frac", 0.0),
            counters,
        )

    round_ms, cmds_per_s, sync_idle, _ = measure(batch)
    pipe_ms, pipe_cps, pipe_idle, pipe_ctrs = measure(batch, pipelined=True)
    out = {
        "serving_batch": batch,
        "serving_pipeline_depth": depth,
        "serving_round_ms": round_ms,
        "serving_cmds_per_s": cmds_per_s,
        "serving_idle_frac": sync_idle,
        "serving_pipelined_round_ms": pipe_ms,
        "serving_pipelined_cmds_per_s": pipe_cps,
        "serving_pipelined_idle_frac": pipe_idle,
        # batch occupancy + chain gauge (run/pipeline.py counters): the
        # full-feed bench runs full rounds, so fill sits near 1 — the
        # gauges earn their keep on the batched open-loop row, where the
        # ingest batcher is what fills them
        "serving_dispatch_fill_frac": pipe_ctrs.get("dispatch_fill_frac", 0.0),
        "serving_chain_len": pipe_ctrs.get("serving_chain_len", 1),
    }
    # the other three consensus families' serving rounds at one batch
    # size — Newt (timestamp proposal + stability), Caesar (timestamp +
    # predecessors with the wait gate), Paxos (leader slot order): all
    # four shapes the device plane serves get a chip row.  Guarded per
    # family: one compile failure must not discard the rows already
    # measured above.
    fam_classes = {
        "newt": "NewtDeviceDriver",
        "caesar": "CaesarDeviceDriver",
        "paxos": "PaxosDeviceDriver",
    }
    for name in families:
        try:
            from fantoch_tpu.run import device_runner as _drivers

            cls = getattr(_drivers, fam_classes[name])
            if name == "newt":
                # the headline family: serving_newt_* IS the pipelined
                # depth-K loop (redefined r07, the steady-state
                # redefinition move of table_cmds_per_s_arrays r06); the
                # synchronous round keeps the old definition as _sync
                sync_ms, sync_cps, fam_sync_idle, _ = measure(batch, cls)
                fam_ms, fam_cps, fam_idle, fam_ctrs = measure(
                    batch, cls, pipelined=True
                )
                out["serving_newt_sync_round_ms"] = sync_ms
                out["serving_newt_sync_cmds_per_s"] = sync_cps
                out["serving_newt_sync_idle_frac"] = fam_sync_idle
                out["serving_newt_round_ms"] = fam_ms
                out["serving_newt_cmds_per_s"] = fam_cps
                out["serving_newt_idle_frac"] = fam_idle
                out["serving_newt_dispatch_fill_frac"] = fam_ctrs.get(
                    "dispatch_fill_frac", 0.0
                )
                out["serving_newt_chain_len"] = fam_ctrs.get(
                    "serving_chain_len", 1
                )
                out["serving_newt_definition"] = (
                    f"depth-{depth} pipelined serving loop "
                    "(run/pipeline.py, r07); r16 stamps "
                    "dispatch_fill_frac/chain_len and adds the "
                    "adaptive-ingest serving_ingest_* keys "
                    "(run/ingest.py); pre-r07 synchronous round kept "
                    "as serving_newt_sync_*"
                )
            else:
                fam_ms, fam_cps, _, _ = measure(batch, cls)
                out[f"serving_{name}_round_ms"] = fam_ms
                out[f"serving_{name}_cmds_per_s"] = fam_cps
                if name == "caesar":
                    # the pred-plane protocol family also gets a
                    # pipelined row (new keys — serving_caesar_* keeps
                    # its synchronous definition); the smoke gates
                    # pipelined >= 0.6x sync like the Newt row
                    pipe_ms2, pipe_cps2, pipe_idle2, _ = measure(
                        batch, cls, pipelined=True
                    )
                    out["serving_caesar_pipelined_round_ms"] = pipe_ms2
                    out["serving_caesar_pipelined_cmds_per_s"] = pipe_cps2
                    out["serving_caesar_pipelined_idle_frac"] = pipe_idle2
        except Exception as exc:  # noqa: BLE001
            print(f"# {name} serving bench failed: {exc!r}", file=sys.stderr)
            out[f"serving_{name}_error"] = repr(exc)[:200]
    if "newt" in families:
        # chained Newt serving (NewtDeviceDriver.step_chained): S rounds
        # per device dispatch — the serving twin of the fused table
        # rounds, what drops serving_newt_round_ms on dispatch-dominated
        # rigs.  Needs >= 2 full chains past the warm round.  The
        # _pipelined variant composes S in-dispatch rounds x depth-K
        # in-flight chains (step_chained_pipelined).
        try:
            out.update(_measure_newt_chained(cmds, total, batch, n))
        except Exception as exc:  # noqa: BLE001
            print(f"# newt chained serving bench failed: {exc!r}", file=sys.stderr)
            out["serving_newt_chained_error"] = repr(exc)[:200]
        try:
            out.update(
                _measure_newt_chained(cmds, total, batch, n, depth=depth)
            )
        except Exception as exc:  # noqa: BLE001
            print(
                f"# newt chained+pipelined serving bench failed: {exc!r}",
                file=sys.stderr,
            )
            out["serving_newt_chained_pipelined_error"] = repr(exc)[:200]
    if sweep:
        for other in (1024, 16384):
            if total < 2 * other:
                continue  # needs >= one steady-state round past the warm one
            ms, cps, _, _ = measure(other)
            out[f"serving_round_ms_{other // 1024}k"] = ms
            out[f"serving_cmds_per_s_{other // 1024}k"] = cps
    return out


def _measure_newt_chained(
    cmds, total: int, batch: int, n: int, chain: int = 3, depth: int = 0
):
    """Per-round cost of the S-rounds-per-dispatch Newt serving chain;
    ``depth > 0`` composes it with the depth-K pipeline
    (step_chained_pipelined: S in-dispatch rounds x K in-flight chain
    dispatches — chaining amortizes the dispatch round trip, pipelining
    overlaps the surviving transfer + emit with compute)."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    driver = NewtDeviceDriver(n, batch_size=batch, key_buckets=8192)
    if depth:
        driver.pipeline_depth = depth
    driver.step(cmds[:batch])  # compile the single-step + warm state
    batches = [
        cmds[start : start + batch] for start in range(batch, total, batch)
    ]
    n_groups = len(batches) // chain
    if n_groups < 2:
        return {}  # not enough rounds for a steady-state chained measure
    groups = [batches[i * chain : (i + 1) * chain] for i in range(n_groups)]
    run = driver.step_chained_pipelined if depth else driver.step_chained
    run(groups[0])  # compile the chained program
    if depth:
        driver.flush_pipeline()
    # idle_frac must cover only the steady-state timed region, not the
    # compile dispatches above
    driver.reset_overlap_instrument()
    served = 0
    t0 = time.perf_counter()
    for group in groups[1:]:
        served += len(run(group))
    if depth:
        served += len(driver.flush_pipeline())
    wall_ms = (time.perf_counter() - t0) * 1000.0
    rounds = (n_groups - 1) * chain
    expected = rounds * batch
    assert served == expected, f"chained served {served}/{expected}"
    prefix = "serving_newt_chained_pipelined" if depth else "serving_newt_chained"
    out = {
        "serving_newt_chain": chain,
        f"{prefix}_round_ms": round(wall_ms / rounds, 2),
        f"{prefix}_cmds_per_s": int(served / (wall_ms / 1000.0)),
    }
    if depth:
        out[f"{prefix}_idle_frac"] = driver.device_counters().get(
            "device_idle_frac", 0.0
        )
    return out


def bench_serving_batched(
    total: int = 16_384, batch: int = 64, n: int = 3,
    rate_factor: float = 2.0, deadline_ms: float = 2.0, chain: int = 8,
):
    """The adaptive-ingest serving row (run/ingest.py): a timed arrival
    stream offered at ``rate_factor``x this rig's measured saturation
    rate feeds the Newt serving loop two ways —

    * **unbatched** (the pre-r16 loop): dispatch the instant anything is
      queued, one round per dispatch — under a trickle the device
      round-trip is paid per near-empty round;
    * **batched**: the size-or-deadline gate holds arrivals, and a
      backlog covering ``chain`` rounds goes out as ONE chained dispatch
      (``step_chained_pipelined``) — rounds leave full and the dispatch
      round-trip is amortized ``chain``x.

    Both arms replay the same arrival schedule (command i arrives at
    ``i / rate`` after t0) against real wall time, so the row measures
    the serving loops, not the generator.  ``serving_ingest_fill_frac``
    is the batched arm's steady-state batch occupancy (delta over the
    timed region) and ``serving_ingest_recompiles_timed`` must stay 0 —
    every program the timed region runs is compiled in the warm phase
    (single step, plus the S=``chain`` chained program for the batched
    arm; the arm only ever dispatches those two shapes).

    Sizing rule: the timed region must be MANY multiples of
    ``chain * batch`` — at 2x saturation the backlog grows at the
    saturation rate, so fused dispatches only engage once it crosses a
    full chain; a short region never gets there and the row degenerates
    to single rounds.

    Regime rule: chaining amortizes PER-DISPATCH overhead, so it only
    wins where that overhead is a large fraction of the round — small
    batches.  Measured on the dev rig: batch=64 S=8 is 1.37x the single
    loop, batch=256 S=4 is 1.21x, and batch=1024 ANY S loses (the big
    batch already amortizes the dispatch and the fused program only
    forfeits drain overlap).  The defaults sit in the winning regime;
    the serving-loop auto-tuner (run/ingest.py ChainAutoTuner) encodes
    the same rule dynamically via the overhead/busy ratio."""
    import numpy as np

    from fantoch_tpu.core import Command, Dot, KVOp, Rifl
    from fantoch_tpu.observability.device import (
        recompile_count,
        subscribe_recompiles,
    )
    from fantoch_tpu.run.device_runner import NewtDeviceDriver
    from fantoch_tpu.run.ingest import AdaptiveIngestBatcher

    subscribe_recompiles()
    rng = np.random.default_rng(23)
    keys = 1 + rng.integers(0, 4096, size=total)
    cmds = [
        (
            Dot(1, i + 1),
            Command.from_single(
                Rifl(1, i + 1), 0, f"bk{keys[i]}", KVOp.put("")
            ),
        )
        for i in range(total)
    ]
    warm_rows = (1 + chain) * batch  # single-step warm + S=chain warm
    assert total > warm_rows + 2 * batch, (
        f"total {total} leaves no steady-state feed past warm {warm_rows}"
    )

    # calibrate saturation on a throwaway driver: warm full rounds of the
    # plain loop give the rate the arrival stream is scaled against
    cal = NewtDeviceDriver(n, batch_size=batch, key_buckets=8192)
    cal.step(cmds[:batch])
    t0 = time.perf_counter()
    cal_rounds = 0
    for start in range(batch, min(total, 4 * batch), batch):
        cal.step(cmds[start : start + batch])
        cal_rounds += 1
    sat_cps = cal_rounds * batch / max(1e-9, time.perf_counter() - t0)
    rate_per_ms = rate_factor * sat_cps / 1000.0

    def serve(batched: bool) -> dict:
        driver = NewtDeviceDriver(n, batch_size=batch, key_buckets=8192)
        driver.pipeline_depth = 2
        driver.step(cmds[:batch])  # compile + warm the single step
        if batched:
            # compile the S=chain fused program outside the timed region
            driver.step_chained_pipelined(
                [
                    cmds[batch + i * batch : batch + (i + 1) * batch]
                    for i in range(chain)
                ]
            )
            driver.flush_pipeline()
        feed = cmds[warm_rows:] if batched else cmds[batch:]
        # identical steady-state length for both arms (the batched arm's
        # extra warm rows come off the front)
        feed = feed[: total - warm_rows]
        ntimed = len(feed)
        batcher = (
            AdaptiveIngestBatcher(deadline_ms, max_target=chain * batch)
            if batched else None
        )
        driver.reset_overlap_instrument()
        c0 = driver.device_counters()
        recompiles0 = recompile_count()
        served = 0
        taken = 0
        noted = 0
        fused_dispatches = 0
        t1 = time.perf_counter()
        while taken < ntimed:
            now_ms = (time.perf_counter() - t1) * 1000.0
            arrived = min(ntimed, int(now_ms * rate_per_ms))
            queued = arrived - taken
            if queued <= 0:
                # sleep to the next arrival instant
                gap_ms = (taken + 1) / rate_per_ms - now_ms
                time.sleep(max(gap_ms, 0.05) / 1000.0)
                continue
            if batcher is None:
                take = min(queued, batch)
                served += len(driver.step_pipelined(feed[taken : taken + take]))
                taken += take
                continue
            if noted < arrived:
                batcher.note_arrivals(now_ms, arrived - noted)
                noted = arrived
            release, wait_ms = batcher.poll(now_ms, queued)
            if not release:
                time.sleep((wait_ms or 0.05) / 1000.0)
                continue
            if queued >= chain * batch:
                # backlog covers a full chain: one fused dispatch (the
                # only chained shape compiled — a partial chain would
                # recompile, so anything shorter goes out as single
                # full-or-partial rounds)
                take = chain * batch
                rows = feed[taken : taken + take]
                taken += take
                batcher.note_release(now_ms, take)
                fused_dispatches += 1
                served += len(
                    driver.step_chained_pipelined(
                        [rows[i * batch : (i + 1) * batch] for i in range(chain)]
                    )
                )
            else:
                take = min(queued, batch)
                served += len(driver.step_pipelined(feed[taken : taken + take]))
                taken += take
                batcher.note_release(now_ms, take)
        served += len(driver.flush_pipeline())
        wall_ms = (time.perf_counter() - t1) * 1000.0
        assert served == ntimed, f"served {served}/{ntimed}"
        c1 = driver.device_counters()
        d_rows = c1["device_dispatched_rows"] - c0["device_dispatched_rows"]
        d_cap = c1["device_batch_capacity"] - c0["device_batch_capacity"]
        return {
            "cmds_per_s": int(served / (wall_ms / 1000.0)),
            "fill_frac": round(d_rows / max(1, d_cap), 4),
            # the chain the arm actually fused (the driver's
            # serving_chain_len gauge reads the LAST dispatch, which is
            # a tail single round here)
            "chain_len": chain if fused_dispatches else 1,
            "fused_dispatches": fused_dispatches,
            "recompiles": recompile_count() - recompiles0,
        }

    plain = serve(batched=False)
    fused = serve(batched=True)
    out = {
        "serving_ingest_deadline_ms": deadline_ms,
        "serving_ingest_rate_factor": rate_factor,
        "serving_ingest_offered_cmds_per_s": int(rate_per_ms * 1000.0),
        "serving_ingest_unbatched_cmds_per_s": plain["cmds_per_s"],
        "serving_ingest_unbatched_fill_frac": plain["fill_frac"],
        "serving_ingest_batched_cmds_per_s": fused["cmds_per_s"],
        "serving_ingest_fill_frac": fused["fill_frac"],
        "serving_ingest_chain_len": fused["chain_len"],
        "serving_ingest_fused_dispatches": fused["fused_dispatches"],
        "serving_ingest_recompiles_timed": (
            plain["recompiles"] + fused["recompiles"]
        ),
    }
    if plain["cmds_per_s"] > 0:
        out["serving_ingest_speedup"] = round(
            fused["cmds_per_s"] / plain["cmds_per_s"], 3
        )
    return out


def _run_child(mode: str, timeout_s: int):
    """Spawn this script as a measurement child; return its JSON line or None."""
    env = dict(os.environ)
    env[_CHILD_ENV] = mode
    # a JAX_PLATFORMS env var hangs interpreter start under the
    # sitecustomize TPU hook; children force platforms in-Python instead
    env.pop("JAX_PLATFORMS", None)
    # child stdout/stderr go to temp FILES, not pipes: on timeout the
    # progressively richer JSON lines the child printed (primary
    # measurement first) survive the kill and are read back — both
    # subprocess.run() (TimeoutExpired.stdout=None on POSIX) and the
    # communicate-after-kill pattern (returns '' on POSIX, verified) lose
    # pipe contents
    import tempfile

    # errors="replace": a SIGKILLed child (or native XLA stderr) can leave
    # truncated multibyte sequences; recovery must never crash the parent
    with tempfile.TemporaryFile("w+", errors="replace") as out_f, (
        tempfile.TemporaryFile("w+", errors="replace")
    ) as err_f:
        proc = subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__)],
            stdout=out_f,
            stderr=err_f,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            print(
                f"# {mode} child exceeded {timeout_s}s; recovering partial output",
                file=sys.stderr,
            )
            proc.kill()
            proc.wait()
            rc = "timeout"
        out_f.seek(0)
        stdout = out_f.read()
        err_f.seek(0)
        stderr = err_f.read()
    if stderr and stderr.strip():
        print(stderr.rstrip(), file=sys.stderr)
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and parsed.get("metric"):
                return line
        except json.JSONDecodeError:
            continue
    print(f"# {mode} child rc={rc}, no JSON line", file=sys.stderr)
    return None


def _probe_backend() -> bool:
    """Quick reachability check of the default (TPU) backend, retried."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for attempt in range(PROBE_RETRIES):
        if attempt:
            time.sleep(2.0 * 2**attempt)
        try:
            out = subprocess.run(
                [sys.executable, "-u", "-c", "import jax; print(jax.devices()[0].platform)"],
                capture_output=True,
                text=True,
                timeout=PROBE_TIMEOUT_S,
                env=env,
            )
            if out.returncode == 0 and out.stdout.strip():
                return True
            err = (out.stderr or "").strip()[-400:]
        except subprocess.TimeoutExpired:
            err = f"probe exceeded {PROBE_TIMEOUT_S}s (backend hang)"
        print(f"# backend probe {attempt + 1}/{PROBE_RETRIES} failed: {err}", file=sys.stderr)
    return False


_TPU_RECORD_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_LATEST.json"
)
# sidecar for chip runs that failed the scale_vs_1m self-consistency gate:
# repeatedly-gated rounds are visible here (with reasons and timestamps)
# instead of silently reusing a stale BENCH_TPU_LATEST.json (ADVICE r5)
_TPU_GATED_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_GATED.json"
)


def _record_gated_candidate(rec: dict, reason: str) -> None:
    """Append the gated measurement to the sidecar and count consecutive
    gated rounds, so staleness of the persisted record is observable."""
    entry = {
        "gated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "reason": reason,
        "gated_candidate": rec,
    }
    try:
        sidecar = {"consecutive_gated": 0, "entries": []}
        if os.path.exists(_TPU_GATED_PATH):
            with open(_TPU_GATED_PATH) as f:
                sidecar = json.load(f)
        sidecar["consecutive_gated"] = sidecar.get("consecutive_gated", 0) + 1
        sidecar["entries"] = (sidecar.get("entries", []) + [entry])[-10:]
        with open(_TPU_GATED_PATH, "w") as f:
            json.dump(sidecar, f, indent=1)
            f.write("\n")
    except Exception as exc:  # noqa: BLE001 — bookkeeping must not fail the bench
        print(f"# could not record gated candidate: {exc!r}", file=sys.stderr)


def _clear_gated_streak() -> None:
    """A persisted (un-gated) chip record resets the staleness counter."""
    try:
        if os.path.exists(_TPU_GATED_PATH):
            with open(_TPU_GATED_PATH) as f:
                sidecar = json.load(f)
            sidecar["consecutive_gated"] = 0
            with open(_TPU_GATED_PATH, "w") as f:
                json.dump(sidecar, f, indent=1)
                f.write("\n")
    except Exception as exc:  # noqa: BLE001
        print(f"# could not reset gated streak: {exc!r}", file=sys.stderr)


def _save_tpu_record(line: str) -> None:
    """Persist a successful TPU measurement (committed artifact) so later
    CPU-fallback records can carry the chip's last verified numbers with
    provenance — the tunnel to the chip flaps for hours at a time and a
    fallback-only record would otherwise erase the TPU story."""
    try:
        rec = json.loads(line)
        if rec.get("platform") != "tpu":
            return
        # self-consistency gate: the 4x-batch scaling row doubles as a
        # cross-check of the primary slope — their ratio should sit near
        # the batch ratio.  A wildly-off ratio means one of the two slope
        # fits was swamped by tunnel jitter (observed once: primary
        # 0.129 ms with scale_vs_1m 88.1); a MISSING ratio means the
        # scale fit itself failed (noise-negative) or the scale row
        # errored, so the primary has no independent witness either way.
        # Keep the previous good record rather than persisting a number
        # we can't stand behind; the round's BENCH_r0N.json still carries
        # the un-gated measurement.
        ratio = rec.get("scale_vs_1m")
        if ratio is None or not (1.0 <= ratio <= 16.0):
            reason = (
                f"scale_vs_1m={ratio} fails the self-consistency gate "
                "[1, 16] (None = no cross-check ran)"
            )
            print(f"# TPU record NOT persisted: {reason}", file=sys.stderr)
            _record_gated_candidate(rec, reason)
            return
        rec["recorded_utc"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        with open(_TPU_RECORD_PATH, "w") as f:
            json.dump(rec, f)
            f.write("\n")
        _clear_gated_streak()
    except Exception as exc:  # noqa: BLE001 — bookkeeping must not fail the bench
        print(f"# could not save TPU record: {exc!r}", file=sys.stderr)


def _attach_last_tpu(line: str) -> str:
    """Embed the last verified TPU record (if any) into a CPU-fallback
    record, clearly labeled: `value` stays the CPU measurement."""
    try:
        rec = json.loads(line)
        if rec.get("platform") == "tpu" or not os.path.exists(_TPU_RECORD_PATH):
            return line
        with open(_TPU_RECORD_PATH) as f:
            rec["last_tpu_record"] = json.load(f)
        # staleness note: if chip runs since then kept failing the gate,
        # say so instead of letting the stale record pass as fresh
        if os.path.exists(_TPU_GATED_PATH):
            with open(_TPU_GATED_PATH) as f:
                streak = json.load(f).get("consecutive_gated", 0)
            if streak:
                rec["last_tpu_record"]["staleness_note"] = (
                    f"{streak} chip run(s) since this record were gated by "
                    "the scale_vs_1m self-consistency check; see "
                    "BENCH_TPU_GATED.json"
                )
        return json.dumps(rec)
    except Exception as exc:  # noqa: BLE001
        print(f"# could not attach TPU record: {exc!r}", file=sys.stderr)
        return line


def bench_overload(
    commands_per_client: int = 30,
    clients_per_process: int = 3,
    rate_points=(0.5, 1.0, 2.0),
) -> dict:
    """Latency-under-load row (the standard consensus-paper plot: offered
    rate on x, p50/p99 + goodput on y, cf. the reference's fantoch_plot
    throughput-latency figure) against a localhost EPaxos n=3 TCP
    cluster.  Phase 1 measures closed-loop saturation throughput; phase 2
    sweeps seeded open-loop Poisson arrivals at fractions of it with
    admission control + client backoff engaged (run/backpressure.py), so
    the 2x point exercises shedding.  Pure asyncio (no device): the row
    measures the serving/overload plane, not a kernel.  The phase runner
    is shared with the CI gate (run/harness.run_overload_phase), so the
    bench row and ``make overload-smoke`` cannot drift on accounting."""
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.core import Config
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.run.harness import run_overload_phase

    def workload():
        return Workload(
            shard_count=1,
            key_gen=ConflictRateKeyGen(30),
            keys_per_command=1,
            commands_per_client=commands_per_client,
            payload_size=16,
        )

    config = Config(
        n=3, f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        admission_limit=8,
        queue_capacity=1024,
        overload_retry_after_ms=5,
    )

    def run(rate_per_client=None):
        return run_overload_phase(
            EPaxos, config, workload(), clients_per_process,
            arrival_rate_per_s=rate_per_client, arrival_seed=13,
        )

    out = {
        "overload_definition": (
            "open-loop Poisson sweep vs closed-loop saturation; EPaxos "
            "n=3 localhost TCP, admission_limit=8, backoff retries (r08)"
        )
    }
    base = run()
    saturation = base["goodput_cmds_per_s"]
    out["overload_saturation_cmds_per_s"] = saturation
    out["overload_closed_loop_p50_ms"] = base["p50_ms"]
    # one client pool per process (the harness's shard-0 topology)
    total_clients = config.n * clients_per_process
    for frac in rate_points:
        per_client = max(1.0, frac * saturation / total_clients)
        tag = f"{frac}x".replace(".", "_")
        row = run(rate_per_client=per_client)
        out[f"overload_{tag}_offered_cmds_per_s"] = int(
            per_client * total_clients
        )
        out[f"overload_{tag}_goodput_cmds_per_s"] = row["goodput_cmds_per_s"]
        out[f"overload_{tag}_p50_ms"] = row["p50_ms"]
        out[f"overload_{tag}_p99_ms"] = row["p99_ms"]
        out[f"overload_{tag}_sheds"] = row["sheds"]
        out[f"overload_{tag}_queue_depth_hwm"] = row["queue_depth_hwm"]
    return out


def bench_curve(
    commands_per_client: int = 10,
    clients_per_process: int = 2,
    rates=(50.0, 400.0, 3200.0),
) -> dict:
    """Scenario-observatory saturation row (r20): a declarative spec
    (exp/scenarios.py) sweeps sim-timeline EPaxos n=3 over an offered
    open-loop rate ladder and the row reports the detected saturation
    knee plus the p99 at half saturation.  Runs on the deterministic
    virtual-time sim — the knee is real (goodput caps at
    total_commands / commit-latency span as the arrival window
    compresses) and byte-stable across machines, so the regression band
    guards the *curve pipeline*, not rig noise."""
    import shutil
    import tempfile

    from fantoch_tpu.exp.scenarios import ScenarioSpec, run_scenario

    spec = ScenarioSpec(
        name="bench_curve",
        protocols=("epaxos",),
        sites=((3, 1),),
        timeline="sim",
        seed=20,
        clients_per_process=clients_per_process,
        commands_per_client=commands_per_client,
        rates=tuple(rates),
    )
    out_dir = tempfile.mkdtemp(prefix="bench_curve_")
    try:
        doc = run_scenario(spec, out_dir, render=False)
    finally:
        shutil.rmtree(out_dir, ignore_errors=True)
    curve = doc["curves"][0]
    out = {
        "curve_definition": (
            "sim-timeline EPaxos n=3 (gcp planet), seed 20, offered "
            "open-loop ladder 50/400/3200 cmds/s via exp/scenarios "
            "run_scenario; knee = detect_knee defaults (r20)"
        ),
        "curve_points": len(curve["points"]),
    }
    knee = curve["knee"]
    assert knee is not None, "bench_curve ladder must reach saturation"
    out["curve_knee_offered_cmds_per_s"] = knee["offered_cmds_per_s"]
    out["curve_knee_goodput_cmds_per_s"] = knee["goodput_cmds_per_s"]
    # p99 at half saturation: the measured point whose offered rate is
    # nearest half the knee's offered rate (no interpolation — the
    # ladder is coarse and the row must stay deterministic)
    half = knee["offered_cmds_per_s"] / 2.0
    nearest = min(
        (p for p in curve["points"] if p["offered_cmds_per_s"]),
        key=lambda p: abs(p["offered_cmds_per_s"] - half),
    )
    out["curve_p99_at_half_saturation_ms"] = nearest["p99_ms"]
    return out


def bench_failover(
    keys: int = 256, rounds: int = 30, votes_per_round: int = 2048,
    fault_at: int = 10, down: int = 8,
) -> dict:
    """Accelerator failover drill (round 17): the device votes-table
    plane (executor/table_plane.py) under a deterministic injected
    dispatch hang (sim/device_faults.py).  Three headline walls:
    ``failover_time_to_failover_ms`` — the faulted dispatch's wall, i.e.
    detection (typed DeviceFailedError) plus the first batch served from
    the host twin; ``failover_degraded_cmds_per_s`` — goodput through
    the twin while the fault window is open; and
    ``failover_time_to_cutback_ms`` — the rebuild dispatch's wall (twin
    fold + the ONE counted resident re-upload).  Self-checking: the
    faulted run's final frontiers must be bit-for-bit the fault-free
    run's, the plane must end healthy, and cutback must cost exactly
    one upload."""
    import numpy as np

    from fantoch_tpu.core import Config
    from fantoch_tpu.executor.table_plane import DeviceTablePlane
    from fantoch_tpu.sim.device_faults import DeviceFault, DeviceFaultInjector

    n = 3
    rng = np.random.default_rng(17)
    batches = []
    for _ in range(rounds):
        vk = rng.integers(0, keys, size=votes_per_round).astype(np.int64)
        vb = rng.integers(1, n + 1, size=votes_per_round).astype(np.int64)
        vs = rng.integers(1, 200, size=votes_per_round).astype(np.int64)
        ve = (vs + rng.integers(0, 6, size=votes_per_round)).astype(np.int64)
        batches.append((vk, vb, vs, ve))

    def build(injector):
        plane = DeviceTablePlane(n, stability_threshold=2, key_buckets=keys)
        for k in range(keys):
            plane.bucket(f"b{k}")
        plane.configure_faults(Config(n, 1), process_id=1)
        if injector is not None:
            plane.attach_injector(injector)
        return plane

    # fault-free reference (also warms the kernel compiles)
    reference = build(None)
    for vk, vb, vs, ve in batches:
        reference.commit_votes(vk, vb, vs, ve)

    fault = DeviceFault(
        plane="table", kind="hang",
        at_dispatch=fault_at, down_dispatches=down,
    )
    plane = build(DeviceFaultInjector((fault,), process_id=1))
    failover_ms = cutback_ms = None
    healthy_walls = []
    degraded_wall_ms = 0.0
    degraded_cmds = 0
    uploads_before_rebuild = None
    for index, (vk, vb, vs, ve) in enumerate(batches):
        before = plane.fault_counters()
        if before["rebuilds"] == 0 and before["failovers"] > 0:
            uploads_before_rebuild = plane.resident_uploads
        t0 = time.perf_counter()
        plane.commit_votes(vk, vb, vs, ve)
        wall_ms = (time.perf_counter() - t0) * 1000.0
        after = plane.fault_counters()
        if failover_ms is None and after["failovers"] > before["failovers"]:
            failover_ms = wall_ms
        if cutback_ms is None and after["rebuilds"] > before["rebuilds"]:
            cutback_ms = wall_ms
        if after["failovers"] > 0 and after["rebuilds"] == 0:
            degraded_wall_ms += wall_ms
            degraded_cmds += votes_per_round
        elif after["failovers"] == 0 and 1 < index < fault_at:
            healthy_walls.append(wall_ms)

    counters = plane.fault_counters()
    assert failover_ms is not None and cutback_ms is not None, counters
    assert counters["failovers"] == 1 and counters["rebuilds"] == 1, counters
    assert counters["health"] == 0, counters  # cut back to healthy
    cutback_uploads = plane.resident_uploads - uploads_before_rebuild
    assert cutback_uploads == 1, (
        f"cutback must cost exactly one counted upload, got {cutback_uploads}"
    )
    assert np.array_equal(plane.frontiers(), reference.frontiers()), (
        "host-twin serving diverged from the fault-free run"
    )
    healthy_ms = sum(healthy_walls) / max(1, len(healthy_walls))
    return {
        "failover_definition": (
            "table plane, injected dispatch hang at dispatch "
            f"{fault_at} for {down} dispatches, {votes_per_round} votes x "
            f"{rounds} rounds over {keys} keys (r17)"
        ),
        "failover_time_to_failover_ms": round(failover_ms, 3),
        "failover_time_to_cutback_ms": round(cutback_ms, 3),
        "failover_degraded_cmds_per_s": int(
            degraded_cmds / max(1e-9, degraded_wall_ms / 1000.0)
        ),
        "failover_healthy_round_ms": round(healthy_ms, 3),
        "failover_degraded_wall_ms": round(degraded_wall_ms, 3),
        "failover_cutback_uploads": cutback_uploads,
    }


def bench_pallas_resolve(
    cap: int = 512, width: int = 8, rounds: int = 8,
) -> dict:
    """Pallas-vs-composed resolve dispatch (round 19): the hand-fused
    pred/graph plane-step kernels (ops/pallas_resolve.py) raced against
    the composed-XLA originals on IDENTICAL multi-dispatch feeds, each
    route threading its own donated resident state.  Self-checking: the
    final step outputs must be bit-for-bit equal across routes before
    any wall is reported.  On the CPU pin the Pallas route runs in
    interpret mode (the parity vehicle — it discharges to the same XLA
    ops, so the CPU walls race plumbing, not Mosaic); the fusion win is
    a chip number, measured when the tpu child runs with the kernels
    lowered through Mosaic."""
    import random

    import jax
    import numpy as np
    import jax.numpy as jnp

    from fantoch_tpu.ops import pallas_resolve as pr
    from fantoch_tpu.ops.graph_resolve import (
        MISSING,
        TERMINAL,
        resolve_graph_plane_step,
    )
    from fantoch_tpu.ops.pred_resolve import resolve_pred_plane_step

    U, P, E = 64, 16, 4
    rng = random.Random(19)

    def pred_feed(installed):
        u_row = np.full((U,), cap, np.int32)
        u_deps = np.full((U, width), TERMINAL, np.int32)
        u_clock = np.zeros((U,), np.int32)
        u_src = np.zeros((U,), np.int32)
        installs = min(U, cap - installed)
        for i in range(installs):
            row = installed + i
            u_row[i] = row
            u_clock[i] = rng.randrange(1, 1 << 20)
            u_src[i] = rng.randrange(1, 4)
            for w in range(rng.randrange(0, width + 1)):
                u_deps[i, w] = rng.choice(
                    [TERMINAL, MISSING, rng.randrange(0, max(row, 1))]
                )
        p_row = np.full((P,), cap, np.int32)
        p_col = np.zeros((P,), np.int32)
        p_val = np.full((P,), TERMINAL, np.int32)
        for j in range(rng.randrange(0, P)):
            if installed == 0:
                break
            p_row[j] = rng.randrange(0, installed)
            p_col[j] = rng.randrange(0, width)
            p_val[j] = rng.choice([TERMINAL, rng.randrange(0, installed)])
        feed = (u_row, u_deps, u_clock, u_src, p_row, p_col, p_val)
        return tuple(jnp.asarray(a) for a in feed), installed + installs

    def graph_feed(installed):
        u_row = np.full((U,), cap, np.int32)
        u_deps = np.full((U, width), TERMINAL, np.int32)
        u_key = np.zeros((U,), np.int32)
        u_src = np.zeros((U,), np.int32)
        u_seq = np.zeros((U,), np.int32)
        installs = min(U, cap - installed)
        for i in range(installs):
            row = installed + i
            u_row[i] = row
            u_key[i] = rng.randrange(0, 16)
            u_src[i] = rng.randrange(1, 4)
            u_seq[i] = row + 1
            for w in range(rng.randrange(0, width + 1)):
                u_deps[i, w] = rng.choice(
                    [TERMINAL, MISSING, rng.randrange(0, max(row, 1))]
                )
        p_row = np.full((P,), cap, np.int32)
        p_col = np.zeros((P,), np.int32)
        p_val = np.full((P,), TERMINAL, np.int32)
        for j in range(rng.randrange(0, P)):
            if installed == 0:
                break
            p_row[j] = rng.randrange(0, installed)
            p_col[j] = rng.randrange(0, width)
            p_val[j] = rng.choice([TERMINAL, rng.randrange(0, installed)])
        e_row = np.full((E,), cap, np.int32)
        feed = (u_row, u_deps, u_key, u_src, u_seq, p_row, p_col, p_val, e_row)
        return tuple(jnp.asarray(a) for a in feed), installed + installs

    # identical feed sequences for both routes, built once up front
    pred_feeds, graph_feeds = [], []
    installed = 0
    for _ in range(rounds):
        feed, installed = pred_feed(installed)
        pred_feeds.append(feed)
    installed = 0
    for _ in range(rounds):
        feed, installed = graph_feed(installed)
        graph_feeds.append(feed)

    def pred_state():
        return (
            jnp.full((cap, width), TERMINAL, jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap,), jnp.bool_),
            jnp.zeros((cap,), jnp.bool_),
        )

    def graph_state():
        return (
            jnp.full((cap, width), TERMINAL, jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap,), jnp.bool_),
            jnp.zeros((cap,), jnp.bool_),
        )

    def run(enabled, step, fresh, feeds, carry):
        """Thread one route through all feeds; the first dispatch warms
        the compile, the rest are timed.  Returns (final output as np,
        mean timed wall ms)."""
        pr.set_pallas_kernels(enabled)
        try:
            state = fresh()
            walls = []
            out = None
            for index, feed in enumerate(feeds):
                t0 = time.perf_counter()
                out = step(*state, *feed)
                jax.block_until_ready(tuple(out))
                if index > 0:
                    walls.append((time.perf_counter() - t0) * 1000.0)
                state = tuple(out[:carry])
            final = tuple(np.asarray(o) for o in tuple(out))
            return final, sum(walls) / max(1, len(walls))
        finally:
            pr.set_pallas_kernels(None)

    pred_p, pred_p_ms = run(True, resolve_pred_plane_step, pred_state,
                            pred_feeds, 5)
    pred_x, pred_x_ms = run(False, resolve_pred_plane_step, pred_state,
                            pred_feeds, 5)
    graph_step = lambda *a: resolve_graph_plane_step(*a, mode="keyed")  # noqa: E731
    graph_p, graph_p_ms = run(True, graph_step, graph_state, graph_feeds, 6)
    graph_x, graph_x_ms = run(False, graph_step, graph_state, graph_feeds, 6)
    for name, got, want in (("pred", pred_p, pred_x), ("graph", graph_p, graph_x)):
        for i, (g, w) in enumerate(zip(got, want)):
            assert np.array_equal(g, w), (
                f"pallas route diverged from composed on {name} field {i}"
            )
    status = pr.pallas_status()
    return {
        "pallas_resolve_definition": (
            f"pred+graph plane step, cap {cap} width {width}, {U} "
            f"installs x {rounds} dispatches, both routes on identical "
            "feeds, final-state parity asserted (r19)"
        ),
        "pallas_resolve_pred_ms": round(pred_p_ms, 3),
        "pallas_resolve_pred_composed_ms": round(pred_x_ms, 3),
        "pallas_resolve_graph_ms": round(graph_p_ms, 3),
        "pallas_resolve_graph_composed_ms": round(graph_x_ms, 3),
        "pallas_resolve_interpret": bool(status["interpret"]),
    }


def bench_table_pallas(keys: int = 256, batch: int = 2048, rounds: int = 8) -> dict:
    """Pallas-vs-composed fused table round (round 19): the one-kernel
    vote-coalesce + frontier + stability commit and the dense fused
    round, raced route-vs-route on identical vote batches, each route
    threading its own donated frontier.  Self-checking: every round's
    full output tuple (stable mask, run/residual columns, frontier)
    must agree bit-for-bit before walls are reported.  Same interpret-
    mode caveat as ``bench_pallas_resolve``: CPU walls race plumbing;
    the fusion win is a chip number."""
    import random

    import jax
    import numpy as np
    import jax.numpy as jnp

    from fantoch_tpu.ops import pallas_resolve as pr
    from fantoch_tpu.ops.table_ops import fused_table_round, fused_votes_commit

    rng = random.Random(23)
    n = 3
    feeds = []
    for _ in range(rounds):
        vkey = np.array([rng.randrange(0, keys) for _ in range(batch)], np.int32)
        vby = np.array([rng.randrange(0, n) for _ in range(batch)], np.int32)
        vstart = np.array(
            [rng.randrange(1, 64) for _ in range(batch)], np.int32
        )
        vend = vstart + np.array(
            [rng.randrange(0, 8) for _ in range(batch)], np.int32
        )
        valid = np.array([rng.random() < 0.9 for _ in range(batch)], bool)
        feeds.append(
            tuple(jnp.asarray(a) for a in (vkey, vby, vstart, vend, valid))
        )
    round_feeds = []
    for _ in range(rounds):
        rkey = np.array(
            [rng.randrange(0, keys - 1) for _ in range(batch)], np.int32
        )
        rmc = np.array([rng.randrange(0, 16) for _ in range(batch)], np.int32)
        round_feeds.append((jnp.asarray(rkey), jnp.asarray(rmc)))

    def run_commit(enabled):
        pr.set_pallas_kernels(enabled)
        try:
            frontier = jnp.zeros((keys, n), jnp.int32)
            walls, outs = [], []
            for index, feed in enumerate(feeds):
                t0 = time.perf_counter()
                out = fused_votes_commit(frontier, *feed, threshold=2)
                jax.block_until_ready(tuple(out))
                if index > 0:
                    walls.append((time.perf_counter() - t0) * 1000.0)
                outs.append(tuple(np.asarray(o) for o in out[1:]))
                frontier = out[0]
            outs.append((np.asarray(frontier),))
            return outs, sum(walls) / max(1, len(walls))
        finally:
            pr.set_pallas_kernels(None)

    def run_round(enabled):
        pr.set_pallas_kernels(enabled)
        try:
            prior = jnp.zeros((keys,), jnp.int32)
            frontier = jnp.zeros((keys, n), jnp.int32)
            walls, outs = [], []
            for index, feed in enumerate(round_feeds):
                t0 = time.perf_counter()
                out = fused_table_round(
                    prior, frontier, *feed, threshold=2, voters=2
                )
                jax.block_until_ready(tuple(out))
                if index > 0:
                    walls.append((time.perf_counter() - t0) * 1000.0)
                outs.append(tuple(np.asarray(o) for o in out[2:]))
                prior, frontier = out[0], out[1]
            outs.append((np.asarray(prior), np.asarray(frontier)))
            return outs, sum(walls) / max(1, len(walls))
        finally:
            pr.set_pallas_kernels(None)

    commit_p, commit_p_ms = run_commit(True)
    commit_x, commit_x_ms = run_commit(False)
    round_p, round_p_ms = run_round(True)
    round_x, round_x_ms = run_round(False)
    for name, got, want in (
        ("votes_commit", commit_p, commit_x),
        ("table_round", round_p, round_x),
    ):
        for r, (g, w) in enumerate(zip(got, want)):
            for i, (a, b) in enumerate(zip(g, w)):
                assert np.array_equal(a, b), (
                    f"pallas route diverged on {name} round {r} field {i}"
                )
    return {
        "table_pallas_definition": (
            f"fused votes-commit + dense round, {batch} votes x "
            f"{rounds} rounds over {keys} keys, both routes on identical "
            "feeds, per-round output parity asserted (r19)"
        ),
        "table_pallas_commit_ms": round(commit_p_ms, 3),
        "table_pallas_commit_composed_ms": round(commit_x_ms, 3),
        "table_pallas_round_ms": round(round_p_ms, 3),
        "table_pallas_round_composed_ms": round(round_x_ms, 3),
    }


# --- perf-regression gate (bench.py --regress) ---
#
# Compare a fresh bench row against the BENCH trajectory with per-key
# tolerance bands, so a perf regression fails CI instead of being
# discovered by the next human reading BENCH_DEV.md.  Keys are
# classified by direction (throughput keys must not fall, latency keys
# must not grow); keys whose family carries a `*_definition` stamp are
# REFUSED (skipped + reported, never ratioed) when the stamps differ —
# the r06/r07 redefinitions made cross-definition ratios a category
# error — and records from different platforms refuse wholesale.

# tolerance bands: (key prefix, allowed degradation ratio); first match
# wins, "" is the default.  Noisy families (host scheduling, shared-CI
# latency-under-load) get wider bands; the default 1.5x is tight enough
# that an injected 2x latency regression trips the gate.
REGRESS_BANDS = (
    ("pool_", 3.0),
    ("overload_", 3.0),
    # adaptive-ingest serving rows ride a wall-clock arrival stream
    # calibrated against the rig's own saturation rate: shared-CI
    # scheduling noise moves both the offered rate and the served rate
    ("serving_ingest_", 2.5),
    ("general_fallback_", 2.5),
    # pred-plane rows time a python-vs-kernel race on shared CI cores:
    # scheduling noise swings the ratio harder than the plane does
    ("pred_", 2.5),
    # graph-plane rows race two kernel paths on the same shared cores:
    # same rationale (pred_plane_serving_* additionally rides asyncio
    # boot noise and is covered by the pred_ band above)
    ("graph_", 2.5),
    # failover drill walls time one-shot detection/rebuild events (a
    # single dispatch each) on shared CI cores — scheduling noise, not
    # the plane, dominates the spread
    ("failover_", 3.0),
    # route-vs-route kernel races (r19): per-dispatch walls of small
    # kernels on shared CI cores — scheduler noise swings a sub-ms wall
    # harder than any plumbing change; the chip rows carry the claim
    ("pallas_resolve_", 2.5),
    ("table_pallas_", 2.5),
    # scenario-curve rows (r20) ride the deterministic sim, but the knee
    # snaps between ladder points when detect_knee thresholds or the
    # serving path move — same coarse-grained band as overload_
    ("curve_", 3.0),
    ("", 1.5),
)

# families whose definition changed across rounds carry a stamp; both
# records must agree on it before any key of the family is compared
DEFINITION_STAMPS = (
    ("serving_", "serving_newt_definition"),
    # r19 kernel-race rows: table_pallas_ MUST precede table_ (first
    # match wins) or its keys would be gated on the r06 table stamp
    ("table_pallas_", "table_pallas_definition"),
    ("pallas_resolve_", "pallas_resolve_definition"),
    ("table_", "table_arrays_definition"),
    ("overload_", "overload_definition"),
    ("pred_plane_serving_", "pred_plane_serving_definition"),
    ("pred_", "pred_plane_definition"),
    ("graph_plane_", "graph_plane_definition"),
    ("graph_host_", "graph_plane_definition"),
    # r13 re-measured the fallback via chained slope (the one-shot
    # executor-seam wall moved to general_fallback_seam_ms)
    ("general_fallback_", "general_fallback_definition"),
    ("failover_", "failover_definition"),
    # r20 scenario-curve rows: the knee keys only compare when both
    # records ran the same ladder + detector definition
    ("curve_", "curve_definition"),
)


def _regress_direction(key: str):
    """"higher" = throughput-like (must not fall), "lower" =
    latency-like (must not grow), None = not a perf key (counts,
    fractions, configuration — informational only)."""
    if key == "jax_compile_ms":
        # cumulative XLA compile wall is a CACHE-STATE observation (cold
        # vs warm .jax_cache), not a perf key: ratioing a cold run
        # against a warm base would fabricate regressions
        return None
    if "cmds_per_s" in key or "goodput" in key:
        return "higher"
    if key.endswith(("_ms", "_p50", "_p95", "_p99")) or "_ms_" in key:
        return "lower"
    return None


def load_bench_record(path: str) -> dict:
    """Load a bench row: a raw JSON record, BENCH_TPU_LATEST.json, or a
    driver-written BENCH_r0N.json wrapper (``{"parsed": record, ...}``;
    some rounds nest the wrapper).  The headline ``value`` is re-keyed
    under its ``metric`` name so it participates like any other key."""
    with open(path) as fh:
        rec = json.load(fh)
    for _ in range(5):
        if isinstance(rec, dict) and "metric" in rec:
            break
        inner = rec.get("parsed") if isinstance(rec, dict) else None
        if not isinstance(inner, dict):
            break
        rec = inner
    if not isinstance(rec, dict) or "metric" not in rec:
        raise ValueError(f"{path} holds no usable bench record")
    if isinstance(rec.get("value"), (int, float)):
        rec = dict(rec)
        rec[rec["metric"]] = rec["value"]
    return rec


def regress_check(new: dict, old: dict, bands=REGRESS_BANDS) -> dict:
    """One gate evaluation: ``{"compared", "violations", "refused"}``
    (each a list of per-key tuples/messages)."""
    refused = []
    violations = []
    compared = []
    if new.get("platform") != old.get("platform"):
        refused.append((
            "*",
            f"platform mismatch: {old.get('platform')!r} vs "
            f"{new.get('platform')!r} — cross-platform ratios are "
            "meaningless; rerun on the same rig",
        ))
        return {"compared": compared, "violations": violations,
                "refused": refused}
    for key in sorted(set(new) & set(old)):
        new_v, old_v = new[key], old[key]
        if (
            not isinstance(new_v, (int, float))
            or not isinstance(old_v, (int, float))
            or isinstance(new_v, bool)
            or isinstance(old_v, bool)
        ):
            continue
        direction = _regress_direction(key)
        if direction is None or old_v <= 0:
            continue
        stamp = next(
            (s for prefix, s in DEFINITION_STAMPS if key.startswith(prefix)),
            None,
        )
        if stamp is not None and new.get(stamp) != old.get(stamp):
            refused.append((
                key,
                f"{stamp} mismatch: {old.get(stamp)!r} vs "
                f"{new.get(stamp)!r} — the family was redefined; "
                "see BENCH_DEV.md",
            ))
            continue
        band = next(b for prefix, b in bands if key.startswith(prefix))
        ratio = new_v / old_v
        row = (key, old_v, new_v, round(ratio, 3), band, direction)
        compared.append(row)
        if (direction == "lower" and ratio > band) or (
            direction == "higher" and ratio < 1.0 / band
        ):
            violations.append(row)
    return {"compared": compared, "violations": violations,
            "refused": refused}


def _default_against(new: dict) -> Tuple[str, dict]:
    """The most recent usable trajectory record matching the fresh row's
    platform: BENCH_r0N.json descending, then BENCH_TPU_LATEST.json."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    candidates = sorted(
        glob.glob(os.path.join(here, "BENCH_r*.json")), reverse=True
    ) + [os.path.join(here, "BENCH_TPU_LATEST.json")]
    fallback = None
    for path in candidates:
        if not os.path.exists(path):
            continue
        try:
            rec = load_bench_record(path)
        except (ValueError, json.JSONDecodeError):
            continue
        if rec.get("platform") == new.get("platform"):
            return path, rec
        if fallback is None:
            fallback = (path, rec)
    if fallback is None:
        raise SystemExit("--regress: no usable trajectory record found; "
                         "pass --against explicitly")
    return fallback


def cmd_regress(argv) -> int:
    """``bench.py --regress NEW.json [--against OLD.json] [--gate]``:
    report (default) or gate (exit 1 on violation) a fresh row against
    the trajectory."""
    args = list(argv)
    gate = "--gate" in args
    if gate:
        args.remove("--gate")
    against = None
    if "--against" in args:
        index = args.index("--against")
        against = args[index + 1]
        del args[index:index + 2]
    index = args.index("--regress")
    new_path = args[index + 1]
    new = load_bench_record(new_path)
    if against is None:
        against, old = _default_against(new)
    else:
        old = load_bench_record(against)
    result = regress_check(new, old)
    print(f"# regress: {new_path} vs {against} "
          f"({'gate' if gate else 'report-only'})")
    for key, reason in result["refused"]:
        print(f"REFUSED {key}: {reason}")
    for key, old_v, new_v, ratio, band, direction in result["compared"]:
        verdict = "ok"
        if (key, old_v, new_v, ratio, band, direction) in result["violations"]:
            verdict = f"REGRESSION (band {band}x, {direction}-is-better)"
        print(f"{key}: {old_v} -> {new_v} (x{ratio}) {verdict}")
    print(
        f"# {len(result['compared'])} compared, "
        f"{len(result['violations'])} violation(s), "
        f"{len(result['refused'])} refused"
    )
    if gate and result["violations"]:
        return 1
    return 0


# where `--smoke` persists its row, so CI can run the regression gate
# (report-only) over the smoke seams right after measuring them
_SMOKE_ROW_PATH = os.environ.get(
    "FANTOCH_SMOKE_ROW",
    os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_SMOKE_LATEST.json"
    ),
)


def smoke_main() -> None:
    """CI bench-smoke (``make bench-smoke``): tiny CPU-sized table +
    serving rows, in-process — catches import breaks and
    order-of-magnitude regressions in the bench seams without a chip.
    Gates are deliberately loose (CI hosts are slow and shared); the real
    numbers come from the full ``python bench.py`` run."""
    from fantoch_tpu.hostenv import force_cpu_platform

    force_cpu_platform()
    enable_compile_cache()
    from fantoch_tpu.observability.device import (
        cache_hit_count,
        cache_miss_count,
        compile_ms,
        recompile_count,
        subscribe_recompiles,
    )

    subscribe_recompiles()
    out = {"metric": "bench_smoke", "platform": "cpu"}
    out.update(bench_table_path(batch=2000, keys=256, n=3, rounds=2))
    out.update(bench_pred_path(batch=1024, keys=128, rounds=2))
    out.update(bench_graph_plane(batch=256, keys=64, rounds=2))
    out.update(
        bench_device_serving(
            total=1024, batch=256, families=("newt", "caesar"), sweep=False,
            pipeline_depth=2,
        )
    )
    out.update(bench_serving_batched(total=8192, batch=256, chain=3))
    # accelerator failover drill, CPU-sized: the row's own asserts cover
    # exactly-one cutback upload + bit-for-bit twin parity; the smoke
    # additionally refuses a degraded plane that served nothing
    out.update(
        bench_failover(keys=64, rounds=16, votes_per_round=256,
                       fault_at=5, down=4)
    )
    # r19 route-vs-route rows, CPU-sized: every round's outputs are
    # parity-asserted inside the bench — a diverging Pallas kernel fails
    # the smoke here, not on the rig
    out.update(bench_pallas_resolve(cap=128, width=4, rounds=4))
    out.update(bench_table_pallas(keys=64, batch=256, rounds=4))
    # r20 scenario-curve row: deterministic sim sweep — asserts in-row
    # that the ladder saturates (a missing knee is a pipeline break)
    out.update(bench_curve())
    out["jax_recompiles"] = recompile_count()
    out["jax_compile_ms"] = compile_ms()
    out["jax_cache_hits"] = cache_hit_count()
    out["jax_cache_misses"] = cache_miss_count()
    assert out["table_cmds_per_s_arrays"] > 1_000, out
    assert out["table_cmds_per_s_plane"] > 500, out
    assert out["serving_newt_cmds_per_s"] > 100, out
    assert out["table_plane_dispatches"] > 0, out
    # the resident pred plane: in-row parity already asserted by
    # bench_pred_path; gate counter sanity and an order-of-magnitude
    # floor (the >=2x speedup target is a full-bench number — on a
    # shared 1-core CI host the python-vs-kernel ratio is noise-bound,
    # so the smoke only refuses a plane that fell behind the host twin
    # outright)
    assert out["pred_plane_cmds_per_s"] > 1_000, out
    assert out["pred_plane_dispatches"] > 0, out
    assert out["pred_plane_residual_rows"] > 0, out  # seam exercised
    assert out["failover_degraded_cmds_per_s"] > 0, out
    assert out["failover_cutback_uploads"] == 1, out
    # one lazy materialization + one counted re-upload per compaction
    # or live capacity/width grow, never an upload per batch (the
    # residency invariant)
    assert (
        1
        <= out["pred_plane_resident_uploads"]
        <= 1 + out["pred_plane_compactions"] + out["pred_plane_grows"]
    ), out
    assert out["pred_plane_resident_uploads"] < out["pred_plane_dispatches"] + 1, out
    assert out["pred_plane_speedup"] >= 0.9, out
    # the resident graph plane: in-row parity (host twin + pipelined)
    # already asserted by bench_graph_plane; gate the residency invariant
    # — a reserved window means EXACTLY one lazy materialization, zero
    # backlog re-uploads across all steady-state feeds — plus counter
    # sanity and the 0.9x CPU slack (the pred-plane convention: the win
    # is claimed on the TPU rig where dispatch dominates; on a shared CI
    # core the two-kernel race is noise-bound)
    assert out["graph_plane_resident_uploads"] == 1, out
    assert out["graph_plane_compactions"] == 0, out
    assert out["graph_plane_dispatches"] > 0, out
    assert out["graph_plane_residual_rows"] > 0, out  # seam exercised
    assert out["graph_plane_patched_cells"] > 0, out  # waiter index exercised
    assert out["graph_plane_cmds_per_s"] > 1_000, out
    # the serving loop runs pipelined (the depth-2 smoke convention):
    # gate on the better of sync/pipelined so one scheduler hiccup on a
    # shared core doesn't flap the gate
    assert (
        max(
            out["graph_plane_cmds_per_s"],
            out["graph_plane_pipelined_cmds_per_s"],
        )
        >= 0.9 * out["graph_host_cmds_per_s"]
    ), out
    # the depth-2 pipelined serving loop: pipelined throughput must not
    # regress below the synchronous round (0.6x slack: CI hosts are slow,
    # shared, and CPU "device" rounds compete with the emit loop for the
    # same cores), and the overlap instrument must be present and sane
    assert out["serving_pipeline_depth"] == 2, out
    assert out["serving_newt_sync_cmds_per_s"] > 100, out
    assert (
        out["serving_newt_cmds_per_s"]
        >= 0.6 * out["serving_newt_sync_cmds_per_s"]
    ), out
    assert 0.0 <= out["serving_newt_idle_frac"] <= 1.0, out
    assert 0.0 <= out["serving_newt_sync_idle_frac"] <= 1.0, out
    # the Caesar serving family (the pred-plane protocol) rides the same
    # depth-2 pipelined loop: pipelined must not regress below 0.6x the
    # synchronous round (the Newt gate's slack, same CPU-rig rationale)
    assert out["serving_caesar_cmds_per_s"] > 100, out
    assert (
        out["serving_caesar_pipelined_cmds_per_s"]
        >= 0.6 * out["serving_caesar_cmds_per_s"]
    ), out
    # the r16 adaptive-ingest row: at 2x-saturation arrivals the batched
    # loop must fill its rounds (the batcher's whole job), must not lose
    # to the legacy dispatch-on-anything loop, and the timed region must
    # run fully warm — zero XLA compiles, every program (single step +
    # S=chain fused) compiled in the warm phase
    assert out["serving_ingest_fill_frac"] >= 0.5, out
    assert (
        out["serving_ingest_batched_cmds_per_s"]
        >= out["serving_ingest_unbatched_cmds_per_s"]
    ), out
    assert out["serving_ingest_recompiles_timed"] == 0, out
    # the r19 kernel-route rows ran their own bit-for-bit parity asserts
    # in-row; gate that both routes actually dispatched and were timed
    assert out["pallas_resolve_pred_ms"] > 0, out
    assert out["pallas_resolve_graph_ms"] > 0, out
    assert out["table_pallas_commit_ms"] > 0, out
    assert out["pallas_resolve_interpret"] is True, out  # cpu smoke
    # the r20 curve row: all three ladder points measured, knee detected
    # past the first point (the 50/s point must serve comfortably), and
    # the knee's goodput nonzero
    assert out["curve_points"] == 3, out
    assert out["curve_knee_goodput_cmds_per_s"] > 0, out
    assert out["curve_knee_offered_cmds_per_s"] > 50, out
    # compile-wall discipline (r19): on a warm persistent cache every
    # program is RETRIEVED (hits, no misses) and the true-recompile
    # counter stays at zero; a cold cache legitimately misses and
    # compiles, so the gate is conditional on observing zero misses
    assert out["jax_cache_misses"] > 0 or out["jax_recompiles"] == 0, out
    # compiled-identity audit: no registered plane program may mint an
    # unbounded signature ladder across the whole smoke (the benches
    # sweep a handful of shapes; a leaked non-canonical axis shows up as
    # a per-batch signature explosion)
    from fantoch_tpu.core.compile_cache import program_compile_counts

    for name, count in program_compile_counts().items():
        assert count <= 8, (name, count, out)
    # persist the row for the telemetry smoke's report-only regression
    # pass (bench.py --regress BENCH_SMOKE_LATEST.json); bookkeeping
    # must never fail the smoke itself
    try:
        with open(_SMOKE_ROW_PATH, "w") as fh:
            json.dump(out, fh)
            fh.write("\n")
    except OSError as exc:
        print(f"# could not persist smoke row: {exc!r}", file=sys.stderr)
    print(json.dumps(out))


def compare_records(path_a: str, path_b: str) -> int:
    """``bench.py --compare A.json B.json``: print new/old ratios for the
    numeric keys two round records share — with the REDEFINITION GUARD
    for the serving family.

    ``serving_newt_*`` was redefined in r07 (BENCH_r06 and earlier
    measured the synchronous round; r07+ measure the depth-K pipelined
    loop, stamped via ``serving_newt_definition``).  Comparing a pre-r07
    ``serving_*`` value against a post-r07 one is a category error — the
    pipelined loop trades per-round latency for overlap — so serving
    keys are only compared when both records carry the SAME
    ``serving_newt_definition`` stamp (absent counts as the pre-r07
    synchronous definition); mismatches are listed, not ratioed.
    Returns the number of keys skipped by the guard."""
    with open(path_a) as fh:
        old = json.load(fh)
    with open(path_b) as fh:
        new = json.load(fh)
    old_def = old.get("serving_newt_definition")
    new_def = new.get("serving_newt_definition")
    serving_comparable = old_def == new_def
    skipped = 0
    for key in sorted(set(old) & set(new)):
        old_v, new_v = old[key], new[key]
        if not isinstance(old_v, (int, float)) or not isinstance(new_v, (int, float)):
            continue
        if isinstance(old_v, bool) or isinstance(new_v, bool):
            continue
        if key.startswith("serving_") and not serving_comparable:
            skipped += 1
            print(f"{key}: SKIPPED (serving_newt_definition mismatch: "
                  f"{old_def!r} vs {new_def!r} — r07 redefined the serving "
                  f"family; see BENCH_DEV.md)")
            continue
        ratio = (new_v / old_v) if old_v else float("inf")
        print(f"{key}: {old_v} -> {new_v} (x{ratio:.3f})")
    if skipped:
        print(f"# {skipped} serving key(s) guarded: pre-r07 serving_* rows "
              "(BENCH_r01-r05) measure the synchronous round, not the "
              "pipelined loop", file=sys.stderr)
    return skipped


def main() -> None:
    if "--regress" in sys.argv[1:]:
        sys.exit(cmd_regress(sys.argv))
    if "--compare" in sys.argv[1:]:
        index = sys.argv.index("--compare")
        compare_records(sys.argv[index + 1], sys.argv[index + 2])
        return
    if "--smoke" in sys.argv[1:]:
        smoke_main()
        return
    mode = os.environ.get(_CHILD_ENV)
    if mode:
        child_main(mode)
        return

    # explicit CPU request short-circuits the TPU probe entirely
    want_cpu = os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    if not want_cpu and _probe_backend():
        line = _run_child("tpu", CHILD_TIMEOUT_S)
        if line is not None:
            _save_tpu_record(line)
            print(line)
            return
        print("# tpu measurement failed; falling back to CPU", file=sys.stderr)

    line = _run_child("cpu", CHILD_TIMEOUT_S)
    if line is not None:
        print(_attach_last_tpu(line))
        return
    print(json.dumps({
        "metric": METRIC,
        "value": None,
        "unit": "ms",
        "vs_baseline": None,
        "error": "all measurement children failed (see stderr)",
    }))
    sys.exit(1)


if __name__ == "__main__":
    main()
