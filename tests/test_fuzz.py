"""Chaos fuzzer: same-seed determinism, shrinker minimality, repro
artifacts, the per-protocol clean rows (FPaxos and Caesar included), and
the mutation self-test — the PR 7 GC-straggler commit-replay bug is
reintroduced under its private flag and must be caught by the fuzzer
within the smoke budget, shrunk, and replayed byte-identically.
"""

import dataclasses
import json

import pytest

from fantoch_tpu.sim.faults import FaultPlan
from fantoch_tpu.sim.fuzz import (
    OK,
    PROTOCOL_SPECS,
    VIOLATION,
    FaultPlanFuzzer,
    FuzzCase,
    FuzzResult,
    load_repro,
    replay_repro,
    repro_artifact,
    run_case,
    shrink_case,
    write_repro,
)

pytestmark = pytest.mark.fuzz

# the smoke seed set (scripts/fuzz_smoke.py uses the same): fuzzer seed 0,
# the first SMOKE_CASES indices forced per protocol
SMOKE_SEED = 0
SMOKE_CASES = 6


# --- determinism: same seed => byte-identical plan, trace, verdict ---


def test_same_seed_case_and_run_identical():
    fuzzer = FaultPlanFuzzer(seed=3)
    case_a, case_b = fuzzer.case(1), fuzzer.case(1)
    assert case_a == case_b
    assert case_a.digest() == case_b.digest()
    result_a, result_b = run_case(case_a), run_case(case_b)
    assert result_a.verdict == result_b.verdict
    assert result_a.plan_digest == result_b.plan_digest
    assert result_a.trace_digest == result_b.trace_digest
    assert result_a.verdict_digest == result_b.verdict_digest
    # non-vacuous: the plan injected something and the digests are real
    assert result_a.trace_digest and result_a.plan_digest


def test_case_json_roundtrip_replays_identically():
    fuzzer = FaultPlanFuzzer(seed=5)
    case = fuzzer.case(2)
    blob = json.dumps(case.to_dict(), sort_keys=True)
    restored = FuzzCase.from_dict(json.loads(blob))
    assert restored == case
    assert run_case(restored).verdict_digest == run_case(case).verdict_digest


def test_different_seeds_differ():
    a = FaultPlanFuzzer(seed=0).case(0)
    b = FaultPlanFuzzer(seed=1).case(0)
    assert a.digest() != b.digest()


# --- the smoke rows: every protocol gets composed nemeses and stays
# auditor-clean (FPaxos and Caesar included — the satellite closing the
# EPaxos/Atlas/Newt-only chaos coverage) ---


@pytest.mark.parametrize("protocol", sorted(PROTOCOL_SPECS))
def test_protocol_smoke_rows_auditor_clean(protocol):
    fuzzer = FaultPlanFuzzer(seed=SMOKE_SEED)
    verdicts = []
    for index in range(3):
        case = fuzzer.case(index, protocol=protocol)
        result = run_case(case)
        assert result.verdict == OK, (
            f"{protocol} case {index}: {result.verdict} "
            f"{result.violations or result.error}"
        )
        verdicts.append(result.verdict)
    assert verdicts.count(OK) >= 1


def test_caesar_wait_condition_targeted_config():
    """Caesar's wait-condition region (the reference's own unsafe-TODO
    area) under its targeted stress: max conflict + reorder + pause —
    the nemeses that reorder MPropose/MRetry around the blocking check.
    A violation here fails the run like every other protocol's (PR 9's
    file-as-issue carve-out died with the Caesar recovery plane)."""
    fuzzer = FaultPlanFuzzer(seed=SMOKE_SEED)
    base = fuzzer.case(1, protocol="caesar")
    case = dataclasses.replace(
        base,
        conflict_rate=100,
        keys_per_command=1,
        plan=base.plan.with_reorder(6.0).with_pause(
            1, at_ms=200, until_ms=700
        ),
    )
    result = run_case(case)
    assert result.verdict == OK, (result.violations, result.error)


def test_caesar_artifact_has_no_filing_special_case():
    """The Caesar filed-as-issue escape hatch is gone: artifacts carry no
    issue text unless the caller supplies one, for every protocol."""
    case = FaultPlanFuzzer(seed=0).case(0, protocol="caesar")
    fake = FuzzResult(case, VIOLATION, violations=["[order-divergence] x"])
    assert repro_artifact(fake)["issue"] is None
    assert repro_artifact(fake, issue="manual")["issue"] == "manual"
    other = dataclasses.replace(case, protocol="newt")
    assert repro_artifact(FuzzResult(other, VIOLATION))["issue"] is None


def test_specs_compose_every_nemesis_class():
    """No silent caps: the spec table has no crash/restart escape hatches
    left, and the sampler demonstrably emits crash, crash-restart, and
    non-crash plans for EVERY protocol (Caesar crash + FPaxos restart
    were PR 9's carve-outs)."""
    assert not hasattr(next(iter(PROTOCOL_SPECS.values())), "crash_ok")
    assert not hasattr(next(iter(PROTOCOL_SPECS.values())), "restart_ok")
    fuzzer = FaultPlanFuzzer(seed=SMOKE_SEED)
    for protocol in sorted(PROTOCOL_SPECS):
        kinds = set()
        for index in range(40):
            plan = fuzzer.case(index, protocol=protocol).plan
            if not plan.crashes:
                kinds.add("none")
            elif any(c.restart_at_ms is not None for c in plan.crashes):
                kinds.add("restart")
            else:
                kinds.add("crash")
            if len(kinds) == 3:
                break
        assert kinds == {"none", "crash", "restart"}, (protocol, kinds)


# --- reorder nemesis (FaultPlan.with_reorder) ---


def test_reorder_nemesis_seeded_and_trace_visible():
    fuzzer = FaultPlanFuzzer(seed=SMOKE_SEED)
    base = fuzzer.case(0, protocol="epaxos")
    plain = dataclasses.replace(base, plan=dataclasses.replace(base.plan, reorder=None))
    reordered = dataclasses.replace(
        base, plan=plain.plan.with_reorder(factor=9.0)
    )
    result_plain = run_case(plain)
    result_a, result_b = run_case(reordered), run_case(reordered)
    # same seed + reorder => byte-identical; reorder on vs off => different
    assert result_a.trace_digest == result_b.trace_digest
    assert result_a.verdict_digest == result_b.verdict_digest
    assert result_a.trace_digest != result_plain.trace_digest
    assert result_a.verdict == OK


# --- shrinker ---


def test_shrinker_minimality_synthetic():
    """Greedy removal reaches a fixpoint where every remaining component
    is load-bearing: the synthetic failure needs the crash AND a loss
    fault; everything else must be stripped."""
    plan = (
        FaultPlan(seed=9, max_sim_time_ms=10_000)
        .with_loss(0.2)
        .with_link_fault(duplicate=0.2)
        .with_link_fault(extra_delay_ms=30)
        .with_crash(2, at_ms=400)
        .with_pause(3, at_ms=100, until_ms=600)
        .with_slow_process(1, 40, until_ms=500)
        .with_partition([(1,), (2, 3)], start_ms=100, heal_ms=900)
        .with_reorder(4.0)
    )
    case = FuzzCase(
        protocol="epaxos", n=3, f=1, plan=plan,
        commands_per_client=8, open_loop_rate_per_s=50.0,
    )

    def fails(candidate: FuzzCase) -> bool:
        has_crash = any(
            c.process_id == 2 for c in candidate.plan.crashes
        )
        has_loss = any(f.drop > 0 for f in candidate.plan.link_faults)
        return has_crash and has_loss

    shrunk, runs = shrink_case(case, still_fails=fails)
    assert fails(shrunk)
    assert len(shrunk.plan.crashes) == 1
    assert len(shrunk.plan.link_faults) == 1
    assert shrunk.plan.link_faults[0].drop > 0
    assert not shrunk.plan.pauses
    assert not shrunk.plan.partitions
    assert not shrunk.plan.slow_processes
    assert shrunk.plan.reorder is None
    assert shrunk.open_loop_rate_per_s is None
    # numeric halving reached the floor
    assert shrunk.commands_per_client == 1
    # minimality: removing EITHER remaining component kills the failure
    no_crash = dataclasses.replace(
        shrunk, plan=dataclasses.replace(shrunk.plan, crashes=())
    )
    no_loss = dataclasses.replace(
        shrunk, plan=dataclasses.replace(shrunk.plan, link_faults=())
    )
    assert not fails(no_crash) and not fails(no_loss)
    assert runs > 0


def test_shrinker_requires_failing_case():
    case = FaultPlanFuzzer(seed=0).case(0)
    with pytest.raises(AssertionError, match="failing case"):
        shrink_case(case, still_fails=lambda _c: False)


# --- the mutation self-test: the PR 7 GC-straggler bug, reintroduced ---


def test_mutation_gc_straggler_bug_caught_and_shrunk(tmp_path):
    """Disable Newt's GC-straggler guards (the historical commit-replay
    bug, reintroduced under its private flag): the fuzzer must catch it
    within the smoke budget, the shrinker must minimize it, the repro
    artifact must replay byte-identically under the mutation, and the
    SAME case must run clean with the guard restored — proving the
    instrument detects real historical violations, not just synthetic
    ones."""
    import fantoch_tpu.protocol.newt as newt_module

    fuzzer = FaultPlanFuzzer(seed=SMOKE_SEED)
    newt_module._set_gc_straggler_guard(False)
    try:
        finding = None
        for index in range(SMOKE_CASES):
            case = fuzzer.case(index, protocol="newt")
            result = run_case(case)
            if result.verdict == VIOLATION:
                finding = (index, case, result)
                break
        assert finding is not None, (
            "the reintroduced GC-straggler bug escaped the smoke budget"
        )
        index, case, result = finding
        shrunk, runs = shrink_case(case, max_runs=60)
        shrunk_result = run_case(shrunk)
        assert shrunk_result.verdict == VIOLATION
        artifact = repro_artifact(shrunk_result, shrink_runs=runs)
        path = str(tmp_path / "gc-straggler-repro.json")
        write_repro(path, artifact)
        replayed, identical = replay_repro(load_repro(path))
        assert replayed.verdict == VIOLATION
        assert identical, "repro replay must be byte-identical"
    finally:
        newt_module._set_gc_straggler_guard(True)
    # guard restored: the exact shrunk schedule is clean again
    healthy = run_case(shrunk)
    assert healthy.verdict == OK, (
        f"guard on, still failing: {healthy.violations or healthy.error}"
    )


def test_bin_fuzz_run_exits_nonzero_on_filed_artifact(tmp_path, monkeypatch, capsys):
    """``bin/fuzz.py run`` fails whenever ANY case files a repro
    artifact — no protocol is exempt (PR 9's Caesar filed-not-fixed
    special case left such sweeps green) — and the failure line names
    the artifact path."""
    import fantoch_tpu.sim.fuzz as fuzz_mod
    from fantoch_tpu.bin import fuzz as bin_fuzz

    def fake_run_case(case, flight_dir=None):
        return FuzzResult(case, VIOLATION, violations=["[order-divergence] x"])

    monkeypatch.setattr(fuzz_mod, "run_case", fake_run_case)
    monkeypatch.setattr(fuzz_mod, "shrink_case", lambda case, **_k: (case, 0))
    rc = bin_fuzz.main(
        [
            "run", "--seed", "0", "--cases", "1",
            "--protocols", "caesar", "--out-dir", str(tmp_path),
        ]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "FAILED: repro artifact" in out
    assert str(tmp_path) in out


def test_repro_artifact_roundtrip_on_clean_case(tmp_path):
    case = FaultPlanFuzzer(seed=SMOKE_SEED).case(0, protocol="atlas")
    result = run_case(case)
    artifact = repro_artifact(result)
    path = str(tmp_path / "clean.json")
    write_repro(path, artifact)
    replayed, identical = replay_repro(load_repro(path))
    assert identical and replayed.verdict == result.verdict
