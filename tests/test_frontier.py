"""DeviceFrontier vs AEClock: the vectorized executed-set mirror must agree
with the host lattice on membership, watermark advancement and counts
(fantoch_tpu/ops/frontier.py vs core/clocks.py AboveExSet/AEClock)."""

import random

import numpy as np

from fantoch_tpu.core.clocks import AEClock
from fantoch_tpu.ops.frontier import DeviceFrontier


def test_scalar_parity_random():
    rng = random.Random(3)
    ids = [1, 2, 3, 4, 5]
    for _ in range(20):
        fr = DeviceFrontier(ids)
        ae: AEClock = AEClock(ids)
        events = [(rng.choice(ids), rng.randint(1, 40)) for _ in range(200)]
        for s, q in events:
            assert fr.add(s, q) == ae.add(s, q)
        for s in ids:
            for q in range(1, 45):
                assert fr.contains(s, q) == ae.contains(s, q), (s, q)
            assert fr.frontier_of(s) == ae.get(s).frontier
        assert fr.event_count() == ae.event_count()


def test_batch_parity_random():
    rng = np.random.default_rng(9)
    ids = [1, 2, 3]
    fr = DeviceFrontier(ids)
    ae: AEClock = AEClock(ids)
    for _ in range(10):
        src = rng.integers(1, 4, size=64)
        seq = rng.integers(1, 200, size=64)
        fr.add_batch(src, seq)
        for s, q in zip(src, seq):
            ae.add(int(s), int(q))
        qs_src = rng.integers(1, 4, size=128)
        qs_seq = rng.integers(1, 220, size=128)
        got = fr.contains_batch(qs_src, qs_seq)
        want = np.array(
            [ae.contains(int(s), int(q)) for s, q in zip(qs_src, qs_seq)]
        )
        assert (got == want).all()


def test_watermark_absorbs_contiguous():
    fr = DeviceFrontier([1])
    fr.add_batch(np.array([1, 1, 1]), np.array([2, 3, 5]))
    assert fr.frontier_of(1) == 0  # 1 missing
    fr.add(1, 1)
    assert fr.frontier_of(1) == 3  # 1,2,3 contiguous; 5 stays an exception
    assert fr.contains(1, 5) and not fr.contains(1, 4)
    assert len(fr.exceptions()) == 1
    fr.add(1, 4)
    assert fr.frontier_of(1) == 5
    assert len(fr.exceptions()) == 0


def test_unknown_source_grows():
    fr = DeviceFrontier([1])
    assert not fr.contains(9, 1)
    fr.add(9, 1)
    assert fr.contains(9, 1) and fr.frontier_of(9) == 1


def test_add_range():
    fr = DeviceFrontier([1, 2])
    fr.add_range(2, 1, 1000)
    assert fr.frontier_of(2) == 1000
    assert fr.contains(2, 1000) and not fr.contains(2, 1001)
