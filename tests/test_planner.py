"""Planner (bote analog): quorum latency arithmetic and config search
(fantoch_bote/src/{lib,protocol,search}.rs behavior)."""

from fantoch_tpu.core.planet import Planet, Region
from fantoch_tpu.planner import Bote, RankingParams, Search, quorum_size


def synthetic_planet():
    """Four regions on a line: A - 10 - B - 10 - C - 10 - D (additive)."""
    a, b, c, d = Region("A"), Region("B"), Region("C"), Region("D")
    pos = {a: 0, b: 10, c: 20, d: 30}
    lat = {
        x: {y: abs(pos[x] - pos[y]) for y in pos}
        for x in pos
    }
    return Planet.from_latencies(lat), (a, b, c, d)


def test_quorum_sizes():
    # protocol.rs:20-35
    assert quorum_size("fpaxos", 5, 1) == 2
    assert quorum_size("fpaxos", 5, 2) == 3
    assert quorum_size("atlas", 5, 1) == 3
    assert quorum_size("atlas", 5, 2) == 4
    assert quorum_size("epaxos", 5, 0) == 3  # f = minority = 2; 2 + 3//2
    assert quorum_size("epaxos", 7, 0) == 5  # f = 3; 3 + 2


def test_nth_closest_counts_self():
    planet, (a, b, c, d) = synthetic_planet()
    bote = Bote(planet)
    servers = [a, b, c]
    # closest to A among servers is A itself at 0
    assert bote.nth_closest(1, a, servers) == (0, a)
    assert bote.nth_closest(2, a, servers) == (10, b)
    # quorum of 2 from A = distance to B
    assert bote.quorum_latency(a, servers, 2) == 10
    assert bote.quorum_latency(b, servers, 3) == 10  # B,A/C at 10


def test_leaderless_latency():
    planet, (a, b, c, d) = synthetic_planet()
    bote = Bote(planet)
    servers = [a, b, c]
    got = dict(bote.leaderless(servers, [a, d], quorum_size=2))
    # client A: closest server A (0) + quorum2 from A (10) = 10
    assert got[a] == 10
    # client D: closest server C (10) + quorum2 from C (10) = 20
    assert got[d] == 20


def test_leader_and_best_leader():
    planet, (a, b, c, d) = synthetic_planet()
    bote = Bote(planet)
    servers = [a, b, c]
    clients = [a, c, d]
    got = dict(bote.leader(b, servers, clients, quorum_size=2))
    # leader B -> quorum2 = 10; clients at 10/10/20 from B
    assert got == {a: 20, c: 20, d: 30}
    leader, hist = bote.best_leader(servers, clients, quorum_size=2)
    # C minimizes mean: clients 20/0/10 + quorum 10 => mean 20 vs B 23.3, A 30
    assert leader == c
    assert hist.mean() == (30 + 10 + 20) / 3


def test_search_stats_and_ranking():
    planet, (a, b, c, d) = synthetic_planet()
    search = Search(planet, [a, b, c, d])
    stats = search.compute_stats([a, b, c])
    # atlas n=3 f=1: quorum 2; per client (a,b,c,d): 10, 10, 10, 10+10
    assert stats["a_f1"].mean() == (10 + 10 + 10 + 20) / 4
    assert "f_f1" in stats and "e" in stats
    # with no thresholds every 3-config is scored; best must be returned
    ranked = search.sorted_configs(
        3, RankingParams(min_mean_decrease_vs_fpaxos=-1000,
                         min_mean_decrease_vs_epaxos=-1000,
                         fault_levels=(1,)),
    )
    assert ranked and len(ranked) <= 10
    assert ranked[0].score >= ranked[-1].score
    # colocated placement drops the client->closest leg
    colo = search.compute_stats([a, b, c], colocated=True)
    assert colo["a_f1C"].mean() == 10.0


def test_search_thresholds_filter():
    planet, (a, b, c, d) = synthetic_planet()
    search = Search(planet, [a, b, c, d])
    # impossible threshold: atlas can't beat fpaxos by 10s on this planet
    ranked = search.sorted_configs(
        3, RankingParams(min_mean_decrease_vs_fpaxos=10_000, fault_levels=(1,))
    )
    assert ranked == []


def test_search_on_real_planet():
    planet = Planet.new("gcp")
    regions = sorted(planet.regions())[:8]
    search = Search(planet, regions)
    ranked = search.sorted_configs(
        3,
        RankingParams(min_mean_decrease_vs_fpaxos=-10_000,
                      min_mean_decrease_vs_epaxos=-10_000,
                      fault_levels=(1,)),
        top=5,
    )
    assert len(ranked) == 5
    for cfg in ranked:
        assert len(cfg.regions) == 3
        assert set(cfg.stats) >= {"a_f1", "f_f1", "e"}
