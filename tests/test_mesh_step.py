"""Multi-chip SPMD protocol step on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fantoch_tpu.ops.graph_resolve import TERMINAL
from fantoch_tpu.parallel import mesh_step


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return mesh_step.make_mesh(8)


def test_mesh_axes(mesh):
    assert set(mesh.axis_names) == {"replica", "batch"}
    assert mesh.shape["replica"] * mesh.shape["batch"] == 8


def test_intra_batch_chain():
    key = jnp.asarray([3, 5, 3, 3, 5, 9], dtype=jnp.int32)
    chain = mesh_step._intra_batch_chain(key)
    assert chain.tolist() == [TERMINAL, TERMINAL, 0, 2, 1, TERMINAL]


def test_protocol_step_executes_batch(mesh):
    num_replicas = 2 * mesh.shape["replica"]
    batch = 8 * mesh.shape["batch"]
    state = mesh_step.init_state(mesh, num_replicas, key_buckets=16)
    step = mesh_step.jit_protocol_step(mesh)

    rng = np.random.default_rng(1)
    key = jnp.asarray(rng.integers(0, 4, size=batch), dtype=jnp.int32)
    src = jnp.asarray(rng.integers(1, num_replicas + 1, size=batch), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)

    state, out = step(state, key, src, seq)
    assert bool(out.resolved.all())
    # order is a permutation
    assert sorted(out.order.tolist()) == list(range(batch))
    # deps respect execution order: a command's dependency executes first
    pos = np.empty(batch, dtype=np.int64)
    pos[np.asarray(out.order)] = np.arange(batch)
    deps = np.asarray(out.deps_gid)
    for i in range(batch):
        if deps[i] >= 0:
            assert pos[deps[i]] < pos[i], f"dep of {i} executed after it"
    # state advanced
    assert int(state.next_gid) == batch
    assert state.frontier.tolist() == [batch] * num_replicas


def test_protocol_step_fast_path_divergence(mesh):
    """Replicas that disagree on prior deps (different key_clock entries)
    must not take the fast path; the committed dep is the union max."""
    num_replicas = mesh.shape["replica"] * 2
    batch = mesh.shape["batch"] * 8
    state = mesh_step.init_state(mesh, num_replicas, key_buckets=16)
    # replica 0 saw gid 7 on key 3; others saw nothing
    kc = np.array(state.key_clock)
    kc[0, 3] = 7
    state = state._replace(
        key_clock=jax.device_put(
            jnp.asarray(kc), state.key_clock.sharding
        ),
        next_gid=jnp.int32(100),
    )
    step = mesh_step.jit_protocol_step(mesh)

    key = jnp.full((batch,), 5, dtype=jnp.int32).at[0].set(3)
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, key, src, seq)

    fast = np.asarray(out.fast_path)
    deps = np.asarray(out.deps_gid)
    assert not fast[0], "diverging replica views must take the slow path"
    assert deps[0] == 7, "union of reported deps = max gid"
    # the rest of the batch chains on key 5: deterministic, fast path
    assert fast[1:].all()
    # the Synod accept round committed the fast-path miss
    assert int(out.slow_paths) == 1
    assert bool(out.resolved.all()), "slow-path command still commits"
    # GC watermark: all replicas executed the whole round
    assert int(out.stable) == batch


def test_slow_path_fails_without_write_quorum(mesh):
    """With fewer live replicas than the write quorum, slow-path commands
    do not commit — and neither does anything chained on them."""
    num_replicas = mesh.shape["replica"] * 2  # n=4: f=2, write quorum 3
    batch = mesh.shape["batch"] * 8
    state = mesh_step.init_state(mesh, num_replicas, key_buckets=16)
    kc = np.array(state.key_clock)
    kc[0, 3] = 7  # replica 0 alone saw a prior commit on key 3
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding),
        next_gid=jnp.int32(100),
    )
    # only 2 live replicas < write quorum 3
    step = mesh_step.jit_protocol_step(mesh, live_replicas=2)

    key = jnp.full((batch,), 3, dtype=jnp.int32)  # all chained on key 3
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, key, src, seq)

    resolved = np.asarray(out.resolved)
    assert not np.asarray(out.fast_path)[0], "cmd 0 sees diverging views"
    assert not resolved[0], "no write quorum -> slow-path cmd uncommitted"
    # every later command chains (directly or transitively) on cmd 0
    assert not resolved.any(), "dependents of an uncommitted cmd cannot run"
    assert int(out.stable) == 0


def test_state_carries_across_steps(mesh):
    """Round 2 commands conflict with round 1 via the key clock."""
    num_replicas = mesh.shape["replica"]
    batch = mesh.shape["batch"] * 4
    state = mesh_step.init_state(mesh, num_replicas, key_buckets=8)
    step = mesh_step.jit_protocol_step(mesh)

    key = jnp.zeros((batch,), jnp.int32)  # everyone on key 0
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, _ = step(state, key, src, seq)

    state, out = step(state, key, src, seq)
    deps = np.asarray(out.deps_gid)
    # first command of round 2 depends on the last command of round 1
    assert deps[0] == batch - 1
    assert bool(out.resolved.all())
