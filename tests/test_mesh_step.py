"""Multi-chip SPMD protocol step on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fantoch_tpu.ops.graph_resolve import TERMINAL
from fantoch_tpu.parallel import mesh_step


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return mesh_step.make_mesh(8)


def test_mesh_axes(mesh):
    assert set(mesh.axis_names) == {"replica", "batch"}
    assert mesh.shape["replica"] * mesh.shape["batch"] == 8


def test_intra_batch_chain():
    key = jnp.asarray([3, 5, 3, 3, 5, 9], dtype=jnp.int32)
    chain = mesh_step._intra_batch_chain(key[:, None])
    assert chain[:, 0].tolist() == [TERMINAL, TERMINAL, 0, 2, 1, TERMINAL]


def test_intra_batch_chain_multikey():
    # rows tagged with up to two keys; per-slot chains follow each key
    keys = jnp.asarray(
        [[3, 5], [5, 9], [3, 9], [9, 3]], dtype=jnp.int32
    )
    chain = mesh_step._intra_batch_chain(keys)
    # row0: first on 3 and 5; row1: 5<-row0, first on 9;
    # row2: 3<-row0, 9<-row1; row3: 9<-row2, 3<-row2
    assert chain.tolist() == [
        [TERMINAL, TERMINAL],
        [0, TERMINAL],
        [0, 1],
        [2, 2],
    ]


def test_protocol_step_executes_batch(mesh):
    num_replicas = 2 * mesh.shape["replica"]
    batch = 8 * mesh.shape["batch"]
    state = mesh_step.init_state(mesh, num_replicas, key_buckets=16)
    step = mesh_step.jit_protocol_step(mesh)

    rng = np.random.default_rng(1)
    key = jnp.asarray(rng.integers(0, 4, size=batch), dtype=jnp.int32)
    src = jnp.asarray(rng.integers(1, num_replicas + 1, size=batch), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)

    state, out = step(state, key, src, seq)
    gids = np.asarray(out.gids)
    valid = gids >= 0
    resolved = np.asarray(out.resolved)
    assert resolved[valid].all()
    work = len(gids)
    # order is a permutation of the working rows
    assert sorted(out.order.tolist()) == list(range(work))
    # deps respect execution order: a command's dependency executes first
    pos = np.empty(work, dtype=np.int64)
    pos[np.asarray(out.order)] = np.arange(work)
    pos_by_gid = {int(g): pos[i] for i, g in enumerate(gids) if g >= 0}
    deps = np.asarray(out.deps_gid)
    for i in range(work):
        if not valid[i]:
            continue
        for d in deps[i]:
            if d >= 0:
                assert pos_by_gid[int(d)] < pos[i], f"dep of {i} executed after it"
    # state advanced
    assert int(state.next_gid) == batch
    assert state.frontier.tolist() == [batch] * num_replicas
    assert int(out.pending) == 0 and int(out.pend_dropped) == 0


def test_protocol_step_fast_path_divergence(mesh):
    """Replicas that disagree on prior deps (different key_clock entries)
    must not take the fast path; the committed dep is the union max."""
    num_replicas = mesh.shape["replica"] * 2
    batch = mesh.shape["batch"] * 8
    state = mesh_step.init_state(mesh, num_replicas, key_buckets=16)
    # replica 0 saw gid 7 on key 3; others saw nothing
    kc = np.array(state.key_clock)
    kc[0, 3] = 7
    state = state._replace(
        key_clock=jax.device_put(
            jnp.asarray(kc), state.key_clock.sharding
        ),
        next_gid=jnp.int32(100),
    )
    step = mesh_step.jit_protocol_step(mesh)

    key = jnp.full((batch,), 5, dtype=jnp.int32).at[0].set(3)
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, key, src, seq)

    fast = np.asarray(out.fast_path)
    deps = np.asarray(out.deps_gid)[:, 0]
    valid = np.asarray(out.gids) >= 0
    new0 = state.pend_gid.shape[0]  # first new-batch working row
    assert not fast[new0], "diverging replica views must take the slow path"
    assert deps[new0] == 7, "union of reported deps = max gid"
    # the rest of the batch chains on key 5: deterministic, fast path
    assert fast[new0 + 1 :].all()
    # the Synod accept round committed the fast-path miss
    assert int(out.slow_paths) == 1
    assert np.asarray(out.resolved)[valid].all(), "slow-path command still commits"
    # GC watermark: all replicas executed the whole round
    assert int(out.stable) == batch


def test_slow_path_fails_without_write_quorum(mesh):
    """With fewer live replicas than the write quorum, slow-path commands
    do not commit — and neither does anything chained on them."""
    num_replicas = mesh.shape["replica"] * 2  # n=4: f=2, write quorum 3
    batch = mesh.shape["batch"] * 8
    state = mesh_step.init_state(mesh, num_replicas, key_buckets=16)
    kc = np.array(state.key_clock)
    kc[0, 3] = 7  # replica 0 alone saw a prior commit on key 3
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding),
        next_gid=jnp.int32(100),
    )
    # only 2 live replicas < write quorum 3
    step = mesh_step.jit_protocol_step(mesh, live_replicas=2)

    key = jnp.full((batch,), 3, dtype=jnp.int32)  # all chained on key 3
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, key, src, seq)

    resolved = np.asarray(out.resolved)
    new0 = state.pend_gid.shape[0]
    assert not np.asarray(out.fast_path)[new0], "cmd 0 sees diverging views"
    assert not resolved[new0], "no write quorum -> slow-path cmd uncommitted"
    # every later command chains (directly or transitively) on cmd 0
    assert not resolved.any(), "dependents of an uncommitted cmd cannot run"
    assert int(out.stable) == 0
    # the liveness fix: the whole round is carried, not dropped
    assert int(out.pending) == batch and int(out.pend_dropped) == 0


def test_state_carries_across_steps(mesh):
    """Round 2 commands conflict with round 1 via the key clock."""
    num_replicas = mesh.shape["replica"]
    batch = mesh.shape["batch"] * 4
    state = mesh_step.init_state(mesh, num_replicas, key_buckets=8)
    step = mesh_step.jit_protocol_step(mesh)

    key = jnp.zeros((batch,), jnp.int32)  # everyone on key 0
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, _ = step(state, key, src, seq)

    state, out = step(state, key, src, seq)
    deps = np.asarray(out.deps_gid)[:, 0]
    valid = np.asarray(out.gids) >= 0
    new0 = state.pend_gid.shape[0]
    # first command of round 2 depends on the last command of round 1
    assert deps[new0] == batch - 1
    assert np.asarray(out.resolved)[valid].all()


def test_protocol_step_multikey(mesh):
    """Multi-key commands (two key buckets each) route through the general
    resolver on-mesh: per-slot deps all execute before their dependents,
    and round-2 chains continue from both key-clock slots."""
    num_replicas = mesh.shape["replica"]
    batch = mesh.shape["batch"] * 4
    state = mesh_step.init_state(
        mesh, num_replicas, key_buckets=16, key_width=2
    )
    step = mesh_step.jit_protocol_step(mesh)

    rng = np.random.default_rng(3)
    keys = np.stack(
        [rng.choice(6, size=2, replace=False) for _ in range(batch)]
    ).astype(np.int32)
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, jnp.asarray(keys), src, seq)

    gids = np.asarray(out.gids)
    valid = gids >= 0
    assert np.asarray(out.resolved)[valid].all()
    work = len(gids)
    pos = np.empty(work, np.int64)
    pos[np.asarray(out.order)] = np.arange(work)
    pos_by_gid = {int(g): pos[i] for i, g in enumerate(gids) if g >= 0}
    deps = np.asarray(out.deps_gid)
    for i in range(work):
        if not valid[i]:
            continue
        for d in deps[i]:
            if d >= 0:
                assert pos_by_gid[int(d)] < pos[i], f"dep of {i} after it"

    # round 2 on the same key sets: both dep slots of the first round-2
    # command come from round 1 via the replicated key clock
    seq2 = jnp.arange(batch, 2 * batch, dtype=jnp.int32)
    state, out2 = step(state, jnp.asarray(keys), src, seq2)
    new0 = state.pend_gid.shape[0]
    deps2 = np.asarray(out2.deps_gid)
    assert (deps2[new0] >= 0).all() and (deps2[new0] < batch).all()
    assert np.asarray(out2.resolved)[np.asarray(out2.gids) >= 0].all()
    assert state.frontier.tolist() == [2 * batch] * num_replicas


@pytest.mark.slow
def test_multikey_pending_commits_after_quorum_recovers(mesh):
    """Degraded-quorum liveness on the MULTI-key path: MISSING deps route
    through resolve_general's iterative branch inside shard_map; carried
    commands commit once the quorum recovers (the comment in
    mesh_step.py's resolver dispatch, proven rather than asserted)."""
    num_replicas = mesh.shape["replica"] * 2  # n=4: write quorum 3
    batch = mesh.shape["batch"] * 4
    state = mesh_step.init_state(
        mesh, num_replicas, key_buckets=16, pending_capacity=2 * batch,
        key_width=2,
    )
    kc = np.array(state.key_clock)
    kc[0, 3] = 7  # replica 0 alone saw a prior commit on key 3: slow path
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding),
        next_gid=jnp.int32(100),
    )

    degraded = mesh_step.jit_protocol_step(mesh, live_replicas=2)
    # every command touches key 3 (the diverging one) plus a second key
    keys = np.stack(
        [[3, 4 + (i % 4)] for i in range(batch)]
    ).astype(np.int32)
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out1 = degraded(state, jnp.asarray(keys), src, seq)
    assert not np.asarray(out1.resolved).any(), "no write quorum -> no commit"
    assert int(out1.pending) == batch

    healthy = mesh_step.jit_protocol_step(mesh)
    keys2 = np.stack(
        [[8 + (i % 4), 12 + (i % 3)] for i in range(batch)]
    ).astype(np.int32)
    seq2 = jnp.arange(batch, 2 * batch, dtype=jnp.int32)
    state, out2 = healthy(state, jnp.asarray(keys2), src, seq2)

    gids = np.asarray(out2.gids)
    resolved = np.asarray(out2.resolved)
    carried = (gids >= 100) & (gids < 100 + batch)
    assert carried.sum() == batch
    assert resolved[carried].all(), "carried multi-key commands must commit"
    assert resolved[gids >= 0].all()
    assert int(out2.pending) == 0
    assert state.frontier.tolist() == [2 * batch] * num_replicas


def test_pending_commands_commit_after_quorum_recovers(mesh):
    """The VERDICT r2 weak-#4 liveness scenario: a quorum-failed round's
    commands carry in the device-resident pending buffer and commit in a
    later round once enough replicas are live again."""
    num_replicas = mesh.shape["replica"] * 2  # n=4: write quorum 3
    batch = mesh.shape["batch"] * 4
    state = mesh_step.init_state(
        mesh, num_replicas, key_buckets=16, pending_capacity=2 * batch
    )
    kc = np.array(state.key_clock)
    kc[0, 3] = 7  # replica 0 alone saw a prior commit on key 3: slow path
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding),
        next_gid=jnp.int32(100),
    )

    degraded = mesh_step.jit_protocol_step(mesh, live_replicas=2)
    key = jnp.full((batch,), 3, dtype=jnp.int32)
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out1 = degraded(state, key, src, seq)
    assert not np.asarray(out1.resolved).any()
    assert int(out1.pending) == batch

    # quorum recovers; a fresh (disjoint-key) batch arrives
    healthy = mesh_step.jit_protocol_step(mesh)
    key2 = jnp.full((batch,), 9, dtype=jnp.int32)
    seq2 = jnp.arange(batch, 2 * batch, dtype=jnp.int32)
    state, out2 = healthy(state, key2, src, seq2)

    gids = np.asarray(out2.gids)
    resolved = np.asarray(out2.resolved)
    carried = (gids >= 100) & (gids < 100 + batch)
    assert carried.sum() == batch, "round-1 commands must be in the working set"
    assert resolved[carried].all(), "carried commands commit after recovery"
    assert resolved[gids >= 0].all()
    assert int(out2.pending) == 0
    # every replica executed both rounds
    assert state.frontier.tolist() == [2 * batch] * num_replicas


# --- Newt timestamp round on the mesh ---


def _newt_setup(mesh, f=1, key_buckets=64, live_replicas=None, pending=64):
    num_replicas = 2 * mesh.shape[mesh_step.REPLICA_AXIS]
    batch = 8 * mesh.shape[mesh_step.BATCH_AXIS]
    state = mesh_step.init_newt_state(
        mesh, num_replicas, key_buckets=key_buckets, pending_capacity=pending
    )
    step = mesh_step.jit_newt_step(mesh, f=f, live_replicas=live_replicas)
    return num_replicas, batch, state, step


def test_newt_step_commits_and_stabilizes(mesh):
    """A healthy round commits everything on the fast path (identical
    replica clocks -> every quorum member reports the same max) and the
    whole batch is stable-ordered by (clock, dot) per key."""
    num_replicas, batch, state, step = _newt_setup(mesh)
    rng = np.random.default_rng(0)
    key = jnp.asarray(rng.integers(0, 4, size=batch), jnp.int32)
    src = jnp.asarray(rng.integers(1, num_replicas + 1, size=batch), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, key, src, seq)
    executed = np.asarray(out.executed)
    assert executed.sum() == batch
    assert np.asarray(out.fast_path).sum() == batch
    assert int(out.slow_paths) == 0
    assert int(out.pending) == 0
    # (clock, dot)-sorted execution, per-key clocks strictly increasing
    order = np.asarray(out.order)
    clock = np.asarray(out.clock)
    pend_cap = state.pend_key.shape[0]
    keys_w = np.concatenate([np.full(pend_cap, -1, np.int32), np.asarray(key)])
    last = {}
    for w in order:
        if not executed[w]:
            continue
        k = int(keys_w[w])
        assert last.get(k, 0) < clock[w]
        last[k] = int(clock[w])


def test_newt_clocks_continue_across_rounds(mesh):
    """Round 2 proposals continue above round 1's committed clocks per
    key (the device key-clock table carries)."""
    num_replicas, batch, state, step = _newt_setup(mesh)
    key = jnp.asarray(np.zeros(batch), jnp.int32)  # one hot key
    src = jnp.asarray(np.ones(batch), jnp.int32)
    state, out1 = step(state, key, src, jnp.arange(batch, dtype=jnp.int32))
    state, out2 = step(
        state, key, src, jnp.arange(batch, 2 * batch, dtype=jnp.int32)
    )
    c1 = np.asarray(out1.clock)[np.asarray(out1.executed)]
    c2 = np.asarray(out2.clock)[np.asarray(out2.executed)]
    assert len(c1) == len(c2) == batch
    assert c2.min() > c1.max()


@pytest.mark.slow
def test_newt_degraded_quorum_carries_pending(mesh):
    """With fewer live replicas than the write quorum, slow-path commands
    cannot commit; they carry in the pending buffer and commit + execute
    once the quorum recovers."""
    num_replicas, batch, state, step = _newt_setup(mesh)
    key = jnp.asarray(np.zeros(batch), jnp.int32)
    src = jnp.asarray(np.ones(batch), jnp.int32)
    # stagger replica 0's key clock so the first proposal's max is unique
    # to one replica (max_count < f is impossible at f=1; force the slow
    # path by staggering so that the max is reported once... at f=1 a
    # single report satisfies the fast path, so instead degrade below the
    # write quorum AND the fast path by staggering every quorum member
    # differently via distinct priors)
    kc = np.array(state.key_clock)
    for r in range(num_replicas):
        kc[r, 0] = r * 10  # all replicas disagree on the hot key's clock
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding)
    )
    degraded = mesh_step.jit_newt_step(mesh, f=1, live_replicas=0)
    state, out = degraded(state, key, src, jnp.arange(batch, dtype=jnp.int32))
    # fast path needs the max reported >= f times: the max proposal comes
    # only from the staggered top replica if it is in the fast quorum...
    # at f=1 one report suffices, so fast commits still happen; what must
    # NOT happen is slow-path commits with zero live replicas
    committed = np.asarray(out.committed)
    fast = np.asarray(out.fast_path)
    assert (committed == fast).all(), "slow path must not commit with no live acks"
    carried = int(out.pending)
    # fast-path commits with no live replicas cannot stabilize either
    # (frontiers never advance), so they carry too
    assert carried == batch - np.asarray(out.executed).sum()

    # recovery: everything (carried + nothing new) commits and executes
    recovered = mesh_step.jit_newt_step(mesh, f=1)
    empty = jnp.full((batch,), mesh_step.KEY_PAD, jnp.int32)
    zeros = jnp.zeros((batch,), jnp.int32)
    state, out2 = recovered(state, empty, zeros, zeros)
    assert int(out2.pending) == 0
    assert np.asarray(out2.executed).sum() == carried


def test_newt_stability_with_lagging_minority(mesh):
    """With a minority of replicas dead, commits still stabilize: the
    (n - threshold)-th smallest frontier ignores the laggards (the Newt
    stability condition, mod.rs:247-270)."""
    num_replicas, batch, state, step = _newt_setup(mesh)
    f = 1
    live = num_replicas - f  # one dead replica
    partial = mesh_step.jit_newt_step(mesh, f=f, live_replicas=live)
    key = jnp.asarray(np.arange(batch) % 3, jnp.int32)
    src = jnp.asarray(np.ones(batch), jnp.int32)
    state, out = partial(state, key, src, jnp.arange(batch, dtype=jnp.int32))
    assert np.asarray(out.executed).sum() == batch, (
        "a lagging minority must not block timestamp stability"
    )


# --- leader-based (FPaxos/MultiPaxos) slot round ---


def test_paxos_step_slot_order_and_recovery(mesh):
    """The third consensus class on the mesh: a healthy round commits and
    executes the whole batch in contiguous slot order; with fewer live
    acceptors than f+1 nothing commits and rows carry with their slots
    (MultiPaxos slot stickiness); recovery commits the SAME slots and the
    frontier resumes contiguously."""
    batch = 8 * mesh.shape[mesh_step.BATCH_AXIS]
    state = mesh_step.init_paxos_state(mesh, pending_capacity=2 * batch)
    step = mesh_step.jit_paxos_step(mesh, f=1)
    valid = jnp.ones(batch, bool)
    src = jnp.ones(batch, jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)

    state, out = step(state, valid, src, seq)
    executed = np.asarray(out.executed)
    slots = np.asarray(out.slot)
    assert executed.sum() == batch
    assert sorted(slots[executed].tolist()) == list(range(batch))
    assert int(state.exec_frontier) == batch

    degraded = mesh_step.jit_paxos_step(mesh, f=1, live_replicas=1)
    state, out2 = degraded(state, valid, src, seq + batch)
    assert np.asarray(out2.executed).sum() == 0
    assert int(out2.pending) == batch

    state, out3 = step(state, jnp.zeros(batch, bool), src, seq)
    ex3 = np.asarray(out3.executed)
    slots3 = np.asarray(out3.slot)
    assert ex3.sum() == batch
    assert sorted(slots3[ex3].tolist()) == list(range(batch, 2 * batch))
    assert int(state.exec_frontier) == 2 * batch


def test_paxos_overflow_reclaims_slots(mesh):
    """Pending overflow must not wedge the slot log: dropped rows are the
    HIGHEST slots and the slot counter rolls back over them, so later
    rounds re-fill a dense log and the contiguous frontier never freezes
    (the livelock a naive drop creates)."""
    batch = 8 * mesh.shape[mesh_step.BATCH_AXIS]
    cap = batch // 2
    state = mesh_step.init_paxos_state(mesh, pending_capacity=cap)
    degraded = mesh_step.jit_paxos_step(mesh, f=1, live_replicas=1)
    valid = jnp.ones(batch, bool)
    src = jnp.ones(batch, jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out1 = degraded(state, valid, src, seq)
    assert int(out1.pend_dropped) == batch - cap
    # the dropped (highest) slots were reclaimed
    assert int(state.next_slot) == cap

    # recovery: the carried low slots commit; new commands take the
    # reclaimed slot numbers — the log stays dense and fully executes
    step = mesh_step.jit_paxos_step(mesh, f=1)
    state, out2 = step(state, valid, src, seq + batch)
    assert np.asarray(out2.executed).sum() == cap + batch
    assert int(state.exec_frontier) == cap + batch
    assert int(state.next_slot) == cap + batch


def test_newt_multikey_round(mesh):
    """Multi-key commands through the Newt mesh round: every command
    commits and executes once its clock is stable on ALL its keys, per-key
    (clock, dot) order is monotone within the round, and round-2 clocks on
    the same keys strictly dominate round 1's commits."""
    num_replicas = 2 * mesh.shape[mesh_step.REPLICA_AXIS]
    batch = 8 * mesh.shape[mesh_step.BATCH_AXIS]
    state = mesh_step.init_newt_state(
        mesh, num_replicas, key_buckets=64, pending_capacity=64, key_width=2
    )
    step = mesh_step.jit_newt_step(mesh, f=1)
    rng = np.random.default_rng(3)
    keys = jnp.asarray(
        np.stack([rng.choice(6, size=2, replace=False) for _ in range(batch)]),
        dtype=jnp.int32,
    )
    src = jnp.asarray(rng.integers(1, num_replicas + 1, size=batch), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, keys, src, seq)
    executed = np.asarray(out.executed)
    clock = np.asarray(out.clock)
    order = np.asarray(out.order)
    assert executed.sum() == batch
    # per-key clocks non-decreasing along the execution order
    pend_cap = state.pend_key.shape[0]
    keys_np = np.asarray(keys)
    last = {}
    for w in order:
        if not executed[w]:
            continue
        for k in keys_np[w - pend_cap]:
            assert last.get(int(k), -1) <= clock[w]
            last[int(k)] = int(clock[w])
    r1_max = clock[executed].max()

    # round 2 on the same key space strictly dominates per key
    state, out2 = step(state, keys, src, seq + batch)
    c2 = np.asarray(out2.clock)
    e2 = np.asarray(out2.executed)
    assert e2.sum() == batch
    for w in np.nonzero(e2)[0]:
        for k in keys_np[w - pend_cap]:
            assert c2[w] > last[int(k)]  # strict per-key domination
    assert c2[e2].max() > r1_max


@pytest.mark.slow
def test_newt_multikey_holdback_preserves_per_key_order(mesh):
    """Regression (r4 review): a multi-key command stable on key A but
    blocked by key B must hold back higher-clocked commands on A, or A's
    (clock, dot) execution order breaks across rounds.  Staged state: key
    0's stability watermark is far ahead (1000) while key 1 lags at 0; a
    carried committed command D{0,1} at clock 5 stays blocked (minority
    of live replicas, so its votes cannot stabilize key 1); a fresh
    command F{0} commits at clock 101 <= stable(key 0) — without the
    holdback it would execute past D on key 0."""
    num_replicas = 2 * mesh.shape[mesh_step.REPLICA_AXIS]
    batch = 8 * mesh.shape[mesh_step.BATCH_AXIS]
    state = mesh_step.init_newt_state(
        mesh, num_replicas, key_buckets=8, pending_capacity=8, key_width=2
    )
    vf = np.array(state.vote_frontier)
    vf[:, 0] = 1000  # key 0 pre-stable far ahead
    kc = np.array(state.key_clock)
    kc[:, 0] = 100
    pend_key = np.full((8, 2), mesh_step.KEY_PAD, np.int32)
    pend_key[0] = [0, 1]  # D{0,1}, committed at clock 5
    pend = lambda a: jax.device_put(jnp.asarray(a, dtype=jnp.int32))
    state = state._replace(
        vote_frontier=jax.device_put(jnp.asarray(vf), state.vote_frontier.sharding),
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding),
        pend_key=pend(pend_key),
        pend_src=pend([1] + [-1] * 7),
        pend_seq=pend([1] + [-1] * 7),
        pend_clock=pend([5] + [-1] * 7),
    )
    # minority-live round: D's carried votes cannot stabilize key 1
    step1 = mesh_step.jit_newt_step(mesh, f=1, live_replicas=1)
    keys = np.full((batch, 2), mesh_step.KEY_PAD, np.int32)
    keys[0, 0] = 0  # F{0}
    state, out = step1(
        state,
        jnp.asarray(keys),
        jnp.asarray(np.r_[2, np.zeros(batch - 1)].astype(np.int32)),
        jnp.asarray(np.r_[1, np.zeros(batch - 1)].astype(np.int32)),
    )
    executed = np.asarray(out.executed)
    committed = np.asarray(out.committed)
    clock = np.asarray(out.clock)
    assert committed[8] and clock[8] > 100, "F must commit above key 0's clock"
    assert committed[0] and not executed[0], "D stays blocked by key 1"
    assert not executed[8], (
        "F executed past the lower-clocked blocked command D on key 0"
    )
    assert int(out.pending) == 2

    # full-live round: D's votes stabilize key 1; D then F execute in
    # (clock, dot) order
    step2 = mesh_step.jit_newt_step(mesh, f=1)
    empty = jnp.full((batch, 2), mesh_step.KEY_PAD, jnp.int32)
    zeros = jnp.zeros((batch,), jnp.int32)
    state, out2 = step2(state, empty, zeros, zeros)
    ex2 = np.asarray(out2.executed)
    clock2 = np.asarray(out2.clock)
    order2 = np.asarray(out2.order)
    assert ex2.sum() == 2
    ex_rows = [w for w in order2 if ex2[w]]
    assert clock2[ex_rows[0]] < clock2[ex_rows[1]], "D must execute before F"


# ---------------------------------------------------------------------------
# partial replication on ONE mesh: sharded key axis + per-shard quorums
# ---------------------------------------------------------------------------


def test_sharded_step_cross_shard_dependencies(mesh):
    """shard_count=2 on one mesh (6 replica rows = 2 shards x 3): a
    multi-shard command orders after its dependency chains on BOTH
    shards' buckets in one round — the mesh-native form of the
    cross-shard dep requests of fantoch_ps/src/executor/graph/
    mod.rs:279-408 — and each shard's replicas learn only their own
    buckets' key state."""
    m = mesh_step.make_mesh(num_replicas=6)
    state = mesh_step.init_state(m, 6, key_buckets=64, key_width=2)
    step = mesh_step.jit_protocol_step(m, shard_count=2)
    KP = mesh_step.KEY_PAD

    # bucket 4 -> shard 0, bucket 5 -> shard 1 (b % 2)
    # rows: two on each shard's bucket, then a multi-shard row, then one
    # more on each bucket — the multi row must land between them on BOTH
    key = jnp.asarray(
        [[4, KP], [5, KP], [4, KP], [5, KP], [4, 5], [4, KP], [5, KP]]
        + [[KP, KP]] * 1,
        dtype=jnp.int32,
    )
    batch = key.shape[0]
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, key, src, seq)
    gids = np.asarray(out.gids)
    resolved = np.asarray(out.resolved)
    order = np.asarray(out.order)
    valid = gids >= 0
    assert resolved[valid].all(), "healthy sharded round must resolve all"

    # positions in the execution order (working rows: pend_cap offset)
    pend_cap = state.pend_gid.shape[0]
    pos = {int(gids[w]): i for i, w in enumerate(order) if gids[w] >= 0}
    g = lambda i: i  # gid == batch index here (fresh state, next_gid=0)
    multi = pos[g(4)]
    assert pos[g(0)] < pos[g(2)] < multi < pos[g(5)]  # shard-0 chain
    assert pos[g(1)] < pos[g(3)] < multi < pos[g(6)]  # shard-1 chain

    # ownership: shard-0 rows (0..2) never learned bucket 5, shard-1
    # rows (3..5) never learned bucket 4
    kc = np.asarray(state.key_clock)
    assert (kc[0:3, 5] == -1).all() and (kc[3:6, 4] == -1).all()
    assert (kc[0:3, 4] >= 0).all() and (kc[3:6, 5] >= 0).all()


@pytest.mark.slow
def test_sharded_step_degraded_shard_blocks_multi_shard(mesh):
    """A dead majority in ONE shard blocks that shard's slow-path
    commands AND any multi-shard command touching it, while the healthy
    shard keeps committing; recovery commits the carried rows."""
    m = mesh_step.make_mesh(num_replicas=6)
    state = mesh_step.init_state(m, 6, key_buckets=64, key_width=2)
    healthy = mesh_step.jit_protocol_step(m, shard_count=2)
    KP = mesh_step.KEY_PAD

    # round 1 (healthy): seed both buckets so the clocks hold real gids
    key1 = jnp.asarray([[4, KP], [5, KP]], dtype=jnp.int32)
    state, out1 = step_pad(healthy, state, key1)
    assert np.asarray(out1.resolved)[np.asarray(out1.gids) >= 0].all()

    # stagger shard 1's member-0 view of bucket 5 (rows 3..5 are shard 1;
    # fq = members 0,1 = rows 3,4): fast path must miss there
    kc = np.array(state.key_clock)
    kc[3, 5] = 0  # an older *executed* gid (gid 0 was row 0 of round 1)
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding)
    )

    # round 2 under a dead shard-1 majority (rows 0..3 live = shard 0
    # full + shard 1 member 0 only): shard-0 command commits; the
    # bucket-5 command and the multi-shard command carry
    degraded = mesh_step.jit_protocol_step(m, shard_count=2, live_replicas=4)
    key2 = jnp.asarray([[4, KP], [5, KP], [4, 5]], dtype=jnp.int32)
    state, out2 = step_pad(degraded, state, key2, seq0=10)
    gids2 = np.asarray(out2.gids)
    res2 = np.asarray(out2.resolved)
    rows2 = res2[gids2 >= 0]  # batch rows in order (pads commit as no-ops)
    assert rows2[0], "the shard-0 command must commit"
    assert not rows2[1] and not rows2[2], (
        "the bucket-5 and multi-shard commands must carry"
    )
    assert int(out2.pending) == 2

    # round 3 recovered: carried rows commit and resolve
    state, out3 = step_pad(healthy, state, None, batch=3)
    gids3 = np.asarray(out3.gids)
    assert np.asarray(out3.resolved)[gids3 >= 0].all()
    assert int(out3.pending) == 0


def step_pad(step, state, key, seq0=0, batch=None):
    """Run one step, padding the key matrix to a mesh-divisible batch."""
    KP = mesh_step.KEY_PAD
    b = 8  # divisible by any batch-axis factor of 8 devices
    full = jnp.full((b, state.pend_key.shape[1]), KP, dtype=jnp.int32)
    if key is not None:
        full = full.at[: key.shape[0]].set(key)
    src = jnp.ones((b,), jnp.int32)
    seq = jnp.arange(seq0, seq0 + b, dtype=jnp.int32)
    return step(state, full, src, seq)


def test_sharded_newt_cross_shard_clocks(mesh):
    """shard_count=2 on the Newt round (6 replica rows = 2 shards x 3):
    per-key clocks advance per shard, a multi-shard command's commit
    clock is the max over its shards' clocks (the MShardCommit
    aggregation), per-key execution order is (clock, dot) on each
    shard's bucket, and replicas never learn foreign buckets."""
    m = mesh_step.make_mesh(num_replicas=6)
    state = mesh_step.init_newt_state(
        m, 6, key_buckets=64, pending_capacity=16, key_width=2
    )
    step = mesh_step.jit_newt_step(m, f=1, shard_count=2)
    KP = mesh_step.KEY_PAD

    # bucket 4 -> shard 0 (rows 0..2), bucket 5 -> shard 1 (rows 3..5)
    key = jnp.asarray(
        [[4, KP], [5, KP], [4, KP], [5, KP], [4, 5], [4, KP], [5, KP]]
        + [[KP, KP]],
        dtype=jnp.int32,
    )
    batch = key.shape[0]
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, key, src, seq)
    executed = np.asarray(out.executed)
    clock = np.asarray(out.clock)
    pend_cap = state.pend_key.shape[0]
    w = lambda i: pend_cap + i  # fresh state: working row of batch row i
    real = [w(i) for i in range(7)]
    assert executed[real].all(), "healthy sharded Newt round executes all"
    assert np.asarray(out.fast_path)[real].all()
    assert int(out.slow_paths) == 0

    # per-key consecutive clocks in batch order; the multi-shard row's
    # clock is the max of its two shard-local assignments
    assert clock[w(0)] < clock[w(2)] < clock[w(4)] < clock[w(5)]  # bucket 4
    assert clock[w(1)] < clock[w(3)] < clock[w(4)] < clock[w(6)]  # bucket 5
    assert clock[w(4)] == max(clock[w(2)], clock[w(3)]) + 1

    # ownership: shard-0 rows never learned bucket 5 and vice versa
    kc = np.asarray(state.key_clock)
    vf = np.asarray(state.vote_frontier)
    assert (kc[0:3, 5] == 0).all() and (kc[3:6, 4] == 0).all()
    assert (vf[0:3, 5] == 0).all() and (vf[3:6, 4] == 0).all()
    assert (kc[0:3, 4] > 0).all() and (kc[3:6, 5] > 0).all()


@pytest.mark.slow
def test_sharded_newt_degraded_shard_blocks_stability(mesh):
    """A dead majority in shard 1 leaves its commits unstable (the
    per-shard frontier order statistic cannot advance), blocking its
    rows AND the multi-shard row, while shard 0 executes; recovery
    drains the carried rows in per-key clock order."""
    m = mesh_step.make_mesh(num_replicas=6)
    state = mesh_step.init_newt_state(
        m, 6, key_buckets=64, pending_capacity=16, key_width=2
    )
    KP = mesh_step.KEY_PAD
    # rows 0..3 live = all of shard 0 + shard 1 member 0 only: shard 1's
    # stability threshold (n - f = 2) cannot be met
    degraded = mesh_step.jit_newt_step(m, f=1, shard_count=2, live_replicas=4)
    key = jnp.asarray(
        [[4, KP], [5, KP], [4, KP], [5, KP], [4, 5], [KP, KP], [KP, KP],
         [KP, KP]],
        dtype=jnp.int32,
    )
    batch = key.shape[0]
    src = jnp.ones((batch,), jnp.int32)
    state, out = degraded(state, key, src, jnp.arange(batch, dtype=jnp.int32))
    executed = np.asarray(out.executed)
    pend_cap = state.pend_key.shape[0]
    w = lambda i: pend_cap + i
    assert executed[[w(0), w(2)]].all(), "shard-0 rows execute"
    assert not executed[[w(1), w(3), w(4)]].any(), (
        "shard-1 and multi-shard rows must wait for shard-1 stability"
    )
    assert int(out.pending) == 3

    # recovery: carried rows stabilize and drain
    healthy = mesh_step.jit_newt_step(m, f=1, shard_count=2)
    empty = jnp.full((batch, 2), KP, jnp.int32)
    zeros = jnp.zeros((batch,), jnp.int32)
    state, out2 = healthy(state, empty, zeros, zeros)
    assert int(out2.pending) == 0
    assert np.asarray(out2.executed).sum() == 3
    # carried per-key order: bucket-5 rows drain in their committed
    # (clock, dot) order
    order2 = np.asarray(out2.order)
    ex2 = np.asarray(out2.executed)
    clocks2 = np.asarray(out2.clock)
    drained = [int(clocks2[i]) for i in order2 if ex2[i]]
    assert drained == sorted(drained)


def test_newt_tiny_quorums_on_mesh(mesh):
    """newt_tiny_quorums shrinks the fast quorum to f+1 (newt.rs:90-100):
    a replica OUTSIDE the tiny quorum with a divergent key clock must not
    influence the commit clock, while the regular quorum consults it."""
    m = mesh_step.make_mesh(num_replicas=4)

    def run(tiny):
        state = mesh_step.init_newt_state(
            m, 4, key_buckets=8, pending_capacity=8
        )
        kc = np.array(state.key_clock)
        kc[2, 0] = 50  # replica 2: inside fq=3 (regular), outside fq=2 (tiny)
        state = state._replace(
            key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding)
        )
        step = mesh_step.jit_newt_step(m, f=1, tiny_quorums=tiny)
        key = jnp.zeros((8,), jnp.int32).at[1:].set(mesh_step.KEY_PAD)
        src = jnp.ones((8,), jnp.int32)
        state, out = step(state, key, src, jnp.arange(8, dtype=jnp.int32))
        w = state.pend_key.shape[0]
        assert bool(np.asarray(out.executed)[w])
        return int(np.asarray(out.clock)[w])

    assert run(tiny=True) == 1  # rows 0,1 agree at clock 1
    assert run(tiny=False) == 51  # row 2's stale view raises the max


@pytest.mark.slow
def test_newt_multikey_fast_path_is_row_level(mesh):
    """Unsharded multi-key fast-path regression (review finding): the
    count-of-max must aggregate at ROW level per shard, not per key slot.
    n=5, f=2, KW=2: quorum members propose per-slot clocks (3,5), (5,3),
    (1,1), (1,1) — each slot's max 5 is reported once, but the ROW max 5
    is reported twice >= f, so the command must take the fast path at
    clock 5 (newt.rs:527-546 counts reports of the single aggregated
    commit clock)."""
    m = mesh_step.make_mesh(num_replicas=5)
    state = mesh_step.init_newt_state(
        m, 5, key_buckets=8, pending_capacity=8, key_width=2
    )
    kc = np.array(state.key_clock)
    kc[0, 0], kc[0, 1] = 2, 4  # replica 0: a=2, b=4 -> proposes (3, 5)
    kc[1, 0], kc[1, 1] = 4, 2  # replica 1: a=4, b=2 -> proposes (5, 3)
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding)
    )
    step = mesh_step.jit_newt_step(m, f=2)
    KP = mesh_step.KEY_PAD
    key = jnp.asarray([[0, 1]] + [[KP, KP]] * 7, dtype=jnp.int32)
    src = jnp.ones((8,), jnp.int32)
    seq = jnp.arange(8, dtype=jnp.int32)
    state, out = step(state, key, src, seq)
    w = state.pend_key.shape[0]  # working row of batch row 0
    assert bool(np.asarray(out.fast_path)[w]), (
        "row-level max reported >= f times must take the fast path"
    )
    assert int(np.asarray(out.clock)[w]) == 5
    assert int(out.slow_paths) == 0
    assert bool(np.asarray(out.executed)[w])


# ---------------------------------------------------------------------------
# Caesar on the mesh: the fourth consensus shape
# ---------------------------------------------------------------------------


def test_caesar_step_timestamp_order(mesh):
    """A healthy Caesar round commits the whole batch on the fast path
    (consistent clock views) and executes conflicts in (clock, dot)
    order; the clock index carries across rounds."""
    state = mesh_step.init_caesar_state(
        mesh, 4, key_buckets=64, pending_capacity=16
    )
    step = mesh_step.jit_caesar_step(mesh, num_replicas=4)
    batch = 8 * mesh.shape[mesh_step.BATCH_AXIS]
    key = jnp.asarray([5] * batch, dtype=jnp.int32)  # one hot bucket
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = step(state, key, src, seq)
    executed = np.asarray(out.executed)
    clock = np.asarray(out.clock)
    order = np.asarray(out.order)
    valid = clock >= 0
    assert executed[valid].all(), "healthy round executes everything"
    assert bool(np.asarray(out.fast_path)[valid].all())
    # within-round same-bucket commands take consecutive, unique clocks,
    # executed in clock order
    ex_rows = [w for w in order.tolist() if executed[w]]
    ex_clocks = clock[ex_rows]
    assert sorted(set(ex_clocks.tolist())) == ex_clocks.tolist()
    # next round proposes above the carried ceiling
    state, out2 = step(state, key[:batch], src, seq + batch)
    clock2 = np.asarray(out2.clock)
    assert clock2[clock2 >= 0].min() > ex_clocks.max()


def test_caesar_step_degraded_wait_and_recovery(mesh):
    """Divergent clock views force the retry (slow) path; with fewer
    live replicas than the write quorum the retry cannot commit and the
    command carries — blocking later commits on its bucket (the wait
    condition) — and a recovered round commits and executes everything
    in timestamp order."""
    state = mesh_step.init_caesar_state(
        mesh, 4, key_buckets=64, pending_capacity=16
    )
    healthy = mesh_step.jit_caesar_step(mesh, num_replicas=4)
    batch = 8 * mesh.shape[mesh_step.BATCH_AXIS]
    KP = mesh_step.KEY_PAD

    # round 1 healthy on bucket 7: seeds the clock index
    key1 = jnp.full((batch,), 7, dtype=jnp.int32)
    src = jnp.ones((batch,), jnp.int32)
    state, out1 = healthy(state, key1, src, jnp.arange(batch, dtype=jnp.int32))
    assert np.asarray(out1.executed)[np.asarray(out1.clock) >= 0].all()

    # stagger replica 0's bucket-7 ceiling: the next proposal diverges
    # across the fast quorum -> retry path; live=1 < write quorum (3) ->
    # uncommitted carry
    kc = np.array(state.key_clock)
    kc[0, 7] += 7
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding)
    )
    degraded = mesh_step.jit_caesar_step(mesh, num_replicas=4, live_replicas=1)
    key2 = jnp.full((batch,), KP, dtype=jnp.int32)
    key2 = key2.at[0].set(7).at[1].set(7)
    state, out2 = degraded(
        state, key2, src, jnp.arange(batch, 2 * batch, dtype=jnp.int32)
    )
    committed2 = np.asarray(out2.committed)
    # working rows: pend_cap offset is 16
    w0, w1 = 16, 17
    assert not committed2[w0] and not committed2[w1]
    assert int(out2.pending) == 2
    assert int(out2.slow_paths) >= 2

    # recovered round: the carried commands commit via retry and execute
    state, out3 = healthy(
        state, jnp.full((batch,), KP, dtype=jnp.int32), src,
        jnp.arange(2 * batch, 3 * batch, dtype=jnp.int32),
    )
    executed3 = np.asarray(out3.executed)
    clock3 = np.asarray(out3.clock)
    assert executed3[:2].all(), "carried rows must execute after recovery"
    assert int(out3.pending) == 0
    # per-bucket timestamp order: the two carried rows' clocks are unique
    assert clock3[0] != clock3[1]


@pytest.mark.slow
def test_caesar_wait_gate_transitive_holdback(mesh):
    """A committed multi-key row held behind an uncommitted lower-clock
    conflict on one bucket must transitively hold back higher-clock rows
    on its OTHER buckets — commitment is not clock-monotone per bucket
    in Caesar, so the gate is a fixpoint (review-caught: the one-pass
    gate let X(22) execute before M(21) on their shared bucket)."""
    state = mesh_step.init_caesar_state(
        mesh, 4, key_buckets=64, pending_capacity=16, key_width=2
    )
    KP = mesh_step.KEY_PAD
    kc = np.array(state.key_clock)
    kc[:, 4] = 5
    kc[0, 4] = 10  # divergent views on bucket 4
    kc[:, 5] = 20
    state = state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), state.key_clock.sharding)
    )
    degraded = mesh_step.jit_caesar_step(mesh, num_replicas=4, live_replicas=1)
    batch = 8 * mesh.shape[mesh_step.BATCH_AXIS]
    key = jnp.full((batch, 2), KP, dtype=jnp.int32)
    key = key.at[0, 0].set(4)                 # A: bucket 4 only
    key = key.at[1, 0].set(4).at[1, 1].set(5)  # M: buckets 4 and 5
    key = key.at[2, 0].set(5)                 # X: bucket 5 only
    src = jnp.ones((batch,), jnp.int32)
    seq = jnp.arange(batch, dtype=jnp.int32)
    state, out = degraded(state, key, src, seq)
    committed = np.asarray(out.committed)
    executed = np.asarray(out.executed)
    w0 = 16  # pend_cap offset
    A, M, X = w0, w0 + 1, w0 + 2
    assert not committed[A], "divergent views + no write quorum: A waits"
    assert committed[M] and committed[X], "M and X fast-commit"
    # the fixpoint gate: M is held by A on bucket 4, and X must be held
    # by M on bucket 5 — nothing executes
    assert not executed[M] and not executed[X]
    assert int(out.pending) == 3

    # recovery: A commits via retry above everything; per-bucket
    # timestamp order holds — M(21) before A and X on their buckets
    healthy = mesh_step.jit_caesar_step(mesh, num_replicas=4)
    state, out2 = healthy(
        state, jnp.full((batch, 2), KP, dtype=jnp.int32), src,
        jnp.arange(batch, 2 * batch, dtype=jnp.int32),
    )
    executed2 = np.asarray(out2.executed)
    clock2 = np.asarray(out2.clock)
    order2 = np.asarray(out2.order)
    assert executed2[:3].all(), "recovered round executes all three"
    pos = {w: i for i, w in enumerate(order2.tolist())}
    # M committed at 21 executes before X (22) and before A (retry > 21)
    m_slot = min(range(3), key=lambda w: clock2[w])
    assert clock2[m_slot] == 21
    assert all(pos[m_slot] < pos[w] for w in range(3) if w != m_slot)
