"""Oracle parity suite for the device-resident predecessors plane
(executor/pred_plane.DevicePredPlane) against the host PredecessorsGraph
twin, plus the both-planes-on-one-base regression rows for the extracted
DevicePlane (executor/device_plane.py) and the memoized watchdog walk.

The parity contract is the agreement contract conflicting commands care
about: identical executed set and identical per-key execution order,
across shuffled delivery, noop commits, recovery-adjusted clocks,
multi-feed residuals, capacity compaction, and snapshot/restore with the
single-re-upload invariant.
"""

import pickle
import random

import numpy as np
import pytest

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, Rifl
from fantoch_tpu.core.kvs import KVOp
from fantoch_tpu.executor.device_plane import DevicePlane, resolve_threshold
from fantoch_tpu.executor.pred import (
    PredArraysBuilder,
    PredecessorsExecutionInfo,
    PredecessorsExecutor,
    PredecessorsGraph,
    PredecessorsNoop,
)
from fantoch_tpu.executor.pred_plane import DevicePredPlane
from fantoch_tpu.executor.table_plane import ClockOverflowError, DeviceTablePlane
from fantoch_tpu.protocol.common.pred_clocks import Clock

SHARD = 0


def cmd(seq: int, keys) -> Command:
    return Command.from_keys(
        Rifl(9, seq), SHARD, {k: (KVOp.put(str(seq)),) for k in keys}
    )


def _plane_executor(**cfg) -> PredecessorsExecutor:
    return PredecessorsExecutor(
        1, SHARD,
        Config(3, 1, device_pred_plane=True,
               executor_monitor_execution_order=True, **cfg),
    )


def _host_executor(**cfg) -> PredecessorsExecutor:
    return PredecessorsExecutor(
        1, SHARD,
        Config(3, 1, executor_monitor_execution_order=True, **cfg),
    )


def _assert_parity(ex_plane, ex_host, expect_executed=None):
    got = sorted(r.rifl for r in ex_plane.to_clients_iter())
    want = sorted(r.rifl for r in ex_host.to_clients_iter())
    assert got == want
    if expect_executed is not None:
        assert len(want) == expect_executed
    mon_p, mon_h = ex_plane.monitor(), ex_host.monitor()
    assert set(mon_p.keys()) == set(mon_h.keys())
    for key in mon_p.keys():
        assert mon_p.get_order(key) == mon_h.get_order(key)


def _conflict_workload(rng, count=60, keys=("Ka", "Kb", "Kc")):
    per_key = {k: [] for k in keys}
    infos = []
    for i in range(count):
        src = rng.randrange(1, 4)
        dot = Dot(src, i + 1)
        ks = rng.sample(list(keys), rng.randrange(1, 3))
        deps = set()
        for k in ks:
            deps.update(per_key[k])
            per_key[k].append(dot)
        infos.append(
            PredecessorsExecutionInfo(dot, cmd(i + 1, ks), Clock(i + 1, src), deps)
        )
    return infos


def test_pred_plane_oracle_parity_multi_feed_residuals():
    """Bit-for-bit per-key execution order vs the host twin across
    shuffled delivery and batch boundaries that leave missing-blocked
    residues resident on device for several feeds."""
    rng = random.Random(5)
    for _trial in range(5):
        infos = _conflict_workload(rng)
        shuffled = infos[:]
        rng.shuffle(shuffled)
        batches = []
        at = 0
        while at < len(shuffled):
            size = rng.randrange(1, 9)
            batches.append(shuffled[at : at + size])
            at += size
        ex_p, ex_h = _plane_executor(), _host_executor()
        for batch in batches:
            ex_p.handle_batch(batch, None)
            for info in batch:
                ex_h.handle(info, None)
        total_keys = sum(i.cmd.key_count(SHARD) for i in infos)
        _assert_parity(ex_p, ex_h, expect_executed=total_keys)


def test_pred_plane_noop_and_recovery_adjusted_clock_parity():
    """Recovered noops resolve dependents in both phases, and a
    dependency whose consensus-decided clock ends up HIGHER than its
    dependent's (the recovery free-choice lift) stops blocking phase 2
    exactly like the host twin."""
    m = Dot(3, 7)  # recovered as a noop below
    a, b, c = Dot(1, 1), Dot(1, 2), Dot(2, 1)
    infos = [
        # a blocked on the never-payloaded m (phase 1)
        PredecessorsExecutionInfo(a, cmd(1, ["K"]), Clock(2, 1), {m}),
        # b blocked on a (lower clock), m, and the yet-uncommitted c
        PredecessorsExecutionInfo(b, cmd(2, ["K"]), Clock(4, 1), {a, m, c}),
    ]
    ex_p, ex_h = _plane_executor(), _host_executor()
    ex_p.handle_batch(infos, None)
    for info in infos:
        ex_h.handle(info, None)
    assert not list(ex_p.to_clients_iter()) and not list(ex_h.to_clients_iter())
    # c commits with a RECOVERY-LIFTED clock above b's: b does not wait
    # for it (phase 2 ignores higher-clock deps) even though b lists it
    late = PredecessorsExecutionInfo(c, cmd(3, ["K"]), Clock(9, 2), set())
    ex_p.handle_batch([late], None)
    ex_h.handle(late, None)
    # the noop unblocks everything
    ex_p.handle_batch([PredecessorsNoop(m)], None)
    ex_h.handle(PredecessorsNoop(m), None)
    _assert_parity(ex_p, ex_h, expect_executed=3)
    # executed clock covers the noop dot on both (drives Caesar GC)
    assert ex_p.executed(None).contains(3, 7)
    assert ex_h.executed(None).contains(3, 7)


def test_pred_plane_arrays_seam_matches_object_feed():
    """The column feed (PredArraysBuilder -> add_arrays, the Caesar
    commit seam) is behaviorally identical to the object feed."""
    rng = random.Random(11)
    infos = _conflict_workload(rng, count=40)
    builder = PredArraysBuilder()
    noop = Dot(3, 99)
    infos[10].deps.add(noop)  # a dep resolved only by the noop row below
    for info in infos[:20]:
        builder.add_commit(info.dot, info.cmd, info.clock, info.deps)
    first = builder.take()
    builder.add_noop(noop)
    for info in infos[20:]:
        builder.add_commit(info.dot, info.cmd, info.clock, info.deps)
    second = builder.take()
    assert builder.take() is None

    ex_arrays, ex_objects = _plane_executor(), _plane_executor()
    ex_arrays.handle_batch([first], None)
    ex_arrays.handle_batch([second], None)
    ex_objects.handle_batch(infos[:20], None)
    ex_objects.handle_batch([PredecessorsNoop(noop)] + infos[20:], None)
    _assert_parity(ex_arrays, ex_objects)


def test_pred_plane_snapshot_restore_single_reupload():
    """The restart seam: a pickled executor re-materializes its resident
    window from the host mirror on the FIRST dispatch after restore —
    exactly one counted re-upload — and pending residuals survive with
    bit-for-bit parity."""
    m = Dot(2, 1)
    a, b = Dot(1, 1), Dot(1, 2)
    ex = _plane_executor()
    ex.handle_batch(
        [
            PredecessorsExecutionInfo(a, cmd(1, ["K"]), Clock(2, 1), {m}),
            PredecessorsExecutionInfo(b, cmd(2, ["K"]), Clock(3, 1), {a, m}),
        ],
        None,
    )
    assert not list(ex.to_clients_iter())
    blob = ex.snapshot()
    restored = PredecessorsExecutor.restore(blob)
    plane = restored._graph
    assert isinstance(plane, DevicePredPlane)
    uploads = plane.resident_uploads
    # the missing dep commits: the restored window wakes the chain
    restored.handle_batch(
        [PredecessorsExecutionInfo(m, cmd(3, ["K"]), Clock(1, 2), set())], None
    )
    got = [r.rifl for r in restored.to_clients_iter()]
    assert got == [Rifl(9, 3), Rifl(9, 1), Rifl(9, 2)]
    assert plane.resident_uploads - uploads == 1, (
        "restore must cost exactly ONE re-upload"
    )
    # a second pickle round-trip with nothing pending still works
    again = PredecessorsExecutor.restore(restored.snapshot())
    assert again.executed(None).contains(1, 2)


def test_pred_plane_compaction_and_growth_preserve_blocked_rows():
    """Window exhaustion re-packs pending rows to the bottom (dep cells
    remapped, waiter cells following): a missing-blocked row must
    survive arbitrarily many compactions and execute when its dep
    finally commits."""
    ex = _plane_executor()
    plane = ex._graph
    plane._cap = 8
    for name in ("_slot_src", "_slot_seq", "_slot_start", "_slot_cseq",
                 "_slot_csrc"):
        setattr(plane, name, getattr(plane, name)[:8].copy())
    missing = Dot(3, 1)
    blocked = Dot(1, 100)
    ex.handle_batch(
        [PredecessorsExecutionInfo(blocked, cmd(100, ["B"]), Clock(200, 1), {missing})],
        None,
    )
    per = []
    for i in range(40):
        dot = Dot(1, i + 1)
        deps = set(per[-2:])
        per.append(dot)
        ex.handle_batch(
            [PredecessorsExecutionInfo(dot, cmd(i + 1, ["K"]), Clock(i + 1, 1), deps)],
            None,
        )
    assert sum(1 for _ in ex.to_clients_iter()) == 40
    assert plane.stats["compactions"] >= 2
    assert plane.pending_count == 1
    ex.handle_batch(
        [PredecessorsExecutionInfo(missing, cmd(101, ["B"]), Clock(150, 3), set())],
        None,
    )
    got = [r.rifl for r in ex.to_clients_iter()]
    assert got == [Rifl(9, 101), Rifl(9, 100)]


def test_pred_plane_wide_dep_sets_grow_width():
    """Dep fan-out beyond the resident width re-pads the window columns
    (a counted grow), preserving earlier state."""
    ex = _plane_executor()
    plane = ex._graph
    start_width = plane._width
    deps = set()
    infos = []
    for i in range(start_width + 3):
        dot = Dot(1, i + 1)
        infos.append(
            PredecessorsExecutionInfo(
                dot, cmd(i + 1, ["K"]), Clock(i + 1, 1), set(deps)
            )
        )
        deps.add(dot)
    ex.handle_batch(infos[: start_width], None)
    ex.handle_batch(infos[start_width:], None)  # widest row exceeds width
    assert plane._width > start_width
    assert sum(1 for _ in ex.to_clients_iter()) == len(infos)


def test_pred_plane_clock_overflow_rejected():
    ex = _plane_executor()
    with pytest.raises(ClockOverflowError):
        ex.handle_batch(
            [
                PredecessorsExecutionInfo(
                    Dot(1, 1), cmd(1, ["K"]), Clock((1 << 31) - 1, 1), set()
                )
            ],
            None,
        )


def test_pred_plane_watchdog_reports_missing_and_fails_bounded():
    """The liveness watchdog on the plane: the missing frontier surfaces
    for nudge_recovery below the bound, a typed StalledExecutionError
    fires past Config.executor_pending_fail_ms, and the exactly-once /
    no-pending-without-missing invariants hold."""
    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.errors import StalledExecutionError

    ex = _plane_executor(executor_pending_fail_ms=5000)
    ex.handle_batch(
        [
            PredecessorsExecutionInfo(
                Dot(2, 1), cmd(2, ["K"]), Clock(5, 2), {Dot(1, 1)}
            )
        ],
        SimTime(0),
    )
    assert ex.monitor_pending(SimTime(2000)) == {Dot(1, 1)}
    with pytest.raises(StalledExecutionError) as err:
        ex.monitor_pending(SimTime(6000))
    assert Dot(1, 1) in err.value.missing[Dot(2, 1)]


def test_pred_plane_duplicate_commit_trips_after_compaction():
    """Exactly-once must hold across compactions: a duplicate commit of
    a dot that executed BEFORE the last compaction (which clears the
    recent-executed probe set) still trips the loud assert, like the
    host twin's committed-clock assert — never a silent re-install and
    double execution."""
    ex = _plane_executor()
    plane = ex._graph
    plane._cap = 8
    for name in ("_slot_src", "_slot_seq", "_slot_start", "_slot_cseq",
                 "_slot_csrc"):
        setattr(plane, name, getattr(plane, name)[:8].copy())
    dup = Dot(1, 1)
    ex.handle_batch(
        [PredecessorsExecutionInfo(dup, cmd(1, ["K"]), Clock(1, 1), set())],
        None,
    )
    for i in range(2, 20):  # run the window through >= 1 compaction
        ex.handle_batch(
            [PredecessorsExecutionInfo(Dot(1, i), cmd(i, ["K"]), Clock(i, 1), set())],
            None,
        )
    assert plane.stats["compactions"] >= 1
    assert dup not in plane._exec_recent  # compaction cleared the probe set
    with pytest.raises(AssertionError, match="exactly once"):
        ex.handle_batch(
            [PredecessorsExecutionInfo(dup, cmd(1, ["K"]), Clock(99, 1), set())],
            None,
        )


def test_pred_plane_watchdog_nudges_only_overdue_missing():
    """The missing frontier also holds dots of healthy in-flight
    commits; the watchdog must only nudge dots missing PAST the pending
    threshold, or one stalled row would start recovery consensus against
    every live coordinator."""
    from fantoch_tpu.core.timing import SimTime

    ex = _plane_executor()
    old_missing, young_missing = Dot(3, 1), Dot(3, 2)
    ex.handle_batch(
        [PredecessorsExecutionInfo(Dot(1, 1), cmd(1, ["K"]), Clock(5, 1), {old_missing})],
        SimTime(0),
    )
    ex.handle_batch(
        [PredecessorsExecutionInfo(Dot(1, 2), cmd(2, ["J"]), Clock(6, 1), {young_missing})],
        SimTime(900),
    )
    # at t=1100: both rows' dots are in the frontier, but only the one
    # missing past the 1000ms threshold is actionable
    assert ex.monitor_pending(SimTime(1100)) == {old_missing}
    # once the young one matures it joins the nudge set
    assert ex.monitor_pending(SimTime(2000)) == {old_missing, young_missing}


def test_pred_plane_device_counters_seam():
    """The Executor.device_counters() seam (the table plane's contract):
    dispatch/occupancy/residual/kernel tallies present and sane, None
    when the plane is off."""
    ex = _plane_executor()
    infos = _conflict_workload(random.Random(3), count=20)
    ex.handle_batch(infos, None)
    counters = ex.device_counters()
    assert counters["pred_plane_dispatches"] == 1
    assert counters["pred_plane_new_rows"] == 20
    assert counters["pred_plane_update_capacity"] >= 20
    assert counters["pred_plane_resident_uploads"] == 1
    assert counters["pred_plane_kernel_ms"] > 0
    assert counters["pred_plane_slot_capacity"] == ex._graph._cap
    assert _host_executor().device_counters() is None
    # counters fold into the process-level snapshot like the table's
    from fantoch_tpu.observability.device import merge_counters

    folded = merge_counters({}, counters)
    folded = merge_counters(folded, counters)
    assert folded["pred_plane_dispatches"] == 2
    # capacity is a gauge: max-folded, never summed
    assert folded["pred_plane_slot_capacity"] == ex._graph._cap


def test_caesar_sim_with_device_pred_plane():
    """End-to-end Caesar over the sim with the plane + arrays commit
    seam on: same client histories as the host-executor runs (the
    sim_test harness checks per-key agreement across replicas)."""
    from harness import sim_test

    from fantoch_tpu.protocol import Caesar

    sim_test(
        Caesar,
        Config(
            n=3, f=1, caesar_wait_condition=True, gc_interval_ms=100,
            device_pred_plane=True,
        ),
    )


def test_caesar_set_commit_arrays_flushes_pending():
    """The runner hook: disabling the arrays seam flushes the
    accumulated column batch so no commit is lost (the Newt
    set_commit_arrays contract)."""
    from fantoch_tpu.protocol import Caesar
    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.protocol.caesar import MCommit, MPropose

    config = Config(
        n=3, f=1, gc_interval_ms=100, device_pred_plane=True,
    )
    caesar = Caesar(1, SHARD, config)
    assert caesar.discover([(pid, SHARD) for pid in range(1, 4)])[0]
    time = SimTime()
    dot = Dot(2, 1)
    caesar.handle(2, SHARD, MPropose(dot, cmd(1, ["K"]), Clock(1, 2)), time)
    list(caesar.to_processes_iter())
    caesar.handle(2, SHARD, MCommit(dot, Clock(1, 2), set()), time)
    assert len(caesar._commit_arrays) == 1
    caesar.set_commit_arrays(False)
    assert caesar._commit_arrays is None
    infos = list(caesar.to_executors_iter())
    assert len(infos) == 1, "the pending column batch must flush"
    ex = _plane_executor()
    ex.handle_batch(infos, time)
    assert [r.rifl for r in ex.to_clients_iter()] == [Rifl(9, 1)]


def test_run_caesar_localhost_through_pred_plane():
    """The serving path (ROADMAP item 4's remainder): a 3-process
    localhost TCP Caesar cluster whose executor path orders through the
    resident pred plane (process_runner -> PredArraysBuilder column
    drains -> PredecessorsExecutor -> DevicePredPlane), with
    cross-replica per-key agreement and the plane counters visible
    through the runtime's device-counter fold."""
    from test_run_localhost import run_cluster

    from fantoch_tpu.core.config import Config as _Config
    from fantoch_tpu.protocol import Caesar

    _slow, runtimes = run_cluster(
        Caesar,
        _Config(n=3, f=1, device_pred_plane=True),
        keys_per_command=1,
        return_runtimes=True,
    )
    for runtime in runtimes.values():
        counters = runtime._device_counters()
        assert counters["pred_plane_dispatches"] > 0
        assert (
            counters["pred_plane_resident_uploads"]
            <= 1
            + counters["pred_plane_compactions"]
            + counters["pred_plane_grows"]
        )


# ---------------------------------------------------------------------------
# both-planes-on-one-base (the DevicePlane extraction)
# ---------------------------------------------------------------------------


def test_both_planes_share_the_device_plane_base():
    """The ROADMAP item-5 extraction: the votes-table plane and the
    predecessors plane are the SAME machinery — one base owning buffer
    lifecycle, durability, and counters — not two hand-rolled copies."""
    assert issubclass(DeviceTablePlane, DevicePlane)
    assert issubclass(DevicePredPlane, DevicePlane)
    for klass in (DeviceTablePlane, DevicePredPlane):
        for member in (
            "_materialize", "_grow", "_upload", "_fetch_state",
            "__getstate__", "__setstate__", "_count_dispatch",
        ):
            # lifecycle methods resolve to the shared base implementation
            assert getattr(klass, member) is getattr(DevicePlane, member), (
                f"{klass.__name__}.{member} forked from the base"
            )


def test_table_plane_on_base_keeps_oracle_behavior():
    """A focused re-run of the table plane's core contract on the
    extracted base (the full oracle suite lives in test_table_plane.py):
    frontier math, residual re-feed, pickle round trip with the single
    re-upload."""
    plane = DeviceTablePlane(3, 2, key_buckets=4)
    k = plane.bucket("x")
    stable = plane.commit_votes(
        np.array([k, k], dtype=np.int64),
        np.array([1, 2], dtype=np.int64),
        np.array([1, 1], dtype=np.int64),
        np.array([3, 2], dtype=np.int64),
    )
    assert stable[k] == 2  # 2-of-3 threshold over frontiers (3, 2, 0)
    # beyond-gap run buffers as residual and re-feeds
    stable = plane.commit_votes(
        np.array([k], dtype=np.int64),
        np.array([3], dtype=np.int64),
        np.array([5], dtype=np.int64),
        np.array([6], dtype=np.int64),
    )
    assert plane.residual_count == 1 and stable[k] == 2
    blob = pickle.dumps(plane)
    restored = pickle.loads(blob)
    uploads = restored.resident_uploads
    stable = restored.commit_votes(
        np.array([k], dtype=np.int64),
        np.array([3], dtype=np.int64),
        np.array([1], dtype=np.int64),
        np.array([4], dtype=np.int64),
    )
    # the gap filled: the buffered 5..6 residual coalesces onto voter
    # 3's 1..4, frontiers (3, 2, 6) -> 2-of-3 stable clock 3
    assert restored.residual_count == 0 and stable[k] == 3
    assert restored.resident_uploads - uploads == 1


def test_resolve_threshold_precedence(monkeypatch):
    """The shared kernel-threshold switch: explicit beats env beats
    default (extracted from the table executor for every plane)."""
    monkeypatch.delenv("FANTOCH_TEST_THRESHOLD", raising=False)
    assert resolve_threshold(None, "FANTOCH_TEST_THRESHOLD", 7) == 7
    monkeypatch.setenv("FANTOCH_TEST_THRESHOLD", "11")
    assert resolve_threshold(None, "FANTOCH_TEST_THRESHOLD", 7) == 11
    assert resolve_threshold(13, "FANTOCH_TEST_THRESHOLD", 7) == 13


# ---------------------------------------------------------------------------
# the memoized watchdog walk (host twin)
# ---------------------------------------------------------------------------


def test_host_watchdog_memoizes_across_ticks():
    """monitor_pending's transitive-missing walk is computed once per
    commit-state generation: idle ticks reuse the memo (no re-walk), and
    any commit invalidates it — at 1M pending the per-tick re-walk was
    the recovery nudge's cost (ISSUE r13 small fix)."""
    from fantoch_tpu.core.timing import SimTime

    ex = _host_executor(executor_pending_fail_ms=None)
    graph = ex._graph
    assert isinstance(graph, PredecessorsGraph)
    missing = Dot(3, 1)
    per = []
    for i in range(10):
        dot = Dot(1, i + 1)
        deps = set(per[-1:]) | {missing}
        per.append(dot)
        ex.handle(
            PredecessorsExecutionInfo(
                dot, cmd(i + 1, ["K"]), Clock(i + 1, 1), deps
            ),
            SimTime(0),
        )
    # a healthy tick (nothing past the threshold yet) walks NOTHING:
    # the map is built lazily on the first long-pending vertex
    assert ex.monitor_pending(SimTime(100)) == set()
    assert graph._memo_gen != graph._gen, "no walk on a healthy tick"
    assert ex.monitor_pending(SimTime(2000)) == {missing}
    memo_gen = graph._memo_gen
    assert memo_gen == graph._gen
    # idle tick: same generation, memo reused (not recomputed)
    memo_before = graph._memo
    assert ex.monitor_pending(SimTime(3000)) == {missing}
    assert graph._memo is memo_before and graph._memo_gen == memo_gen
    # a commit invalidates the memo; with everything executed the next
    # tick has no long-pending vertex and again walks nothing
    ex.handle(
        PredecessorsExecutionInfo(missing, cmd(99, ["K"]), Clock(99, 3), set()),
        SimTime(3000),
    )
    assert graph._memo_gen != graph._gen
    assert ex.monitor_pending(SimTime(4000)) == set()
    assert sum(1 for _ in ex.to_clients_iter()) == 11


def test_host_watchdog_memo_matches_unmemoized_walk():
    """The memoized bottom-up pass computes the same transitive-missing
    sets as a reference per-vertex walk over a random pending graph."""
    rng = random.Random(7)
    ex = _host_executor()
    graph = ex._graph
    committed = []
    missing_pool = [Dot(3, s) for s in range(1, 6)]
    for i in range(60):
        dot = Dot(1, i + 1)
        deps = set(rng.sample(committed, min(len(committed), rng.randrange(0, 3))))
        if rng.random() < 0.4:
            deps.add(rng.choice(missing_pool))
        committed.append(dot)
        ex.handle(
            PredecessorsExecutionInfo(dot, cmd(i + 1, ["K"]), Clock(i + 1, 1), deps),
            None,
        )
    memo = graph._missing_map()

    def reference_walk(vertex):
        missing, visited, stack = set(), {vertex.dot}, [vertex]
        while stack:
            current = stack.pop()
            for dep in current.deps:
                if dep in visited:
                    continue
                if graph._executed_clock.contains(dep.source, dep.sequence):
                    continue
                if not graph._committed_clock.contains(dep.source, dep.sequence):
                    missing.add(dep)
                    continue
                visited.add(dep)
                dep_vertex = graph._vertices.get(dep)
                if dep_vertex is not None and dep_vertex.clock < current.clock:
                    stack.append(dep_vertex)
        return missing

    for vertex in graph._vertices.values():
        assert memo[vertex.dot] == reference_walk(vertex), vertex.dot
