"""Vote ranges, key clocks, quorum clocks and the range event set.

Mirrors the reference's colocated tests:
fantoch_ps/src/protocol/common/table/votes.rs:165-311 (compression),
.../clocks/keys/mod.rs:104-180 (proposal flow / no double votes),
.../clocks/quorum.rs:62-110 (max + count golden vectors).
"""

from fantoch_tpu.core.clocks import RangeEventSet
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Rifl
from fantoch_tpu.core.kvs import KVOp
from fantoch_tpu.protocol.common.table_clocks import (
    QuorumClocks,
    SequentialKeyClocks,
    VoteRange,
    Votes,
)

SHARD = 0


def put_cmd(rifl: Rifl, keys) -> Command:
    return Command.from_keys(rifl, SHARD, {k: (KVOp.put(k),) for k in keys})


def test_vote_range_compress():
    a = VoteRange(1, 1, 1)
    assert a.try_compress(VoteRange(1, 2, 2))
    assert a == VoteRange(1, 1, 2)
    assert a.try_compress(VoteRange(1, 3, 6))
    assert a == VoteRange(1, 1, 6)
    assert not a.try_compress(VoteRange(1, 8, 8))
    assert a == VoteRange(1, 1, 6)


def test_votes_add_compresses_adjacent():
    votes = Votes()
    votes.add("A", VoteRange(1, 1, 3))
    votes.add("A", VoteRange(1, 4, 6))
    assert votes.get("A") == [VoteRange(1, 1, 6)]
    votes.add("A", VoteRange(1, 8, 9))
    assert votes.get("A") == [VoteRange(1, 1, 6), VoteRange(1, 8, 9)]


def test_key_clocks_flow():
    clocks = SequentialKeyClocks(1, SHARD)
    cmd_a = put_cmd(Rifl(100, 1), ["A"])
    cmd_b = put_cmd(Rifl(100, 2), ["B"])
    cmd_ab = put_cmd(Rifl(100, 3), ["A", "B"])

    clock, votes = clocks.proposal(cmd_a, 0)
    assert clock == 1 and votes.get("A") == [VoteRange(1, 1, 1)]
    clock, votes = clocks.proposal(cmd_b, 0)
    assert clock == 1 and votes.get("B") == [VoteRange(1, 1, 1)]
    # multi-key: bumps to max(key clocks) + 1 and votes each key's gap
    clock, votes = clocks.proposal(cmd_ab, 0)
    assert clock == 2
    assert votes.get("A") == [VoteRange(1, 2, 2)]
    assert votes.get("B") == [VoteRange(1, 2, 2)]
    # min_clock dominates
    clock, votes = clocks.proposal(cmd_a, 10)
    assert clock == 10 and votes.get("A") == [VoteRange(1, 3, 10)]


def test_key_clocks_no_double_votes():
    """Across arbitrary proposals, each (process, key, clock-value) is voted
    at most once (mod.rs:150-180)."""
    clocks = SequentialKeyClocks(1, SHARD)
    seen = {"A": set(), "B": set()}
    for seq in range(1, 50):
        keys = ["A"] if seq % 3 == 0 else (["B"] if seq % 3 == 1 else ["A", "B"])
        _, votes = clocks.proposal(put_cmd(Rifl(100, seq), keys), seq % 7)
        for key, ranges in votes:
            for r in ranges:
                for v in r.votes():
                    assert v not in seen[key], f"double vote {v} on {key}"
                    seen[key].add(v)


def test_detached_votes_fill_gaps():
    clocks = SequentialKeyClocks(1, SHARD)
    cmd = put_cmd(Rifl(100, 1), ["A"])
    clocks.proposal(cmd, 0)  # A at 1
    votes = Votes()
    clocks.detached(cmd, 5, votes)
    assert votes.get("A") == [VoteRange(1, 2, 5)]
    # detached_all bumps every known key
    votes = Votes()
    clocks.detached_all(9, votes)
    assert votes.get("A") == [VoteRange(1, 6, 9)]


def test_quorum_clocks_max_and_count():
    q = QuorumClocks(3)
    assert q.add(1, 10) == (10, 1)
    assert q.add(2, 10) == (10, 2)
    assert q.add(3, 10) == (10, 3)
    assert q.all()

    q = QuorumClocks(10)
    assert q.add(1, 10) == (10, 1)
    assert q.add(2, 9) == (10, 1)
    assert q.add(3, 10) == (10, 2)
    assert q.add(4, 9) == (10, 2)
    assert q.add(5, 9) == (10, 2)
    assert q.add(6, 12) == (12, 1)
    assert q.add(7, 12) == (12, 2)
    assert q.add(8, 10) == (12, 2)
    assert q.add(9, 12) == (12, 3)
    assert q.add(10, 13) == (13, 1)
    assert q.all()


def test_range_event_set():
    s = RangeEventSet()
    assert s.frontier == 0
    assert s.add_range(2, 4)
    assert s.frontier == 0  # 1 missing
    assert s.add_range(1, 1)
    assert s.frontier == 4
    # overlapping add: only partially new
    assert s.add_range(3, 6)
    assert s.frontier == 6
    # fully covered add: nothing new
    assert not s.add_range(2, 5)
    # wide ranges are O(1) in events
    assert s.add_range(10, 10_000_000)
    assert s.frontier == 6
    assert s.add_range(7, 9)
    assert s.frontier == 10_000_000
    assert s.contains(123456) and not s.contains(10_000_001)
    assert s.event_count() == 10_000_000
