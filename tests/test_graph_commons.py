"""Unit tests for graph-protocol commons: KeyDeps conflict tracking
(reference: deps/keys/mod.rs:79-470), QuorumDeps union checks
(deps/quorum.rs:103-287), and the Synod flow (synod/single.rs:449-926).
"""

from fantoch_tpu.core import Command, Dot, IdGen, KVOp, Rifl
from fantoch_tpu.protocol.common.graph_deps import Dependency, KeyDeps, QuorumDeps
from fantoch_tpu.protocol.common.synod import (
    MAccept,
    MAccepted,
    MChosen,
    MPrepare,
    MPromise,
    Synod,
)

SHARD = 0


def multi_put(rifl, keys):
    return Command.from_keys(rifl, SHARD, {k: (KVOp.put(""),) for k in keys})


def test_key_deps_flow():
    key_deps = KeyDeps(SHARD)
    dot_gen = IdGen(1)

    cmd_a = multi_put(Rifl(100, 1), ["A"])
    cmd_b = multi_put(Rifl(101, 1), ["B"])
    cmd_ab = multi_put(Rifl(102, 1), ["A", "B"])
    cmd_c = multi_put(Rifl(103, 1), ["C"])

    assert key_deps.cmd_deps(cmd_a) == set()

    d1 = dot_gen.next_id()
    key_deps.add_cmd(d1, cmd_a)  # A -> 1.1
    assert key_deps.cmd_deps(cmd_a) == {d1}
    assert key_deps.cmd_deps(cmd_b) == set()
    assert key_deps.cmd_deps(cmd_ab) == {d1}

    d2 = dot_gen.next_id()
    deps = key_deps.add_cmd(d2, cmd_b)  # B -> 1.2
    assert deps == set()
    assert key_deps.cmd_deps(cmd_ab) == {d1, d2}

    d3 = dot_gen.next_id()
    deps = key_deps.add_cmd(d3, cmd_ab)  # A,B -> 1.3; deps = {1.1, 1.2}
    assert {d.dot for d in deps} == {d1, d2}
    assert key_deps.cmd_deps(cmd_a) == {d3}
    assert key_deps.cmd_deps(cmd_b) == {d3}

    # noops conflict with everything
    d4 = dot_gen.next_id()
    noop_deps = key_deps.add_noop(d4)
    assert {d.dot for d in noop_deps} == {d3}
    assert key_deps.cmd_deps(cmd_c) == {d4}
    d5 = dot_gen.next_id()
    deps = key_deps.add_cmd(d5, cmd_c)
    assert {d.dot for d in deps} == {d4}
    assert key_deps.noop_deps() == {d3, d4, d5}


def _dep(source, seq):
    return Dependency(Dot(source, seq), None)


def test_quorum_deps_check_union():
    # all equal -> fast path (EPaxos)
    q = QuorumDeps(2)
    q.add(1, {_dep(1, 1)})
    assert not q.all()
    q.add(2, {_dep(1, 1)})
    assert q.all()
    deps, equal = q.check_union()
    assert deps == {_dep(1, 1)} and equal

    # different -> no fast path
    q = QuorumDeps(2)
    q.add(1, {_dep(1, 1)})
    q.add(2, {_dep(1, 2)})
    deps, equal = q.check_union()
    assert deps == {_dep(1, 1), _dep(1, 2)} and not equal

    # empty deps everywhere -> trivially equal
    q = QuorumDeps(2)
    q.add(1, set())
    q.add(2, set())
    deps, equal = q.check_union()
    assert deps == set() and equal


def test_quorum_deps_check_threshold_union():
    # every dep reported >= f times -> fast path (Atlas)
    f = 1
    q = QuorumDeps(3)
    q.add(1, {_dep(1, 1)})
    q.add(2, {_dep(1, 1), _dep(2, 1)})
    q.add(3, {_dep(1, 1)})
    deps, equal = q.check_threshold_union(f)
    assert deps == {_dep(1, 1), _dep(2, 1)} and equal

    # with f=2, dep (2,1) reported once < f -> no fast path
    q = QuorumDeps(3)
    q.add(1, {_dep(1, 1)})
    q.add(2, {_dep(1, 1), _dep(2, 1)})
    q.add(3, {_dep(1, 1)})
    _, equal = q.check_threshold_union(2)
    assert not equal


def test_synod_flow():
    # 5 processes, f=1: phase-1 needs n-f=4 promises, phase-2 needs f+1=2 accepts
    n, f = 5, 1

    def proposal_gen(values):
        out = 1
        for v in values.values():
            out *= v
        return out

    synods = {pid: Synod(pid, n, f, proposal_gen, prime) for pid, prime in
              zip(range(1, 6), [2, 3, 5, 7, 11])}

    # process 1 prepares
    prepare = synods[1].new_prepare()
    assert isinstance(prepare, MPrepare)

    # promises from 4 acceptors (1..4)
    accept_msg = None
    for pid in (1, 2, 3, 4):
        promise = synods[pid].handle(1, prepare)
        assert isinstance(promise, MPromise)
        out = synods[1].handle(pid, promise)
        if pid < 4:
            assert out is None
        else:
            accept_msg = out
    assert isinstance(accept_msg, MAccept)
    # nothing accepted before: proposal_gen multiplies the initial values
    assert accept_msg.value == 2 * 3 * 5 * 7

    # accepts from 2 acceptors choose the value
    chosen = None
    for pid in (1, 2):
        accepted = synods[pid].handle(1, accept_msg)
        assert isinstance(accepted, MAccepted)
        chosen = synods[1].handle(pid, accepted)
    assert isinstance(chosen, MChosen)
    assert chosen.value == 210


def test_synod_skip_prepare():
    n, f = 3, 1
    synods = {pid: Synod(pid, n, f, lambda v: 0, 0) for pid in (1, 2, 3)}
    # coordinator 2 sets its value then skips prepare
    assert synods[2].set_if_not_accepted(lambda: 42)
    ballot = synods[2].skip_prepare()
    assert ballot == 2
    accept = MAccept(ballot, 42)
    chosen = None
    for pid in (2, 3):
        accepted = synods[pid].handle(2, accept)
        assert isinstance(accepted, MAccepted)
        chosen = synods[2].handle(pid, accepted)
    assert isinstance(chosen, MChosen) and chosen.value == 42


def test_key_deps_read_write_split():
    """The read/write split (locked.rs:10-122): reads depend only on the
    latest write and never on each other; writes depend on the latest
    read and write."""
    from fantoch_tpu.core.kvs import KVOp

    key_deps = KeyDeps(SHARD)

    def put(seq, key="k"):
        dot = Dot(1, seq)
        cmd = Command.from_single(Rifl(1, seq), SHARD, key, KVOp.put("v"))
        return dot, key_deps.add_cmd(dot, cmd, None)

    def get(seq, key="k"):
        dot = Dot(1, seq)
        cmd = Command.from_single(Rifl(1, seq), SHARD, key, KVOp.get())
        return dot, key_deps.add_cmd(dot, cmd, None)

    w1, w1_deps = put(1)
    assert w1_deps == set()
    # a burst of reads: each depends ONLY on w1 — never on earlier reads
    # (the latest-access index would chain them)
    r_dots = []
    for seq in (2, 3, 4):
        dot, deps = get(seq)
        assert {d.dot for d in deps} == {w1}, deps
        r_dots.append(dot)
    # the next write depends on the latest read + latest write
    w2, w2_deps = put(5)
    assert {d.dot for d in w2_deps} == {w1, r_dots[-1]}
    # and a read after the write depends on w2 alone
    _, r_deps = get(6)
    assert {d.dot for d in r_deps} == {w2}


def test_sim_epaxos_read_heavy_agreement():
    """Read-heavy EPaxos sims stay correct under the split: per-key
    monitor agreement is asserted inside sim_test."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from harness import sim_test

    from fantoch_tpu.core.config import Config
    from fantoch_tpu.protocol import EPaxos

    sim_test(EPaxos, Config(3, 1), conflict_rate=100, keys_per_command=1,
             read_only_percentage=80)
