"""Experiment orchestration + results DB + plots (fantoch_exp /
fantoch_plot analogs): run real localhost experiments through the CLI
binaries, index the results, render plots."""

import json
import os

import pytest

from fantoch_tpu.exp import ExperimentConfig, run_experiment
from fantoch_tpu.plot import ResultsDB
from fantoch_tpu.plot import plots


def test_experiment_config_name_and_args():
    cfg = ExperimentConfig("epaxos", 3, 1, conflict_rate=30, clients_per_process=2)
    assert cfg.name() == "epaxos_n3_f1_s1_cr30_k1_c2"
    args = cfg.server_args(1, 0, 7001, 8001, "2=h:2,3=h:3", "1:0,2:0,3:0")
    assert "--protocol" in args and "epaxos" in args
    cargs = cfg.client_args("1-6", "0=h:8001")
    assert "--commands-per-client" in cargs


def test_non_localhost_testbed_rejected(tmp_path):
    cfg = ExperimentConfig("epaxos", 3, 1)
    with pytest.raises(NotImplementedError, match="aws"):
        run_experiment(cfg, str(tmp_path), testbed="aws")


@pytest.mark.slow
def test_run_experiment_cprofile_mode(tmp_path):
    """run_mode='cprofile' (the RunMode::Flamegraph analog): every
    server runs under cProfile and the experiment pulls one .prof plus a
    rendered .txt per process, alongside the ordinary artifacts."""
    cfg = ExperimentConfig(
        "epaxos", 3, 1, commands_per_client=4, conflict_rate=50
    )
    out = str(tmp_path / "prof")
    manifest = run_experiment(cfg, out, run_mode="cprofile")
    assert manifest["run_mode"] == "cprofile"
    exp_dir = os.path.join(out, cfg.name())
    for pid in (1, 2, 3):
        prof = os.path.join(exp_dir, f"profile_p{pid}.prof")
        txt = os.path.join(exp_dir, f"profile_p{pid}.txt")
        assert os.path.exists(prof), f"missing {prof}"
        assert os.path.exists(txt)
        body = open(txt).read()
        assert "cumulative" in body and "function calls" in body
    assert manifest["outcome"]["commands"] == 4 * 3


def test_run_experiment_device_step(tmp_path):
    """The TPU serving path through the experiment layer: one
    --device-step server instead of an n-process mesh, the stock client
    binary, the serving JSON tallies pulled as the metrics artifact and
    indexed by the plot layer.  keys_per_command=2 with no explicit
    device_key_width pins the width derivation (an under-sized device
    state would reject every 2-key command)."""
    cfg = ExperimentConfig(
        "epaxos", 3, 1, commands_per_client=4, conflict_rate=50,
        keys_per_command=2, device_step=True, device_batch=32,
    )
    out = str(tmp_path / "dev")
    manifest = run_experiment(cfg, out)
    assert manifest["outcome"]["commands"] == 4 * 3
    exp_dir = os.path.join(out, cfg.name())
    assert cfg.name().startswith("dev_")
    assert os.path.exists(os.path.join(exp_dir, "client_summary.json"))
    metrics = os.path.join(exp_dir, "metrics_p1.json")
    assert os.path.exists(metrics), "device tallies not pulled"
    snap = json.load(open(metrics))
    assert snap["executed"] >= 1 and snap["rounds"] >= 1
    # the plot layer indexes the device tallies (fast/slow paths)
    db = ResultsDB(out)
    (res,) = db.results
    assert res.device_tallies()[1]["executed"] == snap["executed"]
    totals = res.protocol_totals()
    assert totals["fast_path"] + totals["slow_path"] >= 1


@pytest.mark.slow
def test_run_experiment_memory_mode(tmp_path):
    """run_mode='memory' (the RunMode::Heaptrack analog): every server
    runs under the tracemalloc wrapper (fantoch_tpu.exp.memprof) and the
    experiment pulls one heap-report text per process, written through
    the SIGINT teardown path."""
    cfg = ExperimentConfig(
        "epaxos", 3, 1, commands_per_client=4, conflict_rate=50
    )
    out = str(tmp_path / "mem")
    manifest = run_experiment(cfg, out, run_mode="memory")
    assert manifest["run_mode"] == "memory"
    exp_dir = os.path.join(out, cfg.name())
    for pid in (1, 2, 3):
        txt = os.path.join(exp_dir, f"memory_p{pid}.txt")
        assert os.path.exists(txt), f"missing {txt}"
        body = open(txt).read()
        assert "tracemalloc" in body and "peak=" in body
        assert "top" in body and "KiB" in body
    assert manifest["outcome"]["commands"] == 4 * 3


@pytest.mark.slow
def test_run_sweep_throughput_latency_curve(tmp_path):
    # the reference's main experiment shape: one protocol at increasing
    # client counts -> a multi-point throughput-latency curve
    from fantoch_tpu.exp import run_sweep

    out = str(tmp_path / "sweep")
    base = ExperimentConfig(
        "epaxos", 3, 1, commands_per_client=6, conflict_rate=50
    )
    manifests = run_sweep(base, out, clients_sweep=[1, 2])
    assert [m["config"]["clients_per_process"] for m in manifests] == [1, 2]
    db = ResultsDB(out)
    assert len(db) == 2
    path = plots.throughput_latency(db.results, str(tmp_path / "curve.png"))
    assert os.path.getsize(path) > 1000


@pytest.mark.slow
def test_run_sweep_device_step_curve(tmp_path):
    """The device-plane throughput-latency curve: a client sweep where
    every point serves through one --device-step server, indexed and
    rendered by the same plot pipeline as the object-runner sweeps."""
    from fantoch_tpu.exp import run_sweep

    out = str(tmp_path / "devsweep")
    base = ExperimentConfig(
        "epaxos", 3, 1, commands_per_client=6, conflict_rate=50,
        device_step=True, device_batch=32,
    )
    manifests = run_sweep(base, out, clients_sweep=[1, 2])
    assert [m["config"]["clients_per_process"] for m in manifests] == [1, 2]
    assert all(m["name"].startswith("dev_") for m in manifests)
    db = ResultsDB(out)
    assert len(db) == 2
    for res in db.results:
        assert res.device_tallies()[1]["executed"] >= 1
    path = plots.throughput_latency(db.results, str(tmp_path / "curve.png"))
    assert os.path.getsize(path) > 1000


@pytest.mark.slow
def test_run_experiments_db_and_plots(tmp_path):
    out = str(tmp_path / "results")
    configs = [
        ExperimentConfig(
            "epaxos", 3, 1, commands_per_client=8, conflict_rate=50, payload_size=2
        ),
        ExperimentConfig(
            "newt", 3, 1, commands_per_client=8, conflict_rate=50, payload_size=2
        ),
    ]
    for cfg in configs:
        manifest = run_experiment(cfg, out)
        assert manifest["outcome"]["commands"] == 8 * 3  # 1 client/process x n
        assert manifest["outcome"]["latency_ms"]["p50"] is not None

    db = ResultsDB(out)
    assert len(db) == 2
    (ep,) = db.search(protocol="epaxos")
    assert ep.config["n"] == 3
    lats = ep.latencies_us()
    assert len(lats) == 24 and all(l > 0 for l in lats)
    totals = ep.protocol_totals()
    assert totals["fast_path"] + totals["slow_path"] == 24
    assert totals["stable"] == 3 * 24

    # plots render to files
    for fn, name in [
        (plots.latency_cdf, "cdf.png"),
        (plots.latency_percentiles, "pct.png"),
        (plots.throughput_latency, "tl.png"),
        (plots.fast_path_split, "split.png"),
    ]:
        path = fn(db.results, str(tmp_path / name))
        assert os.path.getsize(path) > 1000

    # metrics table renders the snapshot counters
    table = plots.metrics_table(db.results)
    assert "fast" in table and "epaxos" in table
    assert len(table.splitlines()) == 1 + len(db.results)

    # dstat-analog resource table from the monitor CSV
    resources = plots.resource_table(db.results)
    assert "cpu% avg" in resources
    assert len(resources.splitlines()) == 1 + len(db.results)
    # the monitor created the series file during the run
    for result in db.results:
        assert os.path.exists(os.path.join(result.path, "resources.jsonl"))


def test_scalability_and_heatmap_plots(tmp_path):
    """The lib.rs:870-1120 analogs over synthetic manifests: heatmap over
    a config grid, intra-machine (workers) and inter-machine (n)
    scalability, plus predicate search over the DB."""
    import json

    out = tmp_path / "grid"
    cases = [
        ("epaxos", 3, 1, 1, 900.0),
        ("epaxos", 3, 2, 1, 1500.0),
        ("epaxos", 3, 1, 2, 1100.0),
        ("epaxos", 3, 2, 2, 2400.0),
        ("epaxos", 5, 2, 2, 2000.0),
        ("newt", 3, 1, 1, 800.0),
        ("newt", 5, 1, 1, 700.0),
    ]
    for i, (proto, n, workers, executors, thr) in enumerate(cases):
        cfg = ExperimentConfig(
            proto, n, 1, workers=workers, executors=executors,
            clients_per_process=i + 1,  # distinct names
        )
        exp_dir = out / cfg.name()
        exp_dir.mkdir(parents=True)
        (exp_dir / "manifest.json").write_text(json.dumps({
            "config": cfg.to_dict(),
            "name": cfg.name(),
            "outcome": {
                "commands": 10,
                "latency_ms": {"p50": 5.0},
                "wall_s": 1.0,
                "throughput_cmds_per_s": thr,
            },
        }))
    db = ResultsDB(str(out))
    assert len(db) == len(cases)

    # predicate search (the Search-refine analog)
    assert len(db.search(protocol="epaxos")) == 5
    assert len(db.search(workers=lambda w: w >= 2)) == 3
    fast = db.search(where=lambda r: r.outcome["throughput_cmds_per_s"] > 1000)
    assert len(fast) == 4

    grid = db.search(protocol="epaxos", n=3)
    p = plots.heatmap(grid, str(tmp_path / "heat.png"))
    assert os.path.getsize(p) > 1000
    p = plots.intra_machine_scalability(grid, str(tmp_path / "intra.png"))
    assert os.path.getsize(p) > 1000
    p = plots.inter_machine_scalability(db.results, str(tmp_path / "inter.png"))
    assert os.path.getsize(p) > 1000


# --- scenario-observatory curve rendering (exp/scenarios.py artifacts) ---


def test_plots_pins_agg_backend():
    """Headless CI safety: importing fantoch_tpu.plot.plots must force
    the Agg backend (force=True — even if something selected an
    interactive backend first, the first savefig must not need a
    display)."""
    import matplotlib

    assert matplotlib.get_backend().lower() == "agg"


def synthetic_curves_doc():
    def point(cell, offered, goodput, p50, p95, p99, sheds=0, degraded=0.0):
        return {
            "cell": cell, "offered_cmds_per_s": offered,
            "goodput_cmds_per_s": goodput, "commands": 60,
            "p50_ms": p50, "p95_ms": p95, "p99_ms": p99,
            "sheds": sheds, "queue_depth_hwm": 0,
            "degraded_ms": degraded, "failovers": 0,
        }

    # offered-rate order, with goodput REGRESSING past the knee (the
    # retrograde case the monotone-axis sort exists for)
    points = [
        point("c_r50", 50.0, 48.0, 10.0, 20.0, 30.0),
        point("c_r400", 400.0, 210.0, 15.0, 30.0, 45.0),
        point("c_r3200", 3200.0, 190.0, 20.0, 40.0, 60.0, sheds=7,
              degraded=12.5),
    ]
    return {
        "scenario": "synthetic", "timeline": "sim", "seed": 0,
        "slo": None, "workload": {}, "placements": {},
        "curves": [{
            "protocol": "epaxos", "n": 3, "f": 1, "points": points,
            "knee_index": 1, "knee": points[1],
            "slo": [],
        }],
    }


def test_curve_axes_monotone_goodput():
    doc = synthetic_curves_doc()
    xs, ys = plots.curve_axes(doc["curves"][0])
    assert xs == sorted(xs)  # monotone even though r3200 regressed
    assert xs == [48.0, 190.0, 210.0]
    # percentiles travel with their point through the sort
    assert ys["p99"] == [30.0, 60.0, 45.0]
    assert len(ys["p50"]) == len(ys["p95"]) == len(xs)


def test_render_saturation_has_knee_marker_and_annotations(tmp_path):
    doc = synthetic_curves_doc()
    fig = plots.render_saturation(doc)
    try:
        ax = fig.axes[0]
        labels = [line.get_label() for line in ax.lines]
        assert "knee" in labels
        # p50/p95/p99 series all present for the curve
        assert sum(1 for l in labels if l.startswith("epaxos n=3")) == 3
        texts = [t.get_text() for t in ax.texts]
        assert any("shed 7" in t for t in texts)
        assert any("degraded" in t for t in texts)
    finally:
        import matplotlib.pyplot as plt

        plt.close(fig)
    # and the file-rendering wrapper produces a real PNG
    path = plots.saturation_curves(doc, str(tmp_path / "curves.png"))
    assert os.path.getsize(path) > 1000


def test_curves_json_round_trips_through_db(tmp_path):
    from fantoch_tpu.plot.db import load_curves, save_curves

    doc = synthetic_curves_doc()
    path = save_curves(doc, str(tmp_path / "curves.json"))
    assert load_curves(path) == doc
    # canonical bytes: saving the loaded doc is byte-identical
    again = save_curves(load_curves(path), str(tmp_path / "again.json"))
    assert open(path, "rb").read() == open(again, "rb").read()
