"""Clock lattice + GC tracking tests (reference: threshold crate semantics,
fantoch/src/protocol/gc.rs:145-224)."""

from fantoch_tpu.core.clocks import AboveExSet, AEClock, VClock
from fantoch_tpu.core.ids import Dot
from fantoch_tpu.protocol.gc import GCTrack


def test_above_ex_set():
    s = AboveExSet()
    assert not s.contains(1)
    assert s.add(1)
    assert s.frontier == 1
    # above-frontier exception
    assert s.add(3)
    assert s.frontier == 1
    assert s.contains(3) and not s.contains(2)
    # filling the gap absorbs the exception
    assert s.add(2)
    assert s.frontier == 3
    # duplicates are no-ops
    assert not s.add(2)
    assert list(s.events()) == [1, 2, 3]


def test_aeclock_frontier_and_join():
    c = AEClock([1, 2, 3])
    c.add(1, 1)
    c.add(1, 2)
    c.add(2, 1)
    c.add(2, 5)
    f = c.frontier()
    assert f.get(1) == 2 and f.get(2) == 1 and f.get(3) == 0

    other = AEClock([1, 2, 3])
    for seq in range(1, 5):
        other.add(2, seq)
    c.join(other)
    assert c.frontier().get(2) == 5  # 1-4 joined + existing 5


def test_vclock_join_meet():
    a = VClock([1, 2])
    a.set(1, 5)
    a.set(2, 3)
    b = VClock([1, 2])
    b.set(1, 2)
    b.set(2, 7)
    a_join = a.copy()
    a_join.join(b)
    assert a_join.get(1) == 5 and a_join.get(2) == 7
    a.meet(b)
    assert a.get(1) == 2 and a.get(2) == 3


def test_gc_track_stable_flow():
    n = 3
    gc = GCTrack(process_id=1, shard_id=0, n=n)
    # locally commit 1.1, 1.2, 2.1
    gc.add_to_clock(Dot(1, 1))
    gc.add_to_clock(Dot(1, 2))
    gc.add_to_clock(Dot(2, 1))

    # no stable dots until all peers report
    assert gc.stable() == []

    # peer 2 reports committed {1.1, 1.2, 2.1}
    peer2 = VClock([1, 2, 3])
    peer2.set(1, 2)
    peer2.set(2, 1)
    gc.update_clock_of(2, peer2)
    assert gc.stable() == []

    # peer 3 reports committed {1.1}
    peer3 = VClock([1, 2, 3])
    peer3.set(1, 1)
    gc.update_clock_of(3, peer3)
    # meet = {1: 1, 2: 0, 3: 0} -> dot 1.1 newly stable
    assert gc.stable() == [(1, 1, 1)]
    # calling again: nothing new
    assert gc.stable() == []

    # peer 3 catches up on 1.2 and 2.1
    peer3b = VClock([1, 2, 3])
    peer3b.set(1, 2)
    peer3b.set(2, 1)
    gc.update_clock_of(3, peer3b)
    assert sorted(gc.stable()) == [(1, 2, 2), (2, 1, 1)]

    # reordered stale message: clock knowledge must not go backwards
    stale = VClock([1, 2, 3])
    gc.update_clock_of(3, stale)
    assert gc.stable() == []
