"""Protocol-level message-walk test for Basic, driving Simulation directly
and asserting each message/action hop (mirrors
fantoch/src/protocol/basic.rs:397-598)."""

from fantoch_tpu.client import Client, ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config, Dot, Planet, Region
from fantoch_tpu.protocol import Basic, ToForward, ToSend
from fantoch_tpu.protocol.basic import MCommit, MCommitDot, MStore, MStoreAck
from fantoch_tpu.sim import Simulation
from fantoch_tpu.utils import closest_process_per_shard, sort_processes_by_distance


def test_basic_flow():
    simulation = Simulation()
    shard_id = 0
    region = Region("europe-west2")  # all colocated, like the reference test
    processes = [(1, shard_id, region), (2, shard_id, region), (3, shard_id, region)]
    planet = Planet.new("gcp")
    n, f = 3, 1
    config = Config(n, f)

    for process_id in (1, 2, 3):
        protocol, _events = Basic.new(process_id, shard_id, config)
        sorted_ps = sort_processes_by_distance(region, planet, processes)
        protocol.discover(sorted_ps)
        executor = Basic.Executor(process_id, shard_id, config)
        simulation.register_process(protocol, executor)

    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(100),
        keys_per_command=1,
        commands_per_client=10,
        payload_size=100,
    )
    client = Client(1, workload)
    client.connect(closest_process_per_shard(region, planet, processes))

    nxt = client.next_cmd(simulation.time)
    assert nxt is not None
    target_shard, cmd = nxt
    target = client.shard_process(target_shard)
    assert target == 1  # ties break by process id
    simulation.register_client(client)

    # submit at process 1
    process, _, pending = simulation.get_process(1)
    pending.wait_for(cmd)
    process.submit(None, cmd, simulation.time)
    actions = list(process.to_processes_iter())
    assert len(actions) == 1
    mstore = actions.pop()
    # MStore goes to the fast quorum of size 2f (f+1 = 2 here)
    assert isinstance(mstore, ToSend) and isinstance(mstore.msg, MStore)
    assert mstore.target == {1, 2}

    # handle mstores -> 2 MStoreAcks
    mstoreacks = simulation.forward_to_processes(1, mstore)
    assert len(mstoreacks) == 2 * f
    assert all(isinstance(a.msg, MStoreAck) for _, a in mstoreacks)

    # first ack: no commit yet
    pid, ack = mstoreacks.pop()
    mcommits = simulation.forward_to_processes(pid, ack)
    assert mcommits == []

    # second ack: commit to everyone
    pid, ack = mstoreacks.pop()
    mcommits = simulation.forward_to_processes(pid, ack)
    assert len(mcommits) == 1
    pid, mcommit = mcommits.pop()
    assert isinstance(mcommit, ToSend) and isinstance(mcommit.msg, MCommit)
    assert len(mcommit.target) == n

    # all processes handle the commit; gc is off (gc_interval None) so no
    # MCommitDot forwards are produced
    to_sends = simulation.forward_to_processes(pid, mcommit)
    assert all(
        isinstance(a, ToForward) and isinstance(a.msg, MCommitDot) for _, a in to_sends
    )

    # process 1 has execution info -> executor -> client result
    process, executor, pending = simulation.get_process(1)
    to_executor = list(process.to_executors_iter())
    assert len(to_executor) == 1
    ready = []
    for info in to_executor:
        executor.handle(info, simulation.time)
        ready.extend(executor.to_clients_iter())
    assert len(ready) == 1
    cmd_result = pending.add_executor_result(ready.pop())
    assert cmd_result is not None

    # client gets the result and submits the next command (dot 1.2)
    submit = simulation.forward_to_client(cmd_result)
    assert submit is not None
    target, cmd = submit
    process, _, _ = simulation.get_process(target)
    process.submit(None, cmd, simulation.time)
    actions = list(process.to_processes_iter())
    assert len(actions) == 1
    mstore = actions.pop()
    assert isinstance(mstore, ToSend) and isinstance(mstore.msg, MStore)
    assert mstore.msg.dot == Dot(1, 2)
