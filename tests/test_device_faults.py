"""Accelerator fault tolerance: the seeded DeviceFault nemesis against
the device planes — dispatch deadlines surfacing hangs as typed
``DeviceFailedError``, the sampled shadow-check naming silent corruption
(``DeviceCorruptionError`` with the first-diverging key), bit-for-bit
host-twin failover, exactly-once replay across in-flight pipeline
rounds, snapshot/restore mid-failover, and online rebuild + cutback.
The sim acceptance rows drive a full protocol run per plane with a
DeviceFault plan: auditor-clean, ``plane_failovers >= 1``,
``plane_rebuilds >= 1``, output bit-for-bit the fault-free run's, and
same-seed byte-identical digests.
"""

import itertools
import pickle
import random

import numpy as np
import pytest

from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
from fantoch_tpu.errors import DeviceCorruptionError, DeviceFailedError
from fantoch_tpu.executor.device_plane import HEALTH_HEALTHY
from fantoch_tpu.executor.table_plane import DeviceTablePlane
from fantoch_tpu.sim.device_faults import (
    DeviceFault,
    DeviceFaultInjector,
    faults_from_env,
)
from fantoch_tpu.sim.faults import FaultPlan

pytestmark = pytest.mark.devicefault

SHARD = 0
TIME = RunTime()


# ---------------------------------------------------------------------------
# the injector model: windows, exactly-once corrupt, env specs
# ---------------------------------------------------------------------------


def test_injector_window_fires_and_vetoes_rebuild():
    fault = DeviceFault("table", "hang", at_dispatch=3, down_dispatches=2)
    fired = []
    injector = DeviceFaultInjector(
        [fault], 1, record=lambda *args: fired.append(args)
    )
    assert injector.on_dispatch("table", 2) is None
    assert injector.on_dispatch("pred", 3) is None  # wrong plane
    assert injector.on_dispatch("table", 3) is fault
    assert injector.on_dispatch("table", 4) is fault  # hangs re-fire
    assert injector.on_dispatch("table", 5) is None  # window closed
    assert not injector.rebuild_allowed("table", 4)
    assert injector.rebuild_allowed("table", 5)
    assert injector.rebuild_allowed("pred", 4)
    assert [f[:3] for f in fired] == [("table", "hang", 3), ("table", "hang", 4)]


def test_injector_corrupt_fires_exactly_once():
    fault = DeviceFault("pred", "corrupt", at_dispatch=2, down_dispatches=4)
    injector = DeviceFaultInjector([fault], 1)
    assert injector.on_dispatch("pred", 2) is fault
    # the bit-flip is a one-shot event; the window still vetoes rebuild
    assert injector.on_dispatch("pred", 3) is None
    assert not injector.rebuild_allowed("pred", 3)


def test_injector_filters_by_process_id():
    fault = DeviceFault(
        "table", "raise", at_dispatch=1, down_dispatches=2, process_id=2
    )
    assert DeviceFaultInjector([fault], 1).on_dispatch("table", 1) is None
    assert DeviceFaultInjector([fault], 2).on_dispatch("table", 1) is fault


def test_env_spec_round_trip():
    faults = faults_from_env("table:hang:3:5:2, pred:corrupt:7")
    assert faults == (
        DeviceFault("table", "hang", at_dispatch=3, down_dispatches=5,
                    process_id=2),
        DeviceFault("pred", "corrupt", at_dispatch=7),
    )
    assert faults_from_env("") == ()
    with pytest.raises(ValueError):
        faults_from_env("table:hang")
    with pytest.raises(ValueError):
        faults_from_env("hbm:hang:3")


def test_fault_plan_carries_device_faults():
    plan = FaultPlan(seed=3).with_device_fault(
        process_id=1, plane="graph", kind="hang", at_dispatch=4,
        down_dispatches=2,
    )
    assert plan.device_faults[0].plane == "graph"
    round_trip = FaultPlan.from_dict(plan.to_dict())
    assert round_trip.device_faults == plan.device_faults


# ---------------------------------------------------------------------------
# table plane: hang -> deadline -> failover; corrupt -> shadow-catch
# ---------------------------------------------------------------------------


def _table_run(fault=None, timeout=None, shadow=0.0, rounds=12, n_keys=8):
    """Feed the same seeded vote batches through a DeviceTablePlane,
    optionally armed (deadline/shadow) and faulted."""
    rng = np.random.default_rng(7)
    plane = DeviceTablePlane(3, stability_threshold=2, key_buckets=n_keys)
    for k in range(n_keys):
        plane.bucket(f"k{k}")
    if fault is not None or timeout is not None or shadow:
        config = Config(
            3, 1,
            device_dispatch_timeout_ms=timeout,
            plane_shadow_rate=shadow,
        )
        plane.configure_faults(config, seed=5, process_id=1)
    if fault is not None:
        plane.attach_injector(DeviceFaultInjector([fault], 1))
    for _ in range(rounds):
        count = 16
        vk = rng.integers(0, n_keys, count).astype(np.int64)
        vb = rng.integers(1, 4, count).astype(np.int64)
        vs = rng.integers(1, 40, count).astype(np.int64)
        plane.commit_votes(
            vk, vb, vs, vs + rng.integers(0, 6, count).astype(np.int64)
        )
    return plane


def test_table_armed_parity_without_fault():
    """Arming (shadow at rate 1.0 + a generous deadline) must not
    change behavior: zero failovers, frontiers bit-for-bit."""
    reference = _table_run()
    plane = _table_run(timeout=60_000.0, shadow=1.0)
    counters = plane.fault_counters()
    assert counters["failovers"] == 0 and counters["rebuilds"] == 0
    assert counters["health"] == HEALTH_HEALTHY
    assert np.array_equal(plane.frontiers(), reference.frontiers())


def test_table_hang_deadline_failover_and_rebuild():
    """A hung dispatch trips the deadline as a typed DeviceFailedError;
    the plane fails over to the host twin (bit-for-bit), serves
    degraded, and rebuilds back to healthy once the window closes."""
    reference = _table_run()
    plane = _table_run(
        fault=DeviceFault("table", "hang", at_dispatch=3, down_dispatches=3),
        timeout=250.0,
    )
    assert isinstance(plane.last_failure, DeviceFailedError)
    counters = plane.fault_counters()
    assert counters["failovers"] == 1
    assert counters["rebuilds"] == 1
    assert counters["health"] == HEALTH_HEALTHY
    assert counters["degraded_ms"] > 0.0
    assert np.array_equal(plane.frontiers(), reference.frontiers())


def test_table_corruption_shadow_catch_names_key():
    """A silent resident bit-flip is caught by the rate-1.0 shadow-check
    on the faulted dispatch and attributed to the first diverging key;
    the twin keeps the output bit-for-bit."""
    reference = _table_run()
    plane = _table_run(
        fault=DeviceFault("table", "corrupt", at_dispatch=4,
                          down_dispatches=2),
        shadow=1.0,
    )
    failure = plane.last_failure
    assert isinstance(failure, DeviceCorruptionError)
    # the nemesis flips bit 20 of state array 0, flat element 0 -> the
    # first registered key's row
    assert failure.row == 0
    assert failure.key == "k0"
    counters = plane.fault_counters()
    assert counters["failovers"] == 1 and counters["rebuilds"] == 1
    assert np.array_equal(plane.frontiers(), reference.frontiers())


# ---------------------------------------------------------------------------
# pred plane: executor-level failover parity + snapshot mid-failover
# ---------------------------------------------------------------------------


def _pred_workload(rng, count=80, keys=("Ka", "Kb", "Kc")):
    from fantoch_tpu.executor.pred import PredecessorsExecutionInfo
    from fantoch_tpu.protocol.common.pred_clocks import Clock

    per_key = {k: [] for k in keys}
    infos = []
    for i in range(count):
        dot = Dot(rng.randrange(1, 4), i + 1)
        ks = rng.sample(list(keys), rng.randrange(1, 3))
        deps = set()
        for k in ks:
            deps.update(per_key[k])
            per_key[k].append(dot)
        command = Command.from_keys(
            Rifl(9, i + 1), SHARD, {k: (KVOp.put(str(i)),) for k in ks}
        )
        infos.append(
            PredecessorsExecutionInfo(dot, command, Clock(i + 1, dot.source),
                                      deps)
        )
    return infos


def _pred_run(fault=None, shadow=0.0, pickle_at=None):
    from fantoch_tpu.executor.pred import PredecessorsExecutor

    config = Config(
        3, 1,
        device_pred_plane=True,
        executor_monitor_execution_order=True,
        plane_shadow_rate=shadow,
    )
    executor = PredecessorsExecutor(1, SHARD, config)
    if fault is not None or shadow:
        executor._plane.configure_faults(config, seed=7, process_id=1)
    injector = DeviceFaultInjector([fault], 1) if fault is not None else None
    if injector is not None:
        executor._plane.attach_injector(injector)
    infos = _pred_workload(random.Random(42))
    for n, i in enumerate(range(0, len(infos), 7)):
        if pickle_at is not None and n == pickle_at:
            # snapshot/restore mid-run: the injector is re-attached the
            # way the sim runner re-arms a restarted process
            executor = pickle.loads(pickle.dumps(executor))
            if injector is not None:
                executor._plane.attach_injector(injector)
        for info in infos[i:i + 7]:
            executor.handle(info, None)
    executed = sorted(r.rifl for r in executor.to_clients_iter())
    monitor = executor.monitor()
    order = {k: monitor.get_order(k) for k in monitor.keys()}
    return executed, order, executor._plane


def test_pred_hang_failover_bit_for_bit():
    want, want_order, _plane = _pred_run()
    got, order, plane = _pred_run(
        fault=DeviceFault("pred", "hang", at_dispatch=3, down_dispatches=3)
    )
    assert (got, order) == (want, want_order)
    counters = plane.fault_counters()
    assert counters["failovers"] == 1 and counters["rebuilds"] == 1
    assert counters["degraded_ms"] > 0.0
    assert counters["health"] == HEALTH_HEALTHY
    assert isinstance(plane.last_failure, DeviceFailedError)


def test_pred_corruption_shadow_catch():
    want, want_order, _plane = _pred_run()
    got, order, plane = _pred_run(
        fault=DeviceFault("pred", "corrupt", at_dispatch=4,
                          down_dispatches=2),
        shadow=1.0,
    )
    assert (got, order) == (want, want_order)
    assert isinstance(plane.last_failure, DeviceCorruptionError)
    counters = plane.fault_counters()
    assert counters["failovers"] == 1 and counters["rebuilds"] == 1


def test_pred_snapshot_restore_mid_failover():
    """Pickle the executor while the plane is serving degraded (the
    fault window still open): the restored twin must carry the full
    state and the run must stay bit-for-bit."""
    want, want_order, _plane = _pred_run()
    got, order, plane = _pred_run(
        fault=DeviceFault("pred", "hang", at_dispatch=3, down_dispatches=4),
        pickle_at=5,
    )
    assert (got, order) == (want, want_order)
    assert plane.fault_counters()["health"] == HEALTH_HEALTHY


# ---------------------------------------------------------------------------
# graph plane: exactly-once across pipeline depth, all fault kinds
# ---------------------------------------------------------------------------


def _graph_args(n, events_per_process, rng):
    from fantoch_tpu.core.ids import process_ids

    possible_keys = ["A", "B", "C", "D"]
    dots = [
        Dot(pid, seq)
        for pid in process_ids(SHARD, n)
        for seq in range(1, events_per_process + 1)
    ]
    keys = {dot: set(rng.sample(possible_keys, 2)) for dot in dots}
    deps = {dot: set() for dot in dots}
    for left, right in itertools.combinations(dots, 2):
        if not (keys[left] & keys[right]):
            continue
        if left.source == right.source:
            if left.sequence < right.sequence:
                deps[right].add(left)
            else:
                deps[left].add(right)
        else:
            choice = rng.randrange(3)
            if choice in (0, 2):
                deps[left].add(right)
            if choice in (1, 2):
                deps[right].add(left)
    return [(dot, sorted(keys[dot]), deps[dot]) for dot in dots]


def _graph_run(feeds, fault=None, depth=1, shadow=0.0, pickle_at=None):
    from fantoch_tpu.executor.graph.batched import BatchedDependencyGraph
    from fantoch_tpu.protocol.common.graph_deps import Dependency

    config = Config(
        3, 1,
        host_native_resolver=False,
        batched_graph_executor=True,
        device_graph_plane=True,
        plane_shadow_rate=shadow,
    )
    graph = BatchedDependencyGraph(1, SHARD, config)
    plane = graph._plane
    plane.pipeline_depth = depth
    injector = DeviceFaultInjector([fault], 1) if fault is not None else None
    if injector is not None or shadow:
        plane.configure_faults(config, seed=11, process_id=1)
    if injector is not None:
        plane.attach_injector(injector)
    order = {}
    pending = set()

    def drain():
        for ready in graph.commands_to_execute():
            pending.discard(ready.rifl)
            for key in ready.keys(SHARD):
                order.setdefault(key, []).append(ready.rifl)

    for n, feed in enumerate(feeds):
        if pickle_at is not None and n == pickle_at:
            graph = pickle.loads(pickle.dumps(graph))
            plane = graph._plane
            if injector is not None:
                plane.attach_injector(injector)
        adds = []
        for dot, keys, dep_dots in feed:
            command = Command.from_keys(
                Rifl(dot.source, dot.sequence), SHARD,
                {k: (KVOp.put(""),) for k in keys},
            )
            pending.add(command.rifl)
            adds.append(
                (dot, command,
                 [Dependency(d, frozenset({SHARD})) for d in dep_dots])
            )
        graph.handle_add_batch(adds, TIME)
        drain()
    graph.resolve_now(TIME)
    plane.drain_all()
    drain()
    # exactly-once: every command executed (none lost), and the order
    # map below dedups nothing (a double emission would show up as a
    # repeated rifl and fail the parity compare)
    assert not pending, f"not all executed: {pending}"
    return order, plane


@pytest.fixture(scope="module")
def graph_feeds():
    rng = random.Random(5)
    args = _graph_args(2, 6, rng)
    rng.shuffle(args)
    feeds = []
    at = 0
    while at < len(args):
        size = rng.randrange(1, 6)
        feeds.append(args[at:at + size])
        at += size
    return feeds


@pytest.mark.parametrize("kind", ["hang", "raise", "corrupt"])
def test_graph_failover_all_kinds(graph_feeds, kind):
    want, _plane = _graph_run(graph_feeds)
    shadow = 1.0 if kind == "corrupt" else 0.0
    got, plane = _graph_run(
        graph_feeds,
        fault=DeviceFault("graph", kind, at_dispatch=2, down_dispatches=3),
        shadow=shadow,
    )
    assert got == want
    counters = plane.fault_counters()
    assert counters["failovers"] == 1 and counters["rebuilds"] == 1
    assert counters["health"] == HEALTH_HEALTHY
    expected = (
        DeviceCorruptionError if kind == "corrupt" else DeviceFailedError
    )
    assert isinstance(plane.last_failure, expected)


def test_graph_exactly_once_across_failover_at_depth_2(graph_feeds):
    """With two rounds in flight, a failure mid-pipeline must replay the
    unserved rounds through the twin exactly once — no command lost, no
    command emitted twice, order bit-for-bit the depth-1 fault-free
    run's."""
    want, _plane = _graph_run(graph_feeds)
    got, plane = _graph_run(
        graph_feeds,
        fault=DeviceFault("graph", "hang", at_dispatch=2, down_dispatches=3),
        depth=2,
    )
    assert got == want
    counters = plane.fault_counters()
    assert counters["failovers"] == 1 and counters["rebuilds"] == 1


def test_graph_snapshot_restore_mid_failover(graph_feeds):
    """Pickle the graph mid-window (the plane FAILED, rounds in the twin
    log): the restored run must stay bit-for-bit and still cut back —
    the window is short enough that post-window dispatches remain."""
    want, _plane = _graph_run(graph_feeds)
    got, plane = _graph_run(
        graph_feeds,
        fault=DeviceFault("graph", "hang", at_dispatch=2, down_dispatches=3),
        pickle_at=3,
    )
    assert got == want
    counters = plane.fault_counters()
    assert counters["failovers"] == 1 and counters["rebuilds"] == 1
    assert counters["health"] == HEALTH_HEALTHY


# ---------------------------------------------------------------------------
# sim acceptance: a full protocol run per plane under a DeviceFault plan
# ---------------------------------------------------------------------------


def _sim_config(protocol):
    from fantoch_tpu.sim.fuzz import DEVICE_PLANE_OF, _DEVICE_PLANE_FLAGS

    kwargs = dict(
        shard_count=1,
        executor_monitor_execution_order=True,
        audit_log_commits=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        device_dispatch_timeout_ms=250.0,
        plane_shadow_rate=1.0,
    )
    if protocol == "newt":
        kwargs["newt_detached_send_interval_ms"] = 100
    kwargs.update(_DEVICE_PLANE_FLAGS[DEVICE_PLANE_OF[protocol]])
    return Config(3, 1, **kwargs)


def _sim_run(protocol, plan, sim_seed=11):
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.sim import Runner
    from fantoch_tpu.sim.fuzz import _fuzz_planet, _protocol_cls

    regions, planet = _fuzz_planet(3)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=6,
        payload_size=1,
    )
    runner = Runner(
        _protocol_cls(protocol),
        planet,
        _sim_config(protocol),
        workload,
        2,
        process_regions=list(regions),
        client_regions=list(regions),
        seed=sim_seed,
        fault_plan=plan,
    )
    _metrics, monitors, _latencies = runner.run(extra_sim_time_ms=2000)
    counters = {
        pid: dict(executor.device_counters() or {})
        for pid, (_process, executor, _pending) in
        runner._simulation.processes()
    }
    unfinished = [
        client_id
        for client_id, client in runner._simulation.clients()
        if client.issued_commands != 6
    ]
    return monitors, counters, list(runner.nemesis.trace), unfinished


@pytest.mark.parametrize(
    "protocol,kind",
    [("newt", "hang"), ("caesar", "corrupt"), ("epaxos", "raise")],
)
def test_sim_failover_acceptance(protocol, kind):
    """The ISSUE acceptance row per plane: a seeded sim run with a
    DeviceFault plan completes (every client finished), records at
    least one failover and one rebuild on the faulted plane, and its
    execution-order monitors are bit-for-bit the fault-free run's."""
    from fantoch_tpu.sim.fuzz import DEVICE_PLANE_OF

    base = FaultPlan(seed=7, max_sim_time_ms=600_000)
    plan = base.with_device_fault(
        process_id=1, plane=DEVICE_PLANE_OF[protocol], kind=kind,
        at_dispatch=2, down_dispatches=3,
    )
    clean_monitors, _cc, _ct, clean_unfinished = _sim_run(protocol, base)
    monitors, counters, trace, unfinished = _sim_run(protocol, plan)
    assert not unfinished and not clean_unfinished
    prefix = f"{DEVICE_PLANE_OF[protocol]}_plane_"
    faulted = counters[1]
    assert faulted[f"{prefix}failovers"] >= 1, faulted
    assert faulted[f"{prefix}rebuilds"] >= 1, faulted
    assert faulted[f"{prefix}health"] == HEALTH_HEALTHY, faulted
    assert any(event == f"device-{kind}" for _t, event, _d in trace), trace
    assert any(event == "device-failover" for _t, event, _d in trace), trace
    for pid in monitors:
        assert repr(monitors[pid]) == repr(clean_monitors[pid]), (
            f"p{pid} execution order diverged from the fault-free run"
        )


def test_sim_device_fault_auditor_clean_and_deterministic():
    """run_case over a device-fault plan: the ConsistencyAuditor finds
    no violation, and the same seed reproduces byte-identical plan,
    fault-trace, and verdict digests."""
    from fantoch_tpu.sim.fuzz import OK, FuzzCase, run_case

    plan = FaultPlan(seed=3, max_sim_time_ms=600_000).with_device_fault(
        process_id=2, plane="pred", kind="corrupt", at_dispatch=3,
        down_dispatches=3,
    )
    case = FuzzCase(protocol="caesar", n=3, f=1, plan=plan, sim_seed=5)
    first = run_case(case)
    assert first.verdict == OK, (first.violations, first.error)
    second = run_case(case)
    assert first.plan_digest == second.plan_digest
    assert first.trace_digest == second.trace_digest
    assert first.verdict_digest == second.verdict_digest


def test_fuzzer_samples_device_faults_with_plane_on():
    """The fuzzer's device-fault stream: sampled plans carry DeviceFaults
    only alongside a plane-on config, and sampling is deterministic."""
    from fantoch_tpu.sim.fuzz import (
        DEVICE_PLANE_OF,
        FaultPlanFuzzer,
        _fuzz_config,
    )

    fuzzer = FaultPlanFuzzer(seed=0)
    hit = None
    for index in range(64):
        case = fuzzer.case(index, protocol="newt")
        if case.plan.device_faults:
            hit = (index, case)
            break
    assert hit is not None, "no device fault sampled in 64 newt cases"
    index, case = hit
    config = _fuzz_config(case)
    assert config.device_table_plane
    assert config.device_dispatch_timeout_ms == 250.0
    assert config.plane_shadow_rate == 1.0
    for fault in case.plan.device_faults:
        assert fault.plane == DEVICE_PLANE_OF["newt"]
        assert 1 <= fault.process_id <= case.n
    again = FaultPlanFuzzer(seed=0).case(index, protocol="newt")
    assert again.plan.device_faults == case.plan.device_faults
