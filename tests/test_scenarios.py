"""Scenario observatory (exp/scenarios.py): deterministic expansion,
saturation-knee detection, placement-as-output, and the end-to-end
curve artifacts (curves.json + ResultsDB-indexable per-cell dirs)."""

import json
import os

import pytest

from fantoch_tpu.core.planet import Planet, Region
from fantoch_tpu.exp.scenarios import (
    ScenarioSpec,
    canonical_expansion,
    cell_seed,
    detect_knee,
    expand,
    load_spec,
    run_scenario,
)


def synthetic_planet():
    """Four regions on an asymmetric line: A - 10 - B - 10 - C - 5 - D,
    plus a far outlier Z at 1000 from everyone (the placement search must
    learn to leave it out).  The asymmetry (C/D cluster tighter than A)
    makes the searched placement strictly beat the identity one for both
    leaderless and leader-based protocols — a pure line ties fpaxos."""
    a, b, c, d, z = (Region(x) for x in "ABCDZ")
    pos = {a: 0, b: 10, c: 20, d: 25}
    lat = {x: {y: abs(pos[x] - pos[y]) for y in pos} for x in pos}
    for x in pos:
        lat[x][z] = 1000
    lat[z] = {y: 1000 for y in pos}
    lat[z][z] = 0
    return Planet.from_latencies(lat), (a, b, c, d, z)


def tiny_spec(**overrides):
    base = dict(
        name="t",
        protocols=("epaxos",),
        sites=((3, 1),),
        timeline="sim",
        seed=11,
        clients_per_process=2,
        commands_per_client=10,
        rates=(50.0, 3200.0),
        slo={"p99_ms": 5000.0, "min_goodput_cmds_per_s": 1.0},
    )
    base.update(overrides)
    return ScenarioSpec(**base)


# --- expansion determinism ---


def test_expansion_byte_identity_and_seed_derivation():
    spec = tiny_spec()
    assert canonical_expansion(spec) == canonical_expansion(spec)
    manifest = expand(spec)
    names = [cell["name"] for cell in manifest["cells"]]
    assert names == ["epaxos_n3_f1_r50", "epaxos_n3_f1_r3200"]
    seeds = [cell["seed"] for cell in manifest["cells"]]
    # distinct, stable, derived from sha256 (never Python hash())
    assert len(set(seeds)) == len(seeds)
    assert seeds == [cell_seed(spec.seed, name) for name in names]
    # a different spec seed moves every cell seed
    other = expand(tiny_spec(seed=12))
    assert all(
        a["seed"] != b["seed"]
        for a, b in zip(manifest["cells"], other["cells"])
    )


def test_spec_json_round_trip(tmp_path):
    spec = tiny_spec(
        key_gen="zipf", zipf_coefficient=0.8, keys_per_command=2,
        knobs={"trace_sample_rate": 1.0},
    )
    again = ScenarioSpec.from_dict(json.loads(json.dumps(spec.to_dict())))
    assert again == spec
    path = tmp_path / "spec.json"
    path.write_text(json.dumps(spec.to_dict()))
    assert load_spec(str(path)) == spec
    assert canonical_expansion(again) == canonical_expansion(spec)


def test_spec_validation():
    with pytest.raises(ValueError, match="unknown protocol"):
        ScenarioSpec(name="x", protocols=("paxos9000",))
    with pytest.raises(ValueError, match="timeline"):
        ScenarioSpec(name="x", timeline="cloud")
    with pytest.raises(ValueError, match="sim-only"):
        ScenarioSpec(name="x", timeline="run", fault_plan={"seed": 1})
    with pytest.raises(ValueError, match="unknown spec field"):
        ScenarioSpec.from_dict({"name": "x", "bogus_knob": 1})
    with pytest.raises(ValueError, match="placement mode"):
        expand(tiny_spec(placement={"mode": "teleport"}))


# --- knee detection (pure) ---


def _pts(pairs):
    return [
        {"offered_cmds_per_s": o, "goodput_cmds_per_s": g} for o, g in pairs
    ]


def test_detect_knee_unsaturated():
    assert detect_knee(_pts([(10, 10), (20, 19.5), (40, 39)])) is None


def test_detect_knee_efficiency_threshold():
    # third point's efficiency (0.625) drops under 75% of the first
    # point's (1.0, capped)
    assert detect_knee(_pts([(10, 10), (20, 19), (40, 25)])) == 2


def test_detect_knee_efficiency_is_relative():
    # a constant 0.5 efficiency is a fixed serving-span tail (finite
    # open-loop run), not saturation: the first point calibrates it out
    assert detect_knee(_pts([(10, 5), (20, 10), (40, 20)])) is None
    # but a *drop* against that calibration is saturation
    assert detect_knee(_pts([(10, 5), (20, 10), (40, 12)])) == 2


def test_detect_knee_flat_curve():
    # goodput stops growing while offered doubles: knee even though
    # each point individually clears the efficiency bar (the 10 -> 11
    # step stays under the 20% offered-growth floor, so only the
    # doubling step can trip the flatness rule)
    assert detect_knee(_pts([(10, 9), (11, 9.1), (24, 9.2)])) == 2


def test_detect_knee_ignores_closed_loop_points():
    points = _pts([(None, 50), (None, 60)])
    assert detect_knee(points) is None


# --- satellite: zipf multi-shard fraction as a planner input ---


def test_zipf_expansion_reports_multi_shard_fraction():
    from fantoch_tpu.bin.shard_distribution import compute_distribution

    spec = tiny_spec(
        key_gen="zipf", zipf_coefficient=0.7, keys_per_command=2,
        keys_per_shard=1000, planner_shard_count=4,
    )
    workload = expand(spec)["workload"]
    assert workload["shard_count"] == 4
    assert workload["multi_shard_pct"] > 0
    assert workload["multi_key_pct"] > workload["multi_shard_pct"] - 1e-9
    # exactly the bin/shard_distribution computation, same seed
    direct = compute_distribution(
        shard_count=4, keys_per_command=2, coefficient=0.7,
        keys_per_shard=1000, commands=2000, seed=spec.seed,
    )
    assert workload["multi_shard_pct"] == direct["multi_shard_pct"]
    # conflict_rate specs report the rate instead
    plain = expand(tiny_spec())["workload"]
    assert "multi_shard_pct" not in plain
    assert plain["conflict_rate"] == 50


# --- satellite: placement search through a spec ---


def test_placement_search_deterministic_and_beats_identity():
    planet, (a, b, c, d, z) = synthetic_planet()
    spec = tiny_spec(
        protocols=("epaxos", "fpaxos"),
        placement={
            "mode": "search",
            "candidates": ["A", "B", "C", "D", "Z"],
            "clients": ["B", "C", "D"],
            "objective": "mean",
        },
    )
    first = expand(spec, planet)
    second = expand(spec, planet)
    assert first == second  # search output deterministic for spec+seed
    for site_key in ("epaxos_n3_f1", "fpaxos_n3_f1"):
        placement = first["placements"][site_key]
        # identity placement is the first 3 candidates (A, B, C); with
        # the clients at B/C/D the searched config must do strictly
        # better on the asymmetric line (B, C, D hugs the clients)
        assert placement["identity_regions"] == ["A", "B", "C"]
        assert placement["objective_ms"] < placement["identity_objective_ms"]
        assert "Z" not in placement["regions"]  # the outlier never helps
    # the cells inherit the searched regions
    for cell in first["cells"]:
        key = f"{cell['protocol']}_n{cell['n']}_f{cell['f']}"
        assert cell["regions"] == first["placements"][key]["regions"]


def test_pinned_placement_mode():
    planet, _ = synthetic_planet()
    spec = tiny_spec(
        placement={"mode": "regions", "regions": ["B", "C", "D"],
                   "clients": ["A"]},
    )
    manifest = expand(spec, planet)
    cell = manifest["cells"][0]
    assert cell["regions"] == ["B", "C", "D"]
    assert cell["client_regions"] == ["A"]


# --- end-to-end: run matrix -> curves artifact ---


def test_sim_scenario_end_to_end(tmp_path):
    from fantoch_tpu.plot.db import ResultsDB, load_curves

    spec = tiny_spec()
    out = str(tmp_path / "obs")
    doc = run_scenario(spec, out, render=False)
    # curves.json round-trips byte-exactly through plot/db
    assert load_curves(os.path.join(out, "curves.json")) == doc
    # expansion.json holds the canonical bytes
    with open(os.path.join(out, "expansion.json")) as fh:
        assert fh.read().rstrip("\n") == canonical_expansion(spec)
    (curve,) = doc["curves"]
    assert [p["offered_cmds_per_s"] for p in curve["points"]] == [50.0, 3200.0]
    for point in curve["points"]:
        assert point["goodput_cmds_per_s"] > 0
        assert point["p50_ms"] <= point["p95_ms"] <= point["p99_ms"]
    # 60 commands over a WAN commit-latency span cap goodput far below
    # the 3200/s offered point: the sim timeline saturates for real
    assert curve["knee_index"] == 1
    assert curve["knee"]["goodput_cmds_per_s"] < 0.75 * 3200
    # typed SLO verdicts for every cell
    assert [v["pass"] for v in curve["slo"]] == [True, True]
    assert curve["slo"][0]["checks"]["p99_ms"]["target"] == 5000.0
    # the per-cell obs dirs are a queryable ResultsDB root
    db = ResultsDB(out)
    assert len(db) == 2
    (fast,) = db.search(rate_cmds_per_s=50.0)
    assert fast.config["protocol"] == "epaxos"
    assert fast.outcome["goodput_cmds_per_s"] == curve["points"][0][
        "goodput_cmds_per_s"
    ]
    # telemetry captured per cell
    assert os.path.exists(os.path.join(out, fast.name, "telemetry.jsonl"))


def test_sim_trace_byte_identity(tmp_path):
    """Same spec + seed => byte-identical per-cell traces on the sim
    timeline (the observability determinism contract)."""
    spec = tiny_spec(
        rates=(200.0,), knobs={"trace_sample_rate": 1.0},
    )
    doc_a = run_scenario(spec, str(tmp_path / "a"), render=False)
    doc_b = run_scenario(spec, str(tmp_path / "b"), render=False)
    assert doc_a == doc_b
    cell = "epaxos_n3_f1_r200"
    trace_a = (tmp_path / "a" / cell / "trace.jsonl").read_bytes()
    trace_b = (tmp_path / "b" / cell / "trace.jsonl").read_bytes()
    assert trace_a and trace_a == trace_b


def test_fault_plan_cell(tmp_path):
    """A spec-carried FaultPlan reaches the sim nemesis (slow process)
    and the run still completes every command."""
    from fantoch_tpu.sim.faults import FaultPlan

    plan = FaultPlan(seed=3).with_slow_process(
        process_id=1, slow_ms=50, from_ms=0, until_ms=10_000
    )
    spec = tiny_spec(
        rates=(100.0,),
        fault_plan=plan.to_dict(),
        extra_sim_time_ms=5000,
    )
    doc = run_scenario(spec, str(tmp_path / "obs"), render=False)
    (point,) = doc["curves"][0]["points"]
    assert point["commands"] == 10 * 2 * 3  # cmds x cpp x regions
    assert point["goodput_cmds_per_s"] > 0


def test_scenario_cli_and_obs_curves(tmp_path, capsys):
    """bin/scenario expand|run + bin/obs curves drive the whole plane
    in-process (the make scenario-smoke shape)."""
    from fantoch_tpu.bin import obs, scenario

    spec = tiny_spec()
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec.to_dict()))
    out = str(tmp_path / "obs")

    assert scenario.main(["expand", str(spec_path)]) == 0
    expansion_text = capsys.readouterr().out.strip()
    assert expansion_text == canonical_expansion(spec)

    assert scenario.main(["run", str(spec_path), "--out", out,
                          "--no-render"]) == 0
    capsys.readouterr()

    assert obs.main(["curves", out]) == 0
    report = capsys.readouterr().out
    assert "knee offered/s" in report
    assert "slo PASS epaxos_n3_f1_r50" in report

    # a violated SLO turns the exit code
    strict = tiny_spec(slo={"p99_ms": 0.001})
    strict_path = tmp_path / "strict.json"
    strict_path.write_text(json.dumps(strict.to_dict()))
    strict_out = str(tmp_path / "strict")
    assert scenario.main(["run", str(strict_path), "--out", strict_out,
                          "--no-render"]) == 1
    capsys.readouterr()
    assert obs.main(["curves", strict_out]) == 1
