"""Oracle parity suite for the device-resident graph plane
(executor/graph/graph_plane.DeviceGraphPlane) against the host-column
``BatchedDependencyGraph`` twin, plus the three-planes-on-one-base
regression rows for the shared DevicePlane and the unified kernel-size
gate (Config.graph_kernel_threshold).

The parity contract is the agreement contract conflicting commands care
about: identical executed set and identical per-key execution order,
across shuffled multi-feed delivery with MISSING deps, cycles,
noop/executed notifications, capacity compaction, pow2 growth, and
snapshot/restore with the single-re-upload invariant.  The depth-K rows
prove the serving claim: feeds pipelined K deep drain bit-for-bit the
depth-1 order, with ``resident_uploads == 1`` (only new-row deltas
travel host->device after warmup).
"""

import itertools
import pickle
import random

import numpy as np
import pytest

from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
from fantoch_tpu.core.ids import process_ids
from fantoch_tpu.executor.device_plane import DevicePlane
from fantoch_tpu.executor.graph.batched import BatchedDependencyGraph, key_hash
from fantoch_tpu.executor.graph.graph_plane import DeviceGraphPlane
from fantoch_tpu.executor.pred_plane import DevicePredPlane
from fantoch_tpu.executor.table_plane import DeviceTablePlane
from fantoch_tpu.protocol.common.graph_deps import Dependency

TIME = RunTime()
SHARD = 0

HOST_CFG = Config(3, 1, host_native_resolver=False)
PLANE_CFG = Config(
    3, 1, host_native_resolver=False, batched_graph_executor=True,
    device_graph_plane=True,
)


def dep(dot):
    return Dependency(dot, frozenset({SHARD}))


def make_cmd(dot, keys):
    rifl = Rifl(dot.source, dot.sequence)
    return Command.from_keys(rifl, SHARD, {k: (KVOp.put(""),) for k in keys})


def run_feeds(config, feeds, batch_feed=True):
    """Drive (dot, keys, dep_dots) feeds through a fresh graph; returns
    the per-key rifl execution order (the agreement contract)."""
    graph = BatchedDependencyGraph(1, SHARD, config)
    order = {}
    pending = set()

    def drain():
        for ready in graph.commands_to_execute():
            pending.remove(ready.rifl)
            for key in ready.keys(SHARD):
                order.setdefault(key, []).append(ready.rifl)

    for feed in feeds:
        adds = []
        for dot, keys, dep_dots in feed:
            cmd = make_cmd(dot, keys)
            pending.add(cmd.rifl)
            adds.append((dot, cmd, [dep(d) for d in dep_dots]))
        if batch_feed:
            graph.handle_add_batch(adds, TIME)
        else:
            for dot, cmd, deps in adds:
                graph.handle_add(dot, cmd, deps, TIME)
        drain()
    assert not pending, f"not all commands executed: {pending}"
    return order


def random_adds(n, events_per_process, rng):
    """Random dep graphs with non-transitive conflicts and 2-cycles (the
    test_graph_executor generator)."""
    possible_keys = ["A", "B", "C", "D"]
    dots = [
        Dot(pid, seq)
        for pid in process_ids(SHARD, n)
        for seq in range(1, events_per_process + 1)
    ]
    keys = {}
    deps = {dot: set() for dot in dots}
    for dot in dots:
        keys[dot] = set(rng.sample(possible_keys, 2))
    for left, right in itertools.combinations(dots, 2):
        if not (keys[left] & keys[right]):
            continue
        if left.source == right.source:
            if left.sequence < right.sequence:
                deps[right].add(left)
            else:
                deps[left].add(right)
        else:
            choice = rng.randrange(3)
            if choice in (0, 2):
                deps[left].add(right)
            if choice in (1, 2):
                deps[right].add(left)
    return [(dot, sorted(keys[dot]), deps[dot]) for dot in dots]


def chop(rng, args):
    """Shuffle and split into random feed batches (multi-feed residuals:
    deps routinely arrive after their dependents, leaving missing-blocked
    rows resident across feeds)."""
    shuffled = args[:]
    rng.shuffle(shuffled)
    feeds = []
    at = 0
    while at < len(shuffled):
        size = rng.randrange(1, 6)
        feeds.append(shuffled[at : at + size])
        at += size
    return feeds


def test_graph_plane_oracle_parity_multi_feed_residuals():
    """Identical per-key execution order vs the host-column twin across
    shuffled multi-feed schedules with MISSING deps and (mutual) cycles —
    batched and per-add delivery both."""
    rng = random.Random(3)
    for _trial in range(6):
        args = random_adds(2, 3, rng)
        feeds = chop(rng, args)
        host = run_feeds(HOST_CFG, feeds)
        plane_batched = run_feeds(PLANE_CFG, feeds)
        plane_scalar = run_feeds(PLANE_CFG, feeds, batch_feed=False)
        assert plane_batched == host
        assert plane_scalar == host


def test_graph_plane_arrays_seam_matches_tuple_feed():
    """handle_add_arrays (the protocol commit-buffer seam) is
    behaviorally identical to per-command adds on the plane, and the
    array drain (take_order_arrays) matches the object drain."""
    batch = 48
    src = np.ones(batch, dtype=np.int64)
    seq = np.arange(1, batch + 1, dtype=np.int64)
    key = np.fromiter(
        (key_hash(f"k{i % 4}") for i in range(batch)), np.int32, batch
    )
    last = {}
    dd = np.full((batch, 1), -1, dtype=np.int64)
    for i in range(batch):
        prev = last.get(int(key[i]))
        if prev is not None:
            dd[i, 0] = (1 << 32) | prev
        last[int(key[i])] = i + 1
    cmds = [make_cmd(Dot(1, i + 1), [f"k{i % 4}"]) for i in range(batch)]

    g_arrays = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    g_arrays.handle_add_arrays(src, seq, key, dd, cmds, TIME)
    got = [c.rifl for c in g_arrays.commands_to_execute()]

    g_tuple = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    for i in range(batch):
        deps = (
            [dep(Dot(1, int(dd[i, 0]) & 0xFFFFFFFF))] if dd[i, 0] >= 0 else []
        )
        g_tuple.handle_add(Dot(1, i + 1), make_cmd(Dot(1, i + 1), [f"k{i % 4}"]), deps, TIME)
    want = [c.rifl for c in g_tuple.commands_to_execute()]
    # per-key orders must agree (whole-batch interleaving may differ)
    by_key_got = {}
    by_key_want = {}
    for r in got:
        by_key_got.setdefault((r.sequence - 1) % 4, []).append(r)
    for r in want:
        by_key_want.setdefault((r.sequence - 1) % 4, []).append(r)
    assert by_key_got == by_key_want

    g_order = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    g_order.record_order_arrays = True
    g_order.handle_add_arrays(src, seq, key, dd, cmds, TIME)
    g_order.resolve_now(TIME)
    o_src, o_seq = g_order.take_order_arrays()
    assert sorted(o_seq.tolist()) == list(range(1, batch + 1))
    assert not g_order.commands_to_execute()  # no object mirror kept


def test_graph_plane_noop_unblocks_waiters():
    """A recovered-noop commit patches every MISSING cell waiting on the
    dot to TERMINAL — dependents drain exactly like the host twin."""
    ghost = Dot(2, 5)
    for config in (HOST_CFG, PLANE_CFG):
        g = BatchedDependencyGraph(1, SHARD, config)
        g.handle_add(Dot(1, 1), make_cmd(Dot(1, 1), ["a"]), [dep(ghost)], TIME)
        g.handle_add(
            Dot(1, 2), make_cmd(Dot(1, 2), ["a"]), [dep(Dot(1, 1))], TIME
        )
        assert g.commands_to_execute() == []
        g.handle_noop(ghost, TIME)
        got = [c.rifl for c in g.commands_to_execute()]
        assert got == [Rifl(1, 1), Rifl(1, 2)]
        # the noop dot counts as executed (GraphExecuted/GC seam)
        assert g._frontier.contains(2, 5)


def test_graph_plane_stuck_cycle_host_oracle_parity():
    """A one-directional 3-cycle (no mutual edges) surfaces as a stuck
    residue; the plane's host-oracle follow-up emits it and wakes
    dependents — same order as the host-column twin."""
    d1, d2, d3, d4 = Dot(1, 1), Dot(2, 1), Dot(3, 1), Dot(1, 2)
    feeds = [
        [(d1, ["a", "b"], {d3})],
        [(d2, ["a", "b"], {d1})],
        # d4 waits on the whole cycle (emits via the follow-up dispatch)
        [(d3, ["a", "b"], {d2}), (d4, ["a", "b"], {d1, d2, d3})],
    ]
    host = run_feeds(HOST_CFG, feeds)
    plane = run_feeds(PLANE_CFG, feeds)
    assert plane == host
    assert [r.source for r in host["a"]] == [1, 2, 3, 1]


def test_graph_plane_snapshot_restore_single_reupload():
    """The restart seam: a pickled graph re-materializes its resident
    backlog from the host mirror on the FIRST dispatch after restore —
    exactly one counted re-upload — and missing-blocked residents
    survive with their waiter cells intact."""
    g = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    ghost = Dot(2, 1)
    g.handle_add(Dot(1, 1), make_cmd(Dot(1, 1), ["K"]), [dep(ghost)], TIME)
    g.handle_add(Dot(1, 2), make_cmd(Dot(1, 2), ["K"]), [dep(Dot(1, 1))], TIME)
    assert g.commands_to_execute() == []
    restored = pickle.loads(pickle.dumps(g))
    plane = restored._plane
    assert isinstance(plane, DeviceGraphPlane)
    uploads = plane.resident_uploads
    restored.handle_add(ghost, make_cmd(ghost, ["K"]), [], TIME)
    got = [c.rifl for c in restored.commands_to_execute()]
    assert got == [Rifl(2, 1), Rifl(1, 1), Rifl(1, 2)]
    assert plane.resident_uploads - uploads == 1, (
        "restore must cost exactly ONE re-upload"
    )
    # the restored plane shares the graph's frontier/metrics objects
    # (pickle preserves the aliasing within one snapshot)
    assert plane._frontier is restored._frontier
    assert plane._metrics is restored._metrics


def _shrink_plane(plane, cap):
    """Shrink a fresh plane's window so compaction paths exercise at
    test scale (the pred-plane test move)."""
    assert plane._next_slot == 0 and plane._resident is None
    plane._cap = cap
    for name in ("_slot_src", "_slot_seq", "_slot_tms", "_slot_key",
                 "_slot_general", "_exec_host"):
        setattr(plane, name, getattr(plane, name)[:cap].copy())
    plane._slot_deps = plane._slot_deps[:cap].copy()


def test_graph_plane_compaction_preserves_blocked_rows():
    """Window exhaustion re-packs pending rows to the bottom (dep cells
    and waiter cells remapped through the LUT): a missing-blocked row
    survives arbitrarily many compactions and executes when its dep
    finally commits; a duplicate commit of a long-executed dot still
    trips the loud assert after the re-pack."""
    g = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    plane = g._plane
    _shrink_plane(plane, 16)
    ghost = Dot(3, 1)
    g.handle_add(Dot(1, 1000), make_cmd(Dot(1, 1000), ["B"]), [dep(ghost)], TIME)
    assert g.commands_to_execute() == []
    last = None
    for i in range(50):
        d = Dot(1, i + 1)
        deps = [dep(last)] if last else []
        last = d
        g.handle_add(d, make_cmd(d, ["K"]), deps, TIME)
        assert [c.rifl for c in g.commands_to_execute()] == [Rifl(1, i + 1)]
    assert plane.stats["compactions"] >= 2
    assert plane.pending_count == 1
    assert plane.resident_uploads == 1 + plane.stats["compactions"] + plane.grows
    g.handle_add(ghost, make_cmd(ghost, ["B"]), [], TIME)
    got = [c.rifl for c in g.commands_to_execute()]
    assert got == [Rifl(3, 1), Rifl(1, 1000)]
    with pytest.raises(AssertionError, match="duplicate"):
        g.handle_add(Dot(1, 5), make_cmd(Dot(1, 5), ["K"]), [], TIME)
        g.commands_to_execute()


def test_graph_plane_width_growth_keeps_pending_state():
    """Dep fan-out beyond the resident width re-pads the dep matrix from
    the host mirrors (a counted grow) without losing blocked rows;
    already-executed deps encode to nothing and never widen."""
    g = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    plane = g._plane
    # executed deps: no widening
    prev = []
    for i in range(6):
        d = Dot(1, i + 1)
        g.handle_add(d, make_cmd(d, ["W"]), [], TIME)
        prev.append(d)
        g.commands_to_execute()
    g.handle_add(Dot(2, 1), make_cmd(Dot(2, 1), ["W"]), [dep(x) for x in prev], TIME)
    assert [c.rifl for c in g.commands_to_execute()] == [Rifl(2, 1)]
    assert plane._width == 4 and plane.grows == 0

    # pending deps: widen and survive
    g2 = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    plane2 = g2._plane
    ghost = Dot(3, 9)
    prev = []
    for i in range(6):
        d = Dot(1, i + 1)
        g2.handle_add(d, make_cmd(d, ["W"]), [dep(ghost)], TIME)
        prev.append(d)
    assert g2.commands_to_execute() == []
    g2.handle_add(Dot(2, 1), make_cmd(Dot(2, 1), ["W"]), [dep(x) for x in prev], TIME)
    assert g2.commands_to_execute() == []
    assert plane2._width == 8 and plane2.grows >= 1
    g2.handle_add(ghost, make_cmd(ghost, ["W"]), [], TIME)
    assert len(g2.commands_to_execute()) == 8


def _serving_rows(total=1024, keys=32, seed=7):
    """Single-key latest-per-key chains in commit order: the EPaxos
    serving shape (one dep per command, arrival mostly backward)."""
    rng = np.random.default_rng(seed)
    last = {}
    rows = []
    for i in range(total):
        k = int(rng.integers(0, keys))
        prev = last.get(k)
        last[k] = i + 1
        rows.append((1, i + 1, key_hash(f"sk{k}"), ((1 << 32) | prev) if prev else -1))
    return rows


def _serve_pipelined(depth, total=1024, feed=64):
    """The depth-K pipelined EPaxos serving loop through the plane:
    feeds dispatched up to K-1 rounds ahead, the order arrays drained as
    rounds retire, the tail flushed at end-of-stream."""
    g = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    g.record_order_arrays = True
    g._plane.pipeline_depth = depth
    g._plane.reserve(total)
    rows = _serving_rows(total)
    chunks = []
    for at in range(0, total, feed):
        chunk = rows[at : at + feed]
        src = np.array([r[0] for r in chunk], np.int64)
        seq = np.array([r[1] for r in chunk], np.int64)
        key = np.array([r[2] for r in chunk], np.int32)
        dd = np.array([[r[3]] for r in chunk], np.int64)
        cmds = [make_cmd(Dot(1, int(s)), ["x"]) for s in seq]
        g.handle_add_arrays(src, seq, key, dd, cmds, TIME)
        g.resolve_now(TIME)
        chunks.append(g.take_order_arrays())
    g.flush_plane_pipeline(TIME)
    chunks.append(g.take_order_arrays())
    return (
        np.concatenate([c[0] for c in chunks]),
        np.concatenate([c[1] for c in chunks]),
        g._plane,
    )


def test_graph_plane_pipelined_depths_bit_for_bit():
    """The depth-K pipelined serving loop: depths 1/2/3 drain the
    bit-for-bit identical execution order, and steady-state residency
    holds — resolves issue ZERO backlog re-uploads after the lazy
    initial materialization (only new-row deltas travel host->device)."""
    s1, q1, p1 = _serve_pipelined(1)
    s2, q2, p2 = _serve_pipelined(2)
    s3, q3, p3 = _serve_pipelined(3)
    assert len(q1) == 1024
    assert (s1 == s2).all() and (q1 == q2).all()
    assert (s1 == s3).all() and (q1 == q3).all()
    for plane in (p1, p2, p3):
        assert plane.resident_uploads == 1, (
            "steady-state serving must never re-upload the backlog"
        )
        assert plane.stats["compactions"] == 0
        assert plane.dispatches >= 16


def test_graph_plane_nonstructure_modes_parity():
    """The large-window modes (the keyed fast kernel without structure
    metrics + the resident peel-and-compact general path), forced at
    test scale via the unified kernel-size gate: identical per-key
    orders vs the host twin on shuffled feeds with missing deps and
    multi-key rows."""
    low = Config(
        3, 1, host_native_resolver=False, batched_graph_executor=True,
        device_graph_plane=True,
        graph_kernel_threshold=64,  # < the 1024-slot window: no structure
    )
    rng = random.Random(11)
    for _trial in range(3):
        args = random_adds(2, 3, rng)
        feeds = chop(rng, args)
        assert run_feeds(low, feeds) == run_feeds(HOST_CFG, feeds)
    # single-key chains ride the non-structure keyed kernel
    rng2 = np.random.default_rng(3)
    last = {}
    chain = []
    for i in range(96):
        k = int(rng2.integers(0, 8))
        prev = last.get(k)
        last[k] = Dot(1, i + 1)
        chain.append(
            (Dot(1, i + 1), [f"sk{k}"], {prev} if prev is not None else set())
        )
    feeds = [chain[at : at + 16] for at in range(0, 96, 16)]
    assert run_feeds(low, feeds) == run_feeds(HOST_CFG, feeds)


def test_graph_plane_monitor_watchdog():
    """The liveness watchdog on the plane: overdue missing dots surface
    for nudge_recovery, a typed StalledExecutionError fires past
    Config.executor_pending_fail_ms, and a lost execution (a waiter dot
    executed in the frontier with no wake) panics as
    pending-without-missing — the host twin's contract."""
    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.errors import StalledExecutionError

    cfg = PLANE_CFG.with_(executor_pending_fail_ms=5000)
    time = SimTime()
    g = BatchedDependencyGraph(1, SHARD, cfg)
    ghost = Dot(2, 7)
    g.handle_add(Dot(1, 1), make_cmd(Dot(1, 1), ["a"]), [dep(ghost)], time)
    assert g.commands_to_execute() == []
    # young: nothing to report yet
    assert not g.monitor_pending(SimTime(100))
    # old but missing-blocked: nudge the missing dot
    assert g.monitor_pending(SimTime(2000)) == {ghost}
    # past the fail bound: typed stall naming the missing dep
    with pytest.raises(StalledExecutionError) as err:
        g.monitor_pending(SimTime(6000))
    assert ghost in err.value.missing[Dot(1, 1)]

    # lost execution: the ghost lands in the frontier without a wake
    g2 = BatchedDependencyGraph(1, SHARD, PLANE_CFG)
    g2.handle_add(Dot(1, 1), make_cmd(Dot(1, 1), ["a"]), [dep(ghost)], SimTime(0))
    assert g2.commands_to_execute() == []
    g2._frontier.add(ghost.source, ghost.sequence)
    with pytest.raises(AssertionError, match="without missing"):
        g2.monitor_pending(SimTime(5000))


def test_graph_plane_device_counters_seam():
    """The Executor.device_counters() seam (the table/pred planes'
    contract): dispatch/occupancy/upload tallies present and sane, None
    when the plane is off, capacity max-folded as a gauge."""
    from fantoch_tpu.executor.graph.executor import GraphExecutor, GraphAdd
    from fantoch_tpu.observability.device import merge_counters

    ex = GraphExecutor(1, SHARD, PLANE_CFG)
    for i in range(4):
        ex.handle(
            GraphAdd(Dot(1, i + 1), make_cmd(Dot(1, i + 1), ["c"]), set()),
            TIME,
        )
    counters = ex.device_counters()
    assert counters["graph_plane_dispatches"] >= 1
    assert counters["graph_plane_new_rows"] == 4
    assert counters["graph_plane_resident_uploads"] == 1
    assert counters["graph_plane_kernel_ms"] > 0
    assert counters["graph_plane_slot_capacity"] == ex.graph._plane._cap
    host_ex = GraphExecutor(
        1, SHARD, HOST_CFG.with_(batched_graph_executor=True)
    )
    assert host_ex.device_counters() is None
    folded = merge_counters({}, counters)
    folded = merge_counters(folded, counters)
    assert folded["graph_plane_new_rows"] == 8
    # capacity is a gauge: max-folded, never summed
    assert folded["graph_plane_slot_capacity"] == counters["graph_plane_slot_capacity"]


def test_graph_kernel_threshold_precedence(monkeypatch):
    """The unified kernel-size gate: explicit config beats the env var
    beats the built-in 4096 (the Config.table_kernel_threshold pattern,
    resolved through the shared device_plane.resolve_threshold)."""
    monkeypatch.delenv("FANTOCH_GRAPH_KERNEL_THRESHOLD", raising=False)
    g = BatchedDependencyGraph(1, SHARD, HOST_CFG)
    assert g._structure_threshold == 4096
    monkeypatch.setenv("FANTOCH_GRAPH_KERNEL_THRESHOLD", "123")
    g = BatchedDependencyGraph(1, SHARD, HOST_CFG)
    assert g._structure_threshold == 123
    g = BatchedDependencyGraph(
        1, SHARD, HOST_CFG.with_(graph_kernel_threshold=77)
    )
    assert g._structure_threshold == 77


def test_graph_threshold_both_branches_agree():
    """Both sides of the kernel-size gate produce identical per-key
    orders on the same workload (the table_kernel_threshold both-branch
    agreement test applied to the graph gate): a threshold of 1 forces
    the above-threshold branches (arrival fast path / resident general /
    no-structure kernels) where the default keeps the exact-structure
    branches."""
    rng = random.Random(19)
    args = random_adds(2, 3, rng)
    feeds = chop(rng, args)
    above = Config(3, 1, host_native_resolver=False, graph_kernel_threshold=1)
    assert run_feeds(above, feeds) == run_feeds(HOST_CFG, feeds)


def test_graph_plane_multi_shard_rejected():
    with pytest.raises(ValueError, match="shard_count"):
        BatchedDependencyGraph(
            1, SHARD,
            Config(3, 1, shard_count=2, batched_graph_executor=True,
                   device_graph_plane=True),
        )


def test_three_planes_share_the_device_plane_base():
    """The ROADMAP item-5 completion: votes-table, predecessors AND the
    graph backlog are the SAME machinery — one base owning buffer
    lifecycle, durability and counters — not three hand-rolled copies."""
    for klass in (DeviceTablePlane, DevicePredPlane, DeviceGraphPlane):
        assert issubclass(klass, DevicePlane)
        for member in (
            "_materialize", "_grow", "_upload", "_fetch_state",
            "_count_dispatch",
        ):
            assert getattr(klass, member) is getattr(DevicePlane, member), (
                f"{klass.__name__}.{member} forked from the base"
            )
    # the graph plane drains its in-flight ring before pickling but
    # otherwise keeps the base's snapshot protocol
    assert DeviceGraphPlane.__setstate__ is DevicePlane.__setstate__


# ---------------------------------------------------------------------------
# serving-path wiring: the sim and the process_runner executor pools
# ---------------------------------------------------------------------------


def test_epaxos_sim_with_device_graph_plane():
    """End-to-end EPaxos over the sim with the plane on: same per-key
    agreement across replicas (the sim_test harness drives the real
    protocol/executor stack — commits cross the boundary as arrays and
    order through the resident backlog)."""
    from harness import sim_test

    from fantoch_tpu.protocol import EPaxos

    sim_test(
        EPaxos,
        Config(
            n=3, f=1, batched_graph_executor=True, device_graph_plane=True,
            host_native_resolver=False,
        ),
        keys_per_command=1,
    )


def test_atlas_sim_with_device_graph_plane():
    from harness import sim_test

    from fantoch_tpu.protocol import Atlas

    sim_test(
        Atlas,
        Config(
            n=3, f=1, batched_graph_executor=True, device_graph_plane=True,
            host_native_resolver=False,
        ),
        keys_per_command=1,
    )


def test_run_epaxos_localhost_through_graph_plane():
    """The serving path: a 3-process localhost TCP EPaxos cluster whose
    executor pools order through the resident graph plane
    (process_runner -> GraphExecutor -> BatchedDependencyGraph ->
    DeviceGraphPlane), with cross-replica per-key agreement and the
    plane counters visible through the runtime's device-counter fold."""
    from test_run_localhost import run_cluster

    from fantoch_tpu.protocol import EPaxos

    _slow, runtimes = run_cluster(
        EPaxos,
        Config(
            n=3, f=1, batched_graph_executor=True, device_graph_plane=True,
            host_native_resolver=False,
        ),
        keys_per_command=1,
        return_runtimes=True,
    )
    for runtime in runtimes.values():
        counters = runtime._device_counters()
        assert counters["graph_plane_dispatches"] > 0
        assert (
            counters["graph_plane_resident_uploads"]
            <= 1
            + counters["graph_plane_compactions"]
            + counters["graph_plane_grows"]
        )
