"""VotesTable stability logic + TableExecutor flow, mirroring
fantoch_ps/src/executor/table/mod.rs:273-450 (majority-quorum table tests:
ops execute exactly when their timestamp is stable, in (clock, dot) order
on every delivery permutation)."""

import itertools

from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, Rifl
from fantoch_tpu.core.kvs import KVOp
from fantoch_tpu.executor.table import TableExecutor, TableVotes, VotesTable
from fantoch_tpu.protocol.common.table_clocks import VoteRange

SHARD = 0


def table(n=5, threshold=3) -> VotesTable:
    return VotesTable("K", 1, SHARD, n, threshold)


def test_nothing_stable_without_threshold_frontiers():
    t = table()
    # n=5, threshold=3: frontiers [1,0,0,0,0] -> stable clock 0
    t.add(Dot(1, 1), 1, Rifl(10, 1), (KVOp.put("x"),), [VoteRange(1, 1, 1)])
    assert t.stable_clock() == 0
    assert t.stable_ops() == []
    # second frontier at 1: sorted [0,0,0,1,1] -> index 5-3=2 -> 0... still 0
    t.add_votes([VoteRange(2, 1, 1)])
    assert t.stable_clock() == 0
    # third frontier at 1: sorted [0,0,1,1,1] -> stable 1 -> op executes
    t.add_votes([VoteRange(3, 1, 1)])
    assert t.stable_clock() == 1
    assert [rifl for rifl, _ in t.stable_ops()] == [Rifl(10, 1)]
    assert t.stable_ops() == []


def test_equal_clocks_break_ties_by_dot():
    t = table(n=3, threshold=2)
    op = (KVOp.put("x"),)
    t.add(Dot(2, 1), 1, Rifl(20, 1), op, [VoteRange(2, 1, 1)])
    t.add(Dot(1, 1), 1, Rifl(10, 1), op, [VoteRange(1, 1, 1)])
    assert [r for r, _ in t.stable_ops()] == [Rifl(10, 1), Rifl(20, 1)]


def test_ops_above_stable_clock_stay_buffered():
    # only ops with clock <= stable_clock execute; an op at stable+1 stays
    # buffered until stability advances (mod.rs:200-244 split_off bound)
    t = table(n=3, threshold=2)
    op = (KVOp.put("x"),)
    t.add(Dot(1, 1), 1, Rifl(10, 1), op, [VoteRange(1, 1, 1), VoteRange(2, 1, 1)])
    t.add(Dot(1, 2), 2, Rifl(10, 2), op, [VoteRange(1, 2, 2)])
    assert [r for r, _ in t.stable_ops()] == [Rifl(10, 1)]


def test_permutations_agree():
    """All vote-delivery permutations execute the same final order.

    The history is protocol-consistent (every command at clock c carries a
    fast quorum's votes covering c): B@1 voted by {p2,p3}, A@2 by {p1,p2},
    C@3 by {p3,p1} — a command's own votes pin the frontier gap below its
    clock, so no permutation can stabilize a higher clock early.
    """
    op = (KVOp.put("x"),)
    adds = [
        (Dot(1, 1), 2, Rifl(10, 1), [VoteRange(1, 1, 2), VoteRange(2, 2, 2)]),
        (Dot(2, 1), 1, Rifl(20, 1), [VoteRange(2, 1, 1), VoteRange(3, 1, 1)]),
        (Dot(3, 1), 3, Rifl(30, 1), [VoteRange(3, 2, 3), VoteRange(1, 3, 3)]),
    ]
    expected = None
    for perm in itertools.permutations(range(3)):
        t = table(n=3, threshold=2)
        executed = []
        for i in perm:
            dot, clock, rifl, votes = adds[i]
            t.add(dot, clock, rifl, op, votes)
            executed.extend(r for r, _ in t.stable_ops())
        assert len(executed) == 3, f"all ops stable: {perm} -> {executed}"
        if expected is None:
            expected = executed
        assert executed == expected, f"order differs for permutation {perm}"
    assert [r.source for r in expected] == [20, 10, 30]  # by (clock, dot)


def test_table_executor_end_to_end():
    config = Config(n=3, f=1)
    ex = TableExecutor(1, SHARD, config)
    rifl = Rifl(10, 1)
    ex.handle(
        TableVotes(
            Dot(1, 1), 1, rifl, "K", (KVOp.put("v"),),
            [VoteRange(1, 1, 1), VoteRange(2, 1, 1)],
        ),
        None,
    )
    result = ex.to_clients()
    assert result is not None and result.rifl == rifl and result.key == "K"
    assert result.op_results == (None,)
    assert ex.to_clients() is None
