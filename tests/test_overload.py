"""Overload-control plane tests: bounded queues + watermark credit gates,
admission control and typed load shedding, client backoff + deadline
budgets, the open-loop (Poisson) load instrument, the SlowProcess nemesis,
and the queue-gauge metrics export.

The reference bounds its channels and warn-then-BLOCKS producers
(fantoch/src/run/task/chan.rs:36-58); this plane warn-then-SHEDS at the
client edge and pauses socket readers in between (run/backpressure.py) —
these rows pin the contract: under sustained open-loop overload queue
depths stay under their bounds, sheds surface to clients as typed
Overloaded replies, backoff-retrying clients eventually complete, and the
system drains back to baseline latency after the burst.
"""

import asyncio
import random

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config
from fantoch_tpu.errors import DeadlineExceededError, OverloadedError
from fantoch_tpu.protocol import EPaxos, Newt
from fantoch_tpu.run.backpressure import (
    Backoff,
    BoundedQueue,
    DEFAULT_QUEUE_CAPACITY,
    OpenLoopPacer,
)
from fantoch_tpu.run.links import LinkState
from fantoch_tpu.run.pipeline import BoundedSubmitRing
from fantoch_tpu.sim.faults import FaultPlan

COMMANDS_PER_CLIENT = 10
CLIENTS_PER_PROCESS = 2


# --- bounded queue / watermark primitives ---


def test_bounded_queue_watermark_gate():
    async def scenario():
        queue = BoundedQueue("q", capacity=4)
        assert not queue.gated
        for i in range(4):
            queue.put_nowait(i)
        # gate closes AT the high watermark, counted once
        assert queue.gated and queue.pauses == 1
        # puts while closed are overflows (producers never block)
        queue.put_nowait(4)
        assert queue.overflows == 1 and queue.depth_hwm == 5
        # drains above the low watermark keep the gate closed (hysteresis)
        queue.get_nowait()
        queue.get_nowait()
        assert queue.gated
        # at/below low (capacity // 2 = 2) the gate re-opens
        queue.get_nowait()
        assert not queue.gated
        # wait_for_credit returns immediately once open
        await asyncio.wait_for(queue.wait_for_credit(), timeout=1)
        # gauges survive
        stats = queue.stats()
        assert stats["depth_hwm"] == 5 and stats["capacity"] == 4
        assert stats["pauses"] == 1 and stats["overflows"] == 1

    asyncio.run(scenario())


def test_bounded_queue_uncapped_never_gates():
    queue = BoundedQueue("q", capacity=None)
    for i in range(DEFAULT_QUEUE_CAPACITY + 10):
        queue.put_nowait(i)
    assert not queue.gated and queue.pauses == 0
    assert queue.depth_hwm == DEFAULT_QUEUE_CAPACITY + 10


def test_bounded_queue_credit_wakes_waiter():
    async def scenario():
        queue = BoundedQueue("q", capacity=2)
        queue.put_nowait("a")
        queue.put_nowait("b")
        assert queue.gated
        woke = asyncio.Event()

        async def reader():
            await queue.wait_for_credit()
            woke.set()

        task = asyncio.ensure_future(reader())
        await asyncio.sleep(0.01)
        assert not woke.is_set()
        queue.get_nowait()  # depth 1 == low -> gate opens
        await asyncio.wait_for(woke.wait(), timeout=1)
        task.cancel()

    asyncio.run(scenario())


def test_submit_ring_bounds_and_sheds():
    ring = BoundedSubmitRing(capacity=2)
    assert ring.try_push("a") and ring.try_push("b")
    assert not ring.try_push("c")  # at the bound -> refused
    assert ring.depth_hwm == 2 and len(ring) == 2
    # the shed tally belongs to the admission edge that sends the
    # Overloaded reply (single owner), not to try_push
    assert ring.sheds == 0
    ring.sheds += 1  # what the session's _shed does on refusal
    assert ring.popleft() == "a"
    assert ring.try_push("c")
    stats = ring.stats()
    assert stats["capacity"] == 2 and stats["sheds"] == 1
    # unbounded legacy mode
    unbounded = BoundedSubmitRing(capacity=None)
    for i in range(100):
        assert unbounded.try_push(i)
    assert unbounded.sheds == 0


def test_backoff_capped_with_jitter_and_hint_floor():
    backoff = Backoff(base_ms=10, factor=2.0, cap_ms=40, rng=random.Random(7))
    delays = [backoff.next_delay_ms() for _ in range(8)]
    # full jitter: everything under the cap, attempts grow the envelope
    assert all(0 <= d <= 40 for d in delays)
    # the server's retry-after hint floors the delay
    backoff.reset()
    assert backoff.next_delay_ms(retry_after_hint_ms=500) >= 500
    # seeded schedules are reproducible
    a = Backoff(base_ms=10, rng=random.Random(3))
    b = Backoff(base_ms=10, rng=random.Random(3))
    assert [a.next_delay_ms() for _ in range(5)] == [
        b.next_delay_ms() for _ in range(5)
    ]


def test_open_loop_pacer_poisson_deterministic():
    a = OpenLoopPacer(rate_per_s=100, seed=11)
    b = OpenLoopPacer(rate_per_s=100, seed=11)
    gaps_a = [a.next_gap_s() for _ in range(50)]
    gaps_b = [b.next_gap_s() for _ in range(50)]
    assert gaps_a == gaps_b
    assert OpenLoopPacer(rate_per_s=100, seed=12).next_gap_s() != gaps_a[0]
    # mean inter-arrival ~ 1/rate (loose: 50 samples)
    mean = sum(gaps_a) / len(gaps_a)
    assert 0.2 / 100 < mean < 5.0 / 100
    # fixed-interval mode unchanged
    fixed = OpenLoopPacer(interval_ms=20)
    assert fixed.next_gap_s() == 0.02


def test_typed_errors_and_config_validation():
    err = OverloadedError(depth=12, limit=8, retry_after_ms=150)
    assert err.retry_after_ms == 150 and "retry after 150ms" in str(err)
    dl = DeadlineExceededError(rifl="r", waited_ms=900, deadline_ms=500)
    assert "deadline exceeded" in str(dl)
    with pytest.raises(ValueError):
        Config(n=3, f=1, admission_limit=0)
    with pytest.raises(ValueError):
        Config(n=3, f=1, queue_capacity=1)
    with pytest.raises(ValueError):
        Config(n=3, f=1, overload_retry_after_ms=0)
    with pytest.raises(ValueError):
        Config(n=3, f=1, link_unacked_cap=-1)
    # 0 spellings are the legacy opt-outs, valid
    Config(n=3, f=1, queue_capacity=0, link_unacked_cap=0)


# --- links: unacked resend window cap ---


def test_link_unacked_cap():
    link = LinkState(2, ("127.0.0.1", 1), 0, rw=None, unacked_cap=4)
    for seq in range(1, 5):
        assert link.note_sent(seq, b"f")
        assert not link.over_unacked_cap()
    # the fifth unacked frame crosses the cap
    assert not link.note_sent(5, b"f")
    assert link.over_unacked_cap() and link.unacked_hwm == 5
    # acks trim the window back under the cap
    link.ack(3)
    assert not link.over_unacked_cap()
    # 0 = uncapped legacy
    uncapped = LinkState(2, ("127.0.0.1", 1), 0, rw=None, unacked_cap=0)
    for seq in range(1, 100):
        assert uncapped.note_sent(seq, b"f")
    assert not uncapped.over_unacked_cap()


def test_aggregate_pending_cancel_clears_state():
    """The deadline-shed cleanup seam (prelude.Unregister -> session ->
    AggregatePending.cancel): a withdrawn rifl leaves no aggregation
    entry and no buffered early partials behind."""
    from fantoch_tpu.core.command import Command
    from fantoch_tpu.core.ids import Rifl
    from fantoch_tpu.core.kvs import KVOp
    from fantoch_tpu.executor.aggregate import AggregatePending
    from fantoch_tpu.executor.base import ExecutorResult

    pending = AggregatePending(1, 0, buffer_early=True)
    rifl = Rifl(7, 1)
    cmd = Command.from_single(rifl, 0, "k", KVOp.put("v"))
    pending.wait_for(cmd)
    assert rifl in pending._pending
    pending.cancel(rifl)
    assert rifl not in pending._pending
    # early partials for a never-registered rifl are dropped too (with
    # the buffered-count bookkeeping kept consistent)
    early_rifl = Rifl(7, 2)
    pending.add_executor_result(ExecutorResult(early_rifl, "k", [None]))
    assert pending._early_count == 1
    pending.cancel(early_rifl)
    assert pending._early_count == 0 and early_rifl not in pending._early
    # cancel of an unknown rifl is a no-op
    pending.cancel(Rifl(7, 3))


# --- sim: SlowProcess nemesis + open-loop arrivals, deterministic ---


def _sim_runner(seed, fault_plan=None, open_loop_rate=None, trace_path=None,
                commands_per_client=COMMANDS_PER_CLIENT):
    from fantoch_tpu.core import Planet
    from fantoch_tpu.sim import Runner

    config = Config(
        n=3, f=1,
        executor_monitor_execution_order=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        trace_sample_rate=1.0 if trace_path else 0.0,
    )
    planet = Planet.new("gcp")
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=commands_per_client,
        payload_size=1,
    )
    regions = sorted(planet.regions())[:3]
    return Runner(
        EPaxos, planet, config, workload, CLIENTS_PER_PROCESS,
        process_regions=list(regions), client_regions=list(regions),
        seed=seed, fault_plan=fault_plan, trace_path=trace_path,
        open_loop_rate_per_s=open_loop_rate,
    )


def _latency_totals(latencies):
    return {
        str(region): (commands, histogram.count, histogram.mean())
        for region, (commands, histogram) in latencies.items()
    }


@pytest.mark.overload
def test_sim_slow_process_completes_and_is_deterministic():
    plan = FaultPlan(seed=5).with_slow_process(
        2, slow_ms=40, from_ms=50, until_ms=4000, jitter_ms=10
    )
    digests, latencies = [], []
    for _ in range(2):
        runner = _sim_runner(seed=3, fault_plan=plan)
        _m, _mon, lat = runner.run(extra_sim_time_ms=5000)
        digests.append(runner.nemesis.trace_digest())
        latencies.append(_latency_totals(lat))
        # every client completed despite the degraded consumer
        assert sum(c for c, _h in lat.values()) == 3 * CLIENTS_PER_PROCESS * COMMANDS_PER_CLIENT
    # same seed => byte-identical nemesis trace and identical latencies
    assert digests[0] == digests[1]
    assert latencies[0] == latencies[1]
    # the slow window is visible as marks in the trace
    runner = _sim_runner(seed=3, fault_plan=plan)
    runner.run(extra_sim_time_ms=5000)
    kinds = {kind for _t, kind, _d in runner.nemesis.trace}
    assert "slow" in kinds and "slow-end" in kinds
    # a different jitter seed perturbs delivery -> different latencies
    other = _sim_runner(
        seed=3,
        fault_plan=FaultPlan(seed=6).with_slow_process(
            2, slow_ms=40, from_ms=50, until_ms=4000, jitter_ms=10
        ),
    )
    _m, _mon, lat_other = other.run(extra_sim_time_ms=5000)
    assert _latency_totals(lat_other) != latencies[0]


@pytest.mark.overload
def test_sim_open_loop_poisson_completes_deterministically(tmp_path):
    """Open-loop arrivals drive submissions regardless of completions;
    same-seed overload runs (open loop + SlowProcess) stay byte-identical
    including the span log."""
    plan = FaultPlan(seed=9).with_slow_process(1, slow_ms=30, from_ms=0)
    traces = []
    for run_index in range(2):
        path = str(tmp_path / f"trace{run_index}.jsonl")
        runner = _sim_runner(
            seed=4, fault_plan=plan, open_loop_rate=20.0, trace_path=path,
            commands_per_client=5,
        )
        _m, monitors, lat = runner.run(extra_sim_time_ms=5000)
        assert sum(c for c, _h in lat.values()) == 3 * CLIENTS_PER_PROCESS * 5
        traces.append(open(path, "rb").read())
        assert runner.nemesis.trace_digest()
    assert traces[0] and traces[0] == traces[1]


# --- TCP: admission control, backoff retries, deadline sheds ---


async def _boot_cluster(config, protocol_cls=EPaxos):
    """A live localhost cluster the test drives through several client
    phases (the harness runs exactly one client pool, so the drain-back
    row boots the runtimes directly)."""
    from fantoch_tpu.core.ids import process_ids
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.process_runner import ProcessRuntime

    ids = list(process_ids(0, config.n))
    peer_ports = {pid: free_port() for pid in ids}
    client_ports = {pid: free_port() for pid in ids}
    runtimes = {}
    for pid in ids:
        sorted_processes = [(pid, 0)] + [(p, 0) for p in ids if p != pid]
        runtimes[pid] = ProcessRuntime(
            protocol_cls, pid, 0, config,
            listen_addr=("127.0.0.1", peer_ports[pid]),
            client_addr=("127.0.0.1", client_ports[pid]),
            peers={p: ("127.0.0.1", peer_ports[p]) for p in ids if p != pid},
            sorted_processes=sorted_processes,
        )
    await asyncio.gather(*(r.start() for r in runtimes.values()))
    return runtimes, client_ports


def _cluster_config(**kw):
    return Config(
        n=3, f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        **kw,
    )


def _workload(commands_per_client, conflict_rate=30):
    return Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(conflict_rate),
        keys_per_command=1,
        commands_per_client=commands_per_client,
        payload_size=1,
    )


@pytest.mark.overload
def test_tcp_admission_sheds_typed_and_backoff_completes():
    """Open-loop burst into a tight admission limit: typed sheds reach
    clients, backoff-retrying clients eventually complete everything,
    and queue depths stay under the configured bounds."""
    from fantoch_tpu.run.client_runner import run_clients

    async def scenario():
        config = _cluster_config(
            admission_limit=1, queue_capacity=256, overload_retry_after_ms=5,
        )
        runtimes, client_ports = await _boot_cluster(config)
        try:
            pid = sorted(runtimes)[0]
            clients = await run_clients(
                list(range(1, 7)),
                {0: ("127.0.0.1", client_ports[pid])},
                _workload(8),
                arrival_rate_per_s=300.0,  # ~2x anything localhost EPaxos does
                arrival_seed=1,
            )
            retries = sum(c.overload_retries for c in clients.values())
            sheds = sum(r.shed_submissions for r in runtimes.values())
            completed = sum(
                len(list(c.data().latency_data())) for c in clients.values()
            )
            # no deadline: every command eventually completes via backoff
            assert completed == 6 * 8
            assert all(c.shed_commands == 0 for c in clients.values())
            # the burst actually overloaded the edge and sheds were typed
            assert sheds > 0 and retries > 0
            assert retries >= sheds  # one client retry per server shed
            # bounded depths: capacity is a PAUSE watermark, not a hard
            # cap (put_nowait never blocks; synchronous producers may
            # overshoot while a gate drains, tallied as overflows) — the
            # bounded-ness invariant is "never past 2x the watermark"
            for runtime in runtimes.values():
                for name, row in runtime.queue_stats().items():
                    if row["capacity"]:
                        assert row["depth_hwm"] <= 2 * row["capacity"], (name, row)
        finally:
            await asyncio.gather(*(r.stop() for r in runtimes.values()))

    asyncio.run(scenario())


@pytest.mark.overload
def test_tcp_deadline_expired_work_is_shed_not_executed_late():
    """With a deadline budget smaller than the server's retry-after hint,
    a shed submission is abandoned by the client (no latency sample) —
    the run still terminates and tallies the shed."""
    from fantoch_tpu.run.client_runner import run_clients

    async def scenario():
        config = _cluster_config(
            admission_limit=1, overload_retry_after_ms=200,
        )
        runtimes, client_ports = await _boot_cluster(config)
        try:
            pid = sorted(runtimes)[0]
            clients = await run_clients(
                list(range(1, 7)),
                {0: ("127.0.0.1", client_ports[pid])},
                _workload(6),
                arrival_rate_per_s=400.0,
                arrival_seed=2,
                deadline_ms=100,  # < retry-after: first shed is final
            )
            sheds = sum(c.shed_commands for c in clients.values())
            completed = sum(
                len(list(c.data().latency_data())) for c in clients.values()
            )
            assert sheds > 0, "burst at 2x saturation must shed something"
            # shed + completed covers every issued command; nothing hangs
            assert completed + sheds == 6 * 6
        finally:
            await asyncio.gather(*(r.stop() for r in runtimes.values()))

    asyncio.run(scenario())


@pytest.mark.overload
def test_tcp_raise_on_shed_propagates_typed_errors():
    """``raise_on_shed``: a deadline-expired shed surfaces as the typed
    DeadlineExceededError chained to the server's OverloadedError (with
    the retry-after hint) instead of a silent tally."""
    from fantoch_tpu.run.client_runner import run_clients
    from fantoch_tpu.run.prelude import Overloaded

    # the wire frame converts to the typed error
    err = Overloaded(rifl="r", retry_after_ms=75, depth=9, limit=4).to_error()
    assert isinstance(err, OverloadedError)
    assert (err.depth, err.limit, err.retry_after_ms) == (9, 4, 75)

    async def scenario():
        config = _cluster_config(
            admission_limit=1, overload_retry_after_ms=200,
        )
        runtimes, client_ports = await _boot_cluster(config)
        try:
            pid = sorted(runtimes)[0]
            with pytest.raises(DeadlineExceededError) as excinfo:
                await run_clients(
                    list(range(1, 7)),
                    {0: ("127.0.0.1", client_ports[pid])},
                    _workload(6),
                    arrival_rate_per_s=400.0,
                    arrival_seed=6,
                    deadline_ms=100,
                    raise_on_shed=True,
                )
            assert isinstance(excinfo.value.__cause__, OverloadedError)
            assert excinfo.value.__cause__.retry_after_ms >= 200
        finally:
            await asyncio.gather(*(r.stop() for r in runtimes.values()))

    asyncio.run(scenario())


@pytest.mark.overload
@pytest.mark.chaos
def test_tcp_sustained_overload_bounded_then_drains_to_baseline():
    """The acceptance row: pre-burst closed-loop baseline, a sustained
    open-loop burst at ~2x saturation against a tight admission limit
    (typed sheds + bounded depths — the RSS proxy: no queue grows past
    2x its pause watermark), then a post-burst closed-loop phase whose p50
    returns to within 2x of the pre-burst baseline (+ absolute slack for
    shared CI hosts)."""
    from fantoch_tpu.run.client_runner import run_clients

    def p50_ms(clients):
        lat = sorted(
            value
            for client in clients.values()
            for value in client.data().latency_data()
        )
        return lat[len(lat) // 2] / 1000.0

    async def scenario():
        config = _cluster_config(
            admission_limit=2, queue_capacity=128, overload_retry_after_ms=5,
        )
        runtimes, client_ports = await _boot_cluster(config)
        try:
            pid = sorted(runtimes)[0]
            addr = {0: ("127.0.0.1", client_ports[pid])}
            # phase 1: closed-loop baseline
            pre = await run_clients([1, 2], addr, _workload(10))
            pre_p50 = p50_ms(pre)
            # phase 2: sustained open-loop burst at ~2x saturation
            burst = await run_clients(
                list(range(11, 19)), addr, _workload(10),
                arrival_rate_per_s=250.0, arrival_seed=3,
            )
            sheds = sum(r.shed_submissions for r in runtimes.values())
            assert sheds > 0, "the burst must trip admission control"
            burst_done = sum(
                len(list(c.data().latency_data())) for c in burst.values()
            )
            assert burst_done == 8 * 10  # backoff completes everything
            # same soft-watermark bound rule as above: never past 2x
            for runtime in runtimes.values():
                for name, row in runtime.queue_stats().items():
                    if row["capacity"]:
                        assert row["depth_hwm"] <= 2 * row["capacity"], (name, row)
            # phase 3: the system drained back — post-burst closed-loop
            # latency is back near the pre-burst baseline
            post = await run_clients([21, 22], addr, _workload(10))
            post_p50 = p50_ms(post)
            assert post_p50 <= 2 * pre_p50 + 15.0, (pre_p50, post_p50)
        finally:
            await asyncio.gather(*(r.stop() for r in runtimes.values()))

    asyncio.run(scenario())


@pytest.mark.overload
def test_newt_cluster_overload_plane_rides_batched_submit():
    """The admission edge composes with Newt's batched submit seam (the
    worker drains runs of submits in one call): sheds + completion under
    an open-loop burst, exactly as for EPaxos."""
    from fantoch_tpu.run.client_runner import run_clients

    async def scenario():
        config = _cluster_config(
            admission_limit=1, overload_retry_after_ms=5,
            newt_detached_send_interval_ms=5,
        )
        runtimes, client_ports = await _boot_cluster(config, Newt)
        try:
            pid = sorted(runtimes)[0]
            clients = await run_clients(
                list(range(1, 5)),
                {0: ("127.0.0.1", client_ports[pid])},
                _workload(6),
                arrival_rate_per_s=300.0,
                arrival_seed=4,
            )
            completed = sum(
                len(list(c.data().latency_data())) for c in clients.values()
            )
            assert completed == 4 * 6
            assert sum(r.shed_submissions for r in runtimes.values()) > 0
        finally:
            await asyncio.gather(*(r.stop() for r in runtimes.values()))

    asyncio.run(scenario())


# --- metrics export: queue gauges survive into snapshots + obs summarize ---


@pytest.mark.overload
def test_queue_gauges_survive_into_metrics_and_obs_summarize(tmp_path):
    from fantoch_tpu.observability.report import summarize
    from fantoch_tpu.observability.tracer import read_trace
    from fantoch_tpu.run.harness import run_localhost_cluster
    from fantoch_tpu.run.observe import read_metrics_snapshot

    observe_dir = str(tmp_path / "obs")
    config = _cluster_config(
        admission_limit=1,
        overload_retry_after_ms=5,
        trace_sample_rate=1.0,
    )
    runtimes, clients = asyncio.run(
        run_localhost_cluster(
            EPaxos, config, _workload(6), 3,
            arrival_rate_per_s=300.0, arrival_seed=5,
            observe_dir=observe_dir,
        )
    )
    total_sheds = sum(r.shed_submissions for r in runtimes.values())
    assert total_sheds > 0
    # per-queue gauges landed in the ProcessMetrics snapshots
    saw_queue_gauges = saw_overload = False
    for pid in runtimes:
        snap = read_metrics_snapshot(f"{observe_dir}/metrics_p{pid}.gz")
        assert snap.queues, "queue gauges missing from the snapshot"
        assert any("workers" in name for name in snap.queues)
        assert all("depth_hwm" in row for row in snap.queues.values())
        saw_queue_gauges = True
        assert snap.overload is not None
        if snap.overload["shed_submissions"] > 0:
            saw_overload = True
    assert saw_queue_gauges and saw_overload
    # ...and ride the span log into `bin/obs.py summarize`
    events = []
    for pid in runtimes:
        events.extend(read_trace(f"{observe_dir}/trace_p{pid}.jsonl"))
    counters = summarize(events).get("device_counters", {})
    assert counters.get("queue_depth_hwm", 0) > 0
    assert counters.get("shed_submissions", 0) == total_sheds
