"""Run-layer observability: metrics snapshots, execution log + replay, the
prof histogram registry (fantoch/src/run/task/{metrics_logger,
execution_logger,tracer}.rs + fantoch_prof/src/lib.rs analogs), and the
dot-lifecycle tracing plane (fantoch_tpu/observability — span schema
roundtrip, deterministic sampling, same-seed trace equality, stage
coverage and stage-sum-equals-client-latency on sim and localhost runs)."""

import asyncio
import glob
import json
import time

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config
from fantoch_tpu.protocol import EPaxos
from fantoch_tpu.run.harness import run_localhost_cluster
from fantoch_tpu.run.observe import (
    ProcessMetrics,
    read_execution_log,
    read_metrics_snapshot,
    replay_execution_log,
    write_metrics_snapshot,
)
from fantoch_tpu.utils import prof


def test_metrics_snapshot_roundtrip(tmp_path):
    from fantoch_tpu.core.metrics import Metrics

    m = Metrics()
    m.aggregate("fast", 7)
    m.collect("lat", 3)
    path = str(tmp_path / "metrics.gz")
    write_metrics_snapshot(path, ProcessMetrics([m], [Metrics()]))
    out = read_metrics_snapshot(path)
    assert out.workers[0].get_aggregated("fast") == 7
    assert out.workers[0].get_collected("lat").count == 1


def test_prof_registry():
    prof.reset()

    @prof.profiled
    def work():
        time.sleep(0.001)

    for _ in range(3):
        work()
    with prof.elapsed("region"):
        time.sleep(0.001)
    snap = prof.snapshot()
    names = set(snap)
    assert any("work" in n for n in names) and "region" in names
    hist = next(v for k, v in snap.items() if "work" in k)
    assert hist.count == 3 and hist.mean() >= 1000  # microseconds
    assert "region" in prof.format_snapshot()


def test_cluster_observability_and_replay(tmp_path):
    """A runner run produces metrics files and a replayable execution log
    (VERDICT r2 item 7 done-criterion)."""
    config = Config(
        n=3,
        f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        executor_monitor_execution_order=True,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=5,
        payload_size=1,
    )
    runtimes, clients = asyncio.run(
        run_localhost_cluster(
            EPaxos,
            config,
            workload,
            clients_per_process=1,
            extra_run_time_ms=600,
            observe_dir=str(tmp_path),
        )
    )
    assert all(c.issued_commands == 5 for c in clients.values())

    # metrics snapshots exist and carry the commit accounting
    snaps = sorted(glob.glob(str(tmp_path / "metrics_p*.gz")))
    assert len(snaps) == 3
    from fantoch_tpu.protocol import ProtocolMetricsKind

    total_commits = 0
    for path in snaps:
        snap = read_metrics_snapshot(path)
        worker = snap.workers[0]
        total_commits += worker.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        total_commits += worker.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
    assert total_commits == 15  # 3 clients x 5 commands

    # execution logs replay through a fresh executor with the same results
    logs = sorted(glob.glob(str(tmp_path / "execution_p*.log")))
    assert len(logs) == 3
    for pid, path in zip(sorted(runtimes), logs):
        batches = list(read_execution_log(path))
        assert batches, "execution log must not be empty"
        summary = replay_execution_log(path, EPaxos, pid, 0, config)
        # every key of every command produces one executor result
        assert summary["results"] == 15 * 2  # keys_per_command = 2


def test_prof_auto_instrument_spans():
    """The span-subscriber analog (fantoch_prof/src/lib.rs:78-136):
    auto_instrument wraps the hot-path methods of every protocol/executor
    subclass; driving a whole sim populates per-function histograms with
    no call-site edits; uninstrument restores the originals."""
    from fantoch_tpu.core.config import Config
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.utils import prof

    import sys as _sys, os as _os
    _sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
    from harness import sim_test

    prof.reset()
    count = prof.auto_instrument()
    try:
        assert count > 0
        sim_test(EPaxos, Config(3, 1))
        snap = prof.snapshot()
        protocol_spans = [k for k in snap if k.endswith(".handle")]
        executor_spans = [k for k in snap if "handle_batch" in k]
        assert protocol_spans, sorted(snap)
        assert executor_spans, sorted(snap)
        assert all(snap[k].count > 0 for k in protocol_spans)
        formatted = prof.format_snapshot()
        assert "p99" in formatted
    finally:
        prof.uninstrument()
        prof.reset()
    # originals restored: no double-wrapping markers left behind
    from fantoch_tpu.protocol.graph_protocol import GraphProtocol

    assert not getattr(GraphProtocol.handle, "_prof_wrapped", False)


# --- prof registry scoping (the global-registry-bleed fix) ---


def test_prof_registry_isolation():
    """Two concurrent scopes (the localhost harness pattern: several
    ProcessRuntimes in one Python process, each calling set_registry
    before spawning its tasks) record into their own registries; the
    default scope stays clean."""
    from fantoch_tpu.core.metrics import Metrics

    prof.reset()

    async def scenario():
        r1, r2 = Metrics(), Metrics()

        async def work(registry, name):
            prof.set_registry(registry)
            for _ in range(3):
                with prof.elapsed(name):
                    await asyncio.sleep(0)
            return set(prof.snapshot())

        # gather wraps each coroutine in a task with its own context copy
        s1, s2 = await asyncio.gather(work(r1, "one"), work(r2, "two"))
        return r1, r2, s1, s2

    r1, r2, s1, s2 = asyncio.run(scenario())
    assert set(r1.collected) == {"one"} and r1.collected["one"].count == 3
    assert set(r2.collected) == {"two"} and r2.collected["two"].count == 3
    # each task's snapshot() saw only its own registry
    assert s1 == {"one"} and s2 == {"two"}
    # the default (module-level) registry never saw either scope
    assert "one" not in prof.snapshot() and "two" not in prof.snapshot()


def test_prof_scoped_registry_context_manager():
    with prof.scoped_registry() as reg:
        with prof.elapsed("inner"):
            pass
        assert "inner" in prof.snapshot()
    assert "inner" not in prof.snapshot()
    assert reg.collected["inner"].count == 1


# --- metrics snapshot: device-counter field (backward-compatible) ---


def test_metrics_snapshot_device_counters_roundtrip(tmp_path):
    from fantoch_tpu.core.metrics import Metrics

    m = Metrics()
    m.aggregate("fast", 2)
    device = {"table_plane_dispatches": 3, "jax_recompiles": 1}
    path = str(tmp_path / "metrics.gz")
    write_metrics_snapshot(path, ProcessMetrics([m], [Metrics()], device))
    out = read_metrics_snapshot(path)
    assert out.device == device


def test_metrics_snapshot_reads_pre_device_snapshots(tmp_path):
    """A snapshot pickled before the ``device`` field existed (its
    __dict__ simply lacks the key) reads back with device=None."""
    from fantoch_tpu.core.metrics import Metrics

    old = ProcessMetrics([Metrics()], [Metrics()])
    del old.__dict__["device"]  # exactly what an old pickle restores to
    path = str(tmp_path / "metrics_old.gz")
    write_metrics_snapshot(path, old)
    out = read_metrics_snapshot(path)
    assert out.device is None
    assert len(out.workers) == 1


def test_table_plane_device_counters():
    """The resident votes-table plane tallies per-dispatch counters
    (occupancy, kernel wall-ms, residual runs) that the snapshot fold and
    the bench rows consume."""
    import numpy as np

    from fantoch_tpu.executor.table import TableExecutor
    from fantoch_tpu.executor.table_plane import DeviceTablePlane

    plane = DeviceTablePlane(3, 2, key_buckets=4)
    plane.bucket("a")
    plane.commit_votes(
        np.zeros(3, np.int64),
        np.array([1, 2, 3], np.int64),
        np.ones(3, np.int64),
        np.ones(3, np.int64),
    )
    assert plane.dispatches == 1
    assert plane.stats["vote_rows"] == 3
    assert plane.stats["row_capacity"] >= 3
    assert plane.stats["kernel_ms"] > 0

    config = Config(3, 1, batched_table_executor=True, device_table_plane=True)
    ex = TableExecutor(1, 0, config)
    counters = ex.device_counters()
    assert counters == {
        "table_plane_dispatches": 0,
        "table_plane_grows": 0,
        "table_plane_vote_rows": 0,
        "table_plane_row_capacity": 0,
        "table_plane_residual_runs": 0,
        "table_plane_kernel_ms": 0,
        "table_plane_resident_uploads": 0,
        # the fault-tolerance tallies (failovers/rebuilds/degraded wall
        # + the severity-ordered health gauge) ride the same surface
        "table_plane_failovers": 0,
        "table_plane_rebuilds": 0,
        "table_plane_degraded_ms": 0.0,
        "table_plane_health": 0,
    }
    # plane off -> no counters contributed
    assert TableExecutor(1, 0, Config(3, 1)).device_counters() is None


def test_idle_frac_fold_semantics():
    """``device_idle_frac`` is a ratio: the fold must never sum it
    across executors; ``derive_idle_frac`` recomputes it from the folded
    busy/span wall totals (clamped to [0, 1])."""
    from fantoch_tpu.observability.device import derive_idle_frac, merge_counters

    a = {"device_busy_ms": 30.0, "device_span_ms": 100.0,
         "device_idle_frac": 0.7, "device_pipeline_depth": 2}
    b = {"device_busy_ms": 50.0, "device_span_ms": 100.0,
         "device_idle_frac": 0.5, "device_pipeline_depth": 2}
    folded = merge_counters(merge_counters({}, a), b)
    assert "device_idle_frac" not in folded  # ratios never sum
    assert folded["device_pipeline_depth"] == 2  # gauges fold by max
    derive_idle_frac(folded)
    assert abs(folded["device_idle_frac"] - (1 - 80.0 / 200.0)) < 1e-9
    # busy > span (overlapping spans after a fold) clamps at 0, and a
    # missing/zero span derives nothing
    assert derive_idle_frac(
        {"device_busy_ms": 5.0, "device_span_ms": 1.0}
    )["device_idle_frac"] == 0.0
    assert "device_idle_frac" not in derive_idle_frac({"device_busy_ms": 5.0})


def test_obs_summarize_prints_overlap(capsys):
    """bin/obs.py summarize surfaces the dispatch/drain overlap line
    from the per-dispatch device counters."""
    from fantoch_tpu.bin.obs import _print_overlap

    _print_overlap(
        {
            "device_dispatch_ms": 12.5,
            "device_drain_ms": 40.0,
            "device_fetch_ms": 33.0,
            "device_busy_ms": 45.0,
            "device_span_ms": 60.0,
            "device_pipeline_depth": 2,
            "device_pipelined_rounds": 7,
        }
    )
    line = capsys.readouterr().out
    assert "device overlap:" in line
    assert "idle_frac 0.250" in line
    assert "depth 2" in line and "pipelined_rounds 7" in line
    # no overlap counters -> silent (plane-only traces)
    _print_overlap({"table_plane_dispatches": 3})
    assert capsys.readouterr().out == ""


# --- dot-lifecycle tracing plane (fantoch_tpu/observability) ---


def _traced_sim(trace_path, seed=3, sample_rate=1.0, commands_per_client=4,
                clients_per_process=2, n=3, reorder=False,
                ingest_deadline_ms=None):
    """A tiny 3-process EPaxos sim at 50% conflict with tracing on;
    returns the runner's (metrics, monitors, latencies) tuple."""
    from fantoch_tpu.core import Planet
    from fantoch_tpu.sim import Runner

    config = Config(
        n=n,
        f=1,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        trace_sample_rate=sample_rate,
        ingest_deadline_ms=ingest_deadline_ms,
    )
    planet = Planet.new("gcp")
    regions = sorted(planet.regions())[:n]
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=commands_per_client,
        payload_size=1,
    )
    runner = Runner(
        EPaxos,
        planet,
        config,
        workload,
        clients_per_process=clients_per_process,
        process_regions=list(regions),
        client_regions=list(regions),
        seed=seed,
        trace_path=str(trace_path),
    )
    if reorder:
        runner.reorder_messages()
    return runner.run(extra_sim_time_ms=1000)


def test_span_schema_roundtrip(tmp_path):
    """Emit -> JSONL -> read -> Perfetto JSON validates; counter events
    ride along; a torn final line is dropped, not fatal."""
    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.observability.perfetto import to_perfetto, validate_perfetto
    from fantoch_tpu.observability.report import assemble_spans
    from fantoch_tpu.observability.tracer import Tracer, read_trace

    clock = SimTime()
    path = str(tmp_path / "t.jsonl")
    tracer = Tracer(clock, path, sample_rate=1.0)
    rifl, dot = (7, 1), (2, 9)
    tracer.span("submit", rifl, cid=7)
    clock.add_millis(5)
    tracer.span("payload", rifl, dot=dot, pid=2)
    tracer.span("path", rifl, dot=dot, pid=2, meta={"path": "fast"})
    clock.add_millis(5)
    tracer.span("commit", rifl, dot=dot, pid=2)
    tracer.span("ready", rifl, pid=2, meta={"batch": 1})
    tracer.span("executed", rifl, pid=2)
    clock.add_millis(5)
    tracer.span("reply", rifl, cid=7)
    tracer.counter("table_plane_dispatches", 4, pid=2)
    tracer.close()

    events = read_trace(path)
    # 8 emitted events + the clock-domain header line
    assert len(events) == 9
    assert events[0] == {"k": "hdr", "clock": "virtual", "v": 1}
    spans = assemble_spans(events)
    assert len(spans) == 1
    span = spans[rifl]
    assert span["dot"] == dot
    assert list(span["stages"]) == [
        "submit", "payload", "path", "commit", "ready", "executed", "reply"
    ]
    assert span["meta"]["path"] == {"path": "fast"}

    perfetto = to_perfetto(events)
    validate_perfetto(perfetto)
    # survives a real serialize/parse round trip (what the viewer loads)
    validate_perfetto(json.loads(json.dumps(perfetto)))
    names = {ev["name"] for ev in perfetto["traceEvents"]}
    assert "submit->payload" in names and "table_plane_dispatches" in names

    # crash consistency: a torn final line is dropped on read
    with open(path, "a") as fh:
        fh.write('{"k":"span","stage":"reply","rifl":[7,')
    assert len(read_trace(path)) == 9


def test_span_assembly_survives_crashed_coordinator():
    """Stages the coordinator never emitted (it crashed; recovery
    committed the dot elsewhere) fall back to the earliest replica
    observation instead of vanishing, and the out-of-chain recovery
    stage is kept whatever pid emitted it — while on the healthy path
    the coordinator's timeline still beats replica re-observations."""
    from fantoch_tpu.observability.report import assemble_spans

    rifl, dot = [7, 1], [1, 5]

    def ev(stage, t, pid=None, cid=None, meta=None):
        e = {"k": "span", "stage": stage, "rifl": rifl, "t": t}
        if pid is not None:
            e["pid"] = pid
        if cid is not None:
            e["cid"] = cid
        if meta is not None:
            e["m"] = meta
        return e

    # coordinator p1 emitted payload then crashed; p2 recovered the dot
    crashed = [
        ev("submit", 0, cid=7),
        {**ev("payload", 10, pid=1), "dot": dot},
        ev("recovery", 30, pid=2, meta={"ballot": 12}),
        ev("commit", 40, pid=2),
        ev("commit", 45, pid=3),  # later replica: earliest fallback wins
        ev("ready", 50, pid=2),
        ev("executed", 60, pid=2),
        ev("reply", 80, cid=7),
    ]
    span = assemble_spans(crashed)[tuple(rifl)]
    assert span["stages"] == {
        "submit": 0, "payload": 10, "recovery": 30, "commit": 40,
        "ready": 50, "executed": 60, "reply": 80,
    }
    assert span["meta"]["recovery"] == {"ballot": 12}
    assert span["pid"] == 1  # the span still lives on the dot's home track

    # healthy path: the coordinator's commit replaces a replica's even
    # when the replica's landed first in the log
    healthy = [
        {**ev("payload", 10, pid=1), "dot": dot},
        ev("commit", 38, pid=2),
        ev("commit", 40, pid=1),
        ev("commit", 39, pid=3),
    ]
    span = assemble_spans(healthy)[tuple(rifl)]
    assert span["stages"]["commit"] == 40


def test_deterministic_sampling(tmp_path):
    """Same seed => same sampled dot set, at any rate; the sampled set is
    exactly the span_hash threshold set (no RNG involved)."""
    from fantoch_tpu.observability.report import assemble_spans
    from fantoch_tpu.observability.tracer import (
        Tracer,
        read_trace,
        span_hash,
    )

    _traced_sim(tmp_path / "a.jsonl", seed=5, sample_rate=0.5)
    _traced_sim(tmp_path / "b.jsonl", seed=5, sample_rate=0.5)
    _traced_sim(tmp_path / "full.jsonl", seed=5, sample_rate=1.0)

    sampled_a = set(assemble_spans(read_trace(tmp_path / "a.jsonl")))
    sampled_b = set(assemble_spans(read_trace(tmp_path / "b.jsonl")))
    full = set(assemble_spans(read_trace(tmp_path / "full.jsonl")))
    assert sampled_a == sampled_b
    assert sampled_a <= full
    # the sampled set is exactly what the hash threshold predicts
    threshold = int(0.5 * (1 << 32))
    assert sampled_a == {r for r in full if span_hash(*r) < threshold}
    # rate edges
    from fantoch_tpu.core.timing import SimTime

    off = Tracer(SimTime(), str(tmp_path / "off.jsonl"), sample_rate=0.0)
    assert not off.sample((1, 1))
    on = Tracer(SimTime(), str(tmp_path / "on.jsonl"), sample_rate=1.0)
    assert all(on.sample((s, q)) for s in range(1, 5) for q in range(1, 50))


def test_sim_same_seed_traces_identical(tmp_path):
    """Two same-seed sim runs produce byte-identical span logs and an
    empty obs diff (the acceptance-criterion determinism property)."""
    from fantoch_tpu.observability.report import diff_events
    from fantoch_tpu.observability.tracer import read_trace

    _traced_sim(tmp_path / "a.jsonl", seed=11)
    _traced_sim(tmp_path / "b.jsonl", seed=11)
    with open(tmp_path / "a.jsonl", "rb") as fa, \
            open(tmp_path / "b.jsonl", "rb") as fb:
        assert fa.read() == fb.read()
    assert diff_events(
        read_trace(tmp_path / "a.jsonl"), read_trace(tmp_path / "b.jsonl")
    ) == []
    # the diff is not vacuously empty: reorder jitter (drawn from the
    # runner RNG) shifts delivery times, so span timestamps change —
    # while two same-seed reordered runs still match byte for byte.
    # (a bare seed change is NOT trace-visible here: it only picks which
    # keys conflict, and this closed-loop workload never overlaps
    # conflicting commands in flight, so timing is identical)
    _traced_sim(tmp_path / "c.jsonl", seed=11, reorder=True)
    _traced_sim(tmp_path / "d.jsonl", seed=11, reorder=True)
    assert diff_events(
        read_trace(tmp_path / "a.jsonl"), read_trace(tmp_path / "c.jsonl")
    )
    with open(tmp_path / "c.jsonl", "rb") as fc, \
            open(tmp_path / "d.jsonl", "rb") as fd:
        assert fc.read() == fd.read()


def test_sim_same_seed_traces_identical_with_ingest_batching(tmp_path):
    """r16: the adaptive ingest batcher rides the sim's virtual clock
    (run/ingest.py injects time), so two same-seed runs with batching ON
    stay byte-identical — span logs included — and every span still
    covers the full canonical chain with monotonic stages.  The batched
    trace is not vacuously equal to the unbatched one: held commands
    shift their ingest (and later) stamps."""
    from fantoch_tpu.observability.report import (
        assemble_spans,
        diff_events,
        monotonic_violations,
    )
    from fantoch_tpu.observability.tracer import STAGES, read_trace

    _traced_sim(tmp_path / "a.jsonl", seed=11, ingest_deadline_ms=5.0)
    _traced_sim(tmp_path / "b.jsonl", seed=11, ingest_deadline_ms=5.0)
    with open(tmp_path / "a.jsonl", "rb") as fa, \
            open(tmp_path / "b.jsonl", "rb") as fb:
        assert fa.read() == fb.read()
    events = read_trace(tmp_path / "a.jsonl")
    assert diff_events(events, read_trace(tmp_path / "b.jsonl")) == []
    spans = assemble_spans(events)
    assert len(spans) == 3 * 2 * 4  # one span per committed command
    assert monotonic_violations(spans) == []
    for span in spans.values():
        assert set(span["stages"]) == set(STAGES)
    # ...and batching is observably ON vs the legacy run: a nonzero
    # payload->ingest hold exists somewhere, or at minimum the event
    # streams differ (the closed-loop trickle may release everything
    # via the cold-target fast path, but never silently diverge)
    _traced_sim(tmp_path / "off.jsonl", seed=11)
    off_spans = assemble_spans(read_trace(tmp_path / "off.jsonl"))
    assert set(off_spans) == set(spans)


def test_sim_trace_stage_breakdown_matches_client_latency(tmp_path):
    """The acceptance criterion: with trace_sample_rate=1.0, a 3-process
    EPaxos sim at 50%% conflict yields a span per committed command with
    monotonic stage timestamps, and the per-stage segments sum exactly to
    the client-observed latency histogram."""
    from fantoch_tpu.observability.report import (
        assemble_spans,
        monotonic_violations,
        span_segments,
        summarize,
    )
    from fantoch_tpu.observability.tracer import STAGES, read_trace

    _metrics, _monitors, latencies = _traced_sim(
        tmp_path / "t.jsonl", seed=21, commands_per_client=5,
        clients_per_process=2,
    )
    events = read_trace(tmp_path / "t.jsonl")
    spans = assemble_spans(events)
    committed = 3 * 2 * 5
    assert len(spans) == committed, "one span per committed command"
    assert monotonic_violations(spans) == []

    # every span covers the full canonical chain, and its segments
    # telescope exactly to reply - submit
    span_ms = []
    for span in spans.values():
        assert set(span["stages"]) == set(STAGES), span
        segments = span_segments(span)
        total = sum(tb - ta for _name, ta, tb in segments)
        end_to_end = span["stages"]["reply"] - span["stages"]["submit"]
        assert total == end_to_end
        span_ms.append(end_to_end // 1000)

    # ...and the end-to-end set IS the client-observed latency histogram
    client_ms = []
    for _region, (_commands, hist) in latencies.items():
        client_ms.extend(hist.all_values())
    assert sorted(span_ms) == sorted(client_ms)

    report = summarize(events)
    assert report["spans"] == committed
    assert report["end_to_end"]["count"] == committed
    assert all(count == committed for count in report["stage_coverage"].values())
    # per-stage percentile means sum to at most the end-to-end mean
    seg_mean = sum(row["mean_us"] for row in report["segments"].values())
    assert abs(seg_mean - report["end_to_end"]["mean_us"]) < 1.0


def test_localhost_trace_covers_lifecycle(tmp_path):
    """A real localhost EPaxos run with tracing on produces spans covering
    every lifecycle stage, readable across the per-process + client span
    logs (the run half of the shared-schema property)."""
    from fantoch_tpu.observability.report import (
        assemble_spans,
        monotonic_violations,
    )
    from fantoch_tpu.observability.tracer import STAGES, read_trace

    config = Config(
        n=3,
        f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        trace_sample_rate=1.0,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=5,
        payload_size=1,
    )
    runtimes, clients = asyncio.run(
        run_localhost_cluster(
            EPaxos,
            config,
            workload,
            clients_per_process=1,
            extra_run_time_ms=400,
            observe_dir=str(tmp_path),
        )
    )
    assert all(c.issued_commands == 5 for c in clients.values())
    paths = sorted(glob.glob(str(tmp_path / "trace_*.jsonl")))
    assert len(paths) == 4, paths  # 3 process logs + the client plane
    events = []
    for path in paths:
        events.extend(read_trace(path))
    spans = assemble_spans(events)
    assert len(spans) == 15
    for span in spans.values():
        assert set(span["stages"]) == set(STAGES), span
    assert monotonic_violations(spans) == []
