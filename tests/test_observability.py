"""Run-layer observability: metrics snapshots, execution log + replay, and
the prof histogram registry (fantoch/src/run/task/{metrics_logger,
execution_logger,tracer}.rs + fantoch_prof/src/lib.rs analogs)."""

import asyncio
import glob
import time

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config
from fantoch_tpu.protocol import EPaxos
from fantoch_tpu.run.harness import run_localhost_cluster
from fantoch_tpu.run.observe import (
    ProcessMetrics,
    read_execution_log,
    read_metrics_snapshot,
    replay_execution_log,
    write_metrics_snapshot,
)
from fantoch_tpu.utils import prof


def test_metrics_snapshot_roundtrip(tmp_path):
    from fantoch_tpu.core.metrics import Metrics

    m = Metrics()
    m.aggregate("fast", 7)
    m.collect("lat", 3)
    path = str(tmp_path / "metrics.gz")
    write_metrics_snapshot(path, ProcessMetrics([m], [Metrics()]))
    out = read_metrics_snapshot(path)
    assert out.workers[0].get_aggregated("fast") == 7
    assert out.workers[0].get_collected("lat").count == 1


def test_prof_registry():
    prof.reset()

    @prof.profiled
    def work():
        time.sleep(0.001)

    for _ in range(3):
        work()
    with prof.elapsed("region"):
        time.sleep(0.001)
    snap = prof.snapshot()
    names = set(snap)
    assert any("work" in n for n in names) and "region" in names
    hist = next(v for k, v in snap.items() if "work" in k)
    assert hist.count == 3 and hist.mean() >= 1000  # microseconds
    assert "region" in prof.format_snapshot()


def test_cluster_observability_and_replay(tmp_path):
    """A runner run produces metrics files and a replayable execution log
    (VERDICT r2 item 7 done-criterion)."""
    config = Config(
        n=3,
        f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        executor_monitor_execution_order=True,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=5,
        payload_size=1,
    )
    runtimes, clients = asyncio.run(
        run_localhost_cluster(
            EPaxos,
            config,
            workload,
            clients_per_process=1,
            extra_run_time_ms=600,
            observe_dir=str(tmp_path),
        )
    )
    assert all(c.issued_commands == 5 for c in clients.values())

    # metrics snapshots exist and carry the commit accounting
    snaps = sorted(glob.glob(str(tmp_path / "metrics_p*.gz")))
    assert len(snaps) == 3
    from fantoch_tpu.protocol import ProtocolMetricsKind

    total_commits = 0
    for path in snaps:
        snap = read_metrics_snapshot(path)
        worker = snap.workers[0]
        total_commits += worker.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        total_commits += worker.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
    assert total_commits == 15  # 3 clients x 5 commands

    # execution logs replay through a fresh executor with the same results
    logs = sorted(glob.glob(str(tmp_path / "execution_p*.log")))
    assert len(logs) == 3
    for pid, path in zip(sorted(runtimes), logs):
        batches = list(read_execution_log(path))
        assert batches, "execution log must not be empty"
        summary = replay_execution_log(path, EPaxos, pid, 0, config)
        # every key of every command produces one executor result
        assert summary["results"] == 15 * 2  # keys_per_command = 2


def test_prof_auto_instrument_spans():
    """The span-subscriber analog (fantoch_prof/src/lib.rs:78-136):
    auto_instrument wraps the hot-path methods of every protocol/executor
    subclass; driving a whole sim populates per-function histograms with
    no call-site edits; uninstrument restores the originals."""
    from fantoch_tpu.core.config import Config
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.utils import prof

    import sys as _sys, os as _os
    _sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
    from harness import sim_test

    prof.reset()
    count = prof.auto_instrument()
    try:
        assert count > 0
        sim_test(EPaxos, Config(3, 1))
        snap = prof.snapshot()
        protocol_spans = [k for k in snap if k.endswith(".handle")]
        executor_spans = [k for k in snap if "handle_batch" in k]
        assert protocol_spans, sorted(snap)
        assert executor_spans, sorted(snap)
        assert all(snap[k].count > 0 for k in protocol_spans)
        formatted = prof.format_snapshot()
        assert "p99" in formatted
    finally:
        prof.uninstrument()
        prof.reset()
    # originals restored: no double-wrapping markers left behind
    from fantoch_tpu.protocol.graph_protocol import GraphProtocol

    assert not getattr(GraphProtocol.handle, "_prof_wrapped", False)
