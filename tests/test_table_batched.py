"""Batched Newt/Tempo table path: kernel-batched clock proposals
(protocol/common/table_batched.py) and vectorized executor stability
(executor/table.py handle_batch), oracle-checked against the sequential
host twins and exercised end-to-end through the simulator and the real
TCP runner with ``Config.batched_table_executor``.
"""

import random

import numpy as np
import pytest

from fantoch_tpu.core import Command, Config, KVOp, Rifl
from fantoch_tpu.protocol import Newt
from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks
from fantoch_tpu.protocol.common.table_clocks import SequentialKeyClocks, Votes

from harness import sim_test

SHARD = 0


def put_cmd(i, keys):
    return Command.from_keys(
        Rifl(1, i + 1), SHARD, {k: (KVOp.put(""),) for k in keys}
    )


def votes_of(votes: Votes):
    return {k: [(v.by, v.start, v.end) for v in rs] for k, rs in votes}


def test_batched_key_clocks_scalar_equivalence():
    """Scalar proposal/detached/detached_all match SequentialKeyClocks on
    a random interleaving (including multi-key commands)."""
    rng = random.Random(0)
    seq = SequentialKeyClocks(1, SHARD)
    bat = BatchedKeyClocks(1, SHARD)
    for i in range(300):
        kind = rng.randrange(3)
        keys = rng.sample(["a", "b", "c", "d", "e"], rng.randrange(1, 3))
        cmd = put_cmd(i, keys)
        if kind == 0:
            min_clock = rng.randrange(0, 20)
            cs, vs = seq.proposal(cmd, min_clock)
            cb, vb = bat.proposal(cmd, min_clock)
            assert (cs, votes_of(vs)) == (cb, votes_of(vb))
        elif kind == 1:
            up_to = rng.randrange(0, 25)
            vs, vb = Votes(), Votes()
            seq.detached(cmd, up_to, vs)
            bat.detached(cmd, up_to, vb)
            assert votes_of(vs) == votes_of(vb)
        else:
            up_to = rng.randrange(0, 25)
            vs, vb = Votes(), Votes()
            seq.detached_all(up_to, vs)
            bat.detached_all(up_to, vb)
            assert votes_of(vs) == votes_of(vb)


def test_batched_proposal_kernel_equivalence():
    """proposal_batch (the batched_clock_proposal kernel) assigns the
    same clocks and consumed vote ranges as running the sequential twin
    command by command — including same-key runs inside one batch."""
    rng = random.Random(1)
    seq = SequentialKeyClocks(1, SHARD)
    bat = BatchedKeyClocks(1, SHARD)
    next_id = 0
    for _round in range(5):
        batch, mins, cmds = [], [], []
        for _ in range(rng.randrange(1, 40)):
            key = f"k{rng.randrange(6)}"
            cmd = put_cmd(next_id, [key])
            next_id += 1
            cmds.append(cmd)
            mins.append(rng.randrange(0, 30))
        expected = [seq.proposal(c, m) for c, m in zip(cmds, mins)]
        got = bat.proposal_batch(cmds, mins)
        for (ce, ve), (cg, vg) in zip(expected, got):
            assert ce == cg
            assert votes_of(ve) == votes_of(vg)
        # interleave a detached round so later batches start from bumped
        # clocks on both sides
        bump = put_cmd(next_id, ["k0", "k3"])
        next_id += 1
        vs, vb = Votes(), Votes()
        seq.detached(bump, 40 * (_round + 1), vs)
        bat.detached(bump, 40 * (_round + 1), vb)
        assert votes_of(vs) == votes_of(vb)


def test_batched_proposal_multikey_fallback():
    """Multi-key commands in a batch route through the sequential loop
    with identical results."""
    seq = SequentialKeyClocks(1, SHARD)
    bat = BatchedKeyClocks(1, SHARD)
    cmds = [put_cmd(0, ["x"]), put_cmd(1, ["x", "y"]), put_cmd(2, ["y"])]
    mins = [0, 0, 5]
    expected = [seq.proposal(c, m) for c, m in zip(cmds, mins)]
    got = bat.proposal_batch(cmds, mins)
    for (ce, ve), (cg, vg) in zip(expected, got):
        assert ce == cg and votes_of(ve) == votes_of(vg)


def test_stable_clocks_kernel_vs_partition():
    """The device stable_clocks kernel and the numpy partition agree over
    a wide random frontier matrix (both sides of the executor's
    _KERNEL_THRESHOLD switch)."""
    from fantoch_tpu.executor.table import TableExecutor

    config = Config(5, 1, newt_detached_send_interval_ms=5,
                    batched_table_executor=True)
    ex = TableExecutor(1, SHARD, config)
    rng = np.random.default_rng(2)
    frontiers = rng.integers(0, 1 << 40, size=(128, 5))  # > threshold
    col = 5 - ex._stability_threshold
    expected = np.sort(frontiers, axis=1)[:, col]
    assert (ex._stable_clocks(frontiers) == expected).all()
    small = frontiers[:8]
    assert (ex._stable_clocks(small) == expected[:8]).all()


@pytest.mark.parametrize("n,f", [(3, 1), (5, 2)])
def test_sim_newt_batched_table(n, f):
    """Newt sims with the batched table path: same oracle (monitor
    agreement inside sim_test) as the sequential configuration, and the
    slow-path profile matches the sequential run."""
    def cfg(batched):
        return Config(
            n=n, f=f, newt_detached_send_interval_ms=100,
            batched_table_executor=batched,
        )

    assert sim_test(Newt, cfg(True), seed=1) == sim_test(Newt, cfg(False), seed=1)


def test_run_newt_batched_table_localhost():
    """Real TCP cluster with batched table path: the worker groups queued
    submits through Newt.submit_batch and the executors run the
    vectorized stability pass; monitor agreement asserted by the harness."""
    import asyncio

    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.run.harness import run_localhost_cluster

    config = Config(
        3, 1,
        newt_detached_send_interval_ms=50,
        batched_table_executor=True,
        executor_monitor_execution_order=True,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=10,
        payload_size=1,
    )
    runtimes, clients = asyncio.run(
        run_localhost_cluster(Newt, config, workload, clients_per_process=2)
    )
    assert len(clients) == 6
    for client in clients.values():
        assert client.issued_commands == 10
    # per-key order agreement across all processes
    monitors = []
    for runtime in runtimes.values():
        for executor in runtime.executors:
            m = executor.monitor()
            if m is not None:
                monitors.append(m)
    assert monitors
    first = monitors[0]
    for other in monitors[1:]:
        assert first == other
