"""Batched Newt/Tempo table path: kernel-batched clock proposals
(protocol/common/table_batched.py) and vectorized executor stability
(executor/table.py handle_batch), oracle-checked against the sequential
host twins and exercised end-to-end through the simulator and the real
TCP runner with ``Config.batched_table_executor``.
"""

import random

import numpy as np
import pytest

from fantoch_tpu.core import Command, Config, KVOp, Rifl
from fantoch_tpu.protocol import Newt
from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks
from fantoch_tpu.protocol.common.table_clocks import SequentialKeyClocks, Votes

from harness import sim_test

SHARD = 0


def put_cmd(i, keys):
    return Command.from_keys(
        Rifl(1, i + 1), SHARD, {k: (KVOp.put(""),) for k in keys}
    )


def votes_of(votes: Votes):
    return {k: [(v.by, v.start, v.end) for v in rs] for k, rs in votes}


def test_batched_key_clocks_scalar_equivalence():
    """Scalar proposal/detached/detached_all match SequentialKeyClocks on
    a random interleaving (including multi-key commands)."""
    rng = random.Random(0)
    seq = SequentialKeyClocks(1, SHARD)
    bat = BatchedKeyClocks(1, SHARD)
    for i in range(300):
        kind = rng.randrange(3)
        keys = rng.sample(["a", "b", "c", "d", "e"], rng.randrange(1, 3))
        cmd = put_cmd(i, keys)
        if kind == 0:
            min_clock = rng.randrange(0, 20)
            cs, vs = seq.proposal(cmd, min_clock)
            cb, vb = bat.proposal(cmd, min_clock)
            assert (cs, votes_of(vs)) == (cb, votes_of(vb))
        elif kind == 1:
            up_to = rng.randrange(0, 25)
            vs, vb = Votes(), Votes()
            seq.detached(cmd, up_to, vs)
            bat.detached(cmd, up_to, vb)
            assert votes_of(vs) == votes_of(vb)
        else:
            up_to = rng.randrange(0, 25)
            vs, vb = Votes(), Votes()
            seq.detached_all(up_to, vs)
            bat.detached_all(up_to, vb)
            assert votes_of(vs) == votes_of(vb)


def test_batched_proposal_kernel_equivalence():
    """proposal_batch (the batched_clock_proposal kernel) assigns the
    same clocks and consumed vote ranges as running the sequential twin
    command by command — including same-key runs inside one batch."""
    rng = random.Random(1)
    seq = SequentialKeyClocks(1, SHARD)
    bat = BatchedKeyClocks(1, SHARD)
    next_id = 0
    for _round in range(5):
        batch, mins, cmds = [], [], []
        for _ in range(rng.randrange(1, 40)):
            key = f"k{rng.randrange(6)}"
            cmd = put_cmd(next_id, [key])
            next_id += 1
            cmds.append(cmd)
            mins.append(rng.randrange(0, 30))
        expected = [seq.proposal(c, m) for c, m in zip(cmds, mins)]
        got = bat.proposal_batch(cmds, mins)
        for (ce, ve), (cg, vg) in zip(expected, got):
            assert ce == cg
            assert votes_of(ve) == votes_of(vg)
        # interleave a detached round so later batches start from bumped
        # clocks on both sides
        bump = put_cmd(next_id, ["k0", "k3"])
        next_id += 1
        vs, vb = Votes(), Votes()
        seq.detached(bump, 40 * (_round + 1), vs)
        bat.detached(bump, 40 * (_round + 1), vb)
        assert votes_of(vs) == votes_of(vb)


def test_batched_proposal_multikey_fallback():
    """Multi-key commands in a batch route through the sequential loop
    with identical results."""
    seq = SequentialKeyClocks(1, SHARD)
    bat = BatchedKeyClocks(1, SHARD)
    cmds = [put_cmd(0, ["x"]), put_cmd(1, ["x", "y"]), put_cmd(2, ["y"])]
    mins = [0, 0, 5]
    expected = [seq.proposal(c, m) for c, m in zip(cmds, mins)]
    got = bat.proposal_batch(cmds, mins)
    for (ce, ve), (cg, vg) in zip(expected, got):
        assert ce == cg and votes_of(ve) == votes_of(vg)


def test_proposal_batch_arrays_matches_objects():
    """proposal_batch_arrays returns the same clocks and consumed ranges
    as the object path (which itself equals the sequential twin) — the
    array seam is just the object loop deleted."""
    rng = random.Random(7)
    bat_obj = BatchedKeyClocks(1, SHARD)
    bat_arr = BatchedKeyClocks(1, SHARD)
    next_id = 0
    for _round in range(4):
        keys, mins, cmds = [], [], []
        for _ in range(rng.randrange(1, 50)):
            key = f"k{rng.randrange(5)}"
            keys.append(key)
            cmds.append(put_cmd(next_id, [key]))
            next_id += 1
            mins.append(rng.randrange(0, 30))
        expected = bat_obj.proposal_batch(cmds, mins)
        clock, start = bat_arr.proposal_batch_arrays(keys, mins)
        for i, (ce, ve) in enumerate(expected):
            assert int(clock[i]) == ce
            ((_k, [(by, s, e)]),) = list(
                (k, [(v.by, v.start, v.end) for v in rs]) for k, rs in ve
            )
            assert (by, s, e) == (1, int(start[i]), int(clock[i]))


def test_scalar_interleaving_keeps_device_residency():
    """The PR 4 regression, fixed: scalar detached-bumps between batch
    dispatches (live Newt's submit-batch shape) must NOT drop the
    resident device clock table — the bumps fold into the next dispatch
    as one scatter-max (ops/table_ops.resident_clock_bump) and the table
    is uploaded exactly ONCE, while results stay bit-for-bit equal to
    the sequential twin."""
    rng = random.Random(11)
    seq = SequentialKeyClocks(1, SHARD)
    bat = BatchedKeyClocks(1, SHARD)
    next_id = 0
    for _round in range(5):
        # a batch dispatch (makes the table resident / keeps it so) ...
        keys, mins, cmds = [], [], []
        for _ in range(rng.randrange(4, 24)):
            key = f"k{rng.randrange(5)}"
            keys.append(key)
            cmds.append(put_cmd(next_id, [key]))
            next_id += 1
            mins.append(rng.randrange(0, 30))
        expected = [seq.proposal(c, m) for c, m in zip(cmds, mins)]
        clock, start = bat.proposal_batch_arrays(keys, mins)
        for i, (ce, ve) in enumerate(expected):
            assert int(clock[i]) == ce
            ((_k, [(by, s, e)]),) = (
                (k, [(v.by, v.start, v.end) for v in rs]) for k, rs in ve
            )
            assert (by, s, e) == (1, int(start[i]), int(clock[i]))
        assert bat._dev_prior is not None, "table dropped by the batch"
        # ... then live-Newt-style scalar interleavings: detached bumps
        # (commit clocks) and a periodic detached_all (clock-bump event)
        bump = put_cmd(next_id, ["k0", "k2"])
        next_id += 1
        up_to = 40 * (_round + 1)
        vs, vb = Votes(), Votes()
        seq.detached(bump, up_to, vs)
        bat.detached(bump, up_to, vb)
        assert votes_of(vs) == votes_of(vb)
        if _round == 2:
            vs, vb = Votes(), Votes()
            seq.detached_all(up_to + 3, vs)
            bat.detached_all(up_to + 3, vb)
            assert votes_of(vs) == votes_of(vb)
        assert bat._dev_prior is not None, "table dropped by a scalar bump"
        assert bat._pending_bumps, "scalar bumps must be recorded for fold"
    # the whole interleaved run re-uploaded the table exactly once (the
    # first build): residency held across every scalar interleaving
    assert bat.resident_uploads == 1
    # scalar reads see the folded/bumped clocks (host mirror re-syncs)
    for key in ("k0", "k1", "k2", "k3", "k4"):
        cs, _ = seq.proposal(put_cmd(next_id, [key]), 0)
        cb, _ = bat.proposal(put_cmd(next_id + 1, [key]), 0)
        next_id += 2
        assert cs == cb


def test_residency_survives_registry_growth_rebuild():
    """A key registry outgrowing the device capacity rebuilds the table
    from the host mirror (one more upload) with pending scalar bumps
    already folded into that mirror — no bump is lost across a rebuild."""
    seq = SequentialKeyClocks(1, SHARD)
    bat = BatchedKeyClocks(1, SHARD)
    keys0 = [f"k{i}" for i in range(4)]
    expected = [seq.proposal(put_cmd(i, [keys0[i]]), 0) for i in range(4)]
    clock, _ = bat.proposal_batch_arrays(keys0, [0, 0, 0, 0])
    assert [int(c) for c in clock] == [c for c, _ in expected]
    uploads0 = bat.resident_uploads
    # scalar bump, then a batch that registers enough new keys to force
    # a capacity regrow: the rebuild must carry the bump
    vs, vb = Votes(), Votes()
    seq.detached(put_cmd(10, ["k0"]), 50, vs)
    bat.detached(put_cmd(10, ["k0"]), 50, vb)
    assert votes_of(vs) == votes_of(vb)
    grow_keys = [f"g{i}" for i in range(64)]
    expected = [
        seq.proposal(put_cmd(100 + i, [k]), 0) for i, k in enumerate(grow_keys)
    ]
    clock, _ = bat.proposal_batch_arrays(grow_keys, [0] * len(grow_keys))
    assert [int(c) for c in clock] == [c for c, _ in expected]
    assert bat.resident_uploads == uploads0 + 1  # the regrow rebuild
    # k0's bumped clock survived the rebuild on the device side
    cs, _ = seq.proposal(put_cmd(200, ["k0"]), 0)
    cb, _ = bat.proposal(put_cmd(201, ["k0"]), 0)
    assert cs == cb == 51


def test_handle_batch_arrays_oracle_equivalence():
    """The array-native executor seam executes exactly what the
    per-info object path executes, in the same per-key order — across a
    round that leaves unstable tails buffered and a second round whose
    votes flush them (the buffered-merge path)."""
    from fantoch_tpu.core import Dot, RunTime
    from fantoch_tpu.executor.table import (
        TableExecutor,
        TableVotes,
        TableVotesArrays,
    )
    from fantoch_tpu.protocol.common.table_clocks import VoteRange

    rng = random.Random(3)
    n = 3
    time = RunTime()

    def executors():
        cfg_a = Config(n, 1, batched_table_executor=True,
                       executor_monitor_execution_order=True)
        cfg_b = Config(n, 1, batched_table_executor=False,
                       executor_monitor_execution_order=True)
        return TableExecutor(1, SHARD, cfg_a), TableExecutor(1, SHARD, cfg_b)

    ex_arrays, ex_oracle = executors()
    key_clock = {}
    next_seq = 1

    def make_round(voters_full):
        """Rows with per-key consecutive clocks; coordinator always votes
        its consumed range, `voters_full` processes vote the full prefix."""
        nonlocal next_seq
        B = rng.randrange(5, 40)
        keys, rows = [], []
        for _ in range(B):
            key = f"k{rng.randrange(4)}"
            clock = key_clock.get(key, 0) + 1
            key_clock[key] = clock
            keys.append(key)
            rows.append((key, clock, next_seq))
            next_seq += 1
        infos = []
        vote_row, vote_by, vote_start, vote_end = [], [], [], []
        for i, (key, clock, seq) in enumerate(rows):
            votes = [VoteRange(1, clock, clock)]
            vote_row.append(i); vote_by.append(1)
            vote_start.append(clock); vote_end.append(clock)
            for p in voters_full:
                votes.append(VoteRange(p, 1, clock))
                vote_row.append(i); vote_by.append(p)
                vote_start.append(1); vote_end.append(clock)
            infos.append(
                TableVotes(Dot(1, seq), clock, Rifl(1, seq), key,
                           (KVOp.put(f"v{seq}"),), votes)
            )
        arrays = TableVotesArrays(
            keys=keys,
            dot_src=np.full(B, 1, dtype=np.int64),
            dot_seq=np.array([r[2] for r in rows], dtype=np.int64),
            clock=np.array([r[1] for r in rows], dtype=np.int64),
            rifl_src=np.full(B, 1, dtype=np.int64),
            rifl_seq=np.array([r[2] for r in rows], dtype=np.int64),
            ops=[(KVOp.put(f"v{r[2]}"),) for r in rows],
            vote_row=np.array(vote_row, dtype=np.int64),
            vote_by=np.array(vote_by, dtype=np.int64),
            vote_start=np.array(vote_start, dtype=np.int64),
            vote_end=np.array(vote_end, dtype=np.int64),
        )
        return infos, arrays

    def drain(ex):
        out = []
        while True:
            r = ex.to_clients()
            if r is None:
                return out
            out.append((r.rifl, r.key, r.op_results))

    # round 1: only the coordinator votes -> below the stability
    # threshold, everything buffers
    infos, arrays = make_round(voters_full=[])
    ex_arrays.handle_batch_arrays(arrays, time)
    for info in infos:
        ex_oracle.handle(info, time)
    assert drain(ex_arrays) == drain(ex_oracle) == []

    # round 2: processes 2 and 3 vote full prefixes -> everything
    # (including the buffered round-1 tails) stabilizes; the arrays path
    # takes the buffered-merge branch
    infos, arrays = make_round(voters_full=[2, 3])
    ex_arrays.handle_batch_arrays(arrays, time)
    for info in infos:
        ex_oracle.handle(info, time)
    got, want = drain(ex_arrays), drain(ex_oracle)
    assert sorted(got, key=str) == sorted(want, key=str)
    # per-key execution order is the contract — compare the monitors
    mon_a, mon_b = ex_arrays.monitor(), ex_oracle.monitor()
    assert set(mon_a.keys()) == set(mon_b.keys())
    for key in mon_a.keys():
        assert mon_a.get_order(key) == mon_b.get_order(key)

    # round 3: mixed — one voter short on a random subset leaves a tail
    infos, arrays = make_round(voters_full=[2])
    ex_arrays.handle_batch_arrays(arrays, time)
    for info in infos:
        ex_oracle.handle(info, time)
    got, want = drain(ex_arrays), drain(ex_oracle)
    assert sorted(got, key=str) == sorted(want, key=str)
    for key in mon_a.keys():
        assert mon_a.get_order(key) == mon_b.get_order(key)


def test_handle_batch_arrays_order_drain():
    """record_order_arrays: the (rifl_src, rifl_seq) column drain yields
    the object drain's exact emit order — across buffered tails flushing
    in a later round — with no ExecutorResult objects and no KVStore
    side effects."""
    from fantoch_tpu.core import Dot, RunTime
    from fantoch_tpu.executor.table import (
        TableExecutor,
        TableVotes,
        TableVotesArrays,
    )
    from fantoch_tpu.protocol.common.table_clocks import VoteRange

    n = 3
    time = RunTime()
    cfg = lambda: Config(n, 1, batched_table_executor=True)  # noqa: E731
    ex_obj = TableExecutor(1, SHARD, cfg())
    ex_ord = TableExecutor(1, SHARD, cfg())
    ex_ord.record_order_arrays = True

    def make(rows, votes_spec):
        """rows: [(key, clock, seq)]; votes_spec: [(row, by, start, end)]"""
        B = len(rows)
        return TableVotesArrays(
            keys=[r[0] for r in rows],
            dot_src=np.full(B, 1, dtype=np.int64),
            dot_seq=np.array([r[2] for r in rows], dtype=np.int64),
            clock=np.array([r[1] for r in rows], dtype=np.int64),
            rifl_src=np.full(B, 1, dtype=np.int64),
            rifl_seq=np.array([r[2] for r in rows], dtype=np.int64),
            ops=[(KVOp.put(f"v{r[2]}"),) for r in rows],
            vote_row=np.array([v[0] for v in votes_spec], dtype=np.int64),
            vote_by=np.array([v[1] for v in votes_spec], dtype=np.int64),
            vote_start=np.array([v[2] for v in votes_spec], dtype=np.int64),
            vote_end=np.array([v[3] for v in votes_spec], dtype=np.int64),
        )

    # round 1: key a stabilizes (3 full voters), key b misses one -> tail
    rows1 = [("a", 1, 1), ("b", 1, 2), ("a", 2, 3)]
    votes1 = [(i, p, 1, c) for i, (_, c, _) in enumerate(rows1)
              for p in ((1, 2, 3) if i != 1 else (1,))]
    # round 2: key b's remaining voters arrive -> buffered tail flushes
    rows2 = [("b", 2, 4)]
    votes2 = [(0, p, 1, 2) for p in (1, 2, 3)]

    for arrays in (make(rows1, votes1), make(rows2, votes2)):
        ex_obj.handle_batch_arrays(arrays, time)
        ex_ord.handle_batch_arrays(arrays, time)
    obj_order = []
    while (r := ex_obj.to_clients()) is not None:
        obj_order.append(r.rifl.sequence)
    src, seq = ex_ord.take_order_arrays()
    assert (src == 1).all()
    assert seq.tolist() == obj_order
    assert ex_ord.to_clients() is None  # no object mirror accumulates
    # a second take returns empty
    src2, seq2 = ex_ord.take_order_arrays()
    assert len(src2) == 0 and len(seq2) == 0


def test_vote_coalescing_differential_fuzz():
    """The vectorized per-group interval merge in handle_batch_arrays must
    leave every (key, process) RangeEventSet identical to feeding the same
    votes through the per-info object path — random overlapping/adjacent/
    disjoint ranges in random order, plus a 2^61-spread round that trips
    the overflow guard into the scalar fallback branch."""
    from fantoch_tpu.core import Dot, RunTime
    from fantoch_tpu.executor.table import (
        TableExecutor,
        TableVotes,
        TableVotesArrays,
    )
    from fantoch_tpu.protocol.common.table_clocks import VoteRange

    n = 3
    time = RunTime()
    rng = random.Random(17)

    def round_pair(n_rows, span, base=1):
        """Same random votes as object infos and as arrays; rows carry
        huge clocks so nothing stabilizes and only vote state changes."""
        nonlocal next_seq
        keys, infos = [], []
        vr_row, vr_by, vr_s, vr_e = [], [], [], []
        rows = []
        for i in range(n_rows):
            key = f"k{rng.randrange(3)}"
            keys.append(key)
            votes = []
            for _ in range(rng.randrange(1, 5)):
                by = rng.randrange(1, n + 1)
                s = base + rng.randrange(span)
                e = s + rng.randrange(span // 4 + 1)
                votes.append(VoteRange(by, s, e))
                vr_row.append(i); vr_by.append(by); vr_s.append(s); vr_e.append(e)
            clock = 1 << 40  # far above any frontier: never stable
            seq = next_seq
            next_seq += 1
            rows.append((key, clock, seq))
            infos.append(TableVotes(Dot(1, seq), clock, Rifl(1, seq), key,
                                    (KVOp.put(""),), votes))
        B = len(rows)
        arrays = TableVotesArrays(
            keys=keys,
            dot_src=np.full(B, 1, dtype=np.int64),
            dot_seq=np.array([r[2] for r in rows], dtype=np.int64),
            clock=np.array([r[1] for r in rows], dtype=np.int64),
            rifl_src=np.full(B, 1, dtype=np.int64),
            rifl_seq=np.array([r[2] for r in rows], dtype=np.int64),
            ops=[(KVOp.put(""),)] * B,
            vote_row=np.array(vr_row, dtype=np.int64),
            vote_by=np.array(vr_by, dtype=np.int64),
            vote_start=np.array(vr_s, dtype=np.int64),
            vote_end=np.array(vr_e, dtype=np.int64),
        )
        return infos, arrays

    for trial in range(20):
        next_seq = 1
        cfg = Config(n, 1, batched_table_executor=True)
        ex_arr = TableExecutor(1, SHARD, cfg)
        ex_obj = TableExecutor(1, SHARD, cfg)
        spans = [50, 50, 1 << 61]  # last round forces the fallback branch
        for span in spans:
            infos, arrays = round_pair(rng.randrange(2, 25), span)
            ex_arr.handle_batch_arrays(arrays, time)
            ex_obj.handle_batch(infos, time)
            tables_a = ex_arr._table._tables
            tables_b = ex_obj._table._tables
            assert set(tables_a) == set(tables_b)
            for key, ta in tables_a.items():
                tb = tables_b[key]
                assert set(ta._votes) == set(tb._votes), (
                    f"trial {trial} span {span} key {key}: process sets differ"
                )
                for pid in ta._votes:
                    assert ta._votes[pid]._ranges == tb._votes[pid]._ranges, (
                        f"trial {trial} span {span} key {key} process {pid}"
                    )


def test_stable_clocks_kernel_vs_partition():
    """The device stable_clocks kernel and the numpy partition agree over
    a wide random frontier matrix.  force_kernel pins the kernel side (the
    work-based _KERNEL_THRESHOLD would otherwise route these sizes to the
    host partition); the 2^40-scale matrix exercises the rebase-overflow
    fallback inside _stable_clocks."""
    from fantoch_tpu.executor.table import TableExecutor

    config = Config(5, 1, newt_detached_send_interval_ms=5,
                    batched_table_executor=True)
    ex = TableExecutor(1, SHARD, config)
    rng = np.random.default_rng(2)
    col = 5 - ex._stability_threshold
    small_vals = rng.integers(0, 1 << 20, size=(128, 5))
    expected = np.sort(small_vals, axis=1)[:, col]
    assert (ex._stable_clocks(small_vals, force_kernel=True) == expected).all()
    assert (ex._stable_clocks(small_vals) == expected).all()
    wide = rng.integers(0, 1 << 40, size=(128, 5))  # rebase > int32: fallback
    expected_w = np.sort(wide, axis=1)[:, col]
    assert (ex._stable_clocks(wide, force_kernel=True) == expected_w).all()


@pytest.mark.parametrize("n,f", [(3, 1), (5, 2)])
def test_sim_newt_batched_table(n, f):
    """Newt sims with the batched table path: same oracle (monitor
    agreement inside sim_test) as the sequential configuration, and the
    slow-path profile matches the sequential run."""
    def cfg(batched):
        return Config(
            n=n, f=f, newt_detached_send_interval_ms=100,
            batched_table_executor=batched,
        )

    assert sim_test(Newt, cfg(True), seed=1) == sim_test(Newt, cfg(False), seed=1)


def test_run_newt_batched_table_localhost():
    """Real TCP cluster with batched table path: the worker groups queued
    submits through Newt.submit_batch and the executors run the
    vectorized stability pass; monitor agreement asserted by the harness."""
    import asyncio

    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.run.harness import run_localhost_cluster

    config = Config(
        3, 1,
        newt_detached_send_interval_ms=50,
        batched_table_executor=True,
        executor_monitor_execution_order=True,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=10,
        payload_size=1,
    )
    runtimes, clients = asyncio.run(
        run_localhost_cluster(Newt, config, workload, clients_per_process=2)
    )
    assert len(clients) == 6
    for client in clients.values():
        assert client.issued_commands == 10
    # per-key order agreement across all processes
    monitors = []
    for runtime in runtimes.values():
        for executor in runtime.executors:
            m = executor.monitor()
            if m is not None:
                monitors.append(m)
    assert monitors
    first = monitors[0]
    for other in monitors[1:]:
        assert first == other
