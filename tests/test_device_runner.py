"""TPU serving path tests: the device protocol step behind a real TCP
client plane (run/device_runner.py), plus direct DeviceDriver rounds.

The serving architecture being validated is the device-step analog of the
reference's runner (fantoch/src/run/mod.rs:105-445): client sessions feed
an array commit buffer, one jit-compiled protocol round orders the batch
for every replica at once, and execution results drain back through
AggregatePending to the sessions.
"""

import asyncio

import jax
import numpy as np
import pytest

# jaxlib 0.4.x CPU segfaults *flakily* while tracing the device drivers'
# scan bodies (C-stack overflow in _scan tracing) — a crash mid-suite
# aborts the whole pytest run, so on that pin this module is skipped
# outright rather than allowed to take the suite down with it
if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
    pytest.skip(
        "jax<0.5: device-driver scan tracing segfaults flakily on this "
        "jaxlib; run the device suite on the jax>=0.5 pin",
        allow_module_level=True,
    )

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl
from fantoch_tpu.run.device_runner import DeviceDriver
from fantoch_tpu.run.harness import run_device_server

COMMANDS_PER_CLIENT = 10


def _driver(n=3, **kw):
    kw.setdefault("batch_size", 16)
    kw.setdefault("key_buckets", 64)
    kw.setdefault("monitor_execution_order", True)
    return DeviceDriver(n, **kw)


def test_driver_hot_key_chain():
    """All commands on one key execute in dependency order: every PUT
    returns the previous PUT's value — across rounds too (the key clock
    carries the last executed gid between batches)."""
    d = _driver()
    batch = [
        (Dot(1, i + 1), Command.from_single(Rifl(1, i + 1), 0, "hot", KVOp.put(str(i))))
        for i in range(10)
    ]
    results = d.step(batch)
    assert [r.op_results[0] for r in results] == [None] + [str(i) for i in range(9)]
    assert d.executed == 10
    assert d.fast_paths == 10  # identical replica views: all fast path
    assert d.in_flight == 0

    # next round chains on the device-resident key clock
    (r,) = d.step(
        [(Dot(1, 11), Command.from_single(Rifl(1, 11), 0, "hot", KVOp.put("x")))]
    )
    assert r.op_results[0] == "9"


def test_driver_multi_key_commands():
    """key_width=2 commands route through the general on-mesh resolver and
    still execute with per-key chains intact."""
    d = _driver(key_width=2)
    # two interleaved chains on keys a/b plus commands touching both
    cmds = []
    for i in range(6):
        keys = {"a": (KVOp.put(f"a{i}"),)} if i % 2 else {
            "a": (KVOp.put(f"a{i}"),),
            "b": (KVOp.put(f"b{i}"),),
        }
        cmds.append((Dot(1, i + 1), Command.from_keys(Rifl(1, i + 1), 0, keys)))
    results = d.step(cmds)
    assert d.executed == 6
    by_key = {}
    for r in results:
        by_key.setdefault(r.key, []).append(r.op_results[0])
    # per-key previous-value chains are consistent
    assert by_key["a"] == [None, "a0", "a1", "a2", "a3", "a4"]
    assert by_key["b"] == [None, "b0", "b2"]


def test_driver_batch_padding_rounds():
    """Short batches pad to the compiled batch size; pad rows execute as
    no-ops and never surface as results."""
    d = _driver(batch_size=32)
    for i in range(5):
        results = d.step(
            [(Dot(1, i + 1), Command.from_single(Rifl(1, i + 1), 0, "k", KVOp.put(str(i))))]
        )
        assert len(results) == 1
    assert d.executed == 5
    assert d.rounds == 5


def test_device_runtime_tcp_serving():
    """Real TCP clients against the device-step server: every client
    finishes its closed-loop workload and every executed command is
    recorded exactly once per key by the execution monitor."""
    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(config, workload, client_count=4, batch_size=32)
    )
    assert len(clients) == 4
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
        assert len(list(client.data().latency_data())) == COMMANDS_PER_CLIENT

    driver = runtime.driver
    assert driver.executed == 4 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0
    # the monitor saw every rifl exactly once across its keys
    monitor = driver.store.monitor
    seen = [
        rifl for key in monitor.keys() for rifl in monitor.get_order(key)
    ]
    assert len(seen) == len(set(seen)) and len(seen) == 4 * COMMANDS_PER_CLIENT
    # the protocol took real paths (tallies are self-evidencing)
    assert driver.fast_paths + driver.slow_paths >= driver.executed


@pytest.mark.overload
def test_device_runtime_bounded_submit_ring_sheds_and_serves():
    """Overload plane at the device serving edge (run/pipeline.py
    BoundedSubmitRing): an open-loop Poisson burst into a tiny admission
    bound sheds with typed Overloaded replies, backoff-retrying clients
    still complete everything, and the ring's depth high-watermark never
    passes its capacity."""
    config = Config(
        3, 1, shard_count=1, admission_limit=4, overload_retry_after_ms=5,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=4, batch_size=8,
            arrival_rate_per_s=500.0, arrival_seed=1,
        )
    )
    for client in clients.values():
        assert len(list(client.data().latency_data())) == COMMANDS_PER_CLIENT
        assert client.shed_commands == 0  # no deadline: retries finish it
    ring = runtime._submit_queue
    assert ring.depth_hwm <= 4
    assert ring.sheds > 0, "the burst must trip the submit-ring bound"
    assert sum(c.overload_retries for c in clients.values()) >= ring.sheds
    # the overload gauges ride the serving tallies
    assert runtime._tallies["queue_capacity"] == 4
    assert runtime._tallies["shed_submissions"] == ring.sheds
    assert runtime.driver.executed == 4 * COMMANDS_PER_CLIENT


def test_device_runtime_multi_key_tcp():
    """keys_per_command=2 over TCP: the general resolver serves."""
    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=5,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=2, batch_size=16, key_width=2
        )
    )
    for client in clients.values():
        assert client.issued_commands == 5
    assert runtime.driver.executed == 10
    assert runtime.driver.in_flight == 0


def test_device_runtime_zipf_workload_tcp():
    """The zipf key generator end to end over TCP (the reference's other
    key-gen family; conflict-rate covers the rest of the suite)."""
    from fantoch_tpu.client.key_gen import ZipfKeyGen

    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ZipfKeyGen(coefficient=1.0, keys_per_shard=64),
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(config, workload, client_count=3, batch_size=16)
    )
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.executed == 3 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0
    monitor = driver.store.monitor
    # zipf keys are numeric ranks within keys_per_shard
    assert all(1 <= int(k) <= 64 for k in monitor.keys())


def test_device_runtime_read_mix_tcp():
    """Mixed read/write workload through the device plane: the device
    round orders read-only commands conservatively (by conflict key,
    like writes — the _LatestRW read optimization is a host-KeyDeps
    refinement, not a device-plane one), and gets execute against the
    KVStore through the serving path without wedging any client."""
    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(100),  # every command on the hot key
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=4,
        read_only_percentage=50,
    )
    runtime, clients = asyncio.run(
        run_device_server(config, workload, client_count=3, batch_size=16)
    )
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.executed == 3 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0
    monitor = driver.store.monitor
    order = monitor.get_order("CONFLICT")  # the hot key (key_gen.py:18)
    assert len(order) == len(set(order)) == 3 * COMMANDS_PER_CLIENT
    assert runtime.failure is None


def test_newt_driver_hot_key_chain():
    """The Newt device driver orders a hot key by (clock, dot) and the
    key clock carries across rounds (second protocol family served)."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    d = NewtDeviceDriver(3, batch_size=16, key_buckets=64,
                         monitor_execution_order=True)
    batch = [
        (Dot(1, i + 1), Command.from_single(Rifl(1, i + 1), 0, "hot", KVOp.put(str(i))))
        for i in range(10)
    ]
    results = d.step(batch)
    assert [r.op_results[0] for r in results] == [None] + [str(i) for i in range(9)]
    assert d.executed == 10 and d.in_flight == 0
    assert d.fast_paths == 10  # identical replica clocks: all fast
    (r,) = d.step(
        [(Dot(1, 11), Command.from_single(Rifl(1, 11), 0, "hot", KVOp.put("x")))]
    )
    assert r.op_results[0] == "9"


def test_device_runtime_newt_tcp_serving():
    """Real TCP clients served through the Newt timestamp round."""
    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=4, batch_size=32, protocol="newt"
        )
    )
    assert len(clients) == 4
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
    assert runtime.driver.executed == 4 * COMMANDS_PER_CLIENT
    assert runtime.driver.in_flight == 0


def test_newt_driver_multi_key():
    """Multi-key commands through the Newt device driver: per-key
    previous-value chains stay consistent (a command executes only once
    stable on every key)."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    d = NewtDeviceDriver(3, batch_size=16, key_buckets=64, key_width=2,
                         monitor_execution_order=True)
    cmds = []
    for i in range(6):
        keys = {"a": (KVOp.put(f"a{i}"),)} if i % 2 else {
            "a": (KVOp.put(f"a{i}"),),
            "b": (KVOp.put(f"b{i}"),),
        }
        cmds.append((Dot(1, i + 1), Command.from_keys(Rifl(1, i + 1), 0, keys)))
    results = d.step(cmds)
    assert d.executed == 6 and d.in_flight == 0
    by_key = {}
    for r in results:
        by_key.setdefault(r.key, []).append(r.op_results[0])
    assert by_key["a"] == [None, "a0", "a1", "a2", "a3", "a4"]
    assert by_key["b"] == [None, "b0", "b2"]


def test_caesar_driver_hot_key_chain():
    """The Caesar device driver orders a hot key by timestamp and the
    clock index carries across rounds (the fourth consensus shape
    served; caesar.rs:216-451)."""
    from fantoch_tpu.run.device_runner import CaesarDeviceDriver

    d = CaesarDeviceDriver(3, batch_size=16, key_buckets=64,
                           monitor_execution_order=True)
    batch = [
        (Dot(1, i + 1), Command.from_single(Rifl(1, i + 1), 0, "hot", KVOp.put(str(i))))
        for i in range(10)
    ]
    results = d.step(batch)
    assert [r.op_results[0] for r in results] == [None] + [str(i) for i in range(9)]
    assert d.executed == 10 and d.in_flight == 0
    assert d.fast_paths == 10  # consistent clock views: all fast
    (r,) = d.step(
        [(Dot(1, 11), Command.from_single(Rifl(1, 11), 0, "hot", KVOp.put("x")))]
    )
    assert r.op_results[0] == "9"


def test_caesar_driver_multi_key():
    """Multi-key commands through the Caesar device driver: per-key
    previous-value chains stay consistent (timestamp order is global, so
    a multi-key command holds one position on every key it touches)."""
    from fantoch_tpu.run.device_runner import CaesarDeviceDriver

    d = CaesarDeviceDriver(3, batch_size=16, key_buckets=64, key_width=2,
                           monitor_execution_order=True)
    cmds = []
    for i in range(6):
        keys = {"a": (KVOp.put(f"a{i}"),)} if i % 2 else {
            "a": (KVOp.put(f"a{i}"),),
            "b": (KVOp.put(f"b{i}"),),
        }
        cmds.append((Dot(1, i + 1), Command.from_keys(Rifl(1, i + 1), 0, keys)))
    results = d.step(cmds)
    assert d.executed == 6 and d.in_flight == 0
    by_key = {}
    for r in results:
        by_key.setdefault(r.key, []).append(r.op_results[0])
    assert by_key["a"] == [None, "a0", "a1", "a2", "a3", "a4"]
    assert by_key["b"] == [None, "b0", "b2"]


def test_device_runtime_caesar_tcp_serving():
    """Real TCP clients served through the Caesar round: the fourth
    protocol shape behind --device-step."""
    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=4, batch_size=32, protocol="caesar"
        )
    )
    assert len(clients) == 4
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.executed == 4 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0
    monitor = driver.store.monitor
    for key in monitor.keys():
        order = monitor.get_order(key)
        assert len(order) == len(set(order))


def test_sharded_driver_cross_shard_chain():
    """VERDICT r4 missing #2: shard_count=2 on one mesh.  A multi-shard
    command orders after its per-shard dependency chains on BOTH shards
    and before later commands on either — the device twin of the
    reference's cross-shard dep resolution
    (fantoch_ps/src/executor/graph/mod.rs:279-408)."""
    from fantoch_tpu.client.workload import Workload
    from fantoch_tpu.run.device_runner import DeviceDriver

    d = DeviceDriver(
        3, shard_count=2, batch_size=16, key_buckets=64, key_width=2,
        monitor_execution_order=True,
    )
    # pick one key per shard (workload hash rule: shard = key_hash % S)
    from fantoch_tpu.utils import key_hash

    key0 = next(f"a{i}" for i in range(100) if key_hash(f"a{i}") % 2 == 0)
    key1 = next(f"b{i}" for i in range(100) if key_hash(f"b{i}") % 2 == 1)

    def single(seq, key, value, shard):
        return (
            Dot(1, seq),
            Command.from_single(Rifl(1, seq), shard, key, KVOp.put(value)),
        )

    def multi(seq, v0, v1):
        return (
            Dot(1, seq),
            Command(Rifl(1, seq), {
                0: {key0: (KVOp.put(v0),)},
                1: {key1: (KVOp.put(v1),)},
            }),
        )

    batch = [
        single(1, key0, "s0a", 0),
        single(2, key1, "s1a", 1),
        multi(3, "m0", "m1"),
        single(4, key0, "s0b", 0),
        single(5, key1, "s1b", 1),
    ]
    results = d.step(batch)
    assert d.executed == 5 and d.in_flight == 0
    # per-key chains prove the multi-shard command landed between the
    # singles on BOTH shards
    by_key = {}
    for r in results:
        by_key.setdefault(r.key, []).append(r.op_results[0])
    assert by_key[key0] == [None, "s0a", "m0"]
    assert by_key[key1] == [None, "s1a", "m1"]
    mon = d.store.monitor
    assert mon.get_order(key0)[1] == Rifl(1, 3) == mon.get_order(key1)[1]


def test_device_runtime_sharded_tcp_cluster():
    """A 2-shard device-step server behind real TCP clients: multi-shard
    commands resolve cross-shard dependencies, every client completes,
    and the monitor agrees per key (the r4 'Done' criterion for sharded
    serving)."""
    config = Config(3, 1, shard_count=2)
    workload = Workload(
        shard_count=2,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,  # two keys -> frequently two shards
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=4, batch_size=32,
            key_width=2, key_buckets=64,
        )
    )
    assert len(clients) == 4
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.executed == 4 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0
    monitor = driver.store.monitor
    for key in monitor.keys():
        order = monitor.get_order(key)
        assert len(order) == len(set(order))
    assert runtime.failure is None


@pytest.mark.parametrize("protocol", ["epaxos", "newt"])
def test_device_runtime_sharded_pipelined_tcp_cluster(protocol):
    """Sharded serving through the pipelined dispatch/drain loop: the
    pipelining scaffold lives in the shared driver core, so both sharded
    drivers (dep-commit and Newt timestamp) must serve saturated
    multi-shard traffic with cross-shard dependencies intact — the
    missing cells of the (sharded x pipelined) matrix."""
    config = Config(3, 1, shard_count=2)
    workload = Workload(
        shard_count=2,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=4, batch_size=8,
            key_width=2, key_buckets=64,
            open_loop_interval_ms=1,
            protocol=protocol,
            pipeline=True,  # auto would disable it on the CPU test backend
        )
    )
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.executed == 4 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0 and not driver.has_outstanding
    monitor = driver.store.monitor
    for key in monitor.keys():
        order = monitor.get_order(key)
        assert len(order) == len(set(order))
    assert runtime.failure is None


def test_sharded_newt_driver_cross_shard_chain():
    """shard_count=2 on the Newt device driver: a multi-shard command's
    timestamp orders it after its per-shard predecessors and before later
    commands on BOTH shards (the MShardCommit max-clock aggregation on
    one mesh)."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver
    from fantoch_tpu.utils import key_hash

    d = NewtDeviceDriver(
        3, shard_count=2, batch_size=16, key_buckets=64, key_width=2,
        monitor_execution_order=True,
    )
    key0 = next(f"a{i}" for i in range(100) if key_hash(f"a{i}") % 2 == 0)
    key1 = next(f"b{i}" for i in range(100) if key_hash(f"b{i}") % 2 == 1)

    def single(seq, key, value, shard):
        return (
            Dot(1, seq),
            Command.from_single(Rifl(1, seq), shard, key, KVOp.put(value)),
        )

    def multi(seq, v0, v1):
        return (
            Dot(1, seq),
            Command(Rifl(1, seq), {
                0: {key0: (KVOp.put(v0),)},
                1: {key1: (KVOp.put(v1),)},
            }),
        )

    batch = [
        single(1, key0, "s0a", 0),
        single(2, key1, "s1a", 1),
        multi(3, "m0", "m1"),
        single(4, key0, "s0b", 0),
        single(5, key1, "s1b", 1),
    ]
    results = d.step(batch)
    assert d.executed == 5 and d.in_flight == 0
    by_key = {}
    for r in results:
        by_key.setdefault(r.key, []).append(r.op_results[0])
    assert by_key[key0] == [None, "s0a", "m0"]
    assert by_key[key1] == [None, "s1a", "m1"]
    mon = d.store.monitor
    assert mon.get_order(key0)[1] == Rifl(1, 3) == mon.get_order(key1)[1]


@pytest.mark.slow
def test_device_runtime_sharded_newt_tcp_cluster():
    """A 2-shard Newt device-step server behind real TCP clients:
    multi-shard commands commit at the max of their shards' clocks,
    every client completes, and per-key execution order is
    duplicate-free."""
    config = Config(3, 1, shard_count=2)
    workload = Workload(
        shard_count=2,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=4, batch_size=32,
            key_width=2, key_buckets=64, protocol="newt",
        )
    )
    assert len(clients) == 4
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.executed == 4 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0
    monitor = driver.store.monitor
    for key in monitor.keys():
        order = monitor.get_order(key)
        assert len(order) == len(set(order))
    assert runtime.failure is None


@pytest.mark.slow
def test_sharded_newt_driver_randomized_soak():
    """Randomized soak of the 2-shard Newt driver: 12 rounds of mixed
    single/multi-shard commands with a degraded stretch in the middle
    (shard 1's majority dead -> its commands and multi-shard commands
    stall on stability, then drain on recovery).  Invariants: everything
    eventually executes exactly once, per-key execution order is
    duplicate-free, and the registry drains."""
    import random as _random

    from fantoch_tpu.parallel import mesh_step
    from fantoch_tpu.run.device_runner import NewtDeviceDriver
    from fantoch_tpu.utils import key_hash

    rng = _random.Random(29)
    d = NewtDeviceDriver(
        3, shard_count=2, batch_size=16, key_buckets=64, key_width=2,
        pending_capacity=32, monitor_execution_order=True,
    )
    keys0 = [next(f"a{i}{j}" for i in range(100)
                  if key_hash(f"a{i}{j}") % 2 == 0) for j in range(3)]
    keys1 = [next(f"b{i}{j}" for i in range(100)
                  if key_hash(f"b{i}{j}") % 2 == 1) for j in range(3)]
    degraded_step = mesh_step.jit_newt_step(
        d._mesh, f=1, shard_count=2, live_replicas=4
    )
    healthy_step = d._step

    seq = 0
    issued = 0
    multis = 0
    for round_no in range(12):
        d._step = degraded_step if round_no in (4, 5, 6) else healthy_step
        batch = list(d.take_requeue())
        for _ in range(rng.randrange(1, 9)):
            seq += 1
            issued += 1
            kind = rng.random()
            if kind < 0.4:
                cmd = Command.from_single(
                    Rifl(1, seq), 0, rng.choice(keys0), KVOp.put(f"v{seq}")
                )
            elif kind < 0.8:
                cmd = Command.from_single(
                    Rifl(1, seq), 1, rng.choice(keys1), KVOp.put(f"v{seq}")
                )
            else:
                multis += 1
                cmd = Command(Rifl(1, seq), {
                    0: {rng.choice(keys0): (KVOp.put(f"m0{seq}"),)},
                    1: {rng.choice(keys1): (KVOp.put(f"m1{seq}"),)},
                })
            batch.append((Dot(1, seq), cmd))
        d.step(batch[: d.batch_size])
        for extra in batch[d.batch_size:]:
            d._requeue.append(extra)

    # drain: healthy empty rounds until everything in flight executes
    d._step = healthy_step
    for _ in range(8):
        if d.in_flight == 0 and not d._requeue:
            break
        batch = list(d.take_requeue())
        d.step(batch[: d.batch_size])
        for extra in batch[d.batch_size:]:
            d._requeue.append(extra)
    assert d.in_flight == 0 and not d._requeue
    assert d.executed == issued
    mon = d.store.monitor
    seen = 0
    for key in mon.keys():
        order = mon.get_order(key)
        assert len(order) == len(set(order)), f"duplicate execution on {key}"
        seen += len(order)
    # every single-shard command appears on one key, every multi-shard
    # command on exactly two (keys0/keys1 are parity-disjoint) — a
    # half-executed multi-shard command would break the count
    assert seen == issued + multis


def _put(src, seq, key, value):
    return (Dot(src, seq), Command.from_single(Rifl(src, seq), 0, key, KVOp.put(value)))


def test_caesar_driver_degraded_requeue_recovery():
    """Caesar driver parity with the Newt/Paxos degraded cases: a round
    with the fast quorum unreachable commits nothing — uncommitted rows
    carry on the device (capacity permitting) and overflow to the host
    requeue — and a healthy round drains everything exactly once with a
    consistent hot-key previous-value chain."""
    from fantoch_tpu.parallel import mesh_step
    from fantoch_tpu.run.device_runner import CaesarDeviceDriver

    import jax
    import jax.numpy as jnp

    from fantoch_tpu.utils import key_hash

    d = CaesarDeviceDriver(
        4, batch_size=8, key_buckets=64, pending_capacity=4,
        monitor_execution_order=True,
    )
    healthy = d._step
    values = {i + 1: f"v{i + 1}" for i in range(12)}
    results = {}

    def absorb(rs):
        for r in rs:
            assert r.rifl.sequence not in results, "duplicate result"
            results[r.rifl.sequence] = r.op_results[0]

    # healthy round seeds the clock index on the hot bucket
    absorb(d.step([_put(1, s, "hot", values[s]) for s in range(1, 5)]))
    assert sorted(results) == [1, 2, 3, 4]

    # stagger replica 0's hot-bucket ceiling: the next proposals diverge
    # across the fast quorum -> retry path; with live=1 < write quorum
    # the retry cannot commit, so everything carries
    bucket = key_hash("hot") % 64
    kc = np.array(d._state.key_clock)
    kc[0, bucket] += 7
    d._state = d._state._replace(
        key_clock=jax.device_put(jnp.asarray(kc), d._state.key_clock.sharding)
    )
    d._step = mesh_step.jit_caesar_step(d._mesh, num_replicas=4, live_replicas=1)
    absorb(d.step([_put(1, s, "hot", values[s]) for s in range(5, 13)]))
    assert sorted(results) == [1, 2, 3, 4], "divergent views must not commit"
    requeued = d.take_requeue()
    assert len(requeued) == 4, "pending capacity 4 of 8 uncommitted"
    assert d.in_flight == 4  # the device-carried half stays registered

    d._step = healthy
    absorb(d.step(requeued))
    for _ in range(4):
        if d.in_flight == 0 and not d._requeue:
            break
        absorb(d.step(d.take_requeue()))
    assert d.in_flight == 0
    assert sorted(results) == sorted(values)
    # previous-value chain: execution order's result sequence is exactly
    # the values in monitor order, shifted by one
    order = d.store.monitor.get_order("hot")
    assert len(order) == 12 and len(set(order)) == 12
    chain = [results[r.sequence] for r in order]
    expected = [None] + [values[r.sequence] for r in order[:-1]]
    assert chain == expected


def test_epaxos_gid_epoch_reset_with_carried_command():
    """VERDICT r4 missing #6: the gid space rebases instead of dying by
    assert — including a command carried uncommitted across the epoch
    boundary, whose pend_gid / registry key / key-clock view all rebase
    together and whose per-key chain survives.

    Setup: one degraded (live=1) round executes A fast but only replica 0
    learns it, so B on the same key splits the fast quorum, misses, fails
    Synod (1 < write quorum) and carries.  The gid counter is then jumped
    to the reset threshold; the next step rebases by B's gid (the oldest
    in flight), clamps A's stale key-clock entry to -1, and B + C commit
    with the a->b->c value chain intact."""
    import jax
    import jax.numpy as jnp

    from fantoch_tpu.run.device_runner import DeviceDriver

    d = _driver(live_replicas=1)
    (ra,) = d.step([_put(1, 1, "k", "a")])
    assert ra.op_results[0] is None and d.executed == 1

    assert d.step([_put(1, 2, "k", "b")]) == []  # B: fast miss, carries
    assert d.in_flight == 1

    jump = DeviceDriver.GID_RESET_THRESHOLD - 8
    span = jump - d._next_gid
    st = d._state
    # jump both mirrors of the gid counter, keeping live gids live: shift
    # B's gid too so the in-flight span stays rebasable
    pend_gid = np.asarray(st.pend_gid)
    pend_gid = np.where(pend_gid >= 0, pend_gid + span, -1)
    d._state = st._replace(
        next_gid=jax.device_put(jnp.int32(jump), st.next_gid.sharding),
        pend_gid=jax.device_put(jnp.asarray(pend_gid), st.pend_gid.sharding),
    )
    d._next_gid = jump
    d._cmds = {g + span: v for g, v in d._cmds.items()}

    results = d.step([_put(1, 3, "k", "c")])
    assert d.gid_epochs == 1
    assert d._next_gid < DeviceDriver.GID_RESET_THRESHOLD
    # the epoch clamp erased the divergent key-clock entry, so B commits
    # fast and C chains behind it — values prove the order a -> b -> c
    assert [r.op_results[0] for r in results] == ["a", "b"]
    assert d.in_flight == 0 and d.executed == 3
    order = d.store.monitor.get_order("k")
    assert len(order) == len(set(order)) == 3


def test_newt_clock_window_advance():
    """Newt timestamp clocks rebase against the stable floor when they
    near int32: serving continues across the window advance with per-key
    chains intact (ops/table_ops.ClockWindow applied to the device
    plane)."""
    import jax
    import jax.numpy as jnp

    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    d = NewtDeviceDriver(3, batch_size=16, key_buckets=64,
                         monitor_execution_order=True)
    high = d.CLOCK_RESET_THRESHOLD + 10
    st = d._state
    d._state = st._replace(
        key_clock=jax.device_put(
            jnp.full_like(st.key_clock, high), st.key_clock.sharding
        ),
        vote_frontier=jax.device_put(
            jnp.full_like(st.vote_frontier, high), st.vote_frontier.sharding
        ),
    )
    results = d.step([_put(1, i + 1, "hot", str(i)) for i in range(5)])
    assert [r.op_results[0] for r in results] == [None, "0", "1", "2", "3"]
    assert d.clock_epochs == 1
    assert d.stable_watermark >= high  # floor accumulates: still monotone
    # next round proposes from the rebased (small) clocks and chains on
    (r,) = d.step([_put(1, 6, "hot", "x")])
    assert r.op_results[0] == "4"
    assert d.executed == 6 and d.in_flight == 0


def test_seq_window_advance_newt():
    """Dot sequences beyond int32 ride the 31-bit window: the driver
    rebases device columns + host mirror + registry keys and keeps
    serving (VERDICT r4 missing #6, the device_runner.py:319 assert)."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    d = NewtDeviceDriver(3, batch_size=16, key_buckets=64,
                         monitor_execution_order=True)
    S = 2**31 - 4  # a long-lived client plane's sequence space
    results = d.step([_put(1, S + i, "hot", str(i)) for i in range(5)])
    assert [r.op_results[0] for r in results] == [None, "0", "1", "2", "3"]
    assert d.seq_epochs == 1
    # sequences keep growing past 2^31 across rounds
    (r,) = d.step([_put(1, S + 10, "hot", "x")])
    assert r.op_results[0] == "4"
    assert d.executed == 6 and d.in_flight == 0


def test_paxos_slot_epoch_reset():
    """The slot log rebases against the contiguous exec frontier before
    int32 exhaustion; the watermark stays monotone across the epoch."""
    import jax
    import jax.numpy as jnp

    from fantoch_tpu.run.device_runner import PaxosDeviceDriver

    d = PaxosDeviceDriver(3, f=1, batch_size=16, monitor_execution_order=True)
    results = d.step([_put(1, i + 1, "k", str(i)) for i in range(3)])
    assert len(results) == 3

    jump = PaxosDeviceDriver.SLOT_RESET_THRESHOLD - 8
    st = d._state
    d._state = st._replace(
        next_slot=jax.device_put(jnp.int32(jump), st.next_slot.sharding),
        exec_frontier=jax.device_put(jnp.int32(jump), st.exec_frontier.sharding),
    )
    d._next_slot = jump

    (r,) = d.step([_put(1, 4, "k", "c")])
    assert d.slot_epochs == 1
    assert r.op_results[0] == "2"  # chain intact across the epoch
    assert d.stable_watermark == jump + 1  # monotone: base + new frontier
    assert d.in_flight == 0 and d.executed == 4


def test_paxos_driver_slot_chain():
    """The leader-based slot round behind the driver seam: execution is
    contiguous slot order == submission order, the key chain reflects it,
    and the frontier carries across rounds (third protocol family
    served; fantoch_ps/src/bin/fpaxos.rs analog)."""
    from fantoch_tpu.run.device_runner import PaxosDeviceDriver

    d = PaxosDeviceDriver(3, f=1, batch_size=16, monitor_execution_order=True)
    batch = [
        (Dot(1, i + 1), Command.from_single(Rifl(1, i + 1), 0, "hot", KVOp.put(str(i))))
        for i in range(10)
    ]
    results = d.step(batch)
    assert [r.op_results[0] for r in results] == [None] + [str(i) for i in range(9)]
    assert d.executed == 10 and d.in_flight == 0
    assert d.stable_watermark == 10
    (r,) = d.step(
        [(Dot(1, 11), Command.from_single(Rifl(1, 11), 0, "hot", KVOp.put("x")))]
    )
    assert r.op_results[0] == "9"
    assert d.stable_watermark == 11


def test_paxos_driver_degraded_requeue_recovery():
    """Slot stickiness + overflow slot-rollback at the driver seam: a
    degraded round commits nothing, overflow beyond the pending buffer
    re-queues the highest slots, and after recovery every command
    executes exactly once in a dense slot log."""
    from fantoch_tpu.parallel import mesh_step
    from fantoch_tpu.run.device_runner import PaxosDeviceDriver

    d = PaxosDeviceDriver(
        3, f=1, batch_size=8, pending_capacity=4,
        live_replicas=1, monitor_execution_order=True,
    )
    batch = [
        (Dot(1, i + 1), Command.from_single(Rifl(1, i + 1), 0, "k", KVOp.put(str(i))))
        for i in range(8)
    ]
    assert d.step(batch) == []
    requeued = d.take_requeue()
    # 8 valid rows, capacity 4: the 4 highest slots were dropped and
    # their commands re-queued under their original dots
    assert [dot.sequence for dot, _ in requeued] == [5, 6, 7, 8]
    assert d.in_flight == 4

    # recovery: all replicas answer again (the runtime would re-jit the
    # step the same way on failure-detector feedback)
    d._step = mesh_step.jit_paxos_step(d._mesh, f=1, num_replicas=3)
    results = d.step(requeued)
    assert d.executed == 8 and d.in_flight == 0
    # carried slots (0-3) execute before the reassigned ones; per-key
    # chain shows every put exactly once
    assert [r.op_results[0] for r in results] == [None, "0", "1", "2", "3", "4", "5", "6"]
    order = d.store.monitor.get_order("k")
    assert len(order) == len(set(order)) == 8


def test_device_runtime_paxos_tcp_serving():
    """Real TCP clients served through the leader-based slot round:
    --device-step --protocol fpaxos end-to-end."""
    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,  # slot order needs no key rows: any width
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=4, batch_size=32, protocol="fpaxos"
        )
    )
    assert len(clients) == 4
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.executed == 4 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0
    # two keys per command: a rifl appears once in each touched key's order
    monitor = driver.store.monitor
    for key in monitor.keys():
        order = monitor.get_order(key)
        assert len(order) == len(set(order))


def test_newt_runtime_requeue_after_degraded_round():
    """VERDICT r4 weak #5: a Newt command that overflows the pending
    buffer in a degraded round re-enters the submit queue under the same
    dot and completes after recovery — through the real TCP runtime —
    with per-key order intact.

    Topology chosen to produce *uncommitted* (requeue-able) overflow:
    n=5, f=2, one live replica.  The first degraded round still commits
    its batch on the fast path (all proposals agree: max-count f is met),
    but those commands cannot stabilize without live voters; from the
    next round the lone live replica's clock has diverged, the fast path
    misses (max reported by 1 < f) and Synod gets 1 < f+1 acks, so later
    commands stay uncommitted.  With 24 hot-key commands against a
    16-slot pending buffer the committed backlog (8, carried with
    priority) plus uncommitted rows overflow — the overflowed uncommitted
    tail cycles through take_requeue() under its original dots."""
    from fantoch_tpu.parallel import mesh_step
    from fantoch_tpu.run.client_runner import run_clients
    from fantoch_tpu.run.device_runner import DeviceRuntime
    from fantoch_tpu.run.harness import free_port

    async def go():
        config = Config(5, 2, shard_count=1)
        port = free_port()
        runtime = DeviceRuntime(
            config,
            ("127.0.0.1", port),
            protocol="newt",
            batch_size=8,
            key_buckets=64,
            pending_capacity=16,
            live_replicas=1,
            monitor_execution_order=True,
        )
        await runtime.start()
        try:
            workload = Workload(
                shard_count=1,
                key_gen=ConflictRateKeyGen(100),  # one hot key: max contention
                keys_per_command=1,
                commands_per_client=8,
                payload_size=1,
            )
            # open-loop clients keep submitting without waiting, pushing
            # past the pending capacity while degraded
            client_task = asyncio.ensure_future(
                run_clients(
                    [1, 2, 3], {0: ("127.0.0.1", port)}, workload,
                    open_loop_interval_ms=5,
                )
            )
            # wait until all 24 commands are in flight with rounds cycling
            # and nothing executing: 24 > pending_capacity=16 proves the
            # overflow tail is living in the requeue loop
            driver = runtime.driver
            for _ in range(400):
                await asyncio.sleep(0.025)
                if driver.rounds >= 6 and driver.in_flight == 24:
                    break
            assert driver.in_flight == 24 and driver.rounds >= 6
            assert driver.executed == 0
            # recovery: swap in the healthy step (what a failure-detector
            # integration would do); in-flight commands must now commit
            driver._step = mesh_step.jit_newt_step(
                driver._mesh, f=config.f, tiny_quorums=False
            )
            clients = await client_task
            for client in clients.values():
                assert client.issued_commands == 8
            assert driver.executed == 24
            assert driver.in_flight == 0
            order = driver.store.monitor.get_order(
                next(iter(driver.store.monitor.keys()))
            )
            assert len(order) == len(set(order)) == 24
            assert runtime.failure is None
        finally:
            await runtime.stop()

    asyncio.run(go())


def test_device_runtime_survives_bad_client():
    """A client submitting a command wider than the compiled key_width is
    rejected at the session boundary with an empty CommandResult — the
    driver's asserts are unreachable from the network, the bad session
    keeps serving valid commands, and a concurrent well-behaved client
    completes its workload (per-connection failure isolation,
    fantoch/src/run/task/process.rs:320-325)."""
    from fantoch_tpu.run.device_runner import DeviceRuntime
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.client_runner import run_clients
    from fantoch_tpu.run.prelude import ClientHi, ClientHiAck, Submit, ToClient
    from fantoch_tpu.run.rw import Rw
    from fantoch_tpu.utils import key_hash

    key_buckets = 64
    # two keys guaranteed to land in distinct buckets (over-wide for kw=1)
    key_a = "a"
    key_b = next(
        k
        for k in (f"b{i}" for i in range(1000))
        if key_hash(k) % key_buckets != key_hash(key_a) % key_buckets
    )

    async def go():
        config = Config(3, 1, shard_count=1)
        port = free_port()
        runtime = DeviceRuntime(
            config,
            ("127.0.0.1", port),
            batch_size=16,
            key_buckets=key_buckets,
            key_width=1,
            monitor_execution_order=True,
        )
        await runtime.start()
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            rw = Rw(reader, writer)
            await rw.send(ClientHi([99]))
            assert isinstance(await rw.recv(), ClientHiAck)
            # over-wide submit: rejected, not crashed
            bad = Command.from_keys(
                Rifl(99, 1), 0,
                {key_a: (KVOp.put("x"),), key_b: (KVOp.put("y"),)},
            )
            await rw.send(Submit(bad))
            reply = await rw.recv()
            assert isinstance(reply, ToClient)
            assert reply.cmd_result.rifl == Rifl(99, 1)
            assert reply.cmd_result.ready  # zero-key error result
            # the same session still serves valid commands afterwards
            good = Command.from_single(Rifl(99, 2), 0, key_a, KVOp.put("z"))
            await rw.send(Submit(good))
            reply = await rw.recv()
            assert isinstance(reply, ToClient)
            assert reply.cmd_result.rifl == Rifl(99, 2)
            writer.close()

            # a concurrent well-behaved client completes its workload
            workload = Workload(
                shard_count=1,
                key_gen=ConflictRateKeyGen(50),
                keys_per_command=1,
                commands_per_client=5,
                payload_size=1,
            )
            clients = await run_clients([1], {0: ("127.0.0.1", port)}, workload)
            assert clients[1].issued_commands == 5
            assert runtime.failure is None
        finally:
            await runtime.stop()
        return runtime

    runtime = asyncio.run(go())
    # the rejected command never reached the driver
    assert runtime.driver.executed == 1 + 5


def test_device_runtime_newt_multi_key_tcp():
    """keys_per_command=2 served through the Newt timestamp round."""
    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=5,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config, workload, client_count=2, batch_size=16,
            key_width=2, protocol="newt",
        )
    )
    for client in clients.values():
        assert client.issued_commands == 5
    assert runtime.driver.executed == 10
    assert runtime.driver.in_flight == 0


def test_driver_pipelined_equivalence():
    """step_pipelined returns each round's results one call late and, with
    a final flush, produces exactly the sync driver's execution: same
    per-round result values, same per-key monitor order, same tallies
    (the overlap must be pure scheduling, never reordering)."""
    def batches():
        out, seq = [], 0
        for r in range(6):
            batch = []
            for j in range(4):
                seq += 1
                key = "hot" if (seq % 2) else f"priv{j}"
                batch.append(_put(1, seq, key, f"v{seq}"))
            out.append(batch)
        return out

    d_sync, d_pipe = _driver(), _driver()
    sync_rounds = [d_sync.step(b) for b in batches()]
    pipe_rounds = [d_pipe.step_pipelined(b) for b in batches()]
    assert pipe_rounds[0] == []  # one round of delivery lag
    pipe_rounds.append(d_pipe.flush_pipeline())
    assert not d_pipe.has_outstanding

    def flat(rounds):
        return [(r.rifl, r.key, tuple(r.op_results)) for rr in rounds for r in rr]

    assert flat(pipe_rounds) == flat(sync_rounds)
    # the lag is exactly one round: pipelined round k+1 == sync round k
    assert flat(pipe_rounds[1:2]) == flat(sync_rounds[0:1])
    assert d_pipe.executed == d_sync.executed == 24
    assert d_pipe.in_flight == 0
    for key in d_sync.store.monitor.keys():
        assert (
            d_pipe.store.monitor.get_order(key)
            == d_sync.store.monitor.get_order(key)
        )


@pytest.mark.parametrize("protocol", ["newt", "caesar", "fpaxos"])
def test_dot_driver_pipelined_equivalence(protocol):
    """The Newt/Caesar/Paxos drivers gain the dispatch/drain split:
    pipelined rounds lag by one call and, with a final flush, reproduce
    the sync driver's execution exactly — results, per-key monitor
    order, and tallies (identity comes from the step outputs, so no host
    mirror can drift while a round is in flight)."""
    from fantoch_tpu.run.device_runner import (
        CaesarDeviceDriver,
        NewtDeviceDriver,
        PaxosDeviceDriver,
    )

    cls = {"newt": NewtDeviceDriver, "caesar": CaesarDeviceDriver,
           "fpaxos": PaxosDeviceDriver}[protocol]
    mk = lambda: cls(3, batch_size=16, key_buckets=64,  # noqa: E731
                     monitor_execution_order=True)

    def batches():
        out, seq = [], 0
        for _r in range(5):
            batch = []
            for j in range(4):
                seq += 1
                key = "hot" if (seq % 2) else f"priv{j}"
                batch.append(_put(1, seq, key, f"v{seq}"))
            out.append(batch)
        return out

    d_sync, d_pipe = mk(), mk()
    sync_rounds = [d_sync.step(b) for b in batches()]
    pipe_rounds = [d_pipe.step_pipelined(b) for b in batches()]
    assert pipe_rounds[0] == []  # one round of delivery lag
    pipe_rounds.append(d_pipe.flush_pipeline())
    assert not d_pipe.has_outstanding
    assert d_pipe.pipelined_rounds == 4

    def flat(rounds):
        return [(r.rifl, r.key, tuple(r.op_results)) for rr in rounds for r in rr]

    assert flat(pipe_rounds) == flat(sync_rounds)
    assert flat(pipe_rounds[1:2]) == flat(sync_rounds[0:1])
    assert d_pipe.executed == d_sync.executed == 20
    assert d_pipe.in_flight == 0
    for key in d_sync.store.monitor.keys():
        assert (
            d_pipe.store.monitor.get_order(key)
            == d_sync.store.monitor.get_order(key)
        )


def test_newt_pipelined_clock_threshold_flushes_outstanding():
    """A Newt clock-window advance must never run with a round in
    flight: when the max committed clock nears the reset threshold,
    step_pipelined retires the outstanding round first (and the drain
    asserts the invariant)."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    d = NewtDeviceDriver(3, batch_size=8, key_buckets=16,
                         pending_capacity=8,
                         monitor_execution_order=True)
    # force the flush condition without 2^31 rounds of work
    d._max_clock = NewtDeviceDriver.CLOCK_RESET_THRESHOLD - 1
    r1 = d.step_pipelined([_put(1, 1, "k", "a")])
    assert r1 == [] and d.has_outstanding
    # threshold trips: the next pipelined call must flush first
    assert d._pipeline_flush_needed([_put(1, 2, "k", "b")])
    r2 = d.step_pipelined([_put(1, 2, "k", "b")])
    # the early flush returned round 1's results; round 2 is in flight
    assert [r.op_results[0] for r in r2] == [None]
    assert d.has_outstanding
    r3 = d.flush_pipeline()
    assert [r.op_results[0] for r in r3] == ["a"]
    assert d.in_flight == 0


def test_pipelined_gid_reset_flushes_outstanding():
    """The gid epoch reset rebases the registry that drain reads, so
    step_pipelined must retire the outstanding round *before* resetting
    (the early-flush branch); the reset then proceeds and chains stay
    intact across it."""
    d = _driver(batch_size=16)
    assert d.step_pipelined([_put(1, 1, "k", "a")]) == []
    assert d.has_outstanding
    # lower the threshold on this instance so the next dispatch triggers
    d.GID_RESET_THRESHOLD = d._next_gid + d.batch_size
    r1 = d.step_pipelined([_put(1, 2, "k", "b")])
    # the early flush returned round 1's results ahead of the reset
    assert [r.op_results[0] for r in r1] == [None]
    assert d.gid_epochs == 1 and d.has_outstanding
    r2 = d.flush_pipeline()
    assert [r.op_results[0] for r in r2] == ["a"]
    assert d.executed == 2 and d.in_flight == 0
    order = d.store.monitor.get_order("k")
    assert len(order) == len(set(order)) == 2


@pytest.mark.parametrize("protocol", ["epaxos", "newt", "caesar", "fpaxos"])
def test_device_runtime_pipelined_tcp_serving(protocol):
    """Saturated serving engages the pipelined loop (batch_size smaller
    than the standing queue) and still answers every client with per-key
    order agreement — the TCP twin of the equivalence test; the Newt
    driver serves through the same dispatch/drain scaffold."""
    config = Config(3, 1, shard_count=1)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config,
            workload,
            client_count=4,
            batch_size=8,
            open_loop_interval_ms=1,
            protocol=protocol,
            pipeline=True,  # auto would disable it on the CPU test backend
        )
    )
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
        assert len(list(client.data().latency_data())) == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.executed == 4 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0 and not driver.has_outstanding
    # engagement itself is asserted deterministically in
    # test_runtime_pipeline_engages_on_backlog (whether the open-loop
    # firehose outpaces the rounds here is host-speed-dependent)
    monitor = driver.store.monitor
    seen = [rifl for key in monitor.keys() for rifl in monitor.get_order(key)]
    assert len(seen) == len(set(seen)) == 4 * COMMANDS_PER_CLIENT


@pytest.mark.parametrize("depth", [1, 2, 3])
@pytest.mark.parametrize("proto_cls", ["epaxos", "newt"])
def test_depth_k_pipelined_parity(proto_cls, depth):
    """The depth-K loop is pure scheduling: at every depth the pipelined
    run (with a mid-stream flush_pipeline thrown in) produces exactly
    the sync driver's execution — same per-round result values in the
    same order, same per-key monitor order, same tallies."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    cls = {"epaxos": DeviceDriver, "newt": NewtDeviceDriver}[proto_cls]
    mk = lambda: cls(3, batch_size=16, key_buckets=64,  # noqa: E731
                     monitor_execution_order=True)

    def batches():
        out, seq = [], 0
        for _r in range(7):
            batch = []
            for j in range(4):
                seq += 1
                key = "hot" if (seq % 2) else f"priv{j}"
                batch.append(_put(1, seq, key, f"v{seq}"))
            out.append(batch)
        return out

    d_sync, d_pipe = mk(), mk()
    d_pipe.pipeline_depth = depth
    sync_rounds = [d_sync.step(b) for b in batches()]
    pipe_rounds = []
    for r, b in enumerate(batches()):
        pipe_rounds.append(d_pipe.step_pipelined(b))
        if r == 3:  # mid-stream flush must retire in order, then refill
            pipe_rounds.append(d_pipe.flush_pipeline())
            assert not d_pipe.has_outstanding
    pipe_rounds.append(d_pipe.flush_pipeline())
    assert not d_pipe.has_outstanding

    def flat(rounds):
        return [(r.rifl, r.key, tuple(r.op_results)) for rr in rounds for r in rr]

    assert flat(pipe_rounds) == flat(sync_rounds)
    # the lag is exactly min(depth, rounds so far): round 0's results
    # surface on call `depth`
    if depth < 4:
        assert flat(pipe_rounds[:depth]) == []
        assert flat(pipe_rounds[depth : depth + 1]) == flat(sync_rounds[0:1])
    assert d_pipe.executed == d_sync.executed == 28
    assert d_pipe.in_flight == 0
    for key in d_sync.store.monitor.keys():
        assert (
            d_pipe.store.monitor.get_order(key)
            == d_sync.store.monitor.get_order(key)
        )
    counters = d_pipe.device_counters()
    assert counters["device_pipeline_depth"] == depth
    assert 0.0 <= counters["device_idle_frac"] <= 1.0
    assert counters["device_busy_ms"] <= counters["device_span_ms"] + 1e-6


@pytest.mark.parametrize("depth", [2, 3])
def test_seq_window_advance_races_inflight_dispatches(depth):
    """A dot-sequence window advance may only run with the pipeline
    empty: forcing a tiny window mid-stream must early-flush the
    in-flight rounds, rebase, and keep bit-for-bit parity with a sync
    driver under the same tiny window."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    def mk():
        d = NewtDeviceDriver(3, batch_size=8, key_buckets=64,
                             monitor_execution_order=True)
        d.SEQ_WINDOW_MAX = 24  # instance override: advance every ~3 rounds
        return d

    def batches():
        out, seq = [], 0
        for _r in range(10):
            batch = []
            for _j in range(4):
                seq += 1
                batch.append(_put(1, seq, "hot" if seq % 2 else "cold",
                                  f"v{seq}"))
            out.append(batch)
        return out

    d_sync, d_pipe = mk(), mk()
    d_pipe.pipeline_depth = depth
    sync_rounds = [d_sync.step(b) for b in batches()]
    pipe_rounds = [d_pipe.step_pipelined(b) for b in batches()]
    pipe_rounds.append(d_pipe.flush_pipeline())

    def flat(rounds):
        return [(r.rifl, r.key, tuple(r.op_results)) for rr in rounds for r in rr]

    assert flat(pipe_rounds) == flat(sync_rounds)
    assert d_pipe.seq_epochs >= 1  # the window really advanced mid-run
    assert d_pipe.seq_epochs == d_sync.seq_epochs
    assert d_pipe.executed == d_sync.executed == 40
    for key in d_sync.store.monitor.keys():
        assert (
            d_pipe.store.monitor.get_order(key)
            == d_sync.store.monitor.get_order(key)
        )


def test_pipelined_requeue_interleaving():
    """Device pending-buffer overflow requeues interleave with the
    depth-2 pipeline: degraded rounds carry + overflow while rounds are
    in flight, requeued commands re-enter through pipelined rounds, and
    after healing everything executes exactly once with the hot-key
    previous-value chain intact.  Topology per
    test_newt_runtime_requeue_after_degraded_round: n=5/f=2/live=1 makes
    the first degraded round's commits a carried (priority) backlog and
    later rounds' rows uncommitted — so the overflow tail is
    requeue-able, never committed."""
    from fantoch_tpu.parallel import mesh_step
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    d = NewtDeviceDriver(5, f=2, batch_size=8, key_buckets=64,
                         pending_capacity=12,
                         monitor_execution_order=True)
    d.pipeline_depth = 2
    healthy = d._step
    values = {i + 1: f"v{i + 1}" for i in range(20)}
    results = {}

    def absorb(rs):
        for r in rs:
            assert r.rifl.sequence not in results, "duplicate result"
            results[r.rifl.sequence] = r.op_results[0]

    # healthy pipelined round seeds the hot-key chain
    absorb(d.step_pipelined([_put(1, s, "hot", values[s]) for s in range(1, 5)]))
    # degrade to one live replica with rounds in flight: round d1 still
    # commits (agreeing proposals) but cannot stabilize; round d2's rows
    # stay uncommitted and, with the committed backlog carried first,
    # overflow the 12-slot pending buffer into the host requeue
    d._step = mesh_step.jit_newt_step(d._mesh, f=2, live_replicas=1)
    absorb(d.step_pipelined([_put(1, s, "hot", values[s]) for s in range(5, 13)]))
    absorb(d.step_pipelined([_put(1, s, "hot", values[s]) for s in range(13, 21)]))
    absorb(d.flush_pipeline())
    assert d.in_flight > 0  # carried (committed backlog + uncommitted)
    requeued = d.take_requeue()
    assert requeued, "pending capacity 12 must have overflowed"

    # heal and feed requeues back through pipelined rounds until drained
    # (empty rounds at the tail let the carried backlog stabilize)
    d._step = healthy
    pending = requeued
    for _ in range(30):
        absorb(d.step_pipelined(pending[:4]))
        pending = pending[4:] + d.take_requeue()
        if not pending and d.in_flight == 0 and not d.has_outstanding:
            break
    absorb(d.flush_pipeline())
    while d.in_flight or d._requeue:
        absorb(d.step(d.take_requeue()))
    assert sorted(results) == sorted(values)
    order = d.store.monitor.get_order("hot")
    assert len(order) == 20 and len(set(order)) == 20
    chain = [results[r.sequence] for r in order]
    expected = [None] + [values[r.sequence] for r in order[:-1]]
    assert chain == expected


def test_chained_pipelined_parity():
    """step_chained_pipelined (S in-dispatch rounds x depth-K in-flight
    chains) reproduces the sync per-round execution exactly, like
    step_chained but with chains carried in flight."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    mk = lambda: NewtDeviceDriver(3, batch_size=8, key_buckets=64,  # noqa: E731
                                  monitor_execution_order=True)

    def batches():
        out, seq = [], 0
        for _r in range(12):
            batch = []
            for j in range(4):
                seq += 1
                key = "hot" if (seq % 2) else f"priv{j}"
                batch.append(_put(1, seq, key, f"v{seq}"))
            out.append(batch)
        return out

    d_sync, d_chp = mk(), mk()
    d_chp.pipeline_depth = 2
    bs = batches()
    groups = [bs[i * 3 : (i + 1) * 3] for i in range(4)]
    sync_rounds = [d_sync.step(b) for b in bs]
    chp_rounds = [d_chp.step_chained_pipelined(g) for g in groups]
    chp_rounds.append(d_chp.flush_pipeline())

    def flat(rounds):
        return [(r.rifl, r.key, tuple(r.op_results)) for rr in rounds for r in rr]

    assert flat(chp_rounds) == flat(sync_rounds)
    assert d_chp.executed == d_sync.executed == 48
    assert not d_chp.has_outstanding and d_chp.in_flight == 0
    for key in d_sync.store.monitor.keys():
        assert (
            d_chp.store.monitor.get_order(key)
            == d_sync.store.monitor.get_order(key)
        )
    # one dispatch per chain (the tail flush only drains), rounds
    # counted per protocol round
    assert d_chp.dispatches == 4
    assert d_chp.rounds == 12


def test_runtime_resolves_depth_from_config():
    """Config.serving_pipeline_depth reaches the driver, and an explicit
    depth opts the runtime into pipelining even on the CPU backend."""
    from fantoch_tpu.run.device_runner import DeviceRuntime
    from fantoch_tpu.run.harness import free_port

    runtime = DeviceRuntime(
        Config(3, 1, serving_pipeline_depth=2),
        ("127.0.0.1", free_port()),
        batch_size=8,
        key_buckets=64,
    )
    assert runtime.pipeline_depth == 2
    assert runtime.driver.pipeline_depth == 2
    assert runtime.pipeline  # depth request == pipelining opt-in


def test_device_runtime_depth2_tcp_serving():
    """Saturated TCP serving through the depth-2 loop answers every
    client with per-key order agreement and retires the pipeline."""
    config = Config(3, 1, serving_pipeline_depth=2)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtime, clients = asyncio.run(
        run_device_server(
            config,
            workload,
            client_count=4,
            batch_size=8,
            open_loop_interval_ms=1,
            protocol="newt",
        )
    )
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
        assert len(list(client.data().latency_data())) == COMMANDS_PER_CLIENT
    driver = runtime.driver
    assert driver.pipeline_depth == 2
    assert driver.executed == 4 * COMMANDS_PER_CLIENT
    assert driver.in_flight == 0 and not driver.has_outstanding
    monitor = driver.store.monitor
    seen = [rifl for key in monitor.keys() for rifl in monitor.get_order(key)]
    assert len(seen) == len(set(seen)) == 4 * COMMANDS_PER_CLIENT
    counters = runtime._tallies
    assert 0.0 <= counters["device_idle_frac"] <= 1.0
    assert counters["device_pipeline_depth"] == 2


def test_runtime_pipeline_engages_on_backlog():
    """Deterministic pipeline engagement: a backlog deeper than the batch
    is enqueued before the driver task first runs, so the queue is
    non-empty at every early batch fill and step_pipelined must engage
    (no dependence on client arrival timing)."""
    from fantoch_tpu.run.device_runner import DeviceRuntime
    from fantoch_tpu.run.harness import free_port

    async def go():
        config = Config(3, 1, shard_count=1)
        runtime = DeviceRuntime(
            config,
            ("127.0.0.1", free_port()),
            batch_size=8,
            key_buckets=64,
            pipeline=True,
            monitor_execution_order=True,
        )
        for i in range(24):
            cmd = Command.from_single(
                Rifl(9, i + 1), 0, f"k{i % 3}", KVOp.put(str(i))
            )
            runtime.submit(runtime.dot_gen.next_id(), cmd)
        await runtime.start()
        for _ in range(500):
            if runtime.failure is not None:
                raise runtime.failure
            if (
                runtime.driver.executed >= 24
                and not runtime.driver.has_outstanding
            ):
                break
            await asyncio.sleep(0.02)
        await runtime.stop()
        return runtime

    runtime = asyncio.run(go())
    driver = runtime.driver
    assert driver.executed == 24
    assert driver.pipelined_rounds > 0
    assert driver.in_flight == 0 and not driver.has_outstanding
    # per-key chains survived the pipelined rounds
    monitor = driver.store.monitor
    seen = [r for key in monitor.keys() for r in monitor.get_order(key)]
    assert len(seen) == len(set(seen)) == 24


def test_quiet_flush_vs_new_arrival_race():
    """r16 audit fix regression: under depth K>1 the quiet-flush path
    (queue went empty with rounds still in flight) retires each
    in-flight round exactly once even as fresh submissions keep landing
    mid-flush on the event loop — no stranded results, no double
    delivery, no dispatch interleaved into the flushing pipeline."""
    from fantoch_tpu.run.device_runner import DeviceRuntime
    from fantoch_tpu.run.harness import free_port

    async def go():
        config = Config(3, 1, shard_count=1, serving_pipeline_depth=2)
        runtime = DeviceRuntime(
            config,
            ("127.0.0.1", free_port()),
            batch_size=8,
            key_buckets=64,
            monitor_execution_order=True,
        )
        # two full rounds land before the driver task first runs: both
        # dispatch pipelined, then the queue is quiet with rounds in
        # flight and the loop takes the quiet-flush branch...
        for i in range(16):
            cmd = Command.from_single(
                Rifl(9, i + 1), 0, f"k{i % 3}", KVOp.put(str(i))
            )
            runtime.submit(runtime.dot_gen.next_id(), cmd)
        await runtime.start()
        # ...while fresh arrivals race it from the event-loop side
        for i in range(16, 40):
            await asyncio.sleep(0.002)
            cmd = Command.from_single(
                Rifl(9, i + 1), 0, f"k{i % 3}", KVOp.put(str(i))
            )
            runtime.submit(runtime.dot_gen.next_id(), cmd)
        # generous bound: the first dispatch pays the driver's XLA
        # compile, ~18 s on the older jaxlib pins
        for _ in range(1500):
            if runtime.failure is not None:
                raise runtime.failure
            if (
                runtime.driver.executed >= 40
                and not runtime.driver.has_outstanding
            ):
                break
            await asyncio.sleep(0.02)
        await runtime.stop()
        return runtime

    runtime = asyncio.run(go())
    driver = runtime.driver
    assert driver.executed == 40
    assert driver.in_flight == 0 and not driver.has_outstanding
    # exactly-once execution across flush/dispatch interleavings
    monitor = driver.store.monitor
    seen = [r for key in monitor.keys() for r in monitor.get_order(key)]
    assert len(seen) == len(set(seen)) == 40


def test_lone_command_fast_path_releases_immediately():
    """The idle-system fast path (run/ingest.py): a lone closed-loop
    command on an idle runtime releases without sitting out the ingest
    deadline.  The deadline here is far longer than the wait loop, so a
    missing fast path fails the test by timeout, not by a timing
    margin; the batcher's cause tally pins the path taken."""
    from fantoch_tpu.run.device_runner import DeviceRuntime
    from fantoch_tpu.run.harness import free_port

    async def go():
        config = Config(3, 1, shard_count=1, ingest_deadline_ms=300_000.0)
        runtime = DeviceRuntime(
            config,
            ("127.0.0.1", free_port()),
            batch_size=8,
            key_buckets=64,
        )
        cmd = Command.from_single(Rifl(9, 1), 0, "k0", KVOp.put("v"))
        runtime.submit(runtime.dot_gen.next_id(), cmd)
        await runtime.start()
        # ~30 s (covers the first-dispatch XLA compile): far under the
        # 300 s deadline a missing fast path would sit out
        for _ in range(1500):
            if runtime.failure is not None:
                raise runtime.failure
            if runtime.driver.executed >= 1:
                break
            await asyncio.sleep(0.02)
        await runtime.stop()
        return runtime

    runtime = asyncio.run(go())
    assert runtime.driver.executed == 1
    assert runtime._batcher.releases_fast >= 1
    assert runtime._batcher.releases_deadline == 0
