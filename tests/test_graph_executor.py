"""Dependency-graph executor tests, mirroring
fantoch_ps/src/executor/graph/mod.rs:713-1045: the simple two-command case,
the two documented ordering-soundness regression tests, the 3-cycle under
all delivery permutations, and randomized dep graphs with non-transitive
conflicts where every permutation must yield the identical per-key order.

Every case runs against BOTH ordering cores — the host Tarjan oracle
(DependencyGraph) and the batched device resolver (BatchedDependencyGraph)
— and the permutation tests additionally assert that the two produce the
identical per-key execution order on every delivery permutation.
"""

import itertools
import random

import pytest

from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
from fantoch_tpu.core.ids import process_ids
from fantoch_tpu.executor.graph.batched import BatchedDependencyGraph
from fantoch_tpu.executor.graph.deps_graph import DependencyGraph
from fantoch_tpu.protocol.common.graph_deps import Dependency

TIME = RunTime()
SHARD = 0

def BatchedNative(pid, shard, config):
    """Batched graph pinned to the native C++ host resolver (forcing it
    without the toolchain raises, so skip there instead of silently
    re-testing the XLA path)."""
    from fantoch_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    return BatchedDependencyGraph(
        pid, shard, config.with_(host_native_resolver=True)
    )


def BatchedXLA(pid, shard, config):
    """Batched graph pinned to the XLA device kernels (the TPU path; on
    CPU backends the auto default would pick native, dropping coverage)."""
    return BatchedDependencyGraph(
        pid, shard, config.with_(host_native_resolver=False)
    )


GRAPHS = [DependencyGraph, BatchedNative, BatchedXLA]


def dep(dot):
    return Dependency(dot, frozenset({SHARD}))


def make_cmd(dot, keys):
    rifl = Rifl(dot.source, dot.sequence)
    return Command.from_keys(rifl, SHARD, {k: (KVOp.put(""),) for k in keys})


def check_termination(n, args, graph_cls=DependencyGraph):
    """Feed (dot, keys, dep_dots) adds in order; every command must execute;
    returns the per-key execution order (mod.rs:1047-1110)."""
    config = Config(n, 1)
    graph = graph_cls(1, SHARD, config)
    all_rifls = set()
    sorted_order = {}
    for dot, keys, dep_dots in args:
        keys = keys if keys is not None else ["CONF"]
        cmd = make_cmd(dot, keys)
        assert cmd.rifl not in all_rifls
        all_rifls.add(cmd.rifl)
        graph.handle_add(dot, cmd, [dep(d) for d in dep_dots], TIME)
        for ready in graph.commands_to_execute():
            all_rifls.remove(ready.rifl)
            for key in ready.keys(SHARD):
                sorted_order.setdefault(key, []).append(ready.rifl)
    assert not all_rifls, f"not all commands executed: {all_rifls}"
    return sorted_order


def shuffle_it(n, args):
    expected = check_termination(n, list(args))
    for perm in itertools.permutations(args):
        perm = list(perm)
        assert check_termination(n, perm) == expected
        # the batched resolver (both cores: XLA kernels and the native
        # host Tarjan) must agree with the host oracle on the per-key
        # order, on every delivery permutation
        assert check_termination(n, perm, BatchedXLA) == expected
        assert check_termination(n, perm, BatchedNative) == expected


@pytest.mark.parametrize("graph_cls", GRAPHS)
def test_simple(graph_cls):
    # two commands in a 2-cycle execute together, sorted by dot
    dot_0, dot_1 = Dot(1, 1), Dot(2, 1)
    config = Config(2, 1)
    graph = graph_cls(1, SHARD, config)
    cmd_0 = make_cmd(dot_0, ["A"])
    cmd_1 = make_cmd(dot_1, ["A"])
    graph.handle_add(dot_0, cmd_0, [dep(dot_1)], TIME)
    assert graph.commands_to_execute() == []
    graph.handle_add(dot_1, cmd_1, [dep(dot_0)], TIME)
    assert graph.commands_to_execute() == [cmd_0, cmd_1]


@pytest.mark.parametrize("graph_cls", GRAPHS)
def test_transitive_conflicts_assumption_regression_1(graph_cls):
    """Commands of one process executed out of submission order can diverge
    across replicas (mod.rs:756-826): the executor is *expected* to produce
    different orders here — the system relies on per-process worker routing
    to make this arrival pattern impossible."""
    n = 5
    d1, d2, d3, d4, d5 = (Dot(1, s) for s in range(1, 6))
    deps = {d1: {d4}, d2: {d4}, d3: {d5}, d4: {d3}, d5: {d4}}
    order_a = [(d, None, deps[d]) for d in [d3, d4, d5, d1, d2]]
    order_b = [(d, None, deps[d]) for d in [d3, d4, d5, d2, d1]]
    a = check_termination(n, order_a, graph_cls)
    b = check_termination(n, order_b, graph_cls)
    assert a != b


@pytest.mark.parametrize("graph_cls", GRAPHS)
def test_transitive_conflicts_assumption_regression_2(graph_cls):
    """Highest-conflict-per-replica dep encoding is order-sensitive
    (mod.rs:828-896)."""
    n = 3
    d11, d12, d21 = Dot(1, 1), Dot(1, 2), Dot(2, 1)
    args = {
        d11: (["A"], set()),
        d12: (["B"], set()),
        d21: (["A", "B"], {d12}),
    }
    order_a = [(d, args[d][0], args[d][1]) for d in [d11, d12, d21]]
    order_b = [(d, args[d][0], args[d][1]) for d in [d12, d21, d11]]
    a = check_termination(n, order_a, graph_cls)
    b = check_termination(n, order_b, graph_cls)
    assert a != b


@pytest.mark.slow
def test_cycle():
    d1, d2, d3 = Dot(1, 1), Dot(2, 1), Dot(3, 1)
    args = [(d1, None, {d3}), (d2, None, {d1}), (d3, None, {d2})]
    shuffle_it(1, args)


def random_adds(n, events_per_process, rng):
    """Random dep graphs with non-transitive conflicts (mod.rs:934-1033)."""
    possible_keys = ["A", "B", "C", "D"]
    dots = [
        Dot(pid, seq)
        for pid in process_ids(SHARD, n)
        for seq in range(1, events_per_process + 1)
    ]
    keys = {}
    deps = {dot: set() for dot in dots}
    for dot in dots:
        keys[dot] = set(rng.sample(possible_keys, 2))
    for left, right in itertools.combinations(dots, 2):
        if not (keys[left] & keys[right]):
            continue
        if left.source == right.source:
            # same process: later depends on earlier
            if left.sequence < right.sequence:
                deps[right].add(left)
            else:
                deps[left].add(right)
        else:
            choice = rng.randrange(3)
            if choice in (0, 2):
                deps[left].add(right)
            if choice in (1, 2):
                deps[right].add(left)
    return [(dot, sorted(keys[dot]), deps[dot]) for dot in dots]


@pytest.mark.slow
def test_add_random():
    rng = random.Random(0)
    n = 2
    for _ in range(10):
        args = random_adds(n, 3, rng)
        shuffle_it(n, args)


def _big_backward_batch(batch, conflict_every=2):
    """A > _STRUCTURE_THRESHOLD batch of latest-per-key backward chains
    (the arrival-order fast-path shape) as handle_add_arrays columns."""
    import numpy as np

    from fantoch_tpu.ops.frontier import pack_dots

    src = np.ones(batch, dtype=np.int64)
    seq = np.arange(1, batch + 1, dtype=np.int64)
    key = np.arange(batch, dtype=np.int32) % conflict_every
    last = {}
    dep = np.full((batch, 1), -1, dtype=np.int64)
    for i in range(batch):
        prev = last.get(int(key[i]))
        if prev is not None:
            dep[i, 0] = (1 << 32) | (prev + 1)
        last[int(key[i])] = i
    cmds = [
        make_cmd(Dot(1, i + 1), [f"key{key[i]}"]) for i in range(batch)
    ]
    return src, seq, key, dep, cmds


def test_arrival_order_fast_path_and_array_drain():
    """Large backward-dep batches take the host arrival-order fast path:
    emission equals arrival order, and the array drain yields the same
    order as the Command drain without materializing objects."""
    import numpy as np

    batch = 5000  # > _STRUCTURE_THRESHOLD
    src, seq, key, dep, cmds = _big_backward_batch(batch)

    graph = BatchedDependencyGraph(1, SHARD, Config(3, 1))
    graph.handle_add_arrays(src, seq, key, dep, cmds, TIME)
    executed = graph.commands_to_execute()
    assert [c.rifl for c in executed] == [c.rifl for c in cmds]

    # array drain: same order as columns, no object materialization
    graph2 = BatchedDependencyGraph(1, SHARD, Config(3, 1))
    graph2.record_order_arrays = True
    graph2.handle_add_arrays(src, seq, key, dep, cmds, TIME)
    graph2.resolve_now(TIME)
    o_src, o_seq = graph2.take_order_arrays()
    assert (o_src == src).all() and (o_seq == seq).all()
    assert not graph2.commands_to_execute()  # no object mirror kept


def test_fast_path_skipped_when_missing_blocked():
    """A missing dependency disables the arrival-order shortcut: the
    blocked suffix of its chain stays pending until the dep arrives."""
    import numpy as np

    batch = 5000
    src, seq, key, dep, cmds = _big_backward_batch(batch)
    # row 0 (head of chain key0) depends on a dot nobody committed yet
    missing_dot = (2 << 32) | 1
    dep[0, 0] = missing_dot

    graph = BatchedDependencyGraph(1, SHARD, Config(3, 1))
    graph.handle_add_arrays(src, seq, key, dep, cmds, TIME)
    executed = graph.commands_to_execute()
    # chain on key0 is fully blocked behind the missing dep; key1 executes
    key0_count = int((key == 0).sum())
    assert len(executed) == batch - key0_count
    assert all(c.rifl.sequence % 2 == 0 for c in executed)  # key1 rows only

    # the missing dot arrives: everything drains in chain order
    graph.handle_add(
        Dot(2, 1), make_cmd(Dot(2, 1), ["key0"]), [], TIME
    )
    late = graph.commands_to_execute()
    assert len(late) == key0_count + 1
    key0_rifls = [c.rifl for c in late if c.rifl != Rifl(2, 1)]
    assert key0_rifls == [c.rifl for c in cmds if c.rifl.sequence % 2 == 1]


def test_stuck_misclassification_never_executes_past_missing():
    """Regression (r4): resolve_general's iteration budget can label rows
    'stuck' when the missing dependency sits deeper than the propagation
    horizon (a ladder of merge vertices stalls both composition and
    missing propagation to one hop per round).  The stuck set handed to
    the host oracle must be dependency-closed, or those rows execute
    before their dependency ever commits.  Nothing may execute here until
    the missing dot arrives; afterwards everything drains in order."""
    import numpy as np

    from fantoch_tpu.ops.frontier import pack_dots

    n = 2048
    # ladder delivered newest-first: row i deps on rows i+1 and i+2
    # (forward refs in batch order dodge the arrival fast path; two live
    # slots everywhere dodge chain composition); the far end awaits a
    # missing dot
    ghost = Dot(2, 1)
    src = np.ones(n, dtype=np.int64)
    seq = np.arange(n, 0, -1).astype(np.int64)  # dots n..1
    key = np.full(n, -1, dtype=np.int32)  # force the general path
    dep = np.full((n, 2), -1, dtype=np.int64)
    for i in range(n - 2):
        dep[i] = [pack_dots(src[i + 1 : i + 2], seq[i + 1 : i + 2])[0],
                  pack_dots(src[i + 2 : i + 3], seq[i + 2 : i + 3])[0]]
    ghost_packed = (2 << 32) | 1
    # dot 2 (row n-2) depends on dot 1 (row n-1) — conflicting commands
    # must be linked — and both await the ghost
    dep[n - 2] = [ghost_packed, (1 << 32) | 1]
    dep[n - 1, 0] = ghost_packed
    cmds = [make_cmd(Dot(1, int(seq[i])), ["x", "y"]) for i in range(n)]

    graph = BatchedDependencyGraph(
        1, SHARD, Config(3, 1, host_native_resolver=False)
    )
    graph.handle_add_arrays(src, seq, key, dep, cmds, TIME)
    executed = graph.commands_to_execute()
    assert executed == [], (
        f"{len(executed)} commands executed while their transitive "
        "dependency is missing"
    )

    # the missing dot commits: the whole ladder drains oldest-first
    graph.handle_add(ghost, make_cmd(ghost, ["x"]), [], TIME)
    drained = graph.commands_to_execute()
    assert len(drained) == n + 1
    assert drained[0].rifl == Rifl(2, 1)
    assert [c.rifl.sequence for c in drained[1:]] == list(range(1, n + 1))


def test_monitor_pending_panics_on_lost_execution():
    """Per-row liveness watchdog (index.rs:53-103): a row whose whole
    dependency closure is executed/present but which never executed means
    a lost execution — monitor_pending must panic on it, while genuinely
    missing-blocked rows never trip it."""
    import numpy as np
    import pytest as _pytest

    from fantoch_tpu.core.timing import SimTime

    time = SimTime()
    graph = BatchedDependencyGraph(1, SHARD, Config(3, 1))
    ghost = Dot(2, 7)
    # a row blocked on a genuinely missing dep: never panics
    graph.handle_add(Dot(1, 1), make_cmd(Dot(1, 1), ["a"]), [dep(ghost)], time)
    assert graph.commands_to_execute() == []
    time.add_millis(5000)
    graph.monitor_pending(time)  # old but missing-blocked: fine

    # simulate a lost execution: the ghost executes elsewhere but the
    # re-resolve notification is lost (frontier learns the dot, nothing
    # marks the backlog dirty)
    graph._frontier.add(ghost.source, ghost.sequence)
    graph._dirty = False
    time.add_millis(5000)
    with _pytest.raises(AssertionError, match="without missing"):
        graph.monitor_pending(time)


def test_large_multikey_adversarial_batch_staged_branch():
    """Large (> _STRUCTURE_THRESHOLD) multi-key backlogs with adversarial
    (permuted) arrival leave the in-jit fast path; on the XLA path they
    route through the staged frontier peeler and must fully resolve with
    per-key order intact."""
    import numpy as np

    from fantoch_tpu.ops.frontier import pack_dots

    n = 5000  # > _STRUCTURE_THRESHOLD (stays on the staged branch)
    rng = random.Random(9)
    nkeys = 64
    key_of = [(i % nkeys, (i * 7 + 1) % nkeys) for i in range(n)]
    last = {}
    deps = []
    for i in range(n):
        row = set()
        for k in key_of[i]:
            if k in last and last[k] != i:
                row.add(last[k])
            last[k] = i
        deps.append(row)
    perm = list(range(n))
    rng.shuffle(perm)  # adversarial arrival

    src = np.ones(n, dtype=np.int64)
    seq = np.array([perm[pos] + 1 for pos in range(n)], dtype=np.int64)
    key_col = np.full(n, -1, dtype=np.int32)  # multi-key: general path
    width = max(len(d) for d in deps)
    dep_dots = np.full((n, width), -1, dtype=np.int64)
    for pos in range(n):
        orig = perm[pos]
        for j, d in enumerate(sorted(deps[orig])):
            dep_dots[pos, j] = pack_dots(
                np.asarray([1], dtype=np.int64), np.asarray([d + 1], dtype=np.int64)
            )[0]
    cmds = [
        make_cmd(Dot(1, perm[pos] + 1), [f"m{k}" for k in set(key_of[perm[pos]])])
        for pos in range(n)
    ]

    graph = BatchedDependencyGraph(
        1, SHARD, Config(3, 1, host_native_resolver=False)
    )
    graph.handle_add_arrays(src, seq, key_col, dep_dots, cmds, TIME)
    executed = graph.commands_to_execute()
    assert len(executed) == n
    # per-key execution order must match dependency (original) order
    seen = {}
    for cmd in executed:
        orig = cmd.rifl.sequence - 1
        for k in set(key_of[orig]):
            assert seen.get(k, -1) < orig
            seen[k] = orig
