"""Device table ops vs the host SequentialKeyClocks / VotesTable oracles."""

import random

import jax.numpy as jnp
import numpy as np

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Rifl
from fantoch_tpu.core.kvs import KVOp
from fantoch_tpu.executor.table import VotesTable
from fantoch_tpu.ops.table_ops import batched_clock_proposal, stable_clocks
from fantoch_tpu.protocol.common.table_clocks import SequentialKeyClocks, VoteRange

SHARD = 0


def oracle_proposals(prior, keys, mins):
    clocks = SequentialKeyClocks(1, SHARD)
    clocks._clocks = {str(k): int(v) for k, v in enumerate(prior)}
    out_clock, out_start = [], []
    for seq, (k, m) in enumerate(zip(keys, mins), start=1):
        cmd = Command.from_single(Rifl(9, seq), SHARD, str(k), KVOp.put("x"))
        clock, votes = clocks.proposal(cmd, int(m))
        ranges = votes.get(str(k))
        assert len(ranges) == 1
        out_clock.append(clock)
        out_start.append(ranges[0].start)
    return out_clock, out_start, [clocks._clocks[str(k)] for k in range(len(prior))]


def test_batched_proposal_matches_oracle():
    rng = random.Random(7)
    for trial in range(20):
        n_keys, batch = 5, 40
        prior = [rng.randrange(0, 10) for _ in range(n_keys)]
        keys = [rng.randrange(n_keys) for _ in range(batch)]
        mins = [rng.choice([0, 0, 0, rng.randrange(30)]) for _ in range(batch)]
        want_clock, want_start, want_prior = oracle_proposals(prior, keys, mins)
        clock, start, new_prior = batched_clock_proposal(
            jnp.asarray(prior, jnp.int32),
            jnp.asarray(keys, jnp.int32),
            jnp.asarray(mins, jnp.int32),
        )
        assert clock.tolist() == want_clock, f"trial {trial}"
        assert start.tolist() == want_start, f"trial {trial}"
        assert new_prior.tolist() == want_prior, f"trial {trial}"


def test_batched_proposal_large_clocks_many_keys():
    """Overflow regression: micros-scale priors across tens of thousands of
    keys must not corrupt the segmented scan."""
    n_keys = 40_000
    prior = np.full((n_keys,), 60_000_000, dtype=np.int32)
    keys = np.arange(n_keys, dtype=np.int32)
    mins = np.zeros((n_keys,), dtype=np.int32)
    clock, start, new_prior = batched_clock_proposal(
        jnp.asarray(prior), jnp.asarray(keys), jnp.asarray(mins)
    )
    assert clock.tolist() == [60_000_001] * n_keys
    assert start.tolist() == [60_000_001] * n_keys
    assert new_prior.tolist() == [60_000_001] * n_keys


def test_batched_proposal_hot_key_chain():
    # every command on one key: consecutive clocks, compressed ranges
    batch = 64
    clock, start, new_prior = batched_clock_proposal(
        jnp.zeros((4,), jnp.int32),
        jnp.full((batch,), 2, jnp.int32),
        jnp.zeros((batch,), jnp.int32),
    )
    assert clock.tolist() == list(range(1, batch + 1))
    assert start.tolist() == list(range(1, batch + 1))
    assert int(new_prior[2]) == batch and int(new_prior[0]) == 0


def test_stable_clocks_matches_votes_table():
    rng = random.Random(11)
    n, threshold = 5, 3
    k = 8
    frontiers = np.array(
        [[rng.randrange(0, 20) for _ in range(n)] for _ in range(k)], dtype=np.int32
    )
    got = stable_clocks(jnp.asarray(frontiers), threshold=threshold)
    for key in range(k):
        table = VotesTable(str(key), 1, SHARD, n, threshold)
        for pid, frontier in enumerate(frontiers[key], start=1):
            if frontier > 0:
                table.add_votes([VoteRange(pid, 1, int(frontier))])
        assert int(got[key]) == table.stable_clock(), f"key {key}"
