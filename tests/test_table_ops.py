"""Device table ops vs the host SequentialKeyClocks / VotesTable oracles."""

import pytest
import random

import jax.numpy as jnp
import numpy as np

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Rifl
from fantoch_tpu.core.kvs import KVOp
from fantoch_tpu.executor.table import VotesTable
from fantoch_tpu.ops.table_ops import batched_clock_proposal, stable_clocks
from fantoch_tpu.protocol.common.table_clocks import SequentialKeyClocks, VoteRange

SHARD = 0


def oracle_proposals(prior, keys, mins):
    clocks = SequentialKeyClocks(1, SHARD)
    clocks._clocks = {str(k): int(v) for k, v in enumerate(prior)}
    out_clock, out_start = [], []
    for seq, (k, m) in enumerate(zip(keys, mins), start=1):
        cmd = Command.from_single(Rifl(9, seq), SHARD, str(k), KVOp.put("x"))
        clock, votes = clocks.proposal(cmd, int(m))
        ranges = votes.get(str(k))
        assert len(ranges) == 1
        out_clock.append(clock)
        out_start.append(ranges[0].start)
    return out_clock, out_start, [clocks._clocks[str(k)] for k in range(len(prior))]


def test_batched_proposal_matches_oracle():
    rng = random.Random(7)
    for trial in range(20):
        n_keys, batch = 5, 40
        prior = [rng.randrange(0, 10) for _ in range(n_keys)]
        keys = [rng.randrange(n_keys) for _ in range(batch)]
        mins = [rng.choice([0, 0, 0, rng.randrange(30)]) for _ in range(batch)]
        want_clock, want_start, want_prior = oracle_proposals(prior, keys, mins)
        clock, start, new_prior = batched_clock_proposal(
            jnp.asarray(prior, jnp.int32),
            jnp.asarray(keys, jnp.int32),
            jnp.asarray(mins, jnp.int32),
        )
        assert clock.tolist() == want_clock, f"trial {trial}"
        assert start.tolist() == want_start, f"trial {trial}"
        assert new_prior.tolist() == want_prior, f"trial {trial}"


@pytest.mark.slow
def test_batched_proposal_large_clocks_many_keys():
    """Overflow regression: micros-scale priors across tens of thousands of
    keys must not corrupt the segmented scan."""
    n_keys = 40_000
    prior = np.full((n_keys,), 60_000_000, dtype=np.int32)
    keys = np.arange(n_keys, dtype=np.int32)
    mins = np.zeros((n_keys,), dtype=np.int32)
    clock, start, new_prior = batched_clock_proposal(
        jnp.asarray(prior), jnp.asarray(keys), jnp.asarray(mins)
    )
    assert clock.tolist() == [60_000_001] * n_keys
    assert start.tolist() == [60_000_001] * n_keys
    assert new_prior.tolist() == [60_000_001] * n_keys


def test_batched_proposal_hot_key_chain():
    # every command on one key: consecutive clocks, compressed ranges
    batch = 64
    clock, start, new_prior = batched_clock_proposal(
        jnp.zeros((4,), jnp.int32),
        jnp.full((batch,), 2, jnp.int32),
        jnp.zeros((batch,), jnp.int32),
    )
    assert clock.tolist() == list(range(1, batch + 1))
    assert start.tolist() == list(range(1, batch + 1))
    assert int(new_prior[2]) == batch and int(new_prior[0]) == 0


def test_stable_clocks_matches_votes_table():
    rng = random.Random(11)
    n, threshold = 5, 3
    k = 8
    frontiers = np.array(
        [[rng.randrange(0, 20) for _ in range(n)] for _ in range(k)], dtype=np.int32
    )
    got = stable_clocks(jnp.asarray(frontiers), threshold=threshold)
    for key in range(k):
        table = VotesTable(str(key), 1, SHARD, n, threshold)
        for pid, frontier in enumerate(frontiers[key], start=1):
            if frontier > 0:
                table.add_votes([VoteRange(pid, 1, int(frontier))])
        assert int(got[key]) == table.stable_clock(), f"key {key}"


def test_clock_window_rebase_roundtrip():
    from fantoch_tpu.ops.table_ops import ClockWindow

    # ~40 minutes of wall-clock micros: beyond int32, the real-time mode
    # that motivates the window (newt.rs clock-bump to time.micros())
    floor = 40 * 60 * 1_000_000
    win = ClockWindow(floor)
    host = np.array([0, floor + 1, floor + 12345], dtype=np.int64)
    dev = win.rebase(host)
    assert dev.dtype == np.int32
    assert dev.tolist() == [0, 1, 12345]
    assert win.restore(dev).tolist() == host.tolist()


def test_clock_window_rejects_out_of_window():
    import pytest

    from fantoch_tpu.ops.table_ops import ClockWindow

    win = ClockWindow(1000)
    with pytest.raises(AssertionError, match="below the window floor"):
        win.rebase(np.array([999], dtype=np.int64))
    with pytest.raises(AssertionError, match="overflows"):
        win.rebase(np.array([1000 + (1 << 31)], dtype=np.int64))


def test_newt_device_clocks_cross_window_boundary():
    """Real-time-scale Newt clock proposals through the 31-bit window:
    batch 1 under floor A, then the window advances (GC stable moved) and
    batch 2's proposals continue the same chains — results must equal the
    unbounded int64 host oracle throughout."""
    from fantoch_tpu.ops.table_ops import ClockWindow, shift_table

    n_keys = 4
    # host truth: unbounded int64 key clocks (the host oracle twin)
    t0 = 50 * 60 * 1_000_000  # 50 min of micros — far beyond int32
    host_prior = [t0 + k * 7 for k in range(n_keys)]

    win = ClockWindow(t0 - 1)
    dev_prior = jnp.asarray(win.rebase(host_prior))

    def run_batch(keys, host_mins):
        mins_dev = jnp.asarray(win.rebase(host_mins))
        clock, start, new_prior = batched_clock_proposal(
            dev_prior, jnp.asarray(keys, jnp.int32), mins_dev
        )
        return win.restore(clock), win.restore(start), new_prior

    keys1 = [0, 1, 0, 2, 0, 3, 1]
    mins1 = [0, 0, t0 + 100, 0, 0, 0, 0]
    # oracle over int64 (rebased to small ints for SequentialKeyClocks)
    base = t0 - 1
    want_clock, want_start, want_prior = oracle_proposals(
        [p - base for p in host_prior], keys1, [max(m - base, 0) for m in mins1]
    )
    got_clock, got_start, dev_prior = run_batch(keys1, mins1)
    assert got_clock.tolist() == [c + base for c in want_clock]
    assert got_start.tolist() == [s + base for s in want_start]

    # the protocol GC'd up to a new stable clock: advance the window and
    # rebase the device table in place
    new_floor = t0 + 50
    shift = win.advance(new_floor)
    dev_prior = shift_table(dev_prior, shift)

    keys2 = [0, 0, 1, 2, 3]
    mins2 = [new_floor + 500, 0, 0, 0, 0]
    host_prior2 = [int(v) for v in win.restore(np.asarray(dev_prior))]
    want_clock2, want_start2, _ = oracle_proposals(
        [max(p - new_floor, 0) for p in host_prior2],
        keys2,
        [max(m - new_floor, 0) for m in mins2],
    )
    got_clock2, got_start2, _ = run_batch(keys2, mins2)
    assert got_clock2.tolist() == [c + new_floor for c in want_clock2]
    assert got_start2.tolist() == [s + new_floor for s in want_start2]
    # chains really continued across the boundary: key 0's first batch-2
    # clock exceeds its batch-1 maximum
    assert got_clock2[0] > max(
        c for k, c in zip(keys1, got_clock.tolist()) if k == 0
    )
