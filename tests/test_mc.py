"""Model checker over small protocol instances (fantoch_mc analog).

Positive checks: exhaustive exploration of conflicting submissions finds
no agreement/terminal violation for Basic and EPaxos.  Negative check:
``execute_at_commit`` (executing in commit-arrival order instead of the
executor's dependency order) is known-unsound for EPaxos under message
reordering — the checker must find a counterexample trace, proving it
actually distinguishes sound from unsound compositions.
"""

import os

import pytest

from fantoch_tpu.core import Command, Config, KVOp, Rifl
from fantoch_tpu.mc import ModelChecker


def put(client: int, seq: int, *keys: str) -> Command:
    return Command.from_keys(
        Rifl(client, seq), 0, {k: (KVOp.put(f"v{client}.{seq}"),) for k in keys}
    )


def test_mc_basic_two_conflicting_commands():
    # Basic is the reference's intentionally inconsistent protocol: check
    # completeness (every process executes everything) but not agreement
    from fantoch_tpu.protocol.basic import Basic

    mc = ModelChecker(
        Basic,
        Config(3, 1),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        check_agreement=False,
    )
    result = mc.run()
    assert result.complete, "state space must be exhausted"
    assert result.ok, result.violations[:1]
    assert result.terminals > 0
    assert result.states > 50  # a real exploration, not a no-op


def test_mc_flags_basic_as_inconsistent():
    # with the agreement invariant ON, the checker must find Basic's
    # documented inconsistency — evidence the invariant has teeth
    from fantoch_tpu.protocol.basic import Basic

    mc = ModelChecker(
        Basic,
        Config(3, 1),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
    )
    result = mc.run()
    assert not result.ok
    assert result.violations[0].kind in ("agreement", "divergent_terminal")


def test_mc_epaxos_two_conflicting_commands():
    from fantoch_tpu.protocol.graph_protocol import EPaxos

    mc = ModelChecker(
        EPaxos,
        # gc on: the stabilized-terminal invariant also proves every
        # per-dot info is GC'd under every delivery interleaving
        Config(3, 1, gc_interval_ms=100),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert result.complete, "state space must be exhausted"
    assert result.ok, result.violations[:1]
    assert result.terminals > 0


@pytest.mark.slow
def test_mc_atlas_two_conflicting_commands():
    from fantoch_tpu.protocol.graph_protocol import Atlas

    mc = ModelChecker(
        Atlas,
        Config(3, 1),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]
    assert result.terminals > 0


def test_mc_fpaxos_two_commands():
    from fantoch_tpu.protocol.fpaxos import FPaxos

    mc = ModelChecker(
        FPaxos,
        Config(3, 1, leader=1),
        [(1, put(1, 1, "A")), (1, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]
    assert result.terminals > 0


def test_mc_catches_execute_at_commit_divergence():
    """EPaxos with execute_at_commit executes in commit-delivery order,
    which differs across processes under reordering: the checker must
    produce a counterexample (this is the knob's documented trade-off,
    fantoch/src/config.rs execute_at_commit)."""
    from fantoch_tpu.protocol.graph_protocol import EPaxos

    mc = ModelChecker(
        EPaxos,
        Config(3, 1, execute_at_commit=True),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert not result.ok, "checker must catch the unsound composition"
    v = result.violations[0]
    assert v.kind in ("agreement", "divergent_terminal")
    assert v.trace, "counterexample must carry a trace"


def test_mc_caesar_two_conflicting_commands():
    # Caesar's wait condition + clock/deps consensus under every delivery
    # order; commit and execution are message-driven (the periodic events
    # only drive GC, outside the MC model)
    from fantoch_tpu.protocol.caesar import Caesar

    mc = ModelChecker(
        Caesar,
        Config(3, 1, gc_interval_ms=100, caesar_wait_condition=True),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]
    assert result.terminals > 0


@pytest.mark.slow
def test_mc_newt_with_quiescent_timers():
    # Newt's executor needs detached-vote flushes (a periodic event) for
    # timestamp stability: quiescence-stage timer firings (to fixpoint)
    # drive it
    from fantoch_tpu.protocol.newt import Newt

    mc = ModelChecker(
        Newt,
        Config(
            3, 1, gc_interval_ms=100, newt_detached_send_interval_ms=50
        ),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]
    assert result.terminals > 0


@pytest.mark.recovery
def test_mc_epaxos_crashed_coordinator_recovery():
    """Exhaustively explore a coordinator crash at n=3/f=1: the crash of
    p1 branches at every state (in-flight messages to it evaporate, its
    unsubmitted commands are abandoned), and the stabilization closure
    drives the survivors' MPrepare/MPromise recovery of its in-flight
    dots.  Every interleaving must keep the consensus agreement invariant
    (identical survivor orders, mandatory commands complete, crashed-
    coordinator commands executed everywhere-or-nowhere)."""
    from fantoch_tpu.protocol.graph_protocol import EPaxos

    mc = ModelChecker(
        EPaxos,
        Config(3, 1, gc_interval_ms=100, recovery_delay_ms=50),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
        crashes=[1],
    )
    result = mc.run()
    assert result.complete, "state space must be exhausted"
    assert result.ok, result.violations[:1]
    assert result.terminals > 0


@pytest.mark.recovery
def test_mc_caesar_crashed_coordinator_recovery():
    """Exhaustively explore a Caesar coordinator crash at n=3/f=1: the
    crash of p1 branches at every state and the stabilization closure
    drives the survivors' (clock, preds)-pair recovery consensus —
    including its interaction with the wait condition (a blocked MPropose
    must be unblocked, never deadlocked, by a recovery-decided or
    noop'd blocker).  Every interleaving must keep agreement; crashed-
    coordinator commands execute everywhere-or-nowhere."""
    from fantoch_tpu.protocol.caesar import Caesar

    mc = ModelChecker(
        Caesar,
        Config(
            3, 1, gc_interval_ms=100, recovery_delay_ms=50,
            caesar_wait_condition=True,
        ),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
        crashes=[1],
    )
    result = mc.run()
    assert result.complete, "state space must be exhausted"
    assert result.ok, result.violations[:1]
    assert result.terminals > 0


@pytest.mark.recovery
@pytest.mark.slow
def test_mc_atlas_crashed_coordinator_recovery():
    from fantoch_tpu.protocol.graph_protocol import Atlas

    mc = ModelChecker(
        Atlas,
        Config(3, 1, gc_interval_ms=100, recovery_delay_ms=50),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
        crashes=[1],
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]
    assert result.terminals > 0


@pytest.mark.skipif(
    not os.environ.get("FANTOCH_MC_SLOW"),
    reason="~8 min exhaustive run; set FANTOCH_MC_SLOW=1",
)
def test_mc_epaxos_three_conflicting_commands_slow():
    # measured: 23,269 states, complete, ok (~7 min)
    from fantoch_tpu.protocol.graph_protocol import EPaxos

    mc = ModelChecker(
        EPaxos,
        Config(3, 1),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A")), (3, put(3, 1, "A"))],
        max_states=400_000,
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]


@pytest.mark.slow
def test_mc_newt_batched_table_path():
    """Model-check Newt over the BATCHED table path (array-backed key
    clocks + vectorized executor stability): every delivery interleaving
    must agree, proving the batched seams preserve the protocol's
    semantics state-for-state."""
    from fantoch_tpu.protocol.newt import Newt

    mc = ModelChecker(
        Newt,
        Config(
            3, 1, gc_interval_ms=100, newt_detached_send_interval_ms=50,
            batched_table_executor=True,
        ),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]
    assert result.terminals > 0


@pytest.mark.slow
def test_mc_caesar_batched_pred_executor():
    """Model-check Caesar over the BATCHED predecessor executor (the
    two-phase countdown kernel, ops/pred_resolve.py): every delivery
    interleaving agrees with the wait-condition semantics — the third
    batched executor seam under exhaustive checking."""
    from fantoch_tpu.protocol.caesar import Caesar

    mc = ModelChecker(
        Caesar,
        Config(
            3, 1, gc_interval_ms=100, caesar_wait_condition=True,
            batched_pred_executor=True,
        ),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]
    assert result.terminals > 0


@pytest.mark.slow
def test_mc_epaxos_batched_graph_executor():
    """Model-check EPaxos over the batched graph executor (array backlog +
    device/native resolvers at MC scope): exhaustive interleavings agree,
    so the tensorized ordering core is semantics-preserving."""
    from fantoch_tpu.protocol import EPaxos

    mc = ModelChecker(
        EPaxos,
        Config(3, 1, gc_interval_ms=100, batched_graph_executor=True),
        [(1, put(1, 1, "A")), (2, put(2, 1, "A"))],
        max_states=500_000,
    )
    result = mc.run()
    assert result.complete and result.ok, result.violations[:1]
    assert result.terminals > 0
