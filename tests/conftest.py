"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (jax.sharding semantics are identical; only perf differs).
Must run before jax initializes its backends.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
