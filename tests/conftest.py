"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (jax.sharding semantics are identical; only perf differs).

The ambient environment may have already imported jax pointed at a single
real chip (a sitecustomize hook registers the TPU plugin at interpreter
start), so env vars alone are too late — override through jax.config before
any backend is initialized.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
