"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (jax.sharding semantics are identical; only perf differs).

The ambient environment may have already imported jax pointed at a single
real chip (a sitecustomize hook registers the TPU plugin at interpreter
start), so env vars alone are too late — the shared
fantoch_tpu.hostenv.force_cpu_platform helper overrides through jax.config
before any backend is initialized.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fantoch_tpu.hostenv import enable_compile_cache, force_cpu_platform

force_cpu_platform(n_devices=8)
# persistent XLA compile cache (shared helper; same dir bench.py uses —
# entries are keyed by topology+program so the 8-device test mesh never
# collides with the bench's 1-device programs): mesh-step compiles
# dominate suite wall time and repeat identically across runs
enable_compile_cache()
