"""Restart & rejoin plane: replicas return to service instead of staying
dead.

PR 3 made crashes heal by routing *around* the corpse — every crash
permanently burned one unit of the n-f budget.  These tests drive the
restart plane through the stronger claim:

* **Restored tolerance** (the acceptance rows) — crash p_a with a
  scheduled restart, let it rejoin (durable image + MSync catch-up +
  vote backfill; MSlotSync slot streaming for FPaxos), then crash p_b
  *forever*.  Without the restart the combined failures exceed ``f``
  and the run must stall; with it, every client not attached to the
  dead-forever replica completes and the execution-order monitors agree
  (exactly-once across the restart: a re-executed command would break
  write-order agreement).  All five protocols run these rows — Caesar
  and FPaxos joined in PR 12.
* **Restart determinism** — same seed twice => byte-identical nemesis
  traces AND byte-identical span logs through crash, durable-image
  capture, restore, and rejoin.
* **Device planes rebuild** — a TableExecutor with the device table
  plane restores from its pickled host mirror: ONE re-upload
  (``resident_uploads``), bit-for-bit KV parity with an uncrashed run.
* **Pipelined serving** — rounds in flight in a depth-2 pipeline at
  crash time are re-fed from the log on recovery and come out
  exactly-once, in order.
* **Run layer** — a killed ProcessRuntime restarts from its WAL
  (snapshot + tail), peers detect it (``on_peer_up``: incarnation-keyed
  link-dedup reset, writer revival), MSync pulls the commits it missed,
  and it serves clients again; monitors agree across all three lives.
"""

import asyncio
import hashlib
import os

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Command, Config, Dot, KVOp, Planet, Rifl
from fantoch_tpu.core.planet import Region
from fantoch_tpu.core.timing import SimTime
from fantoch_tpu.protocol import Atlas, Caesar, EPaxos, FPaxos, Newt
from fantoch_tpu.sim import Runner
from fantoch_tpu.sim.faults import FaultPlan

from harness import check_monitors

pytestmark = [pytest.mark.chaos, pytest.mark.restart]

COMMANDS_PER_CLIENT = 10 if os.environ.get("CI") else 15
CLIENTS_PER_PROCESS = 2


def flat_planet(n):
    """Near-equidistant regions: every crashed replica sits inside live
    fast quorums (the recovery rows' far=0 topology)."""
    regions = [Region(f"r{i}") for i in range(n)]
    latencies = {
        a: {b: (0 if i == j else 10 + abs(i - j)) for j, b in enumerate(regions)}
        for i, a in enumerate(regions)
    }
    return regions, Planet.from_latencies(latencies)


def restart_sim(
    protocol_cls,
    config: Config,
    plan: FaultPlan,
    commands_per_client: int = COMMANDS_PER_CLIENT,
    seed: int = 0,
    trace_path=None,
):
    n = config.n
    regions, planet = flat_planet(n)
    config = config.with_(
        executor_monitor_execution_order=True,
        executor_monitor_pending_interval_ms=500,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        shard_count=1,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(100),
        keys_per_command=1,
        commands_per_client=commands_per_client,
        payload_size=1,
    )
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        CLIENTS_PER_PROCESS,
        process_regions=regions,
        client_regions=list(regions),
        seed=seed,
        fault_plan=plan,
        trace_path=trace_path,
    )
    metrics, monitors, _latencies = runner.run(extra_sim_time_ms=2000)
    return runner, monitors


def assert_restored_tolerance(runner, monitors, restarted, dead_forever, commands):
    """Every client not attached to a dead-forever replica — including
    the restarted one's — completed; surviving monitors agree (a command
    re-executed across the restart would break write-order agreement)."""
    kinds = {kind for _t, kind, _d in runner.nemesis.trace}
    assert {"crash", "durable-image", "restart"} <= kinds
    dead = set(dead_forever)
    for client_id, client in runner._simulation.clients():
        if client.targets() & dead:
            continue
        assert client.issued_commands == commands, (
            f"client {client_id} (targets {client.targets()}) finished "
            f"{client.issued_commands}/{commands} after p{sorted(dead)} died"
        )
    check_monitors({pid: m for pid, m in monitors.items() if pid not in dead})


# --- acceptance rows: restart restores the tolerance budget ---

RESTART_33 = Config(3, 1, recovery_delay_ms=1000)
# p2 crashes and restarts; p3 then dies for good.  Without the restart
# this is 2 > f=1 dead (test_recovery_below_quorum_is_still_bounded's
# stall); with it the mesh is back to full strength when p3 dies.
PLAN_33 = (
    FaultPlan(seed=1, max_sim_time_ms=300_000)
    .with_loss(0.1)
    .with_crash(2, at_ms=150, restart_at_ms=2500)
    .with_crash(3, at_ms=3200)
)


@pytest.mark.parametrize(
    "protocol_cls,config",
    [
        (EPaxos, RESTART_33),
        (Atlas, RESTART_33),
        (Newt, RESTART_33.with_(newt_detached_send_interval_ms=100)),
        # Caesar: snapshot/restore + MSync rejoin over the (clock, preds)
        # commit records (PR 12 closed the restart carve-out)
        (Caesar, RESTART_33.with_(executor_monitor_pending_interval_ms=500)),
    ],
    ids=["epaxos", "atlas", "newt", "caesar"],
)
def test_restart_restores_tolerance_33(protocol_cls, config):
    runner, monitors = restart_sim(protocol_cls, config, PLAN_33)
    assert_restored_tolerance(
        runner, monitors, restarted=[2], dead_forever=[3],
        commands=COMMANDS_PER_CLIENT,
    )


def test_fpaxos_restart_restores_tolerance_33():
    """FPaxos: the LEADER crash-restarts (followers elect, the stale
    restored leader is demoted by the higher-ballot heartbeat and its
    stranded commanders re-forward), MSlotSync pulls the chosen slots it
    missed, and a follower then dies for good — survivable only because
    the restarted replica is back in the write quorum."""
    config = Config(3, 1, leader=1, fpaxos_leader_timeout_ms=400)
    plan = (
        FaultPlan(seed=1, max_sim_time_ms=300_000)
        .with_loss(0.1)
        .with_crash(1, at_ms=150, restart_at_ms=2500)
        .with_crash(3, at_ms=3200)
    )
    runner, monitors = restart_sim(FPaxos, config, plan)
    assert_restored_tolerance(
        runner, monitors, restarted=[1], dead_forever=[3],
        commands=COMMANDS_PER_CLIENT,
    )


def test_fpaxos_follower_restart_inflight_accepts_redriven():
    """A write-quorum FOLLOWER crash-restarts: the MAccepts that
    evaporated during its downtime are re-driven by the leader's
    periodic in-flight sweep (no failure detector ever fires for a
    restarting peer), so the stuck slots — and everything ordered after
    them — complete (the fuzzer-found follower-restart stall)."""
    config = Config(3, 1, leader=1, fpaxos_leader_timeout_ms=400)
    plan = (
        FaultPlan(seed=3, max_sim_time_ms=300_000)
        .with_crash(2, at_ms=200, restart_at_ms=900)
    )
    runner, monitors = restart_sim(FPaxos, config, plan)
    assert_restored_tolerance(
        runner, monitors, restarted=[2], dead_forever=[],
        commands=COMMANDS_PER_CLIENT,
    )


def test_restart_restores_tolerance_52():
    """n=5/f=2: p2 crash-restarts, then p4 AND p5 die for good — three
    crashed processes overall, survivable only because p2 came back."""
    plan = (
        FaultPlan(seed=13, max_sim_time_ms=600_000)
        .with_loss(0.1)
        .with_crash(2, at_ms=150, restart_at_ms=3000)
        .with_crash(4, at_ms=4500)
        .with_crash(5, at_ms=4500)
    )
    runner, monitors = restart_sim(EPaxos, Config(5, 2, recovery_delay_ms=1500), plan)
    assert_restored_tolerance(
        runner, monitors, restarted=[2], dead_forever=[4, 5],
        commands=COMMANDS_PER_CLIENT,
    )


@pytest.mark.slow
@pytest.mark.parametrize("loss", [0.1, 0.3])
@pytest.mark.parametrize(
    "protocol_cls,config",
    [
        (EPaxos, Config(5, 2, recovery_delay_ms=1500)),
        (Atlas, Config(5, 2, recovery_delay_ms=1500)),
        (
            Newt,
            Config(5, 2, recovery_delay_ms=1500, newt_detached_send_interval_ms=100),
        ),
        (
            Caesar,
            Config(
                5, 2, recovery_delay_ms=1500,
                executor_monitor_pending_interval_ms=500,
            ),
        ),
    ],
    ids=["epaxos", "atlas", "newt", "caesar"],
)
def test_restart_matrix_52(protocol_cls, config, loss):
    """Acceptance matrix: crash-restart + subsequent double crash at
    n=5/f=2 under 10-30% loss, across EPaxos/Atlas/Newt."""
    plan = (
        FaultPlan(seed=13, max_sim_time_ms=600_000)
        .with_loss(loss)
        .with_crash(2, at_ms=150, restart_at_ms=3000)
        .with_crash(4, at_ms=4500)
        .with_crash(5, at_ms=4500)
    )
    runner, monitors = restart_sim(protocol_cls, config, plan)
    assert_restored_tolerance(
        runner, monitors, restarted=[2], dead_forever=[4, 5],
        commands=COMMANDS_PER_CLIENT,
    )


# --- determinism: restart decisions replay byte-identically ---


def test_critpath_blame_survives_crash_restart(tmp_path):
    """Critical-path satellite: the PR 5 restart rows assert span-log
    byte identity, but never ASSEMBLE attribution across a crash.  This
    row does: the span log spans all three lives (crash, durable image,
    restore + rejoin) and every assembled blame vector still telescopes
    EXACTLY to reply - submit, with cross-process quorum edges resolved
    for the stitched spans."""
    from fantoch_tpu.observability.critpath import critpath_report
    from fantoch_tpu.observability.tracer import read_trace

    # recovery on, like every restored-tolerance row: a dot whose
    # MCollect was in flight at the crash instant only commits via
    # recovery consensus
    config = Config(3, 1, recovery_delay_ms=1000, trace_sample_rate=1.0)
    plan = FaultPlan(max_sim_time_ms=300_000).with_crash(
        1, at_ms=150, restart_at_ms=700
    )
    path = str(tmp_path / "restart.jsonl")
    runner, _monitors = restart_sim(EPaxos, config, plan, trace_path=path)
    kinds = {kind for _t, kind, _d in runner.nemesis.trace}
    assert {"crash", "durable-image", "restart"} <= kinds
    report = critpath_report(read_trace(path))
    assert report["spans"] > 0
    # exactness survives the crash: no vector may mis-telescope, even
    # ones whose stages straddle the restart
    assert report["telescoping_violations"] == 0
    # most spans still stitch (in-flight hops dropped AT the crash
    # instant legitimately lose their recv half)
    assert report["stitch_rate"] >= 0.9
    assert report["quorum_blame"]


def test_critpath_names_recovery_stage_for_crashed_coordinator(tmp_path):
    """A crashed-forever coordinator's in-flight dots heal by recovery
    consensus — and the blame vector must NAME that detour: the span
    keeps the out-of-chain recovery stage and the attribution carries
    ``blame["recovery"]`` with the entry point and the detour-to-commit
    wall."""
    from fantoch_tpu.observability.critpath import (
        OffsetTable,
        attribute_span,
        commit_times,
        estimate_client_offsets,
        match_edges,
    )
    from fantoch_tpu.observability.report import assemble_spans
    from fantoch_tpu.observability.tracer import read_trace

    config = Config(
        3, 1, recovery_delay_ms=300,
        trace_sample_rate=1.0,
    )
    plan = FaultPlan(max_sim_time_ms=300_000).with_crash(1, at_ms=120)
    path = str(tmp_path / "recover.jsonl")
    restart_sim(EPaxos, config, plan, trace_path=path)
    events = read_trace(path)
    spans = assemble_spans(events)
    recovered = [
        span for span in spans.values() if "recovery" in span["stages"]
    ]
    assert recovered, "a crashed-coordinator dot must enter recovery"
    dot_edges, client_edges = match_edges(events)
    offsets = OffsetTable(events, wall=False)
    client_off = estimate_client_offsets(spans, client_edges, wall=False)
    commits = commit_times(events)
    for span in recovered:
        vector = attribute_span(
            span, dot_edges, client_edges, offsets, client_off, commits
        )
        detour = vector["blame"]["recovery"]
        assert detour["entered_us"] == span["stages"]["recovery"]
        if "commit" in span["stages"]:
            assert detour["to_commit_us"] >= 0


def test_restart_determinism_and_trace_byte_identity(tmp_path):
    """Same seed twice through crash + durable image + restore + rejoin
    => identical nemesis traces, identical committed orders, and
    byte-identical span logs (the tracer survives the restart because
    restore() reattaches it and virtual time is shared)."""
    config = Config(
        3, 1, recovery_delay_ms=1000, newt_detached_send_interval_ms=100,
        trace_sample_rate=1.0,
    )
    plan = (
        FaultPlan(seed=1, max_sim_time_ms=300_000)
        .with_loss(0.1)
        .with_crash(2, at_ms=150, restart_at_ms=2500)
        .with_crash(3, at_ms=3000)
    )

    def one(tag):
        path = str(tmp_path / f"trace_{tag}.jsonl")
        runner, monitors = restart_sim(
            Newt, config, plan, commands_per_client=10, trace_path=path
        )
        committed = {pid: repr(m) for pid, m in monitors.items()}
        with open(path, "rb") as fh:
            blob = fh.read()
        return (
            runner.nemesis.trace_digest(),
            committed,
            hashlib.sha256(blob).hexdigest(),
            {kind for _t, kind, _d in runner.nemesis.trace},
        )

    digest_a, committed_a, trace_a, kinds = one("a")
    digest_b, committed_b, trace_b, _ = one("b")
    assert digest_a == digest_b
    assert committed_a == committed_b
    assert trace_a == trace_b
    # non-vacuous: the restart machinery actually ran ("defer-restart"
    # depends on a client submit being in flight at the crash instant,
    # which this workload shape does not guarantee)
    assert {"durable-image", "restart"} <= kinds


@pytest.mark.parametrize(
    "protocol_cls,config,plan",
    [
        (
            Caesar,
            Config(
                3, 1, recovery_delay_ms=1000,
                executor_monitor_pending_interval_ms=500,
                trace_sample_rate=1.0,
            ),
            PLAN_33,
        ),
        (
            FPaxos,
            Config(
                3, 1, leader=1, fpaxos_leader_timeout_ms=400,
                trace_sample_rate=1.0,
            ),
            FaultPlan(seed=1, max_sim_time_ms=300_000)
            .with_loss(0.1)
            .with_crash(1, at_ms=150, restart_at_ms=2500),
        ),
    ],
    ids=["caesar", "fpaxos"],
)
def test_new_protocol_restart_byte_identity(tmp_path, protocol_cls, config, plan):
    """The PR 7 determinism invariant, extended to the two protocols that
    joined the restart matrix in PR 12: same seed twice through Caesar
    crash + (clock, preds) recovery + restart, and FPaxos leader
    crash-restart + MSlotSync catch-up => byte-identical nemesis traces,
    committed orders, AND span logs."""

    def one(tag):
        path = str(tmp_path / f"trace_{protocol_cls.__name__}_{tag}.jsonl")
        runner, monitors = restart_sim(
            protocol_cls, config, plan, commands_per_client=10, trace_path=path
        )
        committed = {pid: repr(m) for pid, m in monitors.items()}
        with open(path, "rb") as fh:
            blob = fh.read()
        return (
            runner.nemesis.trace_digest(),
            committed,
            hashlib.sha256(blob).hexdigest(),
            {kind for _t, kind, _d in runner.nemesis.trace},
        )

    digest_a, committed_a, trace_a, kinds = one("a")
    digest_b, committed_b, trace_b, _ = one("b")
    assert digest_a == digest_b
    assert committed_a == committed_b
    assert trace_a == trace_b
    # non-vacuous: the restart machinery actually ran
    assert {"crash", "durable-image", "restart"} <= kinds


def test_fpaxos_on_peer_up_refreshes_targets():
    """Protocol-level on_peer_up: the returned peer re-enters the
    election candidate ring and pending forwards are re-sent to the
    leader (frames queued while it was declared dead were dropped)."""
    from fantoch_tpu.protocol.fpaxos import MForwardSubmit

    time = SimTime()
    config = Config(3, 1, leader=1, fpaxos_leader_timeout_ms=400, gc_interval_ms=100)
    follower, _ = FPaxos.new(2, 0, config)
    ok, _ = follower.discover([(2, 0), (1, 0), (3, 0)])
    assert ok
    cmd = Command.from_single(Rifl(7, 1), 0, "k", KVOp.put("v"))
    follower.submit(None, cmd, time)
    first = [a for a in follower.to_processes_iter()]
    assert any(isinstance(a.msg, MForwardSubmit) for a in first)
    follower.on_peer_down(3, time)
    assert 3 in follower._down
    follower.on_peer_up(3, time)
    assert 3 not in follower._down
    reforwards = [
        a for a in follower.to_processes_iter() if isinstance(a.msg, MForwardSubmit)
    ]
    assert len(reforwards) == 1, "the pending forward must be re-sent"
    assert reforwards[0].target == {1}


# --- device planes rebuild from the restored host mirror ---


def test_device_table_plane_rebuilds_after_restore():
    """Acceptance: restart costs the table plane exactly ONE host->device
    re-upload (``resident_uploads``), and the restored executor's KV
    state is bit-for-bit the uncrashed run's."""
    from fantoch_tpu.core import RunTime
    from fantoch_tpu.executor.table import TableExecutor, TableVotes
    from fantoch_tpu.protocol.common.table_clocks import VoteRange

    n = 3
    config = Config(
        n, 1, device_table_plane=True, executor_monitor_execution_order=True
    )
    time = RunTime()

    def rounds():
        out = []
        seq = 0
        for r in range(6):
            infos = []
            for k in range(3):
                seq += 1
                clock = r + 1
                infos.append(
                    TableVotes(
                        Dot(1, seq), clock, Rifl(1, seq), f"key{k}",
                        (KVOp.put(f"v{seq}"),),
                        [VoteRange(p, 1, clock) for p in range(1, n + 1)],
                    )
                )
            out.append(infos)
        return out

    # uncrashed reference
    reference = TableExecutor(1, 0, config)
    for infos in rounds():
        reference.handle_batch(list(infos), time)
    ref_results = sorted((r.rifl, r.key, r.op_results) for r in reference.to_clients_iter())

    # crashed run: snapshot mid-stream, restore, continue
    executor = TableExecutor(1, 0, config)
    all_rounds = rounds()
    results = []
    for infos in all_rounds[:3]:
        executor.handle_batch(list(infos), time)
    results.extend(executor.to_clients_iter())
    uploads_before = executor._plane.resident_uploads
    assert uploads_before == 1, "steady state is one initial upload"
    blob = executor.snapshot()
    restored = TableExecutor.restore(blob)
    assert restored._plane.resident_uploads == uploads_before
    for infos in all_rounds[3:]:
        restored.handle_batch(list(infos), time)
    results.extend(restored.to_clients_iter())
    assert restored._plane.resident_uploads == uploads_before + 1, (
        "recovery must cost exactly one re-upload, not one per batch"
    )
    assert sorted((r.rifl, r.key, r.op_results) for r in results) == ref_results
    # bit-for-bit final state parity
    assert restored._store._store == reference._store._store
    import numpy as np

    np.testing.assert_array_equal(
        restored._plane.frontiers(), reference._plane.frontiers()
    )


# --- depth-2 pipelined serving: in-flight rounds replay exactly-once ---


def test_pipelined_in_flight_rounds_replay_exactly_once():
    """Crash with two rounds dispatched-but-undrained in a depth-2
    pipeline: recovery rebuilds the driver and re-feeds the logged
    rounds; results come out exactly-once and in order (the WAL's
    append-before-dispatch discipline at the pipeline seam)."""
    from fantoch_tpu.run.pipeline import PipelineCore

    class Driver(PipelineCore):
        def __init__(self):
            self.batch_size = 8
            self._init_pipeline()
            self._round = 0
            self.executed = []

        def dispatch(self, batch):
            token = (self._round, list(batch))
            self._round += 1
            return token

        def drain(self, token):
            round_index, batch = token
            results = []
            for item in batch:
                if item in self.executed:
                    continue  # the rifl-dedup seam
                self.executed.append(item)
                results.append((round_index, item))
            return results

    wal_log = []  # (round items) appended BEFORE dispatch, like the WAL

    live = Driver()
    live.pipeline_depth = 2
    emitted = []
    for round_items in (["a1", "a2"], ["b1"], ["c1", "c2"], ["d1"]):
        wal_log.append(round_items)
        emitted.extend(live.step_pipelined(round_items))
    # depth 2: the last two rounds are still in flight — crash now
    assert len(live._inflight) == 2
    drained_rifls = [item for _r, item in emitted]

    recovered = Driver()
    recovered.pipeline_depth = 2
    recovered.executed = list(drained_rifls)  # the durable executed log
    replayed = []
    for round_items in wal_log:
        replayed.extend(recovered.step_pipelined(round_items))
    replayed.extend(recovered.flush_pipeline())
    replayed_rifls = [item for _r, item in replayed]
    # exactly-once: every command executes once across both lives,
    # including the two rounds that were in flight at the crash
    assert drained_rifls + replayed_rifls == ["a1", "a2", "b1", "c1", "c2", "d1"]
    assert recovered.executed == ["a1", "a2", "b1", "c1", "c2", "d1"]


def test_recovery_replay_advances_horizon_and_computes_lease_gap(tmp_path):
    """Boot-time recovery invariants, unit-level: (1) replayed tail
    commit dots fold into the restored protocol's committed clock (the
    rejoin horizon), and (2) the dot-lease's unissued remainder is
    computed as the gap recovery must commit (as noops) on rejoin — an
    unfilled own-source gap would freeze the mesh's contiguous stable
    frontier (and therefore GC) forever."""
    from fantoch_tpu.executor.graph.executor import GraphAdd
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.process_runner import ProcessRuntime
    from fantoch_tpu.run.wal import DOT_LEASE_BATCH, Wal

    wal_dir = tmp_path / "p3"
    wal = Wal(str(wal_dir), sync="always")
    wal.recover()
    for sequence in (1, 2):
        cmd = Command.from_single(
            Rifl(9, sequence), 0, f"k{sequence}", KVOp.put("v")
        )
        wal.append("info", GraphAdd(Dot(3, sequence), cmd, set()))
    wal.append_lease(2 + DOT_LEASE_BATCH)
    wal.close()

    config = Config(3, 1, recovery_delay_ms=500, gc_interval_ms=50)
    runtime = ProcessRuntime(
        EPaxos, 3, 0, config,
        listen_addr=("127.0.0.1", free_port()),
        client_addr=("127.0.0.1", free_port()),
        peers={},
        sorted_processes=[(3, 0), (1, 0), (2, 0)],
        wal_dir=str(wal_dir),
    )
    assert runtime._recovered
    assert runtime.wal_replayed_infos == 2
    # (1) the horizon covers the replayed commits — MSync must not
    # re-fetch them (re-applying would execute twice)
    assert runtime.process._gc_track.contains(Dot(3, 1))
    assert runtime.process._gc_track.contains(Dot(3, 2))
    # (2) the lease gap is exactly the unissued/uncommitted remainder
    gap = runtime._lease_gap_dots
    assert gap == [Dot(3, s) for s in range(3, 2 + DOT_LEASE_BATCH + 1)]
    # and the allocator resumes above the lease
    assert runtime.next_dot().sequence == 2 + DOT_LEASE_BATCH + 1


def test_sync_backfill_barrier_holds_until_records_applied():
    """The rejoin backfill barrier (fuzzer-found, soak seed 99): a peer's
    frontier backfill arriving BEFORE its own record chunks (delivery
    reorders under fault plans) must be HELD — releasing the consumed
    ranges before the records' ops land lets timestamp stability overtake
    a commit at the rejoiner, which then executes a higher-clock command
    around a lower-clock one and diverges from live history."""
    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.protocol.sync import MSyncBackfill, MSyncReply
    from fantoch_tpu.protocol.common.table_clocks import VoteRange, Votes
    from fantoch_tpu.protocol.newt import MDetached, Newt

    time = SimTime()
    config = Config(
        3, 1, gc_interval_ms=100, newt_detached_send_interval_ms=100,
        recovery_delay_ms=1000,
    )
    rejoiner, _ = Newt.new(3, 0, config)
    ok, _ = rejoiner.discover([(3, 0), (1, 0), (2, 0)])
    assert ok
    rejoiner.rejoin(time)
    list(rejoiner.to_processes_iter())

    backfill = Votes()
    backfill.add("K", VoteRange(1, 1, 8))
    # the backfill overtakes the records: it must be held, not applied
    rejoiner.handle(1, 0, MSyncBackfill(backfill, records=2), time)
    assert list(rejoiner.to_executors_iter()) == []
    assert rejoiner._held_backfills[1][1] == 2

    # one record applied (a committed noop — simplest valid record):
    # still below the barrier — and a DUPLICATED delivery of the same
    # chunk must not inflate the counter past it (distinct records, not
    # chunk lengths)
    rejoiner.handle(1, 0, MSyncReply([(Dot(1, 50), None, 0)]), time)
    rejoiner.handle(1, 0, MSyncReply([(Dot(1, 50), None, 0)]), time)
    drained = list(rejoiner.to_executors_iter())
    assert rejoiner._held_backfills, "one of two records is not the barrier"

    # the second record releases the backfill into the detached channel
    rejoiner.handle(1, 0, MSyncReply([(Dot(1, 51), None, 0)]), time)
    from fantoch_tpu.executor.table import TableDetachedVotes

    released = [
        info for info in rejoiner.to_executors_iter()
        if isinstance(info, TableDetachedVotes)
    ]
    assert released and not rejoiner._held_backfills
    assert any(
        any(v.start == 1 and v.end == 8 for v in info.votes)
        for info in released
    )
    # a fresh rejoin round resets the barrier state (a restored counter
    # would release a NEW backfill early)
    rejoiner.rejoin(time)
    assert rejoiner._sync_records_seen == {} and rejoiner._held_backfills == {}
    list(rejoiner.to_processes_iter())

    # the buffered-commit gate (the live-peer variant): a backfill with
    # no record stream (records=0) must still hold while a payload-less
    # buffered commit could own the covered ranges, and release once it
    # resolves (the periodic SendDetached sweep)
    from fantoch_tpu.protocol.newt import MCommit as NewtMCommit, SendDetachedEvent

    rejoiner.handle(1, 0, NewtMCommit(Dot(1, 60), 9, Votes()), time)
    assert Dot(1, 60) in rejoiner._buffered_mcommits
    rejoiner.handle(2, 0, MSyncBackfill(backfill, records=0), time)
    assert rejoiner._held_backfills, "buffered commit must gate the backfill"
    # the commit resolves (chosen-reply piggybacks the payload)
    rejoiner.handle(
        1, 0,
        NewtMCommit(
            Dot(1, 60), 9, Votes(), recovered=True,
            cmd=Command.from_single(Rifl(9, 60), 0, "K", KVOp.put("v")),
        ),
        time,
    )
    rejoiner.handle_event(SendDetachedEvent(), time)
    assert not rejoiner._held_backfills, "resolved commit must release it"


def test_caesar_wal_tail_replay_advances_horizon(tmp_path):
    """Caesar WAL tail replay: logged PredecessorsExecutionInfo records
    re-apply to the executor and their dots fold into the restored
    rejoin horizon (``note_durable_commits``) — MSync must not re-stream
    them (a second application would execute twice)."""
    from fantoch_tpu.executor.pred import PredecessorsExecutionInfo
    from fantoch_tpu.protocol.common.pred_clocks import Clock
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.process_runner import ProcessRuntime
    from fantoch_tpu.run.wal import Wal

    wal_dir = tmp_path / "p3"
    wal = Wal(str(wal_dir), sync="always")
    wal.recover()
    for sequence in (1, 2):
        cmd = Command.from_single(
            Rifl(9, sequence), 0, f"k{sequence}", KVOp.put("v")
        )
        wal.append(
            "info",
            PredecessorsExecutionInfo(
                Dot(3, sequence), cmd, Clock(sequence, 3), set()
            ),
        )
    wal.close()

    config = Config(3, 1, recovery_delay_ms=500, gc_interval_ms=50)
    runtime = ProcessRuntime(
        Caesar, 3, 0, config,
        listen_addr=("127.0.0.1", free_port()),
        client_addr=("127.0.0.1", free_port()),
        peers={},
        sorted_processes=[(3, 0), (1, 0), (2, 0)],
        wal_dir=str(wal_dir),
    )
    assert runtime._recovered
    assert runtime.wal_replayed_infos == 2
    # the replayed dots settle through the durable-tail OVERLAY, not the
    # GC clock: Caesar's handle_executed REPLACES that clock with the
    # executor's executed clock, which would drop a replayed commit
    # still pending on a dependency — the overlay keeps the straggler
    # guards (and the rejoin record latch) covering them regardless
    assert runtime.process._gc_straggler(Dot(3, 1))
    assert runtime.process._gc_straggler(Dot(3, 2))
    # the effects reached the restored executor (its executed clock is
    # what drives Caesar's executed-everywhere GC after rejoin)
    executed = runtime.executors[0].executed(None)
    assert executed.contains(3, 1) and executed.contains(3, 2)
    # once the executor reports, the overlay ages out into the GC clock
    runtime.process.handle_executed(executed, None)
    assert not runtime.process._durable_tail
    assert runtime.process._gc_track.contains(Dot(3, 1))


def test_fpaxos_wal_tail_replay_advances_slot_floor(tmp_path):
    """FPaxos WAL tail replay: logged SlotExecutionInfo records fold into
    the restored chosen log + committed watermark
    (``note_durable_chosen``), so the rejoin MSlotSync floor covers them
    — peers must not re-stream slots the executor replay already
    applied.  Also pins the lease-gap guard: SlotGCTrack has no dot
    clock, and recovery must not crash computing a dot lease gap."""
    from fantoch_tpu.executor.slot import SlotExecutionInfo
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.process_runner import ProcessRuntime
    from fantoch_tpu.run.wal import Wal

    wal_dir = tmp_path / "p2"
    wal = Wal(str(wal_dir), sync="always")
    wal.recover()
    cmds = {}
    for slot in (1, 2):
        cmd = Command.from_single(Rifl(9, slot), 0, f"k{slot}", KVOp.put("v"))
        cmds[slot] = cmd
        wal.append("info", SlotExecutionInfo(slot, cmd))
    wal.append_lease(10)  # a stale dot lease must not crash slot-GC recovery
    wal.close()

    config = Config(
        3, 1, leader=1, fpaxos_leader_timeout_ms=2000, gc_interval_ms=50
    )
    runtime = ProcessRuntime(
        FPaxos, 2, 0, config,
        listen_addr=("127.0.0.1", free_port()),
        client_addr=("127.0.0.1", free_port()),
        peers={},
        sorted_processes=[(2, 0), (1, 0), (3, 0)],
        wal_dir=str(wal_dir),
    )
    assert runtime._recovered
    assert runtime.wal_replayed_infos == 2
    process = runtime.process
    # the rejoin floor covers the replayed slots...
    assert process._slot_sync_floor() >= 2
    # ...and the chosen log can serve them to OTHER rejoiners
    records = process._slot_sync_records(0)
    assert [(slot, cmd.rifl) for slot, cmd in records] == [
        (1, Rifl(9, 1)), (2, Rifl(9, 2))
    ]
    assert process._slot_sync_records(2) == []
    # the executor replay advanced the slot frontier exactly once
    assert runtime.executors[0]._next_slot == 3


# --- run layer: WAL recovery + rejoin over real TCP ---


@pytest.mark.parametrize(
    "snapshot_interval_ms", [500, 600_000], ids=["snapshot+tail", "tail-only"]
)
def test_run_restart_from_wal_and_rejoin(tmp_path, snapshot_interval_ms):
    """Kill a runtime mid-mesh, restart it from its WAL dir: it recovers
    (snapshot + tail), peers revive it (incarnation-keyed dedup reset +
    on_peer_up), MSync pulls the commits it missed, and it serves clients
    again.  Monitors across all three lives agree (exactly-once).

    The ``tail-only`` variant pins the snapshot interval past the run so
    recovery is a pure log replay: the replayed commit dots must fold
    into the rejoin horizon (``note_durable_commits``) — without that,
    MSync re-streams the tail and the replica executes it twice."""
    from fantoch_tpu.run.client_runner import run_clients
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.links import ReconnectPolicy
    from fantoch_tpu.run.process_runner import ProcessRuntime

    commands = 10

    def make_runtime(pid, peer_ports, client_ports, config):
        return ProcessRuntime(
            EPaxos,
            pid,
            0,
            config,
            listen_addr=("127.0.0.1", peer_ports[pid]),
            client_addr=("127.0.0.1", client_ports[pid]),
            peers={p: ("127.0.0.1", peer_ports[p]) for p in (1, 2, 3) if p != pid},
            sorted_processes=[(pid, 0)] + [(p, 0) for p in (1, 2, 3) if p != pid],
            reconnect_policy=ReconnectPolicy(attempts=10, base_s=0.02, cap_s=0.2),
            # wide silence window: every runtime shares one cooperative
            # loop here, so load stalls must not read as peer death
            heartbeat_interval_s=0.2,
            heartbeat_misses=25,
            wal_dir=str(tmp_path / f"p{pid}"),
            wal_snapshot_interval_ms=snapshot_interval_ms,
        )

    async def scenario():
        config = Config(
            3, 1, executor_monitor_execution_order=True,
            gc_interval_ms=50, executor_executed_notification_interval_ms=50,
        )
        peer_ports = {pid: free_port() for pid in (1, 2, 3)}
        client_ports = {pid: free_port() for pid in (1, 2, 3)}
        runtimes = {
            pid: make_runtime(pid, peer_ports, client_ports, config)
            for pid in (1, 2, 3)
        }
        await asyncio.gather(*(r.start() for r in runtimes.values()))
        workload = Workload(
            shard_count=1, key_gen=ConflictRateKeyGen(50), keys_per_command=2,
            commands_per_client=commands, payload_size=1,
        )
        loop = asyncio.get_running_loop()

        # phase 1: p3 serves (its WAL sees commits), then crashes
        phase1 = await asyncio.wait_for(
            run_clients([1, 2], {0: ("127.0.0.1", client_ports[3])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        await asyncio.sleep(1.0)  # let a periodic snapshot land
        await runtimes[3].stop()

        # phase 2: commits p3 misses while dead
        phase2 = await asyncio.wait_for(
            run_clients([3, 4], {0: ("127.0.0.1", client_ports[1])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        deadline = loop.time() + 30
        while loop.time() < deadline:
            if all(3 in runtimes[p].dead_peers for p in (1, 2)):
                break
            await asyncio.sleep(0.1)
        assert all(3 in runtimes[p].dead_peers for p in (1, 2))

        # restart p3 from its WAL
        runtimes[3] = make_runtime(3, peer_ports, client_ports, config)
        assert runtimes[3]._recovered, "the WAL dir must drive a recovery"
        assert runtimes[3].incarnation == 2
        if snapshot_interval_ms > 10_000:
            # tail-only: the log replay itself must have done the work,
            # and the replayed horizon must already cover phase 1
            assert runtimes[3].wal_replayed_infos > 0
            clock = runtimes[3].process._gc_track.my_clock()
            own = clock.get(3)
            assert own is not None and own.frontier >= 2 * commands
        await runtimes[3].start()

        # revival + MSync catch-up: p3's horizon reaches phase-2 commits
        caught_up = False
        deadline = loop.time() + 30
        while loop.time() < deadline:
            clock = runtimes[3].process._gc_track.my_clock()
            events = clock.get(1)
            if (
                events is not None
                and events.frontier >= 2 * commands
                and all(3 not in runtimes[p].dead_peers for p in (1, 2))
            ):
                caught_up = True
                break
            await asyncio.sleep(0.2)
        assert caught_up, "MSync catch-up past the WAL horizon timed out"

        # phase 3: the restarted replica serves again
        phase3 = await asyncio.wait_for(
            run_clients([5, 6], {0: ("127.0.0.1", client_ports[3])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        failures = {pid: runtimes[pid].failure for pid in (1, 2, 3)}
        monitors = {pid: runtimes[pid].executors[0].monitor() for pid in (1, 2, 3)}
        await asyncio.gather(*(r.stop() for r in runtimes.values()))
        return phase1, phase2, phase3, failures, monitors

    phase1, phase2, phase3, failures, monitors = asyncio.run(scenario())
    for group in (phase1, phase2, phase3):
        for client_id, client in group.items():
            assert client.issued_commands == commands, (client_id, client.issued_commands)
    assert failures == {1: None, 2: None, 3: None}
    check_monitors(monitors)


def test_fpaxos_run_leader_restart_from_wal_and_rejoin(tmp_path):
    """FPaxos over real TCP, three phases: (1) the leader p1 serves (its
    WAL logs chosen slots), then is killed; the failure detector fires
    ``on_peer_down`` and the ring successor p2 elects itself; (2) clients
    complete against the new leader while p1 is down; (3) p1 restarts
    from its WAL, peers revive it, the higher-ballot heartbeat demotes
    its stale leadership, MSlotSync streams the chosen slots it missed,
    and it serves clients again (forwarding to p2).  Monitors across all
    three lives agree — exactly-once across the restart."""
    from fantoch_tpu.run.client_runner import run_clients
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.links import ReconnectPolicy
    from fantoch_tpu.run.process_runner import ProcessRuntime

    commands = 10

    def make_runtime(pid, peer_ports, client_ports, config):
        return ProcessRuntime(
            FPaxos,
            pid,
            0,
            config,
            listen_addr=("127.0.0.1", peer_ports[pid]),
            client_addr=("127.0.0.1", client_ports[pid]),
            peers={p: ("127.0.0.1", peer_ports[p]) for p in (1, 2, 3) if p != pid},
            sorted_processes=[(pid, 0)] + [(p, 0) for p in (1, 2, 3) if p != pid],
            reconnect_policy=ReconnectPolicy(attempts=10, base_s=0.02, cap_s=0.2),
            heartbeat_interval_s=0.2,
            heartbeat_misses=25,
            wal_dir=str(tmp_path / f"p{pid}"),
            wal_snapshot_interval_ms=500,
        )

    async def scenario():
        config = Config(
            3, 1, leader=1, fpaxos_leader_timeout_ms=2000,
            executor_monitor_execution_order=True,
            gc_interval_ms=50,
        )
        peer_ports = {pid: free_port() for pid in (1, 2, 3)}
        client_ports = {pid: free_port() for pid in (1, 2, 3)}
        runtimes = {
            pid: make_runtime(pid, peer_ports, client_ports, config)
            for pid in (1, 2, 3)
        }
        await asyncio.gather(*(r.start() for r in runtimes.values()))
        workload = Workload(
            shard_count=1, key_gen=ConflictRateKeyGen(50), keys_per_command=2,
            commands_per_client=commands, payload_size=1,
        )
        loop = asyncio.get_running_loop()

        # phase 1: the leader serves (its WAL sees chosen slots), then dies
        phase1 = await asyncio.wait_for(
            run_clients([1, 2], {0: ("127.0.0.1", client_ports[1])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        await asyncio.sleep(1.0)  # let a periodic snapshot land
        await runtimes[1].stop()

        # followers detect the dead leader; p2 (ring successor) elects
        deadline = loop.time() + 30
        while loop.time() < deadline:
            if all(1 in runtimes[p].dead_peers for p in (2, 3)):
                break
            await asyncio.sleep(0.1)
        assert all(1 in runtimes[p].dead_peers for p in (2, 3))

        # phase 2: the new leader serves while p1 is down
        phase2 = await asyncio.wait_for(
            run_clients([3, 4], {0: ("127.0.0.1", client_ports[2])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        assert runtimes[2].process._multi_synod.is_leader

        # restart p1 from its WAL
        runtimes[1] = make_runtime(1, peer_ports, client_ports, config)
        assert runtimes[1]._recovered, "the WAL dir must drive a recovery"
        assert runtimes[1].incarnation == 2
        await runtimes[1].start()

        # revival + MSlotSync catch-up: p1's slot floor reaches every
        # chosen slot (2 phases x 2 clients x `commands`), and the stale
        # restored leadership is demoted by p2's higher-ballot heartbeat
        total_slots = 4 * commands
        caught_up = False
        deadline = loop.time() + 30
        while loop.time() < deadline:
            if (
                runtimes[1].process._slot_sync_floor() >= total_slots
                and not runtimes[1].process._multi_synod.is_leader
                and runtimes[1].process._leader == 2
                and all(1 not in runtimes[p].dead_peers for p in (2, 3))
            ):
                caught_up = True
                break
            await asyncio.sleep(0.2)
        assert caught_up, (
            "MSlotSync catch-up timed out: floor "
            f"{runtimes[1].process._slot_sync_floor()}/{total_slots}"
        )

        # phase 3: the restarted replica serves again (forwards to p2)
        phase3 = await asyncio.wait_for(
            run_clients([5, 6], {0: ("127.0.0.1", client_ports[1])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        failures = {pid: runtimes[pid].failure for pid in (1, 2, 3)}
        monitors = {pid: runtimes[pid].executors[0].monitor() for pid in (1, 2, 3)}
        await asyncio.gather(*(r.stop() for r in runtimes.values()))
        return phase1, phase2, phase3, failures, monitors

    phase1, phase2, phase3, failures, monitors = asyncio.run(scenario())
    for group in (phase1, phase2, phase3):
        for client_id, client in group.items():
            assert client.issued_commands == commands, (client_id, client.issued_commands)
    assert failures == {1: None, 2: None, 3: None}
    check_monitors(monitors)
