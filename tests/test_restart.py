"""Restart & rejoin plane: replicas return to service instead of staying
dead.

PR 3 made crashes heal by routing *around* the corpse — every crash
permanently burned one unit of the n-f budget.  These tests drive the
restart plane through the stronger claim:

* **Restored tolerance** (the acceptance rows) — crash p_a with a
  scheduled restart, let it rejoin (durable image + MSync catch-up +
  vote backfill), then crash p_b *forever*.  Without the restart the
  combined failures exceed ``f`` and the run must stall; with it, every
  client not attached to the dead-forever replica completes and the
  execution-order monitors agree (exactly-once across the restart: a
  re-executed command would break write-order agreement).
* **Restart determinism** — same seed twice => byte-identical nemesis
  traces AND byte-identical span logs through crash, durable-image
  capture, restore, and rejoin.
* **Device planes rebuild** — a TableExecutor with the device table
  plane restores from its pickled host mirror: ONE re-upload
  (``resident_uploads``), bit-for-bit KV parity with an uncrashed run.
* **Pipelined serving** — rounds in flight in a depth-2 pipeline at
  crash time are re-fed from the log on recovery and come out
  exactly-once, in order.
* **Run layer** — a killed ProcessRuntime restarts from its WAL
  (snapshot + tail), peers detect it (``on_peer_up``: incarnation-keyed
  link-dedup reset, writer revival), MSync pulls the commits it missed,
  and it serves clients again; monitors agree across all three lives.
"""

import asyncio
import hashlib
import os

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Command, Config, Dot, KVOp, Planet, Rifl
from fantoch_tpu.core.planet import Region
from fantoch_tpu.core.timing import SimTime
from fantoch_tpu.protocol import Atlas, EPaxos, FPaxos, Newt
from fantoch_tpu.sim import Runner
from fantoch_tpu.sim.faults import FaultPlan

from harness import check_monitors

pytestmark = [pytest.mark.chaos, pytest.mark.restart]

COMMANDS_PER_CLIENT = 10 if os.environ.get("CI") else 15
CLIENTS_PER_PROCESS = 2


def flat_planet(n):
    """Near-equidistant regions: every crashed replica sits inside live
    fast quorums (the recovery rows' far=0 topology)."""
    regions = [Region(f"r{i}") for i in range(n)]
    latencies = {
        a: {b: (0 if i == j else 10 + abs(i - j)) for j, b in enumerate(regions)}
        for i, a in enumerate(regions)
    }
    return regions, Planet.from_latencies(latencies)


def restart_sim(
    protocol_cls,
    config: Config,
    plan: FaultPlan,
    commands_per_client: int = COMMANDS_PER_CLIENT,
    seed: int = 0,
    trace_path=None,
):
    n = config.n
    regions, planet = flat_planet(n)
    config = config.with_(
        executor_monitor_execution_order=True,
        executor_monitor_pending_interval_ms=500,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        shard_count=1,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(100),
        keys_per_command=1,
        commands_per_client=commands_per_client,
        payload_size=1,
    )
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        CLIENTS_PER_PROCESS,
        process_regions=regions,
        client_regions=list(regions),
        seed=seed,
        fault_plan=plan,
        trace_path=trace_path,
    )
    metrics, monitors, _latencies = runner.run(extra_sim_time_ms=2000)
    return runner, monitors


def assert_restored_tolerance(runner, monitors, restarted, dead_forever, commands):
    """Every client not attached to a dead-forever replica — including
    the restarted one's — completed; surviving monitors agree (a command
    re-executed across the restart would break write-order agreement)."""
    kinds = {kind for _t, kind, _d in runner.nemesis.trace}
    assert {"crash", "durable-image", "restart"} <= kinds
    dead = set(dead_forever)
    for client_id, client in runner._simulation.clients():
        if client.targets() & dead:
            continue
        assert client.issued_commands == commands, (
            f"client {client_id} (targets {client.targets()}) finished "
            f"{client.issued_commands}/{commands} after p{sorted(dead)} died"
        )
    check_monitors({pid: m for pid, m in monitors.items() if pid not in dead})


# --- acceptance rows: restart restores the tolerance budget ---

RESTART_33 = Config(3, 1, recovery_delay_ms=1000)
# p2 crashes and restarts; p3 then dies for good.  Without the restart
# this is 2 > f=1 dead (test_recovery_below_quorum_is_still_bounded's
# stall); with it the mesh is back to full strength when p3 dies.
PLAN_33 = (
    FaultPlan(seed=1, max_sim_time_ms=300_000)
    .with_loss(0.1)
    .with_crash(2, at_ms=150, restart_at_ms=2500)
    .with_crash(3, at_ms=3200)
)


@pytest.mark.parametrize(
    "protocol_cls,config",
    [
        (EPaxos, RESTART_33),
        (Atlas, RESTART_33),
        (Newt, RESTART_33.with_(newt_detached_send_interval_ms=100)),
    ],
    ids=["epaxos", "atlas", "newt"],
)
def test_restart_restores_tolerance_33(protocol_cls, config):
    runner, monitors = restart_sim(protocol_cls, config, PLAN_33)
    assert_restored_tolerance(
        runner, monitors, restarted=[2], dead_forever=[3],
        commands=COMMANDS_PER_CLIENT,
    )


def test_restart_restores_tolerance_52():
    """n=5/f=2: p2 crash-restarts, then p4 AND p5 die for good — three
    crashed processes overall, survivable only because p2 came back."""
    plan = (
        FaultPlan(seed=13, max_sim_time_ms=600_000)
        .with_loss(0.1)
        .with_crash(2, at_ms=150, restart_at_ms=3000)
        .with_crash(4, at_ms=4500)
        .with_crash(5, at_ms=4500)
    )
    runner, monitors = restart_sim(EPaxos, Config(5, 2, recovery_delay_ms=1500), plan)
    assert_restored_tolerance(
        runner, monitors, restarted=[2], dead_forever=[4, 5],
        commands=COMMANDS_PER_CLIENT,
    )


@pytest.mark.slow
@pytest.mark.parametrize("loss", [0.1, 0.3])
@pytest.mark.parametrize(
    "protocol_cls,config",
    [
        (EPaxos, Config(5, 2, recovery_delay_ms=1500)),
        (Atlas, Config(5, 2, recovery_delay_ms=1500)),
        (
            Newt,
            Config(5, 2, recovery_delay_ms=1500, newt_detached_send_interval_ms=100),
        ),
    ],
    ids=["epaxos", "atlas", "newt"],
)
def test_restart_matrix_52(protocol_cls, config, loss):
    """Acceptance matrix: crash-restart + subsequent double crash at
    n=5/f=2 under 10-30% loss, across EPaxos/Atlas/Newt."""
    plan = (
        FaultPlan(seed=13, max_sim_time_ms=600_000)
        .with_loss(loss)
        .with_crash(2, at_ms=150, restart_at_ms=3000)
        .with_crash(4, at_ms=4500)
        .with_crash(5, at_ms=4500)
    )
    runner, monitors = restart_sim(protocol_cls, config, plan)
    assert_restored_tolerance(
        runner, monitors, restarted=[2], dead_forever=[4, 5],
        commands=COMMANDS_PER_CLIENT,
    )


# --- determinism: restart decisions replay byte-identically ---


def test_restart_determinism_and_trace_byte_identity(tmp_path):
    """Same seed twice through crash + durable image + restore + rejoin
    => identical nemesis traces, identical committed orders, and
    byte-identical span logs (the tracer survives the restart because
    restore() reattaches it and virtual time is shared)."""
    config = Config(
        3, 1, recovery_delay_ms=1000, newt_detached_send_interval_ms=100,
        trace_sample_rate=1.0,
    )
    plan = (
        FaultPlan(seed=1, max_sim_time_ms=300_000)
        .with_loss(0.1)
        .with_crash(2, at_ms=150, restart_at_ms=2500)
        .with_crash(3, at_ms=3000)
    )

    def one(tag):
        path = str(tmp_path / f"trace_{tag}.jsonl")
        runner, monitors = restart_sim(
            Newt, config, plan, commands_per_client=10, trace_path=path
        )
        committed = {pid: repr(m) for pid, m in monitors.items()}
        with open(path, "rb") as fh:
            blob = fh.read()
        return (
            runner.nemesis.trace_digest(),
            committed,
            hashlib.sha256(blob).hexdigest(),
            {kind for _t, kind, _d in runner.nemesis.trace},
        )

    digest_a, committed_a, trace_a, kinds = one("a")
    digest_b, committed_b, trace_b, _ = one("b")
    assert digest_a == digest_b
    assert committed_a == committed_b
    assert trace_a == trace_b
    # non-vacuous: the restart machinery actually ran ("defer-restart"
    # depends on a client submit being in flight at the crash instant,
    # which this workload shape does not guarantee)
    assert {"durable-image", "restart"} <= kinds


def test_fpaxos_on_peer_up_refreshes_targets():
    """Protocol-level on_peer_up: the returned peer re-enters the
    election candidate ring and pending forwards are re-sent to the
    leader (frames queued while it was declared dead were dropped)."""
    from fantoch_tpu.protocol.fpaxos import MForwardSubmit

    time = SimTime()
    config = Config(3, 1, leader=1, fpaxos_leader_timeout_ms=400, gc_interval_ms=100)
    follower, _ = FPaxos.new(2, 0, config)
    ok, _ = follower.discover([(2, 0), (1, 0), (3, 0)])
    assert ok
    cmd = Command.from_single(Rifl(7, 1), 0, "k", KVOp.put("v"))
    follower.submit(None, cmd, time)
    first = [a for a in follower.to_processes_iter()]
    assert any(isinstance(a.msg, MForwardSubmit) for a in first)
    follower.on_peer_down(3, time)
    assert 3 in follower._down
    follower.on_peer_up(3, time)
    assert 3 not in follower._down
    reforwards = [
        a for a in follower.to_processes_iter() if isinstance(a.msg, MForwardSubmit)
    ]
    assert len(reforwards) == 1, "the pending forward must be re-sent"
    assert reforwards[0].target == {1}


# --- device planes rebuild from the restored host mirror ---


def test_device_table_plane_rebuilds_after_restore():
    """Acceptance: restart costs the table plane exactly ONE host->device
    re-upload (``resident_uploads``), and the restored executor's KV
    state is bit-for-bit the uncrashed run's."""
    from fantoch_tpu.core import RunTime
    from fantoch_tpu.executor.table import TableExecutor, TableVotes
    from fantoch_tpu.protocol.common.table_clocks import VoteRange

    n = 3
    config = Config(
        n, 1, device_table_plane=True, executor_monitor_execution_order=True
    )
    time = RunTime()

    def rounds():
        out = []
        seq = 0
        for r in range(6):
            infos = []
            for k in range(3):
                seq += 1
                clock = r + 1
                infos.append(
                    TableVotes(
                        Dot(1, seq), clock, Rifl(1, seq), f"key{k}",
                        (KVOp.put(f"v{seq}"),),
                        [VoteRange(p, 1, clock) for p in range(1, n + 1)],
                    )
                )
            out.append(infos)
        return out

    # uncrashed reference
    reference = TableExecutor(1, 0, config)
    for infos in rounds():
        reference.handle_batch(list(infos), time)
    ref_results = sorted((r.rifl, r.key, r.op_results) for r in reference.to_clients_iter())

    # crashed run: snapshot mid-stream, restore, continue
    executor = TableExecutor(1, 0, config)
    all_rounds = rounds()
    results = []
    for infos in all_rounds[:3]:
        executor.handle_batch(list(infos), time)
    results.extend(executor.to_clients_iter())
    uploads_before = executor._plane.resident_uploads
    assert uploads_before == 1, "steady state is one initial upload"
    blob = executor.snapshot()
    restored = TableExecutor.restore(blob)
    assert restored._plane.resident_uploads == uploads_before
    for infos in all_rounds[3:]:
        restored.handle_batch(list(infos), time)
    results.extend(restored.to_clients_iter())
    assert restored._plane.resident_uploads == uploads_before + 1, (
        "recovery must cost exactly one re-upload, not one per batch"
    )
    assert sorted((r.rifl, r.key, r.op_results) for r in results) == ref_results
    # bit-for-bit final state parity
    assert restored._store._store == reference._store._store
    import numpy as np

    np.testing.assert_array_equal(
        restored._plane.frontiers(), reference._plane.frontiers()
    )


# --- depth-2 pipelined serving: in-flight rounds replay exactly-once ---


def test_pipelined_in_flight_rounds_replay_exactly_once():
    """Crash with two rounds dispatched-but-undrained in a depth-2
    pipeline: recovery rebuilds the driver and re-feeds the logged
    rounds; results come out exactly-once and in order (the WAL's
    append-before-dispatch discipline at the pipeline seam)."""
    from fantoch_tpu.run.pipeline import PipelineCore

    class Driver(PipelineCore):
        def __init__(self):
            self.batch_size = 8
            self._init_pipeline()
            self._round = 0
            self.executed = []

        def dispatch(self, batch):
            token = (self._round, list(batch))
            self._round += 1
            return token

        def drain(self, token):
            round_index, batch = token
            results = []
            for item in batch:
                if item in self.executed:
                    continue  # the rifl-dedup seam
                self.executed.append(item)
                results.append((round_index, item))
            return results

    wal_log = []  # (round items) appended BEFORE dispatch, like the WAL

    live = Driver()
    live.pipeline_depth = 2
    emitted = []
    for round_items in (["a1", "a2"], ["b1"], ["c1", "c2"], ["d1"]):
        wal_log.append(round_items)
        emitted.extend(live.step_pipelined(round_items))
    # depth 2: the last two rounds are still in flight — crash now
    assert len(live._inflight) == 2
    drained_rifls = [item for _r, item in emitted]

    recovered = Driver()
    recovered.pipeline_depth = 2
    recovered.executed = list(drained_rifls)  # the durable executed log
    replayed = []
    for round_items in wal_log:
        replayed.extend(recovered.step_pipelined(round_items))
    replayed.extend(recovered.flush_pipeline())
    replayed_rifls = [item for _r, item in replayed]
    # exactly-once: every command executes once across both lives,
    # including the two rounds that were in flight at the crash
    assert drained_rifls + replayed_rifls == ["a1", "a2", "b1", "c1", "c2", "d1"]
    assert recovered.executed == ["a1", "a2", "b1", "c1", "c2", "d1"]


def test_recovery_replay_advances_horizon_and_computes_lease_gap(tmp_path):
    """Boot-time recovery invariants, unit-level: (1) replayed tail
    commit dots fold into the restored protocol's committed clock (the
    rejoin horizon), and (2) the dot-lease's unissued remainder is
    computed as the gap recovery must commit (as noops) on rejoin — an
    unfilled own-source gap would freeze the mesh's contiguous stable
    frontier (and therefore GC) forever."""
    from fantoch_tpu.executor.graph.executor import GraphAdd
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.process_runner import ProcessRuntime
    from fantoch_tpu.run.wal import DOT_LEASE_BATCH, Wal

    wal_dir = tmp_path / "p3"
    wal = Wal(str(wal_dir), sync="always")
    wal.recover()
    for sequence in (1, 2):
        cmd = Command.from_single(
            Rifl(9, sequence), 0, f"k{sequence}", KVOp.put("v")
        )
        wal.append("info", GraphAdd(Dot(3, sequence), cmd, set()))
    wal.append_lease(2 + DOT_LEASE_BATCH)
    wal.close()

    config = Config(3, 1, recovery_delay_ms=500, gc_interval_ms=50)
    runtime = ProcessRuntime(
        EPaxos, 3, 0, config,
        listen_addr=("127.0.0.1", free_port()),
        client_addr=("127.0.0.1", free_port()),
        peers={},
        sorted_processes=[(3, 0), (1, 0), (2, 0)],
        wal_dir=str(wal_dir),
    )
    assert runtime._recovered
    assert runtime.wal_replayed_infos == 2
    # (1) the horizon covers the replayed commits — MSync must not
    # re-fetch them (re-applying would execute twice)
    assert runtime.process._gc_track.contains(Dot(3, 1))
    assert runtime.process._gc_track.contains(Dot(3, 2))
    # (2) the lease gap is exactly the unissued/uncommitted remainder
    gap = runtime._lease_gap_dots
    assert gap == [Dot(3, s) for s in range(3, 2 + DOT_LEASE_BATCH + 1)]
    # and the allocator resumes above the lease
    assert runtime.next_dot().sequence == 2 + DOT_LEASE_BATCH + 1


# --- run layer: WAL recovery + rejoin over real TCP ---


@pytest.mark.parametrize(
    "snapshot_interval_ms", [500, 600_000], ids=["snapshot+tail", "tail-only"]
)
def test_run_restart_from_wal_and_rejoin(tmp_path, snapshot_interval_ms):
    """Kill a runtime mid-mesh, restart it from its WAL dir: it recovers
    (snapshot + tail), peers revive it (incarnation-keyed dedup reset +
    on_peer_up), MSync pulls the commits it missed, and it serves clients
    again.  Monitors across all three lives agree (exactly-once).

    The ``tail-only`` variant pins the snapshot interval past the run so
    recovery is a pure log replay: the replayed commit dots must fold
    into the rejoin horizon (``note_durable_commits``) — without that,
    MSync re-streams the tail and the replica executes it twice."""
    from fantoch_tpu.run.client_runner import run_clients
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.links import ReconnectPolicy
    from fantoch_tpu.run.process_runner import ProcessRuntime

    commands = 10

    def make_runtime(pid, peer_ports, client_ports, config):
        return ProcessRuntime(
            EPaxos,
            pid,
            0,
            config,
            listen_addr=("127.0.0.1", peer_ports[pid]),
            client_addr=("127.0.0.1", client_ports[pid]),
            peers={p: ("127.0.0.1", peer_ports[p]) for p in (1, 2, 3) if p != pid},
            sorted_processes=[(pid, 0)] + [(p, 0) for p in (1, 2, 3) if p != pid],
            reconnect_policy=ReconnectPolicy(attempts=10, base_s=0.02, cap_s=0.2),
            # wide silence window: every runtime shares one cooperative
            # loop here, so load stalls must not read as peer death
            heartbeat_interval_s=0.2,
            heartbeat_misses=25,
            wal_dir=str(tmp_path / f"p{pid}"),
            wal_snapshot_interval_ms=snapshot_interval_ms,
        )

    async def scenario():
        config = Config(
            3, 1, executor_monitor_execution_order=True,
            gc_interval_ms=50, executor_executed_notification_interval_ms=50,
        )
        peer_ports = {pid: free_port() for pid in (1, 2, 3)}
        client_ports = {pid: free_port() for pid in (1, 2, 3)}
        runtimes = {
            pid: make_runtime(pid, peer_ports, client_ports, config)
            for pid in (1, 2, 3)
        }
        await asyncio.gather(*(r.start() for r in runtimes.values()))
        workload = Workload(
            shard_count=1, key_gen=ConflictRateKeyGen(50), keys_per_command=2,
            commands_per_client=commands, payload_size=1,
        )
        loop = asyncio.get_running_loop()

        # phase 1: p3 serves (its WAL sees commits), then crashes
        phase1 = await asyncio.wait_for(
            run_clients([1, 2], {0: ("127.0.0.1", client_ports[3])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        await asyncio.sleep(1.0)  # let a periodic snapshot land
        await runtimes[3].stop()

        # phase 2: commits p3 misses while dead
        phase2 = await asyncio.wait_for(
            run_clients([3, 4], {0: ("127.0.0.1", client_ports[1])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        deadline = loop.time() + 30
        while loop.time() < deadline:
            if all(3 in runtimes[p].dead_peers for p in (1, 2)):
                break
            await asyncio.sleep(0.1)
        assert all(3 in runtimes[p].dead_peers for p in (1, 2))

        # restart p3 from its WAL
        runtimes[3] = make_runtime(3, peer_ports, client_ports, config)
        assert runtimes[3]._recovered, "the WAL dir must drive a recovery"
        assert runtimes[3].incarnation == 2
        if snapshot_interval_ms > 10_000:
            # tail-only: the log replay itself must have done the work,
            # and the replayed horizon must already cover phase 1
            assert runtimes[3].wal_replayed_infos > 0
            clock = runtimes[3].process._gc_track.my_clock()
            own = clock.get(3)
            assert own is not None and own.frontier >= 2 * commands
        await runtimes[3].start()

        # revival + MSync catch-up: p3's horizon reaches phase-2 commits
        caught_up = False
        deadline = loop.time() + 30
        while loop.time() < deadline:
            clock = runtimes[3].process._gc_track.my_clock()
            events = clock.get(1)
            if (
                events is not None
                and events.frontier >= 2 * commands
                and all(3 not in runtimes[p].dead_peers for p in (1, 2))
            ):
                caught_up = True
                break
            await asyncio.sleep(0.2)
        assert caught_up, "MSync catch-up past the WAL horizon timed out"

        # phase 3: the restarted replica serves again
        phase3 = await asyncio.wait_for(
            run_clients([5, 6], {0: ("127.0.0.1", client_ports[3])}, workload,
                        open_loop_interval_ms=10),
            60,
        )
        failures = {pid: runtimes[pid].failure for pid in (1, 2, 3)}
        monitors = {pid: runtimes[pid].executors[0].monitor() for pid in (1, 2, 3)}
        await asyncio.gather(*(r.stop() for r in runtimes.values()))
        return phase1, phase2, phase3, failures, monitors

    phase1, phase2, phase3, failures, monitors = asyncio.run(scenario())
    for group in (phase1, phase2, phase3):
        for client_id, client in group.items():
            assert client.issued_commands == commands, (client_id, client.issued_commands)
    assert failures == {1: None, 2: None, 3: None}
    check_monitors(monitors)
