"""Device-resident votes-table plane: the fused commit kernel
(ops/table_ops.fused_votes_commit), the resident frontier state
(executor/table_plane.DeviceTablePlane), the executor wired through it
(Config.device_table_plane), the resident clock-proposal table
(table_batched.BatchedKeyClocks over resident_clock_proposal), the fused
all-device round chain (fused_table_round/fused_table_rounds), and the
chained Newt serving dispatch (NewtDeviceDriver.step_chained) — each
oracle-checked bit-for-bit against the per-command host twins.
"""

import os
import pickle
import random

import numpy as np
import pytest

from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
from fantoch_tpu.core.clocks import RangeEventSet
from fantoch_tpu.executor.table import (
    TableDetachedVotes,
    TableExecutor,
    TableVotes,
    TableVotesArrays,
    TableVotesArraysBuilder,
)
from fantoch_tpu.executor.table_plane import ClockOverflowError, DeviceTablePlane
from fantoch_tpu.protocol.common.table_clocks import VoteRange

SHARD = 0


# ---------------------------------------------------------------------------
# the fused commit kernel vs the RangeEventSet frontier oracle
# ---------------------------------------------------------------------------


def oracle_frontiers(n_keys, n, applied):
    """Replay (key, by, start, end) votes through RangeEventSets and
    return the frontier matrix (by is 0-based here)."""
    sets = [[RangeEventSet() for _ in range(n)] for _ in range(n_keys)]
    for k, by, s, e in applied:
        sets[k][by].add_range(s, e)
    return np.array(
        [[sets[k][p].frontier for p in range(n)] for k in range(n_keys)],
        dtype=np.int64,
    )


def test_device_plane_matches_range_event_sets():
    """Random overlapping/adjacent/gapped vote ranges over several
    batches: the plane's resident frontiers equal the RangeEventSet
    oracle after every batch once its residual buffer has had the same
    votes (exactness contract: residuals re-feed until gaps fill)."""
    rng = random.Random(5)
    n, n_keys = 3, 8
    plane = DeviceTablePlane(n, stability_threshold=2, key_buckets=8)
    for k in range(n_keys):
        plane.bucket(f"k{k}")
    applied = []
    for _batch in range(12):
        vk, vb, vs, ve = [], [], [], []
        for _ in range(rng.randrange(1, 12)):
            k = rng.randrange(n_keys)
            by = rng.randrange(1, n + 1)
            s = rng.randrange(1, 25)
            e = s + rng.randrange(6)
            vk.append(k)
            vb.append(by)
            vs.append(s)
            ve.append(e)
            applied.append((k, by - 1, s, e))
        stable = plane.commit_votes(
            np.array(vk, np.int64), np.array(vb, np.int64),
            np.array(vs, np.int64), np.array(ve, np.int64),
        )
        oracle = oracle_frontiers(n_keys, n, applied)
        # a plane frontier may lag the oracle only where a residual run
        # is still buffered; with ranges drawn from [1, 31) every gap
        # eventually fills, so drive empty batches until residuals drain
        spins = 0
        while plane.residual_count and spins < 8:
            stable = plane.commit_votes(
                np.empty(0, np.int64), np.empty(0, np.int64),
                np.empty(0, np.int64), np.empty(0, np.int64),
            )
            spins += 1
        got = plane.frontiers()
        lag = got < oracle
        if lag.any():
            # residual runs that STILL start beyond a real gap: the
            # oracle's RangeEventSet also has not merged them into the
            # frontier (frontier = contiguous prefix only) — so the
            # frontiers must already agree; anything else is a bug
            assert (got == oracle).all(), f"plane lost votes:\n{got}\n{oracle}"
        assert (got <= oracle).all(), "plane frontier overtook the oracle"
        col = n - 2
        expect_stable = np.sort(oracle, axis=1)[:, col]
        assert (stable == expect_stable).all()


def test_device_plane_residual_gap_fill():
    """A beyond-gap run buffers as residual and lands exactly when the
    gap fills — the RangeEventSet add/merge sequence, replayed across
    dispatches."""
    plane = DeviceTablePlane(3, stability_threshold=2, key_buckets=4)
    b = plane.bucket("x")
    one = lambda s, e: (  # noqa: E731 — single-vote batch helper
        np.array([b], np.int64), np.array([1], np.int64),
        np.array([s], np.int64), np.array([e], np.int64),
    )
    plane.commit_votes(*one(5, 9))  # beyond the gap [1,4]
    assert plane.residual_count == 1
    assert plane.frontiers()[0].tolist() == [0, 0, 0]
    plane.commit_votes(*one(1, 4))  # fills the gap; residual coalesces
    assert plane.residual_count == 0
    assert plane.frontiers()[0].tolist() == [9, 0, 0]


def test_device_plane_bucket_growth_preserves_state():
    plane = DeviceTablePlane(3, stability_threshold=2, key_buckets=2)
    a = plane.bucket("a")
    plane.commit_votes(
        np.array([a], np.int64), np.array([1], np.int64),
        np.array([1], np.int64), np.array([4], np.int64),
    )
    for i in range(10):  # force capacity doublings past the resident state
        plane.bucket(f"grow{i}")
    assert plane.grows >= 2
    assert plane.frontiers()[a].tolist() == [4, 0, 0]


def test_device_plane_clock_overflow_rejected():
    plane = DeviceTablePlane(3, stability_threshold=2)
    b = plane.bucket("x")
    with pytest.raises(ClockOverflowError):
        plane.commit_votes(
            np.array([b], np.int64), np.array([1], np.int64),
            np.array([1], np.int64), np.array([1 << 31], np.int64),
        )


def test_config_rejects_plane_with_realtime_clocks():
    with pytest.raises(ValueError, match="device_table_plane"):
        Config(
            3, 1, device_table_plane=True, newt_clock_bump_interval_ms=10
        )


# ---------------------------------------------------------------------------
# satellite: the kernel-threshold knob (Config + env override) and the
# kernel/partition agreement it arbitrates
# ---------------------------------------------------------------------------


def test_kernel_threshold_config_knob_and_env(monkeypatch):
    base = Config(3, 1)
    assert TableExecutor(1, SHARD, base)._kernel_threshold == (1 << 20)
    explicit = Config(3, 1, table_kernel_threshold=123)
    assert TableExecutor(1, SHARD, explicit)._kernel_threshold == 123
    monkeypatch.setenv("FANTOCH_TABLE_KERNEL_THRESHOLD", "77")
    assert TableExecutor(1, SHARD, base)._kernel_threshold == 77
    # an explicit Config value beats the env override
    assert TableExecutor(1, SHARD, explicit)._kernel_threshold == 123


def test_kernel_threshold_routes_both_branches_and_they_agree(monkeypatch):
    """threshold=1 routes _stable_clocks through the device kernel,
    a huge threshold through np.partition — same clocks either way."""
    rng = np.random.default_rng(3)
    frontiers = rng.integers(0, 1 << 20, size=(64, 5))
    kernel_cfg = Config(5, 1, table_kernel_threshold=1)
    host_cfg = Config(5, 1, table_kernel_threshold=1 << 60)
    ex_k = TableExecutor(1, SHARD, kernel_cfg)
    ex_h = TableExecutor(1, SHARD, host_cfg)
    col = 5 - ex_k._stability_threshold
    expected = np.sort(frontiers, axis=1)[:, col]
    assert (ex_k._stable_clocks(frontiers) == expected).all()
    assert (ex_h._stable_clocks(frontiers) == expected).all()


# ---------------------------------------------------------------------------
# satellite: randomized oracle equivalence across ALL four executor
# feeds — handle / handle_batch / handle_batch_arrays / device plane —
# covering execute_at_commit, TableDetachedVotes, non-contiguous ranges
# ---------------------------------------------------------------------------


def _random_rounds(rng, n, n_rounds=10):
    """Rounds of protocol-consistent infos: per-key consecutive clocks,
    the coordinator voting its consumed range, peers voting full,
    partial, gapped (non-contiguous), or no prefixes, plus occasional
    detached votes; a final all-votes flush stabilizes everything."""
    key_clock = {}
    seq = 1
    rounds = []
    for _ in range(n_rounds):
        infos = []
        for _ in range(rng.randrange(1, 12)):
            key = f"k{rng.randrange(4)}"
            c = key_clock.get(key, 0) + 1
            key_clock[key] = c
            votes = [VoteRange(1, c, c)]
            for p in range(2, n + 1):
                kind = rng.randrange(4)
                if kind == 0:
                    votes.append(VoteRange(p, 1, c))
                elif kind == 1 and c > 2:
                    votes.append(VoteRange(p, 2, c))  # gap below: residual
                elif kind == 2 and c > 1:
                    votes.append(VoteRange(p, 1, c - 1))
            infos.append(
                TableVotes(
                    Dot(1, seq), c, Rifl(1, seq), key,
                    (KVOp.put(f"v{seq}"),), votes,
                )
            )
            seq += 1
        if rng.randrange(3) == 0 and key_clock:
            key = rng.choice(sorted(key_clock))
            up = key_clock[key]
            infos.append(
                TableDetachedVotes(
                    key, [VoteRange(p, 1, up) for p in range(2, n + 1)]
                )
            )
        rounds.append(infos)
    flush = [
        TableDetachedVotes(k, [VoteRange(p, 1, c) for p in range(1, n + 1)])
        for k, c in sorted(key_clock.items())
    ]
    rounds.append(flush)
    return rounds


def _infos_to_arrays(infos):
    builder = TableVotesArraysBuilder()
    for info in infos:
        if isinstance(info, TableVotes):
            builder.add_row(
                info.dot, info.clock, info.rifl, info.key, info.ops,
                info.votes,
            )
        else:
            builder.add_detached(info.key, info.votes)
    return builder.take()


def _drain_per_key(ex):
    out = {}
    while (r := ex.to_clients()) is not None:
        out.setdefault(r.key, []).append((r.rifl, r.op_results))
    return out


@pytest.mark.parametrize("execute_at_commit", [False, True])
def test_four_feed_oracle_equivalence(execute_at_commit):
    """handle vs handle_batch vs handle_batch_arrays vs the device plane
    produce identical per-key executions and identical KVStore state on
    randomized rounds with detached votes and non-contiguous ranges."""
    rng = random.Random(11)
    n = 3
    time = RunTime()
    rounds = _random_rounds(rng, n)

    def build(batched, plane):
        return TableExecutor(
            1, SHARD,
            Config(
                n, 1,
                batched_table_executor=batched,
                device_table_plane=plane,
                execute_at_commit=execute_at_commit,
            ),
        )

    ex_handle = build(False, False)
    ex_batch = build(True, False)
    ex_arrays = build(True, False)
    ex_plane = build(True, True)
    results = {}
    executions = {}
    for name, ex in (
        ("handle", ex_handle), ("batch", ex_batch),
        ("arrays", ex_arrays), ("plane", ex_plane),
    ):
        per_key = {}
        for infos in rounds:
            if name == "handle":
                for info in infos:
                    ex.handle(info, time)
            elif name == "batch":
                ex.handle_batch(list(infos), time)
            else:
                arrays = _infos_to_arrays(infos)
                if arrays is not None:
                    ex.handle_batch_arrays(arrays, time)
            for key, rows in _drain_per_key(ex).items():
                per_key.setdefault(key, []).extend(rows)
        results[name] = ex._store._store
        executions[name] = per_key
    for name in ("batch", "arrays", "plane"):
        assert executions[name] == executions["handle"], (
            f"{name} diverged from the per-info oracle "
            f"(execute_at_commit={execute_at_commit})"
        )
        assert results[name] == results["handle"]


def test_plane_handles_mixed_info_stream():
    """A mixed stream (objects + pre-built arrays batches) through
    handle_batch on a plane executor equals the per-info oracle — the
    _as_arrays_batches funnel preserves relative order."""
    rng = random.Random(23)
    n = 3
    time = RunTime()
    rounds = _random_rounds(rng, n, n_rounds=6)
    ex_plane = TableExecutor(
        1, SHARD, Config(n, 1, batched_table_executor=True,
                         device_table_plane=True),
    )
    ex_oracle = TableExecutor(1, SHARD, Config(n, 1))
    got, want = {}, {}
    for r, infos in enumerate(rounds):
        if r % 2 == 0 and len(infos) > 1:
            half = len(infos) // 2
            mixed = list(infos[:half])
            arrays = _infos_to_arrays(infos[half:])
            if arrays is not None:
                mixed.append(arrays)
        else:
            mixed = list(infos)
        ex_plane.handle_batch(mixed, time)
        for info in infos:
            ex_oracle.handle(info, time)
        for key, rows in _drain_per_key(ex_plane).items():
            got.setdefault(key, []).extend(rows)
        for key, rows in _drain_per_key(ex_oracle).items():
            want.setdefault(key, []).extend(rows)
    assert got == want
    assert ex_plane._store._store == ex_oracle._store._store


# ---------------------------------------------------------------------------
# the resident clock-proposal table
# ---------------------------------------------------------------------------


def test_resident_proposal_interleaves_with_scalar_access():
    """proposal_batch_arrays keeps the clock table on device; scalar
    proposal/detached_all calls in between must see (and mutate) live
    clocks — parity against the sequential twin across the interleaving,
    plus a pickle round-trip mid-stream (device buffers must not leak
    into snapshots)."""
    from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks
    from fantoch_tpu.protocol.common.table_clocks import (
        SequentialKeyClocks,
        Votes,
    )

    rng = random.Random(2)
    bat = BatchedKeyClocks(1, SHARD)
    seq = SequentialKeyClocks(1, SHARD)
    next_id = 0
    for round_ in range(6):
        keys = [f"k{rng.randrange(5)}" for _ in range(rng.randrange(1, 30))]
        mins = [rng.randrange(0, 10) for _ in keys]
        clock_col, start_col = bat.proposal_batch_arrays(keys, mins)
        for i, key in enumerate(keys):
            cmd = Command.from_single(
                Rifl(1, next_id + 1), SHARD, key, KVOp.put("")
            )
            next_id += 1
            c, votes = seq.proposal(cmd, mins[i])
            assert c == int(clock_col[i])
            ((_k, ranges),) = list(votes)
            assert (ranges[0].start, ranges[0].end) == (
                int(start_col[i]), int(clock_col[i]),
            )
        if round_ == 2:
            bat = pickle.loads(pickle.dumps(bat))  # snapshot mid-stream
        # scalar interleave: a detached_all sweep on both sides
        up = 20 * (round_ + 1)
        vb, vs = Votes(), Votes()
        bat.detached_all(up, vb)
        seq.detached_all(up, vs)
        as_dict = lambda v: {  # noqa: E731
            k: [(r.by, r.start, r.end) for r in rs] for k, rs in v
        }
        assert as_dict(vb) == as_dict(vs)


def test_resident_rebuild_does_not_leak_pad_bucket_clock():
    """Regression: when the key registry outgrows the device table, the
    rebuild must NOT copy the old pad bucket's accumulated clock into
    the key that now occupies that index — its proposal would be
    inflated and this process's vote frontier would gain a permanent
    gap.  (Found by review: two calls on a fresh instance sufficed.)"""
    from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks
    from fantoch_tpu.protocol.common.table_clocks import SequentialKeyClocks

    bat = BatchedKeyClocks(1, SHARD)
    seq = SequentialKeyClocks(1, SHARD)
    rounds = [
        (["k2", "k0", "k1", "k0", "k0"], [1, 3, 0, 0, 3]),
        (["k4"], [0]),  # k4 lands on the old device table's pad slot
        (["k4", "k3", "k5", "k4"], [0, 2, 0, 0]),
    ]
    next_id = 0
    for keys, mins in rounds:
        clock_col, start_col = bat.proposal_batch_arrays(keys, mins)
        for i, key in enumerate(keys):
            cmd = Command.from_single(
                Rifl(1, next_id + 1), SHARD, key, KVOp.put("")
            )
            next_id += 1
            c, votes = seq.proposal(cmd, mins[i])
            assert c == int(clock_col[i]), (key, c, int(clock_col[i]))
            ((_k, ranges),) = list(votes)
            assert (ranges[0].start, ranges[0].end) == (
                int(start_col[i]), int(clock_col[i]),
            )


def test_resident_window_bound_drift_recovers_without_fallback():
    """The incrementally-grown window bound (+bcap per resident batch)
    eventually trips the guard even with tiny real clocks; materializing
    tightens it and the kernel path must continue — no sequential
    fallback, no wrong clocks."""
    from fantoch_tpu.protocol.common import table_batched
    from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks

    bat = BatchedKeyClocks(1, SHARD)
    out = bat.proposal_batch_arrays(["a", "b"], [0, 0])
    assert out is not None
    bat._host_max = table_batched._INT32_MAX - 1  # simulate long drift
    out = bat.proposal_batch_arrays(["a", "b"], [0, 0])
    assert out is not None, "tightened bound must keep the kernel path"
    assert out[0].tolist() == [2, 2]
    assert bat._host_max < 1 << 20  # bound reset to reality


def test_resident_proposal_window_overflow_falls_back():
    from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks

    bat = BatchedKeyClocks(1, SHARD)
    assert bat.proposal_batch_arrays(["a"], [5]) is not None
    # a min clock near the 31-bit cap forces the sequential fallback
    assert bat.proposal_batch_arrays(["a"], [(1 << 31) - 2]) is None
    # the host mirror was materialized before the fallback: scalar path
    # continues from the device-computed clock
    cmd = Command.from_single(Rifl(1, 1), SHARD, "a", KVOp.put(""))
    clock, _ = bat.proposal(cmd, 0)
    assert clock == 6


def test_resident_buffers_never_alias_host_numpy(monkeypatch):
    """Regression: buffers handed to the DONATED argnums of the resident
    kernels must be XLA-owned copies.  On the CPU backend
    jnp.asarray/device_put zero-copy alias numpy memory, and donating the
    alias hands numpy-owned memory to XLA — nondeterministic wrong
    clocks and heap corruption (glibc aborts under the persistent
    compile cache).  Spy on np.zeros to capture every host staging
    buffer the rebuilds allocate and assert the resident device arrays
    share memory with none of them."""
    from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks

    made = []
    orig_zeros = np.zeros

    def spy_zeros(*args, **kwargs):
        arr = orig_zeros(*args, **kwargs)
        made.append(arr)
        return arr

    monkeypatch.setattr(np, "zeros", spy_zeros)

    bat = BatchedKeyClocks(1, SHARD)
    assert bat.proposal_batch_arrays(["a", "b"], [0, 0]) is not None
    dev_prior = np.asarray(bat._dev_prior)
    assert not any(
        m.size and np.shares_memory(dev_prior, m) for m in made
    ), "resident clock table aliases a host numpy buffer (donation UAF)"

    made.clear()
    plane = DeviceTablePlane(3, 2, key_buckets=2)
    plane.commit_votes(
        np.array([plane.bucket("a")], dtype=np.int64),
        np.array([1], dtype=np.int64),
        np.array([1], dtype=np.int64),
        np.array([1], dtype=np.int64),
    )
    for i in range(4):  # outgrow cap=2: _grow re-stages via np.zeros
        plane.bucket(f"g{i}")
    assert plane.grows >= 1
    frontier = np.asarray(plane._frontier)
    assert not any(
        m.size and np.shares_memory(frontier, m) for m in made
    ), "resident frontier matrix aliases a host numpy buffer (donation UAF)"


# ---------------------------------------------------------------------------
# the fused all-device round chain
# ---------------------------------------------------------------------------


def test_fused_table_round_matches_host_twins():
    """fused_table_round (proposal + dense votes + stability in ONE
    dispatch) assigns the clocks the proposal kernel assigns and the
    stability the RangeEventSet oracle derives, round after round on
    donated state."""
    import jax.numpy as jnp

    from fantoch_tpu.ops.table_ops import fused_table_round
    from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks

    rng = np.random.default_rng(7)
    n, kcap, batch = 3, 16, 32
    threshold = Config(n, 1).newt_quorum_sizes()[2]
    prior = jnp.zeros((kcap,), jnp.int32)
    frontier = jnp.zeros((kcap, n), jnp.int32)
    clocks = BatchedKeyClocks(1, SHARD)
    sets = [[RangeEventSet() for _ in range(n)] for _ in range(kcap)]
    for _round in range(5):
        key_np = rng.integers(0, kcap - 1, size=batch).astype(np.int32)
        mins_np = rng.integers(0, 5, size=batch).astype(np.int32)
        prior, frontier, clock, vote_start, executable, gaps = (
            fused_table_round(
                prior, frontier, jnp.asarray(key_np), jnp.asarray(mins_np),
                threshold=threshold, voters=n,
            )
        )
        assert int(gaps) == 0  # dense regime: every voter contiguous
        key_strs = [f"k{k}" for k in key_np]
        expect_clock, expect_start = clocks.proposal_batch_arrays(
            key_strs, mins_np.tolist()
        )
        assert np.asarray(clock).tolist() == expect_clock.tolist()
        assert np.asarray(vote_start).tolist() == expect_start.tolist()
        # oracle stability: every process votes every consumed range
        for i in range(batch):
            for p in range(n):
                sets[key_np[i]][p].add_range(
                    int(expect_start[i]), int(expect_clock[i])
                )
        stable = np.array(
            [
                sorted(es.frontier for es in row)[n - threshold]
                for row in sets
            ],
            dtype=np.int64,
        )
        assert bool(np.asarray(executable).all()) == bool(
            (np.asarray(clock) <= stable[key_np]).all()
        )
        assert (np.asarray(executable) == (np.asarray(clock) <= stable[key_np])).all()


def test_fused_table_rounds_chain_equals_single_rounds():
    """S chained rounds in one dispatch == S sequential fused rounds."""
    import jax.numpy as jnp

    from fantoch_tpu.ops.table_ops import fused_table_round, fused_table_rounds

    rng = np.random.default_rng(13)
    n, kcap, batch, S = 3, 8, 16, 4
    threshold = Config(n, 1).newt_quorum_sizes()[2]
    keys_np = rng.integers(0, kcap - 1, size=(S, batch)).astype(np.int32)
    mins_np = rng.integers(0, 4, size=(S, batch)).astype(np.int32)

    prior_c, frontier_c, clock_c, start_c, exec_c, gaps_c = fused_table_rounds(
        jnp.zeros((kcap,), jnp.int32), jnp.zeros((kcap, n), jnp.int32),
        jnp.asarray(keys_np), jnp.asarray(mins_np),
        threshold=threshold, voters=n,
    )
    prior = jnp.zeros((kcap,), jnp.int32)
    frontier = jnp.zeros((kcap, n), jnp.int32)
    for r in range(S):
        prior, frontier, clock, start, execu, gaps = fused_table_round(
            prior, frontier, jnp.asarray(keys_np[r]), jnp.asarray(mins_np[r]),
            threshold=threshold, voters=n,
        )
        assert np.asarray(clock_c)[r].tolist() == np.asarray(clock).tolist()
        assert np.asarray(start_c)[r].tolist() == np.asarray(start).tolist()
        assert np.asarray(exec_c)[r].tolist() == np.asarray(execu).tolist()
    assert np.asarray(prior_c).tolist() == np.asarray(prior).tolist()
    assert np.asarray(frontier_c).tolist() == np.asarray(frontier).tolist()


# ---------------------------------------------------------------------------
# Newt end-to-end: the commit-arrays seam and the chained serving dispatch
# ---------------------------------------------------------------------------


def test_newt_set_commit_arrays_flushes_pending():
    from fantoch_tpu.protocol import Newt

    config = Config(
        3, 1, batched_table_executor=True, newt_detached_send_interval_ms=5
    )
    newt = Newt(1, SHARD, config)
    assert newt._commit_arrays is not None
    newt._commit_arrays.add_detached("x", [VoteRange(1, 1, 3)])
    newt.set_commit_arrays(False)  # multi-executor pools route per key
    assert newt._commit_arrays is None
    flushed = newt.to_executors()
    assert isinstance(flushed, TableVotesArrays)
    assert flushed.det_keys == ["x"]
    assert newt.to_executors() is None


@pytest.mark.parametrize("plane", [False, True])
def test_sim_newt_plane_matches_sequential(plane):
    from harness import sim_test

    from fantoch_tpu.protocol import Newt

    def cfg(batched, use_plane=False):
        return Config(
            n=3, f=1, newt_detached_send_interval_ms=100,
            batched_table_executor=batched,
            device_table_plane=use_plane,
        )

    assert sim_test(Newt, cfg(True, plane), seed=3, keys_per_command=1) == (
        sim_test(Newt, cfg(False), seed=3, keys_per_command=1)
    )


def test_newt_driver_step_chained_matches_sequential_steps():
    """S rounds through ONE chained dispatch == S sequential step()
    rounds: same execution order, same KVStore."""
    from fantoch_tpu.run.device_runner import NewtDeviceDriver

    rng = np.random.default_rng(5)
    B, rounds_n = 16, 6
    keys = rng.integers(0, 24, size=B * rounds_n)
    cmds = [
        (
            Dot(1, i + 1),
            Command.from_single(
                Rifl(1, i + 1), SHARD, f"c{keys[i]}", KVOp.put(f"v{i}")
            ),
        )
        for i in range(B * rounds_n)
    ]
    batches = [cmds[r * B : (r + 1) * B] for r in range(rounds_n)]

    seq_driver = NewtDeviceDriver(3, batch_size=B, key_buckets=64)
    seq_results = []
    for batch in batches:
        seq_results.extend(seq_driver.step(batch))

    chain_driver = NewtDeviceDriver(3, batch_size=B, key_buckets=64)
    chained = chain_driver.step_chained(batches[:3])
    chained += chain_driver.step_chained(batches[3:])

    assert [(r.rifl, r.key) for r in chained] == [
        (r.rifl, r.key) for r in seq_results
    ]
    assert chain_driver.store._store == seq_driver.store._store
    assert chain_driver.rounds == seq_driver.rounds
