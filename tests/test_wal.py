"""Durable command log (run/wal.py): the durability edges the restart
plane's correctness rests on.

* torn-tail truncation — a crash mid-record loses that record only; the
  crash-consistent prefix replays, and the reopened log never chains new
  records onto garbage;
* duplicate replay — a crash between append and ack means a peer resends
  a message whose effects the WAL already replayed: the protocol layer's
  status / rifl dedup makes re-delivery exactly-once;
* segment rotation racing the GC clock — snapshots rotate + prune, so the
  log stays bounded by the snapshot cadence while every record past the
  snapshot survives;
* dot lease — a restarted process never re-issues a pre-crash sequence;
* fsync-policy resolution — one knob, config > env > default.
"""

import os
import pickle

import pytest

from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl
from fantoch_tpu.core.timing import SimTime
from fantoch_tpu.run.wal import (
    DOT_LEASE_BATCH,
    Wal,
    read_segment,
    resolve_wal_sync,
)

pytestmark = pytest.mark.restart


def test_append_recover_roundtrip(tmp_path):
    wal = Wal(str(tmp_path), sync="always")
    wal.recover()
    records = [("info", {"dot": (1, i), "payload": "x" * i}) for i in range(20)]
    for kind, obj in records:
        wal.append(kind, obj)
    wal.close()
    state = Wal(str(tmp_path)).recover()
    assert state.snapshot is None
    assert state.tail == records
    assert state.incarnation == 2  # one bump per recover()


def test_torn_tail_truncated_mid_record(tmp_path):
    wal = Wal(str(tmp_path), sync="always")
    wal.recover()
    for i in range(10):
        wal.append("info", ("rec", i))
    wal.close()
    # crash mid-write: chop bytes off the last record
    seg = sorted(p for p in os.listdir(tmp_path) if p.endswith(".seg"))[-1]
    path = os.path.join(tmp_path, seg)
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size - 7)
    records, valid = read_segment(path)
    assert [obj for _k, obj in records] == [("rec", i) for i in range(9)]
    assert valid < size - 7  # the torn record's prefix is not "valid"
    # recovery returns the prefix, truncates, and appends cleanly after
    wal2 = Wal(str(tmp_path), sync="always")
    state = wal2.recover()
    assert [obj for _k, obj in state.tail] == [("rec", i) for i in range(9)]
    assert os.path.getsize(path) == valid
    wal2.append("info", ("rec", "post-crash"))
    wal2.close()
    state = Wal(str(tmp_path)).recover()
    assert [obj for _k, obj in state.tail][-1] == ("rec", "post-crash")


def test_corrupt_mid_chain_stops_replay(tmp_path):
    """A flipped byte mid-segment (lost/rotted write) must stop replay at
    the corruption — records past a tear may postdate unseen state."""
    wal = Wal(str(tmp_path), sync="always")
    wal.recover()
    for i in range(10):
        wal.append("info", ("rec", i))
    wal.close()
    seg = sorted(p for p in os.listdir(tmp_path) if p.endswith(".seg"))[-1]
    path = os.path.join(tmp_path, seg)
    with open(path, "r+b") as fh:
        fh.seek(os.path.getsize(path) // 2)
        fh.write(b"\xff")
    state = Wal(str(tmp_path)).recover()
    objs = [obj for _k, obj in state.tail]
    assert objs == [("rec", i) for i in range(len(objs))]  # a strict prefix
    assert len(objs) < 10


def test_mid_chain_tear_unlinks_later_segments(tmp_path):
    """A tear in a non-final segment drops the later segments from
    replay AND from disk: appends resume in the truncated segment, so a
    later recovery must never resurrect the stale segments after the
    new records (out-of-order replay)."""
    wal = Wal(str(tmp_path), sync="always", segment_bytes=1)  # rotate per append
    wal.recover()
    for i in range(4):
        wal.append("info", ("rec", i))
    wal.close()
    segs = sorted(p for p in os.listdir(tmp_path) if p.endswith(".seg"))
    assert len(segs) > 2
    first_nonempty = next(
        p for p in segs if os.path.getsize(os.path.join(tmp_path, p))
    )
    with open(os.path.join(tmp_path, first_nonempty), "r+b") as fh:
        fh.seek(2)
        fh.write(b"\xff")
    wal2 = Wal(str(tmp_path), sync="always")
    state = wal2.recover()
    assert state.tail == []  # replay stopped at the torn first segment
    survivors = sorted(p for p in os.listdir(tmp_path) if p.endswith(".seg"))
    assert survivors == [first_nonempty] or survivors == segs[:1] + [first_nonempty]
    wal2.append("info", ("rec", "new"))
    wal2.close()
    state = Wal(str(tmp_path)).recover()
    assert [obj for _k, obj in state.tail] == [("rec", "new")]


def test_snapshot_rotation_prunes_and_replays_tail_only(tmp_path):
    wal = Wal(str(tmp_path), sync="always", segment_bytes=256)
    wal.recover()
    for i in range(30):
        wal.append("info", ("pre", i))
    wal.save_snapshot({"state": "S", "dot_lease": 7})
    for i in range(5):
        wal.append("info", ("post", i))
    wal.close()
    # rotation pruned everything the snapshot covers: the log is bounded
    # by the snapshot cadence, not the run length
    segs = [p for p in os.listdir(tmp_path) if p.endswith(".seg")]
    snap_tag = max(
        int(p[len("snapshot-"):-len(".bin")])
        for p in os.listdir(tmp_path)
        if p.startswith("snapshot-")
    )
    assert all(int(p[len("wal-"):-len(".seg")]) >= snap_tag for p in segs)
    state = Wal(str(tmp_path)).recover()
    assert state.snapshot == {"state": "S", "dot_lease": 7}
    assert [obj for _k, obj in state.tail] == [("post", i) for i in range(5)]
    assert state.dot_lease == 7


def test_second_snapshot_obsoletes_first(tmp_path):
    wal = Wal(str(tmp_path), sync="always")
    wal.recover()
    wal.append("info", ("a", 1))
    wal.save_snapshot({"v": 1})
    wal.append("info", ("b", 2))
    wal.save_snapshot({"v": 2})
    wal.append("info", ("c", 3))
    wal.close()
    snaps = [p for p in os.listdir(tmp_path) if p.startswith("snapshot-")]
    assert len(snaps) == 1
    state = Wal(str(tmp_path)).recover()
    assert state.snapshot == {"v": 2}
    assert [obj for _k, obj in state.tail] == [("c", 3)]


def test_dot_lease_resumes_above_issued(tmp_path):
    wal = Wal(str(tmp_path), sync="interval")
    wal.recover()
    wal.append_lease(DOT_LEASE_BATCH)
    wal.append_lease(3 * DOT_LEASE_BATCH)
    # crash WITHOUT close: leases are fsync'd regardless of policy
    state = Wal(str(tmp_path)).recover()
    assert state.dot_lease == 3 * DOT_LEASE_BATCH
    from fantoch_tpu.core.ids import AtomicIdGen

    gen = AtomicIdGen(1)
    gen.resume_after(state.dot_lease)
    assert gen.next_id().sequence == 3 * DOT_LEASE_BATCH + 1


def test_incarnation_bumps_per_recovery(tmp_path):
    incs = [Wal(str(tmp_path)).recover().incarnation for _ in range(3)]
    assert incs == [1, 2, 3]


def test_resolve_wal_sync_precedence(monkeypatch):
    monkeypatch.delenv("FANTOCH_WAL_SYNC", raising=False)
    assert resolve_wal_sync(None) == "interval"
    monkeypatch.setenv("FANTOCH_WAL_SYNC", "never")
    assert resolve_wal_sync(None) == "never"
    assert resolve_wal_sync("always") == "always"  # config beats env
    with pytest.raises(ValueError):
        resolve_wal_sync("sometimes")
    with pytest.raises(ValueError):
        Config(3, 1, wal_sync="sometimes")


def test_duplicate_redelivery_after_replay_is_exactly_once():
    """Crash between append and ack: the WAL replayed the commit, then a
    peer's reconnect resends the same MCommit.  The restored protocol's
    per-dot status dedup must swallow it — no second executor info, so
    nothing re-executes through the rifl/KVStore seam."""
    from fantoch_tpu.protocol.graph_protocol import EPaxos, MCollect, MCommit

    time = SimTime()
    config = Config(3, 1, gc_interval_ms=100)
    procs = {}
    for pid in (1, 2, 3):
        p, _events = EPaxos.new(pid, 0, config)
        ok, _ = p.discover([(1, 0), (2, 0), (3, 0)])
        assert ok
        procs[pid] = p

    cmd = Command.from_single(Rifl(100, 1), 0, "k", KVOp.put("v"))
    procs[1].submit(None, cmd, time)
    # drive the full commit at p1 synchronously
    import copy as _copy

    from fantoch_tpu.protocol.base import ToForward

    msgs = [(1, a) for a in procs[1].to_processes_iter()]
    commit_msg = None
    while msgs:
        from_, action = msgs.pop(0)
        targets = [from_] if isinstance(action, ToForward) else sorted(action.target)
        for to in targets:
            msg = _copy.deepcopy(action.msg)
            if isinstance(msg, MCommit):
                commit_msg = msg
            procs[to].handle(from_, 0, msg, time)
            msgs.extend((to, a) for a in procs[to].to_processes_iter())
    assert commit_msg is not None
    infos_first = list(procs[2].to_executors_iter())
    assert infos_first, "the commit must have produced execution info"

    # crash + restore p2 from its snapshot (state includes the commit)...
    restored = EPaxos.restore(procs[2].snapshot())
    # ...then the duplicate arrives from the resend window
    restored.handle(1, 0, commit_msg, time)
    assert list(restored.to_executors_iter()) == []
    assert list(restored.to_processes_iter()) == []
