"""Whole-system simulator tests for EPaxos and Atlas under message
reordering (reference: fantoch_ps/src/protocol/mod.rs:421-520).

Slow-path expectations: with f=1 (and 50% conflicts), both protocols must
commit everything on the fast path; with f=2 on n=5, slow paths must occur.
"""


from fantoch_tpu.core import Config
from fantoch_tpu.protocol.graph_protocol import Atlas, EPaxos
from harness import sim_test


def test_sim_epaxos_3_1():
    slow_paths = sim_test(EPaxos, Config(3, 1))
    assert slow_paths == 0


def test_sim_epaxos_5_2():
    # EPaxos always tolerates a minority: with n=5 its fast quorum is 3 and
    # conflicts among quorums cause slow paths
    slow_paths = sim_test(EPaxos, Config(5, 2))
    assert slow_paths > 0


def test_sim_epaxos_3_1_batched_executor():
    """Full sim with the batched device resolver ordering the graph
    executor (Config.batched_graph_executor) — same agreement and
    accounting checks as the host-Tarjan run."""
    slow_paths = sim_test(EPaxos, Config(3, 1, batched_graph_executor=True))
    assert slow_paths == 0


def test_sim_epaxos_5_2_batched_executor():
    slow_paths = sim_test(EPaxos, Config(5, 2, batched_graph_executor=True))
    assert slow_paths > 0


def test_sim_atlas_3_1():
    slow_paths = sim_test(Atlas, Config(3, 1))
    assert slow_paths == 0


def test_sim_atlas_5_2_batched_executor():
    slow_paths = sim_test(Atlas, Config(5, 2, batched_graph_executor=True))
    assert slow_paths > 0


def test_sim_atlas_5_1():
    slow_paths = sim_test(Atlas, Config(5, 1))
    assert slow_paths == 0


def test_sim_atlas_5_2():
    slow_paths = sim_test(Atlas, Config(5, 2))
    assert slow_paths > 0
