"""MultiSynod agents, SlotExecutor ordering, and FPaxos whole-system sim
tests (reference: fantoch_ps/src/protocol/mod.rs fpaxos rows + the slot
executor permutation test, fantoch_ps/src/executor/slot.rs:184-212)."""

import itertools
import random

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Rifl
from fantoch_tpu.core.kvs import KVOp
from fantoch_tpu.executor.slot import SlotExecutionInfo, SlotExecutor
from fantoch_tpu.protocol import FPaxos
from fantoch_tpu.protocol.common.multi_synod import (
    MAccept,
    MAccepted,
    MChosen,
    MForwardSubmit,
    MSpawnCommander,
    MultiSynod,
    SlotGCTrack,
)

from harness import sim_test

SHARD = 0


def cmd(seq: int) -> Command:
    return Command.from_single(Rifl(9, seq), SHARD, f"K{seq}", KVOp.put(str(seq)))


def test_multi_synod_happy_path():
    # n=3, f=1, leader=1
    synods = {pid: MultiSynod(pid, 1, 3, 1) for pid in (1, 2, 3)}
    out = synods[1].submit(cmd(1))
    assert isinstance(out, MSpawnCommander) and out.slot == 1 and out.ballot == 1
    maccept = synods[1].handle(1, out)
    assert isinstance(maccept, MAccept)
    # acceptors 1 and 2 (write quorum f+1=2) accept
    chosen = None
    for pid in (1, 2):
        maccepted = synods[pid].handle(1, maccept)
        assert isinstance(maccepted, MAccepted)
        result = synods[1].handle(pid, maccepted)
        if result is not None:
            chosen = result
    assert isinstance(chosen, MChosen) and chosen.slot == 1
    assert chosen.value == cmd(1)


def test_multi_synod_non_leader_forwards():
    synod = MultiSynod(2, 1, 3, 1)
    out = synod.submit(cmd(1))
    assert isinstance(out, MForwardSubmit)


def test_multi_synod_stale_ballot_rejected():
    synod = MultiSynod(2, 1, 3, 1)
    # acceptor joined ballot 1 at bootstrap; ballot 0 must be rejected
    assert synod.handle(9, MAccept(0, 1, cmd(1))) is None
    assert synod.handle(1, MAccept(1, 1, cmd(1))) is not None


def test_slot_gc_track():
    track = SlotGCTrack(1, 3)
    track.commit(1)
    track.commit(2)
    assert track.committed() == 2
    # no info from others yet: nothing stable
    assert track.stable() == (1, 0)
    track.committed_by(2, 1)
    track.committed_by(3, 5)
    assert track.stable() == (1, 1)  # min(2, 1, 5) = 1
    track.committed_by(2, 2)
    assert track.stable() == (2, 2)


def test_slot_executor_all_permutations():
    cmds = [cmd(seq) for seq in range(1, 5)]
    expected = None
    for perm in itertools.permutations(range(4)):
        ex = SlotExecutor(1, SHARD, Config(n=3, f=1))
        executed = []
        for i in perm:
            ex.handle(SlotExecutionInfo(i + 1, cmds[i]), None)
            executed.extend(r.rifl for r in ex.to_clients_iter())
        assert executed == [c.rifl for c in cmds], f"slot order broken for {perm}"


def test_fpaxos_3_1():
    sim_test(FPaxos, Config(n=3, f=1, leader=1))


def test_fpaxos_5_2():
    sim_test(FPaxos, Config(n=5, f=2, leader=1), seed=1)
