"""Consistency-audit plane: auditor verdicts on hand-built histories,
execution-digest chains, device-plane digest parity, and run-layer
divergence detection over TCP (a deliberately forked replica surfaces a
typed DivergenceError naming the first diverging key + command).
"""

import asyncio

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config
from fantoch_tpu.core.audit import (
    COMMIT_DIVERGENCE,
    COMMITTED_LOST,
    DUPLICATE_EXECUTION,
    KEYSET_DIVERGENCE,
    MULTISET_DIVERGENCE,
    ORDER_DIVERGENCE,
    ConsistencyAuditor,
    DigestEntry,
    ExecutionDigest,
)
from fantoch_tpu.core.ids import Dot, Rifl
from fantoch_tpu.core.kvs import KVOp, KVStore
from fantoch_tpu.errors import DivergenceError
from fantoch_tpu.executor.monitor import ExecutionOrderMonitor

pytestmark = pytest.mark.fuzz


def _monitor(orders, reads=()):
    """Build an ExecutionOrderMonitor from {key: [rifl, ...]}."""
    monitor = ExecutionOrderMonitor()
    for key, rifls in orders.items():
        for rifl in rifls:
            monitor.add(key, rifl, read=(key, rifl) in reads)
    return monitor


R = [Rifl(1, i) for i in range(10)]


# --- auditor verdicts on hand-built histories ---


def test_audit_clean():
    monitors = {
        1: _monitor({"k": [R[1], R[2], R[3]]}),
        2: _monitor({"k": [R[1], R[2], R[3]]}),
    }
    verdict = ConsistencyAuditor().audit(monitors)
    assert verdict.ok
    assert verdict.counterexample() is None


def test_audit_order_divergence_names_first_position():
    monitors = {
        1: _monitor({"k": [R[1], R[2], R[3]]}),
        2: _monitor({"k": [R[1], R[3], R[2]]}),
    }
    verdict = ConsistencyAuditor().audit(monitors)
    assert not verdict.ok
    violation = next(
        v for v in verdict.violations if v.kind == ORDER_DIVERGENCE
    )
    # minimal counterexample: first diverging position + the two rifls
    assert violation.key == "k"
    assert violation.entries == (1, R[2], R[3])
    assert violation.pids == (1, 2)


def test_audit_reads_commute():
    """Read-order differences are NOT violations (reads commute)."""
    monitors = {
        1: _monitor({"k": [R[1], R[2], R[3]]}, reads={("k", R[2]), ("k", R[3])}),
        2: _monitor({"k": [R[1], R[3], R[2]]}, reads={("k", R[2]), ("k", R[3])}),
    }
    assert ConsistencyAuditor().audit(monitors).ok


def test_audit_duplicate_execution():
    monitors = {
        1: _monitor({"k": [R[1], R[2], R[2]]}),
        2: _monitor({"k": [R[1], R[2], R[2]]}),
    }
    verdict = ConsistencyAuditor().audit(monitors)
    kinds = {v.kind for v in verdict.violations}
    assert DUPLICATE_EXECUTION in kinds
    # disabling the multiplicity assumption drops the absolute check
    verdict = ConsistencyAuditor(expected_ops_per_key=None).audit(monitors)
    assert verdict.ok


def test_audit_multiset_vs_committed_then_lost():
    """A rifl executed at one replica but missing at another is plain
    multiset divergence — unless the missing replica's own commit log
    proves it committed the command, which upgrades it to
    committed-then-lost."""
    monitors = {
        1: _monitor({"k": [R[1], R[2]]}),
        2: _monitor({"k": [R[1]]}),
    }
    verdict = ConsistencyAuditor().audit(monitors)
    kinds = {v.kind for v in verdict.violations}
    assert MULTISET_DIVERGENCE in kinds and COMMITTED_LOST not in kinds

    logs = {
        1: {Dot(1, 1): (R[1], 5), Dot(1, 2): (R[2], 7)},
        2: {Dot(1, 1): (R[1], 5), Dot(1, 2): (R[2], 7)},  # p2 committed R2!
    }
    verdict = ConsistencyAuditor().audit(monitors, logs)
    lost = [v for v in verdict.violations if v.kind == COMMITTED_LOST]
    assert lost and lost[0].entries == (R[2],)


def test_audit_keyset_divergence():
    monitors = {
        1: _monitor({"k": [R[1]], "extra": [R[2]]}),
        2: _monitor({"k": [R[1]]}),
    }
    verdict = ConsistencyAuditor().audit(monitors)
    assert any(
        v.kind == KEYSET_DIVERGENCE and v.key == "extra"
        for v in verdict.violations
    )


def test_audit_commit_value_divergence():
    """Same ident (dot / slot), different agreed value — Newt timestamp,
    graph deps, and FPaxos slot->command agreement as one check."""
    monitors = {1: _monitor({"k": [R[1]]}), 2: _monitor({"k": [R[1]]})}
    logs = {
        1: {Dot(1, 1): (R[1], 5)},
        2: {Dot(1, 1): (R[1], 9)},  # same dot, different clock
    }
    verdict = ConsistencyAuditor().audit(monitors, logs)
    diverged = [v for v in verdict.violations if v.kind == COMMIT_DIVERGENCE]
    assert diverged and diverged[0].entries[0] == Dot(1, 1)
    # noop records (rifl None) participate in agreement too
    logs = {1: {Dot(1, 1): (None, 0)}, 2: {Dot(1, 1): (None, 0)}}
    assert ConsistencyAuditor().audit(monitors, logs).ok


# --- execution digests ---


def test_digest_chains_writes_only_and_deterministically():
    a, b = KVStore(execution_digests=True), KVStore(execution_digests=True)
    for store in (a, b):
        store.execute("k", KVOp.put("x"), R[1])
        store.execute("k", KVOp.get(), R[2])  # read: not chained
        store.execute("k", KVOp.put("y"), R[3])
    assert a.digest.summary() == b.digest.summary()
    entries = a.digest.entries("k")
    assert [(e.src, e.seq) for e in entries] == [(1, 1), (1, 3)]
    assert a.digest.summary()["k"][0] == 2


def test_digest_prefix_verification_and_first_divergence():
    ahead, behind, forked = (ExecutionDigest() for _ in range(3))
    for digest, values in (
        (ahead, ["a", "b", "c"]),
        (behind, ["a", "b"]),
        (forked, ["a", "X", "c"]),
    ):
        for index, value in enumerate(values):
            digest.record("k", Rifl(1, index + 1), "Put", value)
    # the replica that is at least as far along verifies the whole prefix
    assert ahead.mismatched_keys(behind.summary()) == []
    # a behind replica cannot check an ahead summary (skip, not report)
    assert behind.mismatched_keys(ahead.summary()) == []
    # a fork is visible to anyone who reaches its count
    assert ahead.mismatched_keys(forked.summary()) == ["k"]
    position, mine, theirs = ExecutionDigest.first_divergence(
        ahead.entries("k"), forked.entries("k")
    )
    assert position == 1
    assert (mine.src, mine.seq) == (1, 2) and (theirs.src, theirs.seq) == (1, 2)
    # identical chains (or a clean prefix) have no divergence
    assert ExecutionDigest.first_divergence(
        ahead.entries("k"), behind.entries("k")
    ) is None


def test_digest_summary_merge_disjoint_executors():
    a, b = ExecutionDigest(), ExecutionDigest()
    a.record("k1", R[1], "Put", "x")
    b.record("k2", R[2], "Put", "y")
    merged = {}
    a.merge_summary_into(merged)
    b.merge_summary_into(merged)
    assert set(merged) == {"k1", "k2"}


# --- device-table-plane digest parity ---


def test_device_plane_digest_parity():
    """The device table plane executes stable rows through the same
    KVStore seam, so its per-key digest chains are bit-for-bit the host
    path's — the guard that a device-resident executor can still be
    cross-audited (the run layer exchanges these digests over TCP).
    Runs on every jax pin (the plane itself is pin-safe; only the
    drivers' scan tracing is guarded, see make test-device-stripped)."""
    import random

    from fantoch_tpu.core.timing import RunTime
    from fantoch_tpu.executor.table import TableExecutor, TableVotes
    from fantoch_tpu.protocol.common.table_clocks import VoteRange

    def build(plane):
        return TableExecutor(
            1, 0,
            Config(
                3, 1,
                batched_table_executor=plane,
                device_table_plane=plane,
                execution_digests=True,
            ),
        )

    rng = random.Random(7)
    time = RunTime()
    host, device = build(False), build(True)
    clock = 0
    infos = []
    for index in range(40):
        clock += rng.randrange(1, 3)
        key = rng.choice(("a", "b"))
        key_votes = [
            VoteRange(by, 1 if index == 0 else clock - 1, clock)
            for by in (1, 2, 3)
        ]
        infos.append(
            TableVotes(
                Dot(1, index + 1), clock, Rifl(9, index + 1), key,
                (KVOp.put(f"v{index}"),), key_votes,
            )
        )
    for executor in (host, device):
        executor.handle_batch(list(infos), time)
        list(executor.to_clients_iter())
    assert host.digest() is not None and device.digest() is not None
    assert host.digest().summary() == device.digest().summary()
    for key in ("a", "b"):
        assert host.digest().entries(key) == device.digest().entries(key)


# --- run-layer divergence detection over TCP ---


def test_tcp_forked_replica_raises_divergence_error():
    """A replica that executes a write nobody agreed on (the fork) is
    detected by the digest exchange on the heartbeat path: a typed
    DivergenceError naming the key and the first diverging command
    surfaces through the runtime failure seam, and the digest gauges
    show the mismatch."""
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.run.harness import run_localhost_cluster

    config = Config(
        n=3, f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        execution_digests=True,
        audit_log_commits=True,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(100),
        keys_per_command=1,
        commands_per_client=200,
        payload_size=1,
    )
    captured = {}

    async def fork_one_replica(runtimes):
        captured.update(runtimes)
        # wait for real executions, then fork p2: execute a rogue write
        # the mesh never agreed on.  Peers catch up past the fork point
        # on the hot key and the next heartbeat summary mismatches.
        target = runtimes[2]
        for _ in range(200):
            summary = target._digest_summary()
            if summary and summary.get("CONFLICT", (0, ""))[0] >= 3:
                break
            await asyncio.sleep(0.02)
        else:
            raise AssertionError("no executions to fork")
        target.executors[0]._store.execute(
            "CONFLICT", KVOp.put("forked"), Rifl(999, 1)
        )

    async def drive():
        await run_localhost_cluster(
            EPaxos,
            config,
            workload,
            clients_per_process=2,
            open_loop_interval_ms=10,
            runtime_kwargs=dict(
                heartbeat_interval_s=0.05, heartbeat_misses=200
            ),
            chaos=fork_one_replica,
        )

    with pytest.raises(AssertionError, match="failed mid-run"):
        asyncio.run(drive())

    failures = [
        runtime.failure
        for runtime in captured.values()
        if runtime.failure is not None
    ]
    diverged = [f for f in failures if isinstance(f, DivergenceError)]
    assert diverged, f"expected a DivergenceError, got {failures!r}"
    error = diverged[0]
    assert error.key == "CONFLICT"
    assert error.position >= 3
    assert error.mine is not None and error.theirs is not None
    assert Rifl(999, 1) in (error.mine, error.theirs)
    assert "divergence" in str(error)
    # the gauges surface the mismatch (metrics snapshots + obs summarize)
    detector = next(
        runtime
        for runtime in captured.values()
        if isinstance(runtime.failure, DivergenceError)
    )
    counters = detector.overload_counters()
    assert counters["digest_mismatches"] >= 1
    assert counters["digest_checks"] >= 1
    assert counters["digest_keys"] >= 1


def test_tcp_healthy_cluster_digests_stay_clean():
    """Digest exchange on a healthy cluster: checks happen, zero
    mismatches, workload completes."""
    from fantoch_tpu.protocol import Newt
    from fantoch_tpu.run.harness import run_localhost_cluster

    config = Config(
        n=3, f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        newt_detached_send_interval_ms=50,
        execution_digests=True,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=10,
        payload_size=1,
    )
    captured = {}

    async def capture(runtimes):
        captured.update(runtimes)

    async def drive():
        return await run_localhost_cluster(
            Newt,
            config,
            workload,
            clients_per_process=2,
            extra_run_time_ms=400,
            runtime_kwargs=dict(
                heartbeat_interval_s=0.05, heartbeat_misses=200
            ),
            chaos=capture,
        )

    runtimes, clients = asyncio.run(drive())
    for client in clients.values():
        assert client.issued_commands == 10
    checks = sum(r.digest_checks for r in runtimes.values())
    mismatches = sum(r.digest_mismatches for r in runtimes.values())
    assert checks > 0, "heartbeats should have cross-audited digests"
    assert mismatches == 0
