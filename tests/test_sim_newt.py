"""Whole-system simulator tests for Newt, mirroring the reference matrix
(fantoch_ps/src/protocol/mod.rs:62-166): `newt_config!` always sets
``newt_detached_send_interval`` (without it, detached votes accumulate
locally and timestamp stability stalls on any clock divergence); the
real-time variants add tiny quorums + a clock-bump interval.  f=1 must
commit everything on the fast path, f=2 must hit slow paths under
conflicts."""

import pytest

from fantoch_tpu.core.config import Config
from fantoch_tpu.protocol import Newt

from harness import sim_test


def newt_config(n: int, f: int, clock_bump_interval_ms=None, **kwargs) -> Config:
    """The reference's newt_config! macro (mod.rs:62-75)."""
    config = Config(n=n, f=f, newt_detached_send_interval_ms=100, **kwargs)
    if clock_bump_interval_ms is not None:
        config = config.with_(
            newt_tiny_quorums=True,
            newt_clock_bump_interval_ms=clock_bump_interval_ms,
        )
    return config


def test_newt_3_1():
    slow = sim_test(Newt, newt_config(3, 1))
    assert slow == 0, "with f=1 the max clock is always reported >= 1 time"


def test_newt_5_1():
    slow = sim_test(Newt, newt_config(5, 1))
    assert slow == 0


def test_newt_5_2():
    slow = sim_test(Newt, newt_config(5, 2), seed=1)
    assert slow > 0, "f=2 with 50% conflicts must take slow paths"


def test_newt_3_1_skip_fast_ack():
    slow = sim_test(Newt, newt_config(3, 1, newt_tiny_quorums=True, skip_fast_ack=True))
    assert slow == 0


def test_real_time_newt_3_1():
    slow = sim_test(Newt, newt_config(3, 1, clock_bump_interval_ms=50))
    assert slow == 0


def test_real_time_newt_5_1():
    slow = sim_test(Newt, newt_config(5, 1, clock_bump_interval_ms=50))
    assert slow == 0
