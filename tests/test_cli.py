"""CLI binaries: a 3-process localhost cluster driven purely from the
shell completes a workload (VERDICT r2 item 6 done-criterion), plus the
aux tools (simulation sweep, shard distribution, replay).

Reference: fantoch_ps/src/bin/{common/protocol.rs,client.rs,simulation.rs,
shard_distribution.rs,graph_executor_replay.rs} and the reference's own
3-process localhost smoke scripts (bin/{proc,client,bench})."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from fantoch_tpu.run.harness import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cli_env():
    env = dict(os.environ)
    env["FANTOCH_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO
    env.pop("JAX_PLATFORMS", None)
    return env


def run_tool(module, args, timeout=120):
    out = subprocess.run(
        [sys.executable, "-m", module, *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=cli_env(),
        cwd=REPO,
    )
    assert out.returncode == 0, f"{module} failed:\n{out.stdout}\n{out.stderr}"
    return out.stdout


@pytest.mark.slow
def test_cli_cluster_end_to_end(tmp_path):
    n = 3
    peer_ports = {pid: free_port() for pid in (1, 2, 3)}
    client_ports = {pid: free_port() for pid in (1, 2, 3)}
    sorted_flag = "1:0,2:0,3:0"
    servers = []
    try:
        for pid in (1, 2, 3):
            addresses = ",".join(
                f"{peer}=127.0.0.1:{peer_ports[peer]}" for peer in (1, 2, 3) if peer != pid
            )
            own_sorted = ",".join(
                [f"{pid}:0"] + [f"{p}:0" for p in (1, 2, 3) if p != pid]
            )
            servers.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "fantoch_tpu.bin.server",
                        "--protocol", "epaxos",
                        "--id", str(pid),
                        "--port", str(peer_ports[pid]),
                        "--client-port", str(client_ports[pid]),
                        "--addresses", addresses,
                        "--sorted", own_sorted,
                        "-n", str(n), "-f", "1",
                        "--execution-log", str(tmp_path / f"exec_p{pid}.log"),
                        "--metrics-file", str(tmp_path / f"metrics_p{pid}.gz"),
                        "--metrics-interval", "300",
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=cli_env(),
                    cwd=REPO,
                )
            )

        out = run_tool(
            "fantoch_tpu.bin.client",
            [
                "--ids", "1-2",
                "--addresses", f"0=127.0.0.1:{client_ports[1]}",
                "--commands-per-client", "10",
                "--conflict-rate", "50",
                "--payload-size", "8",
                "--metrics-file", str(tmp_path / "client_data.pkl"),
            ],
            timeout=180,
        )
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["clients"] == 2
        assert summary["commands"] == 20
        assert summary["latency_ms"]["p50"] is not None
        assert (tmp_path / "client_data.pkl").exists()

        # give the metrics logger an interval, then check a snapshot exists
        time.sleep(0.5)
        assert any(tmp_path.glob("metrics_p*.gz"))
    finally:
        for proc in servers:
            proc.send_signal(signal.SIGINT)
        for proc in servers:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    # offline replay of a server's execution log through the CLI
    log = tmp_path / "exec_p1.log"
    assert log.exists() and log.stat().st_size > 0
    out = run_tool(
        "fantoch_tpu.bin.replay",
        ["--log", str(log), "--protocol", "epaxos", "--id", "1", "-n", "3", "-f", "1"],
    )
    replayed = json.loads(out.strip().splitlines()[-1])
    assert replayed["results"] == 20  # 20 commands x 1 key


@pytest.mark.slow
def test_cli_device_step_sharded(tmp_path):
    """Partial replication from the shell: one --device-step
    --shard-count 2 server, the stock client with both shards pointed at
    it and two-key (frequently cross-shard) commands."""
    port = free_port()
    server = subprocess.Popen(
        [
            sys.executable, "-m", "fantoch_tpu.bin.server",
            "--protocol", "epaxos",
            "--device-step",
            "--client-port", str(port),
            "--device-batch", "32",
            "--device-key-width", "2",
            "--device-key-buckets", "64",
            "-n", "3", "-f", "1",
            "--shard-count", "2",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=cli_env(),
        cwd=REPO,
    )
    try:
        out = run_tool(
            "fantoch_tpu.bin.client",
            [
                "--ids", "1-2",
                "--addresses", f"0=127.0.0.1:{port},1=127.0.0.1:{port}",
                "--commands-per-client", "10",
                "--keys-per-command", "2",
                "--conflict-rate", "50",
            ],
            timeout=180,
        )
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["clients"] == 2
        assert summary["commands"] == 20
    finally:
        server.send_signal(signal.SIGINT)
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()


def test_cli_device_step_server(tmp_path):
    """The TPU serving path from the shell: one --device-step server, the
    stock client binary against it (same wire protocol).  --multihost
    exercises the topology-aware mesh builder's CLI wiring; on this
    single-process backend it degrades to the stock mesh by contract
    (tests/test_multihost.py pins both layouts)."""
    port = free_port()
    server = subprocess.Popen(
        [
            sys.executable, "-m", "fantoch_tpu.bin.server",
            "--protocol", "epaxos",
            "--device-step",
            "--multihost",
            "--client-port", str(port),
            "--device-batch", "32",
            "-n", "3", "-f", "1",
            "--metrics-file", str(tmp_path / "device_metrics.json"),
            "--metrics-interval", "300",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=cli_env(),
        cwd=REPO,
    )
    try:
        out = run_tool(
            "fantoch_tpu.bin.client",
            [
                "--ids", "1-2",
                "--addresses", f"0=127.0.0.1:{port}",
                "--commands-per-client", "10",
                "--conflict-rate", "50",
                "--payload-size", "8",
            ],
            timeout=180,
        )
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["clients"] == 2
        assert summary["commands"] == 20
        assert summary["latency_ms"]["p50"] is not None
        time.sleep(0.5)
        snap = json.loads((tmp_path / "device_metrics.json").read_text())
        assert snap["executed"] >= 1 and snap["rounds"] >= 1
    finally:
        server.send_signal(signal.SIGINT)
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()


def test_cli_simulation_sweep():
    out = run_tool(
        "fantoch_tpu.bin.simulation",
        [
            "--protocol", "epaxos", "-n", "3", "-f", "1",
            "--clients", "1,2", "--commands-per-client", "5",
        ],
        timeout=240,
    )
    lines = [json.loads(line) for line in out.strip().splitlines() if line.startswith("{")]
    assert len(lines) == 2
    for line in lines:
        assert line["protocol"] == "epaxos"
        assert len(line["latency"]) == 3
        for stats in line["latency"].values():
            assert stats["mean_ms"] >= 0


@pytest.mark.slow
def test_cli_exp_driver(tmp_path):
    """The experiment-harness CLI (fantoch_exp bin/main analog): a
    2-point client sweep through real localhost clusters, one manifest
    line per point.  (ResultsDB indexing of sweep output is covered by
    test_run_sweep_throughput_latency_curve.)"""
    out = run_tool(
        "fantoch_tpu.bin.exp",
        [
            "--protocol", "epaxos", "-n", "3", "-f", "1",
            "--clients-sweep", "1,2", "--commands-per-client", "4",
            "--output-dir", str(tmp_path / "exp"),
        ],
        timeout=420,
    )
    lines = [json.loads(l) for l in out.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 2
    assert lines[0]["outcome"]["commands"] == 3 * 4
    assert lines[1]["outcome"]["commands"] == 3 * 2 * 4


def test_cli_sequencer_bench():
    """The key-clock sequencer microbenchmark CLI (sequencer_bench.rs
    analog): both the host and device implementations report commands/s."""
    out = run_tool(
        "fantoch_tpu.bin.sequencer_bench",
        ["--keys", "16", "--batch", "2000", "--iters", "1"],
        timeout=240,
    )
    line = json.loads(out.strip().splitlines()[-1])
    assert line["device_cmds_per_s"] > 0 and line["host_cmds_per_s"] > 0
    assert line["keys"] == 16 and line["batch"] == 2000


def test_cli_ordering_pool():
    """The multi-process ordering pool CLI (the pool.rs scaling probe):
    reports aggregate commands/s and the host's core count."""
    out = run_tool(
        "fantoch_tpu.bin.ordering_pool",
        ["--commands", "5000", "--workers", "2"],
        timeout=240,
    )
    line = json.loads(out.strip().splitlines()[-1])
    assert line["commands"] == 5000 and line["workers"] == 2
    assert line["cmds_per_s"] > 0 and line["cpus"] >= 1


def test_cli_simulation_leader_based():
    """Regression: the sim CLI must serve the leader-based protocol too
    (it crashed without a leader in the Config; the reference's sim
    configs always set leader = 1 for fpaxos)."""
    out = run_tool(
        "fantoch_tpu.bin.simulation",
        [
            "--protocol", "fpaxos", "-n", "3", "-f", "1",
            "--clients", "1", "--commands-per-client", "5",
        ],
        timeout=240,
    )
    (line,) = [json.loads(l) for l in out.strip().splitlines() if l.startswith("{")]
    assert line["protocol"] == "fpaxos"
    assert all(s["issued"] == 5 for s in line["latency"].values())


@pytest.mark.slow
def test_cli_simulation_sweep_parallel_matches_sequential():
    # --parallel fans points over spawn workers (the rayon analog);
    # deterministic sims must yield identical output either way
    args = [
        "--protocol", "epaxos", "-n", "3", "-f", "1",
        "--clients", "1,2", "--commands-per-client", "5", "--seed", "3",
    ]
    seq = run_tool("fantoch_tpu.bin.simulation", args, timeout=240)
    par = run_tool(
        "fantoch_tpu.bin.simulation", args + ["--parallel", "2"], timeout=240
    )
    keep = lambda s: [l for l in s.strip().splitlines() if l.startswith("{")]
    assert keep(seq) == keep(par)


def test_cli_shard_distribution():
    out = run_tool(
        "fantoch_tpu.bin.shard_distribution",
        ["--shard-count", "4", "--keys-per-command", "2", "--commands", "2000"],
    )
    stats = json.loads(out.strip().splitlines()[-1])
    assert stats["shard_count"] == 4
    assert 0 < stats["multi_shard_pct"] <= 100
    assert stats["multi_key_pct"] >= stats["multi_shard_pct"]
