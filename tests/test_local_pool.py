"""Multi-process ordering pool (run/local_pool.py): key-sharded worker
processes produce exactly the per-key orders of one graph, and the
sharder keeps every dependency local to its worker."""

import numpy as np
import pytest

from fantoch_tpu.run.local_pool import OrderingPool

pytestmark = pytest.mark.slow  # spawns interpreters: seconds per worker


def _workload(batch=2048, keys=64, seed=3):
    rng = np.random.default_rng(seed)
    key = rng.integers(0, keys, size=batch).astype(np.int32)
    dep = np.full(batch, -1, dtype=np.int64)
    last = {}
    for i, k in enumerate(key):
        prev = last.get(int(k))
        if prev is not None:
            dep[i] = prev
        last[int(k)] = i
    src = (1 + rng.integers(0, 5, size=batch)).astype(np.int64)
    seq = np.arange(1, batch + 1, dtype=np.int64)
    return key, dep, src, seq


def test_shard_columns_keeps_deps_local():
    key, dep, src, seq = _workload()
    shards = OrderingPool.shard_columns(key, src, seq, dep, 4)
    assert sum(len(s[0]) for s in shards) == len(key)
    for w, (k, s, q, d) in enumerate(shards):
        assert ((k % 4) == w).all()
        # every remapped dep points inside the shard and at the previous
        # row of the same key
        rows = np.flatnonzero(d >= 0)
        assert (d[rows] < np.arange(len(k))[rows]).all()
        assert (k[d[rows]] == k[rows]).all()


def test_pool_matches_per_key_arrival_order():
    """Across 2 worker processes, each key's execution order is its
    arrival (chain) order — the exact order one graph produces — and
    every command executes exactly once."""
    key, dep, src, seq = _workload(batch=1024)
    shards = OrderingPool.shard_columns(key, src, seq, dep, 2)
    with OrderingPool(2) as pool:
        pool.prepare(max(len(s[0]) for s in shards))
        orders = pool.run_shards(shards)

    key_of = {(int(s), int(q)): int(k) for s, q, k in zip(src, seq, key)}
    seen = set()
    for order_src, order_seq in orders:
        per_key = {}
        for s, q in zip(order_src.tolist(), order_seq.tolist()):
            assert (s, q) not in seen
            seen.add((s, q))
            per_key.setdefault(key_of[(s, q)], []).append((s, q))
        # per-key order == arrival order (the dep chain)
        for k, got in per_key.items():
            want = [
                (int(s), int(q))
                for s, q, kk in zip(src, seq, key)
                if int(kk) == k
            ]
            assert got == want
    assert len(seen) == len(key)


def test_pool_pipelined_matches_sequential():
    """run_shards_pipelined (depth-K in-flight workloads across the
    worker processes — the serving loop's overlap at the host pool seam)
    returns exactly the per-workload orders of sequential run_shards
    calls, in submission order."""
    workloads = []
    for seed in (3, 4, 5, 6):
        key, dep, src, seq = _workload(batch=512, seed=seed)
        workloads.append(OrderingPool.shard_columns(key, src, seq, dep, 2))
    rows = max(len(s[0]) for wl in workloads for s in wl)

    with OrderingPool(2) as pool:
        pool.prepare(rows)
        sequential = [pool.run_shards(wl) for wl in workloads]
    with OrderingPool(2) as pool:
        pool.prepare(rows)
        pipelined = pool.run_shards_pipelined(workloads, depth=2)

    assert len(pipelined) == len(sequential) == 4
    for seq_orders, pipe_orders in zip(sequential, pipelined):
        for (ss, sq), (ps, pq) in zip(seq_orders, pipe_orders):
            assert (ss == ps).all() and (sq == pq).all()


def test_pool_pipelined_feeder_failure_raises():
    """A workload the feeder cannot submit (wrong shard count) raises
    RuntimeError instead of hanging the drain loop on results that will
    never arrive — including when it follows a good workload."""
    key, dep, src, seq = _workload(batch=64)
    good = OrderingPool.shard_columns(key, src, seq, dep, 2)
    bad = good[:1]  # one shard for a 2-worker pool
    with OrderingPool(2) as pool:
        pool.prepare(64)
        with pytest.raises(RuntimeError, match="pool feeder failed"):
            pool.run_shards_pipelined([bad], depth=1)
        key2, dep2, src2, seq2 = _workload(batch=64, seed=9)
        good2 = OrderingPool.shard_columns(key2, src2, seq2 + 1000, dep2, 2)
        with pytest.raises(RuntimeError, match="pool feeder failed"):
            pool.run_shards_pipelined([good2, bad], depth=1)


def test_pool_pipelined_survives_pipe_buffer_sized_payloads():
    """Workloads whose pickled columns exceed the pipe's socket buffer
    (a few hundred KB) used to deadlock a naive submit-then-drain loop:
    the parent blocked sending workload k+1 into a full pipe while the
    worker blocked sending result k the other way.  The feeder-thread
    split must keep large payloads flowing."""
    workloads = []
    base = 0
    for seed in (7, 8):
        key, dep, src, seq = _workload(batch=120_000, keys=512, seed=seed)
        workloads.append(
            OrderingPool.shard_columns(key, src, seq + base, dep, 2)
        )
        base += 200_000  # disjoint dot ranges across workloads
    rows = max(len(s[0]) for wl in workloads for s in wl)
    with OrderingPool(2) as pool:
        pool.prepare(rows)
        results = pool.run_shards_pipelined(workloads, depth=1)
    assert len(results) == 2
    for wl, orders in zip(workloads, results):
        want = sum(len(s[0]) for s in wl)
        got = sum(len(src) for src, _ in orders)
        assert got == want
