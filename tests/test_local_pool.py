"""Multi-process ordering pool (run/local_pool.py): key-sharded worker
processes produce exactly the per-key orders of one graph, and the
sharder keeps every dependency local to its worker."""

import numpy as np
import pytest

from fantoch_tpu.run.local_pool import OrderingPool

pytestmark = pytest.mark.slow  # spawns interpreters: seconds per worker


def _workload(batch=2048, keys=64, seed=3):
    rng = np.random.default_rng(seed)
    key = rng.integers(0, keys, size=batch).astype(np.int32)
    dep = np.full(batch, -1, dtype=np.int64)
    last = {}
    for i, k in enumerate(key):
        prev = last.get(int(k))
        if prev is not None:
            dep[i] = prev
        last[int(k)] = i
    src = (1 + rng.integers(0, 5, size=batch)).astype(np.int64)
    seq = np.arange(1, batch + 1, dtype=np.int64)
    return key, dep, src, seq


def test_shard_columns_keeps_deps_local():
    key, dep, src, seq = _workload()
    shards = OrderingPool.shard_columns(key, src, seq, dep, 4)
    assert sum(len(s[0]) for s in shards) == len(key)
    for w, (k, s, q, d) in enumerate(shards):
        assert ((k % 4) == w).all()
        # every remapped dep points inside the shard and at the previous
        # row of the same key
        rows = np.flatnonzero(d >= 0)
        assert (d[rows] < np.arange(len(k))[rows]).all()
        assert (k[d[rows]] == k[rows]).all()


def test_pool_matches_per_key_arrival_order():
    """Across 2 worker processes, each key's execution order is its
    arrival (chain) order — the exact order one graph produces — and
    every command executes exactly once."""
    key, dep, src, seq = _workload(batch=1024)
    shards = OrderingPool.shard_columns(key, src, seq, dep, 2)
    with OrderingPool(2) as pool:
        pool.prepare(max(len(s[0]) for s in shards))
        orders = pool.run_shards(shards)

    key_of = {(int(s), int(q)): int(k) for s, q, k in zip(src, seq, key)}
    seen = set()
    for order_src, order_seq in orders:
        per_key = {}
        for s, q in zip(order_src.tolist(), order_seq.tolist()):
            assert (s, q) not in seen
            seen.add((s, q))
            per_key.setdefault(key_of[(s, q)], []).append((s, q))
        # per-key order == arrival order (the dep chain)
        for k, got in per_key.items():
            want = [
                (int(s), int(q))
                for s, q, kk in zip(src, seq, key)
                if int(kk) == k
            ]
            assert got == want
    assert len(seen) == len(key)
