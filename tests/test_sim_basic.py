"""Simulator golden tests for the Basic protocol, mirroring
fantoch/src/sim/runner.rs:726-866 (exact mean latencies per f) and
fantoch/src/sim/schedule.rs:63-120 (schedule flow).

These pin the same numbers as the reference: Basic on 3 GCP regions
(asia-east1, us-central1, us-west1) with clients in us-west1/us-west2 must
see mean latencies 0/24 (f=0), 34/58 (f=1), 118/142 (f=2) ms, and latency
must be invariant to client count (infinite-CPU simulator assumption).
"""

import os

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config, Planet, Region, SimTime
from fantoch_tpu.protocol import Basic, ProtocolMetricsKind
from fantoch_tpu.sim import Runner, Schedule

COMMANDS_PER_CLIENT = 100 if os.environ.get("CI") else 1000


def run_basic(f: int, clients_per_process: int):
    planet = Planet.new("gcp")
    config = Config(n=3, f=f, gc_interval_ms=100)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(100),
        keys_per_command=1,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=100,
    )
    process_regions = [Region("asia-east1"), Region("us-central1"), Region("us-west1")]
    client_regions = [Region("us-west1"), Region("us-west2")]
    runner = Runner(
        Basic, planet, config, workload, clients_per_process, process_regions, client_regions
    )
    metrics, _monitors, latencies = runner.run(extra_sim_time_ms=1000)

    west1_issued, west1 = latencies[Region("us-west1")]
    west2_issued, west2 = latencies[Region("us-west2")]
    expected = COMMANDS_PER_CLIENT * clients_per_process
    assert west1_issued == expected
    assert west2_issued == expected

    # all commands must be gc-ed everywhere (2 client regions)
    for process_metrics in metrics.values():
        stable = process_metrics.get_aggregated(ProtocolMetricsKind.STABLE)
        assert stable == expected * 2, "all commands should be stable"
    return west1, west2


def test_runner_single_client_per_process():
    # us-west1 client is colocated with a process: coordinator access is free;
    # us-west2's closest process is us-west1 at 12+12 ms round trip
    west1, west2 = run_basic(f=0, clients_per_process=1)
    assert west1.mean() == 0.0
    assert west2.mean() == 24.0

    west1, west2 = run_basic(f=1, clients_per_process=1)
    assert west1.mean() == 34.0
    assert west2.mean() == 58.0

    west1, west2 = run_basic(f=2, clients_per_process=1)
    assert west1.mean() == 118.0
    assert west2.mean() == 142.0


@pytest.mark.slow
def test_runner_multiple_clients_per_process():
    # the simulator assumes infinite CPU: latency must not depend on load
    one_w1, one_w2 = run_basic(f=1, clients_per_process=1)
    ten_w1, ten_w2 = run_basic(f=1, clients_per_process=10)
    assert one_w1.mean() == ten_w1.mean()
    assert one_w1.cov() == ten_w1.cov()
    assert one_w2.mean() == ten_w2.mean()
    assert one_w2.cov() == ten_w2.cov()


def test_schedule_flow():
    # mirrors fantoch/src/sim/schedule.rs:63-120
    time = SimTime()
    schedule = Schedule()
    assert schedule.next_action(time) is None

    schedule.schedule(time, 10, "a")
    assert schedule.next_action(time) == "a"
    assert time.millis() == 10
    assert schedule.next_action(time) is None

    schedule.schedule(time, 7, "b")
    schedule.schedule(time, 2, "c")
    assert schedule.next_action(time) == "c"
    assert time.millis() == 12

    schedule.schedule(time, 2, "d")
    schedule.schedule(time, 5, "e")
    assert schedule.next_action(time) == "d"
    assert time.millis() == 14

    nxt = schedule.next_action(time)
    assert nxt in ("b", "e")
    assert time.millis() == 17
    nxt = schedule.next_action(time)
    assert nxt in ("b", "e")
    assert time.millis() == 17
