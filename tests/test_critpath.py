"""Causal critical-path attribution + failure flight recorder.

The tentpole claims, pinned:

* every attribution vector telescopes EXACTLY to ``reply - submit``
  (the blame report explains the latency histogram, never approximates
  it), in both clock domains;
* cross-process edges stitch >= 99% of sampled spans (100% at rate
  1.0) — sim virtual time and run-layer wall time alike;
* a deliberately slowed peer (SlowProcess nemesis in the sim, a
  delayed link in the run layer) is named the dominant quorum-wait
  contributor, with the wait decomposed into network vs remote
  turnaround;
* wall-clock traces resolve per-peer offsets from heartbeat RTT
  brackets (run/links.ClockOffsetEstimator) and client offsets from
  the spans' own request/reply brackets;
* typed failures dump per-process flight-recorder black boxes that the
  SAME correlator stitches (sim stalls, run-layer fatal failures,
  SIGUSR1, fuzz repro artifacts).
"""

import asyncio
import dataclasses
import glob
import json
import os
import signal

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config
from fantoch_tpu.core.planet import Planet, Region
from fantoch_tpu.errors import StalledExecutionError
from fantoch_tpu.observability.critpath import (
    OffsetTable,
    attribute_span,
    critpath_report,
    dominant_quorum_peer,
    estimate_client_offsets,
    match_edges,
)
from fantoch_tpu.observability.recorder import (
    FlightRecorder,
    flight_events,
    read_flight,
)
from fantoch_tpu.observability.report import assemble_spans, diff_stages
from fantoch_tpu.observability.tracer import read_trace
from fantoch_tpu.protocol import EPaxos
from fantoch_tpu.run.links import ClockOffsetEstimator
from fantoch_tpu.sim import Runner
from fantoch_tpu.sim.faults import FaultPlan

COMMANDS_PER_CLIENT = 4 if os.environ.get("CI") else 5


# --- unit: offsets ---


def test_clock_offset_estimator_keeps_best_rtt():
    est = ClockOffsetEstimator()
    # peer clock runs 500us ahead: send 0, remote stamps 1500, recv 2000
    assert est.sample(2, 0, 1500, 2000) == (2000, 500)
    # a worse (higher-rtt) sample does not replace the estimate
    assert est.sample(2, 10_000, 25_000, 30_000) is None
    assert est.offset_us(2) == 500
    # a tighter bracket does
    assert est.sample(2, 100, 700, 1100) == (1000, 100)
    assert est.offset_us(2) == 100
    # degenerate bracket (clock stepped backwards) is rejected
    assert est.sample(3, 100, 50, 90) is None
    assert est.offset_us(3) is None


def test_offset_table_resolves_both_directions():
    events = [
        {"k": "hdr", "clock": "wall", "v": 1},
        # p1 measured p2's clock 500us ahead of its own
        {"k": "off", "pid": 1, "peer": 2, "off": 500, "rtt": 300, "t": 0},
        # a lower-rtt (better) re-estimate wins
        {"k": "off", "pid": 1, "peer": 2, "off": 480, "rtt": 100, "t": 5},
    ]
    table = OffsetTable(events, wall=True)
    # moving a p2 timestamp into p1's frame subtracts the offset
    assert table.shift(2, 1) == -480
    # the reverse direction falls back to the negated sample
    assert table.shift(1, 2) == 480
    assert table.shift(1, 1) == 0
    assert table.shift(3, 1) == 0  # unknown pair: no correction
    # virtual clock: no correction ever
    assert OffsetTable(events, wall=False).shift(2, 1) == 0


# --- unit: attribution on hand-built events ---


def _handbuilt_events():
    """One command, coordinator p1, quorum member p2 whose clock runs
    1000us AHEAD: submit 0 -> ingress 100 -> payload 200 -> MCollect out
    at 210 (p2 receives at local 1460 = real 460, acks at local 1660 =
    real 660) -> ack lands 910 -> path 1000 -> commit 1100 -> ready
    1500 -> executed 1600 -> reply-send 1650 -> reply 1900."""
    rifl, dot = [9, 1], [1, 4]
    return [
        {"k": "hdr", "clock": "wall", "v": 1},
        {"k": "off", "pid": 1, "peer": 2, "off": 1000, "rtt": 120, "t": 0},
        {"k": "span", "stage": "submit", "rifl": rifl, "cid": 9, "t": 0},
        {"k": "edge", "io": "r", "mt": "Submit", "src": 0, "dst": 1,
         "seq": 0, "rifl": rifl, "t": 100},
        {"k": "span", "stage": "payload", "rifl": rifl, "dot": dot,
         "pid": 1, "t": 200},
        {"k": "edge", "io": "s", "mt": "MCollect", "src": 1, "dst": 2,
         "seq": 1, "dot": dot, "t": 210},
        {"k": "edge", "io": "r", "mt": "MCollect", "src": 1, "dst": 2,
         "seq": 1, "dot": dot, "t": 1460},
        {"k": "edge", "io": "s", "mt": "MCollectAck", "src": 2, "dst": 1,
         "seq": 1, "dot": dot, "t": 1660},
        {"k": "edge", "io": "r", "mt": "MCollectAck", "src": 2, "dst": 1,
         "seq": 1, "dot": dot, "t": 910},
        {"k": "span", "stage": "path", "rifl": rifl, "dot": dot,
         "pid": 1, "t": 1000, "m": {"path": "fast"}},
        {"k": "span", "stage": "commit", "rifl": rifl, "dot": dot,
         "pid": 1, "t": 1100, "m": {"deps": [[2, 7]]}},
        # the dependency's own commit at p1, 300us later: the dep wait
        {"k": "span", "stage": "commit", "rifl": [8, 1], "dot": [2, 7],
         "pid": 1, "t": 1400},
        {"k": "span", "stage": "ready", "rifl": rifl, "pid": 1, "t": 1500},
        {"k": "span", "stage": "executed", "rifl": rifl, "pid": 1, "t": 1600},
        {"k": "edge", "io": "s", "mt": "Reply", "src": 1, "dst": 0,
         "seq": 0, "rifl": rifl, "t": 1650},
        {"k": "span", "stage": "reply", "rifl": rifl, "cid": 9, "t": 1900},
    ]


def test_attribution_decomposes_and_telescopes():
    events = _handbuilt_events()
    spans = assemble_spans(events)
    dot_edges, client_edges = match_edges(events)
    offsets = OffsetTable(events, wall=True)
    client_off = estimate_client_offsets(spans, client_edges, wall=True)
    from fantoch_tpu.observability.critpath import commit_times

    vector = attribute_span(
        spans[(9, 1)], dot_edges, client_edges, offsets, client_off,
        commit_times(events),
    )
    assert vector["stitched"]
    # exact telescoping: stage segments sum to reply - submit
    assert sum(vector["stages"].values()) == vector["total_us"] == 1900
    blame = vector["blame"]
    # client bracket is symmetric (100us out, 250us back): estimated
    # client offset -75us, net+queue == the submit->payload segment
    assert blame["client_net_us"] + blame["coord_queue_us"] == 200
    quorum = blame["quorum"]
    assert quorum["pid"] == 2 and quorum["mt"] == "MCollectAck"
    # p2's stamps corrected by -1000us: out 210->460 (250us), remote
    # 460->660 (200us), back 660->910 (250us)
    assert quorum["out_net_us"] == 250
    assert quorum["remote_us"] == 200
    assert quorum["back_net_us"] == 250
    assert quorum["wait_us"] == 910 - 200
    # dep wait names the blocking dot and its lateness past our commit
    assert blame["dep"]["dot"] == [2, 7]
    assert blame["dep"]["wait_us"] == 300
    # reply split: emit (executed->reply-send) vs return flight
    assert blame["emit_us"] + blame["reply_net_us"] == 300


# --- sim: stitching, blame, SlowProcess ---


def _near_far_planet():
    """p3 sits inside p1's and p2's fast quorums (r1/r2 are far from
    each other, both near r3)."""
    regions = [Region("r1"), Region("r2"), Region("r3")]
    latencies = {
        regions[0]: {regions[0]: 0, regions[1]: 80, regions[2]: 10},
        regions[1]: {regions[0]: 80, regions[1]: 0, regions[2]: 10},
        regions[2]: {regions[0]: 10, regions[1]: 10, regions[2]: 0},
    }
    return regions, Planet.from_latencies(latencies)


def _sim(trace_path, plan=None, client_regions=None, config=None,
         seed=7, flight_dir=None, extra_ms=2000):
    regions, planet = _near_far_planet()
    config = config or Config(
        n=3, f=1, gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        trace_sample_rate=1.0,
    )
    workload = Workload(
        shard_count=1, key_gen=ConflictRateKeyGen(50), keys_per_command=2,
        commands_per_client=COMMANDS_PER_CLIENT, payload_size=1,
    )
    runner = Runner(
        EPaxos, planet, config, workload, clients_per_process=2,
        process_regions=regions,
        client_regions=client_regions or regions,
        seed=seed, trace_path=str(trace_path), fault_plan=plan,
        flight_dir=flight_dir,
    )
    runner.run(extra_sim_time_ms=extra_ms)
    return runner


def test_sim_critpath_stitches_and_telescopes(tmp_path):
    path = tmp_path / "t.jsonl"
    _sim(path)
    report = critpath_report(read_trace(path))
    assert report["clock"] == "virtual"
    assert report["spans"] == 3 * 2 * COMMANDS_PER_CLIENT
    assert report["stitch_rate"] == 1.0
    assert report["telescoping_violations"] == 0
    assert report["quorum_blame"], "quorum waits must resolve to peers"
    # no skew in the virtual domain: no offset rows, no client offsets
    assert report["peers"] == []
    assert report["client_offsets_us"] == {}
    # exemplars carry full vectors
    assert report["exemplars"][0]["blame"]


def test_sim_slow_process_is_dominant_quorum_contributor(tmp_path):
    path = tmp_path / "slow.jsonl"
    plan = FaultPlan().with_slow_process(3, slow_ms=150)
    regions, _ = _near_far_planet()
    # clients only at r1/r2: every traced span is coordinated by a
    # process whose fast quorum contains the slowed p3
    _sim(path, plan=plan, client_regions=regions[:2])
    report = critpath_report(read_trace(path))
    assert report["stitch_rate"] == 1.0
    assert dominant_quorum_peer(report) == 3
    assert dominant_quorum_peer(report, tail=False) == 3
    row = report["quorum_blame"][3]
    # the 150ms injected delay dominates the wait, attributed to the
    # network leg (the sim delays delivery, not remote processing)
    assert row["mean_wait_us"] >= 150_000
    assert row["mean_net_us"] >= 0.8 * row["mean_wait_us"]


def test_sim_sampled_rate_still_attributes_sampled_spans(tmp_path):
    config = Config(
        n=3, f=1, gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        trace_sample_rate=0.5,
    )
    path = tmp_path / "half.jsonl"
    _sim(path, config=config)
    report = critpath_report(read_trace(path))
    assert 0 < report["spans"] < 3 * 2 * COMMANDS_PER_CLIENT
    # spans whose dot also hashed in are stitched; the rate is reported
    # honestly rather than silently counting unstitchable spans
    assert 0.0 <= report["stitch_rate"] <= 1.0


# --- run layer: wall clocks, offsets, delayed link ---


def test_localhost_critpath_stitches_offsets_and_blames_delayed_acks(tmp_path):
    from fantoch_tpu.run.harness import run_localhost_cluster

    config = Config(
        n=3, f=1, gc_interval_ms=50, trace_sample_rate=1.0,
    )
    workload = Workload(
        shard_count=1, key_gen=ConflictRateKeyGen(50), keys_per_command=2,
        commands_per_client=COMMANDS_PER_CLIENT, payload_size=1,
    )
    # delay EVERYTHING p1 sends to its peers: p1's acks land last at
    # p2/p3 (p1 sits in both their fast quorums on the localhost
    # id-ordered topology), so p1 must be the dominant contributor
    asyncio.run(run_localhost_cluster(
        EPaxos, config, workload, clients_per_process=2,
        observe_dir=str(tmp_path),
        peer_delays={1: {2: 60, 3: 60}},
        # fast heartbeats so the short run collects offset brackets
        runtime_kwargs={"heartbeat_interval_s": 0.1},
    ))
    events = []
    for path in sorted(glob.glob(f"{tmp_path}/trace_*.jsonl")):
        events.extend(read_trace(path))
    report = critpath_report(events)
    assert report["clock"] == "wall"
    assert report["spans"] == 3 * 2 * COMMANDS_PER_CLIENT
    assert report["stitch_rate"] >= 0.99
    assert report["telescoping_violations"] == 0
    # heartbeat offset rows exist for localhost peers, and the shared
    # wall clock keeps undelayed-pair estimates tight
    pairs = {(row["pid"], row["peer"]): row for row in report["peers"]}
    assert pairs, "offset table must resolve from heartbeat brackets"
    tight = [
        row for (pid, peer), row in pairs.items()
        if 1 not in (pid, peer)
    ]
    assert tight and all(abs(r["offset_us"]) < 50_000 for r in tight)
    assert dominant_quorum_peer(report, tail=False) == 1
    assert report["quorum_blame"][1]["mean_wait_us"] >= 50_000


# --- flight recorder ---


def test_sim_stall_dumps_correlatable_flight_rings(tmp_path):
    config = Config(
        n=3, f=1, gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        trace_sample_rate=1.0,
        executor_monitor_pending_interval_ms=200,
        executor_pending_fail_ms=800,
    )
    plan = dataclasses.replace(
        FaultPlan().with_crash(1, at_ms=60), max_sim_time_ms=6000
    )
    flight_dir = str(tmp_path / "flight")
    with pytest.raises(StalledExecutionError):
        _sim(tmp_path / "stall.jsonl", plan=plan, config=config,
             flight_dir=flight_dir)
    dumps = sorted(glob.glob(f"{flight_dir}/flight_p*.json"))
    # every process contributed a black box (p1's holds its pre-crash
    # events), clients their own
    assert [os.path.basename(p) for p in dumps] == [
        "flight_p1.json", "flight_p2.json", "flight_p3.json"
    ]
    assert os.path.exists(f"{flight_dir}/flight_clients.json")
    meta, events = read_flight(dumps[1])
    assert meta["reason"].startswith("StalledExecutionError")
    assert meta["clock"] == "virtual"
    assert events, "the ring must hold the pre-failure events"
    # the same correlator stitches the black boxes
    merged = flight_events(
        dumps + [f"{flight_dir}/flight_clients.json"]
    )
    report = critpath_report(merged)
    assert report["spans"] > 0
    assert report["telescoping_violations"] == 0


def test_flight_ring_is_bounded_and_unsampled(tmp_path, monkeypatch):
    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.observability.tracer import NOOP_TRACER

    monkeypatch.setenv("FANTOCH_FLIGHT_EVENTS", "8")
    clock = SimTime()
    recorder = FlightRecorder(clock, pid=4, inner=NOOP_TRACER,
                              clock="virtual")
    assert recorder.enabled and recorder.sample((1, 1))
    for sequence in range(20):
        recorder.span("submit", (1, sequence), cid=1)
    assert len(recorder.events()) == 8  # capacity-bounded ring
    # the ring kept the LAST events (it is a flight recorder)
    assert recorder.events()[-1]["rifl"] == [1, 19]
    path = recorder.dump(str(tmp_path / "f.json"), "unit")
    meta, events = read_flight(path)
    assert meta["pid"] == 4 and meta["reason"] == "unit"
    assert len(events) == 8


def test_localhost_fatal_failure_dumps_flight(tmp_path):
    from fantoch_tpu.run.harness import run_localhost_cluster

    config = Config(
        n=3, f=1, gc_interval_ms=50, trace_sample_rate=1.0,
        flight_recorder=True,
    )
    workload = Workload(
        shard_count=1, key_gen=ConflictRateKeyGen(50), keys_per_command=2,
        commands_per_client=200, payload_size=1,
    )

    async def chaos(runtimes):
        await asyncio.sleep(0.4)
        runtimes[2]._fail(
            StalledExecutionError(2, {}, 999, recovery_delay_ms=None)
        )

    with pytest.raises(AssertionError, match="StalledExecutionError"):
        asyncio.run(run_localhost_cluster(
            EPaxos, config, workload, clients_per_process=2,
            observe_dir=str(tmp_path), chaos=chaos,
        ))
    dump = f"{tmp_path}/flight_p2.json"
    assert os.path.exists(dump)
    meta, events = read_flight(dump)
    assert meta["reason"].startswith("StalledExecutionError")
    assert meta["clock"] == "wall"
    assert any(ev["k"] == "span" for ev in events)
    # the correlator reads the black box next to the live span logs
    from fantoch_tpu.bin.obs import _load

    merged = _load(sorted(glob.glob(f"{tmp_path}/trace_*.jsonl")) + [dump])
    assert critpath_report(merged)["spans"] > 0


def test_sigusr1_dumps_flight_ring(tmp_path):
    from fantoch_tpu.core.timing import RunTime
    from fantoch_tpu.observability.recorder import install_flight_signal
    from fantoch_tpu.observability.tracer import NOOP_TRACER

    async def scenario():
        recorder = FlightRecorder(RunTime(), pid=7, inner=NOOP_TRACER)
        recorder.span("submit", (1, 1), cid=1)
        assert install_flight_signal(recorder, str(tmp_path))
        os.kill(os.getpid(), signal.SIGUSR1)
        await asyncio.sleep(0.1)  # let the loop run the handler
        asyncio.get_running_loop().remove_signal_handler(signal.SIGUSR1)
        return recorder

    recorder = asyncio.run(scenario())
    assert recorder.dumps == [f"{tmp_path}/flight_p7.json"]
    meta, events = read_flight(recorder.dumps[0])
    assert meta["reason"] == "SIGUSR1" and len(events) == 1


def test_fuzz_finding_attaches_flight_dumps(tmp_path):
    from fantoch_tpu.sim.fuzz import FuzzCase, repro_artifact, run_case

    # a guaranteed stall: crash-forever past f with no recovery
    case = FuzzCase(
        protocol="epaxos", n=3, f=1, conflict_rate=100,
        keys_per_command=1, commands_per_client=3, clients_per_process=1,
        sim_seed=0,
        plan=dataclasses.replace(
            FaultPlan().with_crash(1, at_ms=20).with_crash(2, at_ms=30),
            max_sim_time_ms=3000,
        ),
    )
    result = run_case(case, flight_dir=str(tmp_path / "flight"))
    assert not result.ok
    assert result.flight, "a finding must ship its black box"
    artifact = repro_artifact(result)
    assert artifact["flight"] == result.flight
    for path in result.flight:
        meta, _events = read_flight(path)
        assert meta["format"] == "fantoch-flight-v1"
    # replay WITHOUT the recorder reproduces the verdict digest (the
    # black box is evidence, not part of the determinism contract)
    from fantoch_tpu.sim.fuzz import replay_repro

    _replayed, identical = replay_repro(artifact)
    assert identical


# --- satellites: diff --stages, compile-ms counter ---


def test_diff_stages_tolerates_wall_jitter_and_catches_structure(tmp_path):
    path_a, path_b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _sim(path_a, seed=7)
    _sim(path_b, seed=7)
    verdict = diff_stages(read_trace(path_a), read_trace(path_b))
    assert verdict["matched"] == 3 * 2 * COMMANDS_PER_CLIENT
    assert not verdict["mismatches"]
    assert not verdict["only_a"] and not verdict["only_b"]
    # an injected 10x inflation on one span's quorum wait is caught
    events_b = read_trace(path_b)
    spans = assemble_spans(events_b)
    rifl = next(iter(spans))
    bumped = []
    for ev in events_b:
        ev = dict(ev)
        if (
            ev.get("k") == "span"
            and tuple(ev["rifl"]) == rifl
            and ev["stage"] in ("path", "commit", "ready", "executed",
                                "reply")
        ):
            ev["t"] += 900_000
        bumped.append(ev)
    verdict = diff_stages(read_trace(path_a), bumped)
    # "ingest" sits between payload and path in the canonical chain, so the
    # inflated segment is the ingest->path hop
    assert any("ingest->path" in line for line in verdict["mismatches"])
    # the CLI spelling agrees
    from fantoch_tpu.bin import obs

    assert obs.main(["diff", str(path_a), str(path_b), "--stages"]) == 0


def test_jax_compile_ms_counts_cumulative_compile_wall():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from fantoch_tpu.observability.device import (
        cache_hit_count,
        compile_ms,
        recompile_count,
        subscribe_recompiles,
    )

    assert subscribe_recompiles()
    before_ms = compile_ms()
    before_n, before_hits = recompile_count(), cache_hit_count()
    # a fresh program shape forces one backend-compile event

    @jax.jit
    def _probe(x):
        return (x * 3 + 1).sum()

    _probe(jnp.arange(97)).block_until_ready()
    # the conftest arms the persistent cache, so the program is either a
    # TRUE compile (cold .jax_cache) or a counted disk retrieval (warm);
    # the hit/miss pairing must book it as exactly one of the two —
    # never both, never neither
    assert (recompile_count() > before_n) != (
        cache_hit_count() > before_hits
    )
    # either way the compile-wall gauge advances (the duration event
    # wraps retrievals too — reload time is still wall time)
    assert compile_ms() > before_ms
    # the counter rides the summarize payload like any device counter
    from fantoch_tpu.observability.report import counters_total

    events = [
        {"k": "ctr", "name": "jax_compile_ms", "v": compile_ms(), "t": 0},
        {"k": "ctr", "name": "jax_recompiles", "v": recompile_count(),
         "t": 0},
    ]
    totals = counters_total(events)
    assert totals["jax_compile_ms"] == compile_ms()


def test_obs_critpath_cli_prints_blame(tmp_path, capsys):
    path = tmp_path / "t.jsonl"
    _sim(path)
    from fantoch_tpu.bin import obs

    assert obs.main(["critpath", str(path)]) == 0
    out = capsys.readouterr().out
    assert "stitched" in out and "quorum blame" in out
    assert obs.main(["critpath", str(path), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["stitch_rate"] == 1.0
    assert payload["telescoping_violations"] == 0


def test_perfetto_flow_arrows_pair_and_validate(tmp_path):
    from fantoch_tpu.observability.perfetto import (
        to_perfetto,
        validate_perfetto,
    )

    path = tmp_path / "t.jsonl"
    _sim(path)
    perfetto = to_perfetto(read_trace(path))
    flows = [
        ev for ev in perfetto["traceEvents"] if ev["ph"] in ("s", "f")
    ]
    assert flows, "matched message edges must render as flow arrows"
    validate_perfetto(perfetto)
    validate_perfetto(json.loads(json.dumps(perfetto)))
    # arrows connect distinct process tracks
    by_id: dict = {}
    for ev in flows:
        by_id.setdefault(ev["id"], []).append(ev["pid"])
    assert any(len(set(pids)) == 2 for pids in by_id.values())


def test_perfetto_broadcast_flow_ids_distinct():
    # run-layer broadcasts allocate ONE edge seq across the fan-out
    # (dst disambiguates on the wire): each hop still needs its own
    # flow id or the s/f pairs collide and the trace is invalid
    from fantoch_tpu.observability.perfetto import (
        to_perfetto,
        validate_perfetto,
    )
    from fantoch_tpu.observability.tracer import edge_event

    events = [
        edge_event(10, "s", "MCollect", 1, 2, 7, dot=(1, 1)),
        edge_event(10, "s", "MCollect", 1, 3, 7, dot=(1, 1)),
        edge_event(20, "r", "MCollect", 1, 2, 7, dot=(1, 1)),
        edge_event(26, "r", "MCollect", 1, 3, 7, dot=(1, 1)),
    ]
    perfetto = to_perfetto(events)
    flows = [ev for ev in perfetto["traceEvents"] if ev["ph"] in ("s", "f")]
    assert len(flows) == 4
    assert len({ev["id"] for ev in flows if ev["ph"] == "s"}) == 2
    validate_perfetto(perfetto)
