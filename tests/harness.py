"""Whole-system simulator test harness, mirroring the reference's `sim_test`
(fantoch_ps/src/protocol/mod.rs:835-1080): run a protocol under message
reordering, then assert (a) identical per-key execution order on every
process (linearizable agreement via ExecutionOrderMonitor) and (b) commit/GC
accounting (min <= fast+slow <= max commits; gc_at * commits == stable).
"""

import os
from typing import Dict, Tuple

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.protocol import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

# CI runs a shrunk load (the reference's CI=true trick,
# fantoch_ps/src/protocol/mod.rs:85-110)
COMMANDS_PER_CLIENT = 5 if os.environ.get("CI") else 10
CLIENTS_PER_PROCESS = 3
CONFLICT_RATE = 50


def sim_test(
    protocol_cls,
    config: Config,
    commands_per_client: int = COMMANDS_PER_CLIENT,
    clients_per_process: int = CLIENTS_PER_PROCESS,
    seed: int = 0,
    keys_per_command: int = 2,
    conflict_rate: int = CONFLICT_RATE,
    read_only_percentage: int = 0,
) -> int:
    """Returns the total number of slow paths taken."""
    config = config.with_(
        executor_monitor_execution_order=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        shard_count=1,
    )
    planet = Planet.new("gcp")
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(conflict_rate),
        keys_per_command=keys_per_command,
        commands_per_client=commands_per_client,
        payload_size=1,
        read_only_percentage=read_only_percentage,
    )
    regions = sorted(planet.regions())[: config.n]
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        clients_per_process,
        process_regions=list(regions),
        client_regions=list(regions),
        seed=seed,
    )
    runner.reorder_messages()
    metrics, monitors, _latencies = runner.run(extra_sim_time_ms=10_000)

    # agreement: all processes execute conflicting commands in the same order
    check_monitors(monitors)

    extracted = {
        pid: (
            m.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0,
            m.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0,
            m.get_aggregated(ProtocolMetricsKind.STABLE) or 0,
        )
        for pid, m in metrics.items()
    }
    return check_metrics(config, commands_per_client, clients_per_process, extracted)


def check_monitors(monitors: Dict) -> None:
    """Cross-replica safety via the shared invariant engine
    (core/audit.ConsistencyAuditor): per-key write-order agreement
    (reads commute — the KeyDeps read/write split leaves read-read order
    unforced), executed-multiset agreement, key-set agreement, and
    exactly-once execution.  One engine for every sim test AND the chaos
    fuzzer, so an invariant tightened once protects both."""
    from fantoch_tpu.core.audit import ConsistencyAuditor

    monitors = dict(monitors)
    assert monitors, "there should be monitors"
    for pid, monitor in monitors.items():
        assert monitor is not None, (
            f"p{pid} should be monitoring execution order"
        )
    verdict = ConsistencyAuditor().audit(monitors)
    assert verdict.ok, verdict.describe()


def check_metrics(
    config: Config,
    commands_per_client: int,
    clients_per_process: int,
    metrics: Dict[int, Tuple[int, int, int]],
) -> int:
    total_fast = sum(f for f, _, _ in metrics.values())
    total_slow = sum(s for _, s, _ in metrics.values())
    total_stable = sum(st for _, _, st in metrics.values())

    total_processes = config.n * config.shard_count
    total_clients = clients_per_process * total_processes
    min_commits = commands_per_client * total_clients
    max_commits = min_commits * config.shard_count

    if config.leader is None:
        total_commits = total_fast + total_slow
        assert min_commits <= total_commits <= max_commits, (
            f"number of committed commands out of bounds: "
            f"{min_commits} <= {total_commits} <= {max_commits}"
        )

    # leader-based protocols only gc at f+1 acceptors; leaderless at all n
    gc_at = (config.f + 1) if config.leader is not None else config.n
    assert gc_at * min_commits == total_stable, (
        f"not all processes gced: expected {gc_at * min_commits}, got {total_stable}"
    )
    return total_slow
