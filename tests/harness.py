"""Whole-system simulator test harness, mirroring the reference's `sim_test`
(fantoch_ps/src/protocol/mod.rs:835-1080): run a protocol under message
reordering, then assert (a) identical per-key execution order on every
process (linearizable agreement via ExecutionOrderMonitor) and (b) commit/GC
accounting (min <= fast+slow <= max commits; gc_at * commits == stable).
"""

import os
from typing import Dict, Tuple

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.protocol import ProtocolMetricsKind
from fantoch_tpu.sim import Runner

# CI runs a shrunk load (the reference's CI=true trick,
# fantoch_ps/src/protocol/mod.rs:85-110)
COMMANDS_PER_CLIENT = 5 if os.environ.get("CI") else 10
CLIENTS_PER_PROCESS = 3
CONFLICT_RATE = 50


def sim_test(
    protocol_cls,
    config: Config,
    commands_per_client: int = COMMANDS_PER_CLIENT,
    clients_per_process: int = CLIENTS_PER_PROCESS,
    seed: int = 0,
    keys_per_command: int = 2,
    conflict_rate: int = CONFLICT_RATE,
    read_only_percentage: int = 0,
) -> int:
    """Returns the total number of slow paths taken."""
    config = config.with_(
        executor_monitor_execution_order=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        shard_count=1,
    )
    planet = Planet.new("gcp")
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(conflict_rate),
        keys_per_command=keys_per_command,
        commands_per_client=commands_per_client,
        payload_size=1,
        read_only_percentage=read_only_percentage,
    )
    regions = sorted(planet.regions())[: config.n]
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        clients_per_process,
        process_regions=list(regions),
        client_regions=list(regions),
        seed=seed,
    )
    runner.reorder_messages()
    metrics, monitors, _latencies = runner.run(extra_sim_time_ms=10_000)

    # agreement: all processes execute conflicting commands in the same order
    check_monitors(monitors)

    extracted = {
        pid: (
            m.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0,
            m.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0,
            m.get_aggregated(ProtocolMetricsKind.STABLE) or 0,
        )
        for pid, m in metrics.items()
    }
    return check_metrics(config, commands_per_client, clients_per_process, extracted)


def check_monitors(monitors: Dict) -> None:
    monitors = dict(monitors)
    assert monitors, "there should be monitors"
    items = list(monitors.items())
    pid_a, monitor_a = items[0]
    assert monitor_a is not None, "processes should be monitoring execution order"
    for pid_b, monitor_b in items[1:]:
        assert monitor_b is not None
        assert len(monitor_a) == len(monitor_b), (
            f"p{pid_a} and p{pid_b} monitors have different key counts"
        )
        for key in monitor_a.keys():
            # full-order agreement for writes; reads commute (the KeyDeps
            # read/write split leaves read-read order unforced), so they
            # only need to execute everywhere — counts checked below
            order_a = monitor_a.get_write_order(key)
            order_b = monitor_b.get_write_order(key)
            assert order_a == order_b, (
                f"different write execution orders on key {key!r}:\n"
                f"  p{pid_a}: {order_a}\n  p{pid_b}: {order_b}"
            )
            from collections import Counter

            full_a = monitor_a.get_order(key)
            full_b = monitor_b.get_order(key)
            assert Counter(full_a) == Counter(full_b), (
                f"different executed-command multisets on key {key!r}"
            )


def check_metrics(
    config: Config,
    commands_per_client: int,
    clients_per_process: int,
    metrics: Dict[int, Tuple[int, int, int]],
) -> int:
    total_fast = sum(f for f, _, _ in metrics.values())
    total_slow = sum(s for _, s, _ in metrics.values())
    total_stable = sum(st for _, _, st in metrics.values())

    total_processes = config.n * config.shard_count
    total_clients = clients_per_process * total_processes
    min_commits = commands_per_client * total_clients
    max_commits = min_commits * config.shard_count

    if config.leader is None:
        total_commits = total_fast + total_slow
        assert min_commits <= total_commits <= max_commits, (
            f"number of committed commands out of bounds: "
            f"{min_commits} <= {total_commits} <= {max_commits}"
        )

    # leader-based protocols only gc at f+1 acceptors; leaderless at all n
    gc_at = (config.f + 1) if config.leader is not None else config.n
    assert gc_at * min_commits == total_stable, (
        f"not all processes gced: expected {gc_at * min_commits}, got {total_stable}"
    )
    return total_slow
