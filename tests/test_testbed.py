"""Hosts (SSH/baremetal-analog) testbed: a full experiment through
exp.testbed.HostsTestbed in local-exec mode — staging, remote-command
construction, launch, artifact pull — over 127.0.0.1 entries
(fantoch_exp/src/testbed/baremetal.rs is the reference shape; a real
cluster only changes the transport to ssh/rsync/scp)."""

import pytest
import json
import os

from fantoch_tpu.exp.bench import run_experiment
from fantoch_tpu.exp.config import ExperimentConfig
from fantoch_tpu.exp.testbed import HostsTestbed
from fantoch_tpu.run.harness import free_port


@pytest.mark.slow
def test_hosts_testbed_experiment(tmp_path):
    testbed = HostsTestbed(
        ["127.0.0.1", "127.0.0.1", "127.0.0.1"],
        use_ssh=False,
        base_port=free_port(),
    )
    config = ExperimentConfig(
        protocol="epaxos", n=3, f=1,
        clients_per_process=1, commands_per_client=5,
        conflict_rate=50, keys_per_command=1, payload_size=1,
    )
    try:
        manifest = run_experiment(config, str(tmp_path), testbed=testbed,
                                  client_timeout_s=420)  # generous: full-suite runs contend on one core
    finally:
        testbed.cleanup()
    assert manifest["outcome"]["commands"] == 15
    assert manifest["testbed"]["kind"] == "hosts"
    exp_dir = tmp_path / config.name()
    assert (exp_dir / "manifest.json").exists()
    assert (exp_dir / "client_summary.json").exists()
    # artifacts pulled back from the staged workdirs
    pulled = manifest["testbed"]["pulled"]
    assert any(p.startswith("metrics_p") for p in pulled), pulled
    assert any(p.startswith("execution_p") for p in pulled), pulled
    summary = json.loads((exp_dir / "client_summary.json").read_text())
    assert summary["commands"] == 15
