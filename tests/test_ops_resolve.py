"""Batched TPU graph resolver vs the host Tarjan oracle.

The resolver (fantoch_tpu/ops/graph_resolve.py) must produce, for every
graph the oracle (executor/graph/deps_graph.py — a faithful analog of
fantoch_ps/src/executor/graph/) fully executes, the identical per-key
execution order.  Graph families mirror the reference's executor tests
(fantoch_ps/src/executor/graph/mod.rs:713-1045): chains, cycles, rho
shapes, randomized dep graphs, plus missing-dependency blocking.
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
from fantoch_tpu.core.ids import process_ids
from fantoch_tpu.executor.graph.deps_graph import DependencyGraph
from fantoch_tpu.ops.graph_resolve import (
    MISSING,
    TERMINAL,
    resolve_functional,
    resolve_general,
)
from fantoch_tpu.protocol.common.graph_deps import Dependency

TIME = RunTime()
SHARD = 0


def make_cmd(dot, keys):
    return Command.from_keys(
        Rifl(dot.source, dot.sequence), SHARD, {k: (KVOp.put(""),) for k in keys}
    )


def oracle_per_key_order(n, args):
    """Feed (dot, keys, dep_dots) to the oracle graph; returns {key: [dot]}."""
    graph = DependencyGraph(1, SHARD, Config(n, 1))
    executed = []
    for dot, keys, dep_dots in args:
        deps = [Dependency(d, frozenset({SHARD})) for d in dep_dots]
        graph.handle_add(dot, make_cmd(dot, keys), deps, TIME)
        executed.extend(graph.commands_to_execute())
    order = {}
    for cmd in executed:
        dot = Dot(cmd.rifl.source, cmd.rifl.sequence)
        for key in cmd.keys(SHARD):
            order.setdefault(key, []).append(dot)
    return order, len(executed)


def batch_arrays(args):
    """(dot, keys, dep_dots) list -> (dep or deps, dot_src, dot_seq, slot map).

    Dots absent from the batch map to MISSING (they are neither executed nor
    committed here — graph_resolve.py's pending analog)."""
    slot = {dot: i for i, (dot, _, _) in enumerate(args)}
    width = max((len(d) for _, _, d in args), default=1) or 1
    deps = np.full((len(args), width), TERMINAL, dtype=np.int32)
    for i, (dot, _, dep_dots) in enumerate(args):
        for j, d in enumerate(sorted(dep_dots)):
            if d == dot:
                continue  # self-dependency pruned (tarjan.py:129)
            deps[i, j] = slot.get(d, MISSING)
    src = np.array([d.source for d, _, _ in args], dtype=np.int32)
    seq = np.array([d.sequence for d, _, _ in args], dtype=np.int32)
    return deps, src, seq, slot


def resolver_per_key_order(args, functional):
    deps, src, seq, _ = batch_arrays(args)
    if functional:
        assert deps.shape[1] == 1
        res = resolve_functional(jnp.asarray(deps[:, 0]), jnp.asarray(src), jnp.asarray(seq))
    else:
        res = resolve_general(jnp.asarray(deps), jnp.asarray(src), jnp.asarray(seq))
    order = np.asarray(res.order)
    resolved = np.asarray(res.resolved)
    per_key = {}
    count = 0
    for i in order:
        if not resolved[i]:
            continue
        count += 1
        dot, keys, _ = args[i]
        for key in keys:
            per_key.setdefault(key, []).append(dot)
    return per_key, count, res


def assert_matches_oracle(n, args, functional):
    expected, n_exec = oracle_per_key_order(n, args)
    got, n_res, _ = resolver_per_key_order(args, functional)
    assert n_res == n_exec
    assert got == expected


# --- functional (out-degree <= 1) ---


def test_chain_ranks():
    dots = [Dot(1, s) for s in range(1, 6)]
    args = [(dots[0], ["A"], set())] + [
        (dots[i], ["A"], {dots[i - 1]}) for i in range(1, 5)
    ]
    _, _, res = resolver_per_key_order(args, functional=True)
    assert np.asarray(res.rank).tolist() == [0, 1, 2, 3, 4]
    assert np.asarray(res.resolved).all()
    assert_matches_oracle(1, args, functional=True)


def test_two_cycle():
    # the reference's `test_simple` (mod.rs:713-754): 2-cycle executes
    # together, dot-sorted
    d0, d1 = Dot(1, 1), Dot(2, 1)
    args = [(d0, ["A"], {d1}), (d1, ["A"], {d0})]
    per_key, count, res = resolver_per_key_order(args, functional=True)
    assert count == 2
    assert per_key["A"] == [d0, d1]
    assert np.asarray(res.on_cycle).all()
    assert np.asarray(res.leader).tolist() == [0, 0]
    assert_matches_oracle(2, args, functional=True)


def test_rho_shape():
    # 3-cycle at the oldest end, chain of 4 flowing into it
    cyc = [Dot(1, 1), Dot(2, 1), Dot(3, 1)]
    tail = [Dot(1, s) for s in range(2, 6)]
    args = [
        (cyc[0], ["A"], {cyc[2]}),
        (cyc[1], ["A"], {cyc[0]}),
        (cyc[2], ["A"], {cyc[1]}),
        (tail[0], ["A"], {cyc[2]}),
    ] + [(tail[i], ["A"], {tail[i - 1]}) for i in range(1, 4)]
    per_key, count, res = resolver_per_key_order(args, functional=True)
    assert count == 7
    assert per_key["A"] == sorted(cyc) + tail
    assert np.asarray(res.on_cycle).tolist() == [True] * 3 + [False] * 4
    assert_matches_oracle(3, args, functional=True)


def test_missing_blocks_tail():
    d1, d2, d3 = Dot(1, 1), Dot(1, 2), Dot(1, 3)
    # d1 depends on an uncommitted dot; d2, d3 chain behind it
    args = [(d1, ["A"], {Dot(2, 9)}), (d2, ["A"], {d1}), (d3, ["A"], {d2})]
    _, count, res = resolver_per_key_order(args, functional=True)
    assert count == 0
    assert not np.asarray(res.resolved).any()


def test_executed_dep_pruned():
    # a dep already covered by the executed clock arrives pruned (TERMINAL):
    # the vertex is immediately executable (tarjan.rs:131-136)
    d1 = Dot(1, 2)
    res = resolve_functional(
        jnp.asarray([TERMINAL], dtype=jnp.int32),
        jnp.asarray([d1.source], dtype=jnp.int32),
        jnp.asarray([d1.sequence], dtype=jnp.int32),
    )
    assert np.asarray(res.resolved).all()
    assert np.asarray(res.rank).tolist() == [0]


def random_functional_args(n, keys, cmds_per_key, rng, cycle_prob=0.5):
    """Per-key chains with an optional cycle at the oldest end — the shape
    sequential KeyDeps + concurrent proposals actually produce."""
    args = []
    seq_by_pid = {pid: 0 for pid in process_ids(SHARD, n)}

    def next_dot():
        pid = rng.choice(list(seq_by_pid))
        seq_by_pid[pid] += 1
        return Dot(pid, seq_by_pid[pid])

    for key in keys:
        chain = [next_dot() for _ in range(cmds_per_key)]
        if len(chain) >= 2 and rng.random() < cycle_prob:
            cyc_len = rng.randint(2, min(4, len(chain)))
            for i in range(cyc_len):
                args.append((chain[i], [key], {chain[(i - 1) % cyc_len]}))
            start = cyc_len
        else:
            args.append((chain[0], [key], set()))
            start = 1
        for i in range(start, len(chain)):
            args.append((chain[i], [key], {chain[i - 1]}))
    rng.shuffle(args)
    return args


def test_random_functional_vs_oracle():
    rng = random.Random(7)
    for trial in range(20):
        args = random_functional_args(
            n=3, keys=["A", "B", "C"], cmds_per_key=rng.randint(1, 8), rng=rng
        )
        # oracle needs deps to exist eventually; feeding all args in the
        # shuffled order executes everything
        assert_matches_oracle(3, args, functional=True)


# --- keyed sort-based path (the round-3 north-star kernel) ---


def _key_hash(key: str) -> int:
    import zlib

    return zlib.crc32(key.encode()) & 0x7FFFFFFF


def keyed_per_key_order(args, residual_size=None, collide=False):
    """Drive resolve_functional_keyed; returns (per_key order, count, res)."""
    from fantoch_tpu.ops.graph_resolve import (
        _residual_size_for,
        resolve_functional_keyed,
    )

    deps, src, seq, _ = batch_arrays(args)
    assert deps.shape[1] == 1
    keys = np.array(
        [0 if collide else _key_hash(ks[0]) for _, ks, _ in args], dtype=np.int32
    )
    res = resolve_functional_keyed(
        jnp.asarray(keys),
        jnp.asarray(deps[:, 0]),
        jnp.asarray(src),
        jnp.asarray(seq),
        residual_size=residual_size or _residual_size_for(len(args)),
    )
    assert not bool(res.overflow)
    order = np.asarray(res.order)
    resolved = np.asarray(res.resolved)
    assert int(res.n_resolved) == int(resolved.sum())
    per_key = {}
    count = 0
    for i in order:
        if not resolved[i]:
            continue
        count += 1
        dot, keys_i, _ = args[i]
        for key in keys_i:
            per_key.setdefault(key, []).append(dot)
    return per_key, count, res


def assert_keyed_matches_oracle(n, args, **kw):
    expected, n_exec = oracle_per_key_order(n, args)
    got, n_res, _ = keyed_per_key_order(args, **kw)
    assert n_res == n_exec
    assert got == expected


def test_keyed_chain_ranks():
    # arrival-order chain: the pure sort path, empty residual
    dots = [Dot(1, s) for s in range(1, 6)]
    args = [(dots[0], ["A"], set())] + [
        (dots[i], ["A"], {dots[i - 1]}) for i in range(1, 5)
    ]
    _, _, res = keyed_per_key_order(args)
    assert np.asarray(res.rank).tolist() == [0, 1, 2, 3, 4]
    assert np.asarray(res.resolved).all()
    assert_keyed_matches_oracle(1, args)


def test_keyed_inverted_chain():
    # batch order is the reverse of chain order: every link fails
    # verification, the whole run goes through the residual doubling
    dots = [Dot(1, s) for s in range(1, 6)]
    args = [(dots[i], ["A"], {dots[i - 1]}) for i in range(4, 0, -1)] + [
        (dots[0], ["A"], set())
    ]
    assert_keyed_matches_oracle(1, args)


def test_keyed_two_cycle():
    d0, d1 = Dot(1, 1), Dot(2, 1)
    args = [(d0, ["A"], {d1}), (d1, ["A"], {d0})]
    per_key, count, res = keyed_per_key_order(args)
    assert count == 2
    assert per_key["A"] == [d0, d1]
    assert np.asarray(res.on_cycle).all()
    assert_keyed_matches_oracle(2, args)


def test_keyed_rho_shape():
    cyc = [Dot(1, 1), Dot(2, 1), Dot(3, 1)]
    tail = [Dot(1, s) for s in range(2, 6)]
    args = [
        (cyc[0], ["A"], {cyc[2]}),
        (cyc[1], ["A"], {cyc[0]}),
        (cyc[2], ["A"], {cyc[1]}),
        (tail[0], ["A"], {cyc[2]}),
    ] + [(tail[i], ["A"], {tail[i - 1]}) for i in range(1, 4)]
    per_key, count, res = keyed_per_key_order(args)
    assert count == 7
    assert per_key["A"] == sorted(cyc) + tail
    assert_keyed_matches_oracle(3, args)


def test_keyed_mid_run_cycle_with_verified_prefix():
    # verified prefix (chain from TERMINAL head) followed by a 2-cycle and
    # its tail: prefix resolves by run position, the rest via the residual
    a, b = Dot(1, 1), Dot(1, 2)
    c, d = Dot(2, 5), Dot(3, 5)  # the racing pair
    e = Dot(1, 3)
    args = [
        (a, ["A"], set()),
        (b, ["A"], {a}),
        (c, ["A"], {d}),  # link check fails here (dep is not `b`)
        (d, ["A"], {c}),
        (e, ["A"], {d}),
    ]
    per_key, count, _ = keyed_per_key_order(args)
    assert count == 5
    # prefix a,b first; then the cycle {c,d} dot-sorted; then e
    assert per_key["A"][:2] == [a, b]
    assert per_key["A"][2:4] == sorted([c, d])
    assert per_key["A"][4] == e


def test_keyed_missing_blocks_suffix():
    d1, d2, d3 = Dot(1, 1), Dot(1, 2), Dot(1, 3)
    args = [(d1, ["A"], {Dot(2, 9)}), (d2, ["A"], {d1}), (d3, ["A"], {d2})]
    _, count, res = keyed_per_key_order(args)
    assert count == 0
    assert not np.asarray(res.resolved).any()


def test_keyed_missing_blocks_only_its_run():
    # missing dep blocks one key's run; another key's chain still resolves
    d1, d2 = Dot(1, 1), Dot(1, 2)
    e1, e2 = Dot(2, 1), Dot(2, 2)
    args = [
        (d1, ["A"], {Dot(3, 9)}),
        (d2, ["A"], {d1}),
        (e1, ["B"], set()),
        (e2, ["B"], {e1}),
    ]
    per_key, count, _ = keyed_per_key_order(args)
    assert count == 2
    assert per_key == {"B": [e1, e2]}


def test_keyed_hash_collision_is_correct():
    # all keys collide into one run: pure perf degradation, same answer
    rng = random.Random(11)
    args = random_functional_args(
        n=3, keys=["A", "B", "C", "D"], cmds_per_key=5, rng=rng
    )
    expected, n_exec = oracle_per_key_order(3, args)
    got, n_res, _ = keyed_per_key_order(args, collide=True)
    assert n_res == n_exec
    assert got == expected


def test_keyed_overflow_falls_back():
    from fantoch_tpu.ops.graph_resolve import resolve_keyed_auto

    # inverted chain with a tiny residual: keyed kernel overflows, the
    # auto wrapper must still return the exact doubling answer
    dots = [Dot(1, s) for s in range(1, 9)]
    args = [(dots[i], ["A"], {dots[i - 1]}) for i in range(7, 0, -1)] + [
        (dots[0], ["A"], set())
    ]
    deps, src, seq, _ = batch_arrays(args)
    keys = np.zeros(len(args), dtype=np.int32)
    from fantoch_tpu.ops.graph_resolve import resolve_functional_keyed

    res_small = resolve_functional_keyed(
        jnp.asarray(keys),
        jnp.asarray(deps[:, 0]),
        jnp.asarray(src),
        jnp.asarray(seq),
        residual_size=2,
    )
    assert bool(res_small.overflow)
    res = resolve_keyed_auto(
        jnp.asarray(keys), jnp.asarray(deps[:, 0]), jnp.asarray(src), jnp.asarray(seq)
    )
    assert not bool(res.overflow)
    order = [i for i in np.asarray(res.order) if np.asarray(res.resolved)[i]]
    assert [args[i][0] for i in order] == [dots[i] for i in range(8)]


@pytest.mark.slow
def test_keyed_random_vs_oracle():
    rng = random.Random(7)
    for trial in range(20):
        args = random_functional_args(
            n=3, keys=["A", "B", "C"], cmds_per_key=rng.randint(1, 8), rng=rng
        )
        assert_keyed_matches_oracle(3, args)


def test_keyed_fast_entry_counts():
    # return_structure=False: order + n_resolved only; resolved is a
    # permutation of the true flags (reduction-safe)
    from fantoch_tpu.ops.graph_resolve import (
        _residual_size_for,
        resolve_functional_keyed,
    )

    rng = random.Random(5)
    args = random_functional_args(n=3, keys=["A", "B"], cmds_per_key=6, rng=rng)
    deps, src, seq, _ = batch_arrays(args)
    keys = np.array([_key_hash(ks[0]) for _, ks, _ in args], dtype=np.int32)
    res = resolve_functional_keyed(
        jnp.asarray(keys),
        jnp.asarray(deps[:, 0]),
        jnp.asarray(src),
        jnp.asarray(seq),
        residual_size=_residual_size_for(len(args)),
        return_structure=False,
    )
    full, n_exec = oracle_per_key_order(3, args)
    assert int(res.n_resolved) == n_exec == int(np.asarray(res.resolved).sum())


def test_keyed_fast_entry_order_matches_structure_entry():
    # the latency entry (return_structure=False) takes a lax.cond fast path
    # when the residual is empty; its emitted order must equal the structure
    # entry's on both branches
    from fantoch_tpu.ops.graph_resolve import (
        _residual_size_for,
        resolve_functional_keyed,
    )

    def both_orders(keys, dep, src, seq):
        outs = []
        for structure in (True, False):
            res = resolve_functional_keyed(
                jnp.asarray(keys),
                jnp.asarray(dep),
                jnp.asarray(src),
                jnp.asarray(seq),
                residual_size=_residual_size_for(len(keys)),
                return_structure=structure,
            )
            assert not bool(res.overflow)
            outs.append((np.asarray(res.order), int(res.n_resolved)))
        return outs

    # (a) arrival-order chains on two keys: residual empty -> cond fast path
    keys = np.array([7, 9, 7, 9, 7], dtype=np.int32)
    dep = np.array([-1, -1, 0, 1, 2], dtype=np.int32)
    src = np.ones(5, dtype=np.int32)
    seq = np.arange(1, 6, dtype=np.int32)
    (o_s, n_s), (o_f, n_f) = both_orders(keys, dep, src, seq)
    assert n_s == n_f == 5
    assert o_s.tolist() == o_f.tolist()

    # (b) an inverted chain + a 2-cycle: residual path on both entries
    keys = np.array([7, 7, 7, 9, 9], dtype=np.int32)
    dep = np.array([1, 2, -1, 4, 3], dtype=np.int32)  # 0<-1<-2; 3<->4
    src = np.array([1, 1, 1, 1, 2], dtype=np.int32)
    seq = np.array([3, 2, 1, 1, 1], dtype=np.int32)
    (o_s, n_s), (o_f, n_f) = both_orders(keys, dep, src, seq)
    assert n_s == n_f == 5
    assert o_s.tolist() == o_f.tolist()


# --- general (multi-key, out-degree D) ---


def test_general_chain_and_merge():
    a, b, c, d = Dot(1, 1), Dot(1, 2), Dot(2, 1), Dot(2, 2)
    # two chains merging into d (multi-key command)
    args = [
        (a, ["A"], set()),
        (b, ["A"], {a}),
        (c, ["B"], set()),
        (d, ["A", "B"], {b, c}),
    ]
    assert_matches_oracle(2, args, functional=False)


def test_general_two_cycle_collapse():
    d0, d1 = Dot(1, 1), Dot(2, 1)
    args = [(d0, ["A"], {d1}), (d1, ["A"], {d0})]
    per_key, count, res = resolver_per_key_order(args, functional=False)
    assert count == 2
    assert per_key["A"] == [d0, d1]
    assert not np.asarray(res.stuck).any()


def test_general_three_cycle_goes_stuck():
    # a directed 3-ring has no mutual edge: the device pass flags it stuck
    # for the host oracle instead of resolving it wrong
    d1, d2, d3 = Dot(1, 1), Dot(2, 1), Dot(3, 1)
    args = [(d1, ["A"], {d3}), (d2, ["A"], {d1}), (d3, ["A"], {d2})]
    _, count, res = resolver_per_key_order(args, functional=False)
    assert count == 0
    assert np.asarray(res.stuck).all()


def test_general_three_way_mutual_conflict_collapses():
    # k-way mutual visibility (all proposals saw each other) is one SCC even
    # when not every pair is linked: 0<->2 and 1<->2 connect {0,1,2} through
    # the mutual-edge component pass
    d1, d2, d3 = Dot(1, 1), Dot(2, 1), Dot(3, 1)
    args = [(d1, ["A"], {d3}), (d2, ["A"], {d3}), (d3, ["A"], {d1, d2})]
    per_key, count, res = resolver_per_key_order(args, functional=False)
    assert count == 3
    assert not np.asarray(res.stuck).any()
    assert per_key["A"] == [d1, d2, d3]


def test_general_full_mutual_clique_collapses():
    # every pair mutually dependent (simultaneous conflicting submits on all
    # replicas): single SCC, dot-sorted execution
    dots = [Dot(pid, 1) for pid in (1, 2, 3, 4)]
    args = [(d, ["A"], set(dots) - {d}) for d in dots]
    per_key, count, res = resolver_per_key_order(args, functional=False)
    assert count == 4
    assert per_key["A"] == sorted(dots)


def test_general_resident_matches_host_staged():
    """The device-resident peel-and-compact resolver (ONE dispatch, r13)
    is bit-for-bit the host-orchestrated staged peeler: resolved/stuck
    flags, ranks of resolved rows, and the full execution order — across
    permuted DAGs, missing-blocked rows, injected cycles, and non-pow2
    batches that exercise the publish gate."""
    import jax

    from fantoch_tpu.ops.graph_resolve import (
        MISSING,
        TERMINAL,
        resolve_general_resident,
        resolve_general_staged,
    )

    rng = np.random.default_rng(3)

    def random_graph(B, W, miss_frac=0.0, cycles=0):
        keys = rng.integers(0, max(B // 8, 4), size=(B, W))
        deps = np.full((B, W), TERMINAL, dtype=np.int32)
        last: dict = {}
        for i in range(B):
            slot = 0
            for k in keys[i]:
                prev = last.get(k)
                if prev is not None and prev != i and slot < W:
                    deps[i, slot] = prev
                    slot += 1
                last[k] = i
        # permuted arrival: deps point forward as often as backward
        p = rng.permutation(B)
        inv = np.empty(B, np.int64)
        inv[p] = np.arange(B)
        deps = np.where(
            deps[inv] >= 0, p[np.clip(deps[inv], 0, B - 1)], deps[inv]
        ).astype(np.int32)
        if miss_frac:
            m = rng.random((B, W)) < miss_frac
            deps = np.where(m & (deps != TERMINAL), MISSING, deps)
        for _ in range(cycles):
            a, b, c = rng.choice(B, 3, replace=False)
            deps[a, 0], deps[b, 0], deps[c, 0] = b, c, a
        return deps

    for B, W, mf, cycles in (
        (1000, 4, 0.0, 0),
        (1000, 4, 0.05, 0),
        (2000, 2, 0.1, 4),
        (300, 1, 0.0, 3),  # non-pow2 + cycle-heavy: publish-gate corner
    ):
        deps = random_graph(B, W, mf, cycles)
        src = (1 + rng.integers(0, 5, size=B)).astype(np.int32)
        seq = np.arange(B, dtype=np.int32)
        want = resolve_general_staged(deps, src, seq, min_size=128)
        got = jax.device_get(
            resolve_general_resident(
                jnp.asarray(deps), jnp.asarray(src), jnp.asarray(seq),
                min_size=128,
            )
        )
        assert np.array_equal(np.asarray(got.resolved), want.resolved)
        assert np.array_equal(np.asarray(got.stuck), want.stuck)
        done = want.resolved
        assert np.array_equal(np.asarray(got.rank)[done], want.rank[done])
        assert np.array_equal(np.asarray(got.order), want.order)


def test_general_fast_path_matches_iterative():
    """All-backward, nothing-missing batches take the arrival-order fast
    path; its per-key order, resolved and stuck flags must match the
    iterative fallback run on the same input."""
    from fantoch_tpu.ops.graph_resolve import (
        TERMINAL,
        _resolve_general_iterative,
        resolve_general,
    )

    rng = np.random.default_rng(11)
    batch, width, nkeys = 64, 3, 5
    # distinct keys per row: every same-key pair stays transitively
    # chain-linked (no slot-budget drops), so per-key order is fully forced
    # and comparable across branches
    keys = np.stack(
        [rng.choice(nkeys, size=width, replace=False) for _ in range(batch)]
    )
    deps = np.full((batch, width), TERMINAL, dtype=np.int32)
    last: dict = {}
    for i in range(batch):
        slot = 0
        for k in keys[i]:
            prev = last.get(k)
            if prev is not None and slot < width:
                deps[i, slot] = prev
                slot += 1
            last[k] = i
    src = (1 + rng.integers(0, 3, size=batch)).astype(np.int32)
    seq = np.arange(1, batch + 1, dtype=np.int32)

    fast = resolve_general(jnp.asarray(deps), jnp.asarray(src), jnp.asarray(seq))
    assert np.asarray(fast.resolved).all() and not np.asarray(fast.stuck).any()
    assert np.asarray(fast.order).tolist() == list(range(batch))

    it_out = _resolve_general_iterative(
        jnp.asarray(deps), jnp.asarray(src), jnp.asarray(seq), 1024
    )
    it_order, it_resolved, _rank, _leader, it_stuck = it_out
    assert np.asarray(it_resolved).all() and not np.asarray(it_stuck).any()

    # per-key projected order must agree between the two branches
    def per_key(order):
        out: dict = {}
        for i in np.asarray(order).tolist():
            for k in set(keys[i].tolist()):
                out.setdefault(k, []).append(i)
        return out

    assert per_key(fast.order) == per_key(it_order)


@pytest.mark.slow
def test_general_random_vs_oracle():
    """random_adds-style graphs (mod.rs:934-1033) without 3+-cycles: every
    fully-resolvable graph matches the oracle; stuck vertices are allowed
    only when a >2-cycle exists."""
    rng = random.Random(3)
    possible_keys = ["A", "B", "C", "D"]
    for trial in range(20):
        n = 2
        dots = [
            Dot(pid, seq) for pid in process_ids(SHARD, n) for seq in range(1, 4)
        ]
        keys = {dot: set(rng.sample(possible_keys, 2)) for dot in dots}
        deps = {dot: set() for dot in dots}
        # same-process ordering + random directed conflict edges.  Cross-
        # process picks can compose into directed 3+-cycles with no mutual
        # edge; those trials exercise the weak (stuck-prefix) branch below,
        # mutual-edge-only trials exercise the exact-match branch.
        import itertools as it

        for left, right in it.combinations(dots, 2):
            if not (keys[left] & keys[right]):
                continue
            if left.source == right.source:
                lo, hi = sorted([left, right])
                deps[hi].add(lo)
            else:
                choice = rng.randrange(3)
                if choice in (0, 2):
                    deps[left].add(right)
                if choice in (1, 2):
                    deps[right].add(left)
        args = [(dot, sorted(keys[dot]), deps[dot]) for dot in dots]
        rng.shuffle(args)
        expected, n_exec = oracle_per_key_order(n, args)
        got, n_res, res = resolver_per_key_order(args, functional=False)
        if not np.asarray(res.stuck).any():
            assert n_res == n_exec
            assert got == expected
        else:
            # soundness: everything the device did resolve must be a
            # dependency-closed prefix consistent with the oracle
            for key, dots_got in got.items():
                assert dots_got == expected[key][: len(dots_got)]


# --- staged peeler (resolve_general_staged) ---


def staged_per_key_order(args):
    from fantoch_tpu.ops.graph_resolve import resolve_general_staged

    deps, src, seq, _ = batch_arrays(args)
    res = resolve_general_staged(deps, src, seq, min_size=4)
    order = np.asarray(res.order)
    resolved = np.asarray(res.resolved)
    per_key = {}
    count = 0
    for i in order:
        if not resolved[i]:
            continue
        count += 1
        dot, keys, _ = args[i]
        for key in keys:
            per_key.setdefault(key, []).append(dot)
    return per_key, count, res


def test_staged_matches_oracle_on_dags():
    """Random acyclic multi-key graphs (incl. forward refs in batch
    order): the staged peeler fully resolves and matches the host oracle's
    per-key order."""
    rng = random.Random(5)
    possible_keys = ["A", "B", "C"]
    for _ in range(10):
        n = 2
        dots = [
            Dot(pid, seq) for pid in process_ids(SHARD, n) for seq in range(1, 6)
        ]
        keys = {dot: set(rng.sample(possible_keys, 2)) for dot in dots}
        deps = {dot: set() for dot in dots}
        ordered = sorted(dots)
        # acyclic by construction: edges only point at dot-smaller
        # vertices.  Every conflicting pair must be linked (the protocol
        # invariant) or the per-key order is legitimately unforced and the
        # oracle comparison meaningless.
        for i, dot in enumerate(ordered):
            for prev in ordered[:i]:
                if keys[dot] & keys[prev]:
                    deps[dot].add(prev)
        args = [(dot, sorted(keys[dot]), deps[dot]) for dot in dots]
        rng.shuffle(args)  # adversarial arrival: forward refs everywhere
        expected, n_exec = oracle_per_key_order(n, args)
        got, n_res, res = staged_per_key_order(args)
        assert not np.asarray(res.stuck).any()
        assert n_res == n_exec == len(args)
        assert got == expected


def test_staged_missing_blocks_dependents_only():
    a, b, c, d = Dot(1, 1), Dot(1, 2), Dot(2, 1), Dot(2, 2)
    ghost = Dot(3, 9)  # never added
    args = [
        (a, ["A"], {ghost}),   # missing-blocked
        (b, ["A"], {a}),       # transitively blocked
        (c, ["B"], set()),
        (d, ["B"], {c}),
    ]
    got, count, res = staged_per_key_order(args)
    assert count == 2
    assert got == {"B": [c, d]}
    assert not np.asarray(res.stuck).any()  # blocked, not stuck


def test_staged_cycles_surface_as_stuck():
    d1, d2, d3 = Dot(1, 1), Dot(2, 1), Dot(3, 1)
    e = Dot(1, 2)
    args = [
        (d1, ["A"], {d3}),
        (d2, ["A"], {d1}),
        (d3, ["A"], {d2}),  # 3-ring
        (e, ["A"], {d1}),   # depends on the ring: unresolved, not stuck
    ]
    got, count, res = staged_per_key_order(args)
    assert count == 0
    stuck = np.asarray(res.stuck)
    assert stuck[:3].all()
    # e is neither resolved nor missing-blocked; it waits on the stuck ring
    assert stuck[3]


def test_staged_deep_alternating_chain():
    """A deep chain alternating between two sources (the depth-2187 shape
    that defeats the fixed-budget relaxation) fully resolves."""
    depth = 3000
    dots = [Dot(1 + (i % 2), 1 + i // 2) for i in range(depth)]
    args = [
        (dot, ["K"], {dots[i - 1]} if i else set())
        for i, dot in enumerate(dots)
    ]
    got, count, res = staged_per_key_order(args)
    assert count == depth
    assert got == {"K": dots}
