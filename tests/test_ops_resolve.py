"""Batched TPU graph resolver vs the host Tarjan oracle.

The resolver (fantoch_tpu/ops/graph_resolve.py) must produce, for every
graph the oracle (executor/graph/deps_graph.py — a faithful analog of
fantoch_ps/src/executor/graph/) fully executes, the identical per-key
execution order.  Graph families mirror the reference's executor tests
(fantoch_ps/src/executor/graph/mod.rs:713-1045): chains, cycles, rho
shapes, randomized dep graphs, plus missing-dependency blocking.
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
from fantoch_tpu.core.ids import process_ids
from fantoch_tpu.executor.graph.deps_graph import DependencyGraph
from fantoch_tpu.ops.graph_resolve import (
    MISSING,
    TERMINAL,
    resolve_functional,
    resolve_general,
)
from fantoch_tpu.protocol.common.graph_deps import Dependency

TIME = RunTime()
SHARD = 0


def make_cmd(dot, keys):
    return Command.from_keys(
        Rifl(dot.source, dot.sequence), SHARD, {k: (KVOp.put(""),) for k in keys}
    )


def oracle_per_key_order(n, args):
    """Feed (dot, keys, dep_dots) to the oracle graph; returns {key: [dot]}."""
    graph = DependencyGraph(1, SHARD, Config(n, 1))
    executed = []
    for dot, keys, dep_dots in args:
        deps = [Dependency(d, frozenset({SHARD})) for d in dep_dots]
        graph.handle_add(dot, make_cmd(dot, keys), deps, TIME)
        executed.extend(graph.commands_to_execute())
    order = {}
    for cmd in executed:
        dot = Dot(cmd.rifl.source, cmd.rifl.sequence)
        for key in cmd.keys(SHARD):
            order.setdefault(key, []).append(dot)
    return order, len(executed)


def batch_arrays(args):
    """(dot, keys, dep_dots) list -> (dep or deps, dot_src, dot_seq, slot map).

    Dots absent from the batch map to MISSING (they are neither executed nor
    committed here — graph_resolve.py's pending analog)."""
    slot = {dot: i for i, (dot, _, _) in enumerate(args)}
    width = max((len(d) for _, _, d in args), default=1) or 1
    deps = np.full((len(args), width), TERMINAL, dtype=np.int32)
    for i, (dot, _, dep_dots) in enumerate(args):
        for j, d in enumerate(sorted(dep_dots)):
            if d == dot:
                continue  # self-dependency pruned (tarjan.py:129)
            deps[i, j] = slot.get(d, MISSING)
    src = np.array([d.source for d, _, _ in args], dtype=np.int32)
    seq = np.array([d.sequence for d, _, _ in args], dtype=np.int32)
    return deps, src, seq, slot


def resolver_per_key_order(args, functional):
    deps, src, seq, _ = batch_arrays(args)
    if functional:
        assert deps.shape[1] == 1
        res = resolve_functional(jnp.asarray(deps[:, 0]), jnp.asarray(src), jnp.asarray(seq))
    else:
        res = resolve_general(jnp.asarray(deps), jnp.asarray(src), jnp.asarray(seq))
    order = np.asarray(res.order)
    resolved = np.asarray(res.resolved)
    per_key = {}
    count = 0
    for i in order:
        if not resolved[i]:
            continue
        count += 1
        dot, keys, _ = args[i]
        for key in keys:
            per_key.setdefault(key, []).append(dot)
    return per_key, count, res


def assert_matches_oracle(n, args, functional):
    expected, n_exec = oracle_per_key_order(n, args)
    got, n_res, _ = resolver_per_key_order(args, functional)
    assert n_res == n_exec
    assert got == expected


# --- functional (out-degree <= 1) ---


def test_chain_ranks():
    dots = [Dot(1, s) for s in range(1, 6)]
    args = [(dots[0], ["A"], set())] + [
        (dots[i], ["A"], {dots[i - 1]}) for i in range(1, 5)
    ]
    _, _, res = resolver_per_key_order(args, functional=True)
    assert np.asarray(res.rank).tolist() == [0, 1, 2, 3, 4]
    assert np.asarray(res.resolved).all()
    assert_matches_oracle(1, args, functional=True)


def test_two_cycle():
    # the reference's `test_simple` (mod.rs:713-754): 2-cycle executes
    # together, dot-sorted
    d0, d1 = Dot(1, 1), Dot(2, 1)
    args = [(d0, ["A"], {d1}), (d1, ["A"], {d0})]
    per_key, count, res = resolver_per_key_order(args, functional=True)
    assert count == 2
    assert per_key["A"] == [d0, d1]
    assert np.asarray(res.on_cycle).all()
    assert np.asarray(res.leader).tolist() == [0, 0]
    assert_matches_oracle(2, args, functional=True)


def test_rho_shape():
    # 3-cycle at the oldest end, chain of 4 flowing into it
    cyc = [Dot(1, 1), Dot(2, 1), Dot(3, 1)]
    tail = [Dot(1, s) for s in range(2, 6)]
    args = [
        (cyc[0], ["A"], {cyc[2]}),
        (cyc[1], ["A"], {cyc[0]}),
        (cyc[2], ["A"], {cyc[1]}),
        (tail[0], ["A"], {cyc[2]}),
    ] + [(tail[i], ["A"], {tail[i - 1]}) for i in range(1, 4)]
    per_key, count, res = resolver_per_key_order(args, functional=True)
    assert count == 7
    assert per_key["A"] == sorted(cyc) + tail
    assert np.asarray(res.on_cycle).tolist() == [True] * 3 + [False] * 4
    assert_matches_oracle(3, args, functional=True)


def test_missing_blocks_tail():
    d1, d2, d3 = Dot(1, 1), Dot(1, 2), Dot(1, 3)
    # d1 depends on an uncommitted dot; d2, d3 chain behind it
    args = [(d1, ["A"], {Dot(2, 9)}), (d2, ["A"], {d1}), (d3, ["A"], {d2})]
    _, count, res = resolver_per_key_order(args, functional=True)
    assert count == 0
    assert not np.asarray(res.resolved).any()


def test_executed_dep_pruned():
    # a dep already covered by the executed clock arrives pruned (TERMINAL):
    # the vertex is immediately executable (tarjan.rs:131-136)
    d1 = Dot(1, 2)
    res = resolve_functional(
        jnp.asarray([TERMINAL], dtype=jnp.int32),
        jnp.asarray([d1.source], dtype=jnp.int32),
        jnp.asarray([d1.sequence], dtype=jnp.int32),
    )
    assert np.asarray(res.resolved).all()
    assert np.asarray(res.rank).tolist() == [0]


def random_functional_args(n, keys, cmds_per_key, rng, cycle_prob=0.5):
    """Per-key chains with an optional cycle at the oldest end — the shape
    sequential KeyDeps + concurrent proposals actually produce."""
    args = []
    seq_by_pid = {pid: 0 for pid in process_ids(SHARD, n)}

    def next_dot():
        pid = rng.choice(list(seq_by_pid))
        seq_by_pid[pid] += 1
        return Dot(pid, seq_by_pid[pid])

    for key in keys:
        chain = [next_dot() for _ in range(cmds_per_key)]
        if len(chain) >= 2 and rng.random() < cycle_prob:
            cyc_len = rng.randint(2, min(4, len(chain)))
            for i in range(cyc_len):
                args.append((chain[i], [key], {chain[(i - 1) % cyc_len]}))
            start = cyc_len
        else:
            args.append((chain[0], [key], set()))
            start = 1
        for i in range(start, len(chain)):
            args.append((chain[i], [key], {chain[i - 1]}))
    rng.shuffle(args)
    return args


def test_random_functional_vs_oracle():
    rng = random.Random(7)
    for trial in range(20):
        args = random_functional_args(
            n=3, keys=["A", "B", "C"], cmds_per_key=rng.randint(1, 8), rng=rng
        )
        # oracle needs deps to exist eventually; feeding all args in the
        # shuffled order executes everything
        assert_matches_oracle(3, args, functional=True)


# --- general (multi-key, out-degree D) ---


def test_general_chain_and_merge():
    a, b, c, d = Dot(1, 1), Dot(1, 2), Dot(2, 1), Dot(2, 2)
    # two chains merging into d (multi-key command)
    args = [
        (a, ["A"], set()),
        (b, ["A"], {a}),
        (c, ["B"], set()),
        (d, ["A", "B"], {b, c}),
    ]
    assert_matches_oracle(2, args, functional=False)


def test_general_two_cycle_collapse():
    d0, d1 = Dot(1, 1), Dot(2, 1)
    args = [(d0, ["A"], {d1}), (d1, ["A"], {d0})]
    per_key, count, res = resolver_per_key_order(args, functional=False)
    assert count == 2
    assert per_key["A"] == [d0, d1]
    assert not np.asarray(res.stuck).any()


def test_general_three_cycle_goes_stuck():
    # a directed 3-ring has no mutual edge: the device pass flags it stuck
    # for the host oracle instead of resolving it wrong
    d1, d2, d3 = Dot(1, 1), Dot(2, 1), Dot(3, 1)
    args = [(d1, ["A"], {d3}), (d2, ["A"], {d1}), (d3, ["A"], {d2})]
    _, count, res = resolver_per_key_order(args, functional=False)
    assert count == 0
    assert np.asarray(res.stuck).all()


def test_general_three_way_mutual_conflict_collapses():
    # k-way mutual visibility (all proposals saw each other) is one SCC even
    # when not every pair is linked: 0<->2 and 1<->2 connect {0,1,2} through
    # the mutual-edge component pass
    d1, d2, d3 = Dot(1, 1), Dot(2, 1), Dot(3, 1)
    args = [(d1, ["A"], {d3}), (d2, ["A"], {d3}), (d3, ["A"], {d1, d2})]
    per_key, count, res = resolver_per_key_order(args, functional=False)
    assert count == 3
    assert not np.asarray(res.stuck).any()
    assert per_key["A"] == [d1, d2, d3]


def test_general_full_mutual_clique_collapses():
    # every pair mutually dependent (simultaneous conflicting submits on all
    # replicas): single SCC, dot-sorted execution
    dots = [Dot(pid, 1) for pid in (1, 2, 3, 4)]
    args = [(d, ["A"], set(dots) - {d}) for d in dots]
    per_key, count, res = resolver_per_key_order(args, functional=False)
    assert count == 4
    assert per_key["A"] == sorted(dots)


def test_general_random_vs_oracle():
    """random_adds-style graphs (mod.rs:934-1033) without 3+-cycles: every
    fully-resolvable graph matches the oracle; stuck vertices are allowed
    only when a >2-cycle exists."""
    rng = random.Random(3)
    possible_keys = ["A", "B", "C", "D"]
    for trial in range(20):
        n = 2
        dots = [
            Dot(pid, seq) for pid in process_ids(SHARD, n) for seq in range(1, 4)
        ]
        keys = {dot: set(rng.sample(possible_keys, 2)) for dot in dots}
        deps = {dot: set() for dot in dots}
        # same-process ordering + random directed conflict edges.  Cross-
        # process picks can compose into directed 3+-cycles with no mutual
        # edge; those trials exercise the weak (stuck-prefix) branch below,
        # mutual-edge-only trials exercise the exact-match branch.
        import itertools as it

        for left, right in it.combinations(dots, 2):
            if not (keys[left] & keys[right]):
                continue
            if left.source == right.source:
                lo, hi = sorted([left, right])
                deps[hi].add(lo)
            else:
                choice = rng.randrange(3)
                if choice in (0, 2):
                    deps[left].add(right)
                if choice in (1, 2):
                    deps[right].add(left)
        args = [(dot, sorted(keys[dot]), deps[dot]) for dot in dots]
        rng.shuffle(args)
        expected, n_exec = oracle_per_key_order(n, args)
        got, n_res, res = resolver_per_key_order(args, functional=False)
        if not np.asarray(res.stuck).any():
            assert n_res == n_exec
            assert got == expected
        else:
            # soundness: everything the device did resolve must be a
            # dependency-closed prefix consistent with the oracle
            for key, dots_got in got.items():
                assert dots_got == expected[key][: len(dots_got)]
