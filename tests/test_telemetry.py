"""Live telemetry plane: windowed series (observability/timeseries.py),
Prometheus exposition + the profile trigger (observability/exposition.py),
the sim/run emit wiring, and the bench.py --regress perf gate.

Key properties pinned here:
- same-seed sim runs emit byte-identical telemetry series, with and
  without a FaultPlan (the PR-2 determinism contract);
- the series reader tolerates torn tails and ring rotation; empty
  windows emit no stale histogram percentiles;
- exposition text round-trips through the strict parser (well-formed
  # TYPE lines, cumulative buckets ending at +Inf);
- the regression gate trips on an injected 2x latency and REFUSES
  cross-definition comparisons instead of ratioing them.
"""

import asyncio
import json
import urllib.request

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.core.metrics import Histogram
from fantoch_tpu.core.timing import SimTime
from fantoch_tpu.observability.exposition import (
    MetricsServer,
    parse_prometheus,
    render_prometheus,
)
from fantoch_tpu.observability.timeseries import (
    SeriesWriter,
    latest_windows,
    read_series,
)
from fantoch_tpu.protocol import EPaxos
from fantoch_tpu.sim import Runner
from fantoch_tpu.sim.faults import FaultPlan


# --- SeriesWriter / reader units ---


def test_series_rates_and_hist_windows(tmp_path):
    """Counters rate over the realized window; histograms snapshot only
    the window's delta samples."""
    path = str(tmp_path / "s.jsonl")
    clock = SimTime()
    writer = SeriesWriter(path, clock, window_ms=1000)
    hist = Histogram()
    hist.increment(10, 4)
    clock.add_millis(1000)
    first = writer.emit("p1", {"submitted": 100}, hists={"lat": hist})
    assert first["rate"]["submitted"] == 100.0
    assert first["h"]["lat"]["count"] == 4 and first["h"]["lat"]["p50"] == 10
    # second window: 60 more submissions over 2s => 30/s; 2 new samples
    # at value 50 => the window p50 is 50, not the cumulative 10
    hist.increment(50, 2)
    clock.add_millis(2000)
    second = writer.emit("p1", {"submitted": 160}, hists={"lat": hist})
    assert second["rate"]["submitted"] == 30.0
    assert second["h"]["lat"]["count"] == 2 and second["h"]["lat"]["p50"] == 50
    writer.close()
    windows = read_series(path)
    assert [w["seq"] for w in windows] == [0, 1]
    assert windows == [first, second]


def test_series_empty_window_emits_no_stale_hist(tmp_path):
    path = str(tmp_path / "s.jsonl")
    clock = SimTime()
    writer = SeriesWriter(path, clock, window_ms=1000)
    hist = Histogram()
    hist.increment(5)
    clock.add_millis(1000)
    writer.emit("p1", {"submitted": 1}, hists={"lat": hist})
    # nothing happened this window: no samples, zero rate, empty "h"
    clock.add_millis(1000)
    quiet = writer.emit("p1", {"submitted": 1}, hists={"lat": hist})
    assert quiet["h"] == {}
    assert quiet["rate"]["submitted"] == 0.0
    writer.close()
    assert len(read_series(path)) == 2


def test_series_reader_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "s.jsonl")
    clock = SimTime()
    writer = SeriesWriter(path, clock, window_ms=1000)
    for i in range(3):
        clock.add_millis(1000)
        writer.emit("p1", {"submitted": i})
    writer.close()
    whole = read_series(path)
    assert len(whole) == 3
    # crash mid-write: truncate the final line — the prefix still parses
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: raw.rfind(b'{"ctr"') + 17])
    torn = read_series(path)
    assert torn == whole[:2]
    # an empty live file (crash right after rotation) reads cleanly too
    open(path, "wb").close()
    assert read_series(path) == []


def test_series_ring_rotation(tmp_path):
    path = str(tmp_path / "s.jsonl")
    clock = SimTime()
    writer = SeriesWriter(path, clock, window_ms=1000, ring_windows=4)
    for i in range(10):
        clock.add_millis(1000)
        writer.emit("p1", {"submitted": i})
    writer.close()
    windows = read_series(path)
    # two generations: at most 2*ring on disk, nothing misparses, the
    # latest window survived with cumulative counters intact
    assert 4 <= len(windows) <= 8
    last = latest_windows(windows)["p1"]
    assert last["ctr"]["submitted"] == 9
    assert last["seq"] == 9


def test_series_fresh_writer_drops_stale_generation(tmp_path):
    """A restarted writer on the same path must not let a previous
    run's rotated generation (higher seqs) shadow the new run's windows
    in latest_windows."""
    path = str(tmp_path / "s.jsonl")
    clock = SimTime()
    writer = SeriesWriter(path, clock, window_ms=1000, ring_windows=3)
    for i in range(7):
        clock.add_millis(1000)
        writer.emit("p1", {"submitted": i})
    writer.close()
    assert (tmp_path / "s.jsonl.1").exists()
    fresh_clock = SimTime()
    fresh = SeriesWriter(path, fresh_clock, window_ms=1000, ring_windows=3)
    fresh_clock.add_millis(1000)
    fresh.emit("p1", {"submitted": 0})
    fresh.close()
    last = latest_windows(read_series(path))["p1"]
    assert last["seq"] == 0 and last["ctr"]["submitted"] == 0


# --- sim timeline determinism ---


def _sim_run(path, seed=7, fault_plan=None, commands=4, reorder=False):
    config = Config(
        n=3,
        f=1,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        telemetry_interval_ms=500,
    )
    planet = Planet.new("gcp")
    regions = sorted(planet.regions())[:3]
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=commands,
        payload_size=1,
    )
    runner = Runner(
        EPaxos,
        planet,
        config,
        workload,
        clients_per_process=2,
        process_regions=list(regions),
        client_regions=list(regions),
        seed=seed,
        fault_plan=fault_plan,
        telemetry_path=str(path),
    )
    if reorder:
        runner.reorder_messages()
    runner.run(extra_sim_time_ms=1000)


def test_sim_same_seed_series_byte_identical(tmp_path):
    _sim_run(tmp_path / "a.jsonl")
    _sim_run(tmp_path / "b.jsonl")
    a = (tmp_path / "a.jsonl").read_bytes()
    assert a == (tmp_path / "b.jsonl").read_bytes()
    assert a, "series must not be empty"
    windows = read_series(str(tmp_path / "a.jsonl"))
    assert {w["src"] for w in windows} == {"p1", "p2", "p3", "clients"}
    clients = latest_windows(windows)["clients"]
    assert clients["ctr"]["replied"] == 3 * 2 * 4
    # non-vacuous: a telemetry-visible perturbation changes the bytes.
    # (A bare seed change is NOT guaranteed visible — the PR-5 lesson:
    # in the closed-loop sim it only picks which keys conflict — so
    # perturb with reorder jitter, which shifts the latency windows.)
    _sim_run(tmp_path / "c.jsonl", reorder=True)
    assert a != (tmp_path / "c.jsonl").read_bytes()


def test_sim_same_seed_series_byte_identical_under_faults(tmp_path):
    plan = FaultPlan(seed=3, max_sim_time_ms=300_000).with_loss(0.1)
    _sim_run(tmp_path / "a.jsonl", fault_plan=plan, commands=3)
    _sim_run(tmp_path / "b.jsonl", fault_plan=plan, commands=3)
    a = (tmp_path / "a.jsonl").read_bytes()
    assert a == (tmp_path / "b.jsonl").read_bytes()
    assert read_series(str(tmp_path / "a.jsonl")), "faulted run still emits"


# --- exposition ---


def test_prometheus_roundtrip_and_wellformedness():
    hist = Histogram()
    for value, count in ((1, 3), (7, 2), (900, 1)):
        hist.increment(value, count)
    text = render_prometheus(
        {"submitted": 42, "device_busy_ms": 1.5},
        {"queue_depth": 3},
        {"latency_ms": hist},
        labels={"pid": "1"},
    )
    parsed = parse_prometheus(text)  # strict: raises on malformation
    labels = (("pid", "1"),)
    assert parsed["fantoch_submitted_total"][labels] == 42
    assert parsed["fantoch_device_busy_ms_total"][labels] == 1.5
    assert parsed["fantoch_queue_depth"][labels] == 3
    assert parsed["fantoch_latency_ms_count"][labels] == 6
    assert parsed["fantoch_latency_ms_sum"][labels] == 3 + 14 + 900
    buckets = parsed["fantoch_latency_ms_bucket"]
    inf = next(v for k, v in buckets.items() if dict(k)["le"] == "+Inf")
    assert inf == 6
    le1 = next(v for k, v in buckets.items() if dict(k)["le"] == "1")
    assert le1 == 3
    # cumulative monotonicity across the bucket ladder
    ordered = sorted(
        (float(dict(k)["le"].replace("+Inf", "inf")), v)
        for k, v in buckets.items()
    )
    assert all(a[1] <= b[1] for a, b in zip(ordered, ordered[1:]))


def test_prometheus_parser_rejects_malformed():
    with pytest.raises(ValueError):
        parse_prometheus("fantoch_x_total 1\n")  # no # TYPE line
    with pytest.raises(ValueError):
        parse_prometheus(
            "# TYPE fantoch_h histogram\n"
            'fantoch_h_bucket{le="1"} 5\n'
            'fantoch_h_bucket{le="2"} 3\n'  # non-cumulative
        )
    with pytest.raises(ValueError):
        parse_prometheus(
            "# TYPE fantoch_h histogram\n"
            'fantoch_h_bucket{le="1"} 1\n'  # no +Inf bucket
        )


def test_metrics_server_scrape_roundtrip():
    """A live endpoint serves the sample; the scrape parses strictly."""

    def sample():
        hist = Histogram()
        hist.increment(4, 2)
        return {"submitted": 9}, {"queue_depth": 1}, {"lat": hist}

    async def scenario():
        server = MetricsServer(sample, 0, labels={"pid": "7"})
        await server.start()
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            loop = asyncio.get_running_loop()
            text = await loop.run_in_executor(
                None,
                lambda: urllib.request.urlopen(url, timeout=5).read().decode(),
            )
            # unknown paths 404 without killing the server
            status = await loop.run_in_executor(
                None, lambda: _status(f"http://127.0.0.1:{server.port}/nope")
            )
            return text, status
        finally:
            await server.stop()

    def _status(url):
        try:
            urllib.request.urlopen(url, timeout=5)
            return 200
        except urllib.error.HTTPError as exc:
            return exc.code

    text, status = asyncio.run(scenario())
    parsed = parse_prometheus(text)
    assert parsed["fantoch_submitted_total"][(("pid", "7"),)] == 9
    assert status == 404


# --- run-layer wiring (fast localhost row) ---


def test_localhost_cluster_emits_series_and_exposition(tmp_path):
    from fantoch_tpu.run.harness import run_localhost_cluster

    scraped = {}

    async def scraper(runtimes):
        await asyncio.sleep(0.2)
        port = runtimes[1].metrics_port
        loop = asyncio.get_running_loop()
        scraped["text"] = await loop.run_in_executor(
            None,
            lambda: urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode(),
        )

    config = Config(
        n=3,
        f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        telemetry_interval_ms=100,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=15,
        payload_size=1,
    )
    asyncio.run(
        run_localhost_cluster(
            EPaxos,
            config,
            workload,
            clients_per_process=2,
            observe_dir=str(tmp_path),
            metrics_ports={1: 0},
            chaos=scraper,
        )
    )
    parsed = parse_prometheus(scraped["text"])
    assert "fantoch_submitted_total" in parsed
    assert "fantoch_replied_total" in parsed
    total_replied = 0
    for pid in (1, 2, 3):
        windows = read_series(str(tmp_path / f"telemetry_p{pid}.jsonl"))
        assert windows, f"p{pid} emitted no windows"
        last = latest_windows(windows)[f"p{pid}"]
        assert {"submitted", "replied", "shed_submissions"} <= set(last["ctr"])
        assert "queue_depth" in last["g"]
        total_replied += last["ctr"]["replied"]
    assert total_replied == 3 * 2 * 15
    client_last = latest_windows(
        read_series(str(tmp_path / "telemetry_clients_p1.jsonl"))
    )["clients"]
    assert client_last["ctr"]["replied"] == 2 * 15
    # the legacy pickle snapshot still rides the same cadence (one
    # writer) and still reads back
    from fantoch_tpu.run.observe import read_metrics_snapshot

    snap = read_metrics_snapshot(str(tmp_path / "metrics_p1.gz"))
    assert snap.workers, "unified writer stopped writing the snapshot"


# --- the perf-regression gate ---


def _bench():
    import bench

    return bench


def test_regress_trips_on_2x_latency():
    bench = _bench()
    old = {
        "metric": "epaxos_1m_cmds_50pct_conflict_graph_resolve_p50",
        "value": 3.0,
        "platform": "cpu",
        "serving_newt_cmds_per_s": 40_000,
        "serving_newt_definition": "d",
    }
    new = dict(old, value=6.0)
    result = bench.regress_check(
        bench_record(bench, new), bench_record(bench, old)
    )
    assert [v[0] for v in result["violations"]] == [old["metric"]]
    assert not result["refused"]
    # within the band: no violation
    ok = bench.regress_check(
        bench_record(bench, dict(old, value=4.0)), bench_record(bench, old)
    )
    assert not ok["violations"]


def bench_record(bench, rec):
    """Re-key the headline value the way load_bench_record does."""
    rec = dict(rec)
    rec[rec["metric"]] = rec["value"]
    return rec


def test_regress_throughput_direction():
    bench = _bench()
    old = {"metric": "m", "platform": "cpu", "serving_newt_cmds_per_s": 40_000,
           "serving_newt_definition": "d"}
    dropped = dict(old, serving_newt_cmds_per_s=20_000)
    result = bench.regress_check(dropped, old)
    assert [v[0] for v in result["violations"]] == ["serving_newt_cmds_per_s"]


def test_regress_refuses_definition_mismatch():
    bench = _bench()
    old = {"metric": "m", "platform": "cpu", "serving_newt_cmds_per_s": 40_000,
           "serving_newt_definition": "pipelined (r07)"}
    new = dict(old, serving_newt_cmds_per_s=5,
               serving_newt_definition="sync (r05)")
    result = bench.regress_check(new, old)
    assert not result["violations"], "refused keys must never be ratioed"
    assert any(key == "serving_newt_cmds_per_s" for key, _r in result["refused"])


def test_regress_refuses_platform_mismatch():
    bench = _bench()
    old = {"metric": "m", "platform": "tpu", "serving_newt_cmds_per_s": 1,
           "serving_newt_definition": "d"}
    new = dict(old, platform="cpu")
    result = bench.regress_check(new, old)
    assert not result["compared"] and not result["violations"]
    assert result["refused"] and "platform" in result["refused"][0][1]


def test_regress_loads_wrapped_trajectory_records(tmp_path):
    """BENCH_r0N.json wrappers ({"parsed": record}) and raw records both
    load; the headline value is re-keyed under its metric name."""
    bench = _bench()
    record = {"metric": "graph_resolve_p50", "value": 3.0, "platform": "cpu"}
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps(record))
    wrapped = tmp_path / "wrapped.json"
    wrapped.write_text(json.dumps({"n": 4, "rc": 0, "parsed": record}))
    for path in (raw, wrapped):
        loaded = bench.load_bench_record(str(path))
        assert loaded["graph_resolve_p50"] == 3.0
    with pytest.raises(ValueError):
        empty = tmp_path / "empty.json"
        empty.write_text(json.dumps({"n": 1, "rc": 1, "tail": "boom"}))
        bench.load_bench_record(str(empty))
