"""The shared dispatch/drain pipeline core (run/pipeline.py), tested
host-only: a fake driver stands in for the device planes so depth
semantics, flush ordering, the ingest ring's reuse discipline, and the
busy/idle counters are covered on every jax pin (the real-driver twin
lives in tests/test_device_runner.py, which needs jax >= 0.5)."""

import numpy as np
import pytest

from fantoch_tpu.run.pipeline import (
    DEFAULT_PIPELINE_DEPTH,
    ENV_PIPELINE_DEPTH,
    IngestRing,
    PipelineCore,
    resolve_pipeline_depth,
)


class _FakeDriver(PipelineCore):
    """dispatch() records the batch; drain() 'executes' it.  Tokens are
    (round_index, batch); results are (round_index, item) tuples — enough
    to assert ordering and lag exactly."""

    def __init__(self, flush_at=None):
        self.batch_size = 8
        self._init_pipeline()
        self._round = 0
        self.drained = []
        self.flush_at = flush_at or set()

    def dispatch(self, batch):
        tok = (self._round, list(batch))
        self._round += 1
        return tok

    def drain(self, tok):
        r, batch = tok
        self.drained.append(r)
        return [(r, item) for item in batch]

    def _pipeline_flush_needed(self, batch):
        return any(item in self.flush_at for item in batch)


def test_resolve_depth_precedence(monkeypatch):
    monkeypatch.delenv(ENV_PIPELINE_DEPTH, raising=False)
    assert resolve_pipeline_depth() == DEFAULT_PIPELINE_DEPTH == 1
    monkeypatch.setenv(ENV_PIPELINE_DEPTH, "3")
    assert resolve_pipeline_depth() == 3

    class Cfg:
        serving_pipeline_depth = 2

    # config beats env; explicit beats config
    assert resolve_pipeline_depth(None, Cfg()) == 2
    assert resolve_pipeline_depth(5, Cfg()) == 5

    class CfgNone:
        serving_pipeline_depth = None

    assert resolve_pipeline_depth(None, CfgNone()) == 3  # falls to env
    with pytest.raises(ValueError):
        resolve_pipeline_depth(0)


def test_config_serving_pipeline_depth_validates():
    from fantoch_tpu.core import Config

    assert Config(3, 1, serving_pipeline_depth=2).serving_pipeline_depth == 2
    with pytest.raises(ValueError):
        Config(3, 1, serving_pipeline_depth=0)


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_depth_k_lag_and_order(depth):
    """step_pipelined returns results exactly ``depth`` calls late, in
    dispatch order, and flush_pipeline retires the tail oldest-first."""
    d = _FakeDriver()
    d.pipeline_depth = depth
    rounds = [[f"r{i}a", f"r{i}b"] for i in range(6)]
    outs = [d.step_pipelined(b) for b in rounds]
    # the first `depth` calls return nothing; call k returns round k-depth
    for k, out in enumerate(outs):
        if k < depth:
            assert out == []
        else:
            r = k - depth
            assert out == [(r, item) for item in rounds[r]]
    assert len(d._inflight) == depth and d.has_outstanding
    tail = d.flush_pipeline()
    expected = [
        (r, item) for r in range(6 - depth, 6) for item in rounds[r]
    ]
    assert tail == expected
    assert not d.has_outstanding and d._undrained == 0
    assert d.drained == sorted(d.drained)  # strict FIFO retirement
    assert d.pipelined_rounds == 5  # every dispatch after the first


def test_step_flushes_pipeline_first():
    """A synchronous step retires every in-flight round before its own,
    so mixing step/step_pipelined can never reorder results."""
    d = _FakeDriver()
    d.pipeline_depth = 2
    assert d.step_pipelined(["a"]) == []
    assert d.step_pipelined(["b"]) == []
    out = d.step(["c"])
    assert out == [(0, "a"), (1, "b"), (2, "c")]
    assert not d.has_outstanding


def test_flush_needed_retires_all_before_dispatch():
    """When a dispatch would rebase state in-flight rounds reference,
    every outstanding round drains FIRST and the new round dispatches
    into an empty pipeline (the window-rebase early-flush contract)."""
    d = _FakeDriver(flush_at={"RESET"})
    d.pipeline_depth = 3
    for i in range(3):
        assert d.step_pipelined([f"x{i}"]) == []
    out = d.step_pipelined(["RESET"])
    assert out == [(0, "x0"), (1, "x1"), (2, "x2")]
    assert len(d._inflight) == 1  # the RESET round went in flight
    assert d.flush_pipeline() == [(3, "RESET")]


def test_counters_sane_and_idle_frac_bounded():
    d = _FakeDriver()
    d.pipeline_depth = 2
    for i in range(5):
        d.step_pipelined([f"v{i}", f"w{i}"])
    d.flush_pipeline()
    c = d.device_counters()
    assert c["device_dispatches"] == 5
    assert c["device_dispatched_rows"] == 10
    assert c["device_batch_capacity"] == 5 * d.batch_size
    assert c["device_pipeline_depth"] == 2
    assert c["device_pipelined_rounds"] == 4
    assert 0.0 <= c["device_idle_frac"] <= 1.0
    assert c["device_busy_ms"] <= c["device_span_ms"] + 1e-6
    assert c["device_dispatch_ms"] >= 0 and c["device_drain_ms"] >= 0


def test_counters_snapshot_mid_flight():
    """device_counters must be readable with rounds still in flight (the
    periodic metrics task does) without perturbing the instrument."""
    d = _FakeDriver()
    d.pipeline_depth = 2
    d.step_pipelined(["a"])
    c = d.device_counters()
    assert c["device_dispatches"] == 1
    assert 0.0 <= c["device_idle_frac"] <= 1.0
    assert d.flush_pipeline() == [(0, "a")]
    c2 = d.device_counters()
    assert c2["device_busy_ms"] <= c2["device_span_ms"] + 1e-6


def test_ingest_ring_cycles_and_resets():
    ring = IngestRing(
        3,
        (
            ("key", (4, 2), np.int32, -1),
            ("src", (4,), np.int32, 0),
        ),
    )
    assert ring.slots == 3
    key0, src0 = ring.acquire()
    key0[0, 0] = 7
    src0[1] = 9
    key1, _src1 = ring.acquire()
    assert key1 is not key0  # distinct slots back to back
    _ = ring.acquire()
    key0b, src0b = ring.acquire()  # wrapped: slot 0 again, reset
    assert key0b is key0 and src0b is src0
    assert (key0b == -1).all() and (src0b == 0).all()


def test_ingest_ring_slot_never_reused_while_in_flight():
    """The driver contract: with PipelineCore._staging (the production
    ring sizing: slots = depth + 1), the staging columns of any round
    still in flight are never handed out again — the zero-copy-alias
    safety argument for jnp.asarray staging."""

    class RingDriver(_FakeDriver):
        def __init__(self):
            super().__init__()
            self.live = {}  # round -> staging array it aliases

        def dispatch(self, batch):
            (col,) = self._staging(("col", (4,), np.int64, 0))
            col[: len(batch)] = batch
            tok = (self._round, col, list(batch))
            self._round += 1
            # no OTHER in-flight round may alias this slot
            for r, other in self.live.items():
                assert other is not col, f"slot of round {r} reused in flight"
            self.live[tok[0]] = col
            return tok

        def drain(self, tok):
            r, col, batch = tok
            # the round's staging columns are untouched at drain time
            assert list(col[: len(batch)]) == batch
            del self.live[r]
            self.drained.append(r)
            return [(r, v) for v in batch]

    for depth in (1, 2, 3):
        d = RingDriver()
        d.pipeline_depth = depth
        outs = []
        for i in range(8):
            outs.extend(d.step_pipelined([10 * i + 1, 10 * i + 2]))
        outs.extend(d.flush_pipeline())
        assert [v for _r, v in outs] == [
            10 * i + j for i in range(8) for j in (1, 2)
        ]
