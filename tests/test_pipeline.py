"""The shared dispatch/drain pipeline core (run/pipeline.py), tested
host-only: a fake driver stands in for the device planes so depth
semantics, flush ordering, the ingest ring's reuse discipline, and the
busy/idle counters are covered on every jax pin (the real-driver twin
lives in tests/test_device_runner.py, which needs jax >= 0.5)."""

import numpy as np
import pytest

from fantoch_tpu.run.pipeline import (
    DEFAULT_PIPELINE_DEPTH,
    ENV_PIPELINE_DEPTH,
    IngestRing,
    PipelineCore,
    resolve_pipeline_depth,
)


class _FakeDriver(PipelineCore):
    """dispatch() records the batch; drain() 'executes' it.  Tokens are
    (round_index, batch); results are (round_index, item) tuples — enough
    to assert ordering and lag exactly."""

    def __init__(self, flush_at=None):
        self.batch_size = 8
        self._init_pipeline()
        self._round = 0
        self.drained = []
        self.flush_at = flush_at or set()

    def dispatch(self, batch):
        tok = (self._round, list(batch))
        self._round += 1
        return tok

    def drain(self, tok):
        r, batch = tok
        self.drained.append(r)
        return [(r, item) for item in batch]

    def _pipeline_flush_needed(self, batch):
        return any(item in self.flush_at for item in batch)


def test_resolve_depth_precedence(monkeypatch):
    monkeypatch.delenv(ENV_PIPELINE_DEPTH, raising=False)
    assert resolve_pipeline_depth() == DEFAULT_PIPELINE_DEPTH == 1
    monkeypatch.setenv(ENV_PIPELINE_DEPTH, "3")
    assert resolve_pipeline_depth() == 3

    class Cfg:
        serving_pipeline_depth = 2

    # config beats env; explicit beats config
    assert resolve_pipeline_depth(None, Cfg()) == 2
    assert resolve_pipeline_depth(5, Cfg()) == 5

    class CfgNone:
        serving_pipeline_depth = None

    assert resolve_pipeline_depth(None, CfgNone()) == 3  # falls to env
    with pytest.raises(ValueError):
        resolve_pipeline_depth(0)


def test_config_serving_pipeline_depth_validates():
    from fantoch_tpu.core import Config

    assert Config(3, 1, serving_pipeline_depth=2).serving_pipeline_depth == 2
    with pytest.raises(ValueError):
        Config(3, 1, serving_pipeline_depth=0)


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_depth_k_lag_and_order(depth):
    """step_pipelined returns results exactly ``depth`` calls late, in
    dispatch order, and flush_pipeline retires the tail oldest-first."""
    d = _FakeDriver()
    d.pipeline_depth = depth
    rounds = [[f"r{i}a", f"r{i}b"] for i in range(6)]
    outs = [d.step_pipelined(b) for b in rounds]
    # the first `depth` calls return nothing; call k returns round k-depth
    for k, out in enumerate(outs):
        if k < depth:
            assert out == []
        else:
            r = k - depth
            assert out == [(r, item) for item in rounds[r]]
    assert len(d._inflight) == depth and d.has_outstanding
    tail = d.flush_pipeline()
    expected = [
        (r, item) for r in range(6 - depth, 6) for item in rounds[r]
    ]
    assert tail == expected
    assert not d.has_outstanding and d._undrained == 0
    assert d.drained == sorted(d.drained)  # strict FIFO retirement
    assert d.pipelined_rounds == 5  # every dispatch after the first


def test_step_flushes_pipeline_first():
    """A synchronous step retires every in-flight round before its own,
    so mixing step/step_pipelined can never reorder results."""
    d = _FakeDriver()
    d.pipeline_depth = 2
    assert d.step_pipelined(["a"]) == []
    assert d.step_pipelined(["b"]) == []
    out = d.step(["c"])
    assert out == [(0, "a"), (1, "b"), (2, "c")]
    assert not d.has_outstanding


def test_flush_needed_retires_all_before_dispatch():
    """When a dispatch would rebase state in-flight rounds reference,
    every outstanding round drains FIRST and the new round dispatches
    into an empty pipeline (the window-rebase early-flush contract)."""
    d = _FakeDriver(flush_at={"RESET"})
    d.pipeline_depth = 3
    for i in range(3):
        assert d.step_pipelined([f"x{i}"]) == []
    out = d.step_pipelined(["RESET"])
    assert out == [(0, "x0"), (1, "x1"), (2, "x2")]
    assert len(d._inflight) == 1  # the RESET round went in flight
    assert d.flush_pipeline() == [(3, "RESET")]


def test_counters_sane_and_idle_frac_bounded():
    d = _FakeDriver()
    d.pipeline_depth = 2
    for i in range(5):
        d.step_pipelined([f"v{i}", f"w{i}"])
    d.flush_pipeline()
    c = d.device_counters()
    assert c["device_dispatches"] == 5
    assert c["device_dispatched_rows"] == 10
    assert c["device_batch_capacity"] == 5 * d.batch_size
    assert c["device_pipeline_depth"] == 2
    assert c["device_pipelined_rounds"] == 4
    assert 0.0 <= c["device_idle_frac"] <= 1.0
    assert c["device_busy_ms"] <= c["device_span_ms"] + 1e-6
    assert c["device_dispatch_ms"] >= 0 and c["device_drain_ms"] >= 0


def test_counters_snapshot_mid_flight():
    """device_counters must be readable with rounds still in flight (the
    periodic metrics task does) without perturbing the instrument."""
    d = _FakeDriver()
    d.pipeline_depth = 2
    d.step_pipelined(["a"])
    c = d.device_counters()
    assert c["device_dispatches"] == 1
    assert 0.0 <= c["device_idle_frac"] <= 1.0
    assert d.flush_pipeline() == [(0, "a")]
    c2 = d.device_counters()
    assert c2["device_busy_ms"] <= c2["device_span_ms"] + 1e-6


def test_ingest_ring_cycles_and_resets():
    ring = IngestRing(
        3,
        (
            ("key", (4, 2), np.int32, -1),
            ("src", (4,), np.int32, 0),
        ),
    )
    assert ring.slots == 3
    key0, src0 = ring.acquire()
    key0[0, 0] = 7
    src0[1] = 9
    key1, _src1 = ring.acquire()
    assert key1 is not key0  # distinct slots back to back
    _ = ring.acquire()
    key0b, src0b = ring.acquire()  # wrapped: slot 0 again, reset
    assert key0b is key0 and src0b is src0
    assert (key0b == -1).all() and (src0b == 0).all()


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_step_chained_parity_with_unbatched(depth):
    """The generic chained surfaces (base PipelineCore: S grouped
    rounds, no fusion) are bit-for-bit the unbatched loop — same results
    in the same order at every depth, chains only a grouping hint."""
    rounds = [[f"r{i}a", f"r{i}b", f"r{i}c"] for i in range(12)]
    groups = [rounds[i * 3 : (i + 1) * 3] for i in range(4)]

    plain = _FakeDriver()
    plain.pipeline_depth = depth
    expect = [r for b in rounds for r in plain.step_pipelined(b)]
    expect += plain.flush_pipeline()

    chained = _FakeDriver()
    chained.pipeline_depth = depth
    got = [r for g in groups for r in chained.step_chained_pipelined(g)]
    got += chained.flush_pipeline()
    assert got == expect
    assert chained.dispatches == plain.dispatches == 12

    sync = _FakeDriver()
    got_sync = [r for g in groups for r in sync.step_chained(g)]
    assert got_sync == expect
    assert not sync.has_outstanding


def test_ingest_knob_precedence(monkeypatch):
    """The three r16 knobs follow the one-knob rule: explicit > Config
    field > env var > default, any spelling the same knob."""
    from fantoch_tpu.run.ingest import (
        DEFAULT_INGEST_DEADLINE_MS,
        DEFAULT_SERVING_CHAIN_MAX,
        ENV_INGEST_DEADLINE_MS,
        ENV_INGEST_TARGET,
        ENV_SERVING_CHAIN_MAX,
        requested_ingest_deadline_ms,
        resolve_ingest_deadline_ms,
        resolve_ingest_target,
        resolve_serving_chain_max,
    )

    for var in (ENV_INGEST_DEADLINE_MS, ENV_INGEST_TARGET,
                ENV_SERVING_CHAIN_MAX):
        monkeypatch.delenv(var, raising=False)

    # no channel set: requested is None (opt-in surfaces stay legacy),
    # resolved falls to the defaults
    assert requested_ingest_deadline_ms() is None
    assert resolve_ingest_deadline_ms() == DEFAULT_INGEST_DEADLINE_MS
    assert resolve_ingest_target() is None
    assert resolve_serving_chain_max() == DEFAULT_SERVING_CHAIN_MAX

    monkeypatch.setenv(ENV_INGEST_DEADLINE_MS, "7.5")
    monkeypatch.setenv(ENV_INGEST_TARGET, "32")
    monkeypatch.setenv(ENV_SERVING_CHAIN_MAX, "4")
    assert requested_ingest_deadline_ms() == 7.5
    assert resolve_ingest_target() == 32
    assert resolve_serving_chain_max() == 4

    class Cfg:
        ingest_deadline_ms = 3.0
        ingest_target = 16
        serving_chain_max = 2

    # config beats env; explicit beats config
    assert requested_ingest_deadline_ms(None, Cfg()) == 3.0
    assert requested_ingest_deadline_ms(1.0, Cfg()) == 1.0
    assert resolve_ingest_target(None, Cfg()) == 16
    assert resolve_ingest_target(8, Cfg()) == 8
    assert resolve_serving_chain_max(None, Cfg()) == 2
    assert resolve_serving_chain_max(6, Cfg()) == 6

    # 0 is a valid deadline resolution (batching off), negatives are not
    assert resolve_ingest_deadline_ms(0.0) == 0.0
    with pytest.raises(ValueError):
        resolve_ingest_deadline_ms(-1.0)
    with pytest.raises(ValueError):
        resolve_ingest_target(0)
    with pytest.raises(ValueError):
        resolve_serving_chain_max(0)


def test_config_ingest_knobs_validate():
    from fantoch_tpu.core import Config

    cfg = Config(3, 1, ingest_deadline_ms=1.5, ingest_target=64,
                 serving_chain_max=4)
    assert cfg.ingest_deadline_ms == 1.5
    assert cfg.ingest_target == 64
    assert cfg.serving_chain_max == 4
    with pytest.raises(ValueError):
        Config(3, 1, ingest_deadline_ms=-0.5)
    with pytest.raises(ValueError):
        Config(3, 1, ingest_target=0)
    with pytest.raises(ValueError):
        Config(3, 1, serving_chain_max=0)


def test_batcher_release_causes():
    """The three release causes: fast (idle system, lone command), size
    (queued >= EWMA target), deadline (budget exhausted)."""
    from fantoch_tpu.run.ingest import AdaptiveIngestBatcher

    b = AdaptiveIngestBatcher(deadline_ms=2.0, max_target=1024)

    # lone closed-loop command on an idle system: immediate release
    b.note_arrivals(0.0, 1)
    release, wait = b.poll(0.0, 1, idle_system=True)
    assert release and wait is None
    b.note_release(0.0, 1)
    assert b.releases_fast == 1

    # cold EWMA: target 1, so even a busy system releases a lone command
    assert b.target() == 1
    b.note_arrivals(10.0, 1)
    release, _ = b.poll(10.0, 1)
    assert release
    b.note_release(10.0, 1)
    assert b.releases_size == 1

    # sustained 100/ms raises the target; the backlog itself goes out
    # by size
    t = 20.0
    for _ in range(50):
        t += 0.1
        b.note_arrivals(t, 10)
    assert b.target() > 1
    release, _ = b.poll(t, 500)
    assert release
    b.note_release(t, 500)
    assert b.releases_size == 2

    # a fresh below-target window holds with the remaining budget; the
    # full budget forces a deadline release
    t += 0.1
    b.note_arrivals(t, 1)
    release, wait = b.poll(t, 1)
    assert not release and 0 < wait <= 2.0
    release, wait = b.poll(t + 2.0, 1)
    assert release
    b.note_release(t + 2.0, 1)
    assert b.releases_deadline == 1

    c = b.counters()
    assert c["ingest_releases"] == 4
    assert c["ingest_arrivals"] == 2 + 500 + 1
    assert (
        c["ingest_releases_fast"] + c["ingest_releases_size"]
        + c["ingest_releases_deadline"] == c["ingest_releases"]
    )


def test_batcher_ewma_target_and_hard_reset():
    """The size target tracks expected arrivals per deadline window
    (EWMA rate x deadline, clamped), and an idle gap SNAPS the rate
    down instead of decaying it — the first command after idle must not
    inherit a stale high target."""
    from fantoch_tpu.run.ingest import AdaptiveIngestBatcher

    b = AdaptiveIngestBatcher(deadline_ms=2.0, max_target=256)
    t = 0.0
    for _ in range(200):
        t += 0.1
        b.note_arrivals(t, 10)  # 100/ms sustained
    # converged: ~100/ms * 2ms = 200 rows
    assert 150 <= b.target() <= 256
    assert b.rate_per_s() == pytest.approx(100_000.0, rel=0.15)

    # a gap past ~8 deadline windows ends the regime: the single
    # arrival after it sees target 1 at once
    b.note_arrivals(t + 1000.0, 1)
    assert b.target() == 1

    # fixed_target pins the knob regardless of the EWMA
    fixed = AdaptiveIngestBatcher(2.0, max_target=256, fixed_target=32)
    for i in range(100):
        fixed.note_arrivals(i * 0.1, 10)
    assert fixed.target() == 32

    # deadline 0 = batching off: always release, target 1
    off = AdaptiveIngestBatcher(0.0, max_target=256)
    off.note_arrivals(0.0, 5)
    assert off.target() == 1
    release, _ = off.poll(0.0, 5)
    assert release


def test_chain_autotuner_convergence():
    """Under a synthetic fixed-overhead driver (O ms host overhead per
    dispatch, C ms device time per round) the tuner doubles S while the
    per-round overhead ratio O/(S*C) exceeds grow_frac, then holds —
    and the [shrink_frac, grow_frac] hysteresis band keeps S stable."""
    from fantoch_tpu.run.ingest import ChainAutoTuner

    O, C = 1.0, 0.5  # ratio at S: (O/S)/C = 2/S
    tuner = ChainAutoTuner(chain_max=8)
    counters = [0.0, 0.0, 0.0, 0.0]  # dispatches, wall, busy, rounds

    def feed(n_dispatches):
        S = tuner.chain
        counters[0] += n_dispatches
        counters[1] += n_dispatches * O
        counters[2] += n_dispatches * S * C
        counters[3] += n_dispatches * S
        return tuner.observe(*counters)

    assert feed(8) == 1  # first observation only seeds the baseline
    seen = [feed(8) for _ in range(6)]
    # S: 1 -> 2 (ratio 2.0) -> 4 (1.0) -> 8 (0.5) -> stays (0.25 not >)
    assert seen == [2, 4, 8, 8, 8, 8]
    assert tuner.adjustments == 3

    # overhead collapses far under shrink_frac: S halves down the pow2
    # ladder (never a decrement — each chain length is a distinct
    # compiled program, so the tuner only emits pow2 values; see the
    # ChainAutoTuner docstring) — and an observation under
    # min_dispatches new dispatches is deferred, folding into the next
    # qualifying delta
    O = 0.01
    before = tuner.chain
    assert feed(3) == before
    assert feed(8) == 4
    assert feed(8) == 2

    # hysteresis: a ratio inside [shrink, grow] leaves S alone
    O = 2 * C * 0.1  # ratio 0.1 at S=2
    assert feed(8) == 2
    assert feed(8) == 2


def test_chain_autotuner_pow2_only():
    """Every S the tuner can emit is a power of two, and the ceiling is
    the pow2 FLOOR of an arbitrary chain_max — a non-pow2 ceiling would
    bake a fresh compiled chain program the moment the tuner hit it."""
    from fantoch_tpu.run.ingest import ChainAutoTuner

    tuner = ChainAutoTuner(chain_max=13)
    assert tuner.chain_max == 8
    counters = [0.0, 0.0, 0.0, 0.0]
    O, C = 4.0, 0.5

    def feed(n):
        S = tuner.chain
        counters[0] += n
        counters[1] += n * O
        counters[2] += n * S * C
        counters[3] += n * S
        return tuner.observe(*counters)

    feed(8)  # seed
    seen = set()
    for _ in range(12):
        seen.add(feed(8))
    O = 0.001  # collapse: walk back down
    for _ in range(12):
        seen.add(feed(8))
    assert seen <= {1, 2, 4, 8}
    assert tuner.chain == 1


def test_plan_ingest_releases_oracle():
    """The offline replay (OrderingPool's coalescer and the online
    loops' oracle): releases partition the arrival column, a deadline
    expiring between two arrivals releases at the deadline instant
    WITHOUT the later arrival, and the tail releases at its window's
    deadline."""
    from fantoch_tpu.run.ingest import (
        AdaptiveIngestBatcher,
        plan_ingest_releases,
    )

    # trickle: each arrival 10ms apart, deadline 2ms — the cold/reset
    # EWMA targets 1, so every lone command releases at its own arrival
    # instant (batching never engages without measured sustained load)
    b = AdaptiveIngestBatcher(2.0, max_target=64)
    arrivals = [0.0, 10.0, 20.0]
    plan = plan_ingest_releases(arrivals, b)
    assert plan == [(0.0, 0, 1), (10.0, 1, 2), (20.0, 2, 3)]
    assert b.releases == 3 and b.released_rows == 3

    # a fixed target groups a dense burst into size releases plus a
    # deadline tail
    b2 = AdaptiveIngestBatcher(2.0, max_target=64, fixed_target=4)
    dense = [i * 0.1 for i in range(10)]
    plan2 = plan_ingest_releases(dense, b2)
    starts = [s for _t, s, _e in plan2]
    ends = [e for _t, _s, e in plan2]
    assert starts == [0] + ends[:-1] and ends[-1] == 10  # partition
    assert plan2[0] == (pytest.approx(0.3), 0, 4)
    assert plan2[1] == (pytest.approx(0.7), 4, 8)
    # tail: 2 rows < target, released at the window's deadline
    assert plan2[2] == (pytest.approx(0.8 + 2.0), 8, 10)
    assert b2.releases_size == 2 and b2.releases_deadline == 1

    # empty column: empty plan
    assert plan_ingest_releases([], AdaptiveIngestBatcher(2.0, 64)) == []


def test_ingest_ring_slot_never_reused_while_in_flight():
    """The driver contract: with PipelineCore._staging (the production
    ring sizing: slots = depth + 1), the staging columns of any round
    still in flight are never handed out again — the zero-copy-alias
    safety argument for jnp.asarray staging."""

    class RingDriver(_FakeDriver):
        def __init__(self):
            super().__init__()
            self.live = {}  # round -> staging array it aliases

        def dispatch(self, batch):
            (col,) = self._staging(("col", (4,), np.int64, 0))
            col[: len(batch)] = batch
            tok = (self._round, col, list(batch))
            self._round += 1
            # no OTHER in-flight round may alias this slot
            for r, other in self.live.items():
                assert other is not col, f"slot of round {r} reused in flight"
            self.live[tok[0]] = col
            return tok

        def drain(self, tok):
            r, col, batch = tok
            # the round's staging columns are untouched at drain time
            assert list(col[: len(batch)]) == batch
            del self.live[r]
            self.drained.append(r)
            return [(r, v) for v in batch]

    for depth in (1, 2, 3):
        d = RingDriver()
        d.pipeline_depth = depth
        outs = []
        for i in range(8):
            outs.extend(d.step_pipelined([10 * i + 1, 10 * i + 2]))
        outs.extend(d.flush_pipeline())
        assert [v for _r, v in outs] == [
            10 * i + j for i in range(8) for j in (1, 2)
        ]
