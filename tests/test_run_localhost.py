"""Real-runner integration tests: full n-process TCP clusters on localhost
inside one asyncio loop, mirroring the reference's run_test matrix
(fantoch_ps/src/protocol/mod.rs:112-750 via fantoch/src/run/mod.rs:1030).
"""

import asyncio

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config
from fantoch_tpu.protocol import Atlas, Basic, Caesar, EPaxos, FPaxos, Newt, ProtocolMetricsKind
from fantoch_tpu.run.harness import run_localhost_cluster

COMMANDS_PER_CLIENT = 10
CLIENTS_PER_PROCESS = 2


def run_cluster(
    protocol_cls,
    config,
    workers=1,
    executors=1,
    multiplexing=1,
    open_loop_interval_ms=None,
    check_agreement=True,
    peer_delays=None,
    ping_sort=False,
    conflict_rate=50,
    keys_per_command=2,
    return_runtimes=False,
):
    config = config.with_(
        executor_monitor_execution_order=True,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        shard_count=1,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(conflict_rate),
        keys_per_command=keys_per_command,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtimes, clients = asyncio.run(
        run_localhost_cluster(
            protocol_cls,
            config,
            workload,
            CLIENTS_PER_PROCESS,
            workers=workers,
            executors=executors,
            multiplexing=multiplexing,
            open_loop_interval_ms=open_loop_interval_ms,
            extra_run_time_ms=1000,
            peer_delays=peer_delays,
            ping_sort=ping_sort,
        )
    )

    # every client finished its workload
    total_clients = config.n * CLIENTS_PER_PROCESS
    assert len(clients) == total_clients
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
        assert len(list(client.data().latency_data())) == COMMANDS_PER_CLIENT

    # agreement: merge each process's executor monitors, then compare across
    # processes (protocol/mod.rs:924-1010)
    merged = {}
    for pid, runtime in runtimes.items():
        monitor = None
        for executor in runtime.executors:
            m = executor.monitor()
            if m is None:
                continue
            if monitor is None:
                monitor = m
            else:
                monitor.merge(m)
        assert monitor is not None
        merged[pid] = monitor
    if check_agreement:
        items = list(merged.items())
        pid_a, monitor_a = items[0]
        for pid_b, monitor_b in items[1:]:
            for key in monitor_a.keys():
                assert monitor_a.get_order(key) == monitor_b.get_order(key), (
                    f"p{pid_a} and p{pid_b} disagree on {key!r}"
                )

    # commit + GC accounting (protocol/mod.rs:1015-1080)
    min_commits = COMMANDS_PER_CLIENT * total_clients
    total_fast = total_slow = total_stable = 0
    for pid, runtime in runtimes.items():
        m = runtime.process.metrics()
        total_fast += m.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        total_slow += m.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        total_stable += m.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    if protocol_cls.leaderless():
        # Basic (check_agreement=False) has no fast/slow accounting
        if check_agreement:
            assert total_fast + total_slow == min_commits
        gc_at = config.n
    else:
        gc_at = config.f + 1
    assert total_stable == gc_at * min_commits, (
        f"incomplete gc: {total_stable} != {gc_at} * {min_commits}"
    )
    if return_runtimes:
        return total_slow, runtimes
    return total_slow


def run_multi_shard_cluster(protocol_cls, config, shard_count, executors=2):
    """Multi-shard variant (protocol/mod.rs:786-838): agreement is checked
    within each shard (keys live on exactly one shard), and commit/GC
    accounting is per shard — every shard commits each command that touches
    it (mod.rs:1042-1075)."""
    from fantoch_tpu.core.ids import process_ids

    config = config.with_(
        executor_monitor_execution_order=True,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        executor_cleanup_interval_ms=5,
        shard_count=shard_count,
    )
    workload = Workload(
        shard_count=shard_count,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )
    runtimes, clients = asyncio.run(
        run_localhost_cluster(
            protocol_cls,
            config,
            workload,
            CLIENTS_PER_PROCESS,
            executors=executors,
            extra_run_time_ms=1000,
        )
    )

    total_clients = config.n * CLIENTS_PER_PROCESS
    assert len(clients) == total_clients
    for client in clients.values():
        assert client.issued_commands == COMMANDS_PER_CLIENT
        assert len(list(client.data().latency_data())) == COMMANDS_PER_CLIENT

    shard_pids = {s: list(process_ids(s, config.n)) for s in range(shard_count)}
    # per-shard agreement on per-key execution order
    for s, pids in shard_pids.items():
        monitors = {}
        for pid in pids:
            monitor = None
            for executor in runtimes[pid].executors:
                m = executor.monitor()
                if m is None:
                    continue
                if monitor is None:
                    monitor = m
                else:
                    monitor.merge(m)
            assert monitor is not None
            monitors[pid] = monitor
        items = list(monitors.items())
        pid_a, monitor_a = items[0]
        for pid_b, monitor_b in items[1:]:
            for key in monitor_a.keys():
                assert monitor_a.get_order(key) == monitor_b.get_order(key), (
                    f"shard {s}: p{pid_a} and p{pid_b} disagree on {key!r}"
                )

    # commit + GC accounting (mod.rs:1042-1075): commits are counted once
    # per shard a command touches, so the total lies in [min, min * shards];
    # GC only happens at the dot-owner shard, so stable is exactly
    # n * min_total regardless of shard spread
    min_total = COMMANDS_PER_CLIENT * total_clients
    total_fast = total_slow = total_stable = 0
    for pid, runtime in runtimes.items():
        m = runtime.process.metrics()
        total_fast += m.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
        total_slow += m.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
        total_stable += m.get_aggregated(ProtocolMetricsKind.STABLE) or 0
    commits = total_fast + total_slow
    assert min_total <= commits <= min_total * shard_count, (
        f"commits {commits} outside [{min_total}, {min_total * shard_count}]"
    )
    assert total_stable == config.n * min_total, (
        f"incomplete gc: {total_stable} != {config.n} * {min_total}"
    )


def test_run_atlas_3_1_two_shards():
    run_multi_shard_cluster(Atlas, Config(n=3, f=1), shard_count=2)


def test_run_atlas_3_1_three_shards():
    run_multi_shard_cluster(Atlas, Config(n=3, f=1), shard_count=3)


def test_run_atlas_3_1_four_shards():
    # the reference matrix tops out at 4 shards (fantoch_ps/src/protocol/
    # mod.rs:112-750)
    run_multi_shard_cluster(Atlas, Config(n=3, f=1), shard_count=4)


def test_run_newt_3_1_two_shards():
    run_multi_shard_cluster(
        Newt,
        Config(n=3, f=1, newt_detached_send_interval_ms=50),
        shard_count=2,
    )


def test_run_newt_3_1_three_shards():
    run_multi_shard_cluster(
        Newt,
        Config(n=3, f=1, newt_detached_send_interval_ms=50),
        shard_count=3,
    )


def test_run_basic_3_1():
    # Basic is the reference's *inconsistent* protocol (fantoch/src/protocol/
    # basic.rs): commands execute at commit without cross-process ordering,
    # so only completion + GC accounting apply
    run_cluster(Basic, Config(n=3, f=1), check_agreement=False)


def test_run_epaxos_3_1():
    slow = run_cluster(EPaxos, Config(n=3, f=1))
    assert slow == 0, "f=1: everything commits on the fast path"


def test_run_newt_3_1():
    slow = run_cluster(Newt, Config(n=3, f=1, newt_detached_send_interval_ms=50))
    assert slow == 0


def test_run_newt_3_1_multi_executor():
    run_cluster(
        Newt,
        Config(n=3, f=1, newt_detached_send_interval_ms=50),
        executors=3,
    )


def test_run_fpaxos_3_1_multi_worker():
    run_cluster(FPaxos, Config(n=3, f=1, leader=1), workers=3)


def test_run_caesar_3_1():
    run_cluster(Caesar, Config(n=3, f=1))


def test_run_basic_3_1_open_loop():
    run_cluster(
        Basic, Config(n=3, f=1), open_loop_interval_ms=5, check_agreement=False
    )


# --- n=5 f=2 rows of the reference matrix (protocol/mod.rs:112-750):
# with f=2 the fast quorum is larger, so concurrent conflicting commands
# disagree on deps/clocks and some commits take the slow path ---


def test_run_epaxos_5_2():
    slow = run_cluster(EPaxos, Config(n=5, f=2), conflict_rate=100, keys_per_command=1)
    assert slow > 0, "f=2 with full conflicts must exercise the slow path"


def test_run_atlas_3_1():
    slow = run_cluster(Atlas, Config(n=3, f=1))
    assert slow == 0, "f=1: everything commits on the fast path"


def test_run_atlas_5_2():
    slow = run_cluster(Atlas, Config(n=5, f=2), conflict_rate=100, keys_per_command=1)
    assert slow > 0


def test_run_newt_5_2():
    slow = run_cluster(
        Newt,
        Config(n=5, f=2, newt_detached_send_interval_ms=50),
        conflict_rate=100,
        keys_per_command=1,
    )
    assert slow > 0


def test_run_caesar_5_2():
    run_cluster(Caesar, Config(n=5, f=2), conflict_rate=100, keys_per_command=1)


def test_run_fpaxos_5_2():
    run_cluster(FPaxos, Config(n=5, f=2, leader=1))


def test_run_epaxos_3_1_batched_executor():
    # the device-batched graph executor as a drop-in on the real runner
    slow = run_cluster(
        EPaxos, Config(n=3, f=1, batched_graph_executor=True)
    )
    assert slow == 0


def test_run_epaxos_3_1_delay_injection():
    # odd processes write through a FIFO delay line (delay.rs:6-39; the
    # reference's run tests give odd processes delay entries,
    # run/mod.rs:1184-1192) — correctness must hold under asymmetric delays
    delays = {1: {2: 10}, 3: {2: 10}}
    slow = run_cluster(EPaxos, Config(n=3, f=1), peer_delays=delays)
    assert slow == 0


def test_run_epaxos_3_1_multiplexing():
    # 3 TCP connections per peer with random writer pick: same-peer
    # messages may reorder across links (process.rs:71-97,680-696),
    # exercising the buffered-commit reordering paths
    slow = run_cluster(EPaxos, Config(n=3, f=1), multiplexing=3)
    assert slow == 0


def test_run_ping_sort_orders_by_latency():
    # p1's connection to p3 is delayed, so p1's ping-sorted process list
    # must place p3 after p2 (ping.rs:13-78 distance sorting)
    delays = {1: {3: 40}}
    _slow, runtimes = run_cluster(
        Basic,
        Config(n=3, f=1),
        peer_delays=delays,
        ping_sort=True,
        check_agreement=False,
        return_runtimes=True,
    )
    order = [pid for pid, _ in runtimes[1].sorted_processes]
    assert order == [1, 2, 3], f"delayed peer must sort last: {order}"


def test_run_atlas_3_1_two_shards_batched_graph():
    """Partial replication over real TCP with the tensorized graph
    executor (VERDICT r3 item 6 done-criterion)."""
    run_multi_shard_cluster(
        Atlas, Config(n=3, f=1, batched_graph_executor=True), shard_count=2
    )


def test_warn_queue_threshold_and_hysteresis():
    """WarnQueue warns once per doubling above the threshold and re-arms
    only after the queue genuinely drains (half the threshold) — a queue
    hovering AT the threshold must not warn per put (chan.rs:36-58
    warn-on-full analog for the cooperative loop)."""
    import logging

    from fantoch_tpu.run.prelude import WarnQueue

    async def scenario():
        q = WarnQueue("t", warn_size=8)
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        logger = logging.getLogger("fantoch_tpu")
        handler = Capture()
        logger.addHandler(handler)
        old_level = logger.level
        logger.setLevel(logging.WARNING)
        try:
            for i in range(8):
                q.put_nowait(i)
            assert len(records) == 1  # crossed the threshold once
            # hover at the threshold: get/put cycles must not re-warn
            for i in range(50):
                q.get_nowait()
                q.put_nowait(i)
            assert len(records) == 1
            # runaway growth: one more warning per doubling
            for i in range(8, 16):
                q.put_nowait(i)
            assert len(records) == 2
            # drain below half the threshold re-arms
            while q.qsize() > 3:
                q.get_nowait()
            for i in range(10):
                q.put_nowait(i)
            assert len(records) == 3
        finally:
            logger.removeHandler(handler)
            logger.setLevel(old_level)

    asyncio.run(scenario())
