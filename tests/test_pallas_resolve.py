"""Pallas-vs-composed parity suite (ops/pallas_resolve.py).

The routing contract is EXACT: for every input — permuted DAGs, cycles,
missing deps, residual seams, non-pow2 caps — the Pallas route must
return bit-for-bit the composed route's outputs (same resolved/stuck/
rank/order, same residual-column protocol), under the same donation
discipline (``resident_uploads == 1`` at the executor level).  On the
CPU pin the kernels run in Pallas interpret mode, so this suite proves
the contract on every push; on a TPU backend the same tests exercise the
Mosaic-lowered kernels (scripts/run_device_stripped.py re-runs the suite
with ``FANTOCH_PALLAS=1`` forced through the executor stack).

Every test forces the route explicitly (``set_pallas_kernels``) so the
suite is independent of the backend default (off on CPU).
"""

import contextlib
import random

import numpy as np
import pytest

import jax.numpy as jnp

from fantoch_tpu.ops import pallas_resolve as pallas_resolve
from fantoch_tpu.ops.graph_resolve import (
    MISSING,
    TERMINAL,
    resolve_graph_plane_step,
    resolve_graph_plane_step_xla,
)
from fantoch_tpu.ops.pred_resolve import (
    resolve_pred_plane_step,
    resolve_pred_plane_step_xla,
)
from fantoch_tpu.ops.table_ops import (
    fused_table_round,
    fused_table_round_xla,
    fused_votes_commit,
    fused_votes_commit_xla,
)


@contextlib.contextmanager
def forced_pallas(enabled=True):
    pallas_resolve.set_pallas_kernels(enabled)
    try:
        yield
    finally:
        pallas_resolve.set_pallas_kernels(None)


def _assert_tuples_equal(got, want, fields=None):
    names = fields or range(len(tuple(want)))
    for name, g, w in zip(names, tuple(got), tuple(want)):
        assert np.array_equal(np.asarray(g), np.asarray(w)), name


# ---------------------------------------------------------------------------
# pred plane step
# ---------------------------------------------------------------------------


def _pred_feed(rng, cap, width, n_installed):
    """One random dispatch feed: installs new rows (deps may point at
    already-installed rows, be TERMINAL, or MISSING), plus patches that
    re-point MISSING cells of earlier rows (the residual wake seam)."""
    U, P = 6, 6
    u_row = np.full((U,), cap, np.int32)
    u_deps = np.full((U, width), TERMINAL, np.int32)
    u_clock = np.zeros((U,), np.int32)
    u_src = np.zeros((U,), np.int32)
    installs = min(rng.randrange(1, U + 1), cap - n_installed)
    for i in range(max(installs, 0)):
        row = n_installed + i
        u_row[i] = row
        u_clock[i] = rng.randrange(1, 1000)
        u_src[i] = rng.randrange(1, 4)
        for w in range(rng.randrange(0, width + 1)):
            u_deps[i, w] = rng.choice(
                [TERMINAL, MISSING, rng.randrange(0, max(row, 1))]
            )
    p_row = np.full((P,), cap, np.int32)
    p_col = np.zeros((P,), np.int32)
    p_val = np.full((P,), TERMINAL, np.int32)
    for j in range(rng.randrange(0, P)):
        if n_installed == 0:
            break
        p_row[j] = rng.randrange(0, n_installed)
        p_col[j] = rng.randrange(0, width)
        p_val[j] = rng.choice([TERMINAL, rng.randrange(0, n_installed)])
    return (
        (u_row, u_deps, u_clock, u_src, p_row, p_col, p_val),
        n_installed + max(installs, 0),
    )


def test_pred_plane_step_parity_multi_dispatch():
    """Bit-for-bit PredPlaneStep parity across random multi-dispatch
    sequences, each route threading its OWN resident state (so donation
    runs on both sides) — installs, MISSING-cell patches waking earlier
    rows, and the two-phase fixpoint all inside the window."""
    rng = random.Random(11)
    for _trial in range(4):
        cap, width = 24, 4
        state_p = state_x = None

        def fresh():
            return (
                jnp.full((cap, width), TERMINAL, jnp.int32),
                jnp.zeros((cap,), jnp.int32),
                jnp.zeros((cap,), jnp.int32),
                jnp.zeros((cap,), jnp.bool_),
                jnp.zeros((cap,), jnp.bool_),
            )

        state_p, state_x = fresh(), fresh()
        installed = 0
        for _round in range(5):
            feed, installed = _pred_feed(rng, cap, width, installed)
            feed_j = tuple(jnp.asarray(a) for a in feed)
            with forced_pallas(True):
                out_p = resolve_pred_plane_step(*state_p, *feed_j)
            with forced_pallas(False):
                out_x = resolve_pred_plane_step(*state_x, *feed_j)
            _assert_tuples_equal(out_p, out_x, out_p._fields)
            state_p = tuple(out_p[:5])
            state_x = tuple(out_x[:5])


# ---------------------------------------------------------------------------
# graph plane step
# ---------------------------------------------------------------------------


def _graph_feed(rng, cap, width, n_installed, *, with_cycle):
    U, P, E = 6, 4, 3
    u_row = np.full((U,), cap, np.int32)
    u_deps = np.full((U, width), TERMINAL, np.int32)
    u_key = np.zeros((U,), np.int32)
    u_src = np.zeros((U,), np.int32)
    u_seq = np.zeros((U,), np.int32)
    installs = min(rng.randrange(1, U + 1), cap - n_installed)
    for i in range(max(installs, 0)):
        row = n_installed + i
        u_row[i] = row
        u_key[i] = rng.randrange(0, 4)
        u_src[i] = rng.randrange(1, 4)
        u_seq[i] = row + 1
        for w in range(rng.randrange(0, width + 1)):
            u_deps[i, w] = rng.choice(
                [TERMINAL, MISSING, rng.randrange(0, max(row, 1))]
            )
    if with_cycle and installs >= 2:
        # a deliberate 2-cycle between the first two fresh rows: the
        # general modes must flag both stuck identically on both routes
        a, b = n_installed, n_installed + 1
        u_deps[0, 0] = b
        u_deps[1, 0] = a
    p_row = np.full((P,), cap, np.int32)
    p_col = np.zeros((P,), np.int32)
    p_val = np.full((P,), TERMINAL, np.int32)
    for j in range(rng.randrange(0, P)):
        if n_installed == 0:
            break
        p_row[j] = rng.randrange(0, n_installed)
        p_col[j] = rng.randrange(0, width)
        p_val[j] = rng.choice([TERMINAL, rng.randrange(0, n_installed)])
    e_row = np.full((E,), cap, np.int32)
    if n_installed and rng.random() < 0.5:
        e_row[0] = rng.randrange(0, n_installed)
    return (
        (u_row, u_deps, u_key, u_src, u_seq, p_row, p_col, p_val, e_row),
        n_installed + max(installs, 0),
    )


@pytest.mark.parametrize("mode", ["keyed", "general", "general_resident"])
@pytest.mark.parametrize("cap", [32, 48])  # 48: the non-pow2 corner
def test_graph_plane_step_parity_modes(mode, cap):
    """Bit-for-bit GraphPlaneStep parity in all three modes over random
    permuted-DAG feeds with cycles, missing deps, host-oracle executed
    marks, and a non-pow2 capacity (the keyed residual publish-gate
    corner: residual_size derives from cap)."""
    rng = random.Random(hash((mode, cap)) & 0xFFFF)
    width = 4

    def fresh():
        return (
            jnp.full((cap, width), TERMINAL, jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap,), jnp.int32),
            jnp.zeros((cap,), jnp.bool_),
            jnp.zeros((cap,), jnp.bool_),
        )

    state_p, state_x = fresh(), fresh()
    installed = 0
    for round_i in range(4):
        feed, installed = _graph_feed(
            rng, cap, width, installed, with_cycle=(round_i == 1)
        )
        feed_j = tuple(jnp.asarray(a) for a in feed)
        with forced_pallas(True):
            out_p = resolve_graph_plane_step(*state_p, *feed_j, mode=mode)
        with forced_pallas(False):
            out_x = resolve_graph_plane_step(*state_x, *feed_j, mode=mode)
        _assert_tuples_equal(out_p, out_x, out_p._fields)
        state_p = tuple(out_p[:6])
        state_x = tuple(out_x[:6])


# ---------------------------------------------------------------------------
# table plane
# ---------------------------------------------------------------------------


def test_votes_commit_parity_residual_seam():
    """Bit-for-bit 7-tuple parity (including run_*/residual columns)
    over random vote batches with beyond-gap runs, each route threading
    its own resident frontier."""
    rng = random.Random(23)
    K, n, V = 16, 3, 16
    f_p = jnp.zeros((K, n), jnp.int32)
    f_x = jnp.zeros((K, n), jnp.int32)
    for _round in range(6):
        vkey = np.array([rng.randrange(0, K) for _ in range(V)], np.int32)
        vby = np.array([rng.randrange(0, n) for _ in range(V)], np.int32)
        vstart = np.array([rng.randrange(1, 12) for _ in range(V)], np.int32)
        vend = vstart + np.array(
            [rng.randrange(0, 4) for _ in range(V)], np.int32
        )
        valid = np.array([rng.random() < 0.85 for _ in range(V)], bool)
        feed = tuple(
            jnp.asarray(a) for a in (vkey, vby, vstart, vend, valid)
        )
        with forced_pallas(True):
            out_p = fused_votes_commit(f_p, *feed, threshold=2)
        with forced_pallas(False):
            out_x = fused_votes_commit(f_x, *feed, threshold=2)
        _assert_tuples_equal(
            out_p, out_x,
            ["frontier", "stable", "run_key", "run_by", "run_start",
             "run_end", "residual"],
        )
        f_p, f_x = out_p[0], out_x[0]


def test_table_round_parity_chain():
    """Bit-for-bit parity of the fused dense round across a chain of
    rounds threading donated prior/frontier through both routes."""
    rng = random.Random(31)
    K, n, B = 16, 3, 8
    pr_p, fr_p = jnp.zeros((K,), jnp.int32), jnp.zeros((K, n), jnp.int32)
    pr_x, fr_x = jnp.zeros((K,), jnp.int32), jnp.zeros((K, n), jnp.int32)
    for _round in range(6):
        key = np.array([rng.randrange(0, K - 1) for _ in range(B)], np.int32)
        mc = np.array([rng.randrange(0, 8) for _ in range(B)], np.int32)
        feed = (jnp.asarray(key), jnp.asarray(mc))
        with forced_pallas(True):
            out_p = fused_table_round(pr_p, fr_p, *feed, threshold=2, voters=2)
        with forced_pallas(False):
            out_x = fused_table_round(pr_x, fr_x, *feed, threshold=2, voters=2)
        _assert_tuples_equal(
            out_p, out_x,
            ["prior", "frontier", "clock", "vote_start", "executable",
             "gaps"],
        )
        pr_p, fr_p = out_p[0], out_p[1]
        pr_x, fr_x = out_x[0], out_x[1]


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------


def test_route_resolution_precedence(monkeypatch):
    """Config override beats the env var beats the backend default (off
    on the CPU pin), and FANTOCH_PALLAS=0 is the escape hatch."""
    monkeypatch.delenv("FANTOCH_PALLAS", raising=False)
    pallas_resolve.set_pallas_kernels(None)
    assert pallas_resolve.pallas_enabled() is False  # CPU default
    monkeypatch.setenv("FANTOCH_PALLAS", "1")
    assert pallas_resolve.pallas_enabled() is True
    monkeypatch.setenv("FANTOCH_PALLAS", "0")
    assert pallas_resolve.pallas_enabled() is False
    try:
        pallas_resolve.set_pallas_kernels(True)
        assert pallas_resolve.pallas_enabled() is True  # config beats env
    finally:
        pallas_resolve.set_pallas_kernels(None)


def test_apply_pallas_config():
    """The executor-construction seam folds Config.pallas_kernels into
    the route; None leaves the resolution chain untouched."""
    from fantoch_tpu.core.config import Config

    try:
        pallas_resolve.apply_pallas_config(Config(3, 1))
        assert pallas_resolve._override is None
        pallas_resolve.apply_pallas_config(Config(3, 1, pallas_kernels=True))
        assert pallas_resolve.pallas_enabled() is True
        pallas_resolve.apply_pallas_config(Config(3, 1, pallas_kernels=False))
        assert pallas_resolve.pallas_enabled() is False
    finally:
        pallas_resolve.set_pallas_kernels(None)


def test_unsupported_family_falls_back_for_process_life():
    """A kernel that fails to lower routes that dispatch to the composed
    program (the args are intact: lowering fails before donation
    consumes buffers) and pins the family to the composed path."""
    calls = {"pallas": 0, "composed": 0}

    def bad_kernel(x):
        calls["pallas"] += 1
        raise RuntimeError("mosaic lowering refused")

    def composed(x):
        calls["composed"] += 1
        return x + 1

    pallas_resolve._supported.pop("_test_family", None)
    with forced_pallas(True):
        out = pallas_resolve.route_dispatch(
            "_test_family", bad_kernel, composed, (1,), {}
        )
        assert out == 2
        assert pallas_resolve._supported["_test_family"] is False
        # second dispatch: straight to composed, no re-probe
        out = pallas_resolve.route_dispatch(
            "_test_family", bad_kernel, composed, (2,), {}
        )
        assert out == 3
    assert calls == {"pallas": 1, "composed": 2}
    pallas_resolve._supported.pop("_test_family", None)


def test_vmem_gate_routes_oversized_to_composed():
    """In compiled (non-interpret) mode an operand set past the VMEM
    budget must route composed; interpret mode always fits."""
    big = np.zeros((4096, 4096), np.int32)  # 64 MiB > the 8 MiB budget
    assert pallas_resolve._fits_vmem(big) is True  # interpret on CPU
    # emulate a compiled backend by bypassing the interpret short-circuit
    import unittest.mock as mock

    with mock.patch.object(pallas_resolve, "_interpret", return_value=False):
        assert pallas_resolve._fits_vmem(big) is False
        small = np.zeros((64, 64), np.int32)
        assert pallas_resolve._fits_vmem(small) is True


# ---------------------------------------------------------------------------
# executor-level routing: the planes serve identically on either route,
# with the donation discipline intact (resident_uploads == 1)
# ---------------------------------------------------------------------------


def test_pred_executor_parity_and_single_upload_under_pallas():
    """DevicePredPlane serving through the Pallas route matches the
    composed-route plane (results, per-key order, and upload count —
    the donation contract survives the kernel swap)."""
    from tests.test_pred_plane import (
        _conflict_workload,
        _plane_executor,
        _assert_parity,
    )

    rng = random.Random(7)
    infos = _conflict_workload(rng, count=40)
    with forced_pallas(True):
        ex_pallas = _plane_executor()
        for info in infos:
            ex_pallas.handle(info, None)
        uploads_pallas = ex_pallas._plane.resident_uploads
    with forced_pallas(False):
        ex_composed = _plane_executor()
        for info in infos:
            ex_composed.handle(info, None)
        uploads_composed = ex_composed._plane.resident_uploads
    # identical upload count: capacity growth re-uploads are workload-
    # driven and count the same on either route — the Pallas kernels add
    # ZERO extra uploads (donation discipline unchanged)
    assert uploads_pallas == uploads_composed
    # route-vs-route parity (to_clients_iter drains, so one comparison):
    # the Pallas-routed executor against the composed-routed one
    _assert_parity(ex_pallas, ex_composed)


def test_pred_executor_steady_state_single_upload_under_pallas():
    """A workload inside the initial window: exactly ONE resident upload
    on the Pallas route (the ISSUE's steady-state contract)."""
    from tests.test_pred_plane import _conflict_workload, _plane_executor

    rng = random.Random(3)
    infos = _conflict_workload(rng, count=8, keys=("Ka", "Kb"))
    with forced_pallas(True):
        ex = _plane_executor()
        ex.handle_batch(infos, None)
        assert ex._plane.resident_uploads == 1


def test_table_plane_parity_under_pallas():
    """DeviceTablePlane commit dispatches agree bit-for-bit between the
    two routes, residual re-feeds included, with one resident upload."""
    from fantoch_tpu.executor.table_plane import DeviceTablePlane

    def drive(enabled):
        with forced_pallas(enabled):
            plane = DeviceTablePlane(3, stability_threshold=2, key_buckets=8)
            for k in range(6):
                plane.bucket(f"k{k}")
            r = random.Random(99)
            stables = []
            for _round in range(6):
                vk, vb, vs, ve = [], [], [], []
                for _ in range(8):
                    vk.append(r.randrange(0, 6))
                    vb.append(r.randrange(1, 4))
                    s = r.randrange(1, 12)
                    vs.append(s)
                    ve.append(s + r.randrange(0, 4))
                stables.append(
                    plane.commit_votes(
                        np.array(vk, np.int64), np.array(vb, np.int64),
                        np.array(vs, np.int64), np.array(ve, np.int64),
                    )
                )
            return plane, stables

    plane_p, outs_p = drive(True)
    plane_x, outs_x = drive(False)
    for got, want in zip(outs_p, outs_x):
        assert np.array_equal(got, want)
    assert np.array_equal(plane_p.frontiers(), plane_x.frontiers())
    assert plane_p.resident_uploads == plane_x.resident_uploads == 1
