"""Unit tests for bench.py's measurement harness.

The benchmark is a driver-run artifact generator, so its *robustness*
machinery is product behavior: the median-of-rounds slope fit and the
TPU-record persistence gate both exist because one jitter-swamped
two-point fit published a 0.129 ms primary where three same-day runs of
the identical build said 2.3-3.0 ms (BENCH_DEV.md, session part 4).
These tests pin that machinery without touching a device: the clock is
scripted via monkeypatched ``time.perf_counter``.
"""

import json

import bench


def _scripted_clock(monkeypatch, durations_ms):
    """perf_counter returns cumulative times so consecutive (t0, t1)
    pairs measure exactly the scripted durations, in order."""
    ticks = [0.0]
    for d in durations_ms:
        ticks.append(ticks[-1])  # t0 of the next measurement
        ticks.append(ticks[-2] + d / 1000.0)  # t1 = t0 + duration
    it = iter(ticks[1:])
    monkeypatch.setattr(bench.time, "perf_counter", lambda: next(it))


def test_slope_timed_median_of_rounds(monkeypatch):
    # rounds=3, iters=1: measurement order is lo,hi, lo,hi, lo,hi after
    # two untimed warm calls.  One wild hi outlier must not drag the
    # slope: per-round slopes are (2.0, 42.0, 2.0) ms/step -> median 2.0.
    durations = [100.0, 108.0, 100.0, 268.0, 100.0, 108.0]
    _scripted_clock(monkeypatch, durations)
    slope, lo, hi = bench.slope_timed(lambda k: 0.0, 1, 5, iters=1, rounds=3)
    assert slope is not None
    assert abs(slope - 2.0) < 1e-9
    assert abs(lo - 100.0) < 1e-9
    assert abs(hi - 108.0) < 1e-9


def test_slope_timed_noise_negative_returns_none(monkeypatch):
    # hi consistently BELOW lo (pure jitter): the fit must refuse to
    # fabricate a near-zero latency and signal failure instead
    durations = [100.0, 99.0, 100.0, 98.0, 100.0, 99.5]
    _scripted_clock(monkeypatch, durations)
    slope, lo, hi = bench.slope_timed(lambda k: 0.0, 1, 5, iters=1, rounds=3)
    assert slope is None
    assert lo > hi


def test_tpu_record_gate(tmp_path, monkeypatch):
    path = tmp_path / "BENCH_TPU_LATEST.json"
    monkeypatch.setattr(bench, "_TPU_RECORD_PATH", str(path))
    # the gated-candidate sidecar must land in the sandbox too, not the repo
    monkeypatch.setattr(bench, "_TPU_GATED_PATH", str(tmp_path / "BENCH_TPU_GATED.json"))

    # non-tpu records never persist
    bench._save_tpu_record(json.dumps({"platform": "cpu", "value": 1.0}))
    assert not path.exists()

    # a chip record without its scale cross-check does not persist: the
    # 4M row is the primary slope's independent witness
    bench._save_tpu_record(json.dumps({"platform": "tpu", "value": 0.129}))
    assert not path.exists()

    # a wildly-off ratio (the observed 88.1 incident) does not persist
    bench._save_tpu_record(
        json.dumps({"platform": "tpu", "value": 0.129, "scale_vs_1m": 88.1})
    )
    assert not path.exists()

    # a self-consistent record persists and gets a UTC stamp
    bench._save_tpu_record(
        json.dumps({"platform": "tpu", "value": 2.977, "scale_vs_1m": 3.42})
    )
    assert path.exists()
    rec = json.loads(path.read_text())
    assert rec["value"] == 2.977
    assert "recorded_utc" in rec

    # ... and a later gated record must NOT overwrite it
    bench._save_tpu_record(
        json.dumps({"platform": "tpu", "value": 0.2, "scale_vs_1m": 50.0})
    )
    assert json.loads(path.read_text())["value"] == 2.977


def test_attach_last_tpu_embeds_without_touching_value(tmp_path, monkeypatch):
    path = tmp_path / "BENCH_TPU_LATEST.json"
    monkeypatch.setattr(bench, "_TPU_RECORD_PATH", str(path))
    bench._save_tpu_record(
        json.dumps({"platform": "tpu", "value": 2.977, "scale_vs_1m": 3.42})
    )

    cpu_line = json.dumps({"platform": "cpu", "value": 396.8})
    out = json.loads(bench._attach_last_tpu(cpu_line))
    assert out["value"] == 396.8  # the CPU measurement stays the value
    assert out["last_tpu_record"]["value"] == 2.977

    # a tpu record passes through untouched (no self-embedding)
    tpu_line = json.dumps({"platform": "tpu", "value": 2.9})
    assert json.loads(bench._attach_last_tpu(tpu_line)) == json.loads(tpu_line)
