"""Core-layer unit tests, mirroring the reference's colocated unit tests:
config quorum formulas (fantoch/src/config.rs:449-537), id layout
(fantoch/src/util.rs:196+), planet loading/sorting
(fantoch/src/planet/mod.rs:180-301), kvs flow (fantoch/src/kvs.rs:71-138),
histograms and command semantics.
"""

import pytest

from fantoch_tpu.core import (
    Command,
    CommandResult,
    Config,
    Dot,
    Histogram,
    IdGen,
    KVOp,
    KVStore,
    Planet,
    Region,
    Rifl,
    SimTime,
)
from fantoch_tpu.core.ids import all_process_ids, process_ids
from fantoch_tpu.utils import (
    closest_process_per_shard,
    key_hash,
    sort_processes_by_distance,
)


# --- config quorum formulas (reference: fantoch/src/config.rs:449-537) ---


def test_basic_parameters():
    assert Config(7, 1).basic_quorum_size() == 2
    assert Config(7, 2).basic_quorum_size() == 3
    assert Config(7, 3).basic_quorum_size() == 4


def test_atlas_parameters():
    assert Config(7, 1).atlas_quorum_sizes() == (4, 2)
    assert Config(7, 2).atlas_quorum_sizes() == (5, 3)
    assert Config(7, 3).atlas_quorum_sizes() == (6, 4)


def test_epaxos_parameters():
    ns = [3, 5, 7, 9, 11, 13, 15, 17]
    expected = [(2, 2), (3, 3), (5, 4), (6, 5), (8, 6), (9, 7), (11, 8), (12, 9)]
    assert [Config(n, 0).epaxos_quorum_sizes() for n in ns] == expected


def test_caesar_parameters():
    ns = [3, 5, 7, 9, 11]
    expected = [(3, 2), (4, 3), (6, 4), (7, 5), (9, 6)]
    assert [Config(n, 0).caesar_quorum_sizes() for n in ns] == expected


def test_newt_parameters():
    assert Config(7, 1, newt_tiny_quorums=False).newt_quorum_sizes() == (4, 2, 4)
    assert Config(7, 2, newt_tiny_quorums=False).newt_quorum_sizes() == (5, 3, 4)
    assert Config(7, 1, newt_tiny_quorums=True).newt_quorum_sizes() == (2, 2, 6)
    assert Config(7, 2, newt_tiny_quorums=True).newt_quorum_sizes() == (4, 3, 5)


def test_config_validation():
    with pytest.raises(ValueError):
        Config(3, 4)


# --- ids (reference: fantoch/src/id.rs, fantoch/src/util.rs:196+) ---


def test_process_id_layout():
    assert list(process_ids(0, 3)) == [1, 2, 3]
    assert list(process_ids(1, 3)) == [4, 5, 6]
    assert list(all_process_ids(2, 2)) == [(1, 0), (2, 0), (3, 1), (4, 1)]


def test_dot_target_shard():
    n = 3
    assert Dot(1, 10).target_shard(n) == 0
    assert Dot(3, 10).target_shard(n) == 0
    assert Dot(4, 10).target_shard(n) == 1
    assert Dot(6, 10).target_shard(n) == 1


def test_dot_ordering_and_packing():
    assert Dot(1, 2) < Dot(1, 3) < Dot(2, 1)
    d = Dot(200, 123456789)
    assert Dot.unpack(d.packed()) == d


def test_id_gen():
    gen = IdGen(7)
    assert gen.next_id() == Dot(7, 1)
    assert gen.next_id() == Dot(7, 2)


# --- kvs (reference: fantoch/src/kvs.rs:71-138) ---


def test_kvs_flow():
    store = KVStore()
    rifl = Rifl(1, 1)
    key = "key"
    assert store.execute(key, KVOp.get(), rifl) is None
    assert store.execute(key, KVOp.put("x"), rifl) is None
    assert store.execute(key, KVOp.get(), rifl) == "x"
    assert store.execute(key, KVOp.put("y"), rifl) == "x"
    assert store.execute(key, KVOp.delete(), rifl) == "y"
    assert store.execute(key, KVOp.get(), rifl) is None


# --- commands ---


def test_command_conflicts():
    a = Command.from_single(Rifl(1, 1), 0, "k1", KVOp.put("v"))
    b = Command.from_single(Rifl(1, 2), 0, "k1", KVOp.get())
    c = Command.from_single(Rifl(1, 3), 0, "k2", KVOp.get())
    assert a.conflicts(b)
    assert not a.conflicts(c)
    # same key on different shards does not conflict
    d = Command.from_single(Rifl(1, 4), 1, "k1", KVOp.get())
    assert not a.conflicts(d)


def test_command_read_only():
    ro = Command.from_keys(Rifl(1, 1), 0, {"a": (KVOp.get(),), "b": (KVOp.get(),)})
    rw = Command.from_keys(Rifl(1, 2), 0, {"a": (KVOp.put("v"),), "b": (KVOp.delete(),)})
    assert ro.read_only
    assert not rw.read_only


def test_command_from_single_matches_general_constructor():
    """from_single's __new__ fast path must stay equivalent to __init__
    (every derived field included), so adding a Command field without
    updating the fast path is caught here instead of drifting silently."""
    for op in (KVOp.put("v"), KVOp.get(), KVOp.delete()):
        rifl = Rifl(3, 7)
        fast = Command.from_single(rifl, 2, "key", op)
        general = Command(rifl, {2: {"key": (op,)}})
        assert fast == general
        assert fast.read_only == general.read_only
        assert fast.total_key_count == general.total_key_count
        assert fast.shard_count == general.shard_count
        assert list(fast.iter_ops(2)) == list(general.iter_ops(2))
    # the fast path must cover every slot __init__ fills — a new slot
    # would show up here as an AttributeError on the fast-path object
    fast = Command.from_single(Rifl(1, 1), 0, "k", KVOp.get())
    for slot in Command.__slots__:
        assert getattr(fast, slot) == getattr(Command(Rifl(1, 1), {0: {"k": (KVOp.get(),)}}), slot)


def test_command_result_aggregation():
    rifl = Rifl(9, 1)
    res = CommandResult(rifl, 2)
    assert not res.add_partial("a", (None,))
    assert not res.ready
    assert res.add_partial("b", ("v",))
    assert res.ready


# --- planet (reference: fantoch/src/planet/mod.rs:180-301, dat.rs:124-154) ---


def test_planet_gcp_dataset():
    planet = Planet.new("gcp")
    assert len(planet.regions()) == 20
    w1, w2 = Region("us-west1"), Region("us-west2")
    # floor of measured avg ping; intra-region latency is 0
    assert planet.ping_latency(w1, w2) == 25
    assert planet.ping_latency(w1, w1) == 0


def test_planet_aws_dataset():
    planet = Planet.new("aws")
    assert len(planet.regions()) == 19
    assert planet.ping_latency(Region("eu-west-1"), Region("eu-west-2")) == 10


def test_planet_sorted_by_distance():
    planet = Planet.new("gcp")
    sorted_regions = planet.sorted_by_distance(Region("us-west1"))
    # first entry is always the region itself at distance 0
    assert sorted_regions[0] == (0, Region("us-west1"))
    # distances ascend
    dists = [d for d, _ in sorted_regions]
    assert dists == sorted(dists)


def test_planet_equidistant():
    regions, planet = Planet.equidistant(10, 5)
    assert len(regions) == 5
    assert planet.ping_latency(regions[0], regions[1]) == 10
    assert planet.ping_latency(regions[2], regions[2]) == 0


def test_sort_processes_by_distance():
    planet = Planet.new("gcp")
    processes = [
        (1, 0, Region("asia-east1")),
        (2, 0, Region("us-west1")),
        (3, 0, Region("europe-west3")),
    ]
    ordered = sort_processes_by_distance(Region("us-west1"), planet, processes)
    assert ordered[0] == (2, 0)  # colocated first


def test_closest_process_per_shard():
    planet = Planet.new("gcp")
    processes = [
        (1, 0, Region("asia-east1")),
        (2, 1, Region("us-west1")),
        (3, 0, Region("us-west2")),
        (4, 1, Region("europe-west3")),
    ]
    closest = closest_process_per_shard(Region("us-west1"), planet, processes)
    assert closest == {1: 2, 0: 3}


# --- misc ---


def test_key_hash_stable():
    assert key_hash("CONFLICT") == key_hash("CONFLICT")
    assert key_hash("a") != key_hash("b")


def test_sim_time_monotonic():
    t = SimTime()
    t.set_millis(10)
    assert t.millis() == 10 and t.micros() == 10_000
    with pytest.raises(AssertionError):
        t.set_millis(5)


def test_histogram():
    h = Histogram()
    for v in [1, 2, 2, 3, 100]:
        h.increment(v)
    assert h.count == 5
    assert h.mean() == pytest.approx(21.6)
    assert h.percentile(0.5) == 2
    assert h.min() == 1 and h.max() == 100
    h2 = Histogram()
    h2.increment(7)
    h.merge(h2)
    assert h.count == 6


def test_zipf_key_gen_distribution():
    """ZipfKeyGen (key_gen.rs:15,102-108): keys are ranks 1..keys_per_shard
    x shard_count, low ranks dominate, and a higher coefficient skews
    harder toward rank 1."""
    import random as _random

    from fantoch_tpu.client.key_gen import KeyGenState, ZipfKeyGen

    def top1_share(coefficient, samples=20_000):
        state = KeyGenState(
            ZipfKeyGen(coefficient=coefficient, keys_per_shard=100),
            shard_count=1, client_id=7, rng=_random.Random(3),
        )
        counts = {}
        for _ in range(samples):
            k = state.gen_cmd_key()
            assert 1 <= int(k) <= 100
            counts[k] = counts.get(k, 0) + 1
        assert counts.get("1", 0) > counts.get("50", 0) > 0
        return counts["1"] / samples

    assert top1_share(2.0) > top1_share(1.0) > top1_share(0.5)


def test_conflict_rate_boundaries_deterministic():
    """conflict_rate 0/100 are deterministic (key_gen.rs:111-117)."""
    import random as _random

    from fantoch_tpu.client.key_gen import (
        CONFLICT_COLOR,
        ConflictRateKeyGen,
        KeyGenState,
    )

    always = KeyGenState(ConflictRateKeyGen(100), 1, 5, rng=_random.Random(1))
    never = KeyGenState(ConflictRateKeyGen(0), 1, 5, rng=_random.Random(1))
    for _ in range(50):
        assert always.gen_cmd_key() == CONFLICT_COLOR
        assert never.gen_cmd_key() == "5"


def test_zipf_workload_generates_multikey_commands():
    """A zipf workload generates distinct-key commands whose target shard
    is the first key's shard (workload.rs:136-177, 203)."""
    import random as _random

    from fantoch_tpu.client.key_gen import ZipfKeyGen
    from fantoch_tpu.client.workload import Workload
    from fantoch_tpu.core.ids import RiflGen

    w = Workload(
        shard_count=2,
        key_gen=ZipfKeyGen(coefficient=1.0, keys_per_shard=50),
        keys_per_command=2,
        commands_per_client=20,
        payload_size=4,
    )
    rifl_gen = RiflGen(9)
    state = w.initial_key_gen_state(9, rng=_random.Random(11))
    shards_seen = set()
    while True:
        out = w.next_cmd(rifl_gen, state)
        if out is None:
            break
        shard, cmd = out
        keys = sorted(k for s in cmd.shards() for k in cmd.keys(s))
        assert len(keys) == 2 and keys[0] != keys[1]
        # ops dicts preserve insertion order: the first inserted shard IS
        # the first generated key's shard (the routing target)
        assert shard == next(iter(cmd.shards()))
        shards_seen.add(shard)
    assert w.finished()
    assert shards_seen == {0, 1}  # deterministic with Random(11)
