"""Native C++ SCC resolver (fantoch_tpu/native) vs the Python oracle.

The native resolver is the C++ twin of the host Tarjan oracle
(executor/graph/tarjan.py; reference tarjan.rs:99-319): same contract —
SCC blocks contiguous and dot-sorted, dependencies before dependents,
missing-blocked components omitted.  These tests check the contract
directly on hand-built graphs, check per-key order equality against the
Python ``DependencyGraph`` oracle on randomized KeyDeps-shaped graphs,
and exercise the batched executor's stuck-residue path through both the
native and the Python-fallback resolvers.
"""

import os
import random
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_ops_resolve import (  # noqa: E402
    batch_arrays,
    oracle_per_key_order,
    random_functional_args,
)

from fantoch_tpu import native  # noqa: E402

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def csr_from_args(args):
    deps, src, seq, _slot = batch_arrays(args)
    n = len(args)
    rows = [[int(t) for t in deps[i] if t != -1] for i in range(n)]
    offsets = np.zeros(n + 1, dtype=np.int32)
    offsets[1:] = np.cumsum([len(r) for r in rows])
    targets = np.fromiter((t for r in rows for t in r), np.int32, offsets[-1])
    packed = (src.astype(np.int64) << 32) | seq.astype(np.int64)
    return offsets, targets, packed


def test_contract_chain_cycle_blocked():
    # chain 0<-1<-2 on one key; 2-cycle {3,4}; 5 blocked by a missing dep,
    # 6 chained behind 5 (blocked transitively)
    offsets = np.array([0, 0, 1, 2, 3, 4, 5, 6], np.int32)
    targets = np.array([0, 1, 4, 3, -2, 5], np.int32)
    dots = np.array([10, 11, 12, 20, 13, 30, 31], np.int64)
    order, sizes = native.resolve_sccs(offsets, targets, dots)
    assert order.tolist() == [0, 1, 2, 4, 3]  # cycle dot-sorted: 13 < 20
    assert sizes.tolist() == [1, 1, 1, 2, 2]


def test_matches_python_oracle_on_random_graphs():
    rng = random.Random(13)
    for _trial in range(20):
        args = random_functional_args(
            n=3, keys=["A", "B", "C"], cmds_per_key=rng.randint(1, 8), rng=rng
        )
        offsets, targets, packed = csr_from_args(args)
        order, _sizes = native.resolve_sccs(offsets, targets, packed)
        assert sorted(order.tolist()) == list(range(len(args)))
        per_key = {}
        for i in order.tolist():
            dot, keys, _ = args[i]
            for key in keys:
                per_key.setdefault(key, []).append(dot)
        expected, n_exec = oracle_per_key_order(3, args)
        assert n_exec == len(args)
        assert per_key == expected


def test_missing_blocked_components_omitted():
    # 0 depends on a missing dep; 1 and 2 chain behind it; 3 independent
    offsets = np.array([0, 1, 2, 3, 3], np.int32)
    targets = np.array([-2, 0, 1], np.int32)
    dots = np.array([1, 2, 3, 4], np.int64)
    order, sizes = native.resolve_sccs(offsets, targets, dots)
    assert order.tolist() == [3]
    assert sizes.tolist() == [1]


def _stuck_scenario_graph(config):
    """Feed the batched graph a directed 3-ring (stuck on device: no
    mutual edge) plus a trailing chain member, forcing the host residue
    resolver."""
    from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
    from fantoch_tpu.executor.graph.batched import BatchedDependencyGraph
    from fantoch_tpu.protocol.common.graph_deps import Dependency

    time = RunTime()
    graph = BatchedDependencyGraph(1, 0, config)
    shards = frozenset({0})
    d1, d2, d3, d4 = Dot(1, 1), Dot(2, 1), Dot(3, 1), Dot(1, 2)

    def cmd(dot):
        return Command.from_keys(
            Rifl(dot.source, dot.sequence), 0, {"A": (KVOp.put("v"),)}
        )

    # ring: d1 <- d3 <- d2 <- d1 (directed, no mutual pair) + d4 behind d1
    graph.handle_add(d1, cmd(d1), [Dependency(d3, shards)], time)
    graph.handle_add(d2, cmd(d2), [Dependency(d1, shards)], time)
    graph.handle_add(d3, cmd(d3), [Dependency(d2, shards)], time)
    graph.handle_add(d4, cmd(d4), [Dependency(d1, shards), Dependency(d2, shards), Dependency(d3, shards)], time)
    out = graph.commands_to_execute()
    rifls = [c.rifl for c in out]
    return rifls, [Rifl(1, 1), Rifl(2, 1), Rifl(3, 1), Rifl(1, 2)]


def test_batched_stuck_residue_native_and_python_agree(monkeypatch):
    from fantoch_tpu.core import Config

    config = Config(3, 1, batched_graph_executor=True)
    got_native, expected = _stuck_scenario_graph(config)
    assert got_native == expected

    # force the Python fallback and compare
    monkeypatch.setattr(native, "available", lambda: False)
    got_python, _ = _stuck_scenario_graph(config)
    assert got_python == got_native
