"""Multi-host mesh layout (parallel/multihost.py).

The virtual 8-device CPU backend is one process, so the true multi-host
branch is exercised through fake device records; the single-process path
runs against the real backend and must match make_mesh exactly — the
module's degrade-to-single-host contract."""

from dataclasses import dataclass

import numpy as np
import pytest

from fantoch_tpu.parallel.mesh_step import BATCH_AXIS, REPLICA_AXIS, make_mesh
from fantoch_tpu.parallel.multihost import (
    group_by_process,
    make_multihost_mesh,
)


@dataclass(frozen=True)
class FakeDev:
    id: int
    process_index: int


def test_single_process_defers_to_make_mesh():
    mesh = make_multihost_mesh(num_replicas=4)
    ref = make_mesh(num_replicas=4)
    assert mesh.axis_names == ref.axis_names == (REPLICA_AXIS, BATCH_AXIS)
    assert mesh.devices.shape == ref.devices.shape
    assert (mesh.devices == ref.devices).all()


def test_group_by_process_orders_hosts_and_chips():
    # interleaved arrival order, 2 hosts x 3 chips
    devs = [
        FakeDev(5, 1), FakeDev(0, 0), FakeDev(4, 1),
        FakeDev(2, 0), FakeDev(3, 1), FakeDev(1, 0),
    ]
    groups = group_by_process(devs)
    assert [[d.id for d in g] for g in groups] == [[0, 1, 2], [3, 4, 5]]
    assert [g[0].process_index for g in groups] == [0, 1]


def test_group_by_process_rejects_ragged_topology():
    devs = [FakeDev(0, 0), FakeDev(1, 0), FakeDev(2, 1)]
    with pytest.raises(ValueError, match="ragged"):
        group_by_process(devs)


def test_multihost_rows_are_hosts(monkeypatch):
    """4 hosts x 2 chips: replica axis must cross hosts (row p = host p),
    batch axis must stay on-host — the DCN/ICI layout contract."""
    import fantoch_tpu.parallel.multihost as mh

    devs = [FakeDev(h * 2 + c, h) for h in range(4) for c in range(2)]
    monkeypatch.setattr(mh.jax, "devices", lambda: devs)
    # Mesh would reject fake devices; capture the array it is built from
    captured = {}

    def fake_mesh(dev_array, axes):
        captured["array"] = np.array(dev_array)
        captured["axes"] = axes
        return "mesh-sentinel"

    monkeypatch.setattr(mh, "Mesh", fake_mesh)
    out = mh.make_multihost_mesh(num_replicas=4)
    assert out == "mesh-sentinel"
    assert captured["axes"] == (REPLICA_AXIS, BATCH_AXIS)
    arr = captured["array"]
    assert arr.shape == (4, 2)
    for host in range(4):
        assert {d.process_index for d in arr[host]} == {host}


def test_multihost_divisibility_contract(monkeypatch):
    import fantoch_tpu.parallel.multihost as mh

    devs = [FakeDev(h * 2 + c, h) for h in range(3) for c in range(2)]
    monkeypatch.setattr(mh.jax, "devices", lambda: devs)
    with pytest.raises(ValueError, match="multiple of the host count"):
        mh.make_multihost_mesh(num_replicas=4)  # 3 hosts


def test_distributed_init_noop_without_cluster(monkeypatch):
    import fantoch_tpu.parallel.multihost as mh

    for var in ("JAX_COORDINATOR_ADDRESS", "SLURM_JOB_ID", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(mh, "_DISTRIBUTED_INITIALIZED", False)
    assert mh.distributed_init() is False


def test_distributed_init_survives_half_present_cluster_env(monkeypatch):
    """A rig that sets TPU_WORKER_HOSTNAMES without a derivable
    coordinator (the single-chip axon host does exactly this) must fall
    back to single-host, not kill the server over a hint."""
    import fantoch_tpu.parallel.multihost as mh

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    monkeypatch.setattr(mh, "_DISTRIBUTED_INITIALIZED", False)

    def boom(**_kw):
        raise ValueError("coordinator_address should be defined.")

    monkeypatch.setattr(mh.jax.distributed, "initialize", boom)
    assert mh.distributed_init() is False
    # an EXPLICIT coordinator still fails loudly
    with pytest.raises(ValueError):
        mh.distributed_init(coordinator_address="10.0.0.1:1234")
