"""Multi-host mesh layout (parallel/multihost.py).

The virtual 8-device CPU backend is one process, so the true multi-host
branch is exercised through fake device records; the single-process path
runs against the real backend and must match make_mesh exactly — the
module's degrade-to-single-host contract."""

from dataclasses import dataclass

import numpy as np
import pytest

from fantoch_tpu.parallel.mesh_step import BATCH_AXIS, REPLICA_AXIS, make_mesh
from fantoch_tpu.parallel.multihost import (
    group_by_process,
    make_multihost_mesh,
)


@dataclass(frozen=True)
class FakeDev:
    id: int
    process_index: int


def test_single_process_defers_to_make_mesh():
    mesh = make_multihost_mesh(num_replicas=4)
    ref = make_mesh(num_replicas=4)
    assert mesh.axis_names == ref.axis_names == (REPLICA_AXIS, BATCH_AXIS)
    assert mesh.devices.shape == ref.devices.shape
    assert (mesh.devices == ref.devices).all()


def test_group_by_process_orders_hosts_and_chips():
    # interleaved arrival order, 2 hosts x 3 chips
    devs = [
        FakeDev(5, 1), FakeDev(0, 0), FakeDev(4, 1),
        FakeDev(2, 0), FakeDev(3, 1), FakeDev(1, 0),
    ]
    groups = group_by_process(devs)
    assert [[d.id for d in g] for g in groups] == [[0, 1, 2], [3, 4, 5]]
    assert [g[0].process_index for g in groups] == [0, 1]


def test_group_by_process_rejects_ragged_topology():
    devs = [FakeDev(0, 0), FakeDev(1, 0), FakeDev(2, 1)]
    with pytest.raises(ValueError, match="ragged"):
        group_by_process(devs)


def test_multihost_rows_are_hosts(monkeypatch):
    """4 hosts x 2 chips: replica axis must cross hosts (row p = host p),
    batch axis must stay on-host — the DCN/ICI layout contract."""
    import fantoch_tpu.parallel.multihost as mh

    devs = [FakeDev(h * 2 + c, h) for h in range(4) for c in range(2)]
    monkeypatch.setattr(mh.jax, "devices", lambda: devs)
    # Mesh would reject fake devices; capture the array it is built from
    captured = {}

    def fake_mesh(dev_array, axes):
        captured["array"] = np.array(dev_array)
        captured["axes"] = axes
        return "mesh-sentinel"

    monkeypatch.setattr(mh, "Mesh", fake_mesh)
    out = mh.make_multihost_mesh(num_replicas=4)
    assert out == "mesh-sentinel"
    assert captured["axes"] == (REPLICA_AXIS, BATCH_AXIS)
    arr = captured["array"]
    assert arr.shape == (4, 2)
    for host in range(4):
        assert {d.process_index for d in arr[host]} == {host}


def test_multihost_divisibility_contract(monkeypatch):
    import fantoch_tpu.parallel.multihost as mh

    devs = [FakeDev(h * 2 + c, h) for h in range(3) for c in range(2)]
    monkeypatch.setattr(mh.jax, "devices", lambda: devs)
    with pytest.raises(ValueError, match="multiple of the host count"):
        mh.make_multihost_mesh(num_replicas=4)  # 3 hosts


def test_multihost_mesh_counts_total_rows_not_per_shard(monkeypatch):
    """Sharded deployments size the mesh by n * shard_count rows
    (shard-major, mesh_step.shard_of_row); validating against per-shard n
    would accept meshes the device state cannot shard."""
    import fantoch_tpu.parallel.multihost as mh

    devs = [FakeDev(h * 2 + c, h) for h in range(3) for c in range(2)]
    monkeypatch.setattr(mh.jax, "devices", lambda: devs)
    monkeypatch.setattr(mh, "Mesh", lambda arr, axes: "mesh-sentinel")
    # n=2 x 3 shards = 6 total rows over 3 hosts: whole shard blocks per
    # host, accepted; per-shard n=2 alone would NOT divide by 3 hosts
    assert mh.make_multihost_mesh(num_replicas=6, shard_count=3) == "mesh-sentinel"
    with pytest.raises(ValueError, match="total replica rows"):
        mh.make_multihost_mesh(num_replicas=2, shard_count=1)


def test_multihost_mesh_warns_when_shard_blocks_straddle_hosts(monkeypatch, caplog):
    """Shard-major blocks that don't align with host rows demote the
    quorum fan-in to DCN — surfaced as a warning."""
    import logging

    import fantoch_tpu.parallel.multihost as mh

    devs = [FakeDev(h * 2 + c, h) for h in range(4) for c in range(2)]
    monkeypatch.setattr(mh.jax, "devices", lambda: devs)
    monkeypatch.setattr(mh, "Mesh", lambda arr, axes: "mesh-sentinel")
    with caplog.at_level(logging.WARNING, logger="fantoch_tpu"):
        # 8 rows = 2 shards x n=4 over 4 hosts: 2 rows/host < 4-row blocks
        mh.make_multihost_mesh(num_replicas=8, shard_count=2)
    assert any("shard blocks" in r.message for r in caplog.records)


def test_shard_of_row_is_shard_major():
    """Pin the replica-row order the sharded device state uses: shard s
    owns the contiguous block [s*n, (s+1)*n) (protocol_step's on-device
    row // per_shard), NOT a replica-major interleave."""
    from fantoch_tpu.parallel.mesh_step import shard_of_row

    n, shards = 3, 2
    total = n * shards
    assert [shard_of_row(r, total, shards) for r in range(total)] == [0, 0, 0, 1, 1, 1]
    # replica-major interleave would read [0, 1, 0, 1, 0, 1] — reject it
    assert [shard_of_row(r, total, shards) for r in range(total)] != [0, 1, 0, 1, 0, 1]


def test_distributed_init_auto_detect_times_out_fast(monkeypatch):
    """A runner with SLURM env vars but no peers must hit the short
    auto-detect barrier timeout, not jax's ~300 s default; an explicit
    coordinator keeps the long default."""
    import fantoch_tpu.parallel.multihost as mh

    captured = {}

    def fake_initialize(**kwargs):
        captured.update(kwargs)

    monkeypatch.setenv("SLURM_JOB_ID", "12345")
    monkeypatch.setattr(mh, "_DISTRIBUTED_INITIALIZED", False)
    monkeypatch.setattr(mh.jax.distributed, "initialize", fake_initialize)
    assert mh.distributed_init() is True
    assert captured["initialization_timeout"] == mh.AUTO_DETECT_INIT_TIMEOUT_S

    captured.clear()
    monkeypatch.setattr(mh, "_DISTRIBUTED_INITIALIZED", False)
    assert mh.distributed_init(coordinator_address="10.0.0.1:1234") is True
    assert "initialization_timeout" not in captured

    captured.clear()
    monkeypatch.setattr(mh, "_DISTRIBUTED_INITIALIZED", False)
    assert mh.distributed_init(initialization_timeout_s=7) is True
    assert captured["initialization_timeout"] == 7


def test_distributed_init_noop_without_cluster(monkeypatch):
    import fantoch_tpu.parallel.multihost as mh

    for var in ("JAX_COORDINATOR_ADDRESS", "SLURM_JOB_ID", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(mh, "_DISTRIBUTED_INITIALIZED", False)
    assert mh.distributed_init() is False


def test_distributed_init_survives_half_present_cluster_env(monkeypatch):
    """A rig that sets TPU_WORKER_HOSTNAMES without a derivable
    coordinator (the single-chip axon host does exactly this) must fall
    back to single-host, not kill the server over a hint."""
    import fantoch_tpu.parallel.multihost as mh

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    monkeypatch.setattr(mh, "_DISTRIBUTED_INITIALIZED", False)

    def boom(**_kw):
        raise ValueError("coordinator_address should be defined.")

    monkeypatch.setattr(mh.jax.distributed, "initialize", boom)
    assert mh.distributed_init() is False
    # an EXPLICIT coordinator still fails loudly
    with pytest.raises(ValueError):
        mh.distributed_init(coordinator_address="10.0.0.1:1234")
