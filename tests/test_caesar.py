"""Caesar commons + PredecessorsExecutor + whole-system sim tests
(reference rows: fantoch_ps/src/protocol/mod.rs:557-590 — wait/no-wait
n=3 f=1 and n=5 f=2 wait)."""

import itertools

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, Rifl
from fantoch_tpu.core.kvs import KVOp
from fantoch_tpu.executor.pred import PredecessorsExecutionInfo, PredecessorsExecutor
from fantoch_tpu.protocol import Caesar
from fantoch_tpu.protocol.common.pred_clocks import (
    Clock,
    QuorumClocks,
    SequentialKeyClocks,
)

from harness import sim_test

SHARD = 0


def cmd(seq: int, keys) -> Command:
    return Command.from_keys(
        Rifl(9, seq), SHARD, {k: (KVOp.put(str(seq)),) for k in keys}
    )


def test_clock_lexicographic_order():
    assert Clock(10, 1) < Clock(10, 2) < Clock(11, 1)
    assert Clock(9, 5).join(Clock(10, 1)) == Clock(10, 1)
    assert Clock(10, 1).join(Clock(10, 3)) == Clock(10, 3)
    assert Clock(10, 3).join(Clock(9, 9)) == Clock(10, 3)


def test_key_clocks_predecessors_split():
    clocks = SequentialKeyClocks(1, SHARD)
    a, b, c = Dot(1, 1), Dot(2, 1), Dot(3, 1)
    clocks.add(a, cmd(1, ["K"]), Clock(1, 1))
    clocks.add(b, cmd(2, ["K"]), Clock(3, 2))
    # command c proposed at clock (2, 3): a is lower -> predecessor; b is
    # higher -> blocks
    higher = set()
    deps = clocks.predecessors(c, cmd(3, ["K"]), Clock(2, 3), higher)
    assert deps == {a}
    assert higher == {b}
    # remove a: no longer reported
    clocks.remove(cmd(1, ["K"]), Clock(1, 1))
    assert clocks.predecessors(c, cmd(3, ["K"]), Clock(2, 3)) == set()


def test_quorum_clocks_early_slow_path():
    # fq=3, majority=2: a majority with one not-ok completes early
    q = QuorumClocks(1, 3, 2)
    q.add(1, Clock(1, 1), {Dot(1, 1)}, True)
    assert not q.all()
    q.add(2, Clock(2, 2), {Dot(2, 1)}, False)
    assert q.all(), "majority replied and someone rejected"
    clock, deps, ok = q.aggregated()
    assert clock == Clock(2, 2) and deps == {Dot(1, 1), Dot(2, 1)} and not ok


def test_pred_executor_timestamp_order():
    """Conflicting commands execute in clock order on every delivery
    permutation; phase 1 (committed) gates phase 2 (lower-clock executed)."""
    config = Config(n=3, f=1)
    infos = [
        PredecessorsExecutionInfo(Dot(1, 1), cmd(1, ["K"]), Clock(1, 1), set()),
        PredecessorsExecutionInfo(
            Dot(2, 1), cmd(2, ["K"]), Clock(2, 2), {Dot(1, 1)}
        ),
        PredecessorsExecutionInfo(
            Dot(3, 1), cmd(3, ["K"]), Clock(3, 3), {Dot(1, 1), Dot(2, 1)}
        ),
    ]
    for perm in itertools.permutations(range(3)):
        ex = PredecessorsExecutor(1, SHARD, config)
        executed = []
        for i in perm:
            ex.handle(infos[i], None)
            executed.extend(r.rifl.sequence for r in ex.to_clients_iter())
        assert executed == [1, 2, 3], f"wrong order for {perm}: {executed}"


def test_pred_executor_higher_clock_dep_not_waited():
    """A dep with a *higher* clock is not waited on in phase 2 (it waits for
    us instead) — only committed-ness is required."""
    config = Config(n=3, f=1)
    ex = PredecessorsExecutor(1, SHARD, config)
    # d2 at clock 5 depends on d1; d1 at clock 9 (higher) depends on d2
    ex.handle(
        PredecessorsExecutionInfo(Dot(1, 1), cmd(1, ["K"]), Clock(9, 1), {Dot(2, 1)}),
        None,
    )
    assert [r.rifl.sequence for r in ex.to_clients_iter()] == []
    ex.handle(
        PredecessorsExecutionInfo(Dot(2, 1), cmd(2, ["K"]), Clock(5, 2), {Dot(1, 1)}),
        None,
    )
    # d2 (lower clock) first, then d1
    assert [r.rifl.sequence for r in ex.to_clients_iter()] == [2, 1]


def test_pred_executor_noop_resolves_both_phases():
    """A recovery-committed noop (PredecessorsNoop) executes nothing but
    counts as committed AND executed, so dependents blocked on it in
    phase 1 (commit unknown) or phase 2 (lower-clock execution) drain."""
    from fantoch_tpu.executor.pred import PredecessorsNoop

    config = Config(n=3, f=1)
    ex = PredecessorsExecutor(1, SHARD, config)
    # d2 depends on the never-payloaded d1 (phase 1 blocks on its commit)
    ex.handle(
        PredecessorsExecutionInfo(Dot(2, 1), cmd(2, ["K"]), Clock(5, 2), {Dot(1, 1)}),
        None,
    )
    assert list(ex.to_clients_iter()) == []
    ex.handle(PredecessorsNoop(Dot(1, 1)), None)
    assert [r.rifl.sequence for r in ex.to_clients_iter()] == [2]
    # the executed clock drives Caesar's executed-everywhere GC: the noop
    # dot must be in it
    assert ex.executed(None).contains(1, 1)


def test_pred_executor_watchdog_reports_missing_and_fails_bounded():
    """The liveness watchdog: missing (uncommitted) dependency dots are
    reported for the recovery nudge below the bound, and a typed
    StalledExecutionError fires past Config.executor_pending_fail_ms —
    the bounded-wait contract extended to the predecessors executor."""
    import pytest as _pytest

    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.errors import StalledExecutionError

    config = Config(n=3, f=1, executor_pending_fail_ms=5000)
    ex = PredecessorsExecutor(1, SHARD, config)
    ex.handle(
        PredecessorsExecutionInfo(Dot(2, 1), cmd(2, ["K"]), Clock(5, 2), {Dot(1, 1)}),
        SimTime(0),
    )
    # below the fail bound: the missing dep surfaces for nudge_recovery
    assert ex.monitor_pending(SimTime(2000)) == {Dot(1, 1)}
    with _pytest.raises(StalledExecutionError) as err:
        ex.monitor_pending(SimTime(6000))
    assert Dot(1, 1) in err.value.missing[Dot(2, 1)]


def test_key_clocks_max_seq_excludes_the_recovering_dot():
    """The recovery promise floor: max indexed timestamp sequence on the
    command's keys, excluding the dot under recovery (every replica
    indexes the dot itself at propose time — a floor including it would
    lift unconditionally)."""
    clocks = SequentialKeyClocks(1, SHARD)
    a, b = Dot(1, 1), Dot(2, 1)
    clocks.add(a, cmd(1, ["K"]), Clock(7, 1))
    clocks.add(b, cmd(2, ["K"]), Clock(3, 2))
    assert clocks.max_seq(cmd(1, ["K"])) == 7
    assert clocks.max_seq(cmd(1, ["K"]), exclude=a) == 3
    assert clocks.max_seq(cmd(3, ["OTHER"])) == 0


def test_quorum_clocks_duplicate_ack_dedup():
    """Duplicate MProposeAck deliveries (at-least-once links) must not
    double-count a participant — the quorum would otherwise complete
    with fewer distinct reports (the PR 9 mcollectack dedup class)."""
    q = QuorumClocks(1, 3, 2)
    q.add(1, Clock(1, 1), {Dot(1, 1)}, True)
    assert q.contains(1) and not q.contains(2)
    q.add(2, Clock(2, 2), set(), True)
    assert not q.all(), "two DISTINCT reports are not the fq=3 quorum"


def test_caesar_recovery_adjust_lifts_above_floor_with_fresh_clock():
    """The free-choice lift: when the promise quorum's floor reaches the
    chosen clock, Caesar issues a FRESH unique timestamp above it and
    re-extends the predecessor union under it (a reused seq could
    collide with a timestamp this process already issued)."""
    from fantoch_tpu.protocol.caesar import CaesarConsensusValue

    config = Config(
        n=3, f=1, gc_interval_ms=100, recovery_delay_ms=500,
    )
    proto = Caesar(1, SHARD, config)
    ok, _ = proto.discover([(1, SHARD), (2, SHARD), (3, SHARD)])
    assert ok
    # local knowledge: a conflicting command indexed at seq 9
    conflict = Dot(2, 1)
    proto.key_clocks.add(conflict, cmd(2, ["K"]), Clock(9, 2))
    dot = Dot(3, 1)
    info = proto._cmds.get(dot)
    info.cmd = cmd(3, ["K"])
    low = CaesarConsensusValue(Clock(4, 3), ())
    lifted = proto._recovery_adjust_value(dot, info, low, floor=9)
    assert lifted.clock.seq > 9
    assert lifted.clock.process_id == 1, "a fresh clock is issued locally"
    assert conflict in lifted.deps, "predecessors re-extend under the lift"
    # below the floor: the chosen pair is untouched
    high = CaesarConsensusValue(Clock(12, 3), (conflict,))
    assert proto._recovery_adjust_value(dot, info, high, floor=9) == high
    # noop stays noop
    noop = CaesarConsensusValue.bottom()
    assert proto._recovery_adjust_value(dot, info, noop, floor=9) is noop


def caesar_config(n: int, f: int, wait: bool) -> Config:
    return Config(n=n, f=f, caesar_wait_condition=wait, gc_interval_ms=100)


def test_straggler_ack_after_quorum_completion_is_ignored():
    """MPropose goes to all n but the fast quorum (4 of 5) completes first;
    a 5th ack queued before the self-delivered MCommit flips the status
    must be ignored, not crash the worker (ADVICE r1: the reference panics
    here, reachable under the TCP runner's reader-task queueing)."""
    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.protocol.caesar import MCommit, MPropose, MProposeAck
    from fantoch_tpu.sim.runner import ToSend

    time = SimTime()
    config = caesar_config(5, 2, wait=True)
    caesar = Caesar(1, SHARD, config)
    assert caesar.discover([(pid, SHARD) for pid in range(1, 6)])

    dot = Dot(1, 1)
    caesar.submit(dot, cmd(1, ["K"]), time)
    actions = list(caesar.to_processes_iter())
    (propose,) = [a.msg for a in actions if isinstance(a.msg, MPropose)]

    # self-delivery of the MPropose produces the coordinator's own ack
    caesar.handle(1, SHARD, propose, time)
    actions = list(caesar.to_processes_iter())
    (ack,) = [a.msg for a in actions if isinstance(a.msg, MProposeAck)]
    assert ack.ok

    # the coordinator's own ack plus three identical acks complete the fast
    # quorum (fq = 3n//4+1 = 4) and queue the MCommit broadcast
    for from_ in (1, 2, 3, 4):
        caesar.handle(
            from_, SHARD, MProposeAck(dot, ack.clock, set(ack.deps), True), time
        )
    actions = list(caesar.to_processes_iter())
    assert any(
        isinstance(a, ToSend) and isinstance(a.msg, MCommit) for a in actions
    ), "fast quorum completion must broadcast MCommit"

    # the straggler: 5th ack arrives before the self-MCommit is handled
    caesar.handle(5, SHARD, MProposeAck(dot, ack.clock, set(ack.deps), True), time)
    assert list(caesar.to_processes_iter()) == [], "straggler ack is a no-op"


def test_caesar_wait_3_1():
    sim_test(Caesar, caesar_config(3, 1, wait=True))


def test_caesar_no_wait_3_1():
    sim_test(Caesar, caesar_config(3, 1, wait=False))


def test_caesar_wait_5_2():
    sim_test(Caesar, caesar_config(5, 2, wait=True), seed=2)


def test_pred_executor_batched_oracle_equivalence():
    """The batched two-phase kernel (Config.batched_pred_executor ->
    ops/pred_resolve.resolve_pred) executes exactly what the per-info
    host path executes, in the same per-key order — across shuffled
    delivery, multi-key deps, and batch boundaries that leave
    missing-blocked residues."""
    import random

    rng = random.Random(5)
    for _trial in range(5):
        keys = ["Ka", "Kb", "Kc"]
        per_key = {k: [] for k in keys}
        infos = []
        for i in range(40):
            src = rng.randrange(1, 4)
            dot = Dot(src, i + 1)
            ks = rng.sample(keys, rng.randrange(1, 3))
            deps = set()
            for k in ks:
                deps.update(per_key[k])
                per_key[k].append(dot)
            infos.append(
                PredecessorsExecutionInfo(
                    dot, cmd(i + 1, ks), Clock(i + 1, src), deps
                )
            )
        shuffled = infos[:]
        rng.shuffle(shuffled)
        batches = []
        at = 0
        while at < len(shuffled):
            size = rng.randrange(1, 9)
            batches.append(shuffled[at : at + size])
            at += size

        ex_b = PredecessorsExecutor(
            1, SHARD,
            Config(3, 1, batched_pred_executor=True,
                   executor_monitor_execution_order=True),
        )
        ex_s = PredecessorsExecutor(
            1, SHARD,
            Config(3, 1, executor_monitor_execution_order=True),
        )
        for batch in batches:
            ex_b.handle_batch(batch, None)
            for info in batch:
                ex_s.handle(info, None)
        got = sorted(r.rifl for r in ex_b.to_clients_iter())
        want = sorted(r.rifl for r in ex_s.to_clients_iter())
        assert got == want and len(want) == sum(
            c.key_count(SHARD) for c in (i.cmd for i in infos)
        )
        mon_b, mon_s = ex_b.monitor(), ex_s.monitor()
        assert set(mon_b.keys()) == set(mon_s.keys())
        for key in mon_b.keys():
            assert mon_b.get_order(key) == mon_s.get_order(key)
