"""Fault-tolerance matrix: the paper's actual claim, finally under test.

The leaderless protocols fantoch reproduces stay live and linearizable
with up to ``f`` crashed replicas over a lossy network.  These tests drive
the deterministic nemesis (fantoch_tpu/sim/faults.py), the recovery plane
(fantoch_tpu/protocol/recovery.py) and the crash-tolerant run layer
(fantoch_tpu/run/links.py + process_runner.py) through that claim:

* **Determinism** — same FaultPlan seed twice => byte-identical fault
  trace and committed/executed-command trace (with or without recovery).
* **Liveness under crash + loss** — crash replicas mid-run under >= 10%
  message loss (retransmitted: lossy network, quasi-reliable channels —
  exactly what the protocols assume of TCP); surviving clients' commands
  all commit and execute with write-order agreement across surviving
  replicas.
* **Recovery** (``recovery`` marker) — with ``Config.recovery_delay_ms``
  set, crashing *fast-quorum members and coordinators of in-flight
  commands* (the scenarios that used to assert a typed stall) heals:
  overdue dots go through MPrepare/MPromise recovery, commit (as noops
  when never payloaded), and every surviving client completes.  FPaxos
  survives a leader crash via MultiSynod failover, in sim and over TCP.
* **Bounded wait** — where liveness is *not* achievable (recovery
  disabled, or more than f failures so no n-f promise quorum exists), the
  run surfaces a typed error (StalledExecutionError / SimStalledError)
  whose message says whether recovery ran and why it could not proceed.
* **Run layer** — severing live TCP connections mid-run triggers
  reconnect-with-backoff + seq/ack resend and the workload completes;
  losing peers past quorum surfaces a typed QuorumLostError.

Topology note: fast quorums are fixed per command at submit time
(BaseProcess.discover).  The no-recovery crash-liveness rows use a planet
where the crashed replicas are the farthest from everyone — outside every
survivor's fast quorum (the papers' deployment argument).  The recovery
rows do the opposite: ``far=0`` puts every crashed replica inside live
fast quorums, which stalled forever before PR 3.
"""

import asyncio
import os

import pytest

from fantoch_tpu.client import ConflictRateKeyGen, Workload
from fantoch_tpu.core import Config, Planet
from fantoch_tpu.core.planet import Region
from fantoch_tpu.errors import (
    QuorumLostError,
    SimStalledError,
    StalledExecutionError,
)
from fantoch_tpu.protocol import Atlas, Basic, Caesar, EPaxos, FPaxos, Newt
from fantoch_tpu.sim import Runner
from fantoch_tpu.sim.faults import FaultPlan

from harness import check_monitors

pytestmark = pytest.mark.chaos

# CI-shrunk load, like tests/harness.py
COMMANDS_PER_CLIENT = 5 if os.environ.get("CI") else 10
CLIENTS_PER_PROCESS = 2


def edge_planet(n, far=1):
    """n regions where the last ``far`` are 200ms from everyone and the
    rest are ~10ms apart: the far replicas land outside every core
    replica's fast quorum (distance-sorted, BaseProcess.discover)."""
    regions = [Region(f"r{i}") for i in range(n)]
    latencies = {}
    for i, a in enumerate(regions):
        latencies[a] = {}
        for j, b in enumerate(regions):
            if i == j:
                d = 0
            elif i >= n - far or j >= n - far:
                d = 200
            else:
                d = 10 + abs(i - j)
            latencies[a][b] = d
    return regions, Planet.from_latencies(latencies)


def chaos_sim(
    protocol_cls,
    config: Config,
    plan: FaultPlan,
    far: int = 1,
    clients_on_far: bool = False,
    commands_per_client: int = COMMANDS_PER_CLIENT,
    conflict_rate: int = 50,
    keys_per_command: int = 2,
    seed: int = 0,
    extra_sim_time_ms: int = 2000,
):
    """Run one nemesis scenario; returns (runner, metrics, monitors)."""
    n = config.n
    regions, planet = edge_planet(n, far)
    config = config.with_(
        executor_monitor_execution_order=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        shard_count=1,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(conflict_rate),
        keys_per_command=keys_per_command,
        commands_per_client=commands_per_client,
        payload_size=1,
    )
    client_regions = regions if clients_on_far else regions[: n - far]
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        CLIENTS_PER_PROCESS,
        process_regions=regions,
        client_regions=list(client_regions),
        seed=seed,
        fault_plan=plan,
    )
    metrics, monitors, _latencies = runner.run(extra_sim_time_ms=extra_sim_time_ms)
    return runner, metrics, monitors


def assert_survivors_done_and_agree(runner, monitors, crashed_ids):
    """Liveness + safety: every client not attached to a crashed replica
    finished its whole workload, and all surviving replicas executed
    conflicting writes in the same order."""
    crashed = set(crashed_ids)
    for client_id, client in runner._simulation.clients():
        if client.targets() & crashed:
            continue  # abandoned with its crashed replica
        assert client.issued_commands == COMMANDS_PER_CLIENT, (
            f"surviving client {client_id} finished only "
            f"{client.issued_commands}/{COMMANDS_PER_CLIENT} commands"
        )
    check_monitors({pid: m for pid, m in monitors.items() if pid not in crashed})


def crash_loss_plan(n, loss, seed=7, crash_at_ms=150, crashed=1):
    plan = FaultPlan(seed=seed, max_sim_time_ms=300_000).with_loss(loss)
    for k in range(crashed):
        plan = plan.with_crash(n - k, at_ms=crash_at_ms)
    return plan


# --- determinism: same seed => byte-identical traces ---


def _determinism_traces():
    plan = (
        FaultPlan(seed=11, max_sim_time_ms=300_000)
        .with_loss(0.2)
        .with_link_fault(duplicate=0.3, msg_types=("MCollect", "MCommit"))
        .with_link_fault(extra_delay_ms=40)
        .with_crash(5, at_ms=200)
        .with_partition([(1,), (2, 3)], start_ms=100, heal_ms=400)
    )
    runner, metrics, monitors = chaos_sim(EPaxos, Config(5, 1), plan)
    committed = {
        pid: (sorted(str(k) for k in m.keys()), repr(m)) for pid, m in monitors.items()
    }
    return runner.nemesis.trace_lines(), runner.nemesis.trace_digest(), committed


def test_fault_plan_determinism():
    """Same FaultPlan seed twice over the same sim => identical fault
    trace (every drop/retransmit/duplicate decision) AND identical
    committed/executed order on every process."""
    trace_a, digest_a, committed_a = _determinism_traces()
    trace_b, digest_b, committed_b = _determinism_traces()
    assert trace_a == trace_b
    assert digest_a == digest_b
    assert committed_a == committed_b
    assert trace_a, "the plan must actually have injected faults"


# --- liveness: crash f mid-run under message loss ---


def test_crash_epaxos_5_under_loss():
    runner, _metrics, monitors = chaos_sim(
        EPaxos, Config(5, 1), crash_loss_plan(5, loss=0.15)
    )
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[5])
    # the crash actually happened and bit: messages died on the dead link
    kinds = {kind for _t, kind, _d in runner.nemesis.trace}
    assert {"crash", "retransmit", "drop-dead"} <= kinds


def test_crash_atlas_5_1_under_loss():
    runner, _metrics, monitors = chaos_sim(
        Atlas, Config(5, 1), crash_loss_plan(5, loss=0.15)
    )
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[5])


def test_crash_newt_5_1_under_loss():
    runner, _metrics, monitors = chaos_sim(
        Newt,
        Config(5, 1, newt_detached_send_interval_ms=100),
        crash_loss_plan(5, loss=0.15),
    )
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[5])


def test_atlas_5_2_two_replica_outage():
    """Atlas f=2: two replicas fail mid-run — one crashes for good (the
    far one, outside every fast quorum), one fast-quorum member pauses
    and heals (with fq = n//2 + f = 4 of 5, every in-flight command needs
    it; a *permanent* second crash requires the recovery protocol, which
    is explicitly NotImplemented).  Everything must commit and agree."""
    plan = (
        FaultPlan(seed=5, max_sim_time_ms=600_000)
        .with_loss(0.10)
        .with_crash(5, at_ms=150)
        .with_pause(4, at_ms=300, until_ms=1500)
    )
    runner, _metrics, monitors = chaos_sim(Atlas, Config(5, 2), plan)
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[5])


def test_crash_abandons_attached_clients():
    """Clients attached to a crashed replica are abandoned (counted out of
    the run) while everyone else's workload completes."""
    plan = FaultPlan(seed=3, max_sim_time_ms=300_000).with_loss(0.1).with_crash(
        3, at_ms=120
    )
    runner, _metrics, _monitors = chaos_sim(
        Basic, Config(3, 1), plan, clients_on_far=True
    )
    abandoned = [
        client_id
        for client_id, client in runner._simulation.clients()
        if 3 in client.targets()
    ]
    assert abandoned, "the far replica should have had attached clients"
    for client_id, client in runner._simulation.clients():
        if client_id in abandoned:
            assert client.issued_commands < COMMANDS_PER_CLIENT
        else:
            assert client.issued_commands == COMMANDS_PER_CLIENT
    assert any(kind == "clients-abandoned" for _t, kind, _d in runner.nemesis.trace)


def test_partition_heal_epaxos():
    """A symmetric partition that heals: crossing messages are deferred
    (connection-retry semantics), nothing is lost, everything commits
    once the cut heals — including the minority side's clients."""
    plan = (
        FaultPlan(seed=9, max_sim_time_ms=300_000)
        .with_loss(0.05)
        .with_partition([(1,), (2, 3)], start_ms=100, heal_ms=500)
    )
    runner, _metrics, monitors = chaos_sim(
        EPaxos, Config(3, 1), plan, far=0, clients_on_far=True
    )
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[])
    assert any(kind == "defer-partition" for _t, kind, _d in runner.nemesis.trace)


# --- bounded wait: stalls surface typed errors, never hang ---


def test_executor_stall_surfaces_typed_error():
    """With recovery disabled, permanently isolating a coordinator strands
    its in-flight dots in the survivors' dependency sets: their graph
    executors must raise a typed StalledExecutionError naming the missing
    dots (bounded wait), not wait forever — and the message must say
    recovery was disabled."""
    config = Config(
        5,
        1,
        executor_monitor_pending_interval_ms=500,
        executor_pending_fail_ms=5_000,
    )
    plan = (
        FaultPlan(seed=2, max_sim_time_ms=60_000)
        .with_link_fault(src=5, drop=1.0, retransmit=False, from_ms=600)
        .with_link_fault(dst=5, drop=1.0, retransmit=False, from_ms=600)
    )
    with pytest.raises((StalledExecutionError, SimStalledError)) as err:
        chaos_sim(
            EPaxos,
            config,
            plan,
            clients_on_far=True,
            conflict_rate=100,
            keys_per_command=1,
            commands_per_client=20,
        )
    if isinstance(err.value, StalledExecutionError):
        # the missing dependencies are the isolated coordinator's dots
        assert err.value.missing
        assert all(
            dep.source == 5 for deps in err.value.missing.values() for dep in deps
        )
        assert "recovery disabled" in str(err.value)


def test_crashed_quorum_member_stall_bounded_without_recovery():
    """Without recovery_delay_ms, crashing a fast-quorum member stalls
    in-flight collects forever; the sim's virtual-time bound must convert
    the hang into a typed SimStalledError listing the waiting clients.
    (The recovery rows below run the same scenario and assert completion
    instead.)"""
    plan = FaultPlan(seed=1, max_sim_time_ms=20_000).with_crash(2, at_ms=100)
    with pytest.raises(SimStalledError) as err:
        chaos_sim(
            EPaxos, Config(3, 1), plan, far=0, conflict_rate=100, keys_per_command=1
        )
    assert err.value.waiting_clients


# --- recovery: the same crashes now heal (protocol/recovery.py) ---

recovery = pytest.mark.recovery

RECOVERY_33 = Config(3, 1, recovery_delay_ms=1000)
RECOVERY_PLAN_33 = FaultPlan(seed=1, max_sim_time_ms=120_000).with_crash(2, at_ms=100)


@recovery
@pytest.mark.parametrize(
    "protocol_cls,config",
    [
        (EPaxos, RECOVERY_33),
        (Atlas, RECOVERY_33),
        (EPaxos, RECOVERY_33.with_(batched_graph_executor=True)),
        (Newt, RECOVERY_33.with_(newt_detached_send_interval_ms=100)),
        # Caesar: the coordinator crash heals through the (clock, preds)
        # recovery synod; the executor watchdog nudges dots stranded in
        # the wait-condition region (PR 12 closed the carve-out)
        (Caesar, RECOVERY_33.with_(executor_monitor_pending_interval_ms=500)),
    ],
    ids=["epaxos", "atlas", "epaxos-batched", "newt", "caesar"],
)
def test_recovery_quorum_member_crash_completes(protocol_cls, config):
    """The exact scenario that used to assert SimStalledError: a crashed
    fast-quorum member at n=3/f=1 (far=0: it sits in every live fast
    quorum).  With recovery on, every surviving client completes and the
    execution-order monitors agree."""
    runner, _metrics, monitors = chaos_sim(
        protocol_cls, config, RECOVERY_PLAN_33, far=0,
        conflict_rate=100, keys_per_command=1,
    )
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[2])
    # the slow/recovery path was actually exercised, not a lucky fast run
    assert any(kind == "crash" for _t, kind, _d in runner.nemesis.trace)


@recovery
def test_recovery_epaxos_5_2_double_crash_under_loss():
    """n=5 with two crashed processes (coordinators of in-flight commands
    included) under 15% message loss: recovery heals everything the
    survivors owe."""
    plan = (
        FaultPlan(seed=7, max_sim_time_ms=300_000)
        .with_loss(0.15)
        .with_crash(2, at_ms=150)
        .with_crash(4, at_ms=250)
    )
    runner, _metrics, monitors = chaos_sim(
        EPaxos, Config(5, 2, recovery_delay_ms=1500), plan, far=0
    )
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[2, 4])


@recovery
def test_recovery_determinism():
    """Recovery decisions are deterministic: same plan + seed twice under
    crash + loss + recovery => byte-identical fault traces."""
    plan = (
        FaultPlan(seed=3, max_sim_time_ms=120_000)
        .with_loss(0.1)
        .with_crash(2, at_ms=120)
    )

    def digest():
        runner, _m, monitors = chaos_sim(
            EPaxos, Config(3, 1, recovery_delay_ms=1000), plan, far=0
        )
        assert_survivors_done_and_agree(runner, monitors, crashed_ids=[2])
        return runner.nemesis.trace_digest()

    assert digest() == digest()


@recovery
def test_recovery_noop_payload_starved_dots():
    """The noop path: p3's payload broadcasts are blackholed (true loss),
    p3 acks other commands normally (its key-deps reference its own
    stranded dots), then p3 crashes.  Survivors commit commands whose deps
    name dots payloaded at NO live process; the executor watchdog nudges
    the recovery plane and they heal as committed noops."""
    from fantoch_tpu.core.planet import Region

    regions = [Region("r0"), Region("r1"), Region("r2")]
    lat = {
        regions[0]: {regions[0]: 0, regions[1]: 20, regions[2]: 5},
        regions[1]: {regions[0]: 20, regions[1]: 0, regions[2]: 20},
        regions[2]: {regions[0]: 5, regions[1]: 20, regions[2]: 0},
    }
    planet = Planet.from_latencies(lat)
    config = Config(
        3,
        1,
        recovery_delay_ms=400,
        executor_monitor_pending_interval_ms=200,
        executor_pending_fail_ms=30_000,
        executor_monitor_execution_order=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(100),
        keys_per_command=1,
        commands_per_client=8,
        payload_size=1,
    )
    plan = (
        FaultPlan(seed=2, max_sim_time_ms=120_000)
        .with_link_fault(src=3, drop=1.0, retransmit=False, msg_types=("MCollect",))
        .with_crash(3, at_ms=300)
    )
    runner = Runner(
        EPaxos, planet, config, workload, CLIENTS_PER_PROCESS,
        process_regions=regions, client_regions=regions,
        seed=0, fault_plan=plan,
    )
    _metrics, monitors, _lat = runner.run(extra_sim_time_ms=2000)
    for _cid, client in runner._simulation.clients():
        if 3 in client.targets():
            continue
        assert client.issued_commands == 8
    check_monitors({pid: m for pid, m in monitors.items() if pid != 3})


@recovery
def test_recovery_below_quorum_is_still_bounded():
    """More than f crashes (2 of n=3): recovery cannot gather an n-f
    promise quorum, so the run must still fail with a *typed* error
    rather than hang — the bounded-wait contract survives the recovery
    plane."""
    config = Config(
        3,
        1,
        recovery_delay_ms=500,
        executor_monitor_pending_interval_ms=300,
        executor_pending_fail_ms=4_000,
    )
    plan = (
        FaultPlan(seed=4, max_sim_time_ms=30_000)
        .with_crash(2, at_ms=30)
        .with_crash(3, at_ms=60)
    )
    with pytest.raises((StalledExecutionError, SimStalledError)) as err:
        chaos_sim(
            EPaxos,
            config,
            plan,
            far=0,
            conflict_rate=100,
            keys_per_command=1,
            commands_per_client=30,
        )
    if isinstance(err.value, StalledExecutionError):
        assert "recovery was attempted" in str(err.value)


@recovery
def test_stall_error_names_recovery_attempt():
    """The executor watchdog's StalledExecutionError must say whether
    recovery ran: with recovery_delay_ms set, the message names the
    attempt and the likely cause (no n-f promise quorum)."""
    from fantoch_tpu.core import Command, KVOp, Rifl
    from fantoch_tpu.core.ids import Dot
    from fantoch_tpu.core.timing import SimTime
    from fantoch_tpu.executor.graph.executor import GraphAdd, GraphExecutor
    from fantoch_tpu.protocol.common.graph_deps import Dependency

    config = Config(
        3,
        1,
        recovery_delay_ms=100,
        executor_pending_fail_ms=500,
        executor_monitor_pending_interval_ms=100,
    )
    executor = GraphExecutor(1, 0, config)
    executor.set_executor_index(0)
    time = SimTime()
    cmd = Command.from_keys(Rifl(1, 1), 0, {"A": (KVOp.put("v"),)})
    missing_dep = Dependency(Dot(3, 1), frozenset({0}))
    executor.handle(GraphAdd(Dot(1, 1), cmd, {missing_dep}), time)
    time.set_millis(1_000)
    with pytest.raises(StalledExecutionError) as err:
        executor.monitor_pending(time)
    assert "recovery was attempted every 100ms" in str(err.value)
    # the same watchdog pass, below the fail bound, returns the missing
    # dots so the runner can nudge the recovery plane
    executor2 = GraphExecutor(1, 0, config.with_(executor_pending_fail_ms=10_000))
    executor2.set_executor_index(0)
    time2 = SimTime()
    executor2.handle(GraphAdd(Dot(1, 1), cmd, {missing_dep}), time2)
    time2.set_millis(2_000)
    assert executor2.monitor_pending(time2) == {Dot(3, 1)}


@recovery
def test_recovery_fpaxos_sim_leader_failover():
    """Crash the FPaxos leader mid-run: the ring successor elects itself
    through MultiSynod prepare/promise, carries accepted slots forward,
    and every surviving client completes."""
    config = Config(3, 1, leader=1, fpaxos_leader_timeout_ms=400)
    plan = FaultPlan(seed=5, max_sim_time_ms=120_000).with_crash(1, at_ms=150)
    runner, _metrics, monitors = chaos_sim(FPaxos, config, plan, far=0)
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[1])
    for pid in (2, 3):
        proto = runner._simulation.get_process(pid)[0]
        assert proto._leader == 2, (pid, proto._leader)


@recovery
def test_recovery_fpaxos_tcp_leader_failover():
    """Kill the FPaxos leader's runtime mid-run over real TCP: the
    heartbeat failure detector triggers on_peer_down, p2 wins the
    election, and both client pools complete against the survivors."""
    from fantoch_tpu.run.client_runner import run_clients
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.links import ReconnectPolicy
    from fantoch_tpu.run.process_runner import ProcessRuntime

    commands = 20

    async def scenario():
        config = Config(
            n=3,
            f=1,
            leader=1,
            fpaxos_leader_timeout_ms=2000,
            executor_monitor_execution_order=True,
            gc_interval_ms=50,
            executor_executed_notification_interval_ms=50,
        )
        peer_ports = {pid: free_port() for pid in (1, 2, 3)}
        client_ports = {pid: free_port() for pid in (1, 2, 3)}
        runtimes = {}
        for pid in (1, 2, 3):
            runtimes[pid] = ProcessRuntime(
                FPaxos,
                pid,
                0,
                config,
                listen_addr=("127.0.0.1", peer_ports[pid]),
                client_addr=("127.0.0.1", client_ports[pid]),
                peers={
                    p: ("127.0.0.1", peer_ports[p]) for p in (1, 2, 3) if p != pid
                },
                sorted_processes=[(pid, 0)]
                + [(p, 0) for p in (1, 2, 3) if p != pid],
                reconnect_policy=ReconnectPolicy(attempts=3, base_s=0.02, cap_s=0.1),
                heartbeat_interval_s=0.1,
                heartbeat_misses=5,
            )
        await asyncio.gather(*(r.start() for r in runtimes.values()))
        workload = Workload(
            shard_count=1,
            key_gen=ConflictRateKeyGen(50),
            keys_per_command=2,
            commands_per_client=commands,
            payload_size=1,
        )

        async def chaos():
            await asyncio.sleep(0.15)
            await runtimes[1].stop()  # kill the leader

        client_task = asyncio.gather(
            run_clients(
                [1, 2], {0: ("127.0.0.1", client_ports[2])}, workload,
                open_loop_interval_ms=10,
            ),
            run_clients(
                [3, 4], {0: ("127.0.0.1", client_ports[3])}, workload,
                open_loop_interval_ms=10,
            ),
        )
        chaos_task = asyncio.ensure_future(chaos())
        results = await asyncio.wait_for(client_task, timeout=120)
        await chaos_task
        # the workload may have outrun the kill; the election itself is
        # driven by the failure detector, so wait for it regardless
        deadline = asyncio.get_running_loop().time() + 30
        while asyncio.get_running_loop().time() < deadline:
            if all(runtimes[pid].process._leader == 2 for pid in (2, 3)):
                break
            await asyncio.sleep(0.1)
        leaders = {pid: runtimes[pid].process._leader for pid in (2, 3)}
        failures = {pid: runtimes[pid].failure for pid in (2, 3)}
        await asyncio.gather(*(runtimes[pid].stop() for pid in (2, 3)))
        return results, leaders, failures

    results, leaders, failures = asyncio.run(scenario())
    for group in results:
        for cid, client in group.items():
            assert client.issued_commands == commands, (cid, client.issued_commands)
    assert leaders == {2: 2, 3: 2}, leaders
    assert failures == {2: None, 3: None}, failures


@recovery
@pytest.mark.slow
@pytest.mark.parametrize("loss", [0.1, 0.3])
@pytest.mark.parametrize(
    "protocol_cls,config",
    [
        (EPaxos, Config(5, 2, recovery_delay_ms=1500)),
        (Atlas, Config(5, 2, recovery_delay_ms=1500)),
        (
            Newt,
            Config(
                5, 2, recovery_delay_ms=1500, newt_detached_send_interval_ms=100
            ),
        ),
        (
            Caesar,
            Config(
                5, 2, recovery_delay_ms=1500,
                executor_monitor_pending_interval_ms=500,
            ),
        ),
    ],
    ids=["epaxos", "atlas", "newt", "caesar"],
)
def test_recovery_crash_matrix_5_2(protocol_cls, config, loss):
    """Acceptance matrix: n=5/f=2, two crashed processes inside live fast
    quorums, 10-30% message loss — all surviving clients complete with
    order agreement."""
    plan = (
        FaultPlan(seed=13, max_sim_time_ms=600_000)
        .with_loss(loss)
        .with_crash(2, at_ms=150)
        .with_crash(4, at_ms=250)
    )
    runner, _metrics, monitors = chaos_sim(protocol_cls, config, plan, far=0)
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[2, 4])


# --- the slow rows: crash x loss x protocol sweep ---


@pytest.mark.slow
@pytest.mark.parametrize("loss", [0.1, 0.3])
@pytest.mark.parametrize(
    "protocol_cls,config",
    [
        (EPaxos, Config(5, 1)),
        (Atlas, Config(5, 1)),
        (Atlas, Config(5, 1, batched_graph_executor=True)),
        (Newt, Config(5, 1, newt_detached_send_interval_ms=100)),
    ],
    ids=["epaxos", "atlas", "atlas-batched", "newt"],
)
def test_crash_matrix(protocol_cls, config, loss):
    runner, _metrics, monitors = chaos_sim(
        protocol_cls, config, crash_loss_plan(5, loss=loss)
    )
    assert_survivors_done_and_agree(runner, monitors, crashed_ids=[5])


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_determinism_across_seeds(seed):
    """Different seeds explore different schedules; each is individually
    reproducible."""
    plan = (
        FaultPlan(seed=seed, max_sim_time_ms=300_000)
        .with_loss(0.25)
        .with_crash(5, at_ms=100 + 50 * seed)
    )
    first = chaos_sim(EPaxos, Config(5, 1), plan)[0].nemesis.trace_digest()
    second = chaos_sim(EPaxos, Config(5, 1), plan)[0].nemesis.trace_digest()
    assert first == second


# --- run layer: reconnect + quorum degradation over real TCP ---


def test_run_reconnect_completes_workload():
    """Severing every one of a peer's live TCP connections mid-run must
    trigger reconnect-with-backoff + seq/ack resend, and the cluster
    completes the whole workload with no runtime failure and no peer
    declared dead."""
    from fantoch_tpu.run.harness import run_localhost_cluster
    from fantoch_tpu.run.links import ReconnectPolicy

    commands = 20

    async def chaos(runtimes):
        await asyncio.sleep(0.3)
        severed = runtimes[3].inject_link_failure()
        assert severed > 0, "the chaos hook found no live sockets to sever"
        for pid in (1, 2):
            runtimes[pid].inject_link_failure(peer_id=3)

    async def scenario():
        config = Config(
            n=3,
            f=1,
            executor_monitor_execution_order=True,
            gc_interval_ms=50,
            executor_executed_notification_interval_ms=50,
        )
        workload = Workload(
            shard_count=1,
            key_gen=ConflictRateKeyGen(50),
            keys_per_command=2,
            commands_per_client=commands,
            payload_size=1,
        )
        return await run_localhost_cluster(
            EPaxos,
            config,
            workload,
            2,
            open_loop_interval_ms=10,
            extra_run_time_ms=500,
            runtime_kwargs=dict(
                reconnect_policy=ReconnectPolicy(attempts=10, base_s=0.02, cap_s=0.2),
                heartbeat_interval_s=0.2,
                heartbeat_misses=25,
            ),
            chaos=chaos,
        )

    runtimes, clients = asyncio.run(scenario())
    for client in clients.values():
        assert client.issued_commands == commands
    for pid, runtime in runtimes.items():
        assert runtime.failure is None, (pid, runtime.failure)
        assert not runtime.dead_peers, (pid, runtime.dead_peers)


def test_run_below_quorum_typed_failure():
    """Killing peers past the quorum line must surface a clean, typed
    QuorumLostError through ProcessRuntime.failed — never a hang."""
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.links import ReconnectPolicy
    from fantoch_tpu.run.process_runner import ProcessRuntime

    async def scenario():
        config = Config(n=3, f=1, gc_interval_ms=50)
        peer_ports = {pid: free_port() for pid in (1, 2, 3)}
        client_ports = {pid: free_port() for pid in (1, 2, 3)}
        runtimes = {}
        for pid in (1, 2, 3):
            runtimes[pid] = ProcessRuntime(
                EPaxos,
                pid,
                0,
                config,
                listen_addr=("127.0.0.1", peer_ports[pid]),
                client_addr=("127.0.0.1", client_ports[pid]),
                peers={
                    p: ("127.0.0.1", peer_ports[p]) for p in (1, 2, 3) if p != pid
                },
                sorted_processes=[(pid, 0)]
                + [(p, 0) for p in (1, 2, 3) if p != pid],
                reconnect_policy=ReconnectPolicy(attempts=3, base_s=0.02, cap_s=0.1),
                heartbeat_interval_s=0.1,
                heartbeat_misses=5,
            )
        await asyncio.gather(*(r.start() for r in runtimes.values()))
        await asyncio.sleep(0.3)
        # kill two of three: the survivor is below quorum (alive 1 < n-f=2)
        await runtimes[2].stop()
        await runtimes[3].stop()
        try:
            await asyncio.wait_for(runtimes[1].failed.wait(), timeout=20)
        finally:
            failure = runtimes[1].failure
            await runtimes[1].stop()
        return failure

    failure = asyncio.run(scenario())
    assert isinstance(failure, QuorumLostError), failure
    assert failure.alive == 1 and failure.needed == 2
    assert failure.dead_peers == [2, 3]


def test_run_degrades_gracefully_above_quorum():
    """Losing one peer of three (f=1) is survivable: the runtime records
    the dead peer, logs degradation, and does NOT fail."""
    from fantoch_tpu.run.harness import free_port
    from fantoch_tpu.run.links import ReconnectPolicy
    from fantoch_tpu.run.process_runner import ProcessRuntime

    async def scenario():
        config = Config(n=3, f=1, gc_interval_ms=50)
        peer_ports = {pid: free_port() for pid in (1, 2, 3)}
        client_ports = {pid: free_port() for pid in (1, 2, 3)}
        runtimes = {}
        for pid in (1, 2, 3):
            runtimes[pid] = ProcessRuntime(
                EPaxos,
                pid,
                0,
                config,
                listen_addr=("127.0.0.1", peer_ports[pid]),
                client_addr=("127.0.0.1", client_ports[pid]),
                peers={
                    p: ("127.0.0.1", peer_ports[p]) for p in (1, 2, 3) if p != pid
                },
                sorted_processes=[(pid, 0)]
                + [(p, 0) for p in (1, 2, 3) if p != pid],
                reconnect_policy=ReconnectPolicy(attempts=3, base_s=0.02, cap_s=0.1),
                heartbeat_interval_s=0.1,
                heartbeat_misses=5,
            )
        await asyncio.gather(*(r.start() for r in runtimes.values()))
        await asyncio.sleep(0.3)
        await runtimes[3].stop()
        # wait until both survivors notice the dead peer
        deadline = asyncio.get_running_loop().time() + 20
        while asyncio.get_running_loop().time() < deadline:
            if all(3 in runtimes[pid].dead_peers for pid in (1, 2)):
                break
            await asyncio.sleep(0.1)
        state = {
            pid: (runtimes[pid].failure, set(runtimes[pid].dead_peers))
            for pid in (1, 2)
        }
        for pid in (1, 2):
            await runtimes[pid].stop()
        return state

    state = asyncio.run(scenario())
    for pid in (1, 2):
        failure, dead = state[pid]
        assert failure is None, f"p{pid} must degrade, not fail: {failure!r}"
        assert dead == {3}
