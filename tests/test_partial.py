"""Partial replication end-to-end at the protocol + executor level.

Drives real Atlas instances across 2-3 shards with a deterministic
in-test router (no network): submits multi-shard commands, checks the
MForwardSubmit / MShardCommit / MShardAggregatedCommit aggregation
(fantoch_ps/src/protocol/partial.rs), and runs the committed commands
through per-process GraphExecutors, exercising the cross-shard
Request/RequestReply dependency fetch
(fantoch_ps/src/executor/graph/mod.rs:279-408).
"""

from collections import deque

import pytest

from fantoch_tpu.core import Command, Config, Dot, KVOp, Rifl, RunTime
from fantoch_tpu.core.ids import process_ids
from fantoch_tpu.executor.graph.executor import GraphExecutor
from fantoch_tpu.protocol.base import ToForward, ToSend
from fantoch_tpu.protocol.graph_protocol import Atlas, EPaxos, MCommit
from fantoch_tpu.protocol.partial import (
    MForwardSubmit,
    MShardAggregatedCommit,
    MShardCommit,
)

TIME = RunTime()


class Cluster:
    """shard_count x n Atlas processes + graph executors with a manual
    message router (the protocol-level analog of the reference's
    message-walk tests, atlas.rs:922+)."""

    def __init__(self, n: int, f: int, shard_count: int, protocol_cls=Atlas,
                 config: Config = None):
        self.config = config or Config(
            n=n, f=f, shard_count=shard_count, gc_interval_ms=100
        )
        self.n = n
        self.shard_count = shard_count
        self.protocols = {}
        self.executors = {}
        self.shard_of = {}
        self.queue = deque()  # (from_pid, from_shard, to_pid, msg)
        all_procs = [
            (pid, shard)
            for shard in range(shard_count)
            for pid in process_ids(shard, n)
        ]
        for shard in range(shard_count):
            ids = list(process_ids(shard, n))
            for pid in ids:
                proto = protocol_cls(pid, shard, self.config)
                # own shard (self first) + closest process of other shards
                # (pick the same-offset process of each peer shard)
                offset = pid - ids[0]
                discover = [(pid, shard)] + [
                    (p, shard) for p in ids if p != pid
                ]
                for other in range(shard_count):
                    if other != shard:
                        other_ids = list(process_ids(other, n))
                        discover.append((other_ids[offset], other))
                ok, _ = proto.discover(discover)
                assert ok
                self.protocols[pid] = proto
                executor = protocol_cls.Executor(pid, shard, self.config)
                executor.set_executor_index(0)
                self.executors[pid] = executor
                self.shard_of[pid] = shard
        self.messages_seen = []

    def submit(self, pid: int, cmd: Command) -> None:
        proto = self.protocols[pid]
        proto.submit(None, cmd, TIME)
        self.drain(pid)

    def drain(self, pid: int) -> None:
        import copy

        proto = self.protocols[pid]
        for action in proto.to_processes_iter():
            if isinstance(action, ToSend):
                # one deep copy per target, like the sim/runner's
                # serialize-per-connection: receivers may mutate payloads
                # in place (Newt strips MCommit Votes per key)
                targets = sorted(action.target)
                copies = [action.msg] + [
                    copy.deepcopy(action.msg) for _ in targets[1:]
                ]
                for target, msg in zip(targets, copies):
                    self.queue.append((pid, self.shard_of[pid], target, msg))
            elif isinstance(action, ToForward):
                self.queue.append((pid, self.shard_of[pid], pid, action.msg))
        for info in proto.to_executors_iter():
            self._feed_executor(pid, info)

    def _feed_executor(self, pid: int, info) -> None:
        executor = self.executors[pid]
        executor.handle(info, TIME)
        self._drain_executor(pid)

    def _drain_executor(self, pid: int) -> None:
        executor = self.executors[pid]
        while True:
            out = executor.to_executors()
            if out is None:
                break
            to_shard, xinfo = out
            if to_shard == self.shard_of[pid]:
                target = pid  # local executor traffic
            else:
                target = self.protocols[pid].bp.closest_process(to_shard)
            # requests go to the secondary executor in the real runner; the
            # test uses one executor per process with index 0 for adds and
            # flips to the secondary role for request serving
            peer = self.executors[target]
            from fantoch_tpu.executor.graph.executor import (
                GraphRequest,
                GraphRequestReply,
            )

            if isinstance(xinfo, GraphRequest):
                peer.set_executor_index(1)
                peer.handle(xinfo, TIME)
                peer.graph.cleanup(TIME)
                peer.set_executor_index(0)
            else:
                peer.handle(xinfo, TIME)
            self._drain_executor(target)

    def run(self, max_steps: int = 10_000) -> None:
        steps = 0
        while self.queue:
            steps += 1
            assert steps < max_steps, "message storm / livelock"
            from_pid, from_shard, to_pid, msg = self.queue.popleft()
            self.messages_seen.append(type(msg).__name__)
            self.protocols[to_pid].handle(from_pid, from_shard, msg, TIME)
            self.drain(to_pid)

    def executed(self, pid: int):
        """Rifls executed at pid, in order."""
        out = []
        while True:
            res = self.executors[pid].to_clients()
            if res is None:
                break
            out.append(res.rifl)
        return out


def multi_shard_cmd(rifl_seq: int, keys_by_shard) -> Command:
    return Command(
        Rifl(1, rifl_seq),
        {
            shard: {key: (KVOp.put(f"v{rifl_seq}"),) for key in keys}
            for shard, keys in keys_by_shard.items()
        },
    )


def test_epaxos_rejects_multi_shard():
    cluster = Cluster(3, 1, 2, protocol_cls=EPaxos)
    cmd = multi_shard_cmd(1, {0: ["a"], 1: ["b"]})
    with pytest.raises(AssertionError, match="does not support multi-shard"):
        cluster.protocols[1].submit(None, cmd, TIME)


def test_atlas_two_shard_commit_and_execute():
    cluster = Cluster(3, 1, 2)
    cmd = multi_shard_cmd(1, {0: ["a"], 1: ["b"]})
    cluster.submit(1, cmd)  # p1 is in shard 0: the target shard
    cluster.run()

    # the full partial-commit message trail happened
    seen = set(cluster.messages_seen)
    assert {"MForwardSubmit", "MShardCommit", "MShardAggregatedCommit", "MCommit"} <= seen

    # every process of both shards executed its shard's part exactly once
    for pid, shard in cluster.shard_of.items():
        rifls = cluster.executed(pid)
        assert rifls == [Rifl(1, 1)], f"p{pid} (shard {shard}) executed {rifls}"


def test_atlas_two_shard_conflicting_commands_agree():
    cluster = Cluster(3, 1, 2)
    # two conflicting multi-shard commands from different coordinators of
    # the same shard (the target shard orders them via deps)
    c1 = multi_shard_cmd(1, {0: ["a"], 1: ["b"]})
    c2 = multi_shard_cmd(2, {0: ["a"], 1: ["b"]})
    cluster.submit(1, c1)
    cluster.submit(2, c2)
    cluster.run()

    orders = {}
    for pid in cluster.protocols:
        rifls = cluster.executed(pid)
        assert sorted(r.sequence for r in rifls) == [1, 2]
        orders[pid] = tuple(r.sequence for r in rifls)
    # agreement: conflicting commands execute in the same order everywhere
    assert len(set(orders.values())) == 1, orders


def test_atlas_three_shard_commit_and_execute():
    cluster = Cluster(3, 1, 3)
    cmd = multi_shard_cmd(1, {0: ["a"], 1: ["b"], 2: ["c"]})
    cluster.submit(1, cmd)
    cluster.run()
    # three shards -> two forwards, three shard commits
    assert cluster.messages_seen.count("MForwardSubmit") == 2
    assert cluster.messages_seen.count("MShardCommit") == 3
    for pid in cluster.protocols:
        assert cluster.executed(pid) == [Rifl(1, 1)]


def test_atlas_cross_shard_dependency_fetch():
    """A multi-shard command depending on a single-shard command of another
    shard: the graph executor must fetch the remote dependency's info via
    Request/RequestReply before it can order (mod.rs:279-408)."""
    cluster = Cluster(3, 1, 2)
    # single-shard command on shard 1 only, submitted at p4 (shard 1)
    c1 = multi_shard_cmd(1, {1: ["b"]})
    # multi-shard command conflicting on "b"
    c2 = multi_shard_cmd(2, {0: ["a"], 1: ["b"]})
    cluster.submit(4, c1)
    cluster.run()
    cluster.submit(1, c2)
    cluster.run()

    for pid, shard in cluster.shard_of.items():
        rifls = [r.sequence for r in cluster.executed(pid)]
        if shard == 0:
            # shard 0 never executes c1 (not replicated there)
            assert rifls == [2], f"p{pid}: {rifls}"
        else:
            assert rifls == [1, 2], f"p{pid}: {rifls}"


def test_newt_two_shard_commit_and_execute():
    """Newt partial replication: MForwardSubmit + MBump priming + clock-max
    MShardCommit aggregation (newt.rs:1025-1100); both shards execute their
    part of the command once timestamps stabilize."""
    from fantoch_tpu.protocol.newt import Newt, SendDetachedEvent

    class NewtCluster(Cluster):
        def __init__(self, n, f, shard_count):
            super().__init__(
                n,
                f,
                shard_count,
                protocol_cls=Newt,
                config=Config(
                    n=n,
                    f=f,
                    shard_count=shard_count,
                    gc_interval_ms=100,
                    newt_detached_send_interval_ms=50,
                ),
            )

        def pump_detached(self):
            """Manually fire the detached-vote flush (the periodic event the
            message-walk loop has no timer for)."""
            for pid, proto in self.protocols.items():
                proto.handle_event(SendDetachedEvent(), TIME)
                self.drain(pid)
            self.run()

    cluster = NewtCluster(3, 1, 2)
    cmd = multi_shard_cmd(1, {0: ["a"], 1: ["b"]})
    cluster.submit(1, cmd)
    cluster.run()
    for _ in range(4):
        cluster.pump_detached()

    seen = set(cluster.messages_seen)
    assert {"MForwardSubmit", "MBump", "MShardCommit",
            "MShardAggregatedCommit", "MCommit"} <= seen
    for pid, shard in cluster.shard_of.items():
        rifls = cluster.executed(pid)
        assert rifls == [Rifl(1, 1)], f"p{pid} (shard {shard}) executed {rifls}"

    # bumps trailing a GC'd commit (or preceding their MCollect) buffer in
    # a BOUNDED dict: stale entries age out by eviction instead of leaking
    # (a bump is a clock-priming hint, so dropping one is always safe)
    from fantoch_tpu.protocol.newt import _MBUMP_BUFFER_CAP

    some_shard1 = next(p for p, s in cluster.shard_of.items() if s == 1)
    proto = cluster.protocols[some_shard1]
    proto._handle_mbump(Dot(1, 99), 7)
    assert proto._buffered_mbumps[Dot(1, 99)] == 7
    for seq in range(100, 100 + _MBUMP_BUFFER_CAP + 50):
        proto._handle_mbump(Dot(1, seq), seq)
    assert len(proto._buffered_mbumps) == _MBUMP_BUFFER_CAP
    assert Dot(1, 99) not in proto._buffered_mbumps, "oldest entry evicted"
    # a buffered bump still primes the clocks when its MCollect arrives:
    # re-bumping an existing entry keeps the max without evicting
    newest = Dot(1, 100 + _MBUMP_BUFFER_CAP + 49)
    proto._handle_mbump(newest, 5)
    assert proto._buffered_mbumps[newest] == 100 + _MBUMP_BUFFER_CAP + 49


def test_atlas_two_shard_batched_graph_executor():
    """Partial replication through the *tensorized* graph executor
    (VERDICT r3 item 6): cross-shard fetch, pending serving from the array
    backlog, and per-shard agreement all hold with
    batched_graph_executor=True."""
    config = Config(
        n=3, f=1, shard_count=2, gc_interval_ms=100,
        batched_graph_executor=True,
    )
    cluster = Cluster(3, 1, 2, config=config)
    c1 = multi_shard_cmd(1, {0: ["a"], 1: ["b"]})
    c2 = multi_shard_cmd(2, {0: ["a"], 1: ["b"]})
    cluster.submit(1, c1)
    cluster.submit(2, c2)
    cluster.run()
    orders = {}
    for pid in cluster.protocols:
        rifls = cluster.executed(pid)
        assert sorted(r.sequence for r in rifls) == [1, 2]
        orders[pid] = tuple(r.sequence for r in rifls)
    assert len(set(orders.values())) == 1, orders


def test_atlas_cross_shard_dependency_fetch_batched():
    """The array backlog serves cross-shard dependency requests: a
    multi-shard command depending on another shard's single-shard command
    fetches its info through Request/RequestReply and orders."""
    config = Config(
        n=3, f=1, shard_count=2, gc_interval_ms=100,
        batched_graph_executor=True,
    )
    cluster = Cluster(3, 1, 2, config=config)
    c1 = multi_shard_cmd(1, {1: ["b"]})
    c2 = multi_shard_cmd(2, {0: ["a"], 1: ["b"]})
    cluster.submit(4, c1)
    cluster.run()
    cluster.submit(1, c2)
    cluster.run()
    for pid, shard in cluster.shard_of.items():
        rifls = cluster.executed(pid)
        if shard == 1:
            assert rifls == [Rifl(1, 1), Rifl(1, 2)], f"p{pid}: {rifls}"
        else:
            assert rifls == [Rifl(1, 2)], f"p{pid}: {rifls}"
