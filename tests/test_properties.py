"""Property-based tests (hypothesis) — the analog of the reference's
quickcheck CI runs (.github/workflows: QUICKCHECK_TESTS=10000; quickcheck
dev-dependency across fantoch crates).

Targets the algebraic core where randomized inputs bite hardest:

* AboveExSet/AEClock against a plain set model (threshold crate semantics);
* VoteRange compression preserves the voted-integer set
  (fantoch_ps/src/protocol/common/table/votes.rs:133 try_compress);
* the keyed device resolver against the host Tarjan oracle on generated
  latest-per-key graphs with cycles (ops/graph_resolve.py vs
  executor/graph/deps_graph.py);
* dot packing round-trips (ops/frontier.pack_dots);
* the native C++ SCC resolver against the same oracle.
"""

import os
import sys

# the reference CI caps quickcheck at a budget (QUICKCHECK_TESTS); under
# CI=true we shrink hypothesis the same way
_CI = bool(os.environ.get("CI"))


import numpy as np
import pytest

# gate, don't error: containers without hypothesis skip the property
# suite instead of failing collection (the reference's quickcheck dep is
# likewise dev-only)
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from fantoch_tpu.core.clocks import AboveExSet

# --- AboveExSet vs set model -------------------------------------------------


@settings(max_examples=300 // 4 if _CI else 300, deadline=None)
@given(st.lists(st.integers(min_value=1, max_value=64), max_size=64))
def test_above_ex_set_matches_set_model(events):
    eset = AboveExSet()
    model = set()
    for e in events:
        added = eset.add(e)
        assert added == (e not in model)
        model.add(e)
    for probe in range(1, 70):
        assert eset.contains(probe) == (probe in model), probe
    # frontier: largest f with 1..f all present
    f = 0
    while (f + 1) in model:
        f += 1
    assert eset.frontier == f


@settings(max_examples=200 // 4 if _CI else 200, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(min_value=1, max_value=40),
            st.integers(min_value=0, max_value=8),
        ),
        max_size=30,
    )
)
def test_above_ex_set_add_range_matches_model(ranges):
    eset = AboveExSet()
    model = set()
    for start, width in ranges:
        eset.add_range(start, start + width)
        model.update(range(start, start + width + 1))
    for probe in range(1, 55):
        assert eset.contains(probe) == (probe in model), probe


# --- VoteRange compression ---------------------------------------------------


@settings(max_examples=300 // 4 if _CI else 300, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(min_value=1, max_value=30),
            st.integers(min_value=0, max_value=5),
        ),
        min_size=1,
        max_size=20,
    )
)
def test_vote_range_compression_preserves_votes(ranges):
    from fantoch_tpu.protocol.common.table_clocks import VoteRange

    compressed = []
    model = set()
    for start, width in ranges:
        vr = VoteRange(by=1, start=start, end=start + width)
        model.update(range(start, start + width + 1))
        if compressed and compressed[-1].try_compress(vr):
            pass
        else:
            compressed.append(vr)
    got = set()
    for vr in compressed:
        got.update(range(vr.start, vr.end + 1))
    # compression joins adjacent/overlapping ranges in order; the union of
    # represented votes must never change
    assert got == model


# --- dot packing -------------------------------------------------------------


@settings(max_examples=200 // 4 if _CI else 200, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(min_value=1, max_value=255),
            st.integers(min_value=1, max_value=2**31 - 1),
        ),
        min_size=1,
        max_size=32,
    )
)
def test_pack_dots_roundtrip_and_order(pairs):
    from fantoch_tpu.ops.frontier import pack_dots

    src = np.array([p for p, _ in pairs], dtype=np.int64)
    seq = np.array([q for _, q in pairs], dtype=np.int64)
    packed = pack_dots(src, seq)
    assert ((packed >> 32) == src).all()
    assert ((packed & 0xFFFFFFFF) == seq).all()
    # packing is order-preserving on (src, seq) lexicographic order
    order = np.lexsort((seq, src))
    assert (packed[order] == np.sort(packed)).all()


# --- keyed resolver vs host oracle ------------------------------------------


@st.composite
def functional_graphs(draw):
    """Latest-per-key chains over a few keys, with optional cycles at the
    oldest end — the KeyDeps shape (sequential.rs:8-11)."""
    import random as _random

    from test_ops_resolve import random_functional_args

    seed = draw(st.integers(min_value=0, max_value=2**32 - 1))
    cmds_per_key = draw(st.integers(min_value=1, max_value=7))
    rng = _random.Random(seed)
    return random_functional_args(
        n=3, keys=["A", "B", "C"], cmds_per_key=cmds_per_key, rng=rng
    )


@settings(max_examples=60 // 4 if _CI else 60, deadline=None)
@given(functional_graphs())
@pytest.mark.slow
def test_keyed_resolver_matches_oracle_property(args):
    from test_ops_resolve import assert_keyed_matches_oracle

    assert_keyed_matches_oracle(3, args)


@settings(max_examples=60 // 4 if _CI else 60, deadline=None)
@given(functional_graphs())
def test_native_resolver_matches_oracle_property(args):
    from test_native import csr_from_args
    from test_ops_resolve import oracle_per_key_order

    from fantoch_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    offsets, targets, packed = csr_from_args(args)
    order, _sizes = native.resolve_sccs(offsets, targets, packed)
    per_key = {}
    for i in order.tolist():
        dot, keys, _ = args[i]
        for key in keys:
            per_key.setdefault(key, []).append(dot)
    expected, n_exec = oracle_per_key_order(3, args)
    assert len(order) == n_exec
    assert per_key == expected


# --- sharded Newt mesh round properties ---

_SHARDED_NEWT = {}


def _sharded_newt_step():
    """One jitted 2-shard Newt step + mesh, built once: hypothesis
    examples reuse the compiled program (fixed shapes)."""
    if not _SHARDED_NEWT:
        from fantoch_tpu.parallel import mesh_step

        m = mesh_step.make_mesh(num_replicas=6)
        _SHARDED_NEWT["mesh_step"] = mesh_step
        _SHARDED_NEWT["mesh"] = m
        _SHARDED_NEWT["step"] = mesh_step.jit_newt_step(m, f=1, shard_count=2)
    return _SHARDED_NEWT["mesh_step"], _SHARDED_NEWT["mesh"], _SHARDED_NEWT["step"]


@settings(max_examples=25 // 5 if _CI else 25, deadline=None)
@given(
    st.lists(
        st.one_of(
            st.none(),  # pad row
            st.integers(min_value=0, max_value=7),  # single bucket
            st.tuples(
                st.integers(min_value=0, max_value=7),
                st.integers(min_value=0, max_value=7),
            ).filter(lambda t: t[0] != t[1]),  # two distinct buckets
        ),
        min_size=8,
        max_size=8,
    )
)
@pytest.mark.slow
def test_sharded_newt_round_properties(rows):
    """Random single/multi-bucket batches through one healthy 2-shard
    Newt round: every valid row fast-commits and executes, per-bucket
    execution follows strictly increasing (clock, dot) sort ids — the
    VotesTable contract; multi-key rows may tie on clock within a round
    and break by dot (newt_protocol_step docstring) — and a shard's
    replicas never learn the other shard's buckets."""
    import jax
    import jax.numpy as jnp

    mesh_step, _m, step = _sharded_newt_step()
    KP = mesh_step.KEY_PAD
    state = mesh_step.init_newt_state(
        _SHARDED_NEWT["mesh"], 6, key_buckets=8, pending_capacity=8,
        key_width=2,
    )
    key = np.full((8, 2), KP, np.int32)
    for i, row in enumerate(rows):
        if row is None:
            continue
        if isinstance(row, tuple):
            key[i, 0], key[i, 1] = row
        else:
            key[i, 0] = row
    state, out = step(
        state, jnp.asarray(key), jnp.ones((8,), jnp.int32),
        jnp.arange(8, dtype=jnp.int32),
    )
    pend_cap = state.pend_key.shape[0]
    valid = [i for i, r in enumerate(rows) if r is not None]
    executed = np.asarray(out.executed)
    fast = np.asarray(out.fast_path)
    clock = np.asarray(out.clock)
    for i in valid:
        assert executed[pend_cap + i] and fast[pend_cap + i], f"row {i}"

    # per-bucket (clock, dot) sort ids strictly increase along the
    # execution order (clock alone may tie for multi-key rows in one
    # round; dot breaks the tie — the VotesTable SortId contract)
    order = np.asarray(out.order)
    last = {}
    for w in order.tolist():
        if not executed[w] or w < pend_cap:
            continue
        i = w - pend_cap
        row = rows[i]
        buckets = row if isinstance(row, tuple) else (row,)
        sort_id = (int(clock[w]), i)  # dot = (1, seq=i): seq orders
        for b in buckets:
            assert last.get(b, (0, -1)) < sort_id, (
                f"bucket {b}: {last.get(b)} !< {sort_id}"
            )
            last[b] = sort_id

    # ownership: shard 0 = rows 0..2 owns even buckets, shard 1 odd
    kc = np.asarray(state.key_clock)
    vf = np.asarray(state.vote_frontier)
    odd = np.arange(1, 8, 2)
    even = np.arange(0, 8, 2)
    assert (kc[0:3][:, odd] == 0).all() and (vf[0:3][:, odd] == 0).all()
    assert (kc[3:6][:, even] == 0).all() and (vf[3:6][:, even] == 0).all()
