"""Property-based tests (hypothesis) — the analog of the reference's
quickcheck CI runs (.github/workflows: QUICKCHECK_TESTS=10000; quickcheck
dev-dependency across fantoch crates).

Targets the algebraic core where randomized inputs bite hardest:

* AboveExSet/AEClock against a plain set model (threshold crate semantics);
* VoteRange compression preserves the voted-integer set
  (fantoch_ps/src/protocol/common/table/votes.rs:133 try_compress);
* the keyed device resolver against the host Tarjan oracle on generated
  latest-per-key graphs with cycles (ops/graph_resolve.py vs
  executor/graph/deps_graph.py);
* dot packing round-trips (ops/frontier.pack_dots);
* the native C++ SCC resolver against the same oracle.
"""

import os
import sys

# the reference CI caps quickcheck at a budget (QUICKCHECK_TESTS); under
# CI=true we shrink hypothesis the same way
_CI = bool(os.environ.get("CI"))


import numpy as np
import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from fantoch_tpu.core.clocks import AboveExSet

# --- AboveExSet vs set model -------------------------------------------------


@settings(max_examples=300 // 4 if _CI else 300, deadline=None)
@given(st.lists(st.integers(min_value=1, max_value=64), max_size=64))
def test_above_ex_set_matches_set_model(events):
    eset = AboveExSet()
    model = set()
    for e in events:
        added = eset.add(e)
        assert added == (e not in model)
        model.add(e)
    for probe in range(1, 70):
        assert eset.contains(probe) == (probe in model), probe
    # frontier: largest f with 1..f all present
    f = 0
    while (f + 1) in model:
        f += 1
    assert eset.frontier == f


@settings(max_examples=200 // 4 if _CI else 200, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(min_value=1, max_value=40),
            st.integers(min_value=0, max_value=8),
        ),
        max_size=30,
    )
)
def test_above_ex_set_add_range_matches_model(ranges):
    eset = AboveExSet()
    model = set()
    for start, width in ranges:
        eset.add_range(start, start + width)
        model.update(range(start, start + width + 1))
    for probe in range(1, 55):
        assert eset.contains(probe) == (probe in model), probe


# --- VoteRange compression ---------------------------------------------------


@settings(max_examples=300 // 4 if _CI else 300, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(min_value=1, max_value=30),
            st.integers(min_value=0, max_value=5),
        ),
        min_size=1,
        max_size=20,
    )
)
def test_vote_range_compression_preserves_votes(ranges):
    from fantoch_tpu.protocol.common.table_clocks import VoteRange

    compressed = []
    model = set()
    for start, width in ranges:
        vr = VoteRange(by=1, start=start, end=start + width)
        model.update(range(start, start + width + 1))
        if compressed and compressed[-1].try_compress(vr):
            pass
        else:
            compressed.append(vr)
    got = set()
    for vr in compressed:
        got.update(range(vr.start, vr.end + 1))
    # compression joins adjacent/overlapping ranges in order; the union of
    # represented votes must never change
    assert got == model


# --- dot packing -------------------------------------------------------------


@settings(max_examples=200 // 4 if _CI else 200, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(min_value=1, max_value=255),
            st.integers(min_value=1, max_value=2**31 - 1),
        ),
        min_size=1,
        max_size=32,
    )
)
def test_pack_dots_roundtrip_and_order(pairs):
    from fantoch_tpu.ops.frontier import pack_dots

    src = np.array([p for p, _ in pairs], dtype=np.int64)
    seq = np.array([q for _, q in pairs], dtype=np.int64)
    packed = pack_dots(src, seq)
    assert ((packed >> 32) == src).all()
    assert ((packed & 0xFFFFFFFF) == seq).all()
    # packing is order-preserving on (src, seq) lexicographic order
    order = np.lexsort((seq, src))
    assert (packed[order] == np.sort(packed)).all()


# --- keyed resolver vs host oracle ------------------------------------------


@st.composite
def functional_graphs(draw):
    """Latest-per-key chains over a few keys, with optional cycles at the
    oldest end — the KeyDeps shape (sequential.rs:8-11)."""
    import random as _random

    from test_ops_resolve import random_functional_args

    seed = draw(st.integers(min_value=0, max_value=2**32 - 1))
    cmds_per_key = draw(st.integers(min_value=1, max_value=7))
    rng = _random.Random(seed)
    return random_functional_args(
        n=3, keys=["A", "B", "C"], cmds_per_key=cmds_per_key, rng=rng
    )


@settings(max_examples=60 // 4 if _CI else 60, deadline=None)
@given(functional_graphs())
@pytest.mark.slow
def test_keyed_resolver_matches_oracle_property(args):
    from test_ops_resolve import assert_keyed_matches_oracle

    assert_keyed_matches_oracle(3, args)


@settings(max_examples=60 // 4 if _CI else 60, deadline=None)
@given(functional_graphs())
def test_native_resolver_matches_oracle_property(args):
    from test_native import csr_from_args
    from test_ops_resolve import oracle_per_key_order

    from fantoch_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    offsets, targets, packed = csr_from_args(args)
    order, _sizes = native.resolve_sccs(offsets, targets, packed)
    per_key = {}
    for i in order.tolist():
        dot, keys, _ = args[i]
        for key in keys:
            per_key.setdefault(key, []).append(dot)
    expected, n_exec = oracle_per_key_order(3, args)
    assert len(order) == n_exec
    assert per_key == expected
