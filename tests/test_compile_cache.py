"""Compile-wall regression suite (core/compile_cache.py).

Two proofs the ISSUE demands:

* **Compiled-identity discipline** — a multi-point sweep over batch
  sizes routed through the canonicalized (pow2-padded) shapes compiles
  each plane program exactly ONCE (``program_compile_counts`` reads each
  registered jit's compiled-signature count).  A count > 1 names the
  program whose inputs leaked a non-canonical axis into the signature.

* **Persistent-cache collapse** — a cold-then-warm subprocess pair
  against one cache directory: the warm run retrieves every program from
  disk (``cache_hits > 0``), pays ZERO true XLA compiles
  (``recompile_count() == 0`` — the hit/miss-paired counter), and its
  ``compile_ms`` collapses versus cold.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from fantoch_tpu.core import compile_cache


def test_registry_counts_and_identities():
    """register/program_compile_counts round-trip on a toy jit."""
    import jax

    @jax.jit
    def toy(x):
        return x + 1

    compile_cache.register_program("_toy", toy)
    try:
        assert compile_cache.program_compile_counts()["_toy"] == 0
        toy(np.zeros((4,), np.float32))
        toy(np.ones((4,), np.float32))  # same shape: same signature
        assert compile_cache.program_compile_counts()["_toy"] == 1
        toy(np.zeros((8,), np.float32))  # new shape: second signature
        assert compile_cache.program_compile_counts()["_toy"] == 2
        assert compile_cache.compiled_program_identities() >= 2
    finally:
        compile_cache._programs.pop("_toy", None)


def test_resolve_cache_dir_precedence(monkeypatch, tmp_path):
    """config > FANTOCH_COMPILE_CACHE_DIR > obs-dir default > None."""
    from fantoch_tpu.core.config import Config

    monkeypatch.delenv("FANTOCH_COMPILE_CACHE_DIR", raising=False)
    assert compile_cache.resolve_cache_dir(None) is None
    assert compile_cache.resolve_cache_dir(
        None, obs_dir=str(tmp_path)
    ) == os.path.join(str(tmp_path), ".jax_cache")
    monkeypatch.setenv("FANTOCH_COMPILE_CACHE_DIR", "/env/dir")
    assert compile_cache.resolve_cache_dir(None, obs_dir=str(tmp_path)) == "/env/dir"
    cfg = Config(3, 1, compile_cache_dir="/cfg/dir")
    assert compile_cache.resolve_cache_dir(cfg, obs_dir=str(tmp_path)) == "/cfg/dir"


def test_plane_sweep_compiles_each_program_once():
    """5-point batch-size sweep through the canonicalized shapes: every
    plane program ends the sweep with exactly ONE compiled signature.

    The sweep drives the real call paths (the table plane's pow2 vote
    padding, the pred/graph planes' pow2 feed chopping) with batch sizes
    chosen to land in one pow2 bucket — the canonicalization the compile
    wall depends on."""
    import random

    from fantoch_tpu.executor.table_plane import DeviceTablePlane
    from tests.test_pred_plane import _plane_executor

    # table plane: 5 batch sizes inside one pow2 pad (vcap 16)
    plane = DeviceTablePlane(3, stability_threshold=2, key_buckets=8)
    for k in range(6):
        plane.bucket(f"k{k}")
    before = compile_cache.program_compile_counts()["votes_commit_xla"]
    r = random.Random(5)
    for batch in (9, 11, 13, 15, 16):
        vk = np.array([r.randrange(0, 6) for _ in range(batch)], np.int64)
        vb = np.array([r.randrange(1, 4) for _ in range(batch)], np.int64)
        # contiguous-from-1 ranges: no residual re-feeds, so V == batch
        # and all five sizes land in the SAME pow2 vote pad (16)
        vs = np.ones(batch, np.int64)
        ve = np.array([r.randrange(1, 10) for _ in range(batch)], np.int64)
        plane.commit_votes(vk, vb, vs, ve)
    after = compile_cache.program_compile_counts()["votes_commit_xla"]
    assert after - before == 1, (
        "table-plane sweep minted extra compiled signatures: a batch "
        "axis leaked past the pow2 pad"
    )

    # pred plane: 5 feed sizes inside one pow2 install pad (ucap 8) over
    # a bounded-dep-width chain workload (width growth is a legitimate
    # O(log) axis; this pins the FEED axis)
    from fantoch_tpu.core.ids import Dot
    from fantoch_tpu.executor.pred import PredecessorsExecutionInfo
    from fantoch_tpu.protocol.common.pred_clocks import Clock
    from tests.test_pred_plane import cmd

    def chain_infos(count):
        infos, last = [], {}
        for i in range(count):
            src = (i % 3) + 1
            dot = Dot(src, i + 1)
            k = f"K{i % 2}"
            deps = {last[k]} if k in last else set()
            last[k] = dot
            infos.append(
                PredecessorsExecutionInfo(
                    dot, cmd(i + 1, [k]), Clock(i + 1, src), deps
                )
            )
        return infos

    counts0 = compile_cache.program_compile_counts()["pred_plane_step_xla"]
    ex = _plane_executor()
    infos = chain_infos(40)
    at = 0
    for size in (5, 6, 7, 8, 5):
        ex.handle_batch(infos[at : at + size], None)
        at += size
    counts1 = compile_cache.program_compile_counts()["pred_plane_step_xla"]
    assert counts1 - counts0 == 1, (
        "pred-plane sweep minted extra compiled signatures: a feed axis "
        "leaked past the pow2 chop"
    )


_SUBPROC = textwrap.dedent(
    """
    import json, sys
    from fantoch_tpu.hostenv import force_cpu_platform
    force_cpu_platform()
    from fantoch_tpu.core.compile_cache import ensure_compile_cache
    from fantoch_tpu.observability.device import (
        cache_hit_count, cache_miss_count, compile_ms, recompile_count,
        subscribe_recompiles,
    )

    class Cfg:
        compile_cache_dir = sys.argv[1]

    subscribe_recompiles()
    ensure_compile_cache(Cfg())

    import numpy as np
    from fantoch_tpu.ops.table_ops import fused_votes_commit_xla
    import jax.numpy as jnp

    f = jnp.zeros((8, 3), jnp.int32)
    out = fused_votes_commit_xla(
        f, jnp.zeros((8,), jnp.int32), jnp.zeros((8,), jnp.int32),
        jnp.ones((8,), jnp.int32), jnp.ones((8,), jnp.int32),
        jnp.ones((8,), bool), threshold=2,
    )
    [o.block_until_ready() for o in out]
    print(json.dumps({
        "recompiles": recompile_count(),
        "hits": cache_hit_count(),
        "misses": cache_miss_count(),
        "compile_ms": compile_ms(),
    }))
    """
)


@pytest.mark.slow
def test_cold_vs_warm_persistent_cache(tmp_path):
    """Cold run misses and truly compiles; the warm run against the same
    cache dir retrieves from disk (hits > 0), reports ZERO true
    recompiles, and its compile wall collapses."""

    def run():
        out = subprocess.run(
            [sys.executable, "-c", _SUBPROC, str(tmp_path / "cache")],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["misses"] > 0
    assert cold["recompiles"] > 0
    warm = run()
    assert warm["hits"] > 0
    assert warm["recompiles"] == 0, (
        "warm persistent cache still paid a true XLA compile"
    )
    assert warm["compile_ms"] < max(cold["compile_ms"], 1.0), (
        f"no compile-wall collapse: cold {cold['compile_ms']} ms vs "
        f"warm {warm['compile_ms']} ms"
    )
