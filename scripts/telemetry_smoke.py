"""Telemetry smoke gate (``make telemetry-smoke``): the live-telemetry
plane end to end against a localhost EPaxos n=3 TCP cluster —

- every process serves a Prometheus-text ``/metrics`` endpoint; it is
  scraped twice *while the cluster serves*, both scrapes parse with the
  strict exposition parser, carry the required key set, and the second
  scrape's counters are monotonically >= the first's;
- the windowed series files (telemetry_p<pid>.jsonl + the client plane)
  exist, parse, and carry the submit/reply counters and latency windows;
- ``obs watch --once`` renders a frame over the obs dir (the live view
  the operator runs);
- the perf-regression gate works: an injected 2x ``graph_resolve``
  latency regression exits nonzero in ``--gate`` mode, a definition-
  stamp mismatch refuses the comparison, and — when ``bench-smoke`` ran
  earlier in the job — the fresh smoke row passes a report-only
  ``bench.py --regress`` against the committed baseline.

CPU-only and tiny; the per-push CI step runs it next to the other
smokes.
"""

import asyncio
import json
import os
import subprocess
import sys
import tempfile
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REQUIRED_METRICS = {
    "fantoch_submitted_total",
    "fantoch_replied_total",
    "fantoch_shed_submissions_total",
    "fantoch_backpressure_pauses_total",
    "fantoch_queue_depth",
    "fantoch_queue_depth_hwm",
}


def run_cluster(obs_dir: str):
    """One localhost EPaxos run with telemetry + endpoints live; scrapes
    every process twice mid-run (via the harness chaos hook, which runs
    alongside the clients).  Returns the scrape texts per round."""
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.core import Config
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.run.harness import run_localhost_cluster

    scrapes = [[], []]

    async def scraper(runtimes):
        loop = asyncio.get_running_loop()
        for round_ in range(2):
            await asyncio.sleep(0.25)
            for pid in sorted(runtimes):
                port = runtimes[pid].metrics_port
                url = f"http://127.0.0.1:{port}/metrics"
                text = await loop.run_in_executor(
                    None,
                    lambda u=url: urllib.request.urlopen(u, timeout=5)
                    .read()
                    .decode(),
                )
                scrapes[round_].append((pid, text))

    config = Config(
        n=3,
        f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        telemetry_interval_ms=100,
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=1,
        commands_per_client=60,
        payload_size=8,
    )
    asyncio.run(
        run_localhost_cluster(
            EPaxos,
            config,
            workload,
            clients_per_process=3,
            observe_dir=obs_dir,
            metrics_ports={pid: 0 for pid in (1, 2, 3)},  # OS-assigned
            chaos=scraper,
        )
    )
    return scrapes


def check_scrapes(scrapes) -> None:
    from fantoch_tpu.observability.exposition import parse_prometheus

    assert len(scrapes[0]) == 3 and len(scrapes[1]) == 3, (
        f"expected both scrape rounds to cover 3 processes: "
        f"{[len(s) for s in scrapes]}"
    )
    for round_ in (0, 1):
        for _pid, text in scrapes[round_]:
            parsed = parse_prometheus(text)  # strict: raises on malformed
            missing = REQUIRED_METRICS - set(parsed)
            assert not missing, f"scrape missing required keys: {missing}"
    # counters are monotone between the two live scrapes, per process
    for (pid_a, text_a), (pid_b, text_b) in zip(scrapes[0], scrapes[1]):
        assert pid_a == pid_b
        first = parse_prometheus(text_a)
        second = parse_prometheus(text_b)
        for name in first:
            if not name.endswith("_total"):
                continue
            for labels, value in first[name].items():
                later = second.get(name, {}).get(labels)
                assert later is not None and later >= value, (
                    f"p{pid_a} {name}{labels} not monotonic: "
                    f"{value} -> {later}"
                )


def check_series(obs_dir: str) -> None:
    from fantoch_tpu.observability.timeseries import (
        latest_windows,
        read_series,
    )

    for pid in (1, 2, 3):
        path = f"{obs_dir}/telemetry_p{pid}.jsonl"
        windows = read_series(path)
        assert windows, f"no telemetry windows in {path}"
        last = latest_windows(windows)[f"p{pid}"]
        for key in ("submitted", "replied", "shed_submissions"):
            assert key in last["ctr"], f"{path} missing counter {key}"
        assert "queue_depth" in last["g"], path
    client_windows = []
    for pid in (1, 2, 3):
        client_windows += read_series(
            f"{obs_dir}/telemetry_clients_p{pid}.jsonl"
        )
    last = latest_windows(client_windows)["clients"]
    assert last["ctr"]["replied"] > 0, last
    assert any(
        "latency_ms" in w.get("h", {}) for w in client_windows
    ), "no client latency window emitted"


def check_watch(obs_dir: str) -> None:
    proc = subprocess.run(
        [sys.executable, "-m", "fantoch_tpu.bin.obs", "watch", "--once",
         obs_dir],
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "submit/s" in proc.stdout and "clients" in proc.stdout, proc.stdout


def check_regress(tmp: str) -> None:
    """The regression gate's acceptance rows, against synthetic records,
    plus a report-only pass over the real smoke row when bench-smoke
    left one behind earlier in the CI job."""
    old = {
        "metric": "epaxos_1m_cmds_50pct_conflict_graph_resolve_p50",
        "value": 3.0,
        "platform": "cpu",
        "serving_newt_cmds_per_s": 40_000,
        "serving_newt_definition": "depth-2 pipelined (r07)",
    }
    doubled = dict(old, value=6.0)
    redefined = dict(
        old, serving_newt_cmds_per_s=5, serving_newt_definition="resync"
    )
    paths = {}
    for name, rec in (("old", old), ("doubled", doubled),
                      ("redefined", redefined)):
        paths[name] = os.path.join(tmp, f"{name}.json")
        with open(paths[name], "w") as fh:
            json.dump(rec, fh)

    def regress(*argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--regress",
             *argv],
            capture_output=True, text=True,
        )

    # injected 2x graph_resolve latency must trip the gate (exit 1)
    proc = regress(paths["doubled"], "--against", paths["old"], "--gate")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stdout, proc.stdout
    # a definition-stamp mismatch must REFUSE the family, not ratio it
    proc = regress(paths["redefined"], "--against", paths["old"], "--gate")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "REFUSED serving_newt_cmds_per_s" in proc.stdout, proc.stdout
    # refused means refused: no ratio line for the family's key
    assert "serving_newt_cmds_per_s: 40000" not in proc.stdout, proc.stdout
    # report-only over the real smoke row (bench-smoke writes it
    # earlier in the CI job; BENCH_SMOKE_BASE.json is the committed
    # same-seams baseline) — report-only never fails the build
    smoke_row = os.path.join(REPO, "BENCH_SMOKE_LATEST.json")
    base = os.path.join(REPO, "BENCH_SMOKE_BASE.json")
    if os.path.exists(smoke_row) and os.path.exists(base):
        proc = regress(smoke_row, "--against", base)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "compared" in proc.stdout, proc.stdout
        print("# regress report-only over the smoke row:")
        print(proc.stdout.rstrip())


def main() -> None:
    with tempfile.TemporaryDirectory() as tmp:
        obs_dir = os.path.join(tmp, "obs")
        scrapes = run_cluster(obs_dir)
        check_scrapes(scrapes)
        check_series(obs_dir)
        check_watch(obs_dir)
        check_regress(tmp)
    print(json.dumps({
        "metric": "telemetry_smoke",
        "scraped_processes": 3,
        "ok": True,
    }))


if __name__ == "__main__":
    main()
