"""Micro-profile the primitive ops the batched resolver is built from, on
whatever backend is default (run on the real TPU).  Informs the round-3
kernel redesign (VERDICT weak #1): which of gather / scatter / sort / cumsum
dominates the 894 ms resolve_functional time.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

B = 1_000_000
ITERS = 20


def timeit(name, fn, *args):
    out = jax.block_until_ready(fn(*args))  # compile
    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e3)
    print(f"{name:40s} p50={np.median(times):8.3f} ms  min={min(times):8.3f} ms")
    return out


def main():
    print("platform:", jax.devices()[0].platform, jax.devices()[0].device_kind)
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, B, size=B).astype(np.int32))
    x = jnp.asarray(rng.integers(0, B, size=B).astype(np.int32))
    xb = x > (B // 2)
    keys = jnp.asarray(rng.integers(0, 4096, size=B).astype(np.int32))

    timeit("gather  x[idx] (1M int32)", jax.jit(lambda x, i: x[i]), x, idx)
    timeit("2x gather chained", jax.jit(lambda x, i: x[i][i]), x, idx)
    timeit("gather 2d pack (src,seq) as int64", jax.jit(lambda x, i: (x.astype(jnp.int64) << 32)[i]), x, idx)
    timeit("scatter-max .at[idx].max", jax.jit(lambda x, i: jnp.zeros_like(x).at[i].max(x)), x, idx)
    timeit("scatter-add .at[idx].add", jax.jit(lambda x, i: jnp.zeros_like(x).at[i].add(x)), x, idx)
    timeit("scatter-max bool", jax.jit(lambda b, i: jnp.zeros_like(b).at[i].max(b)), xb, idx)
    timeit("sort 1M int32", jax.jit(jnp.sort), x)
    timeit("argsort 1M int32", jax.jit(jnp.argsort), x)
    timeit("sort 1M int64", jax.jit(lambda x: jnp.sort(x.astype(jnp.int64))), x)
    timeit("lexsort 2key", jax.jit(lambda a, b: jnp.lexsort((a, b))), x, keys)
    timeit("lexsort 4key", jax.jit(lambda a, b: jnp.lexsort((a, b, a, b))), x, keys)
    timeit("cumsum 1M int32", jax.jit(jnp.cumsum), x)
    timeit("cummax 1M int32", jax.jit(lambda x: jax.lax.cummax(x, axis=0)), x)
    timeit("segment boundary+cumsum rank", jax.jit(
        lambda k: jnp.arange(B) - jax.lax.cummax(jnp.where(jnp.concatenate([jnp.array([True]), k[1:] != k[:-1]]), jnp.arange(B), 0), axis=0)
    ), jnp.sort(keys))
    timeit("elementwise where+min mix", jax.jit(lambda x, i: jnp.minimum(jnp.where(x > 5, x, i), i)), x, idx)

    # the actual passes of resolve_functional, isolated
    from fantoch_tpu.ops.graph_resolve import resolve_functional, _num_doubling_steps
    steps = _num_doubling_steps(B)
    print("doubling steps:", steps)

    dep = jnp.where(jnp.arange(B) > 0, jnp.arange(B, dtype=jnp.int32) - 1, -1)

    @jax.jit
    def pass1(dep):
        iidx = jnp.arange(B, dtype=jnp.int32)
        absorbing = dep < 0
        jump = jnp.where(absorbing, iidx, dep)
        acc = jnp.where(absorbing, jnp.int32(B), jump)
        for _ in range(steps):
            acc = jnp.minimum(acc, acc[jump])
            jump = jump[jump]
        return jump, acc

    timeit(f"pass1: {steps}x (2 gathers + min)", pass1, dep)

    src = jnp.ones(B, jnp.int32)
    seq = jnp.arange(B, dtype=jnp.int32)
    timeit("resolve_functional (chain dep)", lambda d: resolve_functional(d, src, seq).order, dep)


if __name__ == "__main__":
    main()
