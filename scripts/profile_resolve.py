"""Stage-level profiling of resolve_functional_keyed at B=1M on the live
backend (VERDICT r2 item 1b: nobody profiled the kernel).  Prints one JSON
object with per-stage milliseconds so the <10 ms push targets the real
bottleneck instead of a guess.

Methodology: the axon tunnel adds bursty, non-iid dispatch noise (tens to
hundreds of ms), so each probe chains K data-dependent repetitions inside
ONE dispatch via ``lax.fori_loop`` (single compile, any K) and estimates
per-op time as (min_t(K_HI) - min_t(K_LO)) / (K_HI - K_LO); min over reps
is the standard latency estimator under asymmetric noise.

Run:  python scripts/profile_resolve.py            # default backend (TPU)
      JAX_PLATFORMS=cpu python scripts/profile_resolve.py
"""

from __future__ import annotations

import functools
import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from bench import BATCH, CONFLICT, build_workload, enable_compile_cache
from fantoch_tpu.ops.graph_resolve import (
    TERMINAL,
    _doubling_core,
    _residual_size_for,
    resolve_functional_keyed,
)

enable_compile_cache(jax)

REPS = 6


def probe(body, ops, k_lo=1, k_hi=None, reps=REPS):
    """body(op_arrays, carry) -> int32 carry.  Returns per-op ms.

    ``ops`` is a tuple of device arrays; the carry data-dependence stops
    XLA from collapsing the fori_loop iterations.
    """

    @jax.jit
    def run_k(k, *ops):
        def step(_i, carry):
            return body(ops, carry)

        return jax.lax.fori_loop(0, k, step, jnp.int32(0))

    def timed(k):
        float(run_k(k, *ops))  # compile/warm (cached across k: k is traced)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run_k(k, *ops))
            best = min(best, (time.perf_counter() - t0) * 1000.0)
        return best

    lo = timed(k_lo)
    hi = timed(k_hi)
    return (hi - lo) / (k_hi - k_lo)


def main():
    key_np, dep_np, src_np, seq_np = build_workload(BATCH, CONFLICT)
    key = jax.device_put(jnp.asarray(key_np))
    dep = jax.device_put(jnp.asarray(dep_np))
    src = jax.device_put(jnp.asarray(src_np))
    seq = jax.device_put(jnp.asarray(seq_np))
    residual = _residual_size_for(BATCH)
    out = {"platform": jax.devices()[0].platform, "batch": BATCH, "residual": residual}
    idx = jnp.arange(BATCH, dtype=jnp.int32)

    def perturb(x, carry):  # runtime zero, data-dependent
        return x + (carry >> jnp.int32(30))

    # --- full kernel (reference point); ~18 ms/op -> K up to 33
    def full(ops, carry):
        k, d, s, q = ops
        r = resolve_functional_keyed(
            perturb(k, carry), d, s, q, residual_size=residual,
            return_structure=False,
        )
        return r.order[0]
    out["full_kernel_ms"] = round(probe(full, (key, dep, src, seq), 1, 17), 3)

    # --- stage 1: the grouping sort alone
    def s1(ops, carry):
        k, d, s, q = ops
        k_s, pos_s, dep_s = jax.lax.sort(
            (perturb(k, carry), idx, d), num_keys=1, is_stable=True
        )
        return pos_s[0]
    out["sort1_ms"] = round(probe(s1, (key, dep, src, seq), 1, 33), 3)

    # --- stage 2 alone: link verification (elementwise/cummax over sorted)
    k_s0, pos_s0, dep_s0 = jax.lax.sort((key, idx, dep), num_keys=1, is_stable=True)
    def s2(ops, carry):
        k_s, pos_s, dep_s = ops
        k_s = perturb(k_s, carry)
        head = jnp.concatenate([jnp.ones((1,), bool), k_s[1:] != k_s[:-1]])
        prev_pos = jnp.roll(pos_s, 1)
        ok = jnp.where(head, dep_s == TERMINAL, dep_s == prev_pos)
        run_start = jax.lax.cummax(jnp.where(head, idx, 0))
        lastbad = jax.lax.cummax(jnp.where(~ok, idx, -1))
        chain_ok = lastbad < run_start
        return chain_ok.astype(jnp.int32).sum()
    out["verify_ms"] = round(probe(s2, (k_s0, pos_s0, dep_s0), 1, 33), 3)

    # --- the residual-compaction sort (binary partition) vs scatter
    cflag0 = jax.device_put(
        jnp.asarray((np.random.default_rng(0).random(BATCH) < 0.98).astype(np.int32))
    )
    def part(ops, carry):
        (cf,) = ops
        a, b = jax.lax.sort(
            (perturb(cf, carry), idx), num_keys=1, is_stable=True
        )
        return b[0]
    out["partition_sort_ms"] = round(probe(part, (cflag0,), 1, 33), 3)

    def part_scatter(ops, carry):
        (cf,) = ops
        bad = perturb(cf, carry) == 0
        rank = jnp.cumsum(bad) - 1
        tgt = jnp.where(bad, rank, residual)
        buf = jnp.full((residual,), -1, jnp.int32).at[tgt].set(idx, mode="drop")
        return buf[0]
    out["partition_scatter_ms"] = round(probe(part_scatter, (cflag0,), 1, 33), 3)

    # --- final sort alone (3 operands, 2 keys)
    def fsort(ops, carry):
        k, d, s, q = ops
        o = jax.lax.sort((perturb(k, carry), d, s), num_keys=2, is_stable=True)
        return o[2][0]
    out["final_sort_ms"] = round(probe(fsort, (key, dep, src, seq), 1, 33), 3)

    # --- B-wide random gather / unique scatter / cumsum (roofline probes)
    perm = jax.device_put(
        jnp.asarray(np.random.default_rng(1).permutation(BATCH).astype(np.int32))
    )
    def gathp(ops, carry):
        p, d = ops
        return d[perturb(p, carry)][0]
    out["random_gather_ms"] = round(probe(gathp, (perm, dep), 1, 65), 3)

    def scatp(ops, carry):
        p, d = ops
        return jnp.zeros((BATCH,), jnp.int32).at[perturb(p, carry)].set(
            d, mode="drop"
        )[0]
    out["random_scatter_ms"] = round(probe(scatp, (perm, dep), 1, 33), 3)
    ident = jax.device_put(jnp.arange(BATCH, dtype=jnp.int32))
    out["ident_scatter_ms"] = round(probe(scatp, (ident, dep), 1, 33), 3)

    def csum(ops, carry):
        (d,) = ops
        return jnp.cumsum(perturb(d, carry))[0]
    out["cumsum_ms"] = round(probe(csum, (dep,), 1, 65), 3)

    # --- doubling core at residual scale
    rdep = jax.device_put(jnp.asarray(dep_np[:residual]))
    def dcore(ops, carry):
        (rd,) = ops
        res, rank, lead, cyc = _doubling_core(perturb(rd, carry))
        return rank[0]
    out["doubling_residual_ms"] = round(probe(dcore, (rdep,), 1, 33), 3)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
