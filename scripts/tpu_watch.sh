#!/bin/bash
# Probe the axon TPU tunnel periodically; on recovery, immediately run the
# full benchmark child and record the output. Dev tool for the tunnel
# outage of 2026-07-30 — safe to re-run; exits after one successful bench.
cd "$(dirname "$0")/.."
for i in $(seq 1 100); do
  if env -u JAX_PLATFORMS timeout 90 python -u -c "import jax; print(jax.devices()[0].platform)" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel up — running bench" >> tpu_watch.log
    env -u JAX_PLATFORMS FANTOCH_BENCH_CHILD=tpu timeout 2400 python -u bench.py >> tpu_watch.log 2>&1
    echo "$(date -u +%H:%M:%S) bench rc=$?" >> tpu_watch.log
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) tunnel still down (probe $i)" >> tpu_watch.log
  sleep 600
done
