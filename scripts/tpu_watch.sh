#!/bin/bash
# Probe the axon TPU tunnel periodically; on recovery, immediately run the
# full benchmark and record the output. Dev tool for the tunnel flapping
# first seen 2026-07-30 — safe to re-run; exits after one successful bench.
# Parent-mode bench.py re-probes, persists BENCH_TPU_LATEST.json through
# the scale_vs_1m self-consistency gate, and falls back to CPU cleanly.
cd "$(dirname "$0")/.."
for i in $(seq 1 100); do
  if env -u JAX_PLATFORMS timeout 90 python -u -c "import jax; print(jax.devices()[0].platform)" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel up — running bench" >> tpu_watch.log
    # outer budget > probe retries + TPU child + CPU fallback child, so a
    # hung TPU child can't starve the fallback.  Derived from the same
    # env var bench.py reads (child timeout, default 1500s): a raised
    # FANTOCH_BENCH_TIMEOUT_S used to overflow the old hardcoded 3400
    # and silently truncate the CPU fallback.
    child_timeout="${FANTOCH_BENCH_TIMEOUT_S:-1500}"
    outer_budget=$((2 * child_timeout + 400))
    before=$(stat -c %Y BENCH_TPU_LATEST.json 2>/dev/null || echo 0)
    out=$(env -u JAX_PLATFORMS timeout "$outer_budget" python -u bench.py 2>>tpu_watch.log)
    rc=$?
    echo "$out" >> tpu_watch.log
    echo "$(date -u +%H:%M:%S) bench rc=$rc" >> tpu_watch.log
    # only a PERSISTED chip record retires the watch — the file mtime is
    # the authoritative signal that _save_tpu_record's self-consistency
    # gate passed.  A CPU fallback, a jitter-swamped record the gate
    # refused, or a timeout-truncated run all leave the file untouched,
    # and the watch re-arms for the next recovery.
    after=$(stat -c %Y BENCH_TPU_LATEST.json 2>/dev/null || echo 0)
    if [ "$after" != "$before" ]; then
      exit 0
    fi
    echo "$(date -u +%H:%M:%S) no verified chip record — re-arming" >> tpu_watch.log
  fi
  echo "$(date -u +%H:%M:%S) tunnel still down (probe $i)" >> tpu_watch.log
  sleep 600
done
