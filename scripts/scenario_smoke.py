"""Scenario-observatory smoke gate (``make scenario-smoke``): one tiny
declarative spec drives the whole sweep factory end to end on the
deterministic sim timeline and asserts the r20 contracts:

- the spec expands deterministically — two expansions of the same spec
  (and a third through the ``bin/scenario expand`` CLI) are
  byte-identical;
- the 3-point offered-rate ladder (EPaxos n=3, open-loop Poisson on
  virtual time) runs every cell through the sim runner with telemetry
  capture into per-cell obs dirs that ``plot.db.ResultsDB`` indexes;
- the resulting throughput-latency curve carries p50/p95/p99 + goodput
  per point and a DETECTED saturation knee (goodput caps at
  total_commands / completion-span as the arrival window compresses —
  real saturation, byte-stable across machines);
- ``curves.json`` round-trips through ``plot.db`` and the PNG renders
  headless (Agg);
- ``bin/obs.py curves`` prints the knee table + typed SLO verdicts and
  exits 0 on the passing SLO declared in the spec.

CPU-only, a few seconds; the per-push CI step runs it next to the other
smokes.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> None:
    from fantoch_tpu.bin import obs, scenario
    from fantoch_tpu.exp.scenarios import (
        ScenarioSpec,
        canonical_expansion,
        load_spec,
    )
    from fantoch_tpu.plot.db import ResultsDB, load_curves

    spec = ScenarioSpec(
        name="scenario_smoke",
        protocols=("epaxos",),
        sites=((3, 1),),
        timeline="sim",
        seed=20,
        clients_per_process=2,
        commands_per_client=10,
        rates=(50.0, 400.0, 3200.0),
        slo={"p99_ms": 2000.0, "min_goodput_cmds_per_s": 10.0},
    )

    with tempfile.TemporaryDirectory(prefix="scenario_smoke_") as tmp:
        spec_path = os.path.join(tmp, "spec.json")
        with open(spec_path, "w") as fh:
            json.dump(spec.to_dict(), fh)

        # byte-identical re-expansion: in-process twice + the CLI
        first = canonical_expansion(spec)
        assert canonical_expansion(load_spec(spec_path)) == first
        cli_out = os.path.join(tmp, "expansion.json")
        assert scenario.main(["expand", spec_path, "--out", cli_out]) == 0
        with open(cli_out) as fh:
            assert fh.read().rstrip("\n") == first, "CLI expansion diverged"
        print("scenario-smoke: expansion byte-identical (in-process + CLI)")

        # run the matrix through the CLI (exit 0 = every SLO verdict ok)
        out_dir = os.path.join(tmp, "obs")
        rc = scenario.main(["run", spec_path, "--out", out_dir])
        assert rc == 0, f"scenario run exited {rc}"

        doc = load_curves(os.path.join(out_dir, "curves.json"))
        (curve,) = doc["curves"]
        assert len(curve["points"]) == 3, curve
        for point in curve["points"]:
            assert point["goodput_cmds_per_s"] > 0, point
            assert (
                point["p50_ms"] <= point["p95_ms"] <= point["p99_ms"]
            ), point
        knee = curve["knee"]
        assert knee is not None, "ladder must saturate on the sim timeline"
        assert knee["offered_cmds_per_s"] > 50.0, knee
        assert all(v["pass"] for v in curve["slo"]), curve["slo"]
        print(
            "scenario-smoke: knee detected at offered "
            f"{knee['offered_cmds_per_s']}/s (goodput "
            f"{knee['goodput_cmds_per_s']}/s) over "
            f"{len(curve['points'])} points"
        )

        # artifacts: per-cell obs dirs indexable, PNG rendered headless
        db = ResultsDB(out_dir)
        assert len(db) == 3, [r.name for r in db.results]
        for result in db.results:
            assert os.path.exists(
                os.path.join(result.path, "telemetry.jsonl")
            ), result.path
        assert os.path.getsize(os.path.join(out_dir, "curves.png")) > 1000
        print("scenario-smoke: 3 cells indexed, curves.png rendered")

        # the capacity/SLO report plane renders and passes
        rc = obs.main(["curves", out_dir])
        assert rc == 0, f"obs curves exited {rc}"
    print("scenario-smoke: OK")


if __name__ == "__main__":
    main()
