"""CI fuzz smoke (``make fuzz-smoke``): a seeded chaos-fuzz sweep with
composed nemeses over EVERY protocol, auditor-clean and byte-identically
deterministic, per push.

The gate:

1. fixed seed set (fuzzer seed 0, the first ``SMOKE_CASES`` indices
   forced per protocol — the same set the mutation self-test in
   tests/test_fuzz.py must catch the reintroduced PR 7 bug within, plus
   two targeted rows: the first sampled Caesar-crash plan and the first
   sampled FPaxos crash-restart plan, the nemesis classes PR 12
   un-gated): every case must come back ``ok`` — the run completed,
   every surviving client finished, and the ConsistencyAuditor found no
   write-order / exactly-once / committed-then-lost / commit-value
   violation;
2. determinism: one case re-run must produce byte-identical plan, fault
   trace, and verdict digests;
3. soak: with ``FANTOCH_FUZZ_BUDGET_S`` set (nightly), keep sampling
   mixed-protocol cases until the wall budget elapses — zero violations
   tolerated (stalls/incompletes are reported but only fail the gate in
   the fixed set, where they are deterministic).

Wall cost of the fixed set: ~10s on a laptop CPU (30 sim runs).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, ".")

SMOKE_SEED = 0
SMOKE_CASES = 6


def main() -> int:
    from fantoch_tpu.sim.fuzz import (
        OK,
        PROTOCOL_SPECS,
        VIOLATION,
        FaultPlanFuzzer,
        repro_artifact,
        run_case,
        shrink_case,
        write_repro,
    )

    fuzzer = FaultPlanFuzzer(seed=SMOKE_SEED)
    started = time.monotonic()
    clean: dict = {}
    failures = []
    total = 0
    for protocol in sorted(PROTOCOL_SPECS):
        for index in range(SMOKE_CASES):
            case = fuzzer.case(index, protocol=protocol)
            result = run_case(case)
            total += 1
            if result.verdict == OK:
                clean[protocol] = clean.get(protocol, 0) + 1
            else:
                failures.append((protocol, index, result))
                print(
                    f"FAIL {protocol} case {index}: {result.verdict} "
                    f"{result.violations or result.error}"
                )
    # targeted rows: the fixed per-protocol indices may not sample the
    # nemesis classes PR 12 un-gated, so scan forward for the first
    # Caesar plan WITH a crash and the first FPaxos plan WITH a
    # crash-restart and pin those cases into the gate (budget-checked:
    # the scan is over pure case values, only the two hits are run)
    targeted = []
    for protocol, wants in (
        ("caesar", "crash"),
        ("fpaxos", "restart"),
        # the accelerator fault nemesis (PR 17), one row per device
        # plane: the first sampled plan WITH a DeviceFault runs with the
        # plane on, a dispatch deadline, and rate-1.0 shadow checking —
        # failover to the host twin must stay auditor-clean
        ("newt", "device"),
        ("caesar", "device"),
        ("epaxos", "device"),
    ):
        for index in range(SMOKE_CASES, 64):
            plan = fuzzer.case(index, protocol=protocol).plan
            if wants == "crash" and plan.crashes:
                targeted.append((protocol, index))
                break
            if wants == "restart" and any(
                crash.restart_at_ms is not None for crash in plan.crashes
            ):
                targeted.append((protocol, index))
                break
            if wants == "device" and plan.device_faults:
                targeted.append((protocol, index))
                break
        else:
            raise AssertionError(f"no {wants} plan sampled for {protocol} in 64 cases")
    for protocol, index in targeted:
        result = run_case(fuzzer.case(index, protocol=protocol))
        total += 1
        if result.verdict == OK:
            clean[protocol] = clean.get(protocol, 0) + 1
        else:
            failures.append((protocol, index, result))
            print(
                f"FAIL targeted {protocol} case {index}: {result.verdict} "
                f"{result.violations or result.error}"
            )
    print(f"targeted rows: {targeted}")
    print(
        f"fixed set: {total} cases in {time.monotonic() - started:.1f}s; "
        "clean per protocol: "
        + ", ".join(f"{p}={c}" for p, c in sorted(clean.items()))
    )
    budget = float(os.environ.get("FANTOCH_FUZZ_SMOKE_BUDGET_S", "300"))
    assert time.monotonic() - started < budget, (
        f"fixed fuzz-smoke set blew its {budget:.0f}s wall budget"
    )
    assert not failures, f"{len(failures)} smoke case(s) failed"
    for protocol in PROTOCOL_SPECS:
        assert clean.get(protocol, 0) >= 1, f"no clean run for {protocol}"

    # determinism gate: same case twice => byte-identical everything
    case = fuzzer.case(2, protocol="newt")
    first, second = run_case(case), run_case(case)
    assert first.plan_digest == second.plan_digest
    assert first.trace_digest == second.trace_digest, (
        "same-seed fault traces diverged"
    )
    assert first.verdict_digest == second.verdict_digest, (
        "same-seed verdicts diverged"
    )
    print(f"determinism: verdict digest {first.verdict_digest[:16]}... stable")

    # soak: keep sampling mixed cases until the wall budget elapses.
    # The soak SEED varies per run (wall clock, overridable for replay)
    # so successive nightly runs explore NEW schedules instead of
    # re-walking the same deterministic prefix — repro artifacts are
    # self-contained (they embed the full case), so a varying seed
    # costs nothing in replayability
    budget_env = os.environ.get("FANTOCH_FUZZ_BUDGET_S")
    if budget_env:
        budget_s = float(budget_env)
        soak_seed = int(
            os.environ.get("FANTOCH_FUZZ_SOAK_SEED", str(int(time.time())))
        )
        soak_fuzzer = FaultPlanFuzzer(seed=soak_seed)
        print(f"soak seed: {soak_seed} (FANTOCH_FUZZ_SOAK_SEED to replay)")
        soak_tally: dict = {}
        index = 0
        violations = []
        while time.monotonic() - started < budget_s:
            case = soak_fuzzer.case(index)
            result = run_case(case)
            soak_tally[result.verdict] = soak_tally.get(result.verdict, 0) + 1
            if result.verdict == VIOLATION:
                shrunk, runs = shrink_case(case)
                path = f"fuzz-soak-{index}.json"
                write_repro(path, repro_artifact(run_case(shrunk), runs))
                violations.append(path)
                print(f"SOAK VIOLATION case {index} -> {path}")
            index += 1
        print(
            f"soak: {sum(soak_tally.values())} extra cases: "
            + "  ".join(f"{k}={v}" for k, v in sorted(soak_tally.items()))
        )
        assert not violations, f"soak found violations: {violations}"

    print("fuzz smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
