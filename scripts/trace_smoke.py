"""Trace smoke gate (``make trace-smoke``): run a tiny 3-process EPaxos
sim with tracing at rate 1.0, twice with the same seed, then assert the
whole observability pipeline end to end:

- the two span logs are byte-identical (``obs diff`` empty) — the PR-2
  determinism property extended to latency structure;
- every committed command has a span whose canonical stages are
  monotonic, and the per-stage segments telescope to the client latency;
- the Perfetto conversion validates and the summarize report parses.

CPU-only and tiny (a few hundred events); the per-push CI step runs it
next to bench-smoke.
"""

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run_sim(trace_path: str, seed: int = 7) -> None:
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.core import Config, Planet
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.sim import Runner

    config = Config(
        n=3,
        f=1,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        trace_sample_rate=1.0,
    )
    planet = Planet.new("gcp")
    regions = sorted(planet.regions())[:3]
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=4,
        payload_size=1,
    )
    runner = Runner(
        EPaxos,
        planet,
        config,
        workload,
        clients_per_process=2,
        process_regions=list(regions),
        client_regions=list(regions),
        seed=seed,
        trace_path=trace_path,
    )
    runner.run(extra_sim_time_ms=1000)


def main() -> None:
    from fantoch_tpu.observability.perfetto import to_perfetto, validate_perfetto
    from fantoch_tpu.observability.report import (
        assemble_spans,
        monotonic_violations,
        summarize,
    )
    from fantoch_tpu.observability.tracer import read_trace

    with tempfile.TemporaryDirectory() as tmp:
        a, b = f"{tmp}/a.jsonl", f"{tmp}/b.jsonl"
        run_sim(a)
        run_sim(b)
        with open(a, "rb") as fa, open(b, "rb") as fb:
            assert fa.read() == fb.read(), "same-seed traces must be byte-identical"

        # the CLI agrees (exit 0 + "identical")
        proc = subprocess.run(
            [sys.executable, "-m", "fantoch_tpu.bin.obs", "diff", a, b],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

        events = read_trace(a)
        assert events, "trace must not be empty"
        spans = assemble_spans(events)
        assert len(spans) == 3 * 2 * 4, f"span per command, got {len(spans)}"
        assert not monotonic_violations(spans)

        report = summarize(events)
        assert report["spans"] == len(spans)
        assert report["end_to_end"]["count"] == len(spans)
        assert report["monotonic_violations"] == 0

        perfetto = to_perfetto(events)
        validate_perfetto(perfetto)
        # a serialized round-trip still validates (what the viewer loads)
        validate_perfetto(json.loads(json.dumps(perfetto)))

        out = f"{tmp}/trace.json"
        proc = subprocess.run(
            [sys.executable, "-m", "fantoch_tpu.bin.obs", "to-perfetto", a, "-o", out],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        with open(out) as fh:
            validate_perfetto(json.load(fh))

    print(json.dumps({
        "metric": "trace_smoke",
        "spans": len(spans),
        "end_to_end_p99_ms": report["end_to_end"]["p99_us"] / 1000,
        "ok": True,
    }))


if __name__ == "__main__":
    main()
