"""Critical-path smoke gate (``make critpath-smoke``): the causal
attribution plane end to end, CPU-only and tiny.

1. A localhost 3-process EPaxos TCP cluster with tracing at rate 1.0:
   ``bin/obs.py critpath`` over the per-process span logs must stitch
   >= 99% of sampled spans across processes (wall clocks, heartbeat
   offset resolution) and every attribution vector must telescope
   EXACTLY to reply - submit.
2. A SlowProcess sim nemesis: the deliberately slowed peer must be
   named the dominant quorum-wait contributor.
3. A forced StalledExecutionError (crash-forever past the executor's
   bounded wait): every live process must dump a flight-recorder black
   box that the SAME correlator stitches.

The per-push CI step runs this next to trace-smoke.
"""

import dataclasses
import glob
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

COMMANDS_PER_CLIENT = 5


def _workload():
    from fantoch_tpu.client import ConflictRateKeyGen, Workload

    return Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=COMMANDS_PER_CLIENT,
        payload_size=1,
    )


def _near_far():
    """p3 sits inside p1's and p2's fast quorums."""
    from fantoch_tpu.core.planet import Planet, Region

    regions = [Region("r1"), Region("r2"), Region("r3")]
    latencies = {
        regions[0]: {regions[0]: 0, regions[1]: 80, regions[2]: 10},
        regions[1]: {regions[0]: 80, regions[1]: 0, regions[2]: 10},
        regions[2]: {regions[0]: 10, regions[1]: 10, regions[2]: 0},
    }
    return regions, Planet.from_latencies(latencies)


def check_localhost(tmp: str) -> dict:
    import asyncio

    from fantoch_tpu.core import Config
    from fantoch_tpu.observability.critpath import critpath_report
    from fantoch_tpu.observability.tracer import read_trace
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.run.harness import run_localhost_cluster

    obs_dir = f"{tmp}/localhost"
    config = Config(n=3, f=1, gc_interval_ms=50, trace_sample_rate=1.0)
    asyncio.run(
        run_localhost_cluster(
            EPaxos, config, _workload(), clients_per_process=2,
            observe_dir=obs_dir,
            runtime_kwargs={"heartbeat_interval_s": 0.1},
        )
    )
    paths = sorted(glob.glob(f"{obs_dir}/trace_*.jsonl"))
    events = []
    for path in paths:
        events.extend(read_trace(path))
    report = critpath_report(events)
    assert report["clock"] == "wall", report["clock"]
    assert report["spans"] == 3 * 2 * COMMANDS_PER_CLIENT, report["spans"]
    assert report["stitch_rate"] >= 0.99, report["stitch_rate"]
    assert report["telescoping_violations"] == 0, report
    assert report["quorum_blame"], "quorum waits must resolve to peers"

    # the CLI agrees (exit 0, machine payload carries the same verdict)
    proc = subprocess.run(
        [sys.executable, "-m", "fantoch_tpu.bin.obs", "critpath", "--json"]
        + paths,
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["stitch_rate"] >= 0.99
    assert payload["telescoping_violations"] == 0
    return report


def check_slow_process(tmp: str) -> int:
    from fantoch_tpu.core import Config
    from fantoch_tpu.observability.critpath import (
        critpath_report,
        dominant_quorum_peer,
    )
    from fantoch_tpu.observability.tracer import read_trace
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.sim import Runner
    from fantoch_tpu.sim.faults import FaultPlan

    regions, planet = _near_far()
    config = Config(
        n=3, f=1, gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        trace_sample_rate=1.0,
    )
    path = f"{tmp}/slow.jsonl"
    runner = Runner(
        EPaxos, planet, config, _workload(), clients_per_process=2,
        process_regions=regions, client_regions=regions[:2], seed=7,
        trace_path=path,
        fault_plan=FaultPlan().with_slow_process(3, slow_ms=150),
    )
    runner.run(extra_sim_time_ms=2000)
    report = critpath_report(read_trace(path))
    blamed = dominant_quorum_peer(report)
    assert blamed == 3, (
        f"slowed p3 must dominate the quorum wait, got p{blamed}: "
        f"{report['quorum_blame']}"
    )
    assert report["quorum_blame"][3]["mean_wait_us"] >= 150_000
    return blamed


def check_flight(tmp: str) -> int:
    from fantoch_tpu.core import Config
    from fantoch_tpu.errors import StalledExecutionError
    from fantoch_tpu.observability.critpath import critpath_report
    from fantoch_tpu.observability.recorder import flight_events
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.sim import Runner
    from fantoch_tpu.sim.faults import FaultPlan

    regions, planet = _near_far()
    config = Config(
        n=3, f=1, gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        trace_sample_rate=1.0,
        executor_monitor_pending_interval_ms=200,
        executor_pending_fail_ms=800,
    )
    flight_dir = f"{tmp}/flight"
    plan = dataclasses.replace(
        FaultPlan().with_crash(1, at_ms=60), max_sim_time_ms=6000
    )
    runner = Runner(
        EPaxos, planet, config, _workload(), clients_per_process=2,
        process_regions=regions, client_regions=regions, seed=7,
        trace_path=f"{tmp}/stall.jsonl", fault_plan=plan,
        flight_dir=flight_dir,
    )
    try:
        runner.run(extra_sim_time_ms=2000)
        raise AssertionError("the crash-without-recovery run must stall")
    except StalledExecutionError:
        pass
    dumps = sorted(glob.glob(f"{flight_dir}/flight_p*.json"))
    names = [os.path.basename(p) for p in dumps]
    assert names == [
        "flight_p1.json", "flight_p2.json", "flight_p3.json"
    ], names
    # the same correlator stitches the black boxes
    report = critpath_report(
        flight_events(dumps + [f"{flight_dir}/flight_clients.json"])
    )
    assert report["spans"] > 0
    assert report["telescoping_violations"] == 0
    return len(dumps)


def main() -> None:
    with tempfile.TemporaryDirectory() as tmp:
        report = check_localhost(tmp)
        blamed = check_slow_process(tmp)
        dumps = check_flight(tmp)
    print(json.dumps({
        "metric": "critpath_smoke",
        "critpath_spans": report["spans"],
        "critpath_stitch_rate": report["stitch_rate"],
        "critpath_p99_dominant_stage": report["p99"]["dominant_stage"],
        "critpath_blamed_slow_peer": blamed,
        "critpath_flight_dumps": dumps,
        "ok": True,
    }))


if __name__ == "__main__":
    main()
