"""Slope-profile the phases of resolve_functional_keyed at 1M on the TPU.

Each variant computes a prefix of the kernel and returns a scalar; the
chained-carry slope method removes the rig's fixed dispatch latency.
"""

import functools
import os
import sys
import time

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from bench import BATCH, CONFLICT, build_workload  # noqa: E402
from fantoch_tpu.ops.graph_resolve import (  # noqa: E402
    TERMINAL,
    _doubling_core,
    _residual_size_for,
)

RES = _residual_size_for(BATCH)


def phase_fn(stop):
    def fn(key, dep, dot_src, dot_seq):
        batch = dep.shape[0]
        res_n = RES
        idx = jnp.arange(batch, dtype=jnp.int32)
        p_iota = idx
        k_s, pos_s, dep_s = jax.lax.sort(
            (key.astype(jnp.int32), idx, dep), num_keys=1, is_stable=True
        )
        if stop == "s1":
            return k_s[0] + pos_s[0] + dep_s[0]
        head = jnp.concatenate([jnp.ones((1,), bool), k_s[1:] != k_s[:-1]])
        prev_pos = jnp.roll(pos_s, 1)
        ok = jnp.where(head, dep_s == TERMINAL, dep_s == prev_pos)
        run_start = jax.lax.cummax(jnp.where(head, p_iota, 0))
        lastbad = jax.lax.cummax(jnp.where(~ok, p_iota, -1))
        chain_ok = lastbad < run_start
        if stop == "verify":
            return chain_ok.sum()
        cflag = chain_ok.astype(jnp.int32)
        _, p_r_full = jax.lax.sort((cflag, p_iota), num_keys=1, is_stable=True)
        n_residual = batch - cflag.sum()
        if stop == "s2":
            return p_r_full[0] + n_residual
        p_r = p_r_full[:res_n]
        r_iota = jnp.arange(res_n, dtype=jnp.int32)
        valid_r = r_iota < n_residual
        rpos = pos_s[p_r]
        rdep = dep_s[p_r]
        rrs = jnp.where(valid_r, run_start[p_r], jnp.iinfo(jnp.int32).max)
        rsrc = dot_src[rpos]
        rseq = dot_seq[rpos]
        if stop == "rgather":
            return rpos.sum() + rdep.sum() + rrs[0] + rsrc[0] + rseq[0]
        remap = jnp.full((batch,), TERMINAL, dtype=jnp.int32)
        remap = remap.at[jnp.where(valid_r, rpos, batch)].set(r_iota, mode="drop")
        rdep_local = jnp.where(rdep >= 0, remap[jnp.clip(rdep, 0, batch - 1)], rdep)
        rdep_local = jnp.where(valid_r, rdep_local, TERMINAL)
        if stop == "remap":
            return rdep_local.sum()
        l_resolved, l_rank, l_leader, l_on_cycle = _doubling_core(rdep_local)
        if stop == "doubling":
            return l_rank.sum() + l_leader[0]
        g_head = jnp.concatenate([jnp.ones((1,), bool), rrs[1:] != rrs[:-1]])
        firstbad = jax.lax.cummax(jnp.where(g_head, p_r, 0))
        l_unres = (~l_resolved).astype(jnp.int32)
        outs = jax.lax.sort(
            (rrs, l_unres, l_rank, l_leader, rsrc, rseq, p_r, firstbad,
             rpos, l_resolved.astype(jnp.int32), jnp.where(valid_r, l_rank, 0),
             rpos[jnp.clip(l_leader, 0, res_n - 1)], l_on_cycle.astype(jnp.int32)),
            num_keys=6, is_stable=True,
        )
        e_p_r, e_firstbad, e_res = outs[6], outs[7], outs[9]
        if stop == "emit":
            return e_p_r.sum() + e_firstbad[0] + e_res[0]
        rrs_emit = jnp.sort(rrs)
        e_g_head = jnp.concatenate([jnp.ones((1,), bool), rrs_emit[1:] != rrs_emit[:-1]])
        e_group_start = jax.lax.cummax(jnp.where(e_g_head, r_iota, 0))
        emit_local = r_iota - e_group_start
        e_valid = r_iota < n_residual
        target_r = e_firstbad + emit_local
        sc_idx = jnp.where(e_valid, e_p_r, batch)
        tgt_b = p_iota.at[sc_idx].set(target_r, mode="drop")
        unres_b = (~chain_ok).at[sc_idx].set(e_res == 0, mode="drop")
        if stop == "scatter":
            return tgt_b.sum() + unres_b.sum()
        order_sorted = jax.lax.sort(
            (unres_b.astype(jnp.int32), tgt_b, pos_s), num_keys=2, is_stable=True
        )
        return order_sorted[2][0] + (batch - unres_b.sum())

    return fn


def slope(name, base, k_lo=1, k_hi=3, iters=9):
    def chain(k):
        def f(key, dep, src, seq):
            carry = jnp.int32(0)
            for _ in range(k):
                out = base(key + (carry >> jnp.int32(30)), dep, src, seq)
                carry = out.astype(jnp.int32)
            return carry
        return jax.jit(f)

    f_lo, f_hi = chain(k_lo), chain(k_hi)

    def t(f):
        float(f(KEY, DEP, SRC, SEQ))
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            float(f(KEY, DEP, SRC, SEQ))
            ts.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(ts))

    lo, hi = t(f_lo), t(f_hi)
    per = (hi - lo) / (k_hi - k_lo)
    print(f"{name:12s} cumulative = {per:7.3f} ms")
    return per


key_np, dep_np, src_np, seq_np = build_workload(BATCH, CONFLICT)
KEY = jax.device_put(jnp.asarray(key_np))
DEP = jax.device_put(jnp.asarray(dep_np))
SRC = jax.device_put(jnp.asarray(src_np))
SEQ = jax.device_put(jnp.asarray(seq_np))

print("platform:", jax.devices()[0].platform, "residual:", RES)
stops = sys.argv[1:] or ["s1", "verify", "s2", "rgather", "remap", "doubling", "emit", "scatter", "full"]
for stop in stops:
    slope(stop, phase_fn(stop))
