"""CI overload smoke (``make overload-smoke``): a tiny CPU open-loop row
proving the overload-control plane end to end, per push.

Three phases against localhost EPaxos n=3 TCP clusters, all driven by
the shared phase runner (``run/harness.run_overload_phase`` — one
accounting implementation for this gate and ``bench.py bench_overload``):

1. closed-loop baseline (pre-burst p50 + saturation estimate);
2. an open-loop Poisson burst at ~2x the measured saturation into a
   tight admission limit — asserts bounded queue depths (no queue past
   2x its pause watermark; the watermark is a credit gate, not a hard
   cap — see run_overload_phase), typed sheds reaching clients as
   backoff retries, and nonzero goodput while shedding;
3. closed-loop again — asserts post-burst p50 drained back to within 2x
   of the pre-burst baseline (+ absolute slack: CI hosts are slow and
   shared).

Pure asyncio (no device): the gate covers run/backpressure.py,
run/process_runner.py admission + reader pauses, and the client plane's
backoff — the seams ``make bench-smoke`` / ``make trace-smoke`` don't.
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, ".")


def main() -> int:
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.core import Config
    from fantoch_tpu.protocol import EPaxos
    from fantoch_tpu.run.harness import run_overload_phase

    def workload(commands_per_client):
        return Workload(
            shard_count=1,
            key_gen=ConflictRateKeyGen(30),
            keys_per_command=1,
            commands_per_client=commands_per_client,
            payload_size=1,
        )

    # admission_limit=1: any nonzero edge depth at a submit instant
    # sheds — the tightest setting, so the shed gate below stays robust
    # across CI hosts of very different speeds
    config = Config(
        n=3, f=1,
        gc_interval_ms=50,
        executor_executed_notification_interval_ms=50,
        admission_limit=1,
        queue_capacity=128,
        overload_retry_after_ms=5,
    )

    def run(rate=None):
        return run_overload_phase(
            EPaxos, config, workload(8), 3,
            arrival_rate_per_s=rate, arrival_seed=2,
        )

    # phase 1: closed-loop baseline + saturation estimate
    pre = run()
    saturation = pre["goodput_cmds_per_s"]

    # phase 2: open-loop Poisson burst at ~2x saturation (9 clients)
    rate_per_client = max(5.0, 2.0 * saturation / 9)
    burst = run(rate=rate_per_client)

    # phase 3: post-burst closed loop (fresh cluster state is fine: the
    # drain-back-within-one-cluster row lives in tests/test_overload.py;
    # the smoke asserts the latency regime, not a warm-state transition)
    post = run()

    out = {
        "metric": "overload_smoke",
        "overload_saturation_cmds_per_s": saturation,
        "overload_offered_cmds_per_s": int(rate_per_client * 9),
        "overload_goodput_cmds_per_s": burst["goodput_cmds_per_s"],
        "overload_sheds": burst["sheds"],
        "overload_client_retries": burst["client_retries"],
        "overload_backpressure_pauses": burst["backpressure_pauses"],
        "overload_queue_depth_hwm": burst["queue_depth_hwm"],
        "overload_unacked_depth_hwm": burst["unacked_depth_hwm"],
        "overload_pre_p50_ms": pre["p50_ms"],
        "overload_post_p50_ms": post["p50_ms"],
    }
    print(json.dumps(out))

    # the gates (loose where CI timing varies, strict where semantics do)
    assert burst["completed"] == 9 * 8, (
        f"backoff-retrying clients must complete everything: "
        f"{burst['completed']}/72"
    )
    assert burst["shed_commands"] == 0, "no deadline was set: nothing sheds"
    assert burst["sheds"] > 0, "a 2x-saturation burst must trip admission"
    assert burst["client_retries"] >= burst["sheds"], (
        "every server shed surfaces as a client retry"
    )
    assert burst["goodput_cmds_per_s"] > 0, "nonzero goodput while shedding"
    assert not burst["bound_violations"], (
        f"queues grew past 2x their pause watermark: "
        f"{burst['bound_violations']}"
    )
    assert post["p50_ms"] <= 2 * pre["p50_ms"] + 15.0, (
        f"post-burst p50 {post['p50_ms']}ms vs pre-burst {pre['p50_ms']}ms: "
        "system did not drain back to baseline"
    )
    print("overload-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
