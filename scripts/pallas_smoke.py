"""CI Pallas-kernel smoke (``make pallas-smoke``): interpret-mode parity
plus compile-cache discipline, per push.

The gate proves the round-19 kernel story end to end on the CPU pin:

1. route-vs-route parity: the ``bench_pallas_resolve`` and
   ``bench_table_pallas`` races assert bit-for-bit equality of every
   round's outputs between the Pallas route (interpret mode on CPU) and
   the composed-XLA route, across all four kernel families (pred step,
   graph step, votes commit, fused table round);
2. probe verdicts: after the races every dispatched family's lowering
   probe reads supported (``pallas_status()["families"]``) — a silent
   permanent fallback would otherwise pass parity trivially;
3. executor seam: a ``DeviceTablePlane`` served through the forced
   Pallas route matches the composed-route plane's frontiers with the
   SAME upload count (the donation discipline survives the kernel swap);
4. compile-wall discipline: every registered plane program's
   compiled-signature count stays bounded (a leaked non-canonical shape
   axis shows up as a signature explosion), and the hit/miss-paired
   recompile counter is consistent — zero cache misses implies zero
   true recompiles.

Wall cost: a few dozen tiny CPU dispatches, seconds on a laptop.
"""

from __future__ import annotations

import random
import sys
import time

sys.path.insert(0, ".")


def main() -> int:
    from fantoch_tpu.hostenv import force_cpu_platform

    force_cpu_platform()
    started = time.monotonic()

    from fantoch_tpu.core.compile_cache import (
        ensure_compile_cache,
        program_compile_counts,
    )
    from fantoch_tpu.observability.device import (
        cache_miss_count,
        recompile_count,
        subscribe_recompiles,
    )

    subscribe_recompiles()
    ensure_compile_cache(None)

    # 1. route-vs-route parity (asserted inside the bench rows)
    from bench import bench_pallas_resolve, bench_table_pallas

    row = bench_pallas_resolve(cap=128, width=4, rounds=4)
    row.update(bench_table_pallas(keys=64, batch=256, rounds=4))
    assert row["pallas_resolve_interpret"] is True, row  # the CPU pin
    print(
        "parity: pred/graph/votes/round all bit-for-bit across routes "
        f"(pred {row['pallas_resolve_pred_ms']}ms pallas vs "
        f"{row['pallas_resolve_pred_composed_ms']}ms composed)"
    )

    # 2. every dispatched family probed supported — parity above must
    # not have been satisfied by a silent composed fallback
    from fantoch_tpu.ops import pallas_resolve

    families = pallas_resolve.pallas_status()["families"]
    expected = {"pred_plane_step", "graph_plane_step", "votes_commit",
                "table_round"}
    assert expected <= set(families), families
    assert all(families[f] is True for f in expected), families
    print(f"probe verdicts: {sorted(expected)} all supported")

    # 3. executor seam: the table plane serves identically on either
    # route with the same upload count
    import numpy as np

    from fantoch_tpu.executor.table_plane import DeviceTablePlane

    def drive(enabled):
        pallas_resolve.set_pallas_kernels(enabled)
        try:
            plane = DeviceTablePlane(3, stability_threshold=2, key_buckets=8)
            for k in range(6):
                plane.bucket(f"k{k}")
            rng = random.Random(19)
            for _round in range(4):
                vk, vb, vs, ve = [], [], [], []
                for _ in range(16):
                    vk.append(rng.randrange(0, 6))
                    vb.append(rng.randrange(1, 4))
                    s = rng.randrange(1, 12)
                    vs.append(s)
                    ve.append(s + rng.randrange(0, 4))
                plane.commit_votes(
                    np.array(vk, np.int64), np.array(vb, np.int64),
                    np.array(vs, np.int64), np.array(ve, np.int64),
                )
            return plane
        finally:
            pallas_resolve.set_pallas_kernels(None)

    plane_p, plane_x = drive(True), drive(False)
    assert np.array_equal(plane_p.frontiers(), plane_x.frontiers())
    assert plane_p.resident_uploads == plane_x.resident_uploads == 1
    print("executor seam: frontiers bit-for-bit, one upload on either route")

    # 4. compile-wall discipline
    for name, count in program_compile_counts().items():
        assert count <= 8, (name, count)
    assert cache_miss_count() > 0 or recompile_count() == 0, (
        cache_miss_count(), recompile_count(),
    )
    print(
        f"compile discipline: {len(program_compile_counts())} registered "
        f"programs bounded, {recompile_count()} true compiles / "
        f"{cache_miss_count()} cache misses"
    )

    print(f"pallas smoke OK in {time.monotonic() - started:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
