#!/usr/bin/env python
"""Run tests/test_device_runner.py with its jax-version guard stripped.

The module skips itself outright on jax < 0.5 (jaxlib 0.4.x CPU segfaults
*flakily* while tracing the device drivers' scan bodies, and a mid-suite
crash would abort the whole pytest run).  That guard opened a silent
tier-1 coverage hole on the pinned jax: a green suite says nothing about
the serving loop there.  This script closes it the way PR 6 validated its
changes — run the SAME tests from a guard-stripped copy, in their own
pytest process so a (rare) tracer segfault cannot take tier-1 down.

On jax >= 0.5 the guard is inactive and the regular suite already runs
the module; the script exits 0 without duplicating the work (pass
``--force`` to run the stripped copy anyway).

Usage: make test-device-stripped  (or: python scripts/run_device_stripped.py)
"""

import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SOURCE = os.path.join(REPO, "tests", "test_device_runner.py")
# no test_ prefix: tier-1's directory collection must never pick the copy
# up (only this script runs it, by explicit path)
STRIPPED = os.path.join(REPO, "tests", "_stripped_device_runner.py")

GUARD = re.compile(
    r"^if tuple\(int\(x\) for x in jax\.__version__.*?\n(?:    .*\n|\)\n)*",
    re.MULTILINE,
)


def main() -> int:
    import jax

    guard_active = tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5)
    if not guard_active and "--force" not in sys.argv[1:]:
        print(
            f"jax {jax.__version__}: the version guard is inactive and the "
            "regular suite runs tests/test_device_runner.py — nothing to "
            "strip (pass --force to run the stripped copy anyway)"
        )
        return 0

    with open(SOURCE) as fh:
        src = fh.read()
    stripped, hits = GUARD.subn("", src)
    if hits != 1:
        print(
            f"expected exactly one version-guard block in {SOURCE}, found "
            f"{hits}: the guard moved — update scripts/run_device_stripped.py",
            file=sys.stderr,
        )
        return 2
    with open(STRIPPED, "w") as fh:
        fh.write(stripped)
    try:
        return subprocess.run(
            [
                sys.executable, "-m", "pytest", STRIPPED, "-q",
                "-p", "no:cacheprovider", "-p", "no:randomly",
            ],
            cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        ).returncode
    finally:
        # never leave the copy behind: a crash of the child must not turn
        # into a stray module a later collection could import
        try:
            os.unlink(STRIPPED)
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main())
