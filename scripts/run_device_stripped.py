#!/usr/bin/env python
"""Run the jax-version-guarded device test modules with the guard stripped.

Some device test modules skip themselves outright on jax < 0.5 (jaxlib
0.4.x CPU segfaults *flakily* while tracing the device drivers' scan
bodies, and a mid-suite crash would abort the whole pytest run).  That
guard opened a silent tier-1 coverage hole on the pinned jax: a green
suite says nothing about the serving loop there.  This script closes it
the way PR 6 validated its changes — run the SAME tests from
guard-stripped copies, in their own pytest process so a (rare) tracer
segfault cannot take tier-1 down.

The module set is DISCOVERED: every ``tests/test_*.py`` carrying the
version-guard block is stripped and run, so new guarded device suites
(the r13 device-plane work added candidates) ride along without editing
this script.  Unguarded device tests (tests/test_pred_plane.py, the
table-plane oracle suite) already run in tier-1 on every pin and need no
stripping.

On jax >= 0.5 the guard is inactive and the regular suite already runs
the modules; the script exits 0 without duplicating the work (pass
``--force`` to run the stripped copies anyway).

A second leg re-runs the Pallas parity suite
(tests/test_pallas_resolve.py) in its own pytest process with
``FANTOCH_PALLAS=1`` forced through the environment: tier-1 already runs
the suite with routes forced per-test, but this leg additionally proves
the ENV escape-hatch path — the route every executor takes when the flag
is set rig-wide — end to end on whatever backend is attached (interpret
mode on the CPU pin, Mosaic-lowered kernels on a TPU rig).

Usage: make test-device-stripped  (or: python scripts/run_device_stripped.py)
"""

import glob
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GUARD = re.compile(
    r"^if tuple\(int\(x\) for x in jax\.__version__.*?\n(?:    .*\n|\)\n)*",
    re.MULTILINE,
)


def guarded_modules():
    found = []
    for path in sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py"))):
        with open(path) as fh:
            src = fh.read()
        if GUARD.search(src):
            found.append((path, src))
    return found


def run_pallas_forced() -> int:
    """Re-run the Pallas parity suite with FANTOCH_PALLAS=1 forced: the
    env-route leg (executors resolve the route from the environment, not
    a per-test override)."""
    suite = os.path.join(REPO, "tests", "test_pallas_resolve.py")
    if not os.path.exists(suite):
        print(
            "tests/test_pallas_resolve.py is gone: update "
            "scripts/run_device_stripped.py",
            file=sys.stderr,
        )
        return 2
    return subprocess.run(
        [
            sys.executable, "-m", "pytest", suite, "-q",
            "-p", "no:cacheprovider", "-p", "no:randomly",
        ],
        cwd=REPO,
        env={**os.environ, "FANTOCH_PALLAS": "1"},
    ).returncode


def main() -> int:
    import jax

    guard_active = tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5)
    if not guard_active and "--force" not in sys.argv[1:]:
        print(
            f"jax {jax.__version__}: the version guard is inactive and the "
            "regular suite runs the guarded device modules — nothing to "
            "strip (pass --force to run the stripped copies anyway); "
            "running the FANTOCH_PALLAS=1 leg only"
        )
        return run_pallas_forced()

    modules = guarded_modules()
    if not modules:
        print(
            "no tests/test_*.py carries the jax version-guard block: the "
            "guard moved — update scripts/run_device_stripped.py",
            file=sys.stderr,
        )
        return 2

    rc = 0
    for path, src in modules:
        stripped_src, hits = GUARD.subn("", src)
        if hits != 1:
            print(
                f"expected exactly one version-guard block in {path}, "
                f"found {hits}: update scripts/run_device_stripped.py",
                file=sys.stderr,
            )
            return 2
        # no test_ prefix: tier-1's directory collection must never pick
        # the copy up (only this script runs it, by explicit path)
        name = os.path.basename(path)[len("test_") :]
        stripped = os.path.join(REPO, "tests", f"_stripped_{name}")
        with open(stripped, "w") as fh:
            fh.write(stripped_src)
        try:
            rc = (
                subprocess.run(
                    [
                        sys.executable, "-m", "pytest", stripped, "-q",
                        "-p", "no:cacheprovider", "-p", "no:randomly",
                    ],
                    cwd=REPO,
                    env={**os.environ, "JAX_PLATFORMS": "cpu"},
                ).returncode
                or rc
            )
        finally:
            # never leave the copy behind: a crash of the child must not
            # turn into a stray module a later collection could import
            try:
                os.unlink(stripped)
            except OSError:
                pass
    return run_pallas_forced() or rc


if __name__ == "__main__":
    sys.exit(main())
