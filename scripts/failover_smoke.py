"""CI failover smoke (``make failover-smoke``): a seeded accelerator
fault against a live device plane, per push.

The gate drives the deterministic sim (Newt with the device votes-table
plane on) twice from the same seed — once fault-free, once with a
DeviceFault dispatch hang injected at p1 — and asserts the whole
fault-tolerance story end to end:

1. the typed error was observed: the nemesis trace records the
   ``device-failover`` transition naming ``DeviceFailedError``;
2. host-twin goodput stays nonzero: the faulted run completes every
   client's workload while p1's plane serves degraded
   (``degraded_ms > 0``), and the execution-order monitors are
   byte-identical to the fault-free run's (bit-for-bit twin serving);
3. online rebuild + cutback: ``plane_rebuilds == 1``, the plane ends
   healthy, and — via the plane-level ``bench_failover`` drill, which
   watches the upload counter round by round across the transition —
   cutback costs exactly ONE counted resident re-upload;
4. determinism: running the faulted case twice yields byte-identical
   fault traces.

Wall cost: ~3 sim runs, a few seconds on a laptop CPU.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

SIM_SEED = 11
FAULT_PID = 1


def _config():
    # the audit-instrumented fuzz config with the table plane forced ON
    # for the fault-free reference run too (``_fuzz_config`` only turns
    # it on when the plan carries device faults) — both runs must serve
    # through the same plane for the upload and bit-for-bit comparisons
    from fantoch_tpu.core.config import Config

    return Config(
        3,
        1,
        shard_count=1,
        executor_monitor_execution_order=True,
        audit_log_commits=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
        newt_detached_send_interval_ms=100,
        device_table_plane=True,
        device_dispatch_timeout_ms=250.0,
        plane_shadow_rate=1.0,
    )


def _run(plan):
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.sim import Runner
    from fantoch_tpu.sim.fuzz import FuzzCase, _fuzz_planet, _protocol_cls

    case = FuzzCase(protocol="newt", n=3, f=1, plan=plan, sim_seed=SIM_SEED)
    regions, planet = _fuzz_planet(case.n)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(50),
        keys_per_command=2,
        commands_per_client=6,
        payload_size=1,
    )
    runner = Runner(
        _protocol_cls(case.protocol),
        planet,
        _config(),
        workload,
        2,
        process_regions=list(regions),
        client_regions=list(regions),
        seed=case.sim_seed,
        fault_plan=plan,
    )
    _metrics, monitors, _latencies = runner.run(extra_sim_time_ms=2000)
    counters = {}
    for pid, (_process, executor, _pending) in runner._simulation.processes():
        device = executor.device_counters() or {}
        counters[pid] = device
    unfinished = [
        client_id
        for client_id, client in runner._simulation.clients()
        if client.issued_commands != 6
    ]
    trace = list(runner.nemesis.trace)
    return monitors, counters, trace, unfinished


def main() -> int:
    from fantoch_tpu.sim.faults import FaultPlan

    started = time.monotonic()
    base_plan = FaultPlan(seed=7, max_sim_time_ms=600_000)
    fault_plan = base_plan.with_device_fault(
        process_id=FAULT_PID, plane="table", kind="hang",
        at_dispatch=2, down_dispatches=3,
    )

    clean_monitors, clean_counters, _trace, clean_unfinished = _run(base_plan)
    monitors, counters, trace, unfinished = _run(fault_plan)

    # 1. typed error observed at the failover transition
    failovers = [t for t in trace if t[1] == "device-failover"]
    assert failovers, f"no device-failover in trace: {trace}"
    assert any("DeviceFailedError" in t[2] for t in failovers), failovers
    injected = [t for t in trace if t[1] == "device-hang"]
    assert injected, f"injected fault never recorded: {trace}"
    print(f"typed error observed: {failovers[0][2]}")

    # 2. host-twin goodput nonzero while degraded
    faulted = counters[FAULT_PID]
    assert faulted.get("table_plane_failovers") == 1, faulted
    assert faulted.get("table_plane_degraded_ms", 0.0) > 0.0, faulted
    assert not unfinished and not clean_unfinished, (
        f"clients unfinished: faulted={unfinished} clean={clean_unfinished}"
    )
    same = {
        pid: repr(monitors[pid]) == repr(clean_monitors[pid])
        for pid in monitors
    }
    assert all(same.values()), f"twin serving diverged: {same}"
    print(
        "host-twin goodput: all clients finished, "
        f"{faulted['table_plane_degraded_ms']:.2f}ms served degraded, "
        "execution orders bit-for-bit vs fault-free"
    )

    # 3. online rebuild: plane cut back healthy.  NB the faulted run can
    # show FEWER total uploads than the fault-free one — growth
    # re-uploads during the failed window are skipped and folded into
    # the single rebuild upload — so "exactly one cutback re-upload" is
    # asserted at the plane level by the bench drill below, which
    # watches the upload counter round by round across the transition.
    assert faulted.get("table_plane_rebuilds") == 1, faulted
    assert faulted.get("table_plane_health") == 0, faulted
    assert faulted.get("table_plane_resident_uploads", 0) >= 2, faulted
    clean_uploads = clean_counters[FAULT_PID]["table_plane_resident_uploads"]
    print(
        f"rebuild + cutback: healthy again "
        f"(uploads {faulted['table_plane_resident_uploads']} faulted "
        f"vs {clean_uploads} clean — failed-window growths folded)"
    )

    from bench import bench_failover

    drill = bench_failover(keys=64, rounds=16, votes_per_round=256,
                           fault_at=5, down=4)
    assert drill["failover_cutback_uploads"] == 1, drill
    assert drill["failover_degraded_cmds_per_s"] > 0, drill
    print(
        f"plane drill: cutback cost exactly 1 re-upload, "
        f"{drill['failover_degraded_cmds_per_s']:.0f} cmds/s degraded, "
        f"time-to-failover {drill['failover_time_to_failover_ms']:.1f}ms"
    )

    # 4. determinism: same seed, same fault trace
    _m, _c, trace2, _u = _run(fault_plan)
    assert trace == trace2, "same-seed fault traces diverged"
    print("determinism: fault trace stable across reruns")

    print(f"failover smoke OK in {time.monotonic() - started:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
