"""Offline quorum-latency planner (the fantoch_bote analog).

Reference: fantoch_bote/src/{lib,protocol,search}.rs.  ``Bote`` computes
client-perceived latencies for leaderless and leader-based protocols over
a Planet RTT matrix; ``Search`` ranks server-region placements against
FPaxos/EPaxos baselines.
"""

from fantoch_tpu.planner.bote import Bote, minority, quorum_size
from fantoch_tpu.planner.search import (
    ConfigScore,
    Placement,
    RankingParams,
    Search,
)

__all__ = [
    "Bote",
    "ConfigScore",
    "Placement",
    "RankingParams",
    "Search",
    "minority",
    "quorum_size",
]
